"""Comm-layer tests — analog of reference ``tests/unit/comm/test_dist.py``:
verify every verb against its mathematical definition on the 8-device mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from deepspeed_tpu.utils.jax_compat import shard_map

import deepspeed_tpu.comm as dist
from deepspeed_tpu.parallel.topology import (initialize_topology, DP_AXES,
                                              EDP_AXIS)


@pytest.fixture
def topo():
    return initialize_topology()


def _run_collective(topo, fn, x, in_spec, out_spec):
    # check_vma=False: collectives like all_gather produce replicated values
    # the varying-mesh-axes checker can't statically prove replicated.
    return jax.jit(shard_map(fn, mesh=topo.mesh, in_specs=(in_spec,),
                             out_specs=out_spec, check_vma=False))(x)


def test_all_reduce_sum(topo):
    x = jnp.arange(8.0)
    out = _run_collective(topo, lambda v: dist.all_reduce(v, group=DP_AXES),
                          x, P(DP_AXES), P(DP_AXES))
    np.testing.assert_allclose(np.asarray(out), np.full(8, x.sum()))


def test_all_reduce_max(topo):
    x = jnp.arange(8.0)
    out = _run_collective(
        topo, lambda v: dist.all_reduce(v, op=dist.ReduceOp.MAX, group=DP_AXES),
        x, P(DP_AXES), P(DP_AXES))
    np.testing.assert_allclose(np.asarray(out), np.full(8, 7.0))


def test_all_gather(topo):
    x = jnp.arange(8.0)
    out = _run_collective(
        topo, lambda v: dist.all_gather_into_tensor(v, group=DP_AXES),
        x, P(DP_AXES), P(None))
    # every shard gathers the full vector
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0))


def test_reduce_scatter(topo):
    # each device holds the full vector; reduce-scatter sums and splits
    x = jnp.ones((8, 8))
    out = _run_collective(
        topo, lambda v: dist.reduce_scatter_tensor(v[0], group=DP_AXES),
        x, P(DP_AXES, None), P(DP_AXES))
    np.testing.assert_allclose(np.asarray(out), np.full(8, 8.0))


def test_all_to_all(topo):
    # tiled all_to_all re-shards: rows-sharded → cols-sharded, data unchanged.
    x = jnp.arange(64.0).reshape(8, 8)
    out = _run_collective(
        topo, lambda v: dist.all_to_all_single(v, group=DP_AXES, split_axis=1,
                                               concat_axis=0),
        x, P(DP_AXES, None), P(None, DP_AXES))
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_broadcast_in_mesh(topo):
    x = jnp.arange(8.0)
    out = _run_collective(
        topo, lambda v: dist.broadcast(v, src=3, group=DP_AXES),
        x, P(DP_AXES), P(DP_AXES))
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.0))


def test_ppermute_shift(topo):
    x = jnp.arange(8.0)
    out = _run_collective(
        topo, lambda v: dist.send_recv_next(v, (EDP_AXIS,)),
        x, P(DP_AXES), P(DP_AXES))
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), 1))


def test_world_size(topo):
    assert dist.get_world_size() == 8
    assert dist.get_world_size(DP_AXES) == 8
    assert dist.get_world_size(("tp",)) == 1


def test_barrier(topo):
    dist.barrier()  # must not hang / raise


def test_eager_all_reduce_single_process(topo):
    out = dist.all_reduce(jnp.ones((4,)))
    np.testing.assert_allclose(np.asarray(out), np.ones(4))


def test_gather_and_list_all_gather(topo):
    """gather/all_gather (list style): shards stacked on a leading axis."""
    x = jnp.arange(8.0)
    want = np.arange(8.0).reshape(8, 1)
    out = _run_collective(
        topo, lambda v: dist.gather(v, group=DP_AXES), x,
        P(DP_AXES), P(None, None))
    np.testing.assert_allclose(np.asarray(out), want)
    out2 = _run_collective(
        topo, lambda v: dist.all_gather(v, group=DP_AXES), x,
        P(DP_AXES), P(None, None))
    np.testing.assert_allclose(np.asarray(out2), want)


def test_scatter(topo):
    """scatter: participant i takes slice i of the (replicated) source."""
    src = jnp.arange(8.0 * 3).reshape(8, 3)
    out = _run_collective(
        topo, lambda v: dist.scatter(v, group=DP_AXES), src,
        P(None, None), P(DP_AXES))
    np.testing.assert_allclose(np.asarray(out).reshape(8, 3), np.asarray(src))


def test_monitored_barrier_and_inference_all_reduce(topo):
    dist.monitored_barrier()
    x = jnp.ones(8)
    out = _run_collective(
        topo, lambda v: dist.inference_all_reduce(v, group=DP_AXES), x,
        P(DP_AXES), P(DP_AXES))
    np.testing.assert_allclose(np.asarray(out), np.full(8, 8.0))


def test_isend_raises_with_guidance():
    with pytest.raises(NotImplementedError):
        dist.isend(jnp.ones(4), dst=1)


def test_p2p_single_pair(topo):
    """dist.p2p: the reference send/recv pair as ONE collective — dst gets
    src's value, everyone else keeps their own."""
    x = jnp.arange(8.0)
    out = _run_collective(
        topo, lambda v: dist.p2p(v, src=2, dst=5, group=(EDP_AXIS,)),
        x, P(EDP_AXIS), P(EDP_AXIS))
    want = np.arange(8.0)
    want[5] = 2.0
    np.testing.assert_allclose(np.asarray(out), want)


def test_send_raises_with_p2p_guidance(topo):
    with pytest.raises(NotImplementedError, match="p2p"):
        dist.send(jnp.zeros(4), dst=1)


def test_send_recv_static_pair_lowers_to_p2p(topo):
    """Reference-shaped send/recv with static endpoints: the pair lowers to
    one collective permute — dst's recv returns src's sent value, everyone
    else keeps their receive buffer (reference ``comm.py:428``)."""
    x = jnp.arange(8.0) + 1.0

    def pair(v):
        dist.send(v, dst=5, group=(EDP_AXIS,))
        return dist.recv(jnp.full_like(v, -1.0), src=2, group=(EDP_AXIS,))

    out = _run_collective(topo, pair, x, P(EDP_AXIS), P(EDP_AXIS))
    want = np.full(8, -1.0)
    want[5] = 3.0                    # src=2 holds x[2] = 3.0
    np.testing.assert_allclose(np.asarray(out), want)


def test_send_recv_mismatch_and_dynamic_raise(topo):
    # recv with no pending send
    with pytest.raises(NotImplementedError, match="p2p"):
        _run_collective(topo,
                        lambda v: dist.recv(v, src=0, group=(EDP_AXIS,)),
                        jnp.zeros(8), P(EDP_AXIS), P(EDP_AXIS))
    # traced (dynamic) endpoint
    def dyn(v):
        return dist.send(v, dst=jnp.argmax(v), group=(EDP_AXIS,))
    with pytest.raises(Exception, match="static"):
        _run_collective(topo, dyn, jnp.zeros(8), P(EDP_AXIS), P(EDP_AXIS))
    from deepspeed_tpu.comm.comm import _pending_send
    _pending_send.clear()
    # group mismatch between the halves
    def mismatched(v):
        dist.send(v, dst=1, group=(EDP_AXIS,))
        return dist.recv(v, src=0, group=("tp",))
    with pytest.raises(ValueError, match="does not match"):
        _run_collective(topo, mismatched, jnp.zeros(8),
                        P(EDP_AXIS), P(EDP_AXIS))
    from deepspeed_tpu.comm.comm import _pending_send
    _pending_send.clear()


def test_aborted_trace_send_does_not_poison_next(topo):
    """A send whose trace aborts leaves a queued entry — the pending queue
    is scoped by trace identity, so the NEXT trace's pair must run clean
    (round-3 weakness: the stale entry paired across traces and raised
    JAX's leaked-tracer error at the innocent call)."""
    from deepspeed_tpu.comm.comm import _pending_send
    _pending_send.clear()

    def aborted(v):
        dist.send(v, dst=3, group=(EDP_AXIS,))
        raise RuntimeError("boom mid-trace")

    with pytest.raises(RuntimeError, match="boom"):
        _run_collective(topo, aborted, jnp.zeros(8),
                        P(EDP_AXIS), P(EDP_AXIS))
    assert _pending_send, "aborted trace should have left a queued send"

    x = jnp.arange(8.0) + 1.0

    def pair(v):
        dist.send(v, dst=5, group=(EDP_AXIS,))
        return dist.recv(jnp.full_like(v, -1.0), src=2, group=(EDP_AXIS,))

    out = _run_collective(topo, pair, x, P(EDP_AXIS), P(EDP_AXIS))
    want = np.full(8, -1.0)
    want[5] = 3.0                    # src=2 holds x[2] = 3.0
    np.testing.assert_allclose(np.asarray(out), want)
    # the stale entry sits inert (scoped to its dead trace) — it must not
    # have paired with the clean trace's recv
    assert len(_pending_send) == 1

    # a recv orphaned by an aborted send still fails at ITS call site,
    # with the stale entries dropped and called out
    with pytest.raises(NotImplementedError, match="stale"):
        _run_collective(topo,
                        lambda v: dist.recv(v, src=0, group=(EDP_AXIS,)),
                        jnp.zeros(8), P(EDP_AXIS), P(EDP_AXIS))
    assert not _pending_send


def test_nested_trace_pair_coexists_with_outer_send(topo):
    """A nested jit's self-contained send/recv pair must not disturb an
    enclosing trace's pending send: each pair lives in its own trace and
    the queue is trace-scoped, not globally FIFO."""
    from deepspeed_tpu.comm.comm import _pending_send
    _pending_send.clear()
    x = jnp.arange(8.0) + 1.0

    def inner_pair(v):
        dist.send(v, dst=1, group=(EDP_AXIS,))
        return dist.recv(jnp.full_like(v, -7.0), src=6, group=(EDP_AXIS,))

    inner_jit = None

    def outer(v):
        dist.send(v, dst=5, group=(EDP_AXIS,))          # outer pending
        inner = inner_jit(v * 10.0)                     # own pair inside
        got = dist.recv(jnp.full_like(v, -1.0), src=2, group=(EDP_AXIS,))
        return got + inner

    import jax
    inner_jit = jax.jit(inner_pair)
    out = _run_collective(topo, outer, x, P(EDP_AXIS), P(EDP_AXIS))
    # outer pair: rank 5 got x[2]=3.0, others keep -1; inner pair: rank 1
    # got 10*x[6]=70.0, others keep -7
    want = np.full(8, -8.0)
    want[5] = 3.0 - 7.0
    want[1] = -1.0 + 70.0
    np.testing.assert_allclose(np.asarray(out), want)
    assert not _pending_send


def test_opaque_trace_state_has_trace_ref():
    """The send/recv shim's dead-trace pruning leans on the PRIVATE
    ``OpaqueTraceState._trace_ref`` weakref; its getattr fallback degrades
    to "always live" (leak-prone) if a JAX upgrade renames it.  This
    canary makes that regression LOUD: if it fails, update
    ``comm._prune_dead_sends`` for the new OpaqueTraceState internals
    (comm.py emits a one-time runtime warning for the same condition)."""
    from deepspeed_tpu.utils.jax_compat import get_opaque_trace_state
    state = get_opaque_trace_state()
    assert hasattr(state, "_trace_ref"), (
        "OpaqueTraceState._trace_ref is gone on this JAX version — "
        "_prune_dead_sends now treats every queued send as live; port it "
        "to the new trace-liveness internals")
    # at top level the current trace is the eval trace and must be LIVE
    assert state._trace_ref() is not None


def test_prune_warns_once_when_trace_ref_missing():
    """The runtime half of the canary: a queue whose entries lack
    ``_trace_ref`` triggers ONE warning (not silence, not spam)."""
    from deepspeed_tpu.comm import comm as comm_mod

    class NoRefState:
        pass

    saved = list(comm_mod._pending_send)
    warned = comm_mod._warned_missing_trace_ref
    try:
        comm_mod._warned_missing_trace_ref = False
        comm_mod._pending_send[:] = [(NoRefState(), None, 0, ("edp",), 0)]
        comm_mod._prune_dead_sends()
        assert comm_mod._warned_missing_trace_ref
        # entries without the weakref read as live → nothing pruned
        assert len(comm_mod._pending_send) == 1
        comm_mod._prune_dead_sends()          # second call: no re-warn path
    finally:
        comm_mod._pending_send[:] = saved
        comm_mod._warned_missing_trace_ref = warned
