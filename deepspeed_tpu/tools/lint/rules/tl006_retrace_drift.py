"""TL006 — jit-signature instability (retrace drift).

A jitted program's cache key is the abstract signature of its arguments
plus the hash of its static args.  Three source patterns quietly destabilize
that key, so a program that should compile ONCE recompiles under drifting
host bookkeeping (the serving engine's one-decode-executable invariant is
exactly this bug class away from regressing):

* **Python scalars in traced positions** — a Python ``int``/``float``/
  ``bool`` literal traces as a *weak-typed* array; call sites that mix
  scalars with real arrays in the same position split the jit cache in two
  (weak vs strong type), and the executable compiled for one refuses the
  other.  Pin the dtype: ``jnp.asarray(x, jnp.int32)``.
* **identity-hashed static args** — a freshly-constructed object (any
  call expression that is not a value-semantics constructor) in a
  ``static_argnums``/``static_argnames`` position hashes by ``id()``:
  every call builds a new object, every call recompiles.  (Unhashable
  literals and array-valued statics are TL004's.)
* **shape-dependent host branches on a hot path** — an ``if``/``while``
  on ``.shape``/``.ndim``/``len(arg)`` selects a different program per
  distinct shape.  Deliberate bucketing is fine — suppress with the
  reason; an unbucketed branch is one odd request away from a 30 s
  recompile mid-serve.

The static rule is paired with a RUNTIME retrace counter
(``tools/lint/retrace_check.py``): dispatch the real serving programs for
several rounds with drifting host bookkeeping and assert each compiled
exactly once.
"""

import ast

from deepspeed_tpu.tools.lint.core import Finding, dotted_name, rule
from deepspeed_tpu.tools.lint.rules.tl002_missing_donation import (
    JIT_NAMES, jit_decorator_kwargs)
from deepspeed_tpu.tools.lint.rules.tl004_bad_static_args import (
    _ARRAY_CTORS, _static_spec)

# value-semantics constructors: hash by content, stable across calls
_SAFE_STATIC_CTORS = {"tuple", "frozenset", "str", "int", "float", "bool",
                      "len"}


def _is_py_scalar(node):
    if isinstance(node, ast.UnaryOp) and \
            isinstance(node.op, (ast.USub, ast.UAdd)):
        node = node.operand
    return isinstance(node, ast.Constant) and \
        isinstance(node.value, (int, float, bool)) and \
        not isinstance(node.value, str)


def _positional_params(fn_node):
    """Names a positional call argument can bind to, in order."""
    a = fn_node.args
    return [p.arg for p in (*a.posonlyargs, *a.args)]


def _jitted_callables(module):
    """Bare name -> (static_nums, static_names, positional_params) for
    every callable the module jit-wraps: ``x = jax.jit(f, ...)`` bindings
    and ``@jit``-decorated defs.  ``positional_params`` is None when the
    wrapped callable's signature is not module-locally resolvable."""
    defs = {fn.name: fn.node for fn in module.functions}
    out = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                and dotted_name(node.value.func) in JIT_NAMES:
            nums, names = _static_spec(node.value.keywords) or ((), ())
            wrapped = node.value.args[0] if node.value.args else None
            params = None
            if isinstance(wrapped, ast.Name) and wrapped.id in defs:
                params = _positional_params(defs[wrapped.id])
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = (nums, names, params)
    for fn in module.functions:
        kws = jit_decorator_kwargs(fn.node)
        if kws is not None:
            nums, names = _static_spec(kws) or ((), ())
            out[fn.name] = (nums, names, _positional_params(fn.node))
    return out


def _static_positions(nums, names, params):
    """All positional indices that are static.  Second value is False when
    ``static_argnames`` exist but the signature is unknown — positional
    traced-vs-static can't be decided, so scalar checks must stand down."""
    if not names:
        return set(nums), True
    if params is None:
        return set(nums), False
    return set(nums) | {params.index(n) for n in names if n in params}, True


def _unstable_static(node):
    """Why this static-arg expression recompiles every call, or None."""
    if isinstance(node, ast.Lambda):
        return "a lambda (hashes by identity -> recompiles every call)"
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if name in _ARRAY_CTORS:        # TL004 flags arrays already
            return None
        if name is None or name.split(".")[-1] not in _SAFE_STATIC_CTORS:
            return (f"a freshly-constructed object "
                    f"({name or 'call result'}: hashes by identity -> "
                    f"recompiles every call)")
    return None


def _shape_probe(test, params):
    """The shape/ndim/len read inside a branch test, or None.  ``len()``
    only counts on a function PARAMETER — ``len`` of a host-local list is
    ordinary bookkeeping, not a shape probe."""
    for node in ast.walk(test):
        if isinstance(node, ast.Attribute) and node.attr in ("shape", "ndim"):
            return f".{node.attr}"
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "len" and node.args \
                and isinstance(node.args[0], ast.Name) \
                and node.args[0].id in params:
            return f"len({node.args[0].id})"
    return None


@rule("TL006", "jit-signature instability (retrace drift)")
def check(module):
    jitted = _jitted_callables(module)

    # (a) Python scalars in traced positions, (b) identity-hashed statics
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = node.func
        spec = None
        cname = None
        if isinstance(callee, ast.Name) and callee.id in jitted:
            spec, cname = jitted[callee.id], callee.id
        elif isinstance(callee, ast.Call) and \
                dotted_name(callee.func) in JIT_NAMES:
            # inline jax.jit(f, ...)(args)
            nums, names = _static_spec(callee.keywords) or ((), ())
            wrapped = callee.args[0] if callee.args else None
            params = None
            if isinstance(wrapped, ast.Name):
                for fn in module.functions:
                    if fn.name == wrapped.id:
                        params = _positional_params(fn.node)
                        break
            spec = (nums, names, params)
            cname = dotted_name(wrapped) if wrapped is not None else "jit"
        if spec is None:
            continue
        nums, names, params = spec
        static_pos, pos_known = _static_positions(nums, names, params)
        for i, arg in enumerate(node.args):
            if i in static_pos:
                why = _unstable_static(arg)
                if why:
                    yield Finding(
                        "TL006", module.path, arg.lineno, arg.col_offset,
                        f"static arg {i} of jitted '{cname}' is {why}")
            elif pos_known and _is_py_scalar(arg):
                yield Finding(
                    "TL006", module.path, arg.lineno, arg.col_offset,
                    f"Python scalar in traced position {i} of jitted "
                    f"'{cname}' — traces weak-typed; mixed scalar/array "
                    f"call sites split the jit cache (pin with "
                    f"jnp.asarray(x, dtype))")
        for kw in node.keywords:
            if kw.arg is None:
                continue
            if kw.arg in names:
                why = _unstable_static(kw.value)
                if why:
                    yield Finding(
                        "TL006", module.path, kw.value.lineno,
                        kw.value.col_offset,
                        f"static arg '{kw.arg}' of jitted '{cname}' is "
                        f"{why}")
            elif _is_py_scalar(kw.value):
                yield Finding(
                    "TL006", module.path, kw.value.lineno,
                    kw.value.col_offset,
                    f"Python scalar in traced argument '{kw.arg}' of "
                    f"jitted '{cname}' — traces weak-typed; pin with "
                    f"jnp.asarray(x, dtype)")

    # (c) shape-dependent host branches on hot paths
    for fn in module.hot_functions():
        own = set()
        for child in ast.walk(fn.node):
            if child is not fn.node and isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                own.update(ast.walk(child))
        params = set(fn.params)
        for node in ast.walk(fn.node):
            if node in own or not isinstance(node, (ast.If, ast.While)):
                continue
            probe = _shape_probe(node.test, params)
            if probe:
                yield Finding(
                    "TL006", module.path, node.lineno, node.col_offset,
                    f"shape-dependent host branch ({probe}) inside hot "
                    f"path '{fn.hot_name or fn.name}' — each distinct "
                    f"shape mints a separate executable; bucket/pad "
                    f"shapes (suppress with the reason when this IS the "
                    f"bucketing)")
