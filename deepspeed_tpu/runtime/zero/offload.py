"""ZeRO-Offload: host-resident optimizer driven by the native C++ Adam.

TPU-native equivalent of the reference's ZeRO-Offload optimizer path
(``runtime/zero/stage_1_and_2.py:1037-1162`` CPU-offload grad copy +
``deepspeed/ops/adam/cpu_adam.py`` step + 16-bit param copy-back, and the
NVMe tier of ``runtime/zero/stage3.py:1637,1686`` optimizer-state swap):

* device keeps only bf16 working params (HBM savings = the point of offload);
* fp32 masters + Adam moments live in host RAM (device="cpu") or in NVMe
  swap files with a bounded host buffer pool (device="nvme");
* at each boundary, grads are unscaled/clipped on device (jitted), pulled to
  host, stepped leaf-by-leaf by ``csrc/adam/cpu_adam.cpp`` (bf16 copy-out in
  the same pass), and the bf16 leaves are shipped back to the device mesh —
  with NVMe reads for the next leaf prefetched behind the current leaf's
  compute (reference ``pipelined_optimizer_swapper.py``).
"""

import os
from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.adam import cpu_adam as cpu_adam_mod
from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.utils.logging import log_dist


class HostOffloadedAdam:
    """Host Adam over the param pytree, with optional NVMe state residency."""

    _instances = 0  # per-process engine counter for swap-dir namespacing

    def __init__(self, abstract_params, offload_config, lr=1e-3,
                 betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 adamw_mode=True, bias_correction=True):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.bias_correction = bias_correction
        self.step_count = 0

        self.nvme = offload_config.device == "nvme"
        self.pipeline_read = bool(getattr(offload_config, "pipeline_read", False))
        leaves, self.treedef = jax.tree.flatten(abstract_params)
        self.shapes = [l.shape for l in leaves]
        self.numels = [int(np.prod(l.shape)) for l in leaves]
        self.names = [f"leaf{i}" for i in range(len(leaves))]

        if self.nvme:
            from deepspeed_tpu.runtime.swap_tensor.optimizer_swapper import \
                OptimizerSwapper
            base = offload_config.nvme_path or "/tmp/dstpu_nvme_swap"
            # namespace by process identity + per-process instance counter:
            # two jobs, ranks, or engines sharing an nvme_path must not
            # clobber each other's swap files (the reference namespaces swap
            # paths by rank); the dir is torn down at exit — swap files are
            # runtime state only (checkpoints go through save()/load())
            HostOffloadedAdam._instances += 1
            swap_dir = os.path.join(
                base, f"rank{jax.process_index()}_pid{os.getpid()}"
                      f"_e{HostOffloadedAdam._instances}")
            self._swap_dir = swap_dir
            import atexit
            import shutil
            atexit.register(shutil.rmtree, swap_dir, ignore_errors=True)
            self.swapper = OptimizerSwapper(
                swap_dir,
                buffer_count=getattr(offload_config, "buffer_count", 4),
                pipeline_read=self.pipeline_read,
                pipeline_write=bool(getattr(offload_config, "pipeline_write", False)))
            # bounded reusable host staging: two sets of 3 state arrays —
            # ping-pong so pipeline_read can prefetch leaf i+1 behind the
            # compute on leaf i (reference pipelined_optimizer_swapper.py)
            maxn = max(self.numels) if self.numels else 0
            self._stage = [[np.zeros(maxn, np.float32) for _ in range(3)]
                           for _ in range(2)]
            self.cpu_opt = None
        else:
            self.swapper = None
            # CPU path delegates to the public host optimizer (single
            # implementation of the per-shard loop; reference
            # deepspeed/ops/adam/cpu_adam.py DeepSpeedCPUAdam)
            self.cpu_opt = None  # built by init_from_params

    # -------------------------------------------------------------- #
    def init_from_params(self, params):
        """Download device params once to seed fp32 host masters
        (reference stage_1_and_2.py:576 partitioned fp32 master creation).
        NVMe path streams leaf-by-leaf so peak host RAM stays one leaf."""
        if self.nvme:
            for name, n, leaf in zip(self.names, self.numels,
                                     jax.tree.leaves(params)):
                m = np.asarray(jax.device_get(leaf), dtype=np.float32).ravel()
                self.swapper.register(name, n, m, np.zeros(n, np.float32),
                                      np.zeros(n, np.float32))
                del m
            self.swapper.drain()
            log_dist(f"offloaded optimizer state for {len(self.names)} leaves "
                     f"to NVMe", ranks=[0])
        else:
            host = [np.ascontiguousarray(
                        np.asarray(jax.device_get(l), dtype=np.float32).ravel())
                    for l in jax.tree.leaves(params)]
            self.cpu_opt = DeepSpeedCPUAdam(
                host, lr=self.lr, betas=(self.beta1, self.beta2), eps=self.eps,
                weight_decay=self.weight_decay, adamw_mode=self.adamw_mode,
                bias_correction=self.bias_correction)

    @staticmethod
    def _host_master(leaf):
        """Device leaf → fresh writable fp32 host vector (device_get views
        can be read-only)."""
        return np.ascontiguousarray(
            np.asarray(jax.device_get(leaf), dtype=np.float32).ravel())

    def reseed_masters(self, params):
        """Overwrite ONLY the fp32 master values from ``params``, keeping
        Adam moments and step count — the write-back half of
        ``zero.GatheredParameters`` surgery (full ``init_from_params``
        would zero m/v and restart bias correction)."""
        leaves = jax.tree.leaves(params)
        if self.nvme:
            for name, leaf in zip(self.names, leaves):
                self.swapper.update_master(name, self._host_master(leaf))
            self.swapper.drain()
        else:
            for i, leaf in enumerate(leaves):
                # the native Adam reads the list per step — installing a
                # fresh array is safe
                self.cpu_opt.params[i] = self._host_master(leaf)

    # -------------------------------------------------------------- #
    def step(self, host_grads, lr=None, fp32_out=False):
        """One Adam step over all leaves.  Returns flat per-leaf arrays for
        the device upload: bf16 (uint16 view) by default, or the updated
        fp32 masters when ``fp32_out`` (fp32-compute training must not round
        working params through bf16)."""
        self.step_count += 1
        lr = float(self.lr if lr is None else lr)
        # optimizer state is flat per leaf; grads may arrive leaf-shaped
        host_grads = [np.ascontiguousarray(g).ravel() for g in host_grads]
        outs = []
        if not self.nvme:
            bf_outs = None if fp32_out else \
                [np.empty(n, np.uint16) for n in self.numels]
            self.cpu_opt.step(host_grads, bf16_outs=bf_outs, lr=lr)
            self.step_count = self.cpu_opt.step_count
            return self.cpu_opt.params if fp32_out else bf_outs

        # NVMe path: ping-pong staging — with pipeline_read the next leaf's
        # state streams in behind the current leaf's C++ Adam compute
        # (reference pipelined_optimizer_swapper.py); writes drain lazily
        # unless pipeline_write=False (the swapper enforces that).
        n_leaves = len(host_grads)
        if self.pipeline_read and n_leaves > 1:
            self.swapper.start_swap_in(self.names[0], self._stage[0])
            self.swapper.finish_swap_ins()
        for i, g in enumerate(host_grads):
            n = self.numels[i]
            cur = self._stage[i % 2]
            if self.pipeline_read and n_leaves > 1:
                if i + 1 < n_leaves:   # prefetch next behind this compute
                    self.swapper.start_swap_in(self.names[i + 1],
                                               self._stage[(i + 1) % 2])
            else:
                self.swapper.swap_in(self.names[i], *cur)
            bf = None if fp32_out else np.empty(n, np.uint16)
            cpu_adam_mod.adam_step(
                cur[0][:n], cur[1][:n], cur[2][:n],
                np.ascontiguousarray(g, np.float32).ravel(),
                lr, self.beta1, self.beta2, self.eps, self.weight_decay,
                self.adamw_mode, self.bias_correction, self.step_count,
                bf16_out=bf)
            self.swapper.swap_out(self.names[i], *cur)
            if self.pipeline_read and n_leaves > 1 and i + 1 < n_leaves:
                self.swapper.finish_swap_ins()
            # staging buffers are reused next leaf — fp32 upload needs a copy
            outs.append(cur[0][:n].copy() if fp32_out else bf)
        self.swapper.drain()
        return outs

    @property
    def masters(self):
        """fp32 master shards (CPU residency only; NVMe states live in swap
        files — use ``_iter_states``/``master_params_tree``)."""
        if self.nvme:
            raise AttributeError("masters are NVMe-resident; use "
                                 "master_params_tree()")
        return self.cpu_opt.params

    # -------------------------------------------------------------- #
    def _iter_states(self):
        """Yield (index, master, exp_avg, exp_avg_sq) leaf by leaf, with
        NVMe reads streamed through one staging set so peak host RAM stays
        one leaf regardless of model size."""
        if not self.nvme:
            for i in range(len(self.names)):
                yield (i, self.cpu_opt.params[i], self.cpu_opt.exp_avg[i],
                       self.cpu_opt.exp_avg_sq[i])
            return
        for i, (name, n) in enumerate(zip(self.names, self.numels)):
            m = np.empty(n, np.float32)
            a = np.empty(n, np.float32)
            v = np.empty(n, np.float32)
            self.swapper.swap_in(name, m, a, v)
            yield i, m, a, v

    def save(self, ckpt_dir):
        """Stream state to per-leaf .npy files (never pickles the whole
        model; reference _save_zero_checkpoint per-rank files,
        engine.py:3220)."""
        import os
        os.makedirs(ckpt_dir, exist_ok=True)
        np.save(os.path.join(ckpt_dir, "step.npy"), np.int64(self.step_count))
        for i, m, a, v in self._iter_states():
            np.save(os.path.join(ckpt_dir, f"leaf{i}.master.npy"), m)
            np.save(os.path.join(ckpt_dir, f"leaf{i}.exp_avg.npy"), a)
            np.save(os.path.join(ckpt_dir, f"leaf{i}.exp_avg_sq.npy"), v)

    def load(self, ckpt_dir):
        import os
        self.step_count = int(np.load(os.path.join(ckpt_dir, "step.npy")))
        for i, (name, n) in enumerate(zip(self.names, self.numels)):
            m = np.ascontiguousarray(
                np.load(os.path.join(ckpt_dir, f"leaf{i}.master.npy")), np.float32)
            a = np.ascontiguousarray(
                np.load(os.path.join(ckpt_dir, f"leaf{i}.exp_avg.npy")), np.float32)
            v = np.ascontiguousarray(
                np.load(os.path.join(ckpt_dir, f"leaf{i}.exp_avg_sq.npy")), np.float32)
            if self.nvme:
                if name in self.swapper.groups:
                    self.swapper.swap_out(name, m, a, v)
                else:
                    self.swapper.register(name, n, m, a, v)
            else:
                self.cpu_opt.params[i] = m
                self.cpu_opt.exp_avg[i] = a
                self.cpu_opt.exp_avg_sq[i] = v
        if self.nvme:
            self.swapper.drain()
        else:
            self.cpu_opt.step_count = self.step_count

    # kept for programmatic access (tests, universal checkpoint)
    def state_dict(self) -> Dict[str, Any]:
        ms, avs, vs = [], [], []
        for _, m, a, v in self._iter_states():
            ms.append(m); avs.append(a); vs.append(v)
        return {"step": self.step_count,
                "masters": ms, "exp_avg": avs, "exp_avg_sq": vs}

    def load_state_dict(self, sd):
        self.step_count = int(sd["step"])
        ms = [np.ascontiguousarray(a, np.float32).ravel() for a in sd["masters"]]
        avs = [np.ascontiguousarray(a, np.float32).ravel() for a in sd["exp_avg"]]
        vs = [np.ascontiguousarray(a, np.float32).ravel() for a in sd["exp_avg_sq"]]
        if self.nvme:
            for name, n, m, a, v in zip(self.names, self.numels, ms, avs, vs):
                if name in self.swapper.groups:
                    self.swapper.swap_out(name, m, a, v)
                else:
                    self.swapper.register(name, n, m, a, v)
            self.swapper.drain()
        else:
            self.cpu_opt.params = ms
            self.cpu_opt.exp_avg = avs
            self.cpu_opt.exp_avg_sq = vs
            self.cpu_opt.step_count = self.step_count

    def master_params_tree(self):
        """fp32 masters as the original pytree (zero_to_fp32 path)."""
        ms = [m.copy() for _, m, _, _ in self._iter_states()]
        return jax.tree.unflatten(
            self.treedef,
            [m.reshape(s) for m, s in zip(ms, self.shapes)])

    def bf16_leaves_to_tree(self, bf_leaves):
        import ml_dtypes
        arrs = [b.view(ml_dtypes.bfloat16).reshape(s)
                for b, s in zip(bf_leaves, self.shapes)]
        return jax.tree.unflatten(self.treedef, arrs)

    def leaves_to_tree(self, leaves):
        """Flat per-leaf step() outputs -> param pytree.  uint16 leaves are
        bf16 views; fp32 leaves pass through (fp32_out path)."""
        if leaves and leaves[0].dtype == np.uint16:
            return self.bf16_leaves_to_tree(leaves)
        return jax.tree.unflatten(
            self.treedef, [a.reshape(s) for a, s in zip(leaves, self.shapes)])
