"""Synthetic-break plans module for the ``ds_lint --comm`` prover tests.

Loaded via ``DSTPU_COMM_PLANS_MODULE`` (a .py path): one deliberately
broken plan whose batch enters the mesh program fully replicated while the
global batch scales with the mesh (weak scaling) — the per-chip all-reduce
volume therefore GROWS with mesh size, the exact replicated-tensor smell
the scaling prover must fail on, readably, with no ``allowed_growth``
escape hatch declared."""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.parallel.plans import PlanProgram
from deepspeed_tpu.utils.jax_compat import shard_map

MESH_POINTS = (1, 2, 4)


def replicated_batch_plan(world=4):
    mesh = Mesh(np.array(jax.devices()[:world]), ("tp",))

    def body(batch, w):   # tpu-lint: disable=TL010 -- fixture: the replication IS the synthetic break
        return jax.lax.psum(batch * w.sum(), "tp")

    fn = jax.jit(shard_map(body, mesh=mesh,
                           in_specs=(P(), P(None, "tp")),
                           out_specs=P()))
    batch = jnp.ones((4 * world, 16), jnp.float32)   # weak scaling
    w = jnp.ones((16, 8), jnp.float32)
    return PlanProgram("fixture.replicated_batch", fn, (batch, w),
                       mesh={"tp": world}, reduction=False, world=world)


PLAN_BUILDERS = (replicated_batch_plan,)
