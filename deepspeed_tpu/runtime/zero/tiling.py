"""TiledLinear — reference ``runtime/zero/tiling.py`` (``TiledLinear``,
296 LoC): split a huge linear into row/column tiles so ZeRO-3 only gathers
one tile's weights at a time.

TPU redesign: the memory motive survives (a tiled linear bounds the live
weight working set; with params sharded over dp, each tile all-gathers
independently and XLA frees it after use).  ``in_splits``/``out_splits``
match the reference; ``input_is_already_split`` supports pre-chunked inputs
like the reference's Megatron integration."""

from typing import Any, Callable, Optional

import jax.numpy as jnp
import flax.linen as nn


class TiledLinear(nn.Module):
    in_features: int
    out_features: int
    in_splits: int = 1
    out_splits: int = 1
    use_bias: bool = True
    input_is_already_split: bool = False
    dtype: Any = jnp.float32
    kernel_init: Optional[Callable] = None

    def _split_input(self, x):
        assert self.in_features % self.in_splits == 0, \
            f"in_features {self.in_features} % in_splits {self.in_splits}"
        in_tile = self.in_features // self.in_splits
        if self.input_is_already_split:
            assert len(x) == self.in_splits
            return list(x)
        return [x[..., i * in_tile:(i + 1) * in_tile]
                for i in range(self.in_splits)]

    def _tile_matmuls(self, xs):
        assert self.out_features % self.out_splits == 0, \
            f"out_features {self.out_features} % out_splits {self.out_splits}"
        out_tile = self.out_features // self.out_splits
        init = self.kernel_init or nn.initializers.lecun_normal()
        outs = []
        for o in range(self.out_splits):
            acc = None
            for i in range(self.in_splits):
                # one (in_tile × out_tile) weight live at a time — under
                # ZeRO-3 sharding this bounds the gathered working set
                y = nn.Dense(out_tile, use_bias=False, dtype=self.dtype,
                             kernel_init=init,
                             name=f"tile_{o}_{i}")(xs[i])
                acc = y if acc is None else acc + y
            outs.append(acc)
        return outs

    def _biases(self):
        out_tile = self.out_features // self.out_splits
        return [self.param(f"bias_{o}", nn.initializers.zeros, (out_tile,),
                           jnp.float32) for o in range(self.out_splits)]

    @nn.compact
    def __call__(self, x):
        outs = self._tile_matmuls(self._split_input(x))
        if self.use_bias:
            outs = [acc + b.astype(acc.dtype)
                    for acc, b in zip(outs, self._biases())]
        return jnp.concatenate(outs, axis=-1)

    @staticmethod
    def full_weight(params, in_splits, out_splits):
        """Reassemble the logical [in, out] kernel from tile params (the
        reference's ``copy_params_from`` inverse, for checkpoint export)."""
        rows = []
        for i in range(in_splits):
            cols = [params[f"tile_{o}_{i}"]["kernel"] for o in range(out_splits)]
            rows.append(jnp.concatenate(cols, axis=-1))
        return jnp.concatenate(rows, axis=0)


class TiledLinearReturnBias(TiledLinear):
    """Reference ``TiledLinearReturnBias``: returns (out, bias) unsummed so a
    caller can defer the bias add (Megatron-style layers fuse it later)."""

    @nn.compact
    def __call__(self, x):
        outs = self._tile_matmuls(self._split_input(x))
        y = jnp.concatenate(outs, axis=-1)
        if not self.use_bias:
            return y, None
        bias = jnp.concatenate(self._biases(), axis=0).astype(y.dtype)
        return y, bias
