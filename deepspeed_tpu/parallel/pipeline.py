"""SPMD pipeline parallelism over the ``pp`` mesh axis.

TPU-native re-design of the reference pipeline engine
(``runtime/pipe/engine.py:42``, ``schedule.py:135,189``, ``p2p.py:50,71``).
The reference interprets an instruction schedule per-rank and exchanges
activations with NCCL point-to-point sends.  Under single-controller SPMD the
whole schedule becomes ONE differentiable program:

* stages are shards of the ``pp`` axis inside ``shard_map`` (manual over
  ``pp`` only — dp/tp/sp/ep stay GSPMD-automatic);
* the schedule is a ``lax.scan`` over ticks; stage *s* works on microbatch
  ``m = t - s`` (the classic pipeline wavefront);
* activation transfer is one ``lax.ppermute`` per tick riding ICI neighbors
  (both halves of the reference's send/recv pair);
* the backward pipeline is **not hand-written**: differentiating the scan
  yields the reverse wavefront with reversed ppermutes automatically, with
  the per-tick stage inputs as residuals (= the reference's activation
  stash).  ``jax.checkpoint`` on the stage body gives the same memory
  behavior as its activation-checkpointed stages.

Schedule honesty: this is a **fill-drain (GPipe) schedule** — all M
microbatches flow forward, then backward.  Its bubble fraction,
``(P-1)/(M+P-1)``, matches 1F1B, but its activation stash grows with M
where the reference's ``TrainSchedule`` (1F1B, ``schedule.py:189``) bounds
in-flight microbatches to ~P.  The 1F1B-class memory bound is provided by
the engine's chunked accumulation (``pipeline.max_in_flight_microbatches``):
chunks of C microbatches are differentiated one at a time, so at most C
stage inputs are ever stashed, at the cost of a per-chunk bubble
``(P-1)/(C+P-1)``.

Activations may be arbitrary pytrees (e.g. ``(hidden, aux_loss)`` for MoE
trunks); every per-tick primitive is tree-mapped.
"""

import jax
import jax.numpy as jnp
from jax import lax

from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import PP_AXIS


def spmd_pipeline(stage_fn, stacked_params, x0, num_micro, mesh,
                  pp_axis=PP_AXIS, remat_stage=True):
    """Run the pipelined forward: returns last-stage outputs ``[M, ...]``.

    ``stage_fn(stage_params, x) -> y`` maps one stage over one microbatch
    activation (a pytree; same structure/shapes in and out).
    ``stacked_params`` leaves have leading dim P (one slice per stage).
    ``x0``: pytree of ``[M, ...]`` microbatch activations entering stage 0.
    Fully differentiable.
    """
    n_stages = mesh.shape[pp_axis]
    if remat_stage:
        stage_fn = jax.checkpoint(stage_fn)

    # XLA's CPU backend (the simulated test mesh) crashes promoting bf16
    # all-reduces, which the region's backward emits for the replicated x0
    # cotangent.  Run the region in f32 on CPU; TPU stays bf16.
    cast_back = None
    if jax.default_backend() == "cpu" and any(
            l.dtype == jnp.bfloat16 for l in jax.tree.leaves(x0)):
        orig_dtypes = jax.tree.map(lambda l: l.dtype, x0)
        cast_back = orig_dtypes
        up = lambda t: jax.tree.map(
            lambda l: l.astype(jnp.float32)
            if l.dtype == jnp.bfloat16 else l, t)
        down = lambda t: jax.tree.map(
            lambda l, d: l.astype(d), t, orig_dtypes)
        inner_stage_fn = stage_fn
        stage_fn = lambda p, x: up(inner_stage_fn(p, down(x)))
        x0 = up(x0)

    def region(params, x0):
        sid = lax.axis_index(pp_axis)
        M = num_micro
        T = M + n_stages - 1
        params_local = jax.tree.map(lambda a: jnp.squeeze(a, 0), params)
        state0 = jax.tree.map(lambda l: jnp.zeros_like(l[0]), x0)

        fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(state, t):
            # receive previous stage's activation (stage 0 receives zeros)
            recv = jax.tree.map(
                lambda l: lax.ppermute(l, pp_axis, fwd_perm),
                state) if n_stages > 1 else state
            x_t = jax.tree.map(
                lambda l: lax.dynamic_index_in_dim(
                    l, jnp.minimum(t, M - 1), 0, keepdims=False), x0)
            inp = jax.tree.map(lambda a, b: jnp.where(sid == 0, a, b),
                               x_t, recv)
            m = t - sid
            active = jnp.logical_and(m >= 0, m < M)
            y = stage_fn(params_local, inp)
            y = jax.tree.map(
                lambda l: jnp.where(active, l, jnp.zeros_like(l)), y)
            # emit only the last stage's finished microbatches
            emit = jnp.logical_and(active, sid == n_stages - 1)
            out = jax.tree.map(
                lambda l: jnp.where(emit, l, jnp.zeros_like(l)), y)
            return y, out

        _, outs = lax.scan(tick, state0, jnp.arange(T))
        # outs[t] holds microbatch m = t-(P-1) on the last stage, zeros
        # elsewhere; psum over pp broadcasts last-stage values to all shards.
        outs = jax.tree.map(lambda l: l[n_stages - 1:], outs)
        if n_stages > 1:
            outs = lax.psum(outs, pp_axis)
        return outs

    in_specs = (jax.tree.map(lambda _: P(pp_axis), stacked_params), P())
    out = jax.shard_map(
        region, mesh=mesh, in_specs=in_specs, out_specs=P(),
        axis_names=frozenset({pp_axis}), check_vma=False,
    )(stacked_params, x0)
    if cast_back is not None:
        out = jax.tree.map(lambda l, d: l.astype(d), out, cast_back)
    return out  # structure matches x0 (stage in == stage out)


def pipeline_bubble_fraction(num_micro, num_stages):
    return (num_stages - 1) / (num_micro + num_stages - 1)


def stack_stage_params(per_layer_params, num_stages):
    """Group L per-layer param trees (identical structure) into
    ``[P, L/P, ...]`` stacked pytrees for the SPMD pipeline."""
    L = len(per_layer_params)
    if L % num_stages != 0:
        raise ValueError(f"{L} body layers not divisible by {num_stages} stages")
    per_stage = L // num_stages
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *per_layer_params)
    return jax.tree.map(
        lambda a: a.reshape(num_stages, per_stage, *a.shape[1:]), stacked)
