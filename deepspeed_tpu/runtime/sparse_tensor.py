"""Sparse gradients — reference ``runtime/sparse_tensor.py`` (``SparseTensor``)
and the engine's ``sparse_allreduce_no_retain`` path (``engine.py:2312``) for
sparse embedding gradients.

COO representation: ``indices`` [nnz] row ids + ``values`` [nnz, row_dim].
The reduction allgathers (indices, values) over the dp axis — exactly what
the reference's sparse allreduce does with all_gather of irregular tensors —
then either keeps the concatenated COO or densifies via ``segment_sum``
(duplicate rows add, matching embedding-grad semantics).  XLA needs static
nnz, so each rank's nnz is padded to the max (padding rows point at row 0
with zero values).
"""

from typing import Any, NamedTuple

import numpy as np

import jax
import jax.numpy as jnp


class SparseTensor(NamedTuple):
    indices: Any          # [nnz] int32 row indices
    values: Any           # [nnz, row_dim]
    dense_size: Any       # (num_rows, row_dim)

    @staticmethod
    def from_dense(dense, threshold=0.0):
        """Rows with any |value| > threshold become COO entries (embedding
        grads: most rows are exactly zero)."""
        d = np.asarray(dense)
        nz = np.where(np.abs(d).max(axis=tuple(range(1, d.ndim))) > threshold)[0]
        return SparseTensor(indices=jnp.asarray(nz, jnp.int32),
                            values=jnp.asarray(d[nz]),
                            dense_size=d.shape)

    def to_dense(self):
        out = jnp.zeros(self.dense_size, self.values.dtype)
        return out.at[self.indices].add(self.values)

    def sparse_size(self):
        nnz = int(np.prod(self.values.shape))
        return nnz, int(np.prod(self.dense_size))


def sparse_allreduce(sparse, axis, mesh=None):
    """Mean-allreduce a per-device SparseTensor over mesh axis ``axis``;
    callable inside shard_map (reference ``engine.py:2340 sparse_allreduce``).
    Returns a SparseTensor whose COO lists are the concatenation over the
    axis (values pre-divided by world size)."""
    from jax import lax
    W = lax.psum(1, axis)
    idx = lax.all_gather(sparse.indices, axis, tiled=True)
    vals = lax.all_gather(sparse.values, axis, tiled=True) / W
    return SparseTensor(idx, vals, sparse.dense_size)


def sparse_allreduce_to_dense(dense_grad, axis):
    """Densifying fallback (reference ``sparse_allreduce_no_retain`` with
    dense output): psum is already optimal when rows are mostly nonzero."""
    from jax import lax
    return lax.pmean(dense_grad, axis)
