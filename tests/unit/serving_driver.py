"""Subprocess driver for the serving kill-at-seam proof
(``test_serving_slo.py``).

Serves a fixed, seeded workload (5 greedy requests + 1 already-expired
deadline request) through ``serve_resilient`` on a tiny Transformer.
The test harness arms ``DSTPU_FAULT_INJECT`` at the serving seams
(``serving.sigterm_at_iter`` / ``serving.pre_admit`` /
``serving.pre_decode_dispatch`` / ``serving.mid_drain``) so this process
dies mid-serving — gracefully (SIGTERM → drain → crash-atomic snapshot)
or hard (``os._exit``) — then relaunches it clean.  A relaunch restores
the snapshot (original rids / client ids / partial tokens), re-submits
only the workload requests that are neither completed (results file) nor
restored, and finishes.  The merged per-request outputs must be
BITWISE-identical to an uninterrupted run, and the deadline request must
report ``SHED_DEADLINE`` without ever occupying a slot.

Results file: one ``<client_idx>,<status>,<tok tok ...>`` line per
terminal request, appended after the serve loop returns (last write
wins).  Exit codes: 0 done, 3 preempted (snapshot written), plus the
injected ``exit_code`` (default 17) when a hard kill fires.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("DSTPU_ACCELERATOR", "cpu")
sys.path.insert(0, os.environ["DSTPU_REPO_ROOT"])

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# per-harness compile cache, NEVER the suite's (see fault_driver.py: an
# os._exit mid-cache-write once poisoned the shared cache for every
# later process)
_cache = os.environ.get("DSTPU_DRIVER_CACHE")
if _cache:
    jax.config.update("jax_compilation_cache_dir", _cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import numpy as np  # noqa: E402

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.inference.serving.resilient import serve_resilient  # noqa: E402
from deepspeed_tpu.models.transformer import (Transformer,  # noqa: E402
                                              TransformerConfig)


def workload():
    """Deterministic request mix: 5 greedy requests and one whose
    deadline is already expired at submit (it must SHED, never admit).
    Entries: (prompt, max_new_tokens, deadline_s)."""
    rng = np.random.default_rng(42)
    reqs = []
    for _ in range(5):
        p = rng.integers(1, 97, (int(rng.integers(9, 21)),)).astype(np.int32)
        reqs.append((p, int(rng.integers(4, 11)), None))
    reqs.append((rng.integers(1, 97, (10,)).astype(np.int32), 6, 0.0))
    return reqs


def read_done(path):
    """client_idx -> (status, tokens) from the results file (last write
    wins — a resumed run may legitimately re-record nothing, but merging
    is what the test does too)."""
    done = {}
    if not os.path.exists(path):
        return done
    with open(path) as f:
        for line in f:
            parts = line.strip().split(",", 2)
            if len(parts) == 3:
                done[int(parts[0])] = (parts[1], parts[2])
    return done


def main():
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument("--ckpt-dir", required=True)
    parser.add_argument("--results", required=True)
    parser.add_argument("--drain-budget", type=float, default=0.0)
    # speculative serving (docs/serving.md "Speculative decoding"):
    # self-draft, k=2 — greedy outputs must stay BITWISE-identical to
    # the non-speculative reference run, and a SIGTERM mid-speculation
    # must snapshot committed tokens only
    parser.add_argument("--spec", action="store_true")
    args = parser.parse_args()

    cfg = TransformerConfig(vocab_size=97, hidden_size=32, num_layers=2,
                            num_heads=4, max_seq_len=64,
                            use_flash_attention=False, dtype="float32")
    model = Transformer(cfg)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.key(0), {"input_ids": ids})
    config = {
        "dtype": "float32", "prefill_chunk_size": 8,
        "serving": {"enabled": True, "num_slots": 2, "max_cache_len": 64,
                    "prefill_chunk": 8, "prefill_token_budget": 16,
                    "decode_block": 2,
                    "drain_budget_s": args.drain_budget,
                    **({"speculative": True, "spec_k": 2,
                        "spec_draft_model": "self"} if args.spec else {})},
    }
    if _cache:
        config["compile_cache"] = {"enabled": True, "cache_dir": _cache,
                                   "min_compile_time_secs": 0.0}
    eng = deepspeed_tpu.init_inference(model, config=config)
    eng.set_params(params)
    srv = eng.serve()

    restored = srv.restore(args.ckpt_dir)
    done = read_done(args.results)
    have = set(done) | {srv._requests[rid].client_id for rid in restored}
    for rid in restored:
        print(f"[driver] restored idx={srv._requests[rid].client_id} "
              f"rid={rid} prefix={len(srv._requests[rid].prefix)}",
              flush=True)
    rids = list(restored)
    for i, (p, n, dl) in enumerate(workload()):
        if i in have:
            continue
        rids.append(srv.submit(p, max_new_tokens=n, deadline_s=dl,
                               client_id=i))

    status, _results = serve_resilient(srv, args.ckpt_dir, resume=False)

    with open(args.results, "a") as f:
        for rid in rids:
            res = srv.result(rid)
            if res is None:               # preempted (snapshotted) — the
                continue                  # restarted run finishes it
            toks = " ".join(str(t) for t in res.output) \
                if res.output is not None else ""
            f.write(f"{res.client_id},{res.status},{toks}\n")
        f.flush()
        os.fsync(f.fileno())
    print(f"[driver] {status}", flush=True)
    return {"done": 0, "preempted": 3}[status]


if __name__ == "__main__":
    sys.exit(main())
