"""TL003 positive fixture: Python side effects inside jitted functions."""
import jax
from deepspeed_tpu.utils.logging import logger

_count = 0


@jax.jit
def step(x):
    global _count                        # TL003
    print("stepping", x)                 # TL003
    logger.info("traced value %s", x)    # TL003
    return x * 2


def loss_fn(x):
    print("loss", x)                     # TL003 (jit-wrapped below)
    return x


loss_jit = jax.jit(loss_fn)
