"""Experiment scheduler (reference ``deepspeed/autotuning/scheduler.py:33``
``ResourceManager``).

The reference fans experiments out over multi-node GPU slots via the
launcher.  On TPU an experiment is a fresh jitted program on the same
mesh, so the manager runs candidates sequentially in-process — each run
re-jits with the candidate's config, which is exactly the isolation the
reference gets from separate processes (XLA programs share nothing but the
device).
"""

import json
import os
import traceback


class Experiment:
    """One tuning trial: a full DeepSpeed config + results."""

    _next_id = 0

    def __init__(self, name, config):
        self.exp_id = Experiment._next_id
        Experiment._next_id += 1
        self.name = name
        self.config = config
        self.results = {}
        self.error = None

    def to_dict(self):
        return {"exp_id": self.exp_id, "name": self.name, "config": self.config,
                "results": self.results, "error": self.error}


class ResourceManager:
    """Runs experiments through a caller-supplied ``run_fn(exp) -> dict`` and
    persists each result under ``exps_dir`` (reference ResourceManager
    ``schedule_experiments``/``run_job``)."""

    def __init__(self, run_fn, exps_dir=None):
        self.run_fn = run_fn
        self.exps_dir = exps_dir
        self.finished_experiments = []
        if exps_dir:
            os.makedirs(exps_dir, exist_ok=True)

    def schedule_experiments(self, exps):
        for exp in exps:
            try:
                exp.results = self.run_fn(exp) or {}
            except Exception as e:  # an OOM/compile failure is a data point
                exp.error = f"{type(e).__name__}: {e}"
                exp.results = {}
                traceback.print_exc()
            self.finished_experiments.append(exp)
            if self.exps_dir:
                path = os.path.join(self.exps_dir, f"exp_{exp.exp_id}_{exp.name}.json")
                with open(path, "w") as f:
                    json.dump(exp.to_dict(), f, indent=2, default=str)
        return exps
