"""Multi-tenant fairness — per-``client_id`` token-rate accounting on a
decaying window (``docs/serving.md`` "Network front end").

A public endpoint in front of a fixed-capacity slot engine needs an
answer to the one abusive tenant problem: without accounting, a client
that submits 4x everyone else's load owns 4x the slots, and every other
client's TTFT degrades in proportion.  :class:`FairnessTracker` charges
each client for the work it actually consumes — admitted prefill tokens
at admission and generated tokens as the host mirror processes them —
into an exponentially decaying accumulator (time constant
``window_s``), and the serving engine's admission control refuses
``submit()`` (``QueueFull`` → HTTP 429) from any client whose window
usage exceeds ``tokens_per_s * window_s``.  Over-quota clients recover
as their usage decays; under-quota clients keep flowing the whole time
(``tests/unit/test_serving_frontend.py`` proves the light client's p99
TTFT stays bounded while only the heavy client sheds).

Host bookkeeping only — all calls run under the serving engine's lock,
and the state round-trips preemption snapshots so a restarted server
keeps enforcing the same quotas.

Concurrency contract: the tracker deliberately has NO lock of its own.
It is reachable only through the engine's ``_fairness`` attribute,
which is declared lock-guarded in the registry
(``inference/serving/concurrency.py`` — TL008 +
``DSTPU_CONCURRENCY_CHECKS``), so every window read/write inherits the
engine lock transitively; ``window_usage()`` compacts the map IN PLACE,
which is exactly why an unlocked iteration (the original ``/metrics``
bug) is unsafe.
"""

import math
import time


class FairnessTracker:
    """Decaying-window token accounting per client.

    ``usage(c)`` decays by ``1/e`` per ``window_s`` seconds, so the
    sustainable steady-state rate is exactly ``tokens_per_s`` and a
    silent client's balance is forgotten after a few windows.  Clients
    are keyed by ``str(client_id)`` (client ids are opaque and may be
    unhashable).  ``clock`` is injectable for deterministic tests."""

    def __init__(self, tokens_per_s, window_s=10.0, clock=time.monotonic):
        self.tokens_per_s = float(tokens_per_s)
        self.window_s = float(window_s)
        if self.tokens_per_s <= 0:
            raise ValueError(f"fairness_tokens_per_s={tokens_per_s}: "
                             f"need > 0 (0 disables fairness upstream)")
        if self.window_s <= 0:
            raise ValueError(f"fairness_window_s={window_s}: need > 0")
        self._clock = clock
        self._usage = {}                 # key -> [window_tokens, last_t]

    @property
    def budget(self):
        """The window budget: usage past it denies admission."""
        return self.tokens_per_s * self.window_s

    @staticmethod
    def key(client_id):
        return str(client_id)

    def _decayed(self, entry, now):
        tokens, t = entry
        if now > t:
            tokens *= math.exp(-(now - t) / self.window_s)
        return tokens

    def usage(self, client_id):
        """The client's current window-token balance (decayed to now)."""
        entry = self._usage.get(self.key(client_id))
        return self._decayed(entry, self._clock()) if entry else 0.0

    def allow(self, client_id):
        """Admission verdict: ``False`` while the client is over budget
        (the caller rejects with ``QueueFull`` — HTTP 429)."""
        return self.usage(client_id) < self.budget

    def charge(self, client_id, tokens):
        """Account ``tokens`` of consumed work (admitted prefill or
        generated tokens) to the client."""
        key = self.key(client_id)
        now = self._clock()
        entry = self._usage.get(key)
        balance = self._decayed(entry, now) if entry else 0.0
        self._usage[key] = [balance + float(tokens), now]

    def window_usage(self):
        """``{client_key: window_tokens}`` decayed to now (metrics and
        snapshots); near-zero balances are dropped so an old tenant set
        cannot grow the map forever."""
        now = self._clock()
        out = {}
        for key, entry in list(self._usage.items()):
            balance = self._decayed(entry, now)
            if balance < 1e-6:
                del self._usage[key]
                continue
            out[key] = balance
        return out

    def state_dict(self):
        """Snapshot payload: balances decayed to NOW.  Restore treats
        them as balances at restore time — decay during the downtime is
        deliberately not credited (conservative: a preempt/restore cycle
        never launders an over-quota client back under budget)."""
        return {"tokens_per_s": self.tokens_per_s,
                "window_s": self.window_s,
                "usage": self.window_usage()}

    def load_state(self, state):
        """Adopt a snapshot's balances (this tracker's own rate/window
        config wins — quotas are a server property, not snapshot
        payload)."""
        now = self._clock()
        for key, tokens in (state.get("usage") or {}).items():
            self._usage[str(key)] = [float(tokens), now]


__all__ = ["FairnessTracker"]
