"""1-bit LAMB — TPU-native re-design of reference
``runtime/fp16/onebit/lamb.py:14`` (OnebitLamb).

Algorithm (Li et al., "1-bit LAMB"): exact LAMB during ``freeze_step`` warmup;
afterwards the variance term and the per-tensor LAMB trust ratios are frozen
(the reference caches ``lamb_coeffs`` at the freeze boundary) and the momentum
is communicated compressed — modeled here as sign compression against ONE
flat-buffer ``‖·‖₂/√n`` scale shared with 1-bit Adam (``sign_compress``; the
reference normalizes its flat allreduce chunk the same way,
``runtime/comm/nccl.py:54``) with an error-feedback buffer.  Post-freeze, the
frozen trust ratio is scaled by the drift of that global momentum scale
(reference's ``scaling_coeff`` update — per-tensor there, global here) and
capped by the live trust ratio so the step norm stays within ``lr·‖w‖``.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.adam.onebit_adam import sign_compress


class OnebitLambState(NamedTuple):
    exp_avg: Any
    exp_avg_sq: Any
    error_feedback: Any
    frozen_lamb_coeff: Any   # per-tensor trust ratio cached at freeze
    frozen_m_scale: Any      # per-tensor mean|m| cached at freeze


class OnebitLamb:

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 max_coeff=10.0, min_coeff=0.01, freeze_step=100000,
                 cuda_aware=False, comm_backend_name="xla",
                 coeff_beta=0.9, factor_max=4.0, factor_min=0.5,
                 factor_threshold=0.1, master_dtype=jnp.float32):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.max_coeff = max_coeff
        self.min_coeff = min_coeff
        self.freeze_step = freeze_step
        self.factor_max = factor_max
        self.factor_min = factor_min
        self.master_dtype = master_dtype

    def init(self, params):
        zeros = lambda p: jnp.zeros(p.shape, dtype=self.master_dtype)
        scalar = lambda p: jnp.asarray(1.0, dtype=self.master_dtype)
        return OnebitLambState(
            exp_avg=jax.tree.map(zeros, params),
            exp_avg_sq=jax.tree.map(zeros, params),
            error_feedback=jax.tree.map(zeros, params),
            frozen_lamb_coeff=jax.tree.map(scalar, params),
            frozen_m_scale=jax.tree.map(scalar, params))

    def update(self, grads, state, params, lr=None, step=1):
        lr = self.lr if lr is None else lr
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay
        step = jnp.asarray(step, dtype=jnp.float32)
        warmup = step <= self.freeze_step
        at_freeze = step == self.freeze_step
        bc1 = 1.0 - b1 ** step
        bc2 = 1.0 - b2 ** jnp.minimum(step, float(self.freeze_step))

        md = self.master_dtype
        m_tree = jax.tree.map(lambda m, g: b1 * m + (1.0 - b1) * g.astype(md),
                              state.exp_avg, grads)
        # post-freeze: compressed momentum (flat-buffer sign compression with
        # error feedback, shared with 1-bit Adam)
        corrected_tree = jax.tree.map(jnp.add, m_tree, state.error_feedback)
        compressed_tree, scale = sign_compress(corrected_tree)

        def leaf(p, g, m_new, corrected, compressed, v, e, coeff, mscale):
            g32 = g.astype(md)
            p32 = p.astype(md)
            e_new = jnp.where(warmup, e, corrected - compressed)
            m_eff = jnp.where(warmup, m_new, compressed)
            v_new = jnp.where(warmup, b2 * v + (1.0 - b2) * (g32 * g32), v)
            upd = (m_eff / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if wd != 0.0:
                upd = upd + wd * p32
            # LAMB trust ratio: exact during warmup; frozen (and rescaled by
            # the momentum-scale drift, clipped to factor bounds) afterwards
            w_norm = jnp.linalg.norm(p32)
            u_norm = jnp.linalg.norm(upd)
            live = jnp.where((w_norm > 0) & (u_norm > 0),
                             jnp.clip(w_norm / jnp.maximum(u_norm, 1e-12),
                                      self.min_coeff, self.max_coeff),
                             1.0)
            coeff_new = jnp.where(warmup, live, coeff)
            coeff_new = jnp.where(at_freeze, live, coeff_new)
            mscale_new = jnp.where(warmup | at_freeze,
                                   jnp.maximum(scale, 1e-12), mscale)
            drift = jnp.clip(scale / jnp.maximum(mscale, 1e-12),
                             self.factor_min, self.factor_max)
            # cap the frozen coeff by the LIVE trust ratio: a coeff frozen
            # early can't shrink when the compressed update norm grows, so
            # without the cap the step norm is unbounded (lr·coeff·u_norm);
            # with it the step never exceeds lr·w_norm
            live_cap = jnp.where(w_norm > 0,
                                 w_norm / jnp.maximum(u_norm, 1e-12), 1.0)
            eff_coeff = jnp.where(warmup, live,
                                  jnp.minimum(coeff_new * drift, live_cap))
            return ((p32 - lr * eff_coeff * upd).astype(p.dtype),
                    m_eff, v_new, e_new, coeff_new, mscale_new)

        out = jax.tree.map(leaf, params, grads, m_tree, corrected_tree,
                           compressed_tree, state.exp_avg_sq,
                           state.error_feedback, state.frozen_lamb_coeff,
                           state.frozen_m_scale)
        is_t = lambda t: isinstance(t, tuple)
        pick = lambda i: jax.tree.map(lambda t: t[i], out, is_leaf=is_t)
        return pick(0), OnebitLambState(pick(1), pick(2), pick(3), pick(4),
                                        pick(5))
