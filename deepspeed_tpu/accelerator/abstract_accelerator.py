"""Accelerator abstraction.

TPU-native re-design of the reference's ``accelerator/abstract_accelerator.py:10``
(``DeepSpeedAccelerator`` ABC).  The reference surface is organized around
torch.cuda concepts (streams, events, per-device RNG); the JAX/XLA execution
model replaces explicit streams with async dispatch, so the TPU surface keeps
the *capabilities* (device enumeration, memory stats, dtype support, RNG,
synchronization, op-builder indirection, communication-backend selection) in
idiomatic JAX terms.
"""

import abc
from abc import ABC


class Accelerator(ABC):
    """Device abstraction: every device-touching layer goes through this.

    Mirrors the capability surface of the reference ABC
    (``accelerator/abstract_accelerator.py:10``): naming, device management,
    RNG, synchronization, memory introspection, dtype support, and the
    communication-backend / op-builder hooks.
    """

    def __init__(self):
        self._name = None
        self._communication_backend_name = None

    # ------------------------------------------------------------------ #
    # Identity
    # ------------------------------------------------------------------ #
    @abc.abstractmethod
    def device_name(self, device_index=None):
        ...

    @abc.abstractmethod
    def is_available(self):
        ...

    @abc.abstractmethod
    def device_count(self):
        """Number of addressable (local-process-visible) devices."""
        ...

    @abc.abstractmethod
    def global_device_count(self):
        """Number of devices across all processes."""
        ...

    @abc.abstractmethod
    def devices(self):
        """The jax.Device list for this accelerator."""
        ...

    @abc.abstractmethod
    def current_device(self):
        ...

    @abc.abstractmethod
    def current_device_name(self):
        ...

    def process_index(self):
        import jax
        return jax.process_index()

    def process_count(self):
        import jax
        return jax.process_count()

    # ------------------------------------------------------------------ #
    # Execution
    # ------------------------------------------------------------------ #
    @abc.abstractmethod
    def synchronize(self, device_index=None):
        """Block until all dispatched device work completes."""
        ...

    def default_matmul_precision(self):
        return "bfloat16"

    # ------------------------------------------------------------------ #
    # RNG — JAX RNG is functional; the accelerator hands out seeds/keys.
    # ------------------------------------------------------------------ #
    @abc.abstractmethod
    def manual_seed(self, seed):
        ...

    @abc.abstractmethod
    def initial_seed(self):
        ...

    @abc.abstractmethod
    def rng_key(self):
        """Current root jax.random key (split on use)."""
        ...

    # ------------------------------------------------------------------ #
    # Memory
    # ------------------------------------------------------------------ #
    @abc.abstractmethod
    def memory_stats(self, device_index=None):
        """dict with at least bytes_in_use / bytes_limit when available."""
        ...

    @abc.abstractmethod
    def memory_allocated(self, device_index=None):
        ...

    @abc.abstractmethod
    def max_memory_allocated(self, device_index=None):
        ...

    @abc.abstractmethod
    def reset_peak_memory_stats(self, device_index=None):
        ...

    @abc.abstractmethod
    def total_memory(self, device_index=None):
        ...

    @abc.abstractmethod
    def available_memory(self, device_index=None):
        ...

    def memory_snapshot(self, device_index=None):
        """The canonical normalized per-device memory view every
        device-memory consumer reads through (``see_memory_usage``, the
        flops profiler's budget, the autotuner's cost model, the
        serving memory sampler, bench watermarks): ``{device, platform,
        bytes_in_use, peak_bytes_in_use, bytes_limit, limit_source}``.
        The base implementation normalizes :meth:`memory_stats`;
        ``TPU_Accelerator`` refines ``bytes_limit`` with the datasheet
        capacity when the backend reports none."""
        stats = self.memory_stats(device_index)
        limit = int(stats.get("bytes_limit") or 0)
        return {
            "device": self.device_name(device_index or 0),
            "platform": self._name,
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
            "bytes_limit": limit,
            "limit_source": "runtime" if limit else "unknown",
        }

    def memory_snapshots(self):
        """One :meth:`memory_snapshot` per local device."""
        return [self.memory_snapshot(i)
                for i in range(self.device_count())]

    # ------------------------------------------------------------------ #
    # Dtype support
    # ------------------------------------------------------------------ #
    @abc.abstractmethod
    def is_bf16_supported(self):
        ...

    @abc.abstractmethod
    def is_fp16_supported(self):
        ...

    @abc.abstractmethod
    def supported_dtypes(self):
        ...

    def preferred_dtype(self):
        import jax.numpy as jnp
        return jnp.bfloat16

    # ------------------------------------------------------------------ #
    # Communication / op-builder hooks (reference:
    # abstract_accelerator.py:177 communication_backend_name;
    # cuda_accelerator.py op_builder indirection)
    # ------------------------------------------------------------------ #
    @abc.abstractmethod
    def communication_backend_name(self):
        ...

    @abc.abstractmethod
    def get_op_builder(self, class_name):
        ...

    @abc.abstractmethod
    def on_accelerator(self, array):
        """True if ``array`` is committed to this accelerator's devices."""
        ...

    # Profiler range annotations (reference: range_push/range_pop
    # abstract_accelerator.py:165-170 → jax.profiler traces on TPU).
    def range_push(self, msg):
        import jax
        ctx = jax.profiler.TraceAnnotation(msg)
        ctx.__enter__()
        self._range_stack = getattr(self, "_range_stack", [])
        self._range_stack.append(ctx)

    def range_pop(self):
        stack = getattr(self, "_range_stack", [])
        if stack:
            stack.pop().__exit__(None, None, None)
