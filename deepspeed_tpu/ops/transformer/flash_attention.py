"""Pallas flash attention (fwd + bwd) — the centerpiece training kernel.

TPU-native equivalent of the reference's fused transformer attention kernels
(``csrc/transformer/*.cu`` softmax/dropout/gemm stack behind
``DeepSpeedTransformerLayer``, and the inference ``softmax_context`` op,
``csrc/transformer/inference/csrc/pt_binding.cpp:1934-``).  Instead of
separate gemm+softmax kernels stitched by a C++ scheduler, this is one
online-softmax kernel: O(S) memory, no S×S materialization, MXU-tiled.

Layout: inputs [B, S, H, D] (model-native); kernel operates in [B, H, S, D].
GQA is handled in the BlockSpec index maps (kv head = h * KVH // H) — no
jnp.repeat materialization.

Causal masking skips fully-masked KV blocks via ``pl.when`` predication.
The backward pass uses the saved LSE (log-sum-exp) rows, with two kernels:
one accumulating dq over kv blocks, one accumulating (dk, dv) over q blocks —
the standard flash-attention-2 decomposition.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import os as _os
from deepspeed_tpu.utils.jax_compat import CompilerParams as _CompilerParams

# tuned on v5e at seq 2048/head_dim 64: large kv blocks amortize the
# VPU-bound online-softmax bookkeeping; q=512 beats 256 and 1024 on the
# OPT-1.3B train workload (larger bwd blocks overflow scoped vmem)
DEFAULT_BLOCK_Q = int(_os.environ.get("DSTPU_FLASH_BLOCK_Q", "512"))
DEFAULT_BLOCK_K = int(_os.environ.get("DSTPU_FLASH_BLOCK_K", "2048"))
DEFAULT_BLOCK_Q_BWD = int(_os.environ.get("DSTPU_FLASH_BLOCK_Q_BWD", "1024"))
DEFAULT_BLOCK_K_BWD = int(_os.environ.get("DSTPU_FLASH_BLOCK_K_BWD", "1024"))
NEG_INF = -1e30
# LSE/delta row vectors carry a small broadcast trailing dim: Mosaic requires
# the last block dim be 128-divisible OR equal to the full array dim, so an
# 8-lane array keeps blocks legal while costing 16x less HBM than 128 lanes
# (these are saved residuals when attention outputs are remat-saveable).
LSE_LANES = 8


def _interpret():
    return jax.default_backend() == "cpu"


def pallas_supported():
    """True when Pallas kernels can run here.

    CPU runs the interpreter; native TPU compiles Mosaic.  Tunneled/relay
    platforms (e.g. 'axon') hang in remote kernel compilation — route those
    to the XLA fallback unless DSTPU_FORCE_FLASH=1.
    """
    import os
    if os.environ.get("DSTPU_FORCE_FLASH") == "1":
        return True
    if os.environ.get("DSTPU_DISABLE_FLASH") == "1":
        return False
    return jax.default_backend() in ("cpu", "tpu")


# --------------------------------------------------------------------- #
# Forward
# --------------------------------------------------------------------- #
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, block_q, block_k, causal, nk, kv_len):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # block classification: interior blocks (fully inside the causal
    # triangle and inside the sequence) skip all mask/iota VPU work — with
    # online softmax that work is a large share of kernel time at small D
    even_kv = kv_len % block_k == 0
    run = (not causal) or (ik * block_k <= iq * block_q + block_q - 1)
    diag = causal and (ik * block_k + block_k > iq * block_q)
    needs_mask = diag if even_kv else True

    def _softmax_update(s, v):
        m_prev = m_scr[:, 0:1]                        # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                        # [bq, bk] f32
        corr = jnp.exp(m_prev - m_new)                # [bq, 1]
        l_new = l_scr[:, 0:1] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(run & jnp.logical_not(needs_mask))
    def _interior():
        # operands stay bf16 — the MXU accumulates in fp32 via
        # preferred_element_type; casting inputs to fp32 would halve
        # matmul throughput
        s = jax.lax.dot_general(q_ref[0, 0], k_ref[0, 0],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if scale != 1.0:        # scale is folded into q by the wrapper
            s = s * scale
        _softmax_update(s, v_ref[0, 0])

    @pl.when(run & needs_mask)
    def _masked():
        q = q_ref[0, 0]                              # [bq, d]
        k = k_ref[0, 0]                              # [bk, d]
        v = v_ref[0, 0]                              # [bk, d]
        if not even_kv:
            # zero padded tail rows: OOB block reads are undefined, and
            # garbage * 0-probability still poisons the matmul with NaN
            kv_rows = ik * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                              (block_k, 1), 0)
            valid_kv = kv_rows < kv_len
            k = jnp.where(valid_kv, k, jnp.zeros_like(k))
            v = jnp.where(valid_kv, v, jnp.zeros_like(v))
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if scale != 1.0:
            s = s * scale
        cols = ik * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                       (block_q, block_k), 1)
        if even_kv:
            # only diagonal blocks reach here — causal mask alone
            rows = iq * block_q + jax.lax.broadcasted_iota(jnp.int32,
                                                           (block_q, block_k), 0)
            mask = rows >= cols
        else:
            mask = cols < kv_len       # tail-block padding
            if causal:
                rows = iq * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                mask = mask & (rows >= cols)
        s = jnp.where(mask, s, NEG_INF)
        _softmax_update(s, v)

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_scr[:, 0:1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        # LSE rides a 128-lane trailing dim: Mosaic requires output block
        # shapes tiled (8, 128) on the last two dims, so a [block_q]-shaped
        # row per (b, h) cannot be written directly
        lse_ref[0, 0] = jnp.broadcast_to(m_scr[:, 0:1] + jnp.log(safe_l),
                                         lse_ref.shape[2:])


def _fwd(q, k, v, scale, causal, block_q, block_k):
    B, H, S, D = q.shape
    KVH, Sk = k.shape[1], k.shape[2]
    block_q = min(block_q, S)
    block_k = min(block_k, Sk)
    nq = pl.cdiv(S, block_q)
    nk = pl.cdiv(Sk, block_k)
    grid = (B * H, nq, nk)

    def q_map(bh, iq, ik):
        return (bh // H, bh % H, iq, 0)

    def kv_map(bh, iq, ik):
        return (bh // H, (bh % H) * KVH // H, ik, 0)

    kernel = functools.partial(_fwd_kernel, scale=scale, block_q=block_q,
                               block_k=block_k, causal=causal, nk=nk, kv_len=Sk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), q_map),
            pl.BlockSpec((1, 1, block_k, D), kv_map),
            pl.BlockSpec((1, 1, block_k, D), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), q_map),
            pl.BlockSpec((1, 1, block_q, LSE_LANES),
                         lambda bh, iq, ik: (bh // H, bh % H, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, LSE_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# --------------------------------------------------------------------- #
# Backward
# --------------------------------------------------------------------- #
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, scale, block_q, block_k, causal, nk, kv_len):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    even_kv = kv_len % block_k == 0
    run = (not causal) or (ik * block_k <= iq * block_q + block_q - 1)
    diag = causal and (ik * block_k + block_k > iq * block_q)
    needs_mask = diag if even_kv else True

    def _accum(p, do, v, k, delta):
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        if scale != 1.0:
            ds = ds * scale
        ds = ds.astype(k.dtype)
        dq_scr[:] += jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(run & jnp.logical_not(needs_mask))
    def _interior():
        lse = lse_ref[0, 0][:, 0:1]                  # [bq, 1]
        s = jax.lax.dot_general(q_ref[0, 0], k_ref[0, 0],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if scale != 1.0:        # scale is folded into q by the wrapper
            s = s * scale
        p = jnp.exp(s - lse)                          # [bq, bk]
        _accum(p, do_ref[0, 0], v_ref[0, 0], k_ref[0, 0],
               delta_ref[0, 0][:, 0:1])

    @pl.when(run & needs_mask)
    def _masked():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, 0:1]                  # [bq, 1]
        delta = delta_ref[0, 0][:, 0:1]              # [bq, 1]
        if not even_kv:
            kv_rows = ik * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                              (block_k, 1), 0)
            valid_kv = kv_rows < kv_len
            k = jnp.where(valid_kv, k, jnp.zeros_like(k))
            v = jnp.where(valid_kv, v, jnp.zeros_like(v))
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if scale != 1.0:
            s = s * scale
        cols = ik * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                       (block_q, block_k), 1)
        if even_kv:
            rows = iq * block_q + jax.lax.broadcasted_iota(jnp.int32,
                                                           (block_q, block_k), 0)
            mask = rows >= cols
        else:
            mask = cols < kv_len
            if causal:
                rows = iq * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                mask = mask & (rows >= cols)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)    # [bq, bk]
        _accum(p, do, v, k, delta)

    @pl.when(ik == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, scale, block_q, block_k, causal, nq, q_len):
    ik = pl.program_id(1)
    iq = pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    even_q = q_len % block_q == 0
    run = (not causal) or (iq * block_q + block_q - 1 >= ik * block_k)
    diag = causal and (iq * block_q < ik * block_k + block_k)
    needs_mask = diag if even_q else True

    def _accum(p, q, v, do, delta):
        dv_scr[:] += jax.lax.dot_general(p.astype(do.dtype), do,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                         # [bq, bk]
        if scale != 1.0:
            ds = ds * scale
        ds = ds.astype(q.dtype)
        dk_scr[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(run & jnp.logical_not(needs_mask))
    def _interior():
        lse = lse_ref[0, 0][:, 0:1]
        s = jax.lax.dot_general(q_ref[0, 0], k_ref[0, 0],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if scale != 1.0:        # scale is folded into q by the wrapper
            s = s * scale
        p = jnp.exp(s - lse)
        _accum(p, q_ref[0, 0], v_ref[0, 0], do_ref[0, 0],
               delta_ref[0, 0][:, 0:1])

    @pl.when(run & needs_mask)
    def _masked():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, 0:1]
        delta = delta_ref[0, 0][:, 0:1]
        if not even_q:
            q_rows = iq * block_q + jax.lax.broadcasted_iota(jnp.int32,
                                                             (block_q, 1), 0)
            valid_q = q_rows < q_len
            q = jnp.where(valid_q, q, jnp.zeros_like(q))
            do = jnp.where(valid_q, do, jnp.zeros_like(do))
            # delta/lse of padded rows are OOB reads; 0*garbage must stay
            # finite
            delta = jnp.where(valid_q, delta, 0.0)
            lse = jnp.where(valid_q, lse, 0.0)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if scale != 1.0:
            s = s * scale
        rows = iq * block_q + jax.lax.broadcasted_iota(jnp.int32,
                                                       (block_q, block_k), 0)
        if even_q:
            cols = ik * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                           (block_q, block_k), 1)
            mask = rows >= cols
        else:
            mask = rows < q_len
            if causal:
                cols = ik * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                mask = mask & (rows >= cols)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)    # [bq, bk]
        _accum(p, q, v, do, delta)

    @pl.when(iq == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, block_q_bwd, block_k_bwd, res, do):
    q, k, v, out, lse = res
    B, H, S, D = q.shape
    KVH, Sk = k.shape[1], k.shape[2]
    block_q = min(block_q_bwd, S)
    block_k = min(block_k_bwd, Sk)
    nq = pl.cdiv(S, block_q)
    nk = pl.cdiv(Sk, block_k)

    delta = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                axis=-1)[..., None],
        lse.shape)

    def q_map(bh, iq, ik):
        return (bh // H, bh % H, iq, 0)

    def kv_map(bh, iq, ik):
        return (bh // H, (bh % H) * KVH // H, ik, 0)

    def lse_map(bh, iq, ik):
        return (bh // H, bh % H, iq, 0)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal, nk=nk, kv_len=Sk),
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), q_map),
            pl.BlockSpec((1, 1, block_k, D), kv_map),
            pl.BlockSpec((1, 1, block_k, D), kv_map),
            pl.BlockSpec((1, 1, block_q, D), q_map),
            pl.BlockSpec((1, 1, block_q, LSE_LANES), lse_map),
            pl.BlockSpec((1, 1, block_q, LSE_LANES), lse_map),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D), q_map),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    # dk/dv computed per (b, h) then reduced over the query-head group for GQA
    def kv_out_map(bh, ik, iq):
        return (bh // H, bh % H, ik, 0)

    def q_map2(bh, ik, iq):
        return (bh // H, bh % H, iq, 0)

    def kv_map2(bh, ik, iq):
        return (bh // H, (bh % H) * KVH // H, ik, 0)

    def lse_map2(bh, ik, iq):
        return (bh // H, bh % H, iq, 0)

    dk_full, dv_full = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal, nq=nq, q_len=S),
        grid=(B * H, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), q_map2),
            pl.BlockSpec((1, 1, block_k, D), kv_map2),
            pl.BlockSpec((1, 1, block_k, D), kv_map2),
            pl.BlockSpec((1, 1, block_q, D), q_map2),
            pl.BlockSpec((1, 1, block_q, LSE_LANES), lse_map2),
            pl.BlockSpec((1, 1, block_q, LSE_LANES), lse_map2),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, D), kv_out_map),
            pl.BlockSpec((1, 1, block_k, D), kv_out_map),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sk, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Sk, D), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    if KVH != H:
        rep = H // KVH
        dk = dk_full.reshape(B, KVH, rep, Sk, D).sum(axis=2)
        dv = dv_full.reshape(B, KVH, rep, Sk, D).sum(axis=2)
    else:
        dk, dv = dk_full, dv_full
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# --------------------------------------------------------------------- #
# Public API
# --------------------------------------------------------------------- #
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_bhsd(q, k, v, scale, causal, block_q, block_k,
                block_q_bwd, block_k_bwd):
    out, _ = _fwd(q, k, v, scale, causal, block_q, block_k)
    return out


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k,
                    block_q_bwd, block_k_bwd):
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k)
    # tag residuals so a remat policy can elect to SAVE them — without the
    # tags, any rematerialized layer re-runs the whole forward kernel inside
    # the backward pass just to regenerate lse (out: bf16 B·S·H·D; lse: 8-lane
    # f32 — together ~20MB/layer at opt-350m/2048, far cheaper than a
    # recompute)
    from jax.ad_checkpoint import checkpoint_name
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, out, lse)


_flash_bhsd.defvjp(_flash_fwd_rule, _bwd)


def flash_attention(q, k, v, causal=True, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    block_q_bwd=None, block_k_bwd=None):
    """Flash attention on [B, S, H, D] tensors (model-native layout).

    ``k``/``v`` may have fewer heads (GQA).  Returns [B, S, H, D].
    The backward kernels tile independently (their accumulators iterate the
    opposite grid dim; v5e sweep favors 1024x1024 there): ``block_q_bwd`` /
    ``block_k_bwd`` default from DSTPU_FLASH_BLOCK_{Q,K}_BWD.
    """
    B, S, H, D = q.shape
    if scale is None:
        scale = 1.0 / float(np.sqrt(D))
    if block_q_bwd is None:
        block_q_bwd = DEFAULT_BLOCK_Q_BWD
    if block_k_bwd is None:
        block_k_bwd = DEFAULT_BLOCK_K_BWD
    # fold the softmax scale into q OUTSIDE the kernel when it is a power
    # of two (D a power of 4, e.g. D=64 → 0.125): saves a [bq, bk] f32
    # multiply per score block in fwd AND bwd, and the multiply is EXACT in
    # q.dtype (mantissa untouched; the chain rule through it restores dq's
    # scale automatically).  Other scales (D=128 → 2^-3.5) stay in-kernel
    # in f32 — pre-scaling bf16 q would round every logit.
    if scale > 0 and float(np.log2(scale)).is_integer():
        qt = (q * jnp.asarray(scale, q.dtype)).transpose(0, 2, 1, 3)
        kernel_scale = 1.0
    else:
        qt = q.transpose(0, 2, 1, 3)
        kernel_scale = float(scale)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _flash_bhsd(qt, kt, vt, kernel_scale, bool(causal),
                      int(block_q), int(block_k),
                      int(block_q_bwd), int(block_k_bwd))
    return out.transpose(0, 2, 1, 3)


# parity alias for the reference inference op name
softmax_context = flash_attention
