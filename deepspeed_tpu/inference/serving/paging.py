"""Host-side paged-KV bookkeeping for the serving engine
(``docs/serving.md``, "Paged KV cache").

The device holds one page POOL (``Transformer.init_paged_cache``:
``[L, num_pages, page_size, KVH*D]``) shared by every slot; which
physical page backs which virtual position of which request is decided
HERE, on the host, and shipped to the device as a traced ``[num_slots,
pages_per_slot]`` page-table argument on every dispatch — page churn
never changes a program shape (vLLM's PagedAttention block tables, Kwon
et al. SOSP'23, under this framework's one-executable constraint).

Three pieces:

* :class:`PagePool` — the refcounted free-list mirror of the device
  pool.  Page 0 is the reserved TRASH page: never allocated, and every
  unmapped/retired table entry points at it, so zombie lanes (retired
  on the host, still decoding masked no-ops on the device) scatter
  their garbage there instead of into reclaimed pages.
* :class:`PrefixIndex` — copy-on-write prefix sharing (SGLang's
  RadixAttention, Zheng et al. 2023, at page granularity): a hash-CHAIN
  index over page-aligned token blocks.  Requests whose leading blocks
  match map those table entries to the SAME physical pages (refcounted);
  the first token past the shared region lands in a private page, so a
  divergent write never touches a shared page — "copy"-on-write is
  realized as recompute-on-divergence of at most one page of tokens
  (cheaper than a dedicated device copy program, and it keeps the
  one-executable invariant).  Unreferenced entries evict LRU, leaves
  first (an interior chain node with live children never evicts — a
  broken chain would strand its descendants' refcounts).
* :class:`PagedPoolWorkspace` — the donated-buffer pool workspace with
  the same dead-after-failed-dispatch liveness check
  ``KVCacheWorkspace`` does.
"""

import hashlib
from collections import deque

import numpy as np

import jax

TRASH_PAGE = 0


def pages_for(virtual_len, page_size):
    """Physical pages needed to back ``virtual_len`` cache positions."""
    return -(-int(virtual_len) // int(page_size))


def compact_page_str(pages):
    """Range-compressed page list: ``[4,5,6,9,2]`` → ``"4-6,9,2"`` —
    the serving snapshot stores page tables this way instead of one JSON
    int per entry (a 4k-position slot at page 16 is 256 entries; the
    compact form is a few bytes for the common contiguous case)."""
    pages = [int(p) for p in pages]
    if not pages:
        return ""
    parts, lo, prev = [], pages[0], pages[0]
    for p in pages[1:]:
        if p == prev + 1:
            prev = p
            continue
        parts.append(f"{lo}-{prev}" if prev > lo else f"{lo}")
        lo = prev = p
    parts.append(f"{lo}-{prev}" if prev > lo else f"{lo}")
    return ",".join(parts)


def expand_page_str(s):
    """Inverse of :func:`compact_page_str` (diagnostics / tests)."""
    if not s:
        return []
    out = []
    for part in s.split(","):
        if "-" in part:
            lo, hi = part.split("-")
            out.extend(range(int(lo), int(hi) + 1))
        else:
            out.append(int(part))
    return out


class PagePool:
    """Refcounted free-list mirror of the device page pool.  Allocation
    and free run at host-scheduler time, one event behind the device by
    design (the serving engine's lag-one bookkeeping): a page is freed
    only when the retirement that releases it has been PROCESSED, and
    every dispatch after that carries a table that no longer maps it."""

    def __init__(self, num_pages):
        self.num_pages = int(num_pages)
        if self.num_pages < 2:
            raise ValueError(f"page pool needs >= 2 pages (1 trash + 1 "
                             f"allocatable), got {num_pages}")
        self._ref = np.zeros((self.num_pages,), np.int32)
        self._ref[TRASH_PAGE] = 1           # pinned forever
        self._free = deque(range(1, self.num_pages))

    @property
    def allocatable(self):
        """Pages a single request could ever hold (trash excluded)."""
        return self.num_pages - 1

    @property
    def free_count(self):
        return len(self._free)

    @property
    def in_use(self):
        return self.allocatable - len(self._free)

    def utilization(self):
        return self.in_use / max(self.allocatable, 1)

    def alloc(self, n):
        """``n`` fresh pages at refcount 1, or ``None`` when the free
        list is short (caller evicts/waits — never a partial grab)."""
        if n > len(self._free):
            return None
        got = [self._free.popleft() for _ in range(n)]
        for p in got:
            self._ref[p] = 1
        return got

    def incref(self, page):
        assert self._ref[page] > 0, f"incref on free page {page}"
        self._ref[page] += 1

    def decref(self, page):
        p = int(page)
        if p == TRASH_PAGE:
            return
        assert self._ref[p] > 0, f"decref on free page {p}"
        self._ref[p] -= 1
        if self._ref[p] == 0:
            self._free.append(p)

    def refcount(self, page):
        return int(self._ref[int(page)])

    def reset(self):
        """All pages free (the pool buffer was dropped/reallocated)."""
        self._ref[:] = 0
        self._ref[TRASH_PAGE] = 1
        self._free = deque(range(1, self.num_pages))


class _PrefixEntry:
    __slots__ = ("page", "parent", "children", "last_use", "depth")

    def __init__(self, page, parent, depth):
        self.page = int(page)
        self.parent = parent                # key of the parent entry
        self.children = 0
        self.last_use = 0
        self.depth = depth


class PrefixIndex:
    """Hash-chain prefix index at page granularity.

    Key ``i`` of a token sequence is ``H(key_{i-1}, tokens[i*page :
    (i+1)*page])`` — a chain, so block ``i`` only ever matches behind an
    identical prefix (no cross-request aliasing of same-content blocks
    at different positions).  Entries hold one pool reference each; a
    lookup increfs every matched page for the requesting slot.  Eviction
    is LRU over LEAF entries whose page nobody else references."""

    def __init__(self):
        self._entries = {}                  # key -> _PrefixEntry
        self._clock = 0

    def __len__(self):
        return len(self._entries)

    @staticmethod
    def _chain(tokens, page_size, upto_blocks):
        key = b"prefix"
        tokens = np.ascontiguousarray(np.asarray(tokens, np.int32))
        for i in range(upto_blocks):
            block = tokens[i * page_size:(i + 1) * page_size]
            key = hashlib.sha1(key + block.tobytes()).digest()
            yield key

    def lookup(self, tokens, page_size, pool, max_blocks):
        """The longest indexed chain matching ``tokens``' leading full
        blocks (capped at ``max_blocks``); increfs and returns the
        matched physical pages (possibly empty)."""
        self._clock += 1
        matched = []
        full = min(len(tokens) // page_size, max_blocks)
        for key in self._chain(tokens, page_size, full):
            ent = self._entries.get(key)
            if ent is None:
                break
            ent.last_use = self._clock
            pool.incref(ent.page)
            matched.append(ent.page)
        return matched

    def register(self, tokens, page_size, row_pages, pool, upto_blocks):
        """Index ``tokens``' first ``upto_blocks`` full blocks as
        sharable, backed by ``row_pages`` (the slot's table row, whose
        prefill just wrote them).  Blocks already indexed keep their
        existing entry (same content; the slot may be holding either
        copy).  Each NEW entry takes one pool reference."""
        self._clock += 1
        parent = None
        registered = 0
        for i, key in enumerate(self._chain(tokens, page_size,
                                            upto_blocks)):
            ent = self._entries.get(key)
            if ent is None:
                ent = _PrefixEntry(row_pages[i], parent, i)
                pool.incref(ent.page)
                self._entries[key] = ent
                if parent is not None:
                    self._entries[parent].children += 1
                registered += 1
            ent.last_use = self._clock
            parent = key
        return registered

    def evict(self, pool, need_pages):
        """Free index references LRU-leaf-first until ``need_pages``
        pages would land on the free list (entries whose page is still
        referenced elsewhere release the index ref without freeing the
        page).  Returns the number of pages actually freed."""
        freed = 0
        while freed < need_pages:
            victim_key, victim = None, None
            for key, ent in self._entries.items():
                if ent.children:
                    continue
                if victim is None or ent.last_use < victim.last_use:
                    victim_key, victim = key, ent
            if victim is None:
                break
            if pool.refcount(victim.page) == 1:
                freed += 1
            pool.decref(victim.page)
            if victim.parent is not None:
                self._entries[victim.parent].children -= 1
            del self._entries[victim_key]
        return freed

    def clear(self, pool):
        """Drop every entry (and its pool reference) — the pool buffer
        died or the server is retiring."""
        for ent in self._entries.values():
            pool.decref(ent.page)
        self._entries.clear()


class PagedPoolWorkspace:
    """The serving engine's persistent page-pool buffer: donated into
    every paged program and reclaimed from its output, reallocated only
    when the geometry changes or a failed dispatch left the returned
    buffers dead (same liveness contract as ``KVCacheWorkspace``)."""

    def __init__(self, module):
        self._module = module
        self._key = None
        self._pool = None

    def take(self, num_pages, page_size, dtype):
        import jax.numpy as jnp
        key = (int(num_pages), int(page_size), jnp.dtype(dtype).name)
        pool, self._pool = self._pool, None
        if pool is not None and any(
                getattr(l, "is_deleted", lambda: False)()
                for l in jax.tree.leaves(pool)):
            pool = None
        if pool is None or self._key != key:
            pool = None
            self._key = key
            pool = self._module.init_paged_cache(num_pages, page_size,
                                                 dtype=dtype)
        return pool

    def give_back(self, pool):
        self._pool = pool

    def release(self):
        self._pool = None
        self._key = None
