"""Progressive Layer Dropping (PLD).

Capability parity with reference ``runtime/progressive_layer_drop.py``
(arXiv:2010.13369): a theta schedule that anneals keep-probability from 1.0
toward ``theta``; models consume it as a per-layer keep probability.  For a
jit-friendly apply, ``layer_keep_prob`` gives the closed-form per-layer
probability and ``maybe_drop_layer`` applies stochastic identity-skip with a
traced PRNG key (the decision is data-independent so it stays XLA-legal via
``lax.cond``-free arithmetic blending).
"""

import math

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import log_dist


class ProgressiveLayerDrop:

    def __init__(self, theta=0.5, gamma=0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0
        log_dist(f"Enabled progressive layer dropping (theta = {self.theta})",
                 ranks=[0])

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self):
        return self.current_theta

    def update_state(self, global_step):
        self.current_theta = ((1.0 - self.theta)
                              * math.exp(-self.gamma * global_step)
                              + self.theta)
        return self.current_theta


def layer_keep_prob(theta, layer_idx, num_layers):
    """Per-layer keep probability: deeper layers drop more aggressively
    (PLD paper eq. 6: p_l = 1 - (l/L)(1 - theta))."""
    return 1.0 - (layer_idx / max(num_layers, 1)) * (1.0 - theta)


def maybe_drop_layer(layer_fn, x, rng, keep_prob):
    """Stochastic-depth residual skip: with prob (1-keep_prob) the layer is
    identity; surviving outputs are scaled 1/keep_prob so expectations match.
    Traceable (no Python branching on traced values)."""
    keep = jax.random.bernoulli(rng, keep_prob).astype(x.dtype)
    out = layer_fn(x)
    scale = keep / jnp.maximum(keep_prob, 1e-6)
    return x + (out - x) * scale
