"""ZeRO-3 linear — reference ``runtime/zero/linear.py`` (the custom autograd
``LinearFunctionForZeroStage3`` + ``LinearModuleForZeroStage3`` that keeps
fp16 params gatherable and avoids materializing the weight grad as a second
full tensor).

Under GSPMD none of that machinery is needed — a plain Dense with sharded
params IS the ZeRO-3 linear — so these exist for API parity and carry the
one real knob that survives: computing in the param's dtype with fp32
accumulation."""

import jax
import jax.numpy as jnp
import flax.linen as nn


def zero3_linear_wrap(x, weight, bias=None):
    """Functional form (reference ``LinearFunctionForZeroStage3.apply``):
    y = x @ W^T + b with fp32 accumulation."""
    y = jax.lax.dot_general(x, weight, (((x.ndim - 1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


class LinearModuleForZeroStage3(nn.Module):
    """Reference ``LinearModuleForZeroStage3``: a Linear whose weight layout
    matches torch ([out, in]) so injected/converted checkpoints map 1:1."""
    in_features: int
    out_features: int
    use_bias: bool = True

    @nn.compact
    def __call__(self, x):
        w = self.param("weight", nn.initializers.lecun_normal(),
                       (self.out_features, self.in_features), jnp.float32)
        b = self.param("bias", nn.initializers.zeros, (self.out_features,),
                       jnp.float32) if self.use_bias else None
        return zero3_linear_wrap(x, w.astype(x.dtype),
                                 None if b is None else b)
