"""Parse the ``compression_training`` config block.

Same JSON schema as the reference (``deepspeed/compression/config.py``):
each technique has ``shared_parameters`` plus named ``different_groups``
entries carrying per-group ``params``, ``modules`` scope, and
``related_modules``.
"""

from . import constants as C

_TECH_DEFAULT_SHARED = {
    C.WEIGHT_QUANTIZATION: {
        C.TECHNIQUE_ENABLED: False,
        C.WEIGHT_QUANTIZE_KERNEL: False,
        C.TECHNIQUE_SCHEDULE_OFFSET: 0,
        C.WEIGHT_QUANTIZE_GROUPS: 1,
        C.WEIGHT_QUANTIZE_VERBOSE: False,
        C.WEIGHT_QUANTIZE_TYPE: C.WEIGHT_QUANTIZE_SYMMETRIC,
        C.WEIGHT_QUANTIZE_IN_FORWARD_ENABLED: False,
        C.WEIGHT_QUANTIZE_ROUNDING: C.WEIGHT_QUANTIZE_NEAREST_ROUNDING,
        C.WEIGHT_QUANTIZE_FP16_MIXED_QUANTIZE: {
            C.TECHNIQUE_ENABLED: False,
            C.WEIGHT_QUANTIZE_CHANGE_RATIO: 0.001,
        },
    },
    C.ACTIVATION_QUANTIZATION: {
        C.TECHNIQUE_ENABLED: False,
        C.ACTIVATION_QUANTIZE_TYPE: C.WEIGHT_QUANTIZE_SYMMETRIC,
        C.ACTIVATION_QUANTIZE_RANGE: C.ACTIVATION_QUANTIZE_RANGE_DYNAMIC,
        C.TECHNIQUE_SCHEDULE_OFFSET: 1000,
    },
    C.SPARSE_PRUNING: {
        C.TECHNIQUE_ENABLED: False,
        C.SPARSE_PRUNING_METHOD: C.SPARSE_PRUNING_METHOD_L1,
        C.TECHNIQUE_SCHEDULE_OFFSET: 1000,
    },
    C.ROW_PRUNING: {
        C.TECHNIQUE_ENABLED: False,
        C.ROW_PRUNING_METHOD: C.SPARSE_PRUNING_METHOD_L1,
        C.TECHNIQUE_SCHEDULE_OFFSET: 1000,
    },
    C.HEAD_PRUNING: {
        C.TECHNIQUE_ENABLED: False,
        C.HEAD_PRUNING_METHOD: C.SPARSE_PRUNING_METHOD_TOPK,
        C.TECHNIQUE_SCHEDULE_OFFSET: 1000,
    },
    C.CHANNEL_PRUNING: {
        C.TECHNIQUE_ENABLED: False,
        C.CHANNEL_PRUNING_METHOD: C.SPARSE_PRUNING_METHOD_L1,
        C.TECHNIQUE_SCHEDULE_OFFSET: 1000,
    },
}


def get_layer_reduction_config(ds_config):
    block = (ds_config or {}).get(C.COMPRESSION_TRAINING, {})
    lr = dict(block.get(C.LAYER_REDUCTION, {}))
    lr.setdefault(C.LAYER_REDUCTION_ENABLED, False)
    return lr


def get_compression_config(ds_config):
    """→ {technique: {'shared_parameters': {...}, 'different_groups':
    {group_name: {'params': {...}, 'modules': [...], 'related_modules': [...]}}}}
    with defaults filled (reference ``config.py get_compression_config``)."""
    block = (ds_config or {}).get(C.COMPRESSION_TRAINING, {})
    out = {}
    for tech, defaults in _TECH_DEFAULT_SHARED.items():
        tc = block.get(tech, {})
        shared = dict(defaults)
        shared.update(tc.get(C.SHARED_PARAMETERS, {}))
        groups = {}
        for gname, gcfg in tc.get(C.DIFFERENT_GROUPS, {}).items():
            params = dict(gcfg.get(C.DIFFERENT_GROUPS_PARAMETERS, {}))
            modules = gcfg.get(C.DIFFERENT_GROUPS_MODULE_SCOPE,
                               C.DIFFERENT_GROUPS_MODULE_SCOPE_DEFAULT)
            if isinstance(modules, str):
                modules = [modules]
            related = gcfg.get(C.DIFFERENT_GROUPS_RELATED_MODULE_SCOPE,
                               C.DIFFERENT_GROUPS_RELATED_MODULE_SCOPE_DEFAULT)
            groups[gname] = {
                C.DIFFERENT_GROUPS_PARAMETERS: params,
                C.DIFFERENT_GROUPS_MODULE_SCOPE: modules,
                C.DIFFERENT_GROUPS_RELATED_MODULE_SCOPE: related,
            }
        if shared.get(C.TECHNIQUE_ENABLED) and not groups:
            raise ValueError(
                f"compression technique {tech} enabled but no different_groups")
        out[tech] = {C.SHARED_PARAMETERS: shared, C.DIFFERENT_GROUPS: groups}
    return out
