"""``ds_ckpt`` — inspect, verify and garbage-collect checkpoint
directories against their manifests (see ``docs/fault_tolerance.md``).

Subcommands::

    ds_ckpt list   <dir>              # tags, steps, sizes, validity
    ds_ckpt verify <dir> [--tag TAG]  # deep-verify manifests; exit 1 on
                                      # any invalid tag
    ds_ckpt gc     <dir> --keep N     # retention: keep newest N valid
                                      # tags, drop older + .tmp orphans
"""

import argparse
import json
import os
import sys

from deepspeed_tpu.runtime.fault.manifest import (
    gc_checkpoints, list_tags, read_manifest, verify_manifest)


def _tag_bytes(path):
    total = 0
    for dirpath, _d, filenames in os.walk(path):
        for name in filenames:
            p = os.path.join(dirpath, name)
            if os.path.isfile(p):
                total += os.path.getsize(p)
    return total


def _latest(save_dir):
    latest = os.path.join(save_dir, "latest")
    if os.path.exists(latest):
        with open(latest) as f:
            return f.read().strip()
    return None


def cmd_list(args):
    tags = list_tags(args.dir)
    if not tags:
        print(f"{args.dir}: no checkpoint tags")
        return 0
    latest = _latest(args.dir)
    print(f"{'tag':<28} {'step':>10} {'files':>6} {'MB':>10} "
          f"{'status':<10}")
    for tag in tags:
        p = os.path.join(args.dir, tag)
        manifest = read_manifest(p)
        if manifest is None:
            step, nfiles, status = "-", "-", "no-manifest"
        else:
            step = manifest.get("step", {}).get("global_steps", "-")
            nfiles = len(manifest.get("files", {}))
            # shallow check (existence + sizes): the cheap scan; use
            # `verify` for checksums
            status = "ok" if not verify_manifest(p, deep=False) \
                else "INVALID"
        mark = " <- latest" if tag == latest else ""
        print(f"{tag:<28} {step!s:>10} {nfiles!s:>6} "
              f"{_tag_bytes(p) / 1e6:>10.2f} {status:<10}{mark}")
    return 0


def cmd_verify(args):
    tags = [args.tag] if args.tag else list_tags(args.dir)
    if not tags:
        print(f"{args.dir}: no checkpoint tags", file=sys.stderr)
        return 1
    bad = 0
    report = {}
    for tag in tags:
        p = os.path.join(args.dir, tag)
        problems = verify_manifest(p, deep=not args.shallow)
        report[tag] = problems
        if problems:
            bad += 1
            print(f"{tag}: INVALID ({len(problems)} problem(s))")
            for prob in problems:
                print(f"  - {prob}")
        else:
            print(f"{tag}: ok")
    if args.json:
        print(json.dumps(report, indent=2))
    return 1 if bad else 0


def cmd_gc(args):
    """Real run and --dry-run share ONE implementation
    (``gc_checkpoints(dry_run=...)``) so the preview can never diverge
    from what the real run does (incl. the keep-newest-valid rule and
    orphaned-backup restores)."""
    latest = _latest(args.dir)
    actions = gc_checkpoints(args.dir, args.keep,
                             protect=(latest,) if latest else (),
                             dry_run=args.dry_run)
    would = "would " if args.dry_run else ""
    for name in sorted(actions):
        if name.startswith("restore:"):
            print(f"{would}restore{'' if args.dry_run else 'd'} "
                  f"{name[len('restore:'):]}")
        else:
            print(f"{would}remove{'' if args.dry_run else 'd'} {name}")
    print(f"{len(actions)} action{'' if len(actions) == 1 else 's'}; "
          f"{len(list_tags(args.dir))} tag(s) remain")
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="ds_ckpt",
        description="verify / list / gc a DeepSpeed-TPU checkpoint "
                    "directory against its MANIFEST.json files")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("list", help="tags with step, size and validity")
    p.add_argument("dir")
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("verify", help="verify manifests (checksums)")
    p.add_argument("dir")
    p.add_argument("--tag", help="verify one tag only")
    p.add_argument("--shallow", action="store_true",
                   help="existence + sizes only, skip checksums")
    p.add_argument("--json", action="store_true",
                   help="also print a JSON problem report")
    p.set_defaults(fn=cmd_verify)

    p = sub.add_parser("gc", help="apply retention policy")
    p.add_argument("dir")
    p.add_argument("--keep", type=int, required=True,
                   help="number of newest tags to keep")
    p.add_argument("--dry-run", action="store_true",
                   help="report what would be removed, touch nothing")
    p.set_defaults(fn=cmd_gc)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
