// SIMD-vectorized host Adam/AdamW for offloaded optimizer states.
//
// TPU-native equivalent of reference csrc/adam/cpu_adam.cpp (+ simd.h):
// the ZeRO-Offload host optimizer. Same design — flat fp32 state arrays on
// host memory, vectorized elementwise update, optional 16-bit param copy-out
// for the device upload — but bound via a plain C ABI (ctypes) instead of
// pybind11, and the 16-bit side is bfloat16 (TPU native) rather than fp16.
//
// Vectorization strategy: the inner loops are written so GCC/Clang
// auto-vectorize them at -O3 -march=native (verified: AVX2/AVX-512 on x86,
// NEON on aarch64), with OpenMP across cores. This replaces the reference's
// hand-written AVX256/AVX512 intrinsics (csrc/includes/simd.h) with the same
// effective ILP and far less code.

#include <cmath>
#include <cstdint>
#include <cstring>

#if defined(_OPENMP)
#include <omp.h>
#endif

extern "C" {

// Round-to-nearest-even float32 -> bfloat16 (matches XLA/TPU semantics).
static inline uint16_t float_to_bf16(float f) {
    uint32_t x;
    std::memcpy(&x, &f, sizeof(x));
    uint32_t rounding_bias = 0x7fff + ((x >> 16) & 1);
    return static_cast<uint16_t>((x + rounding_bias) >> 16);
}

// One fused Adam/AdamW step over a contiguous fp32 shard.
//   adamw_mode=1: decoupled weight decay (AdamW); 0: L2-style (classic Adam).
//   bias_correction=1 applies the standard 1/(1-beta^t) correction.
//   bf16_out: optional (may be null) bfloat16 copy of updated params for the
//             host->device upload of the 16-bit working weights.
void ds_adam_step(float* params,
                  float* exp_avg,
                  float* exp_avg_sq,
                  const float* grads,
                  int64_t n,
                  float lr,
                  float beta1,
                  float beta2,
                  float eps,
                  float weight_decay,
                  int adamw_mode,
                  int bias_correction,
                  int step,
                  uint16_t* bf16_out) {
    float bc1 = 1.0f, bc2 = 1.0f;
    if (bias_correction) {
        bc1 = 1.0f - std::pow(beta1, (float)step);
        bc2 = 1.0f - std::pow(beta2, (float)step);
    }
    const float step_size = lr / bc1;
    const float bc2_sqrt = std::sqrt(bc2);
    const float w_decay = (adamw_mode && weight_decay > 0.0f)
                              ? (1.0f - lr * weight_decay)
                              : 1.0f;

#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i];
        float p = params[i];
        if (!adamw_mode && weight_decay > 0.0f) g += weight_decay * p;
        float m = exp_avg[i] * beta1 + g * (1.0f - beta1);
        float v = exp_avg_sq[i] * beta2 + g * g * (1.0f - beta2);
        float denom = std::sqrt(v) / bc2_sqrt + eps;
        p = p * w_decay - step_size * (m / denom);
        params[i] = p;
        exp_avg[i] = m;
        exp_avg_sq[i] = v;
        if (bf16_out) bf16_out[i] = float_to_bf16(p);
    }
}

// Fused host Adagrad step (reference csrc/adagrad/cpu_adagrad.cpp).
void ds_adagrad_step(float* params,
                     float* exp_avg_sq,
                     const float* grads,
                     int64_t n,
                     float lr,
                     float eps,
                     float weight_decay,
                     uint16_t* bf16_out) {
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i];
        float p = params[i];
        if (weight_decay > 0.0f) g += weight_decay * p;
        float v = exp_avg_sq[i] + g * g;
        p -= lr * g / (std::sqrt(v) + eps);
        params[i] = p;
        exp_avg_sq[i] = v;
        if (bf16_out) bf16_out[i] = float_to_bf16(p);
    }
}

}  // extern "C"
