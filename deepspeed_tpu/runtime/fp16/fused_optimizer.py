"""FP16_Optimizer — standalone mixed-precision optimizer wrapper.

Reference parity: ``runtime/fp16/fused_optimizer.py:22`` (``FP16_Optimizer``):
fp32 master weights + dynamic loss scaling + global-norm clipping around an
inner fused optimizer, with the 3-call contract
``backward(loss) → step()`` and overflow-skip semantics.

TPU redesign: the engine's fused train step subsumes this in production; the
standalone class exists for reference-API users and tests.  State is
functional (masters, inner opt state, scaler state) and every step is one
jitted program; on overflow the update is a branch-free no-op, exactly like
the engine path.
"""

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.fp16.loss_scaler import (DynamicLossScaler,
                                                    LossScalerState,
                                                    StaticLossScaler)


class FP16_Optimizer:

    def __init__(self, init_optimizer, params=None, static_loss_scale=None,
                 dynamic_loss_scale=True, initial_dynamic_scale=2**16,
                 dynamic_loss_args=None, clip_grad=0.0, verbose=False,
                 mpu=None, fused_adam_legacy=False, timers=None):
        self.optimizer = init_optimizer
        self.clip_grad = float(clip_grad or 0.0)
        if dynamic_loss_scale and static_loss_scale is None:
            args = dynamic_loss_args or {}
            self.loss_scaler = DynamicLossScaler(
                init_scale=initial_dynamic_scale, **args)
        else:
            self.loss_scaler = StaticLossScaler(static_loss_scale or 1.0)
        self.fp32_groups_flat = None   # master params (pytree)
        self.opt_state = None
        self.scaler_state = self.loss_scaler.init()
        self.overflow = False
        self.step_count = 0
        self._pending_grads = None
        if params is not None:
            self.initialize_masters(params)

    # -------------------------------------------------------------- #
    def initialize_masters(self, fp16_params):
        self.fp32_groups_flat = jax.tree.map(
            lambda p: jnp.asarray(p, jnp.float32), fp16_params)
        self.opt_state = self.optimizer.init(self.fp32_groups_flat)

    @property
    def cur_scale(self):
        return float(self.scaler_state.scale)

    def scale_loss(self, loss):
        """Multiply the loss by the live scale before differentiation (the
        functional analog of reference ``backward(loss)``'s scaled
        ``loss.backward()``)."""
        return loss * self.scaler_state.scale

    def backward(self, grads_of_scaled_loss):
        """Stage the (scaled) grads for ``step`` (reference computes them via
        autograd; jax hands them to us)."""
        self._pending_grads = grads_of_scaled_loss

    # -------------------------------------------------------------- #
    def _step_fn(self):
        clip = self.clip_grad
        scaler = self.loss_scaler
        opt = self.optimizer

        def step(masters, opt_state, scaler_state, grads, step_no):
            inv = 1.0 / scaler_state.scale
            grads = jax.tree.map(lambda g: g.astype(jnp.float32) * inv, grads)
            flat = jax.tree.leaves(grads)
            found_inf = jnp.logical_not(jnp.all(
                jnp.stack([jnp.all(jnp.isfinite(g)) for g in flat])))
            gnorm = jnp.sqrt(sum(jnp.sum(g * g) for g in flat))
            if clip > 0:
                factor = jnp.minimum(1.0, clip / (gnorm + 1e-6))
                grads = jax.tree.map(lambda g: g * factor, grads)
            new_masters, new_opt = opt.update(grads, opt_state, masters,
                                              step=step_no)
            keep = lambda new, old: jax.tree.map(
                lambda n, o: jnp.where(found_inf, o, n), new, old)
            return (keep(new_masters, masters), keep(new_opt, opt_state),
                    scaler.update(scaler_state, found_inf), found_inf, gnorm)

        return jax.jit(step, donate_argnums=(0, 1))

    def step(self, closure=None):
        assert self._pending_grads is not None, "backward() not called"
        assert self.fp32_groups_flat is not None, \
            "initialize_masters() not called"
        if not hasattr(self, "_jitted_step"):
            self._jitted_step = self._step_fn()
        self.step_count += 1
        (self.fp32_groups_flat, self.opt_state, self.scaler_state,
         found_inf, self._last_norm) = self._jitted_step(
            self.fp32_groups_flat, self.opt_state, self.scaler_state,
            self._pending_grads, jnp.asarray(self.step_count, jnp.int32))
        self._pending_grads = None
        self.overflow = bool(jax.device_get(found_inf))
        return self.overflow

    # -------------------------------------------------------------- #
    def get_fp16_params(self):
        """Current working (half) weights derived from the masters."""
        return jax.tree.map(lambda p: p.astype(jnp.float16),
                            self.fp32_groups_flat)

    def state_dict(self):
        return {
            "step": self.step_count,
            "fp32_groups_flat": jax.device_get(self.fp32_groups_flat),
            "optimizer_state": jax.device_get(self.opt_state),
            "loss_scaler": jax.device_get(self.scaler_state),
            "overflow": self.overflow,
        }

    def load_state_dict(self, sd, load_optimizer_states=True):
        self.step_count = sd["step"]
        self.fp32_groups_flat = jax.tree.map(jnp.asarray,
                                             sd["fp32_groups_flat"])
        if load_optimizer_states and sd.get("optimizer_state") is not None:
            from deepspeed_tpu.runtime.utils import rehydrate_opt_state
            self.opt_state = rehydrate_opt_state(self.opt_state,
                                                 sd["optimizer_state"])
        sc = sd.get("loss_scaler")
        if sc is not None:
            self.scaler_state = sc if isinstance(sc, LossScalerState) else \
                LossScalerState(*sc)
