"""Network front end for the serving engine (``docs/serving.md``
"Network front end"): the asyncio HTTP transport with per-token
streaming (``transport.py``) and multi-tenant fairness accounting
(``fairness.py``).  Priority lanes live in the engine's admission queue
(``serving.priority_lanes``); this package is pure host orchestration —
it adds no jitted programs, so the one-decode-executable-per-server
invariant is untouched.

``transport`` is imported lazily: ``fairness`` must stay importable from
the engine's ``__init__`` without dragging in asyncio machinery.
"""

from deepspeed_tpu.inference.serving.frontend.fairness import \
    FairnessTracker

__all__ = ["FairnessTracker", "ServingHTTPFrontend", "serve_http"]


def __getattr__(name):
    if name in ("ServingHTTPFrontend", "serve_http"):
        from deepspeed_tpu.inference.serving.frontend import transport
        return getattr(transport, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
