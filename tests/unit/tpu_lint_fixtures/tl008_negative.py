"""TL008 negative fixture — every guarded access is lock-correct.
Expect ZERO findings.
# tpu-lint: concurrency-scope
"""
import threading


class MiniEngine:
    GUARDED_FIELDS = {"_queue": "_lock", "stats": "_lock"}

    def __init__(self):
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._queue = []
        self.stats = {"n": 0}
        self._mirror = {}                # guarded-by: _lock
        self.config = {"depth": 4}       # undeclared: not checked

    def submit(self, x):
        with self._lock:
            self._queue.append(x)
            self.stats["n"] += 1

    def _drain_locked(self):             # lock-held: _lock
        while self._queue:
            self._queue.pop()
        self._mirror.clear()

    def blocked_submit(self, x):
        with self._cond:                 # condvar alias of _lock
            self._queue.append(x)
            self._cond.notify_all()

    def free_reads(self):
        return self.config["depth"]      # undeclared field: fine


def metrics(srv):
    with srv._lock:
        return dict(srv.stats)           # locked non-self access


def unrelated(obj):
    return obj.config                    # not a guarded field
