"""Coverage for LR schedules, monitor backends, checkpoint engines, timers,
and comms logging (analogs of reference tests/unit/{runtime/test_lr_schedules,
monitor/test_monitor,checkpoint})."""

import os
import time

import numpy as np
import pytest

import jax.numpy as jnp

from deepspeed_tpu.runtime.lr_schedules import (LRRangeTest, OneCycle,
                                                WarmupCosineLR, WarmupDecayLR,
                                                WarmupLR, build_lr_scheduler)


# ------------------------------------------------------------------ #
# LR schedules
# ------------------------------------------------------------------ #
def _curve(sched, n):
    out = []
    for _ in range(n):
        sched.step()
        out.append(sched.get_lr()[0])
    return np.asarray(out)


def test_warmup_lr_ramps_then_holds():
    s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=1.0, warmup_num_steps=10)
    lrs = _curve(s, 20)
    assert lrs[0] < 0.2 and lrs[9] == pytest.approx(1.0, rel=1e-6)
    np.testing.assert_allclose(lrs[10:], 1.0)
    assert np.all(np.diff(lrs[:10]) >= 0)


def test_warmup_decay_lr_decays_to_zero():
    s = WarmupDecayLR(total_num_steps=20, warmup_max_lr=1.0,
                      warmup_num_steps=5)
    lrs = _curve(s, 20)
    assert np.argmax(lrs) <= 5
    assert lrs[-1] < 0.1 * lrs.max()


def test_warmup_cosine_lr_shape():
    s = WarmupCosineLR(total_num_steps=40, warmup_max_lr=1.0,
                       warmup_num_steps=4)
    lrs = _curve(s, 40)
    assert np.argmax(lrs) <= 5
    assert lrs[-1] < lrs[20] < lrs.max()


def test_lr_range_test_grows():
    s = LRRangeTest(lr_range_test_min_lr=1e-4, lr_range_test_step_size=5,
                    lr_range_test_step_rate=2.0)
    lrs = _curve(s, 25)
    assert lrs[-1] > lrs[0]
    assert np.all(np.diff(lrs) >= -1e-12)


def test_one_cycle_up_down():
    s = OneCycle(cycle_min_lr=0.1, cycle_max_lr=1.0, cycle_first_step_size=10)
    lrs = _curve(s, 30)
    peak = np.argmax(lrs)
    assert 5 <= peak <= 15
    assert lrs[-1] < lrs[peak]


def test_build_lr_scheduler_and_state_roundtrip():
    from deepspeed_tpu.runtime.config import SchedulerConfig
    cfg = SchedulerConfig(type="WarmupLR",
                          params={"warmup_max_lr": 0.5, "warmup_num_steps": 4})
    s = build_lr_scheduler(cfg, None)
    for _ in range(3):
        s.step()
    sd = s.state_dict()
    s2 = build_lr_scheduler(cfg, None)
    s2.load_state_dict(sd)
    assert s2.get_lr() == s.get_lr()


# ------------------------------------------------------------------ #
# monitor backends
# ------------------------------------------------------------------ #
def test_csv_monitor_and_master(tmp_path):
    from deepspeed_tpu.monitor.monitor import MonitorMaster
    from deepspeed_tpu.runtime.config import (CSVConfig, MonitorConfig,
                                              TensorBoardConfig, WandbConfig)
    mc = MonitorConfig(
        tensorboard=TensorBoardConfig(enabled=False),
        wandb=WandbConfig(enabled=False),
        csv_monitor=CSVConfig(enabled=True, output_path=str(tmp_path),
                              job_name="job"))
    master = MonitorMaster(mc)
    assert master.enabled
    master.write_events([("Train/loss", 1.5, 10), ("Train/lr", 0.1, 10)])
    master.write_events([("Train/loss", 1.2, 20)])
    # the CSV backend keeps its handles OPEN and buffered between
    # write_events calls — flush() makes the rows durable
    master.flush()
    files = [f for root, _, fs in os.walk(tmp_path) for f in fs]
    assert any(f.endswith(".csv") for f in files), files
    csvs = [os.path.join(root, f) for root, _, fs in os.walk(tmp_path)
            for f in fs if "loss" in f]
    content = open(csvs[0]).read()
    assert "1.5" in content and "1.2" in content
    master.close()


def test_csv_monitor_flush_modes_and_context_manager(tmp_path):
    """The flush/close contract (docs/observability.md): the default
    backend is durable per write_events batch (training engines never
    flush); batch_flush=False buffers in the persistent handle until
    flush()/close(), and the context manager closes — so a short-lived
    serving process using `with` never drops its tail events."""
    from deepspeed_tpu.monitor.monitor import csvMonitor
    from deepspeed_tpu.runtime.config import CSVConfig

    cfg = CSVConfig(enabled=True, output_path=str(tmp_path),
                    job_name="job")
    path = os.path.join(str(tmp_path), "job", "Serving_tok_s.csv")
    # default: every batch is durable without an explicit flush (the
    # seed contract non-serving callers rely on)
    mon0 = csvMonitor(cfg)
    mon0.write_events([("Serving/tok_s", 1.75, 0)])
    assert "1.75" in open(path).read()
    mon0.close()

    mon = csvMonitor(cfg, batch_flush=False)
    mon.write_events([("Serving/tok_s", 3.25, 1)])
    # a tiny row sits in the userspace buffer: the file on disk does
    # not yet hold it until flush()
    assert "3.25" not in open(path).read()
    mon.flush()
    assert "3.25" in open(path).read()
    mon.write_events([("Serving/tok_s", 7.5, 2)])
    mon.close()                          # close flushes
    assert "7.5" in open(path).read()
    assert not mon.filehandles           # handles released

    # context-manager form: exit closes (and therefore flushes)
    with csvMonitor(cfg, batch_flush=False) as mon2:
        mon2.write_events([("Serving/tok_s", 9.125, 3)])
    assert "9.125" in open(path).read()


# ------------------------------------------------------------------ #
# checkpoint engines
# ------------------------------------------------------------------ #
@pytest.mark.parametrize("engine_name", ["torch", "nebula"])
def test_checkpoint_engine_roundtrip(tmp_path, engine_name):
    from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import (
        NebulaCheckpointEngine, TorchCheckpointEngine)
    eng = (TorchCheckpointEngine() if engine_name == "torch"
           else NebulaCheckpointEngine())
    arrays = {"w": jnp.arange(8.0), "nested": {"b": jnp.ones((2, 2))}}
    meta = {"global_steps": 7, "client_state": {"run": "x"}}
    path = str(tmp_path / "state")
    eng.create("tag1")
    eng.save(arrays, meta, path)
    eng.commit("tag1")
    loaded, meta2 = eng.load(path)
    np.testing.assert_array_equal(np.asarray(loaded["w"]), np.arange(8.0))
    np.testing.assert_array_equal(np.asarray(loaded["nested"]["b"]),
                                  np.ones((2, 2)))
    assert meta2["global_steps"] == 7


# ------------------------------------------------------------------ #
# timers + comms logging
# ------------------------------------------------------------------ #
def test_throughput_timer_windows():
    from deepspeed_tpu.utils.timer import ThroughputTimer
    t = ThroughputTimer(batch_size=4, start_step=0, steps_per_output=100)
    for _ in range(5):
        t.start()
        time.sleep(0.01)
        t.stop(global_step=True, report_speed=False)
    assert t.global_step_count == 5
    assert 4 / 0.5 < t.avg_samples_per_sec() < 4 / 0.005


def test_comms_logger_records_eager_ops():
    """Eager (untraced) comm verbs feed the CommsLogger via @timed_op."""
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.comm.comm import comms_logger
    dist.configure(enabled=True, prof_all=True)
    try:
        x = jnp.ones((16,))
        dist.all_reduce(x, log_name="test_ar")
        assert any("test_ar" in k or "all_reduce" in k
                   for k in comms_logger.comms_dict), \
            list(comms_logger.comms_dict)
    finally:
        dist.configure(enabled=False)


def test_comms_logger_prof_ops_filter():
    """prof_all=False restricts logging to the prof_ops allowlist."""
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.comm.comm import comms_logger
    dist.configure(enabled=True, prof_all=False, prof_ops=["broadcast"])
    try:
        comms_logger.comms_dict.clear()
        x = jnp.ones((8,))
        dist.all_reduce(x, log_name="filtered_ar")
        dist.broadcast(x, src=0)
        keys = list(comms_logger.comms_dict)
        assert not any("filtered_ar" in k for k in keys), keys
        assert any("broadcast" in k for k in keys), keys
    finally:
        dist.configure(enabled=False, prof_all=True, prof_ops=[])


def test_nebula_config_selects_async_engine(tmp_path):
    """Reference ``nebula`` config block (engine.py:858
    _configure_checkpointing): enabled → async tiered checkpoint engine."""
    import deepspeed_tpu
    from simple_model import SimpleModel, random_batch
    from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import (
        NebulaCheckpointEngine, OrbaxCheckpointEngine)
    conf = {"train_micro_batch_size_per_gpu": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "nebula": {"enabled": True,
                       "persistent_storage_path": str(tmp_path)}}
    engine, *_ = deepspeed_tpu.initialize(model=SimpleModel(), config=conf)
    assert isinstance(engine.checkpoint_engine, NebulaCheckpointEngine)
    loss = engine(random_batch())
    engine.backward(loss)
    engine.step()
    engine.save_checkpoint(str(tmp_path))
    engine2, *_ = deepspeed_tpu.initialize(model=SimpleModel(), config=conf)
    engine2.load_checkpoint(str(tmp_path))
    assert engine2.global_steps == engine.global_steps

    # default stays sync orbax
    engine3, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(),
        config={"train_micro_batch_size_per_gpu": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    assert type(engine3.checkpoint_engine) is OrbaxCheckpointEngine
