"""Accelerator selection.

Analog of the reference's ``accelerator/real_accelerator.py:37,55``
(``get_accelerator``/``set_accelerator``): pick the accelerator from the
runtime platform (TPU if present, else CPU simulation), overridable via the
``DSTPU_ACCELERATOR`` env var or ``set_accelerator()``.
"""

import os

_accelerator = None


def _detect_platform():
    override = os.environ.get("DSTPU_ACCELERATOR")
    if override:
        return override
    import jax
    try:
        platform = jax.default_backend()
    except RuntimeError:
        return "cpu"
    # 'axon' is a tunneled TPU platform; treat any non-cpu backend as TPU-like.
    return "cpu" if platform == "cpu" else "tpu"


def get_accelerator():
    global _accelerator
    if _accelerator is None:
        from .tpu_accelerator import TPU_Accelerator, CPU_Accelerator
        if _detect_platform() == "cpu":
            _accelerator = CPU_Accelerator()
        else:
            _accelerator = TPU_Accelerator()
    return _accelerator


def set_accelerator(accel):
    global _accelerator
    _accelerator = accel
    return _accelerator
