"""PipelineEngine end-to-end: pipelined transformer trains, matches the
non-pipelined engine's semantics, and composes with ZeRO/bf16."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.transformer import TransformerConfig
from deepspeed_tpu.models.pipeline_transformer import transformer_pipe
from deepspeed_tpu.runtime.pipe.schedule import TrainSchedule, InferenceSchedule


def tiny_cfg(**over):
    base = dict(vocab_size=64, hidden_size=32, num_layers=4, num_heads=4,
                max_seq_len=32, use_flash_attention=False, dtype="float32",
                scan_layers=False, remat=False)
    base.update(over)
    return TransformerConfig(**base)


def pipe_batch(M=2, mb=4, seq=16, vocab=64, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, vocab, (M, mb, seq)).astype(np.int32)}


def make_engine(pp=2, M=2, zero=0, **cfg_over):
    module = transformer_pipe(tiny_cfg(**cfg_over))
    engine, *_ = deepspeed_tpu.initialize(
        model=module,
        config={
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": M,
            "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
            "zero_optimization": {"stage": zero},
            "pipeline": {"stages": pp},
        })
    return engine


@pytest.mark.parametrize("pp", [2, 4])
def test_pipeline_transformer_trains(pp):
    engine = make_engine(pp=pp)
    batch = pipe_batch(seed=3)
    losses = [float(jax.device_get(engine.train_batch(batch=batch)))
              for _ in range(6)]
    assert losses[-1] < losses[0], f"pp={pp} no learning: {losses}"


def test_pipeline_with_zero2():
    engine = make_engine(pp=2, zero=2)
    batch = pipe_batch()
    l0 = float(jax.device_get(engine.train_batch(batch=batch)))
    l1 = float(jax.device_get(engine.train_batch(batch=batch)))
    assert np.isfinite(l0) and l1 < l0


def test_pipeline_matches_dense_engine_loss():
    """Pipelined loss at init ≈ dense-engine loss at init for the same
    architecture (different inits → compare magnitude only)."""
    engine = make_engine(pp=2)
    batch = pipe_batch()
    loss = float(jax.device_get(engine.eval_batch(batch=batch)))
    assert abs(loss - np.log(64)) < 0.8   # ~uniform prediction at init


def test_pipeline_forbids_forward_backward():
    engine = make_engine(pp=2)
    with pytest.raises(RuntimeError):
        engine({"input_ids": np.zeros((2, 4), np.int32)})
    with pytest.raises(RuntimeError):
        engine.backward(0.0)
    with pytest.raises(RuntimeError):
        engine.step()


def test_body_param_sharded_over_pp():
    engine = make_engine(pp=4)
    engine.train_batch(batch=pipe_batch())
    body_leaves = jax.tree.leaves(engine.params["body"])
    assert any("pp" in str(l.sharding.spec) for l in body_leaves), \
        "body params not sharded over pp axis"


def test_train_schedule_wavefront():
    sched = TrainSchedule(micro_batches=4, stages=2, stage_id=0)
    steps = sched.steps()
    # first tick on stage 0 loads microbatch 0 and runs forward
    names = [type(c).__name__ for c in steps[0]]
    assert names == ["LoadMicroBatch", "ForwardPass", "SendActivation"]
    # total fwd ticks = M + P - 1
    fwd_ticks = 4 + 2 - 1
    inf = InferenceSchedule(4, 2, 1).steps()
    assert len(inf) == fwd_ticks
    # last stage's first tick is idle (wavefront delay)
    assert inf[0] == []
    assert [type(c).__name__ for c in inf[1]] == ["RecvActivation", "ForwardPass"]


def test_transformer_pipe_rejects_unsupported_configs():
    """Pipe layers implement the pre-LN dense trunk only — configs they
    would silently mis-build must raise loudly."""
    from deepspeed_tpu.models.pipeline_transformer import transformer_pipe
    from deepspeed_tpu.models.transformer import TransformerConfig
    base = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                max_seq_len=16, dtype="float32", use_flash_attention=False)
    for bad in (dict(pre_layer_norm=False),
                dict(embed_proj_dim=16),
                dict(moe_num_experts=4, scan_layers=False)):
        with pytest.raises(NotImplementedError):
            transformer_pipe(TransformerConfig(**base, **bad))
