"""save_16bit_model (reference ``engine.py:3297``): real consumer-loadable
16-bit exports — torch state dict / safetensors — with HF key naming via the
injection policies' inverse mapping, round-tripped back through
``module_inject`` with logit parity."""

import numpy as np

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.transformer import Transformer, TransformerConfig


def opt_cfg(**over):
    base = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                max_seq_len=32, dtype="float32", use_flash_attention=False,
                remat=False, scan_layers=False, activation="relu",
                position_embedding="learned")
    base.update(over)
    return TransformerConfig(**base)


def make_engine(cfg):
    engine, *_ = deepspeed_tpu.initialize(
        model=Transformer(cfg),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2}})
    b = {"input_ids": np.random.default_rng(0).integers(0, 64, (8, 16))
         .astype(np.int32)}
    loss = engine(b)
    engine.backward(loss)
    engine.step()
    return engine


def test_torch_bin_is_torch_loadable(tmp_path):
    """The default pytorch_model.bin must be a REAL torch state dict
    (round-1 verdict: it was a pickle a torch consumer could not load)."""
    import torch
    engine = make_engine(opt_cfg())
    engine.save_16bit_model(str(tmp_path), hf_policy="opt")
    sd = torch.load(str(tmp_path / "pytorch_model.bin"))
    assert isinstance(sd, dict)
    assert "model.decoder.embed_tokens.weight" in sd
    assert "model.decoder.layers.0.self_attn.q_proj.weight" in sd
    w = sd["model.decoder.layers.0.fc1.weight"]
    assert isinstance(w, torch.Tensor) and w.dtype == torch.bfloat16
    # torch Linear layout: fc1 is [ffn, hidden]
    assert tuple(w.shape) == (128, 32)


def test_safetensors_export_roundtrip_logit_parity(tmp_path):
    """Export (safetensors, HF keys) → re-import through module_inject's
    OPT policy → logits match the live engine's to bf16 tolerance."""
    from safetensors.numpy import load_file
    from deepspeed_tpu.module_inject.containers import OPTPolicy
    from deepspeed_tpu.module_inject.replace_module import _materialize

    cfg = opt_cfg(pre_layer_norm=False, embed_proj_dim=16,
                  tie_word_embeddings=True)
    engine = make_engine(cfg)
    engine.save_16bit_model(str(tmp_path), "model.safetensors",
                            hf_policy="opt")
    sd = load_file(str(tmp_path / "model.safetensors"))
    # OPT-350M layout keys present, no final norm (post-LN), no lm_head (tied)
    assert "model.decoder.project_in.weight" in sd
    assert "model.decoder.final_layer_norm.weight" not in sd
    assert "lm_head.weight" not in sd

    model = Transformer(cfg)
    flat = OPTPolicy().convert(sd, cfg)
    params = _materialize(model, flat, param_dtype=jnp.float32)

    ids = np.random.default_rng(1).integers(0, 64, (2, 16)).astype(np.int32)
    want = np.asarray(jax.jit(model.apply, static_argnames="method")(
        engine.params, ids, method="logits"), np.float32)
    got = np.asarray(jax.jit(model.apply, static_argnames="method")(
        params, ids, method="logits"), np.float32)
    # the export rounded weights to bf16: logits agree to bf16 tolerance
    np.testing.assert_allclose(got, want, rtol=0.05, atol=0.05)
    agree = np.mean(np.argmax(got, -1) == np.argmax(want, -1))
    assert agree >= 0.95, agree


def test_flax_key_fallback_without_policy(tmp_path):
    """Without hf_policy the export keeps flax paths (documented default)."""
    import torch
    engine = make_engine(opt_cfg())
    engine.save_16bit_model(str(tmp_path), "flax_model.bin")
    sd = torch.load(str(tmp_path / "flax_model.bin"))
    assert any(k.startswith("embed_tokens/") for k in sd)


def test_inference_engine_loads_single_file_exports(tmp_path):
    """The export→serve handoff: InferenceEngine.load_checkpoint reads
    flax-named save_16bit_model files (both formats)."""
    import pytest
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig

    cfg = opt_cfg()
    engine = make_engine(cfg)
    engine.save_16bit_model(str(tmp_path), "flax_model.safetensors")
    engine.save_16bit_model(str(tmp_path), "flax_model.bin")
    engine.save_16bit_model(str(tmp_path), "hf_model.safetensors",
                            hf_policy="opt")
    ids = np.random.default_rng(2).integers(0, 64, (2, 8)).astype(np.int32)
    want = None
    for fname in ("flax_model.safetensors", "flax_model.bin"):
        ie = InferenceEngine(Transformer(cfg),
                             DeepSpeedInferenceConfig(dtype="float32"))
        ie.load_checkpoint(str(tmp_path / fname))
        got = np.asarray(ie.forward(ids), np.float32)
        if want is None:
            want = got
        else:
            np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)
    # HF-named files are rejected with guidance toward module_inject
    ie = InferenceEngine(Transformer(cfg),
                         DeepSpeedInferenceConfig(dtype="float32"))
    with pytest.raises(ValueError, match="module_inject"):
        ie.load_checkpoint(str(tmp_path / "hf_model.safetensors"))
