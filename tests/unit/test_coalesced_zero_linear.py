"""Tests: coalesced collectives + ZeRO-3 linear parity shims."""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.utils.jax_compat import shard_map

_SM = lambda f, mesh, i, o: shard_map(f, mesh=mesh, in_specs=i,
                                      out_specs=o, check_vma=False)


def test_reduce_scatter_coalesced(eight_devices):
    from deepspeed_tpu.runtime.comm.coalesced_collectives import (
        reduce_scatter_coalesced)
    mesh = Mesh(np.asarray(eight_devices), ("dp",))
    t1 = jnp.arange(16.0)
    t2 = jnp.ones((3, 5))  # 15 elems → padded to 16

    def run(a, b):
        outs = reduce_scatter_coalesced([a, b], "dp")
        return outs[0], outs[1]

    f = _SM(run, mesh, (P(), P()), (P("dp"), P("dp")))
    s1, s2 = f(t1, t2)
    # every device held identical copies → psum_scatter yields 8× the shard
    np.testing.assert_allclose(np.asarray(s1).ravel()[:16],
                               8 * np.arange(16.0))
    got2 = np.asarray(s2).ravel()
    np.testing.assert_allclose(got2[:15], 8 * np.ones(15))
    np.testing.assert_allclose(got2[15:], 0)  # padding


def test_all_gather_coalesced(eight_devices):
    from deepspeed_tpu.runtime.comm.coalesced_collectives import (
        all_gather_coalesced)
    mesh = Mesh(np.asarray(eight_devices), ("dp",))
    shards = jnp.arange(8.0).reshape(8, 1)  # each rank holds one scalar shard

    def run(s):
        (full,) = all_gather_coalesced([s[0]], "dp")
        return full

    f = _SM(run, mesh, (P("dp"),), P())
    np.testing.assert_allclose(np.asarray(f(shards)), np.arange(8.0))


def test_zero3_linear_matches_torch_layout():
    from deepspeed_tpu.runtime.zero.linear import (LinearModuleForZeroStage3,
                                                   zero3_linear_wrap)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 8)), jnp.float32)
    m = LinearModuleForZeroStage3(in_features=8, out_features=3)
    params = m.init(jax.random.key(0), x)
    y = m.apply(params, x)
    W = np.asarray(params["params"]["weight"])     # [out, in] torch layout
    b = np.asarray(params["params"]["bias"])
    np.testing.assert_allclose(np.asarray(y), x @ W.T + b, rtol=1e-5)
    y2 = zero3_linear_wrap(x, jnp.asarray(W), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(y2), np.asarray(y), rtol=1e-6)
