from deepspeed_tpu.autotuning.tuner.base_tuner import BaseTuner  # noqa: F401
from deepspeed_tpu.autotuning.tuner.index_based_tuner import (  # noqa: F401
    GridSearchTuner, RandomTuner)
from deepspeed_tpu.autotuning.tuner.model_based_tuner import ModelBasedTuner  # noqa: F401
