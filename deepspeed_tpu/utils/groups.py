"""Process-group registry — reference ``deepspeed/utils/groups.py`` (expert /
expert-data / model parallel group creation and cached getters).

On TPU a "group" is a set of mesh axes, not an NCCL communicator; creation is
free and the getters answer from the live ``ParallelTopology``.  Reference
names are preserved so engine/MoE code ports directly.
"""

from deepspeed_tpu.parallel import topology as _topo


def _require_topo():
    t = _topo.get_topology()
    if t is None:
        raise RuntimeError("topology not initialized; call "
                           "deepspeed_tpu.initialize or initialize_topology")
    return t


def _create_expert_and_data_parallel(expert_parallel_size):
    """Reference ``groups.py:108``: on TPU this is a mesh re-build."""
    return _topo.initialize_topology(ep=expert_parallel_size)


def _create_expert_data_and_model_parallel(expert_parallel_size, mpu=None,
                                           tensor_parallel_size=1):
    """Reference ``groups.py:202``."""
    return _topo.initialize_topology(ep=expert_parallel_size,
                                     tp=tensor_parallel_size)


# cached getters (reference groups.py:280-392) — groups are axis tuples
def _get_data_parallel_group():
    return _require_topo().get_data_parallel_axes()


def _get_model_parallel_group():
    return _require_topo().get_model_parallel_axes()


def _get_expert_parallel_group(name=None):
    return _require_topo().get_expert_parallel_axes()


def _get_expert_data_parallel_group(name=None):
    return _require_topo().get_expert_data_parallel_axes()


def _get_sequence_parallel_group():
    return _require_topo().get_sequence_parallel_axes()


def _get_data_parallel_world_size():
    return _require_topo().get_data_parallel_world_size()


def _get_model_parallel_world_size():
    return _require_topo().get_model_parallel_world_size()


def _get_expert_parallel_world_size(name=None):
    return _require_topo().get_expert_parallel_world_size()


def _get_data_parallel_rank():
    import jax
    return jax.process_index()


def _get_expert_model_parallel_world_size():
    t = _require_topo()
    return t.get_expert_parallel_world_size() * t.get_model_parallel_world_size()
