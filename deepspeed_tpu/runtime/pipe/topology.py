"""Pipeline topology shims — parity with reference
``runtime/pipe/topology.py`` (``ProcessTopology:12``,
``PipeDataParallelTopology:232``, ``PipeModelDataParallelTopology:244``,
``PipelineParallelGrid:251``).

The real topology on TPU is the named device mesh
(``deepspeed_tpu/parallel/topology.py``); these classes provide the
axes/coords rank-grid algebra for user code and tests that address ranks the
Megatron way."""

import itertools
from collections import namedtuple


class ProcessTopology:
    """Cartesian rank grid with named axes (reference ``topology.py:12``)."""

    def __init__(self, axes, dims):
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", axes)
        self.mapping = {}
        for coord in itertools.product(*[range(d) for d in dims]):
            rank = 0
            for ax, idx in enumerate(coord):
                rank = rank * dims[ax] + idx
            self.mapping[self.ProcessCoord(*coord)] = rank

    def get_rank(self, **coord_kwargs):
        key = self.ProcessCoord(**coord_kwargs)
        return self.mapping[key]

    def get_axis_names(self):
        return self.axes

    def get_coord(self, rank):
        for coord, r in self.mapping.items():
            if r == rank:
                return coord
        raise ValueError(f"rank {rank} not in topology")

    def get_dim(self, axis):
        return self.dims[self.axes.index(axis)]

    def world_size(self):
        out = 1
        for d in self.dims:
            out *= d
        return out

    def get_axis_comm_lists(self, axis):
        """Lists of ranks varying only along ``axis`` — the rank sets the
        reference builds communicators from (here: documentation of which
        mesh axis a collective rides)."""
        ax = self.axes.index(axis)
        others = [a for a in self.axes if a != axis]
        lists = []
        for coord in itertools.product(*[range(self.get_dim(a)) for a in others]):
            fixed = dict(zip(others, coord))
            lists.append([self.get_rank(**{**fixed, axis: i})
                          for i in range(self.dims[ax])])
        return lists

    def filter_match(self, **filter_kwargs):
        return [rank for coord, rank in self.mapping.items()
                if all(getattr(coord, k) == v for k, v in filter_kwargs.items())]


class PipeDataParallelTopology(ProcessTopology):
    def __init__(self, num_pp, num_dp):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    def __init__(self, num_pp, num_mp, num_dp):
        super().__init__(axes=["pipe", "data", "model"],
                         dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """mpu-compatible facade (reference ``topology.py:251``) backed by the
    live device mesh."""

    def __init__(self, topology=None, process_group=None):
        from deepspeed_tpu.parallel.topology import get_topology
        self._mesh_topo = get_topology()
        self.pipe_parallel_size = self._mesh_topo.pp
        self.data_parallel_size = self._mesh_topo.dp
        self.model_parallel_size = self._mesh_topo.tp

    def get_pipe_parallel_world_size(self):
        return self.pipe_parallel_size

    def get_data_parallel_world_size(self):
        return self.data_parallel_size

    def get_model_parallel_world_size(self):
        return self.model_parallel_size

    def get_global_rank(self):
        import jax
        return jax.process_index()

    def get_pipe_parallel_group(self):
        return ("pp",)

    def get_data_parallel_group(self):
        from deepspeed_tpu.parallel.topology import DP_AXES
        return DP_AXES

    def get_model_parallel_group(self):
        return ("tp",)
