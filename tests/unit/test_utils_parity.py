"""Tests for utils parity components: state_dict_factory TP reshard,
tensor_fragment, OnDevice, debug, groups, SparseTensor, elastic agent
(analogs of reference tests/unit/{checkpoint/test_checkpoint_sharding,
utils,runtime/sparse_tensor,elasticity})."""

import os
import signal

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from simple_model import SimpleModel, random_batch


# ------------------------------------------------------------------ #
# state_dict_factory
# ------------------------------------------------------------------ #
def _fake_megatron_shards(tmp_path, tp=2, din=8, dout=12):
    """Write tp .npz shards of a toy megatron-ish layer set."""
    rng = np.random.default_rng(0)
    full = {
        "attn.query_key_value.weight": rng.standard_normal((3 * dout, din)).astype(np.float32),
        "attn.query_key_value.bias": rng.standard_normal(3 * dout).astype(np.float32),
        "attn.dense.weight": rng.standard_normal((din, dout)).astype(np.float32),
        "attn.dense.bias": rng.standard_normal(din).astype(np.float32),
        "ln.weight": rng.standard_normal(din).astype(np.float32),
    }
    paths = []
    for r in range(tp):
        shard = {
            # column-parallel: outputs split (torch layout axis 0)
            "attn.query_key_value.weight": np.split(full["attn.query_key_value.weight"], tp, 0)[r],
            "attn.query_key_value.bias": np.split(full["attn.query_key_value.bias"], tp, 0)[r],
            # row-parallel: inputs split (torch layout axis 1); bias replicated
            "attn.dense.weight": np.split(full["attn.dense.weight"], tp, 1)[r],
            "attn.dense.bias": full["attn.dense.bias"],
            "ln.weight": full["ln.weight"],
        }
        p = str(tmp_path / f"mp_rank_{r:02d}_model_states.npz")
        np.savez(p, **shard)
        paths.append(p)
    return full, paths


def test_sd_loader_merge(tmp_path):
    from deepspeed_tpu.runtime.state_dict_factory import MegatronSDLoader
    full, paths = _fake_megatron_shards(tmp_path, tp=2)
    merged = MegatronSDLoader(paths).merge_state_dict()
    for k, v in full.items():
        np.testing.assert_array_equal(merged[k], v, err_msg=k)


def test_sd_loader_split_roundtrip(tmp_path):
    from deepspeed_tpu.runtime.state_dict_factory import MegatronSDLoader
    full, paths = _fake_megatron_shards(tmp_path, tp=2)
    loader = MegatronSDLoader(paths)
    # 2 shards → 4-way TP: each target rank gets half of one source shard
    r0 = loader.load(mp_world_size=4, mp_rank=0)
    r1 = loader.load(mp_world_size=4, mp_rank=1)
    both = np.concatenate([r0["attn.query_key_value.weight"],
                           r1["attn.query_key_value.weight"]], axis=0)
    np.testing.assert_array_equal(
        both, np.split(full["attn.query_key_value.weight"], 2, 0)[0])
    # 2 shards → 1: full merge
    whole = loader.load(mp_world_size=1, mp_rank=0)
    np.testing.assert_array_equal(whole["attn.dense.weight"],
                                  full["attn.dense.weight"])


def test_sd_loader_factory_json(tmp_path):
    from deepspeed_tpu.runtime.state_dict_factory import SDLoaderFactory
    _, paths = _fake_megatron_shards(tmp_path, tp=2)
    t, lst, ver = SDLoaderFactory.get_sd_loader_json(
        {"type": "Megatron", "checkpoints": paths, "version": 1.0})
    assert t == "Megatron" and len(lst) == 2 and ver == 1.0
    loader = SDLoaderFactory.get_sd_loader(lst)
    assert len(loader) == 2


# ------------------------------------------------------------------ #
# tensor_fragment / OnDevice / debug
# ------------------------------------------------------------------ #
def _engine():
    e, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16),
        config={"train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {"stage": 3}})
    loss = e(random_batch())
    e.backward(loss)
    e.step()
    return e


def test_tensor_fragment_full_views():
    from deepspeed_tpu.utils.tensor_fragment import (
        get_local_fragment, safe_get_full_fp32_param,
        safe_get_full_optimizer_state, safe_set_full_fp32_param)
    e = _engine()
    path = "params/linear_0/kernel"
    w = safe_get_full_fp32_param(e, path)
    assert w.shape == (16, 16)
    m = safe_get_full_optimizer_state(e, path, "exp_avg")
    assert m is not None and m.shape == (16, 16)
    # ZeRO-3: the param is genuinely sharded → local fragment is a slice
    leaf = e._params["params"]["linear_0"]["kernel"]
    frags = get_local_fragment(leaf)
    assert len(frags) >= 1
    new = np.zeros_like(w)
    safe_set_full_fp32_param(e, path, new)
    np.testing.assert_array_equal(safe_get_full_fp32_param(e, path), new)


def test_on_device_meta_init():
    from deepspeed_tpu.utils.init_on_device import OnDevice, abstract_init
    model = SimpleModel(hidden_dim=16)
    with OnDevice(dtype=jnp.bfloat16, device="meta"):
        tree = abstract_init(model.init, jax.random.key(0), random_batch())
    leaves = jax.tree.leaves(tree)
    assert all(isinstance(l, jax.ShapeDtypeStruct) for l in leaves)
    assert all(l.dtype == jnp.bfloat16 for l in leaves)


def test_debug_name_maps():
    from deepspeed_tpu.utils.debug import debug_extract_module_and_param_names
    e = _engine()
    names = debug_extract_module_and_param_names(jax.device_get(e.params))
    assert "params/linear_0/kernel" in names
    assert names["params/linear_0/kernel"] == (16, 16)


def test_groups_getters():
    from deepspeed_tpu.utils import groups
    deepspeed_tpu.initialize_topology(tp=2)
    assert groups._get_model_parallel_world_size() == 2
    assert groups._get_data_parallel_world_size() == 4
    assert groups._get_model_parallel_group()


# ------------------------------------------------------------------ #
# SparseTensor + sparse allreduce
# ------------------------------------------------------------------ #
def test_sparse_tensor_roundtrip():
    from deepspeed_tpu.runtime.sparse_tensor import SparseTensor
    d = np.zeros((10, 4), np.float32)
    d[2] = 1.0
    d[7] = -2.0
    st = SparseTensor.from_dense(d)
    assert st.indices.shape == (2,)
    np.testing.assert_array_equal(np.asarray(st.to_dense()), d)
    nnz, total = st.sparse_size()
    assert nnz == 8 and total == 40


def test_sparse_allreduce(eight_devices):
    import functools
    from deepspeed_tpu.utils.jax_compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    from deepspeed_tpu.runtime.sparse_tensor import SparseTensor, sparse_allreduce
    mesh = Mesh(np.array(eight_devices), ("dp",))
    # each device contributes one row (row = device index), duplicates add
    idx = jnp.arange(8, dtype=jnp.int32).reshape(8, 1) % 4
    vals = jnp.ones((8, 1, 4), jnp.float32)

    @functools.partial(shard_map, mesh=mesh, in_specs=(P("dp"), P("dp")),
                       out_specs=(P(), P()), check_vma=False)
    def run(i, v):
        st = SparseTensor(i[0], v[0], (10, 4))
        red = sparse_allreduce(st, "dp")
        return red.indices, red.values

    gi, gv = run(idx, vals)
    st = SparseTensor(gi, gv, (10, 4))
    dense = np.asarray(st.to_dense())
    # rows 0..3 each hit by 2 devices, mean-reduced values 1/8 → sum 2/8
    np.testing.assert_allclose(dense[:4], np.full((4, 4), 0.25))
    np.testing.assert_allclose(dense[4:], 0.0)


# ------------------------------------------------------------------ #
# elastic agent
# ------------------------------------------------------------------ #
def test_elastic_agent_preemption(tmp_path):
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    e = _engine()
    agent = DSElasticAgent({}, checkpoint_dir=str(tmp_path))
    calls = {"n": 0}

    def step():
        calls["n"] += 1
        if calls["n"] == 3:
            os.kill(os.getpid(), signal.SIGTERM)

    status, steps = agent.run(step, e, max_steps=10)
    assert status == "preempted" and steps == 3
    assert os.path.exists(os.path.join(str(tmp_path), "latest"))


def test_elastic_agent_config_resize():
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    ds_cfg = {"elasticity": {"enabled": True, "micro_batch_sizes": [2, 4],
                             "max_train_batch_size": 64, "min_gpus": 1,
                             "max_gpus": 64, "version": 0.1}}
    agent = DSElasticAgent(ds_cfg, world_size=8)
    cfg4 = agent.elastic_config_for(4)
    cfg8 = agent.elastic_config_for(8)
    # global batch preserved across slice resize
    assert cfg4["train_batch_size"] == cfg8["train_batch_size"]
    for cfg, n in ((cfg4, 4), (cfg8, 8)):
        assert cfg["train_micro_batch_size_per_gpu"] * \
            cfg["gradient_accumulation_steps"] * n == cfg["train_batch_size"]

def test_megatron_v1_qkv_split_merge_roundtrip(tmp_path):
    """Version-aware fused-QKV shard handling (reference
    ``merge_query_key_value``): v1 shards are [q_r|k_r|v_r]; naive concat
    would interleave per-rank blocks."""
    import numpy as np
    from deepspeed_tpu.runtime.state_dict_factory import MegatronSDLoader

    rng = np.random.default_rng(0)
    w = rng.standard_normal((12, 4)).astype(np.float32)   # [3h=12, in]
    b = rng.standard_normal((12,)).astype(np.float32)
    full = {"transformer.layers.0.attention.query_key_value.weight": w,
            "transformer.layers.0.attention.query_key_value.bias": b}
    p0 = tmp_path / "full.npz"
    np.savez(p0, **full)

    loader = MegatronSDLoader([str(p0)], version=1.0)
    shard_paths = []
    for r in range(2):
        shard = loader.split_state_dict(2, r)
        # v1 rank shard really is [q_r|k_r|v_r]
        np.testing.assert_array_equal(
            shard["transformer.layers.0.attention.query_key_value.weight"],
            np.concatenate([np.split(t, 2)[r] for t in np.split(w, 3)]))
        p = tmp_path / f"rank{r}.npz"
        np.savez(p, **shard)
        shard_paths.append(str(p))

    merged = MegatronSDLoader(shard_paths, version=1.0).merge_state_dict()
    np.testing.assert_array_equal(
        merged["transformer.layers.0.attention.query_key_value.weight"], w)
    np.testing.assert_array_equal(
        merged["transformer.layers.0.attention.query_key_value.bias"], b)


def test_megatron_vocab_parallel_embedding_merge(tmp_path):
    """VocabParallelEmbedding shards (differing across ranks) concatenate on
    the vocab dim; replicated embeddings pass through."""
    import numpy as np
    from deepspeed_tpu.runtime.state_dict_factory import MegatronSDLoader

    rng = np.random.default_rng(1)
    emb = rng.standard_normal((8, 4)).astype(np.float32)
    pos = rng.standard_normal((6, 4)).astype(np.float32)
    paths = []
    for r in range(2):
        p = tmp_path / f"r{r}.npz"
        np.savez(p, **{"word_embeddings.weight": np.split(emb, 2)[r],
                       "position_embeddings.weight": pos})
        paths.append(str(p))
    merged = MegatronSDLoader(paths, version=2.0).merge_state_dict()
    np.testing.assert_array_equal(merged["word_embeddings.weight"], emb)
    np.testing.assert_array_equal(merged["position_embeddings.weight"], pos)


def test_megatron_vocab_embedding_uneven_and_split_symmetry(tmp_path):
    """Unevenly-split vocab shards must concatenate (no broadcast crash);
    split_state_dict shards the vocab dim so merge∘split is the identity."""
    import numpy as np
    from deepspeed_tpu.runtime.state_dict_factory import MegatronSDLoader

    rng = np.random.default_rng(2)
    emb = rng.standard_normal((10, 4)).astype(np.float32)
    paths = []
    for r, sl in enumerate((slice(0, 6), slice(6, 10))):   # 6 + 4 rows
        p = tmp_path / f"u{r}.npz"
        np.savez(p, **{"word_embeddings.weight": emb[sl]})
        paths.append(str(p))
    merged = MegatronSDLoader(paths, version=2.0).merge_state_dict()
    np.testing.assert_array_equal(merged["word_embeddings.weight"], emb)

    # split from a single full checkpoint shards the vocab dim
    full = tmp_path / "full.npz"
    np.savez(full, **{"word_embeddings.weight": emb})
    loader = MegatronSDLoader([str(full)], version=2.0)
    s0 = loader.split_state_dict(2, 0)["word_embeddings.weight"]
    s1 = loader.split_state_dict(2, 1)["word_embeddings.weight"]
    np.testing.assert_array_equal(np.concatenate([s0, s1]), emb)
