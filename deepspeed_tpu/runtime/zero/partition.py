"""ZeRO as GSPMD sharding.

The reference implements ZeRO with ~7k lines of gradient hooks, bucketed
reduce-scatter, and just-in-time parameter all-gather
(``runtime/zero/stage_1_and_2.py:90``, ``stage3.py:65``,
``partition_parameters.py:603``, ``partitioned_param_coordinator.py:43``).
On TPU the same memory/communication behavior is a *sharding annotation*:

* **ZeRO-1** — optimizer state sharded over the DP axes; XLA all-gathers the
  updated params once per step (= reference ``stage_1_and_2.py:1750``
  allgather of updated 16-bit params).
* **ZeRO-2** — gradients additionally stored sharded; grad production inside
  the jitted step lowers to reduce-scatter instead of all-reduce
  (= reference IPG bucketing ``stage_1_and_2.py:833`` — XLA's latency-hiding
  scheduler provides the comm/compute overlap the comm-stream machinery
  hand-builds on GPU).
* **ZeRO-3** — parameters themselves sharded; XLA inserts per-layer
  all-gathers at use sites and frees gathered buffers after use
  (= reference trace-based fetch/release coordinator,
  ``partitioned_param_coordinator.py:230``).
* **MiCS** — params sharded over the inner (ICI-local) ``edp`` sub-axis only
  and replicated across the outer axis (= reference two-hop gather,
  ``runtime/zero/mics.py:24-29``).

This module turns (abstract param tree, topology, zero config, TP rules) into
``PartitionSpec`` trees for params / grads / optimizer state.
"""

import re

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.topology import (DP_AXES, EDP_AXIS, EP_AXIS, TP_AXIS)
from deepspeed_tpu.utils.logging import logger


def _used_axes(spec):
    used = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return used


def _axis_group_size(mesh, axes):
    size = 1
    for a in axes:
        size *= mesh.shape[a]
    return size


def choose_zero_dim(shape, spec, mesh, zero_axes):
    """Pick the dimension to additionally shard over the ZeRO axes: the
    largest dim divisible by the zero-group size that isn't already sharded.
    Returns None if nothing fits (leaf stays replicated over DP — the analog
    of the reference's ``param_persistence_threshold`` persisted params)."""
    n = _axis_group_size(mesh, zero_axes)
    if n == 1:
        return None
    candidates = []
    for d, size in enumerate(shape):
        if spec[d] is None and size % n == 0 and size >= n:
            candidates.append((size, d))
    if not candidates:
        return None
    return max(candidates)[1]


def apply_zero_to_spec(shape, spec, mesh, zero_axes):
    """Extend a (possibly TP-sharded) spec with ZeRO sharding over ``zero_axes``."""
    spec = list(spec) + [None] * (len(shape) - len(spec))
    used = _used_axes(spec)
    zero_axes = tuple(a for a in zero_axes if a not in used and mesh.shape[a] > 1)
    if not zero_axes:
        return P(*spec)
    d = choose_zero_dim(shape, spec, mesh, zero_axes)
    if d is None:
        return P(*spec)
    spec[d] = zero_axes if len(zero_axes) > 1 else zero_axes[0]
    return P(*spec)


# --------------------------------------------------------------------- #
# Tensor-parallel sharding rules (AutoTP analog: reference
# ``module_inject/auto_tp.py:13`` infers row/column slicing from module
# structure; here we infer from param-tree path names).
# --------------------------------------------------------------------- #
# (regex over joined path, partition spec entries by dim-from-the-right)
# "col" = shard output features: the last dim of a 2-D kernel, the HEAD dim
# (ndim-2) of a ≥3-D DenseGeneral kernel (whole heads per tp rank).
# "row" = shard input features (dim 0) — Megatron column/row linear.
# Expert-parameter contract: a path component named ``experts`` or a leaf
# named ``experts_*`` marks a STACKED expert parameter whose dim 0 is the
# expert dim (the layout ``moe/layer.py ExpertsMLP`` produces).  Custom
# expert modules must follow this naming to get ep sharding.
EXPERT_PARAM_PATTERN = r"(^|/)experts(_[a-z0-9_]+)?(/|$)"

DEFAULT_TP_RULES = [
    (r"(q_proj|k_proj|v_proj|qkv|query|key|value|gate_proj|up_proj|wi|fc1|fc_in|c_fc|dense_h_to_4h).*(kernel|weight)$", "col"),
    (r"(o_proj|out_proj|down_proj|wo|fc2|fc_out|c_proj|dense_4h_to_h|attention_output|dense$).*", "row"),
    (r"(embed|wte|word_embeddings|embed_tokens).*(embedding|kernel|weight)$", "vocab"),
    (r"(lm_head|output_projection).*(kernel|weight)$", "col"),
    (r".*(norm|ln_|layernorm|layer_norm|bias|scale).*", "replicate"),
]


def path_to_str(path):
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def tp_dim_for(kind, ndim, expert_stacked=False):
    """The ONE source of truth mapping a rule kind to the sharded dim —
    shared by runtime placement (``tp_spec_for``) and offline checkpoint
    surgery (``checkpoint/reshape_utils.infer_tp_dim``), which must agree by
    construction.

    col → output dim: last dim of a 2-D kernel; the HEAD dim (ndim-2) of a
    ≥3-D DenseGeneral kernel (whole heads per tp rank, Megatron layout).
    row → first input dim (dim 0).  ``expert_stacked`` strips the leading
    expert dim first (stacked MoE params shard their PER-EXPERT shape)."""
    if expert_stacked:
        inner = tp_dim_for(kind, ndim - 1)
        # a per-expert shape too small to shard must NOT fall back onto the
        # expert dim
        return None if inner is None or inner < 0 else inner + 1
    col_dim = ndim - 1 if ndim == 2 else ndim - 2
    dim = {"col": col_dim, "row": 0, "vocab": 0}.get(kind)
    return None if dim is not None and dim < 0 else dim


def is_expert_stacked(path_str, ndim):
    """Shared predicate: does this leaf carry a leading stacked-expert dim?
    Used by runtime placement AND checkpoint surgery — one definition so
    they cannot disagree."""
    return re.search(EXPERT_PARAM_PATTERN, path_str.lower()) is not None \
        and ndim >= 2


def tp_rule_kind(path_str, rules=None):
    rules = rules if rules is not None else DEFAULT_TP_RULES
    low = path_str.lower()
    for pattern, kind in rules:
        if re.search(pattern, low):
            return kind
    return None


def tp_spec_for(path_str, shape, mesh, rules=None, expert_stacked=False):
    """PartitionSpec from TP rules for one leaf.  A rule only applies when
    the target dim is divisible by the tp size (e.g. odd vocab sizes stay
    replicated — the reference pads instead, ``replace_module.py`` weight
    slicing asserts divisibility)."""
    ndim = len(shape)
    tp_size = mesh.shape.get(TP_AXIS, 1)
    if tp_size == 1:
        return P(*([None] * ndim))
    kind = tp_rule_kind(path_str, rules)
    if kind is not None:
        spec = [None] * ndim
        dim = tp_dim_for(kind, ndim, expert_stacked=expert_stacked)
        # "replicate" (or non-divisible) leaves all None
        if dim is not None and dim >= 0 and shape[dim] % tp_size == 0:
            spec[dim] = TP_AXIS
        return P(*spec)
    return P(*([None] * ndim))


# --------------------------------------------------------------------- #
def spec_or_replicated(mesh, spec, leaf):
    """NamedSharding for ``leaf`` under ``spec`` — replicated when the spec
    has more dims than the leaf.  Optimizer states may carry per-tensor
    scalar stats (e.g. 1-bit LAMB's frozen trust ratios) that mirror a
    param's tree *path* but not its rank; a param-ranked spec would be an
    invalid sharding for them."""
    if len(spec) > getattr(leaf, "ndim", np.ndim(leaf)):
        return NamedSharding(mesh, P())
    return NamedSharding(mesh, spec)


class ZeroShardingPlan:
    """Per-tree PartitionSpec plans for the three state classes."""

    def __init__(self, param_specs, grad_specs, opt_specs, mesh):
        self.param_specs = param_specs
        self.grad_specs = grad_specs
        self.opt_specs = opt_specs
        self.mesh = mesh

    def shardings(self, specs):
        return jax.tree.map(lambda s: NamedSharding(self.mesh, s), specs,
                            is_leaf=lambda x: isinstance(x, P))

    @property
    def param_shardings(self):
        return self.shardings(self.param_specs)

    @property
    def grad_shardings(self):
        return self.shardings(self.grad_specs)

    def opt_shardings_for(self, opt_state):
        """Match opt-state leaves (moments mirror param shapes) to opt specs."""
        flat_specs = {path_to_str(p): s for p, s in
                      jax.tree_util.tree_leaves_with_path(
                          self.opt_specs, is_leaf=lambda x: isinstance(x, P))}
        # opt state is a NamedTuple of param-shaped trees; map by suffix path
        def leaf_spec(path, leaf):
            ps = path_to_str(path)
            for k, s in flat_specs.items():
                if ps.endswith(k) or k.endswith(ps):
                    return spec_or_replicated(self.mesh, s, leaf)
            # scalars (loss scale, step counters) replicate
            if np.ndim(leaf) == 0 or not hasattr(leaf, "shape") or leaf.shape == ():
                return NamedSharding(self.mesh, P())
            return NamedSharding(self.mesh, P())
        return jax.tree_util.tree_map_with_path(leaf_spec, opt_state)


def build_sharding_plan(abstract_params, topo, zero_config, tp_rules=None):
    """The ZeRO "partitioner": params → spec trees for params/grads/opt state.

    ``abstract_params``: pytree of ShapeDtypeStruct (or arrays).
    """
    mesh = topo.mesh
    stage = zero_config.stage if zero_config else 0
    mics = zero_config.mics_shard_size if zero_config else -1
    # MiCS: restrict ZeRO sharding to the inner edp sub-axis (ICI-local)
    # and replicate across ep/outer — reference mics.py two-level gather.
    if mics and mics > 0:
        zero_axes = (EDP_AXIS,)
    else:
        zero_axes = DP_AXES

    def specs_for(path, leaf, shard_over_zero):
        shape = leaf.shape
        ps = path_to_str(path)
        is_expert = re.search(EXPERT_PARAM_PATTERN, ps.lower()) is not None
        if is_expert and len(shape) >= 1 and mesh.shape[EP_AXIS] > 1 \
                and shape[0] % mesh.shape[EP_AXIS] == 0:
            # expert params: expert dim over 'ep', TP rules on the trailing
            # (per-expert) dims; ZeRO restricted to edp — expert grads must
            # never average across experts (reference ``stage_1_and_2.py:1781``
            # expert-data-parallel averaging)
            inner = tp_spec_for(ps, shape[1:], mesh, tp_rules)
            spec = P(EP_AXIS, *inner)
            if shard_over_zero:
                spec = apply_zero_to_spec(shape, spec, mesh, (EDP_AXIS,))
            return spec
        # stacked expert params keep per-expert TP dims even when the ep
        # fast-path doesn't apply (ep=1 / non-divisible expert count)
        spec = tp_spec_for(ps, shape, mesh, tp_rules,
                           expert_stacked=is_expert_stacked(ps, len(shape)))
        if shard_over_zero:
            spec = apply_zero_to_spec(shape, spec, mesh, zero_axes)
        return spec

    param_specs = jax.tree_util.tree_map_with_path(
        lambda p, l: specs_for(p, l, stage >= 3), abstract_params)
    grad_specs = jax.tree_util.tree_map_with_path(
        lambda p, l: specs_for(p, l, stage >= 2), abstract_params)
    opt_specs = jax.tree_util.tree_map_with_path(
        lambda p, l: specs_for(p, l, stage >= 1), abstract_params)

    n_leaves = len(jax.tree.leaves(param_specs, is_leaf=lambda x: isinstance(x, P)))
    logger.info(f"ZeRO stage {stage}: sharding plan over mesh {dict(mesh.shape)} "
                f"for {n_leaves} param tensors (zero axes={zero_axes})")
    return ZeroShardingPlan(param_specs, grad_specs, opt_specs, mesh)
