"""Auto-resume supervisor: ``run_resilient(engine, step_fn)``.

The in-process half of surviving preemptible capacity (the out-of-process
half — restarting the killed process — belongs to the cluster scheduler;
this loop makes every restart land on its feet):

* **resume-before-run**: load the newest *valid* checkpoint (manifest
  verification + walk-back — see ``fault/manifest.py``) before the first
  step, so a restarted process continues instead of restarting.
* **retry with exponential backoff + jitter** for transient I/O and
  collective-init failures (``fault/retry.py``).
* **heartbeat watchdog**: a step exceeding ``heartbeat_timeout_secs``
  dumps every thread's stack (``faulthandler``) and raises
  :class:`StepHangError` in the main thread; the supervisor saves an
  emergency checkpoint and recovers.
* **reload-latest-valid-then-continue**: a faulted step reloads the newest
  valid checkpoint into the live engine and keeps going, up to
  ``max_resumes`` times.
* **preemption** (via :class:`DSElasticAgent`): SIGTERM marks the run; the
  next step boundary writes an emergency checkpoint and returns
  ``("preempted", ...)`` so the scheduler can reschedule; on the resized
  slice, :func:`elastic_resume_config` recomputes a global-batch-preserving
  config before the engine is rebuilt.

``step_fn(engine)`` runs ONE optimizer step (e.g. ``engine.train_batch``
on a batch derived from ``engine.global_steps``) — deriving data from the
step counter is what makes a resumed trajectory bitwise-identical to an
uninterrupted one.
"""

import faulthandler
import os
import signal
import sys
import threading
import time

from deepspeed_tpu.runtime.fault import inject
from deepspeed_tpu.runtime.fault.config import FaultConfig
from deepspeed_tpu.runtime.fault.retry import (is_transient, retry_call,
                                               retry_policy_from_config,
                                               TRANSIENT_IO_ERRORS)
from deepspeed_tpu.utils.logging import logger


class StepHangError(RuntimeError):
    """Raised in the main thread when the heartbeat watchdog expires."""


class HeartbeatWatchdog:
    """Background thread that watches an armed step deadline; on expiry it
    dumps all thread stacks and delivers a signal to the main thread whose
    handler raises :class:`StepHangError` — which interrupts blocking
    Python code (sleeps, socket waits) at the next bytecode boundary.

    The watchdog covers the ARMED window only (``arm()`` at step start,
    ``disarm()`` at step end) — checkpoint saves and recovery reloads run
    outside it, so a slow checksum pass is never mistaken for a hang."""

    _SIGNAL = getattr(signal, "SIGALRM", None)

    def __init__(self, timeout_secs, poll_secs=None):
        self.timeout = float(timeout_secs)
        self.poll = poll_secs or max(0.05, min(1.0, self.timeout / 4))
        self._beat = time.monotonic()
        self._armed = False
        self._fired = False
        self._stop = threading.Event()
        self._thread = None
        self._prev_handler = None

    def arm(self):
        self._beat = time.monotonic()
        self._fired = False
        self._armed = True

    def disarm(self):
        self._armed = False

    def _on_signal(self, signum, frame):
        if not self._armed:
            # the step finished (or recovery began) between the watchdog's
            # deadline check and the signal landing — a late StepHangError
            # outside the guarded step block would crash the supervisor
            # or interrupt a checkpoint save mid-write
            return
        raise StepHangError(
            f"step exceeded heartbeat timeout ({self.timeout:.1f}s)")

    def _watch(self):
        while not self._stop.wait(self.poll):
            if not self._armed or self._fired:
                continue
            if time.monotonic() - self._beat <= self.timeout:
                continue
            self._fired = True
            logger.error(f"[fault] heartbeat missed for "
                         f"{time.monotonic() - self._beat:.1f}s — dumping "
                         "all thread stacks")
            try:
                faulthandler.dump_traceback(file=sys.stderr,
                                            all_threads=True)
            except Exception:
                pass
            # re-check: the stack dump takes tens of ms and the step may
            # have completed during it (the handler re-checks too)
            if self._SIGNAL is not None and self._armed:
                os.kill(os.getpid(), self._SIGNAL)

    def start(self):
        if self._SIGNAL is not None:
            self._prev_handler = signal.signal(self._SIGNAL, self._on_signal)
        self._thread = threading.Thread(target=self._watch, daemon=True,
                                        name="ds-heartbeat-watchdog")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._SIGNAL is not None and self._prev_handler is not None:
            signal.signal(self._SIGNAL, self._prev_handler)
            self._prev_handler = None


def elastic_resume_config(ds_config, world_size=None):
    """Global-batch-preserving config for resuming on a (possibly resized)
    slice: when the ``elasticity`` block is enabled, recompute the batch
    triple for ``world_size`` devices via the elasticity solver (the
    reference's v0.1/v0.2 schedulers); otherwise return the config
    unchanged.  Call BEFORE constructing the engine of a restarted run."""
    if not dict(ds_config).get("elasticity", {}).get("enabled", False):
        return dict(ds_config)
    if world_size is None:
        import jax
        world_size = jax.device_count()
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    agent = DSElasticAgent(ds_config, world_size=world_size)
    cfg = agent.elastic_config_for(world_size)
    logger.info(f"[fault] elastic resume config for world={world_size}: "
                f"global={cfg['train_batch_size']} "
                f"micro={cfg['train_micro_batch_size_per_gpu']} "
                f"gas={cfg['gradient_accumulation_steps']}")
    return cfg


class _Counters:
    def __init__(self):
        self.retries = 0
        self.resumes = 0
        self.hangs = 0
        self.saves = 0


def run_resilient(engine, step_fn, checkpoint_dir, max_steps=None,
                  agent=None, fault_config=None, save_interval=None,
                  save_final=True, client_state=None, monitor=None):
    """Supervised training loop.  Returns ``(status, info)`` with status
    one of ``"done"`` / ``"preempted"`` / ``"failed"`` and info carrying
    the counters (steps/resumes/retries/hangs).

    ``max_steps`` bounds ``engine.global_steps`` (the absolute step count,
    checkpoint-resumable), not steps executed by this call.
    """
    cfg = fault_config or getattr(engine._config, "fault", None) \
        or FaultConfig()
    monitor = monitor if monitor is not None \
        else getattr(engine, "monitor", None)
    policy = retry_policy_from_config(cfg)
    counters = _Counters()

    own_agent = agent is None
    if own_agent:
        from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
        agent = DSElasticAgent(getattr(engine._config, "_param_dict", {}),
                               checkpoint_dir=checkpoint_dir)
    agent.start()

    watchdog = None
    if cfg.enabled and cfg.heartbeat_timeout_secs > 0:
        watchdog = HeartbeatWatchdog(cfg.heartbeat_timeout_secs).start()

    last_saved_step = [-1]

    def _save(tag=None):
        # no outer retry_call here: the engine's fault-enabled save
        # already retries its write stage with this same policy, and two
        # stacked layers compound to (retries+1)^2 attempts against a
        # genuinely down filesystem
        engine.save_checkpoint(checkpoint_dir, tag=tag,
                               client_state=client_state)
        counters.saves += 1
        last_saved_step[0] = engine.global_steps

    def _count_retry():
        counters.retries += 1
        _emit("Fault/retry_count", counters.retries)

    def _emit(name, value):
        if monitor is not None and getattr(monitor, "enabled", False):
            monitor.write_events([(name, value, engine.global_steps)])

    def _reload():
        """Reload the newest valid checkpoint into the live engine (the
        engine's fault-aware load verifies + walks back).  Any half-done
        accumulation window is dropped — the reloaded state is a step
        boundary."""
        engine.zero_grad()
        engine._pending = None
        retry_call(engine.load_checkpoint, checkpoint_dir,
                   on_retry=lambda a, e: _count_retry(),
                   label="load_checkpoint", **policy)
        counters.resumes += 1
        _emit("Fault/resume_events", counters.resumes)

    interval = cfg.save_interval if save_interval is None else save_interval
    status = "done"
    try:
        # resume-before-run: a restarted process picks up where the newest
        # valid checkpoint left off
        if checkpoint_dir and os.path.isdir(checkpoint_dir) \
                and _has_checkpoint(checkpoint_dir):
            start = engine.global_steps
            retry_call(engine.load_checkpoint, checkpoint_dir,
                       on_retry=lambda a, e: _count_retry(),
                       label="load_checkpoint", **policy)
            if engine.global_steps != start or start == 0:
                logger.info(f"[fault] resumed at global step "
                            f"{engine.global_steps}")
                _emit("Fault/resume_events", counters.resumes)
        steps_run = 0
        while max_steps is None or engine.global_steps < max_steps:
            try:
                if watchdog is not None:
                    watchdog.arm()
                # the injection seam sits INSIDE the recovery scope: a
                # hang/raise fired here exercises the same path a fault
                # inside step_fn would
                inject.fire("train.step_begin")
                step_fn(engine)
                steps_run += 1
            except StepHangError:
                if watchdog is not None:
                    # disarm BEFORE recovery: the emergency save + reload
                    # below can legitimately outlast the step timeout, and
                    # a watchdog firing mid-recovery would escape the
                    # supervisor entirely
                    watchdog.disarm()
                counters.hangs += 1
                logger.error("[fault] step hang detected")
                if cfg.emergency_checkpoint_on_hang:
                    try:
                        _save(tag=f"hang_step{engine.global_steps}")
                    except Exception as e:
                        logger.error(f"[fault] emergency checkpoint after "
                                     f"hang failed: {e}")
                if counters.resumes >= cfg.max_resumes:
                    status = "failed"
                    break
                _reload()
                continue
            except TRANSIENT_IO_ERRORS as e:
                if not is_transient(e):
                    # FileNotFoundError/PermissionError etc. are BUGS —
                    # reload-and-retry would re-run the identical failing
                    # step max_resumes times and mask the real problem
                    raise
                if watchdog is not None:
                    watchdog.disarm()   # recovery runs outside the window
                logger.error(f"[fault] step fault: {type(e).__name__}: {e}")
                if counters.resumes >= cfg.max_resumes:
                    status = "failed"
                    break
                _reload()
                continue
            finally:
                if watchdog is not None:
                    watchdog.disarm()
            if agent.checkpoint_if_preempted(engine):
                status = "preempted"
                break
            if interval and engine.global_steps % interval == 0:
                _save()
        if status == "done" and save_final and steps_run \
                and last_saved_step[0] != engine.global_steps:
            _save()
    finally:
        if watchdog is not None:
            watchdog.stop()
        if own_agent:
            agent.stop()
        _emit("Fault/resume_events", counters.resumes)
        _emit("Fault/retry_count", counters.retries)
    info = {"steps": engine.global_steps, "resumes": counters.resumes,
            "retries": counters.retries, "hangs": counters.hangs,
            "saves": counters.saves}
    logger.info(f"[fault] run_resilient: {status} {info}")
    return status, info


def _has_checkpoint(checkpoint_dir):
    from deepspeed_tpu.runtime.fault.manifest import list_tags
    return os.path.exists(os.path.join(checkpoint_dir, "latest")) \
        or bool(list_tags(checkpoint_dir))
