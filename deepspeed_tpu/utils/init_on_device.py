"""``OnDevice`` — materialization-free model init, reference
``deepspeed/utils/init_on_device.py`` (``OnDevice`` meta-tensor context).

The reference monkey-patches tensor constructors to build torch modules on
the ``meta`` device.  JAX has this natively: ``jax.eval_shape`` traces init
without allocating.  The context keeps the reference's API shape and adds
the TPU-idiomatic ``abstract_init`` helper.
"""

import contextlib

import jax
import jax.numpy as jnp


class OnDevice(contextlib.AbstractContextManager):
    """``with OnDevice(dtype=jnp.bfloat16, device="meta"): ...``

    Inside the context, ``abstract_init(model, *args)`` returns the abstract
    (shape/dtype-only) parameter pytree; with ``device`` set to a real jax
    device, init is jitted and placed there directly.
    """

    _current = None

    def __init__(self, dtype=None, device="meta", enabled=True):
        self.dtype = dtype
        self.device = device
        self.enabled = enabled
        self._prev = None

    def __enter__(self):
        self._prev = OnDevice._current
        if self.enabled:
            OnDevice._current = self
        return self

    def __exit__(self, *exc):
        OnDevice._current = self._prev
        return False

    def _cast(self, tree):
        if self.dtype is None:
            return tree
        return jax.tree.map(
            lambda l: (l if not jnp.issubdtype(l.dtype, jnp.floating) else
                       (jax.ShapeDtypeStruct(l.shape, self.dtype)
                        if isinstance(l, jax.ShapeDtypeStruct)
                        else l.astype(self.dtype))), tree)

    def abstract_init(self, init_fn, *args, **kwargs):
        if self.device == "meta":
            return self._cast(jax.eval_shape(init_fn, *args, **kwargs))
        out = jax.jit(init_fn)(*args, **kwargs)
        out = self._cast(out)
        if self.device is not None:
            out = jax.device_put(out, self.device)
        return out


def abstract_init(init_fn, *args, **kwargs):
    """Module-level convenience honoring an active ``OnDevice`` context."""
    ctx = OnDevice._current or OnDevice()
    return ctx.abstract_init(init_fn, *args, **kwargs)
