"""Elasticity tests — analog of reference ``tests/unit/elasticity/``."""

import pytest

from deepspeed_tpu.elasticity import (compute_elastic_config,
                                      ElasticityConfigError,
                                      ElasticityIncompatibleWorldSize)

BASE = {
    "elasticity": {
        "enabled": True,
        "max_train_batch_size": 10000,
        "micro_batch_sizes": [8, 12, 16, 17],
        "min_gpus": 32,
        "max_gpus": 1500,
        "min_time": 20,
        "version": 0.1,
    }
}


def test_basic_10k():
    batch, valid = compute_elastic_config(BASE)
    assert batch <= 10000
    assert len(valid) > 1
    for w in valid:
        assert any(batch % (mb * w) == 0
                   for mb in BASE["elasticity"]["micro_batch_sizes"])


def test_global_batch_invariant_across_worlds():
    cfg = dict(BASE)
    b1, valid = compute_elastic_config(cfg)
    for w in valid[:5]:
        b2, _, mb = compute_elastic_config(cfg, world_size=w, return_microbatch=True)
        assert b2 == b1
        gas = b1 // (mb * w)
        assert mb * gas * w == b1


def test_disabled_raises():
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config({"elasticity": {"enabled": False}})


def test_incompatible_world_raises():
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 4,
                          "micro_batch_sizes": [4], "min_gpus": 1,
                          "max_gpus": 4, "version": 0.1}}
    with pytest.raises(ElasticityIncompatibleWorldSize):
        compute_elastic_config(cfg, world_size=3)


def test_v02_node_granularity():
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 1024,
                          "micro_batch_sizes": [4, 8], "min_gpus": 4,
                          "max_gpus": 64, "version": 0.2,
                          "num_gpus_per_node": 4}}
    batch, valid = compute_elastic_config(cfg)
    assert all(w % 4 == 0 for w in valid)
