"""Fault-tolerance subsystem — crash-atomic checkpoints, fault injection,
auto-resume supervision.

The paper's reference stack survives preemption through elastic agents and
tiered (Nebula) checkpointing; on preemptible TPU slices recovery is the
difference between a production system and a demo (CheckFreq, FAST '21;
Gemini, SOSP '23).  This package provides the pieces and the proof:

* :mod:`~deepspeed_tpu.runtime.fault.manifest` — the crash-atomic
  checkpoint protocol: write into ``<tag>.tmp/``, emit a ``MANIFEST.json``
  (per-file sizes + checksums, jax/topology fingerprint, step metadata),
  fsync, atomically rename to ``<tag>/``, atomically swap ``latest``.
* :mod:`~deepspeed_tpu.runtime.fault.inject` — named deterministic fault
  injection points so tests can kill the run at every seam.
* :mod:`~deepspeed_tpu.runtime.fault.retry` — bounded retry with
  exponential backoff + jitter for transient I/O.
* :mod:`~deepspeed_tpu.runtime.fault.supervisor` — ``run_resilient``:
  heartbeat watchdog, reload-latest-valid-then-continue, elastic config
  recompute, integrated with ``DSElasticAgent``.

All knobs live in the ``fault`` config block (:class:`FaultConfig`),
default off = seed behavior.  See ``docs/fault_tolerance.md``.
"""

from deepspeed_tpu.runtime.fault.config import FaultConfig  # noqa: F401
from deepspeed_tpu.runtime.fault.inject import (  # noqa: F401
    InjectedFault, fire, configure_injection, reset_injection,
    injection_points)
from deepspeed_tpu.runtime.fault.manifest import (  # noqa: F401
    MANIFEST_NAME, CheckpointCorrupt, build_manifest, write_manifest,
    verify_manifest, read_manifest, list_tags, newest_valid_tag,
    gc_checkpoints)
from deepspeed_tpu.runtime.fault.retry import retry_call, TRANSIENT_IO_ERRORS  # noqa: F401
from deepspeed_tpu.runtime.fault.supervisor import (  # noqa: F401
    run_resilient, StepHangError, elastic_resume_config)
