from deepspeed_tpu.ops.adagrad.cpu_adagrad import DeepSpeedCPUAdagrad  # noqa: F401
