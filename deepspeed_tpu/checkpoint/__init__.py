"""Offline checkpoint tools: inspection, universal (topology-free)
conversion, TP shard surgery.  Reference: ``deepspeed/checkpoint/``."""

from deepspeed_tpu.checkpoint.deepspeed_checkpoint import (  # noqa: F401
    DeepSpeedCheckpoint, ZeROCheckpoint)
from deepspeed_tpu.checkpoint.universal_checkpoint import (  # noqa: F401
    convert_to_universal, load_hp_checkpoint_state, load_universal_meta,
    load_universal_into_engine)
from deepspeed_tpu.checkpoint.reshape_utils import (  # noqa: F401
    merge_tp_shards, split_tp_shards, reshape_tp, reshape_flat_state_dict,
    infer_tp_dim, partition_data)
