"""Jaxpr-level checks over the registered hot-path entry points.

Where the AST rules see SOURCE, these checks see what the COMPILER sees:

* **no host callbacks** — the traced program must contain no
  ``pure_callback`` / ``io_callback`` / ``debug_callback`` / host-transfer
  primitives; any of those stalls the per-step pipeline on the host link.
* **donations alias** — an entry point that declares buffer donation must
  actually get the aliasing (a dtype/layout mismatch silently keeps both
  copies live and re-opens the OOM the donation was added to close); we
  assert the lowered module carries ``tf.aliasing_output`` and that
  compilation emits no "donated buffers were not usable" warning.

Runs under ``JAX_PLATFORMS=cpu`` in tier-1 via ``tests/unit/test_tpu_lint.py``.
"""

import dataclasses
import warnings
from typing import List

import jax

FORBIDDEN_PRIMITIVES = {
    "pure_callback", "io_callback", "debug_callback", "python_callback",
    "outside_call", "host_callback", "infeed", "outfeed",
}

_DONATION_WARNING = "donated buffers were not usable"
# donation shows up as an input-output pairing fixed at lowering time
# (tf.aliasing_output) or as a donor XLA pairs during compilation
# (jax.buffer_donor) — either means the buffer is actually given up
_ALIAS_ATTRS = ("tf.aliasing_output", "jax.buffer_donor")


@dataclasses.dataclass
class CheckResult:
    name: str
    ok: bool
    problems: List[str]


def _walk_primitives(jaxpr, out):
    # ClosedJaxpr params expose ``.jaxpr``; remat2 and pallas_call carry
    # a RAW Jaxpr (``.eqns`` only) — both shapes must recurse or the
    # callback gate goes blind inside rematerialized attention bodies
    for eqn in jaxpr.eqns:
        out.add(eqn.primitive.name)
        for v in eqn.params.values():
            sub = getattr(v, "jaxpr", None)
            if sub is None and hasattr(v, "eqns"):
                sub = v
            if sub is not None:
                _walk_primitives(sub, out)
            elif isinstance(v, (list, tuple)):
                for item in v:
                    sub = getattr(item, "jaxpr", None)
                    if sub is None and hasattr(item, "eqns"):
                        sub = item
                    if sub is not None:
                        _walk_primitives(sub, out)


def primitives_of(fn, *args):
    closed = jax.make_jaxpr(fn)(*args)
    prims = set()
    _walk_primitives(closed.jaxpr, prims)
    return prims


def check_entry_point(ep):
    """Run both checks over one :class:`entry_points.EntryPoint`."""
    problems = []
    prims = primitives_of(ep.fn, *ep.args)
    bad = sorted(prims & FORBIDDEN_PRIMITIVES)
    if bad:
        problems.append(f"host callback primitive(s) in traced program: "
                        f"{', '.join(bad)}")
    # the unusable-donation warning fires at LOWERING time (compile() is
    # silent), so both stages run inside the capture
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        lowered = ep.fn.lower(*ep.args)
        lowered.compile()
    text = lowered.as_text()
    if ep.expect_donation and not any(a in text for a in _ALIAS_ATTRS):
        problems.append("entry point declares no usable buffer donation "
                        f"(none of {_ALIAS_ATTRS} in lowered module)")
    min_aliased = getattr(ep, "min_aliased", 0)
    if min_aliased:
        # consumed-donation programs: the unusable warning is expected for
        # the consumed inputs — require the STATE buffers' aliasing count
        n = sum(text.count(a) for a in _ALIAS_ATTRS)
        if n < min_aliased:
            problems.append(f"only {n} donated buffers alias an output "
                            f"(state requires >= {min_aliased})")
    else:
        unusable = [str(w.message) for w in caught
                    if _DONATION_WARNING in str(w.message)]
        if unusable:
            problems.append(f"declared donation does not alias: "
                            f"{unusable[0]}")
    return CheckResult(ep.name, not problems, problems)


def run_all():
    from deepspeed_tpu.tools.lint.entry_points import iter_entry_points
    return [check_entry_point(ep) for ep in iter_entry_points()]


def main():
    results = run_all()
    ok = True
    for r in results:
        status = "OK " if r.ok else "FAIL"
        print(f"[{status}] {r.name}")
        for p in r.problems:
            print(f"       - {p}")
        ok = ok and r.ok
    return 0 if ok else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
