"""Tests: FP16_Optimizer wrappers, MoE mappings/utils, runtime utils, nvtx,
mpu interop (analogs of reference tests/unit/runtime/half_precision/
test_fp16.py, moe utils coverage, utils)."""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from simple_model import SimpleModel, random_batch


# ------------------------------------------------------------------ #
# FP16_Optimizer
# ------------------------------------------------------------------ #
def _quadratic_setup(optimizer_cls):
    from deepspeed_tpu.runtime.optimizers import build_optimizer
    from deepspeed_tpu.runtime.config import OptimizerConfig
    inner = build_optimizer(OptimizerConfig(type="Adam",
                                            params={"lr": 1e-1}))
    params = {"w": jnp.asarray([2.0, -3.0, 1.0])}
    opt = optimizer_cls(inner, params=params, clip_grad=1.0)
    return opt, params


@pytest.mark.parametrize("cls_name", ["FP16_Optimizer", "FP16_UnfusedOptimizer"])
def test_fp16_optimizer_converges(cls_name):
    from deepspeed_tpu.runtime.fp16.fused_optimizer import FP16_Optimizer
    from deepspeed_tpu.runtime.fp16.unfused_optimizer import FP16_UnfusedOptimizer
    cls = {"FP16_Optimizer": FP16_Optimizer,
           "FP16_UnfusedOptimizer": FP16_UnfusedOptimizer}[cls_name]
    opt, params = _quadratic_setup(cls)
    loss_fn = lambda p: jnp.sum(p["w"] ** 2)
    for _ in range(50):
        masters = opt.fp32_groups_flat
        scaled_grads = jax.grad(lambda p: opt.scale_loss(loss_fn(p)))(masters)
        opt.backward(scaled_grads)
        overflow = opt.step()
        assert not overflow
    assert float(loss_fn(opt.fp32_groups_flat)) < 0.1


def test_fp16_optimizer_overflow_skips_and_rescales():
    from deepspeed_tpu.runtime.fp16.fused_optimizer import FP16_Optimizer
    opt, params = _quadratic_setup(FP16_Optimizer)
    before = np.asarray(jax.device_get(opt.fp32_groups_flat["w"]))
    scale0 = opt.cur_scale
    opt.backward({"w": jnp.asarray([jnp.inf, 0.0, 0.0])})
    overflow = opt.step()
    assert overflow
    # params untouched, scale not raised (hysteresis may defer the drop)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(opt.fp32_groups_flat["w"])), before)
    assert opt.cur_scale <= scale0
    # state dict round-trip
    sd = opt.state_dict()
    opt.load_state_dict(sd)
    assert opt.step_count == sd["step"]


# ------------------------------------------------------------------ #
# MoE mappings / utils
# ------------------------------------------------------------------ #
def test_moe_gather_drop_tokens_roundtrip(eight_devices):
    from jax.sharding import Mesh, PartitionSpec as P
    from deepspeed_tpu.utils.jax_compat import shard_map
    from deepspeed_tpu.moe.mappings import drop_tokens, gather_tokens
    mesh = Mesh(np.asarray(eight_devices).reshape(8), ("tp",))
    x = jnp.arange(32.0).reshape(8, 4)  # [tokens, dim] split over tp

    @functools.partial(shard_map, mesh=mesh, in_specs=(P("tp"),),
                       out_specs=P("tp"), check_vma=False)
    def gd(xs):
        full = gather_tokens(xs, "tp", 0)       # every rank: all 32 rows
        return drop_tokens(full, "tp", 0)       # back to this rank's rows

    np.testing.assert_array_equal(np.asarray(gd(x)), np.asarray(x))

    # gradient flows: d/dx of sum(gather(x)) == ones (drop is gather's vjp)
    @functools.partial(shard_map, mesh=mesh, in_specs=(P("tp"),),
                       out_specs=P("tp"), check_vma=False)
    def g(xs):
        return jax.grad(lambda y: gather_tokens(y, "tp", 0).sum())(xs)

    np.testing.assert_array_equal(np.asarray(g(x)), np.ones((8, 4)))


def test_moe_param_split():
    from deepspeed_tpu.moe.utils import (
        has_moe_layers, is_moe_param,
        split_params_grads_into_shared_and_expert_params,
        split_params_into_different_moe_groups_for_optimizer)
    params = {"dense": {"kernel": jnp.ones((2, 2))},
              "experts": {"0": {"kernel": jnp.ones((2, 2)) * 2}}}
    assert has_moe_layers(params)
    assert is_moe_param("experts/0/kernel")
    assert not is_moe_param("dense/kernel")
    dense_mask, expert_mask = \
        split_params_into_different_moe_groups_for_optimizer(params)
    assert dense_mask["dense"]["kernel"] is True
    assert expert_mask["experts"]["0"]["kernel"] is True
    shared, expert = split_params_grads_into_shared_and_expert_params(params)
    assert float(shared["experts"]["0"]["kernel"].sum()) == 0.0
    assert float(expert["dense"]["kernel"].sum()) == 0.0
    assert float(expert["experts"]["0"]["kernel"].sum()) == 8.0


# ------------------------------------------------------------------ #
# runtime utils
# ------------------------------------------------------------------ #
def test_grad_norm_and_clip():
    from deepspeed_tpu.runtime.utils import (CheckOverflow, clip_grad_norm_,
                                             get_global_norm, get_grad_norm)
    grads = {"a": jnp.asarray([3.0, 4.0]), "b": jnp.zeros(2)}
    assert float(get_grad_norm(grads)) == pytest.approx(5.0)
    clipped, norm = clip_grad_norm_(grads, max_norm=1.0)
    assert float(norm) == pytest.approx(5.0)
    assert float(get_grad_norm(clipped)) == pytest.approx(1.0, rel=1e-3)
    assert float(get_global_norm([3.0, 4.0])) == pytest.approx(5.0)
    assert not bool(CheckOverflow.has_overflow(grads))
    assert bool(CheckOverflow.has_overflow({"a": jnp.asarray([jnp.nan])}))


def test_partition_helpers():
    from deepspeed_tpu.runtime.utils import (PartitionedTensor,
                                             partition_balanced,
                                             partition_uniform)
    assert partition_uniform(10, 3) == [0, 4, 7, 10]
    bounds = partition_balanced([1, 1, 1, 10, 1, 1], 2)
    assert bounds[0] == 0 and bounds[-1] == 6
    assert bounds[1] in (3, 4)  # heavy item isolates
    t = jnp.arange(10.0).reshape(2, 5)
    pt = PartitionedTensor(t, num_parts=4)
    assert len(pt.parts) == 4
    np.testing.assert_array_equal(np.asarray(pt.full()), np.asarray(t))


def test_nvtx_and_memory():
    from deepspeed_tpu.runtime.utils import see_memory_usage
    from deepspeed_tpu.utils.nvtx import instrument_w_nvtx, range_pop, range_push

    @instrument_w_nvtx
    def f(x):
        return x + 1

    assert f(1) == 2
    range_push("region")
    range_pop()
    see_memory_usage("test", force=True)  # must not raise


def test_mpu_interop():
    class FakeMPU:
        def get_model_parallel_world_size(self):
            return 2

        def get_pipe_parallel_world_size(self):
            return 1

    engine, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), mpu=FakeMPU(),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    assert engine.topology.get_model_parallel_world_size() == 2
    loss = engine(random_batch(batch_size=8))
    assert np.isfinite(float(jax.device_get(loss)))

# ------------------------------------------------------------------ #
# BF16_Optimizer
# ------------------------------------------------------------------ #
def test_bf16_optimizer_converges_and_shards():
    """BF16_Optimizer (reference ``runtime/bf16_optimizer.py:30``): unit
    scale, fp32 grad accumulation, masters sharded ZeRO-1-style over dp."""
    from deepspeed_tpu.runtime.bf16_optimizer import BF16_Optimizer
    from deepspeed_tpu.parallel.topology import (initialize_topology,
                                                 reset_topology)
    reset_topology()
    topo = initialize_topology(dp=8)
    try:
        opt, params = _quadratic_setup(BF16_Optimizer)
        loss_fn = lambda p: jnp.sum(p["w"].astype(jnp.float32) ** 2)
        for _ in range(50):
            grads = jax.grad(loss_fn)(opt.fp32_groups_flat)
            opt.backward(grads)
            assert opt.step() is False
        assert float(loss_fn(opt.fp32_groups_flat)) < 0.1
        assert opt.cur_scale == 1.0

        # masters sharded over dp when divisible (ZeRO-1 partitioning)
        big = {"w": jnp.zeros((16, 4))}
        opt2 = BF16_Optimizer(opt.optimizer, params=big)
        sh = opt2.fp32_groups_flat["w"].sharding
        assert not sh.is_fully_replicated, sh

        # GAS: two backward() calls accumulate
        opt3, _ = _quadratic_setup(BF16_Optimizer)
        g = {"w": jnp.asarray([1.0, 1.0, 1.0])}
        opt3.backward(g)
        opt3.backward(g)
        acc = np.asarray(opt3._accum_grads["w"])
        np.testing.assert_allclose(acc, [2.0, 2.0, 2.0])

        # state-dict round trip
        sd = opt3.state_dict()
        opt4, _ = _quadratic_setup(BF16_Optimizer)
        opt4.load_state_dict(sd)
        assert opt4.step_count == opt3.step_count
    finally:
        reset_topology()
