"""Block-sparse attention — the long-context workhorse.

TPU-native equivalent of the reference's Triton block-sparse SDD/DSD matmul +
sparse softmax (``deepspeed/ops/sparse_attention/{matmul.py,softmax.py}``,
``csrc/sparse_attention/utils.cpp``) behind ``SparseSelfAttention``
(``sparse_self_attention.py:12``).  Two execution paths:

* **Gather path (default backward, and CPU/XLA fallback)** — for each query
  block, gather its (static) active KV blocks with ``jnp.take`` and run
  attention on the packed ``[bq, A·bk]`` slab.  Pure jnp: differentiable by
  autodiff, fused by XLA, and the FLOPs/memory scale with the layout density
  (A = max active blocks per row), not S².
* **Pallas path (forward)** — a flash-style online-softmax kernel whose grid
  walks only active KV blocks via scalar-prefetched index tables
  (``PrefetchScalarGridSpec``), the splash-attention technique: the layout
  becomes a compile-time-shaped `[H, nq, A]` table, masked per-row by a
  count table.

The custom-vjp wrapper runs the Pallas forward and recomputes the backward
through the gather path — O(S·A·bk) residency, no S×S tensors anywhere.

Layouts come from ``sparsity_config.py`` as ``[num_layout_heads, nb, nb]``
numpy arrays (static at trace time).
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.transformer.flash_attention import (
    _interpret, pallas_supported)

NEG_INF = -1e30


def layout_tables(layout):
    """Compress a [H, nb, nb] 0/1 layout into per-row index tables.

    Returns (idx, counts): idx [H, nb, A] int32 — the active kv-block
    indices per query block-row, padded with 0; counts [H, nb] int32.
    A = max active blocks over all rows/heads (static).
    """
    layout = np.asarray(layout)
    H, nb, _ = layout.shape
    counts = layout.sum(-1).astype(np.int32)            # [H, nb]
    A = max(1, int(counts.max()))
    idx = np.zeros((H, nb, A), np.int32)
    for h in range(H):
        for r in range(nb):
            cols = np.nonzero(layout[h, r])[0]
            idx[h, r, :len(cols)] = cols
    return idx, counts


def _expand_heads(layout, num_heads):
    layout = np.asarray(layout)
    if layout.shape[0] == 1 and num_heads > 1:
        layout = np.broadcast_to(layout, (num_heads,) + layout.shape[1:])
    assert layout.shape[0] == num_heads, \
        f"layout heads {layout.shape[0]} != attention heads {num_heads}"
    return layout


# --------------------------------------------------------------------- #
# Gather path (jnp; differentiable)
# --------------------------------------------------------------------- #
def _sparse_attn_gather(q, k, v, idx, counts, scale, causal, block):
    """q,k,v: [B, H, S, D]; idx [H, nq, A]; counts [H, nq]."""
    B, H, S, D = q.shape
    nb = S // block
    A = idx.shape[-1]
    qb = q.reshape(B, H, nb, block, D)
    kb = k.reshape(B, H, nb, block, D)
    vb = v.reshape(B, H, nb, block, D)
    idx_j = jnp.asarray(idx)
    # gather active kv blocks per (head, q-row): vmap over heads
    take = jax.vmap(lambda kb_h, idx_h: jnp.take(kb_h, idx_h, axis=1),
                    in_axes=(1, 0), out_axes=1)
    k_sel = take(kb, idx_j)        # [B, H, nq, A, bk, D]
    v_sel = take(vb, idx_j)
    scores = jnp.einsum("bhqid,bhqajd->bhqiaj", qb.astype(jnp.float32),
                        k_sel.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * scale
    # mask: inactive slots + causal element mask
    a_ids = jax.lax.broadcasted_iota(jnp.int32, (H, nb, A), 2)
    active = a_ids < jnp.asarray(counts)[:, :, None]     # [H, nq, A]
    mask = active[None, :, :, None, :, None]
    if causal:
        qpos = (jnp.arange(nb)[:, None] * block
                + jnp.arange(block)[None, :])            # [nq, bq]
        kvpos = (idx_j[..., None] * block
                 + jnp.arange(block)[None, None, None, :])  # [H, nq, A, bk]
        cmask = (kvpos[:, :, None, :, :]                  # [H,nq,1,A,bk]
                 <= qpos[None, :, :, None, None])         # -> [H,nq,bq,A,bk]
        mask = jnp.logical_and(mask, cmask[None])
    scores = jnp.where(mask, scores, NEG_INF)
    flat = scores.reshape(B, H, nb, block, A * block)
    m = jnp.max(flat, axis=-1, keepdims=True)
    e = jnp.exp(flat - m)
    # rows with no active kv at all produce 0 output, not NaN
    denom = jnp.sum(e, axis=-1, keepdims=True)
    probs = e / jnp.maximum(denom, 1e-30)
    probs = probs.reshape(B, H, nb, block, A, block)
    out = jnp.einsum("bhqiaj,bhqajd->bhqid", probs,
                     v_sel.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.reshape(B, H, S, D).astype(q.dtype)


# --------------------------------------------------------------------- #
# Pallas path (forward)
# --------------------------------------------------------------------- #
def _sparse_fwd_kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, o_ref,
                       m_scr, l_scr, acc_scr, *, scale, block, causal, H, A):
    bh = pl.program_id(0)
    iq = pl.program_id(1)
    a = pl.program_id(2)
    h = bh % H

    @pl.when(a == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = a < cnt_ref[h, iq]

    @pl.when(run)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)              # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)              # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            ik = idx_ref[h, iq, a]
            qpos = iq * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            kvpos = ik * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            s = jnp.where(kvpos <= qpos, s, NEG_INF)
        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_scr[:, 0:1] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(a == A - 1)
    def _finish():
        l = l_scr[:, 0:1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        # zero rows (no active kv) emit 0
        o_ref[0, 0] = jnp.where(
            l > 0.0, acc_scr[:] / safe_l, 0.0).astype(o_ref.dtype)


def _sparse_fwd_pallas(q, k, v, idx, counts, scale, causal, block):
    B, H, S, D = q.shape
    Dv = v.shape[-1]
    nq = S // block
    A = idx.shape[-1]
    grid = (B * H, nq, A)

    def q_map(bh, iq, a, idx_ref, cnt_ref):
        return (bh // H, bh % H, iq, 0)

    def kv_map(bh, iq, a, idx_ref, cnt_ref):
        # walk only this row's active kv blocks, via the prefetched table
        return (bh // H, bh % H, idx_ref[bh % H, iq, a], 0)

    kernel = functools.partial(_sparse_fwd_kernel, scale=scale, block=block,
                               causal=causal, H=H, A=A)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block, D), q_map),
            pl.BlockSpec((1, 1, block, D), kv_map),
            pl.BlockSpec((1, 1, block, Dv), kv_map),
        ],
        out_specs=pl.BlockSpec((1, 1, block, Dv), q_map),
        scratch_shapes=[
            pltpu.VMEM((block, 128), jnp.float32),
            pltpu.VMEM((block, 128), jnp.float32),
            pltpu.VMEM((block, Dv), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, S, Dv), q.dtype),
        interpret=_interpret(),
    )(jnp.asarray(idx), jnp.asarray(counts), q, k, v)


# --------------------------------------------------------------------- #
# Public entry
# --------------------------------------------------------------------- #
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _sparse_attention_core(q, k, v, idx_t, cnt_t, scale, causal, block):
    idx, counts = np.asarray(idx_t), np.asarray(cnt_t)
    if pallas_supported():
        return _sparse_fwd_pallas(q, k, v, idx, counts, scale, causal, block)
    return _sparse_attn_gather(q, k, v, idx, counts, scale, causal, block)


def _core_fwd(q, k, v, idx_t, cnt_t, scale, causal, block):
    return (_sparse_attention_core(q, k, v, idx_t, cnt_t, scale, causal, block),
            (q, k, v))


def _core_bwd(idx_t, cnt_t, scale, causal, block, res, g):
    q, k, v = res
    idx, counts = np.asarray(idx_t), np.asarray(cnt_t)
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _sparse_attn_gather(q_, k_, v_, idx, counts,
                                               scale, causal, block),
        q, k, v)
    return vjp(g)


_sparse_attention_core.defvjp(_core_fwd, _core_bwd)


def cached_layout(sparsity_config, seq_len, causal=False):
    """Per-config-instance layout cache (the analog of the reference's
    per-seq_len master_layout cache in ``SparseSelfAttention``).  Caching is
    essential for stateful-RNG configs (Variable/BigBird draw random blocks):
    without it every retrace would sample a *different* layout.  When
    ``causal``, strictly-upper blocks are dropped up front so they never
    count into the kernel's A (max-active-blocks) dimension."""
    cache = getattr(sparsity_config, "_layout_cache", None)
    if cache is None:
        cache = {}
        sparsity_config._layout_cache = cache
    key = (seq_len, causal)
    if key not in cache:
        lay = np.asarray(sparsity_config.make_layout(seq_len))
        if causal:
            lay = np.tril(lay)
        cache[key] = lay
    return cache[key]


def block_sparse_attention(q, k, v, layout, block, scale=None, causal=False,
                           key_padding_mask=None):
    """Block-sparse attention over a static layout.

    Args:
      q, k, v: [B, S, H, D] (model-native layout, matching flash_attention).
      layout: [num_layout_heads, nb, nb] 0/1 array (numpy; static).
      block: block size in tokens; S must be divisible.
      causal: additionally mask within diagonal blocks.
      key_padding_mask: optional [B, S] (1 = attend, 0 = pad).  Folded in by
        appending a constant-1 feature to q and a 0/-1e4 bias feature to k —
        padded keys' scores go to -inf without any S×S mask tensor.
    Returns [B, S, H, D].
    """
    if scale is None:
        scale = 1.0 / float(np.sqrt(q.shape[-1]))
    if key_padding_mask is not None:
        keep = jnp.asarray(key_padding_mask).astype(bool)        # [B, S]
        big = jnp.where(keep[:, :, None, None], 0.0, -1e4)
        big = jnp.broadcast_to(big, k.shape[:-1] + (1,)).astype(k.dtype)
        ones = jnp.ones(q.shape[:-1] + (1,), q.dtype)
        q = jnp.concatenate([q, ones], axis=-1)
        k = jnp.concatenate([k, big], axis=-1)
    B, S, H, D = q.shape
    assert S % block == 0, f"seq {S} not divisible by block {block}"
    layout = _expand_heads(layout, H)
    assert layout.shape[1] == S // block, \
        f"layout built for {layout.shape[1]} blocks, seq has {S // block}"
    if causal:
        layout = np.tril(layout)  # upper blocks are fully masked anyway
    idx, counts = layout_tables(layout)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    # tables ride as hashable static aux via tuples (trace-time constants)
    out = _sparse_attention_core(qt, kt, vt,
                                 _Hashable(idx), _Hashable(counts),
                                 float(scale), bool(causal), int(block))
    return out.transpose(0, 2, 1, 3)


class _Hashable:
    """Wrap a numpy array as a hashable static argument for custom_vjp."""

    def __init__(self, arr):
        self.arr = np.asarray(arr)

    def __hash__(self):
        return hash(self.arr.tobytes())

    def __eq__(self, other):
        return isinstance(other, _Hashable) and \
            np.array_equal(self.arr, other.arr)

    def __array__(self, dtype=None):
        return self.arr if dtype is None else self.arr.astype(dtype)


def sparse_attention_reference(q, k, v, layout, block, scale=None,
                               causal=False):
    """Dense O(S²) reference with the layout as an explicit mask — for tests
    (the analog of the reference's torch reference in
    ``tests/unit/ops/sparse_attention/test_sparse_attention.py``)."""
    B, S, H, D = q.shape
    layout = _expand_heads(layout, H)
    if scale is None:
        scale = 1.0 / float(np.sqrt(D))
    mask = np.kron(layout, np.ones((block, block)))      # [H, S, S]
    if causal:
        mask = np.tril(np.ones((S, S)))[None] * mask
    qt = q.transpose(0, 2, 1, 3).astype(jnp.float32)
    kt = k.transpose(0, 2, 1, 3).astype(jnp.float32)
    vt = v.transpose(0, 2, 1, 3).astype(jnp.float32)
    s = jnp.einsum("bhid,bhjd->bhij", qt, kt) * scale
    s = jnp.where(jnp.asarray(mask[None]) > 0, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    denom = jnp.maximum(jnp.sum(e, axis=-1, keepdims=True), 1e-30)
    out = jnp.einsum("bhij,bhjd->bhid", e / denom, vt)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)
