"""Sequence-parallel attention tests: ulysses + ring vs the dense reference
on the 8-device CPU mesh (beyond-reference feature; SURVEY §5 notes v0.9.3
has no Ulysses/ring — TPU-native superset)."""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.models.transformer import reference_attention
from deepspeed_tpu.parallel.sequence import shard_map_attention


def _qkv(B=2, S=64, H=8, D=16, seed=0):
    r = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(r.standard_normal((B, S, H, D)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("impl", ["ulysses", "ring"])
@pytest.mark.parametrize("causal", [True, False])
def test_seq_parallel_matches_dense(eight_devices, impl, causal):
    mesh = Mesh(np.asarray(eight_devices), ("sp",))
    q, k, v = _qkv()
    want = np.asarray(reference_attention(q, k, v, causal=causal))
    fn = shard_map_attention(mesh, impl=impl, causal=causal)
    sharded = NamedSharding(mesh, P(None, "sp"))
    qs, ks, vs = (jax.device_put(x, sharded) for x in (q, k, v))
    got = np.asarray(jax.jit(fn)(qs, ks, vs))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("impl", ["ulysses", "ring"])
def test_seq_parallel_grads_match_dense(eight_devices, impl):
    mesh = Mesh(np.asarray(eight_devices), ("sp",))
    q, k, v = _qkv(B=1, S=32, H=8, D=8, seed=1)
    fn = shard_map_attention(mesh, impl=impl, causal=True)

    def loss_sp(q, k, v):
        return (fn(q, k, v).astype(jnp.float32) ** 2).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True)
                .astype(jnp.float32) ** 2).sum()

    g_sp = jax.jit(jax.grad(loss_sp, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_sp, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3)


def test_ring_attention_skips_future_blocks(eight_devices):
    """Causal ring attention of position 0 must ignore every other chunk —
    output equals local-chunk-only attention for the first query row."""
    mesh = Mesh(np.asarray(eight_devices), ("sp",))
    q, k, v = _qkv(B=1, S=64, H=4, D=8, seed=2)
    fn = shard_map_attention(mesh, impl="ring", causal=True)
    out = np.asarray(jax.jit(fn)(q, k, v))
    # row 0 attends only to position 0 → output == v[0]
    np.testing.assert_allclose(out[0, 0], np.asarray(v)[0, 0], rtol=1e-4,
                               atol=1e-4)


@pytest.mark.parametrize("impl", ["ulysses", "ring"])
def test_model_trains_with_sequence_parallel(impl):
    """End-to-end: a Transformer with sequence_parallel_impl set trains over
    a live sp axis through the engine's fused step."""
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import Transformer, TransformerConfig
    cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=2,
                            num_heads=8, max_seq_len=32, dtype="float32",
                            sequence_parallel_impl=impl,
                            use_flash_attention=False, remat=False)
    engine, *_ = deepspeed_tpu.initialize(
        model=Transformer(cfg),
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "sequence_parallel": {"sp_size": 4}})
    assert engine.topology.get_sequence_parallel_world_size() == 4
    rng = np.random.default_rng(0)
    losses = []
    for i in range(6):
        ids = rng.integers(0, 64, (2, 32)).astype(np.int32)
        loss = engine.train_batch(batch={"input_ids": ids[None]})
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0], losses


def test_ring_attention_long_context(eight_devices):
    """Long-context: seq 4096 over 8 sp shards — each device only ever holds
    a 512-token KV block; numerics still match dense attention."""
    mesh = Mesh(np.asarray(eight_devices), ("sp",))
    q, k, v = _qkv(B=1, S=4096, H=2, D=8, seed=7)
    fn = shard_map_attention(mesh, impl="ring", causal=True)
    sharded = NamedSharding(mesh, P(None, "sp"))
    qs, ks, vs = (jax.device_put(x, sharded) for x in (q, k, v))
    got = np.asarray(jax.jit(fn)(qs, ks, vs))
    want = np.asarray(reference_attention(q, k, v, causal=True))
    np.testing.assert_allclose(got, want, rtol=3e-3, atol=3e-3)


def test_sequence_parallel_unknown_impl():
    from deepspeed_tpu.parallel.sequence import sequence_parallel_attention
    with pytest.raises(ValueError):
        sequence_parallel_attention(None, None, None, impl="bogus")
