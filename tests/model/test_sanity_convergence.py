"""Model-tier convergence sanity checks — the analog of reference
``tests/model/Megatron_GPT2/run_sanity_check.py`` (+ BingBertSquad): train a
REAL (small) GPT through the full production stack to an absolute loss
threshold with a fixed seed, prove determinism, and prove checkpoint-resume
preserves the trajectory.

Unlike the unit tier (a few steps, "loss decreased"), this tier demands
actual convergence on a learnable language task and runs the composition a
user would: 4-layer GPT2-style trunk, fused engine step, ZeRO sharding on
the 8-device CPU mesh, bf16 + TP variants.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import jax

import deepspeed_tpu
from deepspeed_tpu.models.transformer import Transformer, TransformerConfig

VOCAB = 96
SEQ = 64
SEED = 1234


def gpt_cfg(**over):
    """4-layer GPT2-style decoder (gelu MLP, learned positions, pre-LN)."""
    base = dict(vocab_size=VOCAB, hidden_size=128, num_layers=4, num_heads=4,
                max_seq_len=SEQ, activation="gelu",
                position_embedding="learned", dtype="float32",
                use_flash_attention=False, remat=False, scan_layers=True)
    base.update(over)
    return TransformerConfig(**base)


def lm_batch(rng, bs=8):
    """Learnable synthetic language: each row is a random 8-token phrase
    repeated — an induction task a 4-layer GPT must drive far below the
    uniform baseline ln(96) ~ 4.56."""
    phrase = rng.integers(2, VOCAB, (bs, 8)).astype(np.int32)
    ids = np.tile(phrase, (1, SEQ // 8))
    return {"input_ids": ids}


def make_engine(config_over=None, cfg_over=None, seed=SEED):
    config = {
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
        "zero_optimization": {"stage": 3},
        "gradient_clipping": 1.0,
        "seed": seed,
    }
    config.update(config_over or {})
    engine, *_ = deepspeed_tpu.initialize(
        model=Transformer(gpt_cfg(**(cfg_over or {}))), config=config)
    return engine


def run(engine, steps, rng):
    losses = []
    for _ in range(steps):
        loss = engine(lm_batch(rng))
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return losses


def test_gpt4l_converges_to_threshold():
    """Fixed seed, absolute target: the induction task must reach loss
    < 1.0 (uniform baseline ~4.56, init ~ln V) within 200 steps."""
    engine = make_engine()
    losses = run(engine, 200, np.random.default_rng(SEED))
    assert losses[0] > 3.0, f"suspicious init loss {losses[0]}"
    assert min(losses[-10:]) < 1.0, \
        f"no convergence: first={losses[0]:.3f} last10={losses[-10:]}"


def test_convergence_is_deterministic():
    """Two fresh runs with the same seed produce the SAME trajectory —
    the jit-determinism guarantee standing in for the reference's
    race-detection tier (SURVEY §5)."""
    a = run(make_engine(), 30, np.random.default_rng(SEED))
    from deepspeed_tpu.parallel.topology import reset_topology
    reset_topology()
    b = run(make_engine(), 30, np.random.default_rng(SEED))
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)


def test_resume_preserves_trajectory(tmp_path):
    """Checkpoint mid-training, resume in a FRESH engine: the resumed
    trajectory matches an uninterrupted run step-for-step (same data
    stream, same fold_in(step) rng), and training converges."""
    from deepspeed_tpu.parallel.topology import reset_topology

    data = np.random.default_rng(SEED)
    ref_engine = make_engine()
    ref = run(ref_engine, 80, data)

    reset_topology()
    data = np.random.default_rng(SEED)
    e1 = make_engine()
    run(e1, 40, data)
    e1.save_checkpoint(str(tmp_path))

    reset_topology()
    e2 = make_engine()
    e2.load_checkpoint(str(tmp_path))
    assert e2.global_steps == 40
    resumed = run(e2, 40, data)
    np.testing.assert_allclose(resumed, ref[40:], rtol=1e-4, atol=1e-5)
    assert min(resumed[-10:]) < 1.5


@pytest.mark.parametrize("variant", ["bf16_zero1", "tp2_zero3"])
def test_convergence_across_parallel_variants(variant):
    """The same task converges under the bf16 and TP compositions."""
    if variant == "bf16_zero1":
        engine = make_engine({"bf16": {"enabled": True},
                              "zero_optimization": {"stage": 1}})
        threshold = 1.3          # bf16 rounding slows the tail slightly
    else:
        engine = make_engine({"tensor_parallel": {"tp_size": 2}})
        threshold = 1.0
    losses = run(engine, 200, np.random.default_rng(SEED))
    assert min(losses[-10:]) < threshold, \
        f"{variant}: last10={losses[-10:]}"


LEAN_PARITY_STEPS = 300


def _run_lean_variant(lean, steps=LEAN_PARITY_STEPS):
    from deepspeed_tpu.parallel.topology import reset_topology
    reset_topology()
    opt_params = {"lr": 3e-3}
    if lean:
        opt_params["state_dtype"] = "bfloat16"
    engine = make_engine({
        "bf16": {"enabled": True, "master_weights_in_bf16": lean},
        "optimizer": {"type": "Adam", "params": opt_params},
        "zero_optimization": {"stage": 3},
    })
    return run(engine, steps, np.random.default_rng(SEED))


def test_lean_optimizer_states_convergence_parity():
    """The memory-lean optimizer variant the OPT-1.3B headline bench runs
    (``bf16.master_weights_in_bf16`` + Adam ``state_dtype: bfloat16`` —
    a documented deviation from the reference's fp32-master semantics,
    ``runtime/bf16_optimizer.py:87-165``) must CONVERGE like fp32 masters:
    same task, same seed, a few hundred steps, final losses within
    tolerance and no divergence anywhere in the lean trajectory.

    Runs in a SUBPROCESS: after the tier's earlier engines, XLA:CPU
    intermittently aborts (C++ CHECK, not an OOM) executing yet another
    600-step pair of compiled programs in the same process; isolation
    keeps the guard reliable and the trajectory clean-room."""
    import os
    import subprocess
    import sys
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(here))
    code = (
        "import os, sys;"
        f"sys.path.insert(0, {repo!r});"
        f"sys.path.insert(0, {here!r});"
        "os.environ['XLA_FLAGS'] = "
        "'--xla_force_host_platform_device_count=8';"
        "os.environ['DSTPU_ACCELERATOR'] = 'cpu';"
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        "import test_sanity_convergence as m; m._lean_parity_main()")
    result = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=900, cwd=repo)
    assert result.returncode == 0, \
        f"lean-parity worker failed\nstdout:\n{result.stdout[-3000:]}" \
        f"\nstderr:\n{result.stderr[-3000:]}"
    assert "LEAN_PARITY_OK" in result.stdout


def _lean_parity_main():
    fp32_masters = _run_lean_variant(lean=False)
    lean = _run_lean_variant(lean=True)
    assert np.isfinite(lean).all(), "lean-mode diverged (non-finite loss)"
    # both reach the converged regime...
    assert min(fp32_masters[-20:]) < 1.3, fp32_masters[-20:]
    assert min(lean[-20:]) < 1.3, \
        f"lean mode failed to converge: last20={lean[-20:]}"
    # ...and the lean tail tracks the fp32-master tail closely
    tail_fp32 = float(np.mean(fp32_masters[-20:]))
    tail_lean = float(np.mean(lean[-20:]))
    assert abs(tail_lean - tail_fp32) < 0.35, \
        f"lean tail {tail_lean:.3f} vs fp32 tail {tail_fp32:.3f}"
    # the lean trajectory never blows up mid-run relative to its own floor
    assert max(lean[LEAN_PARITY_STEPS // 2:]) < 3.0, \
        max(lean[LEAN_PARITY_STEPS // 2:])
    print(f"LEAN_PARITY_OK fp32_tail={tail_fp32:.4f} "
          f"lean_tail={tail_lean:.4f}")


