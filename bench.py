"""Benchmark suite: the reference's headline workloads on the local chip(s).

Mirrors DeepSpeed-Chat's numbers (``BASELINE.json`` / ``BASELINE.md``):

1. **North star** — step-1 SFT of OPT-1.3B with ZeRO-3, target >=35% MFU.
   A single v5e chip (16 GB) cannot hold fp32 master+moments for 1.3B
   params (12 bytes/param = 15.8 GB), and this environment's tunneled
   device makes host offload throughput-meaningless, so the 1.3B run uses
   the documented memory-lean mode (bf16 master weights + bf16 Adam
   moments, fp32 optimizer arithmetic — ``bf16.master_weights_in_bf16`` +
   optimizer ``state_dtype``).  Headline metric.
2. **Regression guard** — OPT-350M SFT with full fp32 master/moments
   (reference-exact semantics), the round-1 38%-MFU config.
3. **Generation** — the DS-Chat generation phase (prompt 256 + gen 256,
   ``blogs/deepspeed-chat/README.md:57``) through ``InferenceEngine``'s
   jitted prefill+decode program; reports decode tokens/s/chip.

Prints ONE JSON line: headline fields from (1), the others nested.
``BENCH_MODEL``/``BENCH_*`` env vars run a single custom training bench
instead (old behavior).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def _setup_compile_cache():
    """Persistent XLA compile cache: the six-phase suite is
    compile-dominated through the tunneled remote-compile service (~100 s
    per unrolled decode program); warm reruns cut wall time by well over
    half."""
    import jax
    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".jax_bench_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)


_setup_compile_cache()


def _sync_scalar(x):
    """Dependent-sync fence (see deepspeed_tpu.utils.sync)."""
    from deepspeed_tpu.utils.sync import dependent_sync_scalar
    return dependent_sync_scalar(x)


def train_bench(model_name, *, micro_bs, zero_stage, steps, seq=2048,
                lean=False, remat=False, remat_policy="dots_and_attn_saveable",
                scan_layers=False, fused_qkv=False, loss_chunks=8):
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.opt import opt_config
    from deepspeed_tpu.models.transformer import Transformer
    from deepspeed_tpu.profiling.flops_profiler.profiler import device_peak_tflops

    cfg = opt_config(model_name, max_seq_len=seq, dtype="bfloat16",
                     remat=remat, remat_policy=remat_policy,
                     scan_layers=scan_layers, fused_qkv=fused_qkv,
                     loss_seq_chunks=loss_chunks)
    model = Transformer(cfg)
    opt_params = {"lr": 9.65e-6, "weight_decay": 0.0}
    if lean:
        opt_params["state_dtype"] = "bfloat16"
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": micro_bs,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "AdamW", "params": opt_params},
            "bf16": {"enabled": True, "master_weights_in_bf16": bool(lean)},
            "zero_optimization": {"stage": zero_stage},
            "gradient_clipping": 1.0,
        })

    rng = np.random.default_rng(0)
    n_dev = jax.device_count()
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size,
        (1, micro_bs * engine.topology.dp, seq)).astype(np.int32)}

    loss = engine.train_batch(batch=batch)
    loss = engine.train_batch(batch=batch)
    _sync_scalar(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch=batch)
    final_loss = _sync_scalar(loss)
    dt = (time.perf_counter() - t0) / steps

    tokens_per_step = micro_bs * engine.topology.dp * seq
    n_params = cfg.num_params()
    peak = device_peak_tflops() * 1e12 * n_dev
    mfu = 6.0 * n_params * tokens_per_step / dt / peak if peak else 0.0
    return {
        "model": model_name,
        "tokens_per_sec_chip": round(tokens_per_step / dt / n_dev, 1),
        "mfu": round(mfu, 4),
        "step_time_s": round(dt, 4),
        "loss": round(final_loss, 4),
        "seq": seq,
        "micro_bs": micro_bs,
        "zero_stage": zero_stage,
        "lean_optimizer_states": bool(lean),
    }


def decode_bench(model_name="opt-1.3b", *, batch_size=16, prompt=256,
                 gen=256, int8=False, kv_int8=False, mxu_int8=False):
    """DS-Chat generation-phase workload (prompt 256 + gen 256) through the
    jitted prefill+decode program (reference Hybrid Engine `generate`,
    ``blogs/deepspeed-chat/README.md:265``).  ``int8=True`` runs the
    per-channel INT8-at-rest weight path (reference
    ``runtime/weight_quantizer.py``); layers are unrolled
    (``scan_layers=False``) — scanning the trunk dynamic-slices a relayout
    copy of each layer's qkv weights per token.

    ``hbm_utilization`` is estimated traffic / peak bandwidth: weight bytes
    once per decode step plus the KV blocks the Pallas decode kernel
    actually DMAs (live blocks only, at its block_k granularity)."""
    import jax
    from deepspeed_tpu.models.opt import opt_config
    from deepspeed_tpu.models.transformer import Transformer
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.ops.transformer.decode_attention import \
        DEFAULT_BLOCK_K_DECODE
    from deepspeed_tpu.profiling.flops_profiler.profiler import \
        device_peak_hbm_gbps

    cfg = opt_config(model_name, max_seq_len=prompt + gen, dtype="bfloat16",
                     scan_layers=False, kv_cache_quant=kv_int8,
                     decode_int8_matmuls=mxu_int8)
    model = Transformer(cfg)
    quant = {"enabled": True, "bits": 8, "per_channel": True} if int8 else {}
    eng = InferenceEngine(model, DeepSpeedInferenceConfig(
        dtype="bfloat16", quant=quant))
    eng.init_params()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch_size, prompt)).astype(np.int32)

    def timed(n_new):
        out = eng.generate(ids, max_new_tokens=n_new)   # compile + warm
        _sync_scalar(out[:, -1])
        t0 = time.perf_counter()
        out = eng.generate(ids, max_new_tokens=n_new)
        _sync_scalar(out[:, -1])
        return time.perf_counter() - t0

    # two run lengths isolate the pure-decode rate from the shared prefill
    dt_full, dt_half = timed(gen), timed(gen // 2)
    if dt_full > dt_half:
        decode_rate = round(batch_size * (gen - gen // 2)
                            / (dt_full - dt_half) / jax.device_count(), 1)
        # estimated HBM traffic per decode step: all params once + the live
        # KV blocks (the kernel skips blocks past the cache's live region)
        param_bytes = sum(int(np.prod(l.shape)) * l.dtype.itemsize
                          for l in jax.tree.leaves(eng.params))
        bk = min(DEFAULT_BLOCK_K_DECODE, prompt + gen)
        steps = np.arange(gen // 2, gen)        # the measured decode steps
        live_blocks = np.ceil((prompt + steps + 1) / bk)
        # bytes per cached position: bf16 payload, or int8 + f32 scale/head
        kv_row = cfg.kv_heads * cfg.head_dim * (1 if kv_int8 else 2) \
            + (cfg.kv_heads * 4 if kv_int8 else 0)
        cache_bytes = 2 * cfg.num_layers * batch_size * kv_row * bk \
            * float(np.mean(live_blocks))
        step_t = (dt_full - dt_half) / (gen - gen // 2)
        # per-chip traffic: params are replicated at tp=1, so EVERY chip
        # streams the full param_bytes per step; only the batch's KV cache
        # spreads across chips (dp-sharded)
        hbm_util = (param_bytes + cache_bytes / jax.device_count()) \
            / step_t / (device_peak_hbm_gbps() * 1e9)
    else:
        decode_rate = None      # timing inversion: measurement invalid
        hbm_util = None
    return {
        "model": model_name,
        "weights": "int8-per-channel" if int8 else "bf16",
        "kv_cache": "int8" if kv_int8 else "bf16",
        "decode_tokens_per_sec_chip": decode_rate,
        "e2e_tokens_per_sec_chip": round(batch_size * gen / dt_full
                                         / jax.device_count(), 1),
        "hbm_utilization": round(hbm_util, 3) if hbm_util else None,
        "batch_size": batch_size,
        "prompt_len": prompt,
        "gen_len": gen,
        "e2e_time_s": round(dt_full, 3),
    }


def long_context_bench(model_name="opt-1.3b", *, seq=8192, micro_bs=1,
                       steps=4):
    """Long-context SFT through the Pallas flash-attention path (the
    reference's long-sequence story rides its sparse/flash attention kernels,
    ``csrc/sparse_attention`` + ``ops/sparse_attention/``, SURVEY §5) — at
    the flagship OPT-1.3B scale.  ``flash_only_saveable`` remat keeps only
    the O(S) attention residuals (r3 sweep: 29.7% MFU vs 25.9% full
    recompute; dots-saveable OOMs at this length).  Reports tokens/s and an
    attention-aware MFU: at seq 8k the causal attention FLOPs (~6·L·S·H per
    token) rival the 6·N·tokens parameter FLOPs that the standard MFU
    formula counts."""
    from deepspeed_tpu.models.opt import opt_config
    from deepspeed_tpu.profiling.flops_profiler.profiler import \
        device_peak_tflops
    import jax
    r = train_bench(model_name, micro_bs=micro_bs, zero_stage=3, steps=steps,
                    seq=seq, lean=True, remat=True,
                    remat_policy="flash_only_saveable", loss_chunks=32)
    cfg = opt_config(model_name, max_seq_len=seq)
    attn_flops_per_tok = 6.0 * cfg.num_layers * seq * cfg.hidden_size
    total_per_tok = 6.0 * cfg.num_params() + attn_flops_per_tok
    peak = device_peak_tflops() * 1e12
    r["mfu_attn_aware"] = round(
        r["tokens_per_sec_chip"] * total_per_tok / peak, 4)
    return r


def hybrid_bench(model_name="opt-1.3b", *, train_bs=2, rollout_bs=8,
                 prompt=256, gen=128, seq=2048, cycles=2, train_steps=4):
    """DS-Chat step-3 RLHF loop at OPT-1.3B scale through the Hybrid Engine
    (reference ``runtime/hybrid_engine.py:32``; headline rows in
    ``blogs/deepspeed-chat/README.md:38,52``): N ZeRO-3 train steps → rollout
    ``generate`` through the shared-weight inference view → training resumes
    on the same engine.  Reports rollout throughput, train step time before
    and after a rollout (the engine-flip cost the reference's blog headlines)
    and a weight-identity check between the master params and the inference
    view."""
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.opt import opt_config
    from deepspeed_tpu.models.transformer import Transformer

    # remat OFF, like the north-star phase: even with the decode program
    # resident, lean states leave room for full activations at bs2
    # (r3 probe: 0.364 s/step vs 0.393 with remat)
    cfg = opt_config(model_name, max_seq_len=seq, dtype="bfloat16",
                     remat=False, scan_layers=False, loss_seq_chunks=8)
    model = Transformer(cfg)
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": train_bs,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 9.65e-6, "weight_decay": 0.0,
                                     "state_dtype": "bfloat16"}},
            "bf16": {"enabled": True, "master_weights_in_bf16": True},
            "zero_optimization": {"stage": 3},
            "gradient_clipping": 1.0,
            "hybrid_engine": {"enabled": True},
        })
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size,
        (1, train_bs * engine.topology.dp, seq)).astype(np.int32)}
    prompts = rng.integers(0, cfg.vocab_size,
                           (rollout_bs, prompt)).astype(np.int32)

    # warm both compiled programs (train step + rollout decode)
    _sync_scalar(engine.train_batch(batch=batch))
    out = engine.generate(prompts, max_new_tokens=gen)
    _sync_scalar(out[:, -1])

    def timed_train(n):
        t0 = time.perf_counter()
        for _ in range(n):
            loss = engine.train_batch(batch=batch)
        _sync_scalar(loss)
        return (time.perf_counter() - t0) / n

    train_before = timed_train(train_steps)
    rollout_times = []
    train_after = None
    for _ in range(cycles):
        t0 = time.perf_counter()
        out = engine.generate(prompts, max_new_tokens=gen, do_sample=True,
                              temperature=1.0, top_p=0.9)
        _sync_scalar(out[:, -1])
        rollout_times.append(time.perf_counter() - t0)
        train_after = timed_train(train_steps)

    # weight identity: the inference view IS the (cast) master weights —
    # rollouts see every optimizer step with no copy drift.  Compared
    # on-device (HBM is near-full with both programs resident).
    import jax.numpy as jnp
    check = jax.jit(lambda a, b: jnp.all(jnp.isclose(
        a.astype(jnp.float32), b.astype(jnp.float32), rtol=8e-3, atol=8e-3)))
    masters = jax.tree.leaves(engine._params)
    views = jax.tree.leaves(engine._inference_view())
    small = int(np.argmin([int(np.prod(l.shape)) for l in masters]))
    identical = bool(jax.device_get(check(masters[small], views[small])))
    rollout_t = min(rollout_times)
    return {
        "model": model_name,
        "zero_stage": 3,
        "train_step_s_before_rollout": round(train_before, 4),
        "train_step_s_after_rollout": round(train_after, 4),
        "rollout_tokens_per_sec_chip": round(
            rollout_bs * gen / rollout_t / jax.device_count(), 1),
        "rollout_bs": rollout_bs,
        "prompt_len": prompt,
        "gen_len": gen,
        "rollout_time_s": round(rollout_t, 3),
        "weights_shared_identical": identical,
        "cycles": cycles,
    }


def custom_single_bench():
    """Env-driven single training bench (BENCH_MODEL etc.) — the round-1
    interface, kept for sweeps."""
    result = train_bench(
        os.environ.get("BENCH_MODEL", "opt-350m"),
        micro_bs=int(os.environ.get("BENCH_BS", "4")),
        zero_stage=int(os.environ.get("BENCH_ZERO", "1")),
        steps=int(os.environ.get("BENCH_STEPS", "10")),
        seq=int(os.environ.get("BENCH_SEQ", "2048")),
        lean=os.environ.get("BENCH_LEAN", "0") == "1",
        remat=os.environ.get("BENCH_REMAT", "0") == "1",
        remat_policy=os.environ.get("BENCH_REMAT_POLICY",
                                    "dots_and_attn_saveable"),
        scan_layers=os.environ.get("BENCH_SCAN", "0") == "1",
        fused_qkv=os.environ.get("BENCH_FQ", "0") == "1",
        loss_chunks=int(os.environ.get("BENCH_LOSS_CHUNKS", "8")))
    import jax
    print(json.dumps({
        "metric": f"{result['model']}-sft-tokens/sec/chip"
                  f"(seq{result['seq']},bs{result['micro_bs']},"
                  f"zero{result['zero_stage']},{jax.devices()[0].platform})",
        "value": result["tokens_per_sec_chip"],
        "unit": "tokens/s/chip",
        "vs_baseline": round(result["mfu"] / 0.35, 4),
        **result,
    }))


def _phase_cleanup():
    """Free the previous phase's device arrays: drop compiled-executable
    caches (their closures pin param/opt buffers) and force collection."""
    import gc
    import jax
    from deepspeed_tpu.parallel.topology import reset_topology
    reset_topology()
    jax.clear_caches()
    gc.collect()


def main():
    import jax
    platform = jax.devices()[0].platform

    if os.environ.get("BENCH_MODEL"):
        custom_single_bench()
        return

    steps = int(os.environ.get("BENCH_STEPS", "8"))
    # (1) north star: OPT-1.3B ZeRO-3 training (memory-lean states; see
    # module docstring for why fp32 states cannot fit one 16 GB chip).
    # remat OFF: the lean states leave room for full activations at bs2,
    # worth ~2 MFU points (r3 sweep: 48.8% vs 46.9% with remat)
    north = train_bench("opt-1.3b", micro_bs=2, zero_stage=3, steps=steps,
                        lean=True, remat=False)
    _phase_cleanup()
    # (2) regression guard: OPT-350M, reference-exact fp32 master/moments
    guard = train_bench("opt-350m", micro_bs=4, zero_stage=1, steps=steps)
    _phase_cleanup()
    # (3) DS-Chat generation phase: bf16 weights + per-channel INT8-at-rest
    dec = decode_bench("opt-1.3b")
    _phase_cleanup()
    dec_int8 = decode_bench("opt-1.3b", int8=True)
    _phase_cleanup()
    # (3b) int8 KV cache on top of int8 weights at the DS-Chat shape
    dec_int8_kv = decode_bench("opt-1.3b", int8=True, kv_int8=True)
    _phase_cleanup()
    # (3c) throughput-oriented serving point: at bs64 the KV stream
    # dominates decode traffic, so the int8 cache is worth ~17% more
    # (decode_int8_matmuls measured NEUTRAL-to-slower here — the q/p
    # quantize work offsets the cast savings; kept opt-in only)
    dec_int8_kv_bs64 = decode_bench("opt-1.3b", int8=True, kv_int8=True,
                                    batch_size=64, gen=128)
    _phase_cleanup()
    # (4) DS-Chat step-3 RLHF loop through the Hybrid Engine
    hybrid = hybrid_bench("opt-1.3b")
    _phase_cleanup()
    # (5) long-context SFT (flash attention at seq 8k, flagship scale)
    long_ctx = long_context_bench("opt-1.3b")

    result = {
        "metric": "opt-1.3b-sft-tokens/sec/chip(seq2048,bs2,zero3,"
                  "bf16-lean-opt-states," + platform + ")",
        "value": north["tokens_per_sec_chip"],
        "unit": "tokens/s/chip",
        # north star: >=35% MFU on the OPT-1.3B ZeRO-3 SFT workload
        "vs_baseline": round(north["mfu"] / 0.35, 4),
        "mfu": north["mfu"],
        "step_time_s": north["step_time_s"],
        "loss": north["loss"],
        "n_devices": jax.device_count(),
        # honesty: on one chip the zero/dp mesh axes are size-1, so the
        # zero3 label shards nothing here — real ZeRO-3 collectives are
        # exercised on the virtual multi-device mesh (tests + driver dryrun)
        "sharding_note": ("single-chip: zero/dp axes size-1 (nominal); "
                          "multi-device sharding covered by dryrun_multichip"
                          if jax.device_count() == 1 else None),
        "sft_350m_guard": guard,
        "generation": dec,
        "generation_int8": dec_int8,
        "generation_int8_kv": dec_int8_kv,
        "generation_int8_kv_bs64": dec_int8_kv_bs64,
        "hybrid_rlhf": hybrid,
        "long_context": long_ctx,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    # the tunneled remote-compile service occasionally drops a request on
    # the first cold compile; one retry rides the now-warm cache
    try:
        main()
    except Exception:
        import traceback
        traceback.print_exc()
        print("bench: transient failure, retrying once", file=sys.stderr)
        main()
