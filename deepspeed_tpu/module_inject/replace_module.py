"""HF-model conversion front-end — the TPU analog of reference
``module_inject/replace_module.py:282 replace_transformer_layer``.

The reference walks an HF torch module tree and swaps each transformer layer
for a fused-CUDA module, slicing weights across TP ranks in the process
(``ReplaceWithTensorSlicing :31``).  Here the "replacement implementation" is
the framework's flax ``Transformer`` compiled by XLA, so conversion is
checkpoint-level, one-shot and whole-model:

    model, params = convert_hf_model(hf_model)          # torch → flax/jax
    engine = deepspeed_tpu.init_inference(hf_model, ...)  # does it for you

TP sharding afterwards is a sharding annotation over the converted names
(``runtime/zero/partition.py DEFAULT_TP_RULES`` / ``auto_tp.py``), executed
by GSPMD — no per-rank weight surgery.
"""

import re

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.module_inject.containers import ALL_POLICIES
from deepspeed_tpu.runtime.zero.partition import path_to_str
from deepspeed_tpu.utils.logging import logger

# HF buffer keys that are not parameters and never need converting.
_IGNORED_KEY_PATTERNS = (".attn.bias", ".attn.masked_bias", "rotary_emb",
                         ".attention.bias", ".attention.masked_bias")


def policy_for(hf_config):
    for policy_cls in ALL_POLICIES:
        if policy_cls.match(hf_config):
            return policy_cls()
    raise NotImplementedError(
        f"no injection policy for model_type="
        f"{getattr(hf_config, 'model_type', None)!r}; supported: "
        f"{sorted(t for p in ALL_POLICIES for t in p.model_types)}")


def _materialize(model, flat, param_dtype=None):
    """Fill the flax param tree of ``model`` from a flat {path: np.ndarray}
    dict produced by a policy (keys relative to the 'params' collection)."""
    abstract = jax.eval_shape(model.init, jax.random.key(0),
                              {"input_ids": jnp.zeros((1, 4), jnp.int32)})
    missing, used = [], set()

    def fill(path, leaf):
        name = path_to_str(path)
        rel = name[len("params/"):] if name.startswith("params/") else name
        if rel not in flat:
            missing.append(rel)
            return leaf
        arr = flat[rel]
        used.add(rel)
        if tuple(arr.shape) != tuple(leaf.shape):
            raise ValueError(f"converted {rel} has shape {arr.shape}, "
                             f"model expects {leaf.shape}")
        return jnp.asarray(arr, param_dtype or leaf.dtype)

    params = jax.tree_util.tree_map_with_path(fill, abstract)
    if missing:
        raise KeyError(f"conversion missing parameters: {missing}")
    unused = set(flat) - used
    if unused:
        logger.warning(f"conversion produced unused tensors: {sorted(unused)}")
    return params


def convert_hf_model(model_or_name, param_dtype=None, **config_overrides):
    """(HF torch model | HF name/path) → (flax Transformer, params pytree).

    ``config_overrides`` go into ``TransformerConfig`` (e.g.
    ``dtype="float32"``, ``use_flash_attention=False``, ``max_seq_len=...``);
    ``param_dtype`` overrides the stored parameter dtype."""
    if isinstance(model_or_name, str):
        from transformers import AutoModelForCausalLM
        hf_model = AutoModelForCausalLM.from_pretrained(model_or_name)
    else:
        hf_model = model_or_name
    hf_config = hf_model.config
    policy = policy_for(hf_config)
    cfg = policy.build_config(hf_config, **config_overrides)
    sd = hf_model.state_dict()
    flat = policy.convert(sd, cfg)

    consumed_hint = [k for k in sd
                     if not any(p in k for p in _IGNORED_KEY_PATTERNS)]
    logger.info(f"converted {hf_config.model_type} model: "
                f"{len(consumed_hint)} HF tensors → {len(flat)} flax tensors, "
                f"{cfg.num_layers}L/{cfg.hidden_size}H")
    model = policy.build_model(cfg)
    params = _materialize(model, flat, param_dtype=param_dtype)
    return model, params


def load_megatron_model(checkpoint, num_heads=None, megatron_v2=True,
                        param_dtype=None, **config_overrides):
    """Megatron-LM GPT checkpoint → (flax Transformer, params).

    ``checkpoint``: a DeepSpeed checkpoint-description json (path or dict,
    reference ``SDLoaderFactory.get_sd_loader_json``), a list of TP shard
    files, or an already-merged flat state dict.  TP shards are folded by
    ``MegatronSDLoader.merge_state_dict``; model dims are inferred from the
    merged tensors (heads can't be — pass ``num_heads``)."""
    import numpy as np
    from deepspeed_tpu.module_inject.containers import MegatronGPTPolicy
    from deepspeed_tpu.runtime.state_dict_factory import (get_sd_loader,
                                                          get_sd_loader_json)

    if isinstance(checkpoint, dict) and "checkpoints" not in checkpoint:
        # a dict without a "checkpoints" key must be an already-merged state
        # dict.  Real Megatron saves carry metadata siblings ('iteration',
        # 'checkpoint_version', ...) next to the tensors — keep the array
        # entries, drop the rest; reject only when nothing is an array.
        sd = {k: v for k, v in checkpoint.items() if hasattr(v, "shape")}
        if not sd:
            raise ValueError(
                "checkpoint dict is neither a checkpoint-description json "
                "(no 'checkpoints' key) nor a merged state dict (no array "
                f"values among keys: {list(checkpoint)[:5]})")
    else:
        if isinstance(checkpoint, (str, dict)):
            _, ckpt_list, version = get_sd_loader_json(checkpoint)
        else:
            ckpt_list, version = list(checkpoint), None
        if not version:               # merge must know the fused-QKV layout
            version = 2.0 if megatron_v2 else 1.0
        sd = get_sd_loader(ckpt_list, version=version).merge_state_dict()

    sd = MegatronGPTPolicy.normalize(sd)
    emb_key = "embedding.word_embeddings.weight" \
        if "embedding.word_embeddings.weight" in sd else "word_embeddings.weight"
    pos_key = emb_key.replace("word", "position")
    layer_ids = {int(m.group(1)) for k in sd
                 if (m := re.match(r"transformer\.layers\.(\d+)\.", k))}
    # MoE-GPT checkpoints (Megatron-DeepSpeed): per-expert MLPs under
    # mlp.deepspeed_moe.* on every expert_interval-th layer
    from deepspeed_tpu.module_inject.containers import MegatronGPTMoEPolicy
    num_experts, expert_interval, first_moe_layer = \
        MegatronGPTMoEPolicy.detect_moe(sd)
    dense_key = "transformer.layers.0.mlp.dense_h_to_4h.weight"
    h4h = sd[dense_key] if dense_key in sd else \
        sd["transformer.layers.0.mlp.deepspeed_moe.experts."
           "deepspeed_experts.0.dense_h_to_4h.weight"]

    class _Args:                              # megatron arg namespace
        vocab_size = np.asarray(sd[emb_key]).shape[0]
        hidden_size = np.asarray(sd[emb_key]).shape[1]
        num_layers = max(layer_ids) + 1
        num_attention_heads = num_heads
        ffn_hidden_size = np.asarray(h4h).shape[0]
        max_position_embeddings = np.asarray(sd[pos_key]).shape[0]

    _Args.num_experts = num_experts
    _Args.expert_interval = expert_interval
    _Args.first_moe_layer = first_moe_layer if num_experts else -1
    if num_heads is None:
        raise ValueError("num_heads is not recoverable from a megatron "
                         "state dict — pass num_heads=")
    policy = MegatronGPTMoEPolicy() if num_experts else MegatronGPTPolicy()
    policy.megatron_v2 = megatron_v2
    cfg = policy.build_config(_Args(), **config_overrides)
    flat = policy.convert(sd, cfg)
    model = policy.build_model(cfg)
    params = _materialize(model, flat, param_dtype=param_dtype)
    return model, params


def replace_transformer_layer(orig_layer_impl=None, model=None, config=None,
                              **kwargs):
    """Reference-parity entry (``replace_module.py:282``): converts the whole
    model (layer-granular swapping has no TPU analog — XLA compiles the full
    graph) and returns (flax_model, params)."""
    return convert_hf_model(model, **kwargs)
