"""TL006 negative fixture: stable jit signatures."""
import jax
import jax.numpy as jnp

from deepspeed_tpu.tools.lint.hotpath import hot_path


def step(params, lr, step_no):
    return params


step_jit = jax.jit(step)
# dtypes pinned: no weak-type drift against array-typed call sites
out = step_jit(jnp.ones(4), jnp.asarray(1e-3, jnp.float32),
               jnp.asarray(7, jnp.int32))


def run(x, cfg):
    return x


run_jit = jax.jit(run, static_argnames=("cfg",))
out2 = run_jit(jnp.ones(2), cfg=(4, "relu"))     # tuple static: value-hashed
out3 = run_jit(jnp.ones(2), cfg=tuple([1, 2]))   # tuple(): value-hashed


def pick(k, x):
    return x


pick_jit = jax.jit(pick, static_argnums=(0,))
out4 = pick_jit(8, jnp.ones(2))                  # scalar in a STATIC position

# positional scalar at a static_argnames position: resolved via run's
# signature (cfg is position 1), so it is static, not traced
out6 = run_jit(jnp.ones(2), 4)

# static_argnames on a callable whose signature is NOT module-local:
# traced-vs-static is undecidable per position — the scalar check stands down
ext_jit = jax.jit(jnp.round, static_argnames=("decimals",))
out7 = ext_jit(jnp.ones(2), 2)


def plain(a, b):
    return a + b


# not jitted: Python scalars are fine
out5 = plain(1, 2)


@hot_path("fixture.decode")
def decode(batch, cache):
    flags = [True, False]
    if len(flags) > 1:          # len() of a host-local list: bookkeeping
        pass
    done = batch.sum()
    if done is None:            # no shape probe in the test
        return cache
    return batch
