"""Groupwise quantization kernels.

Capability parity with the reference's CUDA quantization kernels
(``csrc/quantization/{quantize,dequantize,fake_quantizer}.cu``, bound via
``QuantizerBuilder`` ``op_builder/quantizer.py:9``): groupwise symmetric /
asymmetric INT8/INT4 quantize + dequantize + straight-through fake-quant.

TPU-first: these are pure ``jnp`` programs — XLA fuses scale computation,
rounding and packing into a couple of VPU loops, so no Pallas kernel is
warranted (memory-bound elementwise work; see pallas guide "don't hand-write
what XLA already fuses").  INT4 values are packed two-per-int8 so quantized
buffers really are 4-bit in HBM.
"""

import jax
import jax.numpy as jnp


def _grouped(x, num_groups):
    n = x.size
    assert n % num_groups == 0, f"size {n} not divisible into {num_groups} groups"
    return x.reshape(num_groups, n // num_groups)


def quantize(x, num_groups, num_bits=8, symmetric=True):
    """Groupwise quantize.  Returns (q, scale, zero_point) where q is int8
    (for 4-bit, values live in [-8,7] before packing)."""
    g = _grouped(x.astype(jnp.float32), num_groups)
    qmax = 2.0 ** (num_bits - 1) - 1.0
    if symmetric:
        absmax = jnp.max(jnp.abs(g), axis=1, keepdims=True)
        scale = jnp.where(absmax > 0, absmax / qmax, 1.0)
        q = jnp.clip(jnp.round(g / scale), -qmax - 1, qmax).astype(jnp.int8)
        zero = jnp.zeros_like(scale)
    else:
        gmin = jnp.min(g, axis=1, keepdims=True)
        gmax = jnp.max(g, axis=1, keepdims=True)
        span = jnp.maximum(gmax - gmin, 1e-8)
        scale = span / (2.0 ** num_bits - 1.0)
        zero = gmin
        q = jnp.clip(jnp.round((g - zero) / scale), 0, 2.0 ** num_bits - 1.0)
        q = (q - 2.0 ** (num_bits - 1)).astype(jnp.int8)
    return q, scale, zero


def dequantize(q, scale, zero, num_bits=8, symmetric=True, shape=None):
    g = q.astype(jnp.float32)
    if symmetric:
        out = g * scale
    else:
        out = (g + 2.0 ** (num_bits - 1)) * scale + zero
    return out.reshape(shape) if shape is not None else out


def pack_int4(q):
    """Pack int8-held 4-bit values [-8,7] two-per-byte (low nibble first)."""
    flat = q.reshape(q.shape[0], -1)
    assert flat.shape[1] % 2 == 0
    lo = (flat[:, 0::2] & 0xF).astype(jnp.uint8)
    hi = (flat[:, 1::2] & 0xF).astype(jnp.uint8)
    return lo | (hi << 4)


def unpack_int4(packed):
    lo = (packed & 0xF).astype(jnp.int8)
    hi = ((packed >> 4) & 0xF).astype(jnp.int8)
    # sign-extend nibbles
    lo = jnp.where(lo > 7, lo - 16, lo)
    hi = jnp.where(hi > 7, hi - 16, hi)
    out = jnp.stack([lo, hi], axis=-1)
    return out.reshape(packed.shape[0], -1)


@jax.custom_vjp
def fake_quantize(x, num_groups, num_bits):
    q, scale, zero = quantize(x, num_groups, num_bits, symmetric=True)
    return dequantize(q, scale, zero, num_bits, shape=x.shape).astype(x.dtype)


def _fq_fwd(x, num_groups, num_bits):
    return fake_quantize(x, num_groups, num_bits), None


def _fq_bwd(_, g):
    # straight-through estimator (reference fake_quantizer.cu semantics)
    return (g, None, None)


fake_quantize.defvjp(_fq_fwd, _fq_bwd)


def quantize_ternary(x, num_groups):
    """Ternary {-a, 0, +a} per group (reference ``quantize_tenary``)."""
    g = _grouped(x.astype(jnp.float32), num_groups)
    thres = 0.7 * jnp.mean(jnp.abs(g), axis=1, keepdims=True)
    mask = (jnp.abs(g) > thres).astype(jnp.float32)
    alpha = jnp.sum(jnp.abs(g) * mask, axis=1, keepdims=True) / \
        jnp.maximum(jnp.sum(mask, axis=1, keepdims=True), 1.0)
    return jnp.sign(g) * mask * alpha


def quantize_binary(x, num_groups):
    """Binary {-a, +a} per group (reference ``quantize_binary``)."""
    g = _grouped(x.astype(jnp.float32), num_groups)
    alpha = jnp.mean(jnp.abs(g), axis=1, keepdims=True)
    return jnp.sign(g) * alpha
