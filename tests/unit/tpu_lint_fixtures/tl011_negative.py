"""TL011 negative fixture — placements at setup time, canonical axis
names, and variable axis names (out of static reach by design)."""
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from deepspeed_tpu.tools.lint.hotpath import hot_path

mesh = Mesh(jax.devices(), ("tp",))


def build_engine(params, batch):
    # placement at SETUP time is where it belongs — not a hot path
    params = jax.device_put(params, NamedSharding(mesh, P("tp")))
    batch = jax.device_put(batch, NamedSharding(mesh, P("edp")))
    return params, batch


@hot_path("fixture.clean_step")
def clean_step(params, cache, token):
    return apply(params, cache, token)


def body(x, w):
    return x @ w


# canonical topology axes, including compound specs
smap_ok = shard_map(body, mesh=mesh,
                    in_specs=(P(("edp", "ep")), P(None, "tp")),
                    out_specs=P("sp"))


def reduce_over(x, axis):
    # variable axis names resolve at runtime from the topology helpers
    y = jax.lax.psum(x, axis)
    return jax.lax.all_gather(y, axis_name=axis)


def reduce_canonical(x):
    return jax.lax.psum(x, "tp")
