"""Data-efficiency pipeline tests — analog of the reference's
``tests/unit/runtime/test_data_efficiency.py``: curriculum schedules,
curriculum data sampler, and random-LTD token routing."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.data_pipeline import (
    CurriculumScheduler, DeepSpeedDataSampler, DataAnalyzer,
    RandomLTDScheduler, random_ltd_layer, sample_kept_indices,
    gather_tokens, scatter_tokens)


# ------------------------- curriculum scheduler ------------------------- #
def _sched(stype="fixed_linear", **extra):
    cfg = {
        "min_difficulty": 8,
        "max_difficulty": 64,
        "schedule_type": stype,
        "schedule_config": extra,
    }
    return CurriculumScheduler(cfg)


def test_fixed_linear_ramps_and_quantises():
    s = _sched(total_curriculum_step=100, difficulty_step=8)
    d0 = s.update_difficulty(1)
    d50 = s.update_difficulty(50)
    d100 = s.update_difficulty(100)
    d200 = s.update_difficulty(200)
    assert d0 >= 8 and d50 > d0 and d100 == 64 and d200 == 64
    assert all(d % 8 == 0 for d in (d0, d50, d100))


def test_fixed_root_slower_than_linear_early():
    lin = _sched(total_curriculum_step=100, difficulty_step=1)
    root = _sched("fixed_root", total_curriculum_step=100, difficulty_step=1,
                  root_degree=2)
    # sqrt schedule reaches difficulty faster early on
    assert root.get_difficulty(25) >= lin.get_difficulty(25)


def test_fixed_discrete():
    s = CurriculumScheduler({
        "min_difficulty": 8, "max_difficulty": 64,
        "schedule_type": "fixed_discrete",
        "schedule_config": {"difficulty": [8, 16, 64], "max_step": [10, 20]},
    })
    assert s.get_difficulty(5) == 8
    assert s.get_difficulty(15) == 16
    assert s.get_difficulty(50) == 64


def test_custom_schedule_and_state_roundtrip():
    s = _sched("custom")
    s.set_custom_get_difficulty(lambda step: min(64, step))
    assert s.get_difficulty(30) == 30
    state = s.get_state()
    s2 = _sched("custom")
    s2.set_state(state)
    assert s2.get_current_difficulty() == s.get_current_difficulty()


# --------------------------- data sampler ------------------------------ #
def test_sampler_respects_difficulty_and_dp_shard():
    metric = np.arange(100)  # sample i has difficulty i
    sched = CurriculumScheduler({
        "min_difficulty": 16, "max_difficulty": 100,
        "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 10, "difficulty_step": 1},
    })
    samplers = [DeepSpeedDataSampler(
        sched if r == 0 else CurriculumScheduler({
            "min_difficulty": 16, "max_difficulty": 100,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 10,
                                "difficulty_step": 1}}),
        total_samples=100, micro_batch_size=2, data_parallel_rank=r,
        data_parallel_size=2, metric_values=metric) for r in range(2)]
    its = [iter(s) for s in samplers]
    b0, b1 = next(its[0]), next(its[1])
    # shards are disjoint, all samples eligible at current difficulty
    assert set(b0).isdisjoint(b1)
    diff = samplers[0].curriculum_scheduler.get_current_difficulty()
    assert all(metric[i] <= diff for i in b0 + b1)


def test_sampler_state_dict_resume():
    s = DeepSpeedDataSampler(None, total_samples=64, micro_batch_size=4,
                             data_parallel_rank=0, data_parallel_size=1)
    it = iter(s)
    next(it), next(it)
    state = s.state_dict()
    b3 = next(it)
    s2 = DeepSpeedDataSampler(None, total_samples=64, micro_batch_size=4,
                              data_parallel_rank=0, data_parallel_size=1)
    s2.load_state_dict(state)
    assert next(iter(s2)) == b3


def test_data_analyzer(tmp_path):
    data = [np.arange(i + 1) for i in range(10)]
    da = DataAnalyzer(data, metric_fn=len)
    path = str(tmp_path / "metric.npy")
    vals = da.run_and_save(path)
    np.testing.assert_array_equal(DataAnalyzer.load(path), vals)
    assert vals[3] == 4


# ---------------------------- random-LTD ------------------------------- #
def test_random_ltd_scheduler_ramp():
    s = RandomLTDScheduler({"random_ltd": {
        "total_layer_num": 12, "random_ltd_layer_num": 10,
        "random_ltd_schedule": {
            "min_value": 128, "max_value": 512,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_layer_tokens_steps": 100,
                                "seq_step": 16}},
    }})
    assert s.get_current_seq() == 128
    s.update_seq(50)
    mid = s.get_current_seq()
    assert 128 < mid < 512 and mid % 16 == 0
    s.update_seq(200)
    assert s.get_current_seq() == 512
    sd = s.state_dict()
    s.reset_to_init()
    assert s.get_current_seq() == 128
    s.load_state_dict(sd)
    assert s.get_current_seq() == 512


def test_gather_scatter_roundtrip():
    rng = jax.random.key(0)
    x = jnp.arange(2 * 8 * 4, dtype=jnp.float32).reshape(2, 8, 4)
    idx = sample_kept_indices(rng, 8, 5)
    assert idx.shape == (5,) and bool(jnp.all(idx[1:] > idx[:-1]))
    sub = gather_tokens(x, idx)
    assert sub.shape == (2, 5, 4)
    back = scatter_tokens(x, sub * 0, idx)
    # scattered positions zeroed, others untouched
    kept = set(np.asarray(idx).tolist())
    for t in range(8):
        if t in kept:
            assert float(jnp.sum(jnp.abs(back[:, t]))) == 0.0
        else:
            np.testing.assert_array_equal(back[:, t], x[:, t])


def test_random_ltd_layer_applies_to_subset_only():
    x = jnp.ones((2, 16, 4), jnp.float32)
    out = random_ltd_layer(lambda h: h + 1.0, x, jax.random.key(1), keep_len=6)
    ones = float(jnp.sum(out == 1.0)) / 4 / 2
    twos = float(jnp.sum(out == 2.0)) / 4 / 2
    assert twos == 6 and ones == 10


def test_random_ltd_layer_full_keep_is_identity_path():
    x = jnp.ones((2, 8, 4), jnp.float32)
    out = random_ltd_layer(lambda h: h * 3, x, jax.random.key(0), keep_len=8)
    np.testing.assert_allclose(out, x * 3)


def test_random_ltd_inside_jit_with_mask():
    x = jnp.ones((1, 8, 4), jnp.float32)
    mask = jnp.ones((1, 1, 8, 8), jnp.float32)

    @jax.jit
    def f(h, m, key):
        return random_ltd_layer(
            lambda s, sm: s * jnp.mean(sm), h, key, keep_len=4, mask=m)

    out = f(x, mask, jax.random.key(2))
    assert out.shape == x.shape


# ----------------------- engine curriculum wiring ---------------------- #
def test_engine_curriculum_slices_seq(eight_devices):
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import Transformer, TransformerConfig

    model = Transformer(TransformerConfig(
        vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
        max_seq_len=32, use_flash_attention=False))
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "curriculum_learning": {
            "enabled": True, "min_difficulty": 8, "max_difficulty": 32,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 4,
                                "difficulty_step": 8}},
    })
    ids = np.random.default_rng(0).integers(0, 64, (1, 16, 32))
    loss = engine.train_batch(batch={"input_ids": jnp.asarray(ids, jnp.int32)})
    assert np.isfinite(float(loss))
    assert engine.curriculum_scheduler.get_current_difficulty() <= 32
