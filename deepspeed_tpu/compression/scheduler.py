"""Compression scheduler (reference ``deepspeed/compression/scheduler.py:12``
``compression_scheduler``): tracks the training step and reports which
techniques are live, so the engine can pass the right static step into
``apply_compression`` and log activation transitions."""

from deepspeed_tpu.utils.logging import logger
from . import constants as C


class compression_scheduler:

    def __init__(self, spec, ds_config=None):
        self.spec = spec
        self.training_steps = 0
        self._announced = set()

    def check_all(self):
        """Log every technique whose schedule_offset has just been reached
        (the analog of the reference flipping ``*_enabled`` module flags)."""
        for mod, techs in self.spec.bindings.items():
            for tech, gp in techs.items():
                offset = int(gp.get(C.TECHNIQUE_SCHEDULE_OFFSET, 0))
                key = (mod, tech)
                if self.training_steps >= offset and key not in self._announced:
                    self._announced.add(key)
                    logger.info(f"compression: {tech} active on {mod} "
                                f"at step {self.training_steps}")

    def step(self, step_zero_check=False):
        if not step_zero_check:
            self.training_steps += 1
        self.check_all()

    def is_active(self, mod, tech):
        gp = self.spec.techniques(mod).get(tech)
        if gp is None:
            return False
        return self.training_steps >= int(gp.get(C.TECHNIQUE_SCHEDULE_OFFSET, 0))
