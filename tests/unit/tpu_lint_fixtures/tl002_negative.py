"""TL002 negative fixture: donated, or no large buffers."""
import jax
import functools


def apply_update(params, opt_state, grads):
    return params, opt_state


update_fn = jax.jit(apply_update, donate_argnums=(0, 1, 2))


@functools.partial(jax.jit, donate_argnames=("kv_cache",))
def prefill(params, kv_cache, chunk):
    return kv_cache


small_fn = jax.jit(lambda x, y: x + y)       # no large-buffer params
