"""MoE utilities — reference ``deepspeed/moe/utils.py``:
``has_moe_layers``, ``is_moe_param``, and
``split_params_into_different_moe_groups_for_optimizer`` (expert params get
their own optimizer group so expert grads average over expert-data-parallel
only, reference ``stage_1_and_2.py:1781``).

On TPU, expert params are identified by tree path (the sharding planner uses
the same convention, ``runtime/zero/partition.py`` EXPERT_PARAM_PATTERN), and
"groups" are path-predicate partitions of the param pytree.
"""

import re

import jax

EXPERT_PATTERN = r"(^|[/.])experts?([/._]|$)|expert_"


def is_moe_param_path(path):
    return re.search(EXPERT_PATTERN, path.lower()) is not None


def is_moe_param(path_or_leaf, path=None):
    """Reference ``is_moe_param``: torch checks ``param.allreduce is False``;
    here identity is the tree path."""
    p = path_or_leaf if isinstance(path_or_leaf, str) else path
    return p is not None and is_moe_param_path(p)


def has_moe_layers(params):
    """True if any param path looks expert-partitioned (reference checks for
    MoE modules on the torch module tree)."""
    flat = jax.tree_util.tree_leaves_with_path(params)
    return any(is_moe_param_path(_path_str(p)) for p, _ in flat)


def _path_str(path):
    parts = []
    for p in path:
        parts.append(str(getattr(p, "key", getattr(p, "idx", p))))
    return "/".join(parts)


def split_params_into_different_moe_groups_for_optimizer(params):
    """Partition a param pytree into (dense_mask, expert_mask) boolean trees
    (reference returns split torch param groups).  Masks feed optimizers
    that need per-group treatment (e.g. expert-lr or grad-averaging groups)."""
    dense = jax.tree_util.tree_map_with_path(
        lambda p, _: not is_moe_param_path(_path_str(p)), params)
    expert = jax.tree.map(lambda d: not d, dense)
    return dense, expert


def split_params_grads_into_shared_and_expert_params(grads):
    """Reference helper of the same name: zero out the complementary part of
    each split so both pytrees keep the full structure."""
    import jax.numpy as jnp
    shared = jax.tree_util.tree_map_with_path(
        lambda p, g: g if not is_moe_param_path(_path_str(p))
        else jnp.zeros_like(g), grads)
    expert = jax.tree_util.tree_map_with_path(
        lambda p, g: g if is_moe_param_path(_path_str(p))
        else jnp.zeros_like(g), grads)
    return shared, expert
