"""SPMD pipeline tests — analog of reference
``tests/unit/runtime/pipe/test_pipe.py``: the pipelined program must be
numerically identical to running the layer stack sequentially, in both value
and gradient."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel.topology import initialize_topology
from deepspeed_tpu.parallel.pipeline import (spmd_pipeline, stack_stage_params,
                                             pipeline_bubble_fraction)


def make_layers(n_layers, dim, seed=0):
    rng = np.random.default_rng(seed)
    return [{"w": jnp.asarray(rng.standard_normal((dim, dim)).astype(np.float32) / np.sqrt(dim)),
             "b": jnp.asarray(rng.standard_normal(dim).astype(np.float32) * 0.1)}
            for _ in range(n_layers)]


def layer_apply(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def sequential_reference(layers, x):
    for p in layers:
        x = layer_apply(p, x)
    return x


@pytest.mark.parametrize("n_stages,n_layers", [(2, 4), (4, 4), (4, 8)])
def test_pipeline_matches_sequential(n_stages, n_layers):
    topo = initialize_topology(pp=n_stages)
    dim, M, mb = 16, 4, 2
    layers = make_layers(n_layers, dim)
    stacked = stack_stage_params(layers, n_stages)
    per_stage = n_layers // n_stages

    def stage_fn(stage_params, x):
        def body(x, p):
            return layer_apply(p, x), None
        out, _ = jax.lax.scan(body, x, stage_params)
        return out

    rng = np.random.default_rng(1)
    x0 = jnp.asarray(rng.standard_normal((M, mb, dim)).astype(np.float32))
    ys = jax.jit(lambda sp, x: spmd_pipeline(stage_fn, sp, x, M, topo.mesh))(
        stacked, x0)
    ref = jnp.stack([sequential_reference(layers, x0[m]) for m in range(M)])
    np.testing.assert_allclose(np.asarray(ys), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_pipeline_gradients_match_sequential():
    n_stages, n_layers, dim, M, mb = 4, 4, 16, 4, 2
    topo = initialize_topology(pp=n_stages)
    layers = make_layers(n_layers, dim)
    stacked = stack_stage_params(layers, n_stages)

    def stage_fn(stage_params, x):
        def body(x, p):
            return layer_apply(p, x), None
        out, _ = jax.lax.scan(body, x, stage_params)
        return out

    rng = np.random.default_rng(1)
    x0 = jnp.asarray(rng.standard_normal((M, mb, dim)).astype(np.float32))
    tgt = jnp.asarray(rng.standard_normal((M, mb, dim)).astype(np.float32))

    def pipe_loss(sp):
        ys = spmd_pipeline(stage_fn, sp, x0, M, topo.mesh)
        return jnp.mean((ys - tgt) ** 2)

    def seq_loss(layers_flat):
        ys = jnp.stack([sequential_reference(layers_flat, x0[m]) for m in range(M)])
        return jnp.mean((ys - tgt) ** 2)

    g_pipe = jax.jit(jax.grad(pipe_loss))(stacked)
    g_seq = jax.grad(seq_loss)(layers)
    g_seq_stacked = stack_stage_params(g_seq, n_stages)
    for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_seq_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4)


def test_bubble_fraction():
    assert pipeline_bubble_fraction(8, 4) == pytest.approx(3 / 11)
    assert pipeline_bubble_fraction(1, 1) == 0.0


def test_stack_stage_params_shape():
    layers = make_layers(8, 4)
    stacked = stack_stage_params(layers, 4)
    assert stacked["w"].shape == (4, 2, 4, 4)
    with pytest.raises(ValueError):
        stack_stage_params(layers, 3)
