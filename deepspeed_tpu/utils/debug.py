"""Debug helpers (reference ``deepspeed/utils/debug.py``): name maps for
modules/params and rank-guarded printing for multi-host runs."""

import os

import numpy as np

import jax

module_names = {}
param_names = {}


def debug_extract_module_and_param_names(params, prefix=""):
    """Flatten a param pytree into {path: shape} maps (the analog of the
    reference's named_modules/named_parameters walk)."""
    global param_names
    out = {}

    def walk(node, path):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, f"{path}/{k}" if path else k)
        elif hasattr(node, "shape"):
            out[path] = tuple(node.shape)

    walk(params, prefix)
    param_names = out
    return out


def debug_param2name_id_shape(path, value):
    return f"name={path} id={id(value)} shape={tuple(np.shape(value))}"


def print_rank_0(message, debug=True, force=False):
    if (debug or force) and jax.process_index() == 0:
        print(message, flush=True)


def debug_rank0(message, debug=True):
    print_rank_0(message, debug)


def printflock(*msgs):
    """Interleave-safe print across processes (reference printflock uses an
    fcntl lock; multi-host TPU processes share no fs lock, so prefix with the
    process index instead)."""
    print(f"[proc {jax.process_index()}]", *msgs, flush=True)


def log_rank_file(rank, *msgs):
    """Per-rank debug log files (reference ``log_rank_file``)."""
    path = f"debug_rank_{rank}.txt"
    with open(path, "a") as f:
        for m in msgs:
            f.write(f"{m}\n")


def enabled():
    return os.environ.get("DSTPU_DEBUG", "0") == "1"
