"""Registered jaxpr-check entry points: the REAL hot paths, tiny-sized.

Each builder returns an :class:`EntryPoint` wrapping the jit-wrapped
callable the engine itself dispatches per step (the fused train step, the
generation/decode loop, the split-prefill chunk program) plus concrete CPU
args to trace it with, and whether the program is expected to declare buffer
donation.  Runs entirely on CPU (``JAX_PLATFORMS=cpu``) at toy shapes —
tracing and lowering exercise everything the checks need.
"""

import dataclasses
from typing import Any, Callable, Tuple

import numpy as np

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class EntryPoint:
    name: str
    fn: Callable            # jit-wrapped callable
    args: Tuple[Any, ...]
    expect_donation: bool   # program must declare (and use) buffer donation
    # minimum number of donated inputs that must actually alias an output.
    # When set, the "donated buffers were not usable" warning is tolerated —
    # for programs that deliberately donate CONSUMED inputs (e.g. grads,
    # freed for scratch reuse) the warning is expected; the count is what
    # guards the state buffers' aliasing.
    min_aliased: int = 0


def _tiny_train_engine():
    import flax.linen as nn
    import deepspeed_tpu

    class TinyModel(nn.Module):
        @nn.compact
        def __call__(self, batch):
            x, y = batch["x"], batch["y"]
            h = nn.relu(nn.Dense(16, name="l0")(x))
            logits = nn.Dense(16, name="head")(h)
            one_hot = jax.nn.one_hot(y, 16)
            return -jnp.mean(jnp.sum(
                jax.nn.log_softmax(logits) * one_hot, axis=-1))

    engine, *_ = deepspeed_tpu.initialize(
        model=TinyModel(),
        config={"train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 0}})
    return engine


def runtime_train_step():
    """The fused train step ``runtime/engine.py`` dispatches per
    ``train_batch`` (params/opt_state/scaler donated)."""
    engine = _tiny_train_engine()
    rng = np.random.default_rng(0)
    micro = {"x": jnp.asarray(rng.standard_normal((2, 16)), jnp.float32),
             "y": jnp.asarray(rng.integers(0, 16, (2,)), jnp.int32)}
    batch = jax.tree.map(lambda x: x[None], micro)     # [gas=1, ...]
    engine._lazy_init((micro,), {})
    fused = engine._get_fused_step()
    args = (engine._params, engine._opt_state, engine._scaler_state,
            jnp.asarray(1e-3, jnp.float32), jnp.asarray(1, jnp.int32),
            engine._rng, batch)
    return EntryPoint("runtime.train_step", fused, args, expect_donation=True)


def runtime_apply_update():
    """The 3-call path's optimizer step (params/opt_state/scaler/grads all
    donated; grads are CONSUMED — their donation never aliases, so the check
    demands the params+opt_state aliasing count instead of a clean warning
    log)."""
    engine = _tiny_train_engine()
    rng = np.random.default_rng(0)
    micro = {"x": jnp.asarray(rng.standard_normal((2, 16)), jnp.float32),
             "y": jnp.asarray(rng.integers(0, 16, (2,)), jnp.int32)}
    engine._lazy_init((micro,), {})
    apply = engine._get_apply()
    grads = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                         engine._params)
    args = (engine._params, engine._opt_state, engine._scaler_state, grads,
            jnp.asarray(False), jnp.asarray(1e-3, jnp.float32),
            jnp.asarray(1, jnp.int32))
    n_state = len(jax.tree.leaves((engine._params, engine._opt_state)))
    return EntryPoint("runtime.apply_update", apply, args,
                      expect_donation=True, min_aliased=n_state)


def _tiny_inference_engine(prefill_chunk=None):
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import (Transformer,
                                                  TransformerConfig)
    cfg = TransformerConfig(vocab_size=97, hidden_size=32, num_layers=2,
                            num_heads=4, max_seq_len=64,
                            use_flash_attention=False, dtype="float32")
    model = Transformer(cfg)
    config = {"dtype": "float32"}
    if prefill_chunk is not None:
        config["prefill_chunk_size"] = prefill_chunk
    engine = deepspeed_tpu.init_inference(model, config=config)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 97, (1, 8)),
                      jnp.int32)
    params = model.init(jax.random.key(0), {"input_ids": ids})
    engine.set_params(params)
    return engine


def inference_decode():
    """The generation program (prefill + decode scan) ``inference/engine.py``
    dispatches per ``generate`` — the KV cache is donated through it."""
    from deepspeed_tpu.inference.engine import required_cache_len
    engine = _tiny_inference_engine()
    B, P, T = 1, 8, 4
    ids = jnp.asarray(np.random.default_rng(1).integers(0, 97, (B, P)),
                      jnp.int32)
    fn = engine._get_generate(P, T, False, 1.0, 0, 1.0, with_mask=False,
                              prefill_chunk=None)
    cache = engine._workspace.take(B, required_cache_len(P, T, None),
                                   engine.compute_dtype)
    args = (engine._params, cache, ids, jax.random.key(0),
            jnp.asarray(-1))
    return EntryPoint("inference.decode", fn, args, expect_donation=True)


def inference_prefill_chunk():
    """The split-prefill per-chunk program (donated-cache; the round-5 OOM
    fix) — built by driving a real chunked ``generate`` and re-tracing the
    compiled chunk function."""
    engine = _tiny_inference_engine(prefill_chunk=8)
    B, P, C, T = 1, 24, 8, 2
    ids = jnp.asarray(np.random.default_rng(2).integers(0, 97, (B, P)),
                      jnp.int32)
    engine.generate(ids, max_new_tokens=T, seed=0)
    key = next(k for k in engine._compiled
               if isinstance(k, tuple) and k and k[0] == "chunkfill")
    chunk_fn = engine._compiled[key]
    cache = engine._workspace.take(B, 64, engine.compute_dtype)
    args = (engine._params, cache, ids[:, :C],
            jnp.asarray(0, jnp.int32), jnp.zeros((B,), jnp.int32))
    return EntryPoint("inference.prefill_chunk", chunk_fn, args,
                      expect_donation=True)


def serving_decode_step():
    """The serving loop's single reusable decode-step program
    (``inference/serving/slots.py``): cache AND slot-state donated — the
    whole continuous-batching design rests on this one executable updating
    the slot workspace in place with no host callbacks."""
    from deepspeed_tpu.inference.engine import build_sample_fn
    from deepspeed_tpu.inference.serving.slots import make_decode_block_fn
    engine = _tiny_inference_engine()
    N, S = 2, 32
    fn = make_decode_block_fn(engine.module,
                              build_sample_fn(False, 1.0, 0, 1.0),
                              None, 2, S)
    cache = engine.module.init_cache(N, S, dtype=engine.compute_dtype)
    state = {"token": jnp.zeros((N,), jnp.int32),
             "pos": jnp.asarray([8, 3], jnp.int32),
             "active": jnp.asarray([True, False]),
             "remaining": jnp.asarray([4, 0], jnp.int32),
             "eos": jnp.asarray([-1, -1], jnp.int32)}
    args = (engine._params, cache, state, jax.random.key(0))
    return EntryPoint("serving.decode_step", fn, args, expect_donation=True)


def serving_admission_prefill():
    """The serving admission prefill — the donated per-chunk program at
    lane width B=1, replayed for every admitted prompt (the serving
    engine holds a dedicated instance of this program; same body)."""
    engine = _tiny_inference_engine()
    C = 8
    chunk_fn = engine._make_chunk_fn()
    lane = engine.module.init_cache(1, 32, dtype=engine.compute_dtype)
    ids = jnp.asarray(np.random.default_rng(3).integers(0, 97, (1, C)),
                      jnp.int32)
    args = (engine._params, lane, ids, jnp.asarray(0, jnp.int32),
            jnp.zeros((1,), jnp.int32))
    return EntryPoint("serving.admission_prefill", chunk_fn, args,
                      expect_donation=True)


def serving_admit():
    """The fused admission program (first-token sample + lane insert +
    in-program slot-state write; slot index traced, cache AND slot state
    donated)."""
    from deepspeed_tpu.inference.engine import build_sample_fn
    from deepspeed_tpu.inference.serving.slots import make_admit_fn
    engine = _tiny_inference_engine()
    fn = make_admit_fn(build_sample_fn(False, 1.0, 0, 1.0))
    N, S = 2, 32
    cache = engine.module.init_cache(N, S, dtype=engine.compute_dtype)
    lane = engine.module.init_cache(1, S, dtype=engine.compute_dtype)
    state = {"token": jnp.zeros((N,), jnp.int32),
             "pos": jnp.zeros((N,), jnp.int32),
             "active": jnp.zeros((N,), bool),
             "remaining": jnp.zeros((N,), jnp.int32),
             "eos": jnp.full((N,), -1, jnp.int32)}
    logits = jnp.zeros((1, 1, 97), jnp.float32)
    args = (cache, state, lane, logits, jax.random.key(0),
            jnp.asarray(1, jnp.int32), jnp.asarray(8, jnp.int32),
            jnp.asarray(4, jnp.int32), jnp.asarray(-1, jnp.int32))
    return EntryPoint("serving.admit", fn, args, expect_donation=True)


def _paged_state(N):
    return {"token": jnp.zeros((N,), jnp.int32),
            "pos": jnp.asarray([8, 3], jnp.int32),
            "active": jnp.asarray([True, False]),
            "remaining": jnp.asarray([4, 0], jnp.int32),
            "eos": jnp.full((N,), -1, jnp.int32)}


def serving_decode_step_paged():
    """The PAGED decode-step program (``serving.paged``): page pool +
    slot state donated, the per-slot page tables a plain traced input —
    the pool/state donations must alias (the whole paged design rests on
    in-place pool updates) and the program must stay callback-free even
    though every cache touch routes through a gather/scatter."""
    from deepspeed_tpu.inference.engine import build_sample_fn
    from deepspeed_tpu.inference.serving.slots import \
        make_paged_decode_block_fn
    engine = _tiny_inference_engine()
    N, NP, PG = 2, 9, 8                 # 9 pages of 8 (page 0 = trash)
    fn = make_paged_decode_block_fn(engine.module,
                                    build_sample_fn(False, 1.0, 0, 1.0),
                                    None, 2, 4 * PG)
    pool = engine.module.init_paged_cache(NP, PG,
                                          dtype=engine.compute_dtype)
    pages = jnp.asarray([[3, 5, 2, 7], [1, 4, 0, 0]], jnp.int32)
    args = (engine._params, pool, _paged_state(N), pages,
            jax.random.key(0))
    return EntryPoint("serving.decode_step_paged", fn, args,
                      expect_donation=True)


def serving_admission_prefill_paged():
    """The PAGED admission-prefill chunk program: the pool is the
    donated buffer (chunk writes land in the slot's pages directly —
    no staging lane), the [1, pages_per_slot] table row a separate
    traced input so the pool donation aliases cleanly."""
    from deepspeed_tpu.inference.serving.slots import make_paged_chunk_fn
    engine = _tiny_inference_engine()
    C, NP, PG = 8, 9, 8
    chunk_fn = make_paged_chunk_fn(engine.module, None)
    pool = engine.module.init_paged_cache(NP, PG,
                                          dtype=engine.compute_dtype)
    pages = jnp.asarray([[3, 5, 2, 7]], jnp.int32)
    ids = jnp.asarray(np.random.default_rng(4).integers(0, 97, (1, C)),
                      jnp.int32)
    args = (engine._params, pool, pages, ids, jnp.asarray(0, jnp.int32),
            jnp.zeros((1,), jnp.int32))
    return EntryPoint("serving.prefill_chunk_paged", chunk_fn, args,
                      expect_donation=True)


def serving_admit_paged():
    """The PAGED admission program (first-token sample + in-program
    slot-state write; no cache argument at all — prefill already wrote
    the pages)."""
    from deepspeed_tpu.inference.engine import build_sample_fn
    from deepspeed_tpu.inference.serving.slots import make_paged_admit_fn
    fn = make_paged_admit_fn(build_sample_fn(False, 1.0, 0, 1.0))
    logits = jnp.zeros((1, 1, 97), jnp.float32)
    args = (_paged_state(2), logits, jax.random.key(0),
            jnp.asarray(1, jnp.int32), jnp.asarray(8, jnp.int32),
            jnp.asarray(4, jnp.int32), jnp.asarray(-1, jnp.int32))
    return EntryPoint("serving.admit_paged", fn, args,
                      expect_donation=True)


def serving_spec_propose():
    """The speculative draft-propose program: k+1 greedy draft steps in
    one in-program scan (the extra step is the write-only cache
    catch-up), ONLY the draft KV workspace donated — the slot state is
    read-only here (the verify program owns its donation)."""
    from deepspeed_tpu.inference.serving.slots import make_draft_propose_fn
    engine = _tiny_inference_engine()
    N, S, K = 2, 32, 2
    fn = make_draft_propose_fn(engine.module, None, K, S)
    dcache = engine.module.init_cache(N, S, dtype=engine.compute_dtype)
    args = (engine._params, dcache, _paged_state(N))
    return EntryPoint("serving.spec_propose", fn, args,
                      expect_donation=True)


def serving_spec_verify():
    """The speculative verify-and-commit program: ONE batched target
    forward over [token, drafts], in-program accept mask + per-slot
    accepted length, per-row MULTI-token scatter cache writes — target
    cache AND slot state donated, no host callbacks (the whole point is
    committing up to k+1 tokens per dispatch without a sync)."""
    from deepspeed_tpu.inference.engine import build_sample_fn
    from deepspeed_tpu.inference.serving.slots import make_spec_verify_fn
    engine = _tiny_inference_engine()
    N, S, K = 2, 32, 2
    fn = make_spec_verify_fn(engine.module,
                             build_sample_fn(False, 1.0, 0, 1.0),
                             None, K, S)
    cache = engine.module.init_cache(N, S, dtype=engine.compute_dtype)
    draft = jnp.asarray(np.random.default_rng(6).integers(0, 97, (N, K)),
                        jnp.int32)
    args = (engine._params, cache, _paged_state(N), draft,
            jax.random.key(0))
    return EntryPoint("serving.spec_verify", fn, args,
                      expect_donation=True)


def serving_spec_verify_paged():
    """The PAGED speculative verify program: pool + slot state donated,
    page tables traced; inactive lanes' window writes redirect to the
    trash page in-program, live lanes' per-row multi-token scatter
    routes through the table."""
    from deepspeed_tpu.inference.engine import build_sample_fn
    from deepspeed_tpu.inference.serving.slots import \
        make_paged_spec_verify_fn
    engine = _tiny_inference_engine()
    N, NP, PG, K = 2, 9, 8, 2
    fn = make_paged_spec_verify_fn(engine.module,
                                   build_sample_fn(False, 1.0, 0, 1.0),
                                   None, K, 4 * PG)
    pool = engine.module.init_paged_cache(NP, PG,
                                          dtype=engine.compute_dtype)
    pages = jnp.asarray([[3, 5, 2, 7], [1, 4, 0, 0]], jnp.int32)
    draft = jnp.asarray(np.random.default_rng(7).integers(0, 97, (N, K)),
                        jnp.int32)
    args = (engine._params, pool, _paged_state(N), pages, draft,
            jax.random.key(0))
    return EntryPoint("serving.spec_verify_paged", fn, args,
                      expect_donation=True)


def serving_spec_draft_prefill():
    """The draft-side admission-prefill chunk program (the draft cache
    needs the prompt's K/V too): same body as the engine chunk program
    bound to the draft module, draft lane donated."""
    from deepspeed_tpu.inference.serving.slots import make_draft_chunk_fn
    engine = _tiny_inference_engine()
    C = 8
    chunk_fn = make_draft_chunk_fn(engine.module, None)
    lane = engine.module.init_cache(1, 32, dtype=engine.compute_dtype)
    ids = jnp.asarray(np.random.default_rng(8).integers(0, 97, (1, C)),
                      jnp.int32)
    args = (engine._params, lane, ids, jnp.asarray(0, jnp.int32),
            jnp.zeros((1,), jnp.int32))
    return EntryPoint("serving.spec_draft_prefill", chunk_fn, args,
                      expect_donation=True)


def serving_spec_draft_admit():
    """The draft-side admission insert: prefilled draft lane into the
    draft cache over the traced slot index (draft cache donated); no
    sampling, no state write — the target admit owns both."""
    from deepspeed_tpu.inference.serving.slots import make_draft_admit_fn
    engine = _tiny_inference_engine()
    N, S = 2, 32
    fn = make_draft_admit_fn()
    dcache = engine.module.init_cache(N, S, dtype=engine.compute_dtype)
    lane = engine.module.init_cache(1, S, dtype=engine.compute_dtype)
    args = (dcache, lane, jnp.asarray(1, jnp.int32))
    return EntryPoint("serving.spec_draft_admit", fn, args,
                      expect_donation=True)


def hybrid_rollout():
    """The hybrid engine's rollout generation program (RLHF: decode over
    the live training weights' inference view) — same jitted body as
    ``inference.decode`` (``make_generate_fn``) but built through
    ``DeepSpeedHybridEngine._get_rollout_fn`` with the rollout view as
    params; the KV cache is donated through it."""
    import deepspeed_tpu
    from deepspeed_tpu.inference.engine import (KVCacheWorkspace,
                                                required_cache_len)
    from deepspeed_tpu.models.transformer import (Transformer,
                                                  TransformerConfig)
    cfg = TransformerConfig(vocab_size=97, hidden_size=32, num_layers=2,
                            num_heads=4, max_seq_len=64,
                            use_flash_attention=False, dtype="float32")
    engine, *_ = deepspeed_tpu.initialize(
        model=Transformer(cfg),
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 0},
                "hybrid_engine": {"enabled": True}})
    B, P, T = 1, 8, 4
    ids = jnp.asarray(np.random.default_rng(5).integers(0, 97, (B, P)),
                      jnp.int32)
    key = (P, T, False, 1.0, 0, 1.0, False, None,
           engine._rollout_early_exit)
    fn = engine._get_rollout_fn(key)
    params = engine._inference_view()
    cache = KVCacheWorkspace(engine.module).take(
        B, required_cache_len(P, T, None), engine.compute_dtype)
    args = (params, cache, ids, jax.random.key(0), jnp.asarray(-1))
    return EntryPoint("hybrid.rollout", fn, args, expect_donation=True)


BUILDERS = (runtime_train_step, runtime_apply_update, inference_decode,
            inference_prefill_chunk, serving_decode_step,
            serving_admission_prefill, serving_admit,
            serving_decode_step_paged, serving_admission_prefill_paged,
            serving_admit_paged, serving_spec_propose,
            serving_spec_verify, serving_spec_verify_paged,
            serving_spec_draft_prefill, serving_spec_draft_admit,
            hybrid_rollout)

# builder function name -> the EntryPoint name it constructs.  Lets
# name-filtered sweeps (``ds_lint --mem <program>``, the bench
# memory_snapshot subset) skip the engine builds of filtered-out
# programs instead of paying all 16 just to learn their names.  Kept
# honest mechanically: every consumer cross-checks ``ep.name`` against
# this map after building, so drift fails loudly instead of silently
# skipping the wrong program.
BUILDER_PROGRAMS = {
    "runtime_train_step": "runtime.train_step",
    "runtime_apply_update": "runtime.apply_update",
    "inference_decode": "inference.decode",
    "inference_prefill_chunk": "inference.prefill_chunk",
    "serving_decode_step": "serving.decode_step",
    "serving_admission_prefill": "serving.admission_prefill",
    "serving_admit": "serving.admit",
    "serving_decode_step_paged": "serving.decode_step_paged",
    "serving_admission_prefill_paged": "serving.prefill_chunk_paged",
    "serving_admit_paged": "serving.admit_paged",
    "serving_spec_propose": "serving.spec_propose",
    "serving_spec_verify": "serving.spec_verify",
    "serving_spec_verify_paged": "serving.spec_verify_paged",
    "serving_spec_draft_prefill": "serving.spec_draft_prefill",
    "serving_spec_draft_admit": "serving.spec_draft_admit",
    "hybrid_rollout": "hybrid.rollout",
}


def iter_entry_points():
    for build in BUILDERS:
        yield build()
