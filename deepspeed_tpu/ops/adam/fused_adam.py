"""Fused Adam/AdamW.

TPU-native equivalent of the reference's multi-tensor-apply CUDA Adam
(``csrc/adam/multi_tensor_adam.cu`` behind ``deepspeed/ops/adam/fused_adam.py:18``).
On TPU there is no separate "fused kernel" to write: the whole update below is
jitted together with gradient production into ONE XLA program, so every
moment/param update fuses into a handful of elementwise HLO loops over HBM —
the same memory-bound optimum the multi-tensor kernel achieves on GPU.

The optimizer is expressed functionally: ``init(params) -> state``,
``update(grads, state, params, lr, step) -> (new_params, new_state)`` with
``lr``/``step`` as traced scalars so LR schedules don't retrigger compilation.
"""

from typing import NamedTuple, Any

import jax
import jax.numpy as jnp


class AdamState(NamedTuple):
    exp_avg: Any       # first moment, same pytree as params
    exp_avg_sq: Any    # second moment


class FusedAdam:
    """Adam/AdamW with bias correction (reference fused_adam.py:18 semantics:
    ``adam_w_mode`` selects decoupled weight decay)."""

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 adam_w_mode=True, bias_correction=True, amsgrad=False,
                 master_dtype=jnp.float32, state_dtype=None):
        if amsgrad:
            raise ValueError("FusedAdam does not support amsgrad (parity with reference)")
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.bias_correction = bias_correction
        self.master_dtype = jnp.dtype(master_dtype)
        # moment STORAGE dtype (memory-lean option for chips whose HBM can't
        # hold 8 bytes/param of fp32 moments; arithmetic stays master_dtype).
        # Default = master_dtype → exact reference semantics.
        self.state_dtype = jnp.dtype(state_dtype) if state_dtype is not None \
            else self.master_dtype

    def init(self, params):
        zeros = lambda p: jnp.zeros(p.shape, dtype=self.state_dtype)
        return AdamState(exp_avg=jax.tree.map(zeros, params),
                         exp_avg_sq=jax.tree.map(zeros, params))

    def update(self, grads, state, params, lr=None, step=1):
        lr = self.lr if lr is None else lr
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay
        step = jnp.asarray(step, dtype=jnp.float32)
        if self.bias_correction:
            bc1 = 1.0 - b1 ** step
            bc2 = 1.0 - b2 ** step
        else:
            bc1 = bc2 = 1.0

        def leaf(p, g, m, v):
            g32 = g.astype(self.master_dtype)
            p32 = p.astype(self.master_dtype)
            if wd != 0.0 and not self.adam_w_mode:
                g32 = g32 + wd * p32
            m = b1 * m.astype(self.master_dtype) + (1.0 - b1) * g32
            v = b2 * v.astype(self.master_dtype) + (1.0 - b2) * (g32 * g32)
            denom = jnp.sqrt(v / bc2) + eps
            upd = (m / bc1) / denom
            if wd != 0.0 and self.adam_w_mode:
                upd = upd + wd * p32
            return ((p32 - lr * upd).astype(p.dtype),
                    m.astype(self.state_dtype), v.astype(self.state_dtype))

        out = jax.tree.map(leaf, params, grads, state.exp_avg, state.exp_avg_sq)
        new_params = jax.tree.map(lambda t: t[0], out, is_leaf=lambda t: isinstance(t, tuple))
        new_m = jax.tree.map(lambda t: t[1], out, is_leaf=lambda t: isinstance(t, tuple))
        new_v = jax.tree.map(lambda t: t[2], out, is_leaf=lambda t: isinstance(t, tuple))
        return new_params, AdamState(new_m, new_v)


class FusedAdamW(FusedAdam):

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.01, **kw):
        super().__init__(lr=lr, betas=betas, eps=eps, weight_decay=weight_decay,
                         adam_w_mode=True, **kw)
