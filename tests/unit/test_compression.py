"""Compression subsystem tests — the analog of the reference's
``tests/unit/compression/test_compression.py``: config parsing, QAT
fake-quant behavior, pruning mask semantics, redundancy_clean dim
reduction with forward equivalence, and student init."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.compression import (apply_compression, init_compression,
                                       quant_act, redundancy_clean,
                                       student_initialization,
                                       get_compression_config,
                                       compression_scheduler)
from deepspeed_tpu.compression import constants as C


def _mlp_params(key=0, din=16, dh=32, dout=16):
    rng = np.random.default_rng(key)
    return {
        "fc1": {"kernel": jnp.asarray(rng.normal(size=(din, dh)), jnp.float32),
                "bias": jnp.zeros((dh,), jnp.float32)},
        "fc2": {"kernel": jnp.asarray(rng.normal(size=(dh, dout)), jnp.float32),
                "bias": jnp.zeros((dout,), jnp.float32)},
    }


def _mlp_fwd(params, x):
    h = jnp.maximum(x @ params["fc1"]["kernel"] + params["fc1"]["bias"], 0)
    return h @ params["fc2"]["kernel"] + params["fc2"]["bias"]


def _wq_config(start_bits=8, target_bits=8, offset=0, period=1,
               modules=("fc1",)):
    return {
        "compression_training": {
            "weight_quantization": {
                "shared_parameters": {"enabled": True,
                                      "quantize_groups": 1},
                "different_groups": {
                    "wq1": {"params": {"start_bits": start_bits,
                                       "target_bits": target_bits,
                                       "quantization_period": period,
                                       "schedule_offset": offset},
                            "modules": list(modules)}
                }
            }
        }
    }


class TestConfig:

    def test_defaults_filled(self):
        cfg = get_compression_config(_wq_config())
        shared = cfg[C.WEIGHT_QUANTIZATION][C.SHARED_PARAMETERS]
        assert shared[C.TECHNIQUE_ENABLED]
        assert shared[C.WEIGHT_QUANTIZE_TYPE] == "symmetric"
        assert not cfg[C.SPARSE_PRUNING][C.SHARED_PARAMETERS][C.TECHNIQUE_ENABLED]

    def test_enabled_without_groups_raises(self):
        bad = {"compression_training": {"sparse_pruning": {
            "shared_parameters": {"enabled": True}}}}
        with pytest.raises(ValueError):
            get_compression_config(bad)


class TestWeightQuantization:

    def test_fake_quant_applied_and_close(self):
        params = _mlp_params()
        spec = init_compression(params, _wq_config())
        viewed = apply_compression(params, spec, step=0)
        w0, w1 = params["fc1"]["kernel"], viewed["fc1"]["kernel"]
        assert not np.allclose(w0, w1)                 # actually quantized
        assert np.max(np.abs(np.asarray(w0 - w1))) < 0.1   # 8-bit is close
        # fc2 untouched
        assert np.allclose(params["fc2"]["kernel"], viewed["fc2"]["kernel"])

    def test_bit_shedding_schedule(self):
        params = _mlp_params()
        cfg = _wq_config(start_bits=12, target_bits=4, offset=10, period=5)
        spec = init_compression(params, cfg)
        before = apply_compression(params, spec, step=5)
        assert np.allclose(before["fc1"]["kernel"], params["fc1"]["kernel"],
                           atol=1e-3)  # 12 bits ~ lossless at this scale
        later = apply_compression(params, spec, step=10 + 5 * 8)
        err4 = np.max(np.abs(np.asarray(later["fc1"]["kernel"] -
                                        params["fc1"]["kernel"])))
        assert err4 > 0.01  # shed down to 4 bits → visible error

    def test_ste_gradient_flows(self):
        params = _mlp_params()
        spec = init_compression(params, _wq_config())
        x = jnp.ones((2, 16))

        def loss(p):
            return jnp.sum(_mlp_fwd(apply_compression(p, spec, 0), x) ** 2)

        g = jax.grad(loss)(params)
        assert np.isfinite(np.asarray(g["fc1"]["kernel"])).all()
        assert np.abs(np.asarray(g["fc1"]["kernel"])).sum() > 0


class TestPruning:

    def test_sparse_pruning_ratio(self):
        params = _mlp_params()
        cfg = {"compression_training": {"sparse_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0,
                                  "method": "l1"},
            "different_groups": {"sp1": {"params": {"dense_ratio": 0.25},
                                         "modules": ["fc1"]}}}}}
        spec = init_compression(params, cfg)
        viewed = apply_compression(params, spec, step=0)
        nz = np.count_nonzero(np.asarray(viewed["fc1"]["kernel"]))
        total = viewed["fc1"]["kernel"].size
        assert nz == pytest.approx(0.25 * total, rel=0.05)
        # keeps the largest-magnitude entries
        kept = np.abs(np.asarray(params["fc1"]["kernel"]))[
            np.asarray(viewed["fc1"]["kernel"]) != 0]
        dropped = np.abs(np.asarray(params["fc1"]["kernel"]))[
            np.asarray(viewed["fc1"]["kernel"]) == 0]
        assert kept.min() >= dropped.max() - 1e-6

    def test_schedule_offset_gates_pruning(self):
        params = _mlp_params()
        cfg = {"compression_training": {"sparse_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 100},
            "different_groups": {"sp1": {"params": {"dense_ratio": 0.5,
                                                    "schedule_offset": 100},
                                         "modules": ["fc1"]}}}}}
        spec = init_compression(params, cfg)
        early = apply_compression(params, spec, step=50)
        assert np.allclose(early["fc1"]["kernel"], params["fc1"]["kernel"])

    def _row_cfg(self, ratio=0.5):
        return {"compression_training": {"row_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0},
            "different_groups": {"rp1": {"params": {"dense_ratio": ratio,
                                                    "schedule_offset": 0},
                                         "modules": ["fc1"],
                                         "related_modules": [["fc2"]]}}}}}

    def test_row_pruning_masks_and_related(self):
        params = _mlp_params()
        spec = init_compression(params, self._row_cfg())
        viewed = apply_compression(params, spec, step=0)
        col_norms = np.abs(np.asarray(viewed["fc1"]["kernel"])).sum(axis=0)
        assert (col_norms == 0).sum() == 16  # half of 32 outputs zeroed
        # related fc2 input rows zeroed consistently
        row_norms = np.abs(np.asarray(viewed["fc2"]["kernel"])).sum(axis=1)
        assert ((col_norms == 0) == (row_norms == 0)).all()

    def test_redundancy_clean_shrinks_and_preserves_forward(self):
        params = _mlp_params()
        spec = init_compression(params, self._row_cfg())
        x = jnp.asarray(np.random.default_rng(1).normal(size=(4, 16)),
                        jnp.float32)
        masked_out = _mlp_fwd(apply_compression(params, spec, 0), x)
        cleaned = redundancy_clean(params, spec)
        assert cleaned["fc1"]["kernel"].shape == (16, 16)
        assert cleaned["fc2"]["kernel"].shape == (16, 16)
        clean_out = _mlp_fwd(cleaned, x)
        np.testing.assert_allclose(np.asarray(masked_out),
                                   np.asarray(clean_out), atol=1e-5)

    def test_head_pruning(self):
        rng = np.random.default_rng(2)
        nh, hd, d = 4, 8, 32
        params = {
            "attn": {
                "q_proj": {"kernel": jnp.asarray(rng.normal(size=(d, d)),
                                                 jnp.float32)},
                "o_proj": {"kernel": jnp.asarray(rng.normal(size=(d, d)),
                                                 jnp.float32)},
            }
        }
        cfg = {"compression_training": {"head_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0},
            "different_groups": {"hp1": {
                "params": {"dense_ratio": 0.5, "num_heads": nh,
                           "schedule_offset": 0},
                "modules": ["o_proj"],
                "related_modules": [["q_proj"]]}}}}}
        spec = init_compression(params, cfg)
        viewed = apply_compression(params, spec, step=0)
        w = np.asarray(viewed["attn"]["o_proj"]["kernel"]).reshape(nh, hd, d)
        zero_heads = [h for h in range(nh) if np.abs(w[h]).sum() == 0]
        assert len(zero_heads) == 2
        cleaned = redundancy_clean(params, spec)
        assert cleaned["attn"]["o_proj"]["kernel"].shape == (d // 2, d)
        assert cleaned["attn"]["q_proj"]["kernel"].shape == (d, d // 2)


class TestActivationQuant:

    def test_quant_act_ste(self):
        x = jnp.linspace(-1, 1, 64)
        q = quant_act(x, bits=4)
        assert not np.allclose(q, x)
        assert len(np.unique(np.round(np.asarray(q), 6))) <= 17
        g = jax.grad(lambda y: jnp.sum(quant_act(y, bits=4) ** 2))(x)
        np.testing.assert_allclose(np.asarray(g), np.asarray(2 * q), atol=1e-5)


class TestSchedulerAndStudentInit:

    def test_scheduler_activation(self):
        params = _mlp_params()
        cfg = _wq_config(offset=3)
        spec = init_compression(params, cfg)
        sched = compression_scheduler(spec, cfg)
        assert not sched.is_active("fc1", C.WEIGHT_QUANTIZATION)
        for _ in range(3):
            sched.step()
        assert sched.is_active("fc1", C.WEIGHT_QUANTIZATION)

    def test_student_initialization(self):
        rng = np.random.default_rng(3)

        def layers(n):
            return {f"layers_{i}": {"fc": {"kernel": jnp.asarray(
                rng.normal(size=(4, 4)), jnp.float32)}} for i in range(n)}

        teacher = {**layers(6), "embed": {"embedding": jnp.asarray(
            rng.normal(size=(10, 4)), jnp.float32)}}
        student = {**{k: jax.tree_util.tree_map(jnp.zeros_like, v)
                      for k, v in layers(3).items()},
                   "embed": {"embedding": jnp.zeros((10, 4), jnp.float32)}}
        cfg = {"compression_training": {"layer_reduction": {
            "enabled": True, "keep_number_layer": 3,
            "module_name_prefix": "layers",
            "teacher_layer": [1, 3, 5],
            "other_module_name": ["embed"]}}}
        out = student_initialization(student, teacher, cfg)
        np.testing.assert_array_equal(
            np.asarray(out["layers_0"]["fc"]["kernel"]),
            np.asarray(teacher["layers_1"]["fc"]["kernel"]))
        np.testing.assert_array_equal(
            np.asarray(out["layers_2"]["fc"]["kernel"]),
            np.asarray(teacher["layers_5"]["fc"]["kernel"]))
        np.testing.assert_array_equal(np.asarray(out["embed"]["embedding"]),
                                      np.asarray(teacher["embed"]["embedding"]))
