"""Sharded MoE: gating + expert-parallel dispatch.

TPU-native re-design of reference ``deepspeed/moe/sharded_moe.py``
(``top1gating:179``, ``top2gating:277``, ``TopKGate:343``, ``MOELayer:420``,
``_AllToAll:90``).  The reference dispatches tokens with an explicit
``all_to_all_single`` over an expert process group; here dispatch is the
GShard einsum formulation — dispatch/combine tensors contracted against
expert-sharded arrays, letting GSPMD place the all-to-alls on ICI:

    expert_in  = einsum('tec,tm->ecm', dispatch, x)   # → a2a when E sharded
    expert_out = expert_fn(expert_in)                 # E sharded over 'ep'
    y          = einsum('ecm,tec->tm', expert_out, combine)

Capacity, token dropping, load-balancing aux loss, and the noisy gate
policies keep the reference's semantics.
"""

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp


def _one_hot(idx, num):
    return jax.nn.one_hot(idx, num, dtype=jnp.float32)


def _capacity(num_tokens, num_experts, capacity_factor, min_capacity, k=1):
    cap = int(np.ceil(k * num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def top1gating(logits, capacity_factor=1.0, min_capacity=4,
               noisy_gate_policy=None, rng=None, drop_tokens=True,
               used_token_mask=None):
    """Top-1 gating (Switch-style; reference ``sharded_moe.py:179``).

    logits: [T, E].  Returns (aux_loss, combine [T,E,C], dispatch bool
    [T,E,C], exp_counts [E]).
    """
    T, E = logits.shape
    C = _capacity(T, E, capacity_factor, min_capacity, k=1)
    if noisy_gate_policy == "RSample" and rng is not None:
        logits_for_choice = logits + jax.random.normal(rng, logits.shape) / E
    else:
        logits_for_choice = logits
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(logits_for_choice, axis=-1)          # [T]
    mask1 = _one_hot(expert_idx, E)                               # [T, E]
    if used_token_mask is not None:
        mask1 = mask1 * used_token_mask[:, None]

    # position of each token within its expert's queue
    pos_in_expert = jnp.cumsum(mask1, axis=0) * mask1             # [T, E]
    exp_counts = jnp.sum(mask1, axis=0)
    if drop_tokens:
        keep = pos_in_expert <= C
        mask1 = mask1 * keep
        pos_in_expert = pos_in_expert * keep

    # load-balancing loss (fraction of tokens * mean gate prob per expert)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    aux_loss = jnp.sum(me * ce) * E

    gate1 = jnp.sum(gates * mask1, axis=-1, keepdims=True)        # [T, 1]
    slot = _one_hot(jnp.int32(jnp.sum(pos_in_expert, axis=-1)) - 1, C)  # [T, C]
    combine = gate1[:, :, None] * mask1[:, :, None] * slot[:, None, :]
    dispatch = combine > 0
    return aux_loss, combine, dispatch, exp_counts


def topkgating(logits, k=2, capacity_factor=1.0, min_capacity=4,
               noisy_gate_policy=None, rng=None, drop_tokens=True):
    """Top-k gating with normalized top-k gates (reference top2gating
    ``sharded_moe.py:277`` generalized)."""
    T, E = logits.shape
    C = _capacity(T, E, capacity_factor, min_capacity, k=k)
    if noisy_gate_policy == "RSample" and rng is not None:
        choice_logits = logits + jax.random.normal(rng, logits.shape) / E
    else:
        choice_logits = logits
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)   # [T, E]

    combine = jnp.zeros((T, E, C), jnp.float32)
    used = jnp.zeros((T, E), jnp.float32)
    slots_taken = jnp.zeros((E,), jnp.float32)
    aux_masks = []
    masked_logits = choice_logits
    gate_sum = jnp.zeros((T, 1), jnp.float32)
    picks = []
    for i in range(k):
        idx = jnp.argmax(masked_logits, axis=-1)
        mask = _one_hot(idx, E)
        aux_masks.append(mask)
        pos = (jnp.cumsum(mask, axis=0) - 1) * mask + slots_taken[None, :] * mask
        if drop_tokens:
            keep = pos < C
            mask = mask * keep
        gate_i = jnp.sum(gates * mask, axis=-1, keepdims=True)    # [T,1]
        slot = _one_hot(jnp.int32(jnp.sum(pos * mask, axis=-1)), C)
        combine = combine + gate_i[:, :, None] * mask[:, :, None] * slot[:, None, :]
        gate_sum = gate_sum + gate_i
        slots_taken = slots_taken + jnp.sum(mask, axis=0)
        masked_logits = jnp.where(aux_masks[-1] > 0, -1e30, masked_logits)
        used = used + mask

    # normalize by the sum of selected gates
    denom = jnp.maximum(gate_sum, 1e-9)[:, :, None]
    combine = combine / denom
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(aux_masks[0], axis=0)
    aux_loss = jnp.sum(me * ce) * E
    dispatch = combine > 0
    exp_counts = jnp.sum(used, axis=0)
    return aux_loss, combine, dispatch, exp_counts


top2gating = lambda logits, **kw: topkgating(logits, k=2, **kw)


class TopKGate:
    """Gate wrapper (reference ``TopKGate:343``) — functional: the engine
    owns the gate weight; this class carries hyperparameters."""

    def __init__(self, model_dim, num_experts, k=1, capacity_factor=1.0,
                 eval_capacity_factor=1.0, min_capacity=4,
                 noisy_gate_policy=None, drop_tokens=True, use_rts=True):
        self.model_dim = model_dim
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.noisy_gate_policy = noisy_gate_policy
        self.drop_tokens = drop_tokens

    def __call__(self, logits, train=True, rng=None):
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if self.k == 1:
            return top1gating(logits, cf, self.min_capacity,
                              self.noisy_gate_policy if train else None, rng,
                              self.drop_tokens)
        return topkgating(logits, self.k, cf, self.min_capacity,
                          self.noisy_gate_policy if train else None, rng,
                          self.drop_tokens)


def moe_dispatch_combine(x, combine, dispatch, expert_fn):
    """The MOELayer dataflow (reference ``MOELayer.forward :472``):
    dispatch-einsum → experts → combine-einsum.  ``x``: [T, M]."""
    expert_in = jnp.einsum("tec,tm->ecm", dispatch.astype(x.dtype), x)
    expert_out = expert_fn(expert_in)                             # [E, C, M']
    return jnp.einsum("ecm,tec->tm", expert_out, combine.astype(expert_out.dtype))
