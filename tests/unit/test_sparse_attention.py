"""Block-sparse attention tests — analog of reference
``tests/unit/ops/sparse_attention/test_sparse_attention.py``: layouts are
sane, kernel matches the dense-masked reference, gradients flow, module runs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (
    DenseSparsityConfig, FixedSparsityConfig, VariableSparsityConfig,
    BigBirdSparsityConfig, BSLongformerSparsityConfig,
    LocalSlidingWindowSparsityConfig, block_sparse_attention,
    sparse_attention_reference, layout_tables, SparseSelfAttention,
    SparseAttentionFn)

BLOCK = 16  # small block for CPU-interpreter speed


def _qkv(B=2, S=64, H=2, D=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    return mk(), mk(), mk()


# ------------------------------ layouts -------------------------------- #
def test_dense_layout_all_ones():
    lay = DenseSparsityConfig(num_heads=2, block=BLOCK).make_layout(64)
    assert lay.shape == (2, 4, 4) and lay.sum() == 32


def test_fixed_layout_local_and_global():
    cfg = FixedSparsityConfig(num_heads=2, block=BLOCK, num_local_blocks=2,
                              num_global_blocks=1)
    lay = cfg.make_layout(128)  # 8 blocks
    assert lay.shape == (2, 8, 8)
    # local: diagonal 2x2 chunks present
    assert lay[0, 0, 0] == 1 and lay[0, 1, 0] == 1
    # global: column of each chunk's last block reaches all rows
    assert lay[0, :, 1].all()


def test_fixed_unidirectional_lower_triangular():
    cfg = FixedSparsityConfig(num_heads=1, block=BLOCK, num_local_blocks=4,
                              attention="unidirectional")
    lay = cfg.make_layout(128)
    assert np.array_equal(lay, np.tril(lay))


def test_variable_layout_windows_and_random():
    cfg = VariableSparsityConfig(num_heads=1, block=BLOCK,
                                 num_random_blocks=1,
                                 local_window_blocks=[1, 2],
                                 global_block_indices=[0])
    lay = cfg.make_layout(128)
    assert lay[0, :, 0].all()          # global col
    assert lay[0, 0, 0] == 1           # first local window


def test_bigbird_layout():
    cfg = BigBirdSparsityConfig(num_heads=1, block=BLOCK, num_random_blocks=1,
                                num_sliding_window_blocks=3,
                                num_global_blocks=1)
    lay = cfg.make_layout(128)
    assert lay[0, 0, :].all() and lay[0, :, 0].all()    # global row+col
    for r in range(1, 7):
        assert lay[0, r, r] == 1 and lay[0, r, r - 1] == 1  # window


def test_longformer_layout():
    cfg = BSLongformerSparsityConfig(num_heads=1, block=BLOCK,
                                     num_sliding_window_blocks=3,
                                     global_block_indices=[0])
    lay = cfg.make_layout(128)
    assert lay[0, :, 0].all() and lay[0, 0, :].all()


def test_sliding_window_layout_causal():
    cfg = LocalSlidingWindowSparsityConfig(num_heads=1, block=BLOCK,
                                           num_sliding_window_blocks=2,
                                           attention="unidirectional")
    lay = cfg.make_layout(128)
    assert np.array_equal(lay, np.tril(lay))
    assert lay[0, 5, 5] == 1 and lay[0, 5, 4] == 1 and lay[0, 5, 3] == 0


def test_layout_tables_roundtrip():
    lay = np.asarray([[[1, 0, 1], [0, 1, 0], [1, 1, 1]]])
    idx, counts = layout_tables(lay)
    assert counts.tolist() == [[2, 1, 3]]
    assert idx[0, 0, :2].tolist() == [0, 2]
    assert idx[0, 2].tolist() == [0, 1, 2]


def test_seq_not_divisible_raises():
    with pytest.raises(ValueError):
        DenseSparsityConfig(num_heads=1, block=BLOCK).make_layout(65)


# ------------------------------ kernel --------------------------------- #
@pytest.mark.parametrize("causal", [False, True])
def test_dense_layout_matches_reference(causal):
    q, k, v = _qkv()
    lay = DenseSparsityConfig(num_heads=2, block=BLOCK).make_layout(64)
    out = block_sparse_attention(q, k, v, lay, BLOCK, causal=causal)
    ref = sparse_attention_reference(q, k, v, lay, BLOCK, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("cfg_fn", [
    lambda: FixedSparsityConfig(num_heads=2, block=BLOCK, num_local_blocks=2),
    lambda: BigBirdSparsityConfig(num_heads=2, block=BLOCK,
                                  num_random_blocks=1,
                                  num_sliding_window_blocks=3),
    lambda: BSLongformerSparsityConfig(num_heads=2, block=BLOCK),
])
def test_sparse_layouts_match_reference(cfg_fn):
    q, k, v = _qkv(S=64)
    lay = cfg_fn().make_layout(64)
    out = block_sparse_attention(q, k, v, lay, BLOCK)
    ref = sparse_attention_reference(q, k, v, lay, BLOCK)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_causal_sliding_window_matches_reference():
    q, k, v = _qkv(S=64)
    cfg = LocalSlidingWindowSparsityConfig(num_heads=2, block=BLOCK,
                                           num_sliding_window_blocks=2,
                                           attention="unidirectional")
    lay = cfg.make_layout(64)
    out = block_sparse_attention(q, k, v, lay, BLOCK, causal=True)
    ref = sparse_attention_reference(q, k, v, lay, BLOCK, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_gradients_match_reference():
    q, k, v = _qkv(S=32, H=1)
    lay = FixedSparsityConfig(num_heads=1, block=BLOCK,
                              num_local_blocks=2).make_layout(32)

    def loss_sparse(q, k, v):
        return jnp.sum(block_sparse_attention(q, k, v, lay, BLOCK) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(sparse_attention_reference(q, k, v, lay, BLOCK) ** 2)

    gs = jax.grad(loss_sparse, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gs, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=1e-4)


def test_inside_jit():
    q, k, v = _qkv(S=32, H=1)
    lay = DenseSparsityConfig(num_heads=1, block=BLOCK).make_layout(32)
    f = jax.jit(lambda q, k, v: block_sparse_attention(q, k, v, lay, BLOCK))
    out = f(q, k, v)
    assert out.shape == q.shape
    out2 = f(q, k, v)  # cache hit with hashable layout
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


# ------------------------------ module --------------------------------- #
def test_sparse_self_attention_module():
    model = SparseSelfAttention(
        hidden_size=32, num_heads=2,
        sparsity_config=FixedSparsityConfig(num_heads=2, block=BLOCK,
                                            num_local_blocks=2))
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 64, 32)),
                    jnp.float32)
    params = model.init(jax.random.key(0), x)
    out = model.apply(params, x)
    assert out.shape == (2, 64, 32)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_sparse_attention_fn_key_padding_mask():
    q, k, v = _qkv(B=1, S=64, H=2, D=8)
    fn = SparseAttentionFn(DenseSparsityConfig(num_heads=2, block=BLOCK))
    keep = np.ones((1, 64))
    keep[0, 48:] = 0  # pad the tail
    out = fn(q, k, v, key_padding_mask=jnp.asarray(keep))
    # padded keys must not influence outputs: compare vs slicing them away
    fn2 = SparseAttentionFn(DenseSparsityConfig(num_heads=2, block=BLOCK))
    out_ref = fn2(q[:, :48], k[:, :48], v[:, :48])
    np.testing.assert_allclose(np.asarray(out[:, :48]), np.asarray(out_ref),
                               rtol=2e-3, atol=2e-4)


def test_transformer_with_sparse_attention():
    """End-to-end: a Transformer whose attention runs block-sparse (causal
    sliding window) trains a step and stays close to the dense model."""
    from deepspeed_tpu.models.transformer import (Transformer,
                                                  TransformerConfig)
    cfg = TransformerConfig(
        vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
        max_seq_len=64, dtype="float32", use_flash_attention=False,
        sparse_attention=LocalSlidingWindowSparsityConfig(
            num_heads=2, block=BLOCK, num_sliding_window_blocks=4,
            attention="unidirectional"),
        remat=False, scan_layers=False)
    model = Transformer(cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 64)),
                      jnp.int32)
    params = model.init(jax.random.key(0), {"input_ids": ids})
    loss = model.apply(params, {"input_ids": ids})
    assert np.isfinite(float(loss))
    g = jax.grad(lambda p: model.apply(p, {"input_ids": ids}))(params)
    assert all(bool(jnp.all(jnp.isfinite(x))) for x in jax.tree.leaves(g))
