"""CLI: ``python -m deepspeed_tpu.tools.lint [paths] [options]``."""

import argparse
import json
import os
import sys

from deepspeed_tpu.tools.lint.core import RULES, run_lint


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="tpu-lint",
        description="Framework-aware static analysis for host-transfer, "
                    "donation, and recompilation hazards.")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to lint (default: the "
                             "installed deepspeed_tpu package)")
    parser.add_argument("--rules", help="comma-separated rule ids to run "
                                        "(default: all)")
    parser.add_argument("--json", action="store_true",
                        help="emit findings as JSON")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        from deepspeed_tpu.tools.lint import rules as _r  # noqa: F401
        for rid, check in sorted(RULES.items()):
            print(f"{rid}  {check.title}")
        return 0

    paths = args.paths
    if not paths:
        # resolve the default against the installed package, not the cwd —
        # `ds_lint` from anywhere must not silently check zero files
        import deepspeed_tpu
        paths = [os.path.dirname(os.path.abspath(deepspeed_tpu.__file__))]
    rules = None
    if args.rules:
        from deepspeed_tpu.tools.lint import rules as _r  # noqa: F401
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(RULES)
        if unknown:
            print(f"tpu-lint: error: unknown rule id(s) "
                  f"{sorted(unknown)}; known: {sorted(RULES)}",
                  file=sys.stderr)
            return 2
    findings, stats = run_lint(paths, rules=rules)
    if stats["files"] == 0:
        print(f"tpu-lint: error: no Python files found under {paths}",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps([f.__dict__ for f in findings], indent=2))
    else:
        for f in findings:
            print(f)
        suppressed = sum(stats["suppressed"].values())
        print(f"tpu-lint: {len(findings)} finding(s), {suppressed} "
              f"suppressed, {stats['files']} file(s) checked")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
