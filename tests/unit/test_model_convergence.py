"""End-to-end model convergence — the analog of reference
``tests/model/Megatron_GPT2/run_sanity_check.py``: train a real (tiny)
decoder-only LM on a learnable synthetic task and demand the loss actually
converges, not merely ticks down.  Runs the full production path: Transformer
trunk + flash-attention fallbacks + fused engine step + ZeRO sharding on the
8-device CPU mesh."""

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.models.transformer import Transformer, TransformerConfig


VOCAB = 64


def copy_task_batch(rng, bs=8, seq=32):
    """Next-token-predictable stream: the second half of every row repeats
    the first half, so a 2-layer model can drive loss well below the
    uniform-baseline ln(VOCAB)≈4.16 by learning to copy."""
    half = rng.integers(2, VOCAB, (bs, seq // 2)).astype(np.int32)
    ids = np.concatenate([half, half], axis=1)
    return {"input_ids": ids}


@pytest.mark.slow
@pytest.mark.parametrize("stage", [1, 3])
def test_tiny_lm_converges(stage):
    cfg = TransformerConfig(
        vocab_size=VOCAB, hidden_size=64, num_layers=2, num_heads=4,
        max_seq_len=32, dtype="float32", use_flash_attention=False,
        remat=False, scan_layers=True)
    engine, *_ = deepspeed_tpu.initialize(
        model=Transformer(cfg),
        config={"train_micro_batch_size_per_gpu": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
                "zero_optimization": {"stage": stage},
                "gradient_clipping": 1.0})
    rng = np.random.default_rng(0)
    first = None
    for step in range(150):
        loss = engine(copy_task_batch(rng))
        engine.backward(loss)
        engine.step()
        if first is None:
            first = float(jax.device_get(loss))
    last = float(jax.device_get(loss))
    # copying the second half is learnable: demand real convergence, far
    # beyond "decreased" (uniform baseline ~4.16, start ~ln V)
    assert last < 0.6 * first, (first, last)
    assert last < 2.5, f"did not learn the copy task: {last}"
