"""Device-mesh topology: the TPU-native replacement for process groups.

The reference builds NCCL communicators per parallelism axis
(``deepspeed/utils/groups.py:59,108,202`` for model/expert/expert-data groups,
``deepspeed/runtime/pipe/topology.py:12,251`` for the pipeline rank grid).
On TPU the same capability is one ``jax.sharding.Mesh`` whose named axes ARE
the groups: collectives take an axis name instead of a communicator handle,
and XLA lays the collective onto ICI/DCN from the mesh's device order.

Axis order (outermost → innermost): ``pp, edp, ep, sp, tp``.
``tp`` is innermost so tensor-parallel collectives ride the fastest ICI links;
``pp`` is outermost so pipeline stages land on DCN-adjacent slices in
multi-host meshes.  The data-parallel "group" is the compound axis
``(edp, ep)`` — when expert parallelism is enabled, ``ep`` carves expert
groups out of the DP world exactly like the reference
(``groups.py:108 _create_expert_and_data_parallel``).
"""

from dataclasses import dataclass, field

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis names.
PP_AXIS = "pp"      # pipeline stages
MDP_AXIS = "mdp"    # MiCS replica groups (ZeRO shards live WITHIN a group,
                    # replicate ACROSS this axis — reference mics.py:24-29)
EDP_AXIS = "edp"    # expert-data-parallel (DP within an expert group)
EP_AXIS = "ep"      # expert parallel
SP_AXIS = "sp"      # sequence/context parallel
TP_AXIS = "tp"      # tensor/model parallel

AXIS_ORDER = (PP_AXIS, MDP_AXIS, EDP_AXIS, EP_AXIS, SP_AXIS, TP_AXIS)

# Compound groups, named for parity with the reference group getters.
DP_AXES = (MDP_AXIS, EDP_AXIS, EP_AXIS)    # dense data-parallel group
DENSE_GRAD_AXES = (MDP_AXIS, EDP_AXIS, EP_AXIS, SP_AXIS)  # grad axes, dense
EXPERT_GRAD_AXES = (MDP_AXIS, EDP_AXIS, SP_AXIS)          # grad axes, expert


@dataclass
class ParallelTopology:
    """A named device mesh plus the group algebra DeepSpeed exposes.

    Analog of ``PipeModelDataParallelTopology`` (reference
    ``runtime/pipe/topology.py:244``) generalized with expert and sequence
    axes.
    """

    dp: int = 1
    tp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    mdp: int = 1
    devices: list = field(default=None, repr=False)
    mesh: Mesh = field(default=None, repr=False)

    def __post_init__(self):
        if self.dp % (self.ep * self.mdp) != 0:
            raise ValueError(
                f"expert parallel size {self.ep} x MiCS replica groups "
                f"{self.mdp} must divide data parallel size {self.dp}")
        self.edp = self.dp // (self.ep * self.mdp)
        devices = self.devices
        if devices is None:
            devices = jax.devices()
        need = self.world_size
        if len(devices) < need:
            raise ValueError(
                f"topology dp={self.dp} tp={self.tp} pp={self.pp} sp={self.sp} "
                f"needs {need} devices, have {len(devices)}")
        devices = devices[:need]
        if self.mesh is None:
            shape = (self.pp, self.mdp, self.edp, self.ep, self.sp, self.tp)
            try:
                from jax.experimental import mesh_utils
                dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
            except Exception:
                dev_array = np.asarray(devices).reshape(shape)
            self.mesh = Mesh(dev_array, AXIS_ORDER)

    # ------------------------------------------------------------------ #
    @property
    def world_size(self):
        return self.pp * self.mdp * self.edp * self.ep * self.sp * self.tp

    # Group getters — parity with reference ``utils/groups.py:280-392``.
    def get_data_parallel_axes(self):
        return DP_AXES

    def get_model_parallel_axes(self):
        return (TP_AXIS,)

    def get_pipe_parallel_axes(self):
        return (PP_AXIS,)

    def get_expert_parallel_axes(self):
        return (EP_AXIS,)

    def get_expert_data_parallel_axes(self):
        # the DP replicas of one expert: the MiCS replica axis is part of
        # the group, else expert grads would never reduce across groups
        return (MDP_AXIS, EDP_AXIS)

    def get_sequence_parallel_axes(self):
        return (SP_AXIS,)

    def axis_size(self, name):
        return self.mesh.shape[name]

    def get_data_parallel_world_size(self):
        return self.dp

    def get_model_parallel_world_size(self):
        return self.tp

    def get_pipe_parallel_world_size(self):
        return self.pp

    def get_sequence_parallel_world_size(self):
        return self.sp

    def get_expert_parallel_world_size(self):
        return self.ep

    # ------------------------------------------------------------------ #
    def batch_spec(self, extra_dims=0):
        """PartitionSpec for a [batch, ...] array: batch sharded over DP
        (and sequence over sp when present on dim 1)."""
        dims = [DENSE_GRAD_AXES if self.dp > 1 or self.ep > 1 else None]
        if self.sp > 1:
            # With an active sp axis the batch dim carries (edp, ep) only and
            # dim 1 (sequence) carries sp.
            dims = [DP_AXES, SP_AXIS]
        return P(*dims, *([None] * extra_dims))

    def data_spec(self, batch_sharded=True, seq_dim=None):
        """Spec for input batches: dim0 over DP; optional seq dim over sp."""
        parts = [DP_AXES if batch_sharded else None]
        if seq_dim == 1:
            parts.append(SP_AXIS if self.sp > 1 else None)
        return P(*parts)

    def replicated_spec(self):
        return P()

    # ------------------------------------------------------------------ #
    # Introspection hooks — used by the comm-cost analyzer, the sharding
    # lint's registry tests, and the PartitionSpec-helper placement tests.
    # ------------------------------------------------------------------ #
    def axis_sizes(self):
        """``{axis: size}`` of the live mesh (all six canonical axes)."""
        return {k: int(v) for k, v in self.mesh.shape.items()}

    def shard_shape(self, spec, global_shape):
        """Per-device shard shape a ``PartitionSpec`` produces for a
        global array shape on THIS mesh — the statically checkable
        ground truth the spec helpers are validated against (a replicated
        batch dim shows up here as a full-size shard on every device)."""
        return NamedSharding(self.mesh, spec).shard_shape(
            tuple(global_shape))

    def shards_per_device(self, spec, global_shape):
        """Fraction of a global array each device holds under ``spec``
        (1.0 = fully replicated — the TL010 smell, numerically)."""
        shard = self.shard_shape(spec, global_shape)
        total = float(np.prod(global_shape)) or 1.0
        return float(np.prod(shard)) / total


# --------------------------------------------------------------------- #
# Global topology registry — analog of the module-level group cache in
# reference ``utils/groups.py``.
# --------------------------------------------------------------------- #
_TOPOLOGY = None


def initialize_topology(dp=None, tp=1, pp=1, ep=1, sp=1, mics=0,
                        devices=None):
    """``mics`` > 0 sizes the ZeRO shard group (reference
    ``mics_shard_size``, ``runtime/zero/mics.py:54``): the DP world splits
    into ``mdp`` replica groups of ``mics`` ZeRO-sharding devices each —
    params/opt-state shard WITHIN a group (ICI-local gathers), replicate
    ACROSS groups; grads still reduce over all of DP."""
    global _TOPOLOGY
    if devices is None:
        devices = jax.devices()
    if dp is None:
        denom = tp * pp * ep * sp
        if len(devices) % denom != 0:
            raise ValueError(
                f"device count {len(devices)} not divisible by tp*pp*ep*sp={denom}")
        dp = (len(devices) // denom) * ep  # dp includes the ep sub-axis
    mdp = 1
    if mics and mics > 0:
        edp_world = dp // ep
        if edp_world % mics != 0:
            raise ValueError(
                f"mics_shard_size={mics} must divide the expert-data-"
                f"parallel world {edp_world} (dp={dp} / ep={ep})")
        mdp = edp_world // mics
    _TOPOLOGY = ParallelTopology(dp=dp, tp=tp, pp=pp, ep=ep, sp=sp, mdp=mdp,
                                 devices=devices)
    return _TOPOLOGY


def get_topology():
    global _TOPOLOGY
    if _TOPOLOGY is None:
        _TOPOLOGY = initialize_topology()
    return _TOPOLOGY


def set_topology(topo):
    global _TOPOLOGY
    _TOPOLOGY = topo
    return _TOPOLOGY


def reset_topology():
    global _TOPOLOGY
    _TOPOLOGY = None
