"""DSUNet — accelerated UNet wrapper for diffusion pipelines.

Reference parity: ``model_implementations/diffusers/unet.py`` (``DSUNet``):
wraps the pipeline UNet in a captured CUDA graph replayed every denoise step.
TPU version: the denoise step compiles once per shape and replays (the UNet
is called hundreds of times per image with identical shapes — exactly the
workload graph capture exists for)."""

from deepspeed_tpu.model_implementations.features.cuda_graph import (
    CompiledGraphModule)


class DSUNet:

    def __init__(self, unet, params=None, enable_cuda_graph=True):
        self.unet = unet
        self.params = params
        self.config = getattr(unet, "config", None)
        self.in_channels = getattr(unet, "in_channels", None)
        apply = (lambda p, sample, t, enc: unet.apply(p, sample, t, enc)) \
            if hasattr(unet, "apply") else (lambda p, sample, t, enc:
                                            unet(sample, t, enc))
        self._forward = CompiledGraphModule(apply, enable_cuda_graph)

    def __call__(self, sample, timestep, encoder_hidden_states, params=None,
                 **kwargs):
        return self._forward(params if params is not None else self.params,
                             sample, timestep, encoder_hidden_states)
