"""TL002 positive fixture: jit over large buffers, no donation."""
import jax
import functools


def apply_update(params, opt_state, grads):
    return params, opt_state


update_fn = jax.jit(apply_update)                       # TL002


@jax.jit                                                # TL002
def fused_step(params, opt_state, batch):
    return params, opt_state


@functools.partial(jax.jit, static_argnums=(2,))        # TL002
def prefill(params, kv_cache, chunk):
    return kv_cache
