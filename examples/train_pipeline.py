"""Pipeline-parallel training — the reference pipeline tutorial's workflow
(``docs/_tutorials/pipeline.md``: PipelineModule + train_batch) on the SPMD
pipeline, composed 3D (pp × tp × dp) with the interleaved 1F1B schedule.

Run on a CPU dev mesh (pp=2 × tp=2 × dp=2):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu DSTPU_ACCELERATOR=cpu python examples/train_pipeline.py
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

# a sitecustomize may pin a hardware platform before this script runs; the
# live jax config must be updated before first device use (env is too late)
if os.environ.get("DSTPU_ACCELERATOR") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", type=int, default=2)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--micro_batches", type=int, default=4,
                    help="gradient_accumulation_steps = microbatches in flight")
    ap.add_argument("--schedule", default="1f1b",
                    choices=["fill_drain", "1f1b"],
                    help="fill_drain: O(M) stash; 1f1b: O(P) stash at the "
                         "same (P-1)/(M+P-1) bubble")
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.pipeline_transformer import transformer_pipe
    from deepspeed_tpu.models.transformer import TransformerConfig

    cfg = TransformerConfig(vocab_size=512, hidden_size=64, num_layers=4,
                            num_heads=4, max_seq_len=64, dtype="float32",
                            use_flash_attention=False, scan_layers=False,
                            remat=False)
    # transformer_pipe splits the model into LayerSpecs: embedding (pre),
    # the uniform block trunk (stacked over pp), final norm + head (post)
    engine, *_ = deepspeed_tpu.initialize(
        model=transformer_pipe(cfg),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": args.micro_batches,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "tensor_parallel": {"tp_size": args.tp},
            "pipeline": {"stages": args.stages, "schedule": args.schedule},
        })
    print(f"mesh: pp={engine.topology.pp} tp={engine.topology.tp} "
          f"dp={engine.topology.dp}, schedule={args.schedule}")

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, 512, (args.micro_batches, 2 * engine.topology.dp, 64))
        .astype(np.int32)}
    for step in range(args.steps):
        # train_batch is the unit of work — forward/backward/step are
        # forbidden on the pipeline engine, exactly like the reference
        loss = engine.train_batch(batch=batch)
        print(f"step {step}: loss {float(jax.device_get(loss)):.4f}")


if __name__ == "__main__":
    main()
