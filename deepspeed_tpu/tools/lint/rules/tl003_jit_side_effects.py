"""TL003 — Python side effects inside a jitted function.

``print``, ``logger.*`` calls, ``open`` and ``global`` writes inside a
function that is jit-wrapped run at TRACE time only: they fire once per
compilation, not once per step — a logging call that looks per-step is
silently dropped after the first call, and any value it prints is a tracer.
Use ``jax.debug.print``/``jax.debug.callback`` (which are traced) or move
the effect outside the jitted region.
"""

import ast

from deepspeed_tpu.tools.lint.core import Finding, dotted_name, rule
from deepspeed_tpu.tools.lint.rules.tl002_missing_donation import (
    is_jit_call, jit_decorator_kwargs)

_LOGGER_NAMES = {"logger", "logging", "log"}
_ALLOWED_DOTTED = {"jax.debug.print", "jax.debug.callback",
                   "debug.print", "debug.callback"}


def _jitted_functions(module):
    """FunctionInfos that are jit-wrapped: decorator form, or passed by name
    to a jit call in this module."""
    out = []
    jit_arg_names = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call) and is_jit_call(node) and node.args:
            f = node.args[0]
            if isinstance(f, ast.Name):
                jit_arg_names.add(f.id)
            elif isinstance(f, ast.Attribute):
                jit_arg_names.add(f.attr)
    for fn in module.functions:
        if jit_decorator_kwargs(fn.node) is not None or \
                fn.name in jit_arg_names:
            out.append(fn)
    return out


@rule("TL003", "Python side effect inside a jitted function")
def check(module):
    seen = set()
    for fn in _jitted_functions(module):
        for node in ast.walk(fn.node):
            if id(node) in seen:
                continue
            seen.add(id(node))
            if isinstance(node, ast.Global):
                yield Finding(
                    "TL003", module.path, node.lineno, node.col_offset,
                    f"'global' write inside jitted '{fn.name}' runs at trace "
                    f"time only — once per compile, not per step")
                continue
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in _ALLOWED_DOTTED:
                continue
            what = None
            if name in ("print", "open"):
                what = f"{name}()"
            elif isinstance(node.func, ast.Attribute):
                root = node.func.value
                if isinstance(root, ast.Name) and root.id in _LOGGER_NAMES:
                    what = f"{root.id}.{node.func.attr}()"
            if what:
                yield Finding(
                    "TL003", module.path, node.lineno, node.col_offset,
                    f"{what} inside jitted '{fn.name}' fires at trace time "
                    f"only (values are tracers) — use jax.debug.print or "
                    f"move it out of the jitted region")
