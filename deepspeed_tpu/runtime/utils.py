"""Runtime utilities — reference ``deepspeed/runtime/utils.py`` (the
grab-bag the engine and ZeRO lean on: ``see_memory_usage``,
``clip_grad_norm_``, ``get_global_norm``, ``CheckOverflow``,
``call_to_str``, ``get_grad_norm``…).

Functional JAX forms: norm/clip/overflow take and return pytrees and are
jit-safe (they are exactly what the engine's fused step inlines)."""

import gc
import os

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


# ------------------------------------------------------------------ #
# norms / clipping / overflow (jit-safe)
# ------------------------------------------------------------------ #
def get_grad_norm(grads, norm_type=2):
    """Global norm over a grad pytree (reference ``get_grad_norm``)."""
    leaves = [g.astype(jnp.float32) for g in jax.tree.leaves(grads)]
    if norm_type == float("inf"):
        return jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in leaves]))
    return jnp.sqrt(sum(jnp.sum(g * g) for g in leaves))


def get_global_norm(norm_list):
    """Combine per-group norms (reference ``get_global_norm``)."""
    arr = jnp.stack([jnp.asarray(n, jnp.float32) for n in norm_list])
    return jnp.sqrt(jnp.sum(arr * arr))


def clip_grad_norm_(grads, max_norm, norm_type=2):
    """Scale grads so the global norm ≤ max_norm; returns (grads, norm)
    (reference ``clip_grad_norm_`` — functional, no in-place mutation)."""
    norm = get_grad_norm(grads, norm_type)
    factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda g: g * factor, grads), norm


class CheckOverflow:
    """Reference ``CheckOverflow``: has-inf/nan over grads, optionally
    reduced across the mesh (GSPMD makes the reduction implicit when the
    check runs inside the jitted step)."""

    def __init__(self, param_groups=None, mpu=None, zero_reduce_scatter=False,
                 deepspeed=None):
        self.params = param_groups

    @staticmethod
    def has_overflow(grads):
        flat = jax.tree.leaves(grads)
        if not flat:
            return jnp.asarray(False)
        return jnp.logical_not(jnp.all(
            jnp.stack([jnp.all(jnp.isfinite(g)) for g in flat])))

    @staticmethod
    def check_using_norm(norm_list):
        total = float(np.sum(np.asarray(norm_list)))
        return not np.isfinite(total)


# ------------------------------------------------------------------ #
# memory reporting
# ------------------------------------------------------------------ #
def memory_status(msg=""):
    return see_memory_usage(msg, force=True)


def see_memory_usage(message, force=False):
    """Device + host memory dump (reference ``see_memory_usage``) —
    device numbers come from the accelerator's canonical
    ``memory_snapshot`` reader, so "HBM in use" here is the same number
    the profiler budget, the autotuner and the serving memory sampler
    report."""
    if not force and os.environ.get("DSTPU_MEMORY_DEBUG", "0") != "1":
        return
    from deepspeed_tpu.accelerator.real_accelerator import get_accelerator
    lines = [message]
    try:
        snaps = get_accelerator().memory_snapshots()
    except Exception:
        snaps = []
        lines.append("  device memory stats unavailable")
    for s in snaps:
        used, limit = s["bytes_in_use"], s["bytes_limit"]
        lines.append(f"  {s['device']}: {used / 2**30:.2f}GB used"
                     + (f" / {limit / 2**30:.2f}GB "
                        f"({s['limit_source']})" if limit else ""))
    try:
        import resource
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 2**20
        lines.append(f"  host max RSS: {rss:.2f}GB")
    except Exception:
        pass
    logger.info("\n".join(lines))


def empty_cache():
    """Best-effort allocation reclaim (reference calls torch empty_cache)."""
    gc.collect()


# ------------------------------------------------------------------ #
# misc
# ------------------------------------------------------------------ #
def call_to_str(base, *args, **kwargs):
    """Pretty call formatting (reference ``call_to_str``, used by pipeline
    instruction reprs)."""
    name = f"{base}("
    if args:
        name += ", ".join(repr(a) for a in args)
        if kwargs:
            name += ", "
    if kwargs:
        name += ", ".join(f"{k}={v!r}" for k, v in kwargs.items())
    return name + ")"


def partition_uniform(num_items, num_parts):
    """Balanced contiguous partition bounds (reference ``partition_uniform``,
    used by pipeline layer assignment)."""
    parts = [0] * (num_parts + 1)
    chunk, extra = divmod(num_items, num_parts)
    for p in range(num_parts):
        parts[p + 1] = parts[p] + chunk + (1 if p < extra else 0)
    return parts


def partition_balanced(weights, num_parts):
    """Weight-balanced contiguous partition (reference
    ``partition_balanced`` via prefix sums + binary search)."""
    prefix = np.concatenate([[0], np.cumsum(np.asarray(weights, np.float64))])
    total = prefix[-1]
    bounds = [0]
    for p in range(1, num_parts):
        target = total * p / num_parts
        bounds.append(int(np.searchsorted(prefix, target)))
    bounds.append(len(weights))
    # enforce monotonicity in degenerate cases
    for i in range(1, len(bounds)):
        bounds[i] = max(bounds[i], bounds[i - 1])
    return bounds


class PartitionedTensor:
    """Reference ``PartitionedTensor`` (pipeline's activation-partition
    helper): split a tensor across a group, reassemble on demand — the jax
    form keeps the parts as a list plus metadata."""

    def __init__(self, tensor=None, num_parts=1, parts=None, orig_shape=None):
        if tensor is not None:
            flat = jnp.ravel(tensor)
            pad = (-flat.size) % num_parts
            flat = jnp.pad(flat, (0, pad))
            self.parts = list(jnp.split(flat, num_parts))
            self.orig_shape = tensor.shape
        else:
            self.parts = parts
            self.orig_shape = orig_shape

    def to_meta(self):
        return {"orig_shape": self.orig_shape, "num_parts": len(self.parts)}

    def full(self):
        flat = jnp.concatenate(self.parts)
        n = int(np.prod(self.orig_shape))
        return flat[:n].reshape(self.orig_shape)


def rehydrate_opt_state(template, loaded):
    """Restore a NamedTuple optimizer state from its dict serialization
    (checkpoint metadata loses the namedtuple type).  Shared by the engine,
    BF16/FP16 wrappers and the universal-checkpoint loader."""
    if template is not None and hasattr(template, "_fields") \
            and isinstance(loaded, dict):
        return type(template)(**loaded)
    return loaded
