"""Flash-attention kernel vs jnp golden reference (the test pattern the
reference uses for its CUDA kernels, e.g. ``tests/unit/ops/transformer/``) —
forward and gradients, MHA and GQA, causal and full."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.transformer import reference_attention
from deepspeed_tpu.ops.transformer.flash_attention import flash_attention


def make_qkv(B=2, S=256, H=4, KVH=None, D=64, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    KVH = KVH or H
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), dtype=dtype)
    k = jnp.asarray(rng.standard_normal((B, S, KVH, D)), dtype=dtype)
    v = jnp.asarray(rng.standard_normal((B, S, KVH, D)), dtype=dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_reference(causal):
    q, k, v = make_qkv()
    out = flash_attention(q, k, v, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_forward_gqa():
    q, k, v = make_qkv(H=8, KVH=2)
    out = flash_attention(q, k, v, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_forward_uneven_blocks():
    # seq not a multiple of the block size exercises padding/cdiv paths
    q, k, v = make_qkv(S=192)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_gradients_match_reference(causal):
    q, k, v = make_qkv(B=1, S=128, H=2, D=32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4,
                                   rtol=5e-4, err_msg=f"d{name} mismatch")


def test_gradients_gqa():
    q, k, v = make_qkv(B=1, S=128, H=4, KVH=2, D=32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4,
                                   rtol=5e-4, err_msg=f"d{name} mismatch")


def test_bf16_forward_close():
    q, k, v = make_qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref, dtype=np.float32),
                               atol=3e-2, rtol=3e-2)
    assert out.dtype == jnp.bfloat16
