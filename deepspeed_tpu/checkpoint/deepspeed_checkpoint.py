"""Offline checkpoint inspection and resharding.

TPU-native counterpart of the reference's ``deepspeed/checkpoint/``
(``deepspeed_checkpoint.py:33 DeepSpeedCheckpoint``,
``zero_checkpoint.py:17 ZeROCheckpoint``): open a checkpoint directory
written by ``DeepSpeedEngine.save_checkpoint`` *without* a live engine,
enumerate tags/parameters/shapes, and lazily materialise arrays on host.

Where the reference needs 3D-reshape machinery (``reshape_3d_utils.py``,
``reshape_meg_2d.py``) because each rank wrote its own shard file, our
checkpoints are a single logically-global Orbax array store — loading onto a
different mesh/TP/DP degree is a property of *load-time shardings*, not of
file surgery.  The file-surgery helpers that remain useful (importing or
exporting foreign per-rank shard sets) live in ``reshape_utils.py``.
"""

import os
import pickle
import re

import numpy as np

import jax


def _flatten_with_paths(tree):
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        from deepspeed_tpu.runtime.zero.partition import path_to_str
        flat[path_to_str(path)] = leaf
    return flat


class DeepSpeedCheckpoint:
    """View over one checkpoint directory (possibly many tags).

    Reference parity: ``deepspeed/checkpoint/deepspeed_checkpoint.py:33``.
    """

    def __init__(self, ckpt_dir, tag=None):
        self.ckpt_dir = ckpt_dir
        if not os.path.isdir(ckpt_dir):
            raise FileNotFoundError(f"no checkpoint directory at {ckpt_dir}")
        self.tag = tag or self._latest_tag()
        self.state_path = os.path.join(ckpt_dir, str(self.tag), "state")
        if not os.path.isdir(self.state_path):
            raise FileNotFoundError(f"tag {self.tag!r} has no state at "
                                    f"{self.state_path}")
        self._meta = None
        self._arrays = None
        self._flat_params = None

    # ------------------------------------------------------------------ #
    def _latest_tag(self):
        latest = os.path.join(self.ckpt_dir, "latest")
        if os.path.exists(latest):
            with open(latest) as f:
                return f.read().strip()
        tags = self.get_tags()
        if not tags:
            raise FileNotFoundError(f"no tags under {self.ckpt_dir}")

        # Natural sort so global_step10 beats global_step9.
        def key(tag):
            nums = re.findall(r"\d+", tag)
            return (int(nums[-1]) if nums else -1, tag)
        return max(tags, key=key)

    def get_tags(self):
        tags = []
        for name in sorted(os.listdir(self.ckpt_dir)):
            if os.path.isdir(os.path.join(self.ckpt_dir, name, "state")):
                tags.append(name)
        return tags

    # ------------------------------------------------------------------ #
    @property
    def meta(self):
        if self._meta is None:
            with open(os.path.join(self.state_path, "meta.pkl"), "rb") as f:
                self._meta = pickle.load(f)
        return self._meta

    @property
    def global_steps(self):
        return self.meta.get("global_steps", 0)

    @property
    def ds_config(self):
        return self.meta.get("ds_config", {})

    def _load_arrays(self):
        if self._arrays is None:
            from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import (
                OrbaxCheckpointEngine)
            arrays, _ = OrbaxCheckpointEngine().load(self.state_path)
            self._arrays = arrays or {}
        return self._arrays

    # ------------------------------------------------------------------ #
    def module_state(self):
        """The model parameter pytree (host arrays)."""
        return jax.tree.map(np.asarray, self._load_arrays().get("module"))

    def optimizer_state(self):
        return self._load_arrays().get("optimizer")

    def flat_parameters(self):
        """{dotted-path: np.ndarray} over module parameters (cached)."""
        if self._flat_params is None:
            mod = self._load_arrays().get("module")
            self._flat_params = {} if mod is None else {
                k: np.asarray(v) for k, v in _flatten_with_paths(mod).items()}
        return self._flat_params

    def parameter_names(self):
        return sorted(self.flat_parameters().keys())

    def parameter_shapes(self):
        return {k: tuple(v.shape) for k, v in self.flat_parameters().items()}

    def num_parameters(self):
        return int(sum(v.size for v in self.flat_parameters().values()))


class ZeROCheckpoint(DeepSpeedCheckpoint):
    """Optimizer-state-centric view (reference ``zero_checkpoint.py:17``).

    Adds per-parameter access to the sharded optimizer moments, matched to
    module parameters by tree congruence.
    """

    def flat_optimizer_moments(self):
        """{field-name: {dotted-path: np.ndarray}} for optimizer-state fields
        that are congruent to the parameter tree (e.g. adam mu/nu)."""
        opt = self._load_arrays().get("optimizer")
        mod = self._load_arrays().get("module")
        if opt is None or mod is None:
            return {}
        params_def = jax.tree.structure(mod)
        out = {}

        def visit(field, name):
            try:
                if jax.tree.structure(field) == params_def:
                    out[name] = {k: np.asarray(v) for k, v in
                                 _flatten_with_paths(field).items()}
                    return
            except Exception:
                pass
            if hasattr(field, "_fields"):
                for f in field._fields:
                    visit(getattr(field, f), f"{name}.{f}" if name else f)
            elif isinstance(field, (tuple, list)):
                for i, f in enumerate(field):
                    visit(f, f"{name}.{i}" if name else str(i))
            elif isinstance(field, dict):
                for k, f in field.items():
                    visit(f, f"{name}.{k}" if name else str(k))

        visit(opt, "")
        return out
