"""Weight quantization for inference — reference ``runtime/weight_quantizer.py``
(``WeightQuantization``): groupwise-symmetric INT8/INT4 quantization of model
weights at checkpoint-load time, halving (int8) or quartering (packed int4)
weight HBM.

TPU redesign: the reference dequantizes inside custom CUDA gemms; here the
quantized payload + per-group scales live in HBM as ``QuantizedWeight``
pytree leaves, and ``dequantize_tree`` runs INSIDE the jitted program — XLA
fuses the dequant into each weight's consumer, so the compute-dtype copy of
a layer's weights exists only transiently while that layer computes.
"""

import re

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.quantizer.kernels import (dequantize, pack_int4,
                                                 quantize, unpack_int4)
from deepspeed_tpu.utils.logging import logger


@jax.tree_util.register_pytree_node_class
class QuantizedWeight:
    """Pytree node for one quantized tensor: payload ``q`` ([G, group] int8,
    or nibble-packed uint8 for 4-bit), per-group ``scale``/``zero``, and the
    original ``shape``/``bits``/``symmetric`` as static metadata (dequant
    must read the tensor's OWN metadata, not the deserializing quantizer's
    settings)."""

    def __init__(self, q, scale, zero, shape, bits, symmetric=True,
                 per_channel=False):
        self.q = q
        self.scale = scale
        self.zero = zero
        self.shape = tuple(shape)
        self.bits = int(bits)
        self.symmetric = bool(symmetric)
        self.per_channel = bool(per_channel)

    def tree_flatten(self):
        return ((self.q, self.scale, self.zero),
                (self.shape, self.bits, self.symmetric, self.per_channel))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)


def _is_qw(x):
    return isinstance(x, QuantizedWeight)


# Matrices whose name matches any of these stay float: the reference's
# WeightQuantization quantizes attention/MLP matrices, not embeddings or
# the LM head, where groupwise int error costs disproportionate accuracy.
# Matched token-anchored (like state_dict_factory._classify) so short
# patterns never fire inside unrelated names.
DEFAULT_SKIP_PATTERNS = ("embed", "embedding", "embeddings", "wte", "wpe",
                         "lm_head")


class WeightQuantization:
    """Groupwise weight quantizer (reference ``WeightQuantization``).

    ``quantize_tree`` converts every float leaf with ``ndim >= min_ndim``
    (default: matrices; biases/norms stay float) into a
    :class:`QuantizedWeight`; ``dequantize_tree`` is its jit-friendly
    inverse.  Leaves whose tree path matches ``skip_patterns`` (embeddings,
    LM head by default) are left unquantized; pass ``skip_patterns=()`` to
    quantize everything.
    """

    def __init__(self, bits=8, group_size=64, symmetric=True, min_ndim=2,
                 mlp_extra_grouping=False, mp_size=1,
                 skip_patterns=DEFAULT_SKIP_PATTERNS, per_channel=False):
        if bits not in (4, 8):
            raise ValueError(f"bits must be 4 or 8, got {bits}")
        if per_channel and (bits != 8 or not symmetric):
            raise ValueError("per_channel quantization supports symmetric "
                             "int8 only")
        self.per_channel = bool(per_channel)
        if group_size < 2:
            raise ValueError(f"group_size must be >= 2, got {group_size}")
        if group_size % 2:
            # int4 nibble-packing needs even groups; keep scales honest by
            # declaring the real granularity rather than silently drifting
            logger.warning(
                f"WeightQuantization: odd group_size {group_size} rounded up "
                f"to {group_size + 1} (int4 nibble-packing needs even groups)")
            group_size += 1
        if mlp_extra_grouping or mp_size != 1:
            logger.warning(
                "WeightQuantization: mlp_extra_grouping/mp_size are accepted "
                "for reference-API compatibility but have no effect here "
                "(grouping is uniform; TP layout comes from the mesh)")
        self.bits = bits
        self.group_size = group_size
        self.symmetric = symmetric
        self.min_ndim = min_ndim
        # whether dequantize_tree MATERIALIZES full compute-dtype weights
        # (grouped scales / int4: reshape chains) vs a bare convert×scale
        # that XLA fuses into each consumer (per-channel int8).  Decode
        # loops key on this: a materializing dequant should ride the scan
        # carry (else XLA hoists a full-size weight copy out of the loop);
        # a fusable one should not (the carry would copy the tree into
        # loop temps for nothing).
        self.materializing_dequant = not self.per_channel
        self.skip_patterns = tuple(p.lower() for p in skip_patterns)
        # token-anchored (like state_dict_factory._classify): short patterns
        # must not fire inside unrelated names; precompiled once
        self._skip_re = re.compile(
            "|".join(rf"(?:^|[^a-z0-9]){re.escape(p)}(?:[^a-z0-9]|$)"
                     for p in self.skip_patterns)) if self.skip_patterns \
            else None

    def should_quantize(self, leaf):
        return hasattr(leaf, "ndim") and leaf.ndim >= self.min_ndim and \
            jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)

    def _name_skipped(self, name):
        return self._skip_re is not None and \
            self._skip_re.search(name.lower()) is not None

    def quantize_leaf(self, leaf):
        x = jnp.asarray(leaf)
        if self.per_channel:
            # symmetric int8, one scale per output channel (all axes but the
            # leading contraction axis).  The point is the DEQUANT shape: a
            # bare ``q.astype(dtype) * scale`` with no reshape/pad lets XLA
            # fuse the dequant into the consuming matmul, so decode streams
            # int8 from HBM — the groupwise path's reshape chains
            # re-materialize a bf16 copy of every weight per decode step.
            xf = x.astype(jnp.float32)
            absmax = jnp.max(jnp.abs(xf), axis=0, keepdims=True)
            scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
            q = jnp.clip(jnp.round(xf / scale), -128, 127).astype(jnp.int8)
            return QuantizedWeight(q, scale, None, x.shape, 8,
                                   symmetric=True, per_channel=True)
        # pad the flat vector to a multiple of group_size: every tensor gets
        # the CONFIGURED group granularity (prime/awkward sizes must not
        # collapse to one whole-tensor scale)
        gsz = self.group_size
        pad = (-x.size) % gsz
        flat = jnp.pad(x.reshape(-1), (0, pad))
        groups = flat.size // gsz
        q, scale, zero = quantize(flat, groups, num_bits=self.bits,
                                  symmetric=self.symmetric)
        q = pack_int4(q) if self.bits == 4 else q.astype(jnp.int8)
        return QuantizedWeight(q, scale, zero, x.shape, self.bits,
                               self.symmetric)

    @staticmethod
    def dequantize_leaf(qw, dtype=jnp.bfloat16):
        if getattr(qw, "per_channel", False):
            return qw.q.astype(dtype) * qw.scale.astype(dtype)
        q = unpack_int4(qw.q) if qw.bits == 4 else qw.q
        groups = qw.scale.shape[0]
        flat = dequantize(q.reshape(groups, -1), qw.scale, qw.zero,
                          num_bits=qw.bits, symmetric=qw.symmetric)
        numel = int(np.prod(qw.shape))
        return flat.reshape(-1)[:numel].reshape(qw.shape).astype(dtype)

    def quantize_tree(self, params):
        n_q, n_skip = [0], [0]

        def one(path, leaf):
            if not self.should_quantize(leaf):
                return leaf
            if self._name_skipped(jax.tree_util.keystr(path)):
                n_skip[0] += 1
                return leaf
            n_q[0] += 1
            return self.quantize_leaf(leaf)
        out = jax.tree_util.tree_map_with_path(one, params)
        logger.info(f"weight-quantized {n_q[0]} tensors to int{self.bits} "
                    f"(group {self.group_size}); {n_skip[0]} matrices kept "
                    f"float by name filter {self.skip_patterns}")
        return out

    def dequantize_tree(self, params, dtype=jnp.bfloat16):
        return jax.tree.map(
            lambda l: self.dequantize_leaf(l, dtype) if _is_qw(l) else l,
            params, is_leaf=_is_qw)

    # reference-API sugar: quantize a flat state-dict's matrices in place
    def model_quantize(self, sd):
        return {k: (self.quantize_leaf(v)
                    if self.should_quantize(v) and not self._name_skipped(k)
                    else v)
                for k, v in sd.items()}
