"""TL009 — engine call blocking the asyncio loop thread (or owner-bound
call from a context that can never be the scheduler owner).

The HTTP front end's event loop parses requests and serializes
responses; the serving engine's thread-safe surface (``submit`` /
``cancel`` / ``status`` / ``result`` / ``token_events`` / ...) takes the
ENGINE LOCK, which the scheduler-owner thread holds across a whole
``step()`` — a dispatch plus host-mirror bookkeeping.  A direct call
from an ``async def`` handler therefore stalls EVERY connection for the
duration of a scheduler iteration (and a ``queue_policy="block"`` submit
can park the loop indefinitely).  The PR 8 hardening rounds hit exactly
this; the fix is mechanical and this rule enforces it:

* inside ``async def`` bodies (and sync callbacks registered via
  ``call_soon_threadsafe``/``call_soon`` — the ``on_event`` bridges that
  "must never block"), a DIRECT call to a lock-taking engine method is
  flagged — route it through ``loop.run_in_executor(None, srv.submit,
  ...)`` instead (a bare method REFERENCE passed to the executor is
  fine and is the fix);
* any appearance of an owner-bound driving method (``step`` / ``drain``
  / ``preempt``) in those contexts is flagged outright — the loop
  thread (and every executor worker) can never be the scheduler owner,
  so even an executor detour just moves the runtime ``RuntimeError``.

The lock-taking and owner-bound method sets come from the TL008
registry (``inference/serving/concurrency.py``: ``LOCKED_METHODS``,
``OWNER_BOUND_METHODS``, parsed statically) plus, per module, every
method of a guarded-field-declaring class whose body takes ``with
self.<lock>``.  Receivers are matched by the engine naming convention —
the attribute chain's last base segment is ``srv``/``eng``/``engine``
(or ``*_srv``/``*_engine``) — so ``self._server.close()`` or
``writer.drain()`` never false-positive.  Nested ``def``/``lambda``
bodies are exempt: they are the executor thunks.

Suppress a deliberate loop-thread call with
``# tpu-lint: disable=TL009 -- reason``.
"""

import ast

from deepspeed_tpu.tools.lint.core import Finding, dotted_name, rule
from deepspeed_tpu.tools.lint.rules.tl008_lock_discipline import (
    _local_declarations, _own_nodes, canonical_registry)

_ENGINE_SEGMENTS = ("srv", "eng", "engine")


def _engine_receiver(value):
    """True when the attribute base names an engine by convention."""
    base = dotted_name(value)
    if not base:
        return False
    seg = base.split(".")[-1]
    return seg in _ENGINE_SEGMENTS or seg.endswith("_srv") \
        or seg.endswith("_engine") or seg.lstrip("_") in _ENGINE_SEGMENTS


def _module_locked_methods(module):
    """Methods of locally-declared guarded classes whose bodies take
    ``with self.<lock>`` — the module's own thread-safe surface."""
    declared, aliases = _local_declarations(module)
    out = set()
    for fn in module.functions:
        cls = fn.class_name
        if cls not in declared:
            continue
        locks = set(declared[cls].values()) | set(aliases.get(cls, {}))
        for node in ast.walk(fn.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Attribute) \
                            and isinstance(ctx.value, ast.Name) \
                            and ctx.value.id == "self" \
                            and ctx.attr in locks:
                        out.add(fn.name)
    return out


def _callback_names(module):
    """Sync functions handed to ``call_soon_threadsafe``/``call_soon`` —
    they run ON the loop thread and must never block."""
    out = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute) \
                and node.func.attr in ("call_soon_threadsafe",
                                       "call_soon") and node.args:
            target = node.args[0]
            if isinstance(target, ast.Name):
                out.add(target.id)
    return out


@rule("TL009", "engine call blocking the asyncio loop thread")
def check(module):
    _g, _a, locked, owner_bound = canonical_registry()
    locked = set(locked) | _module_locked_methods(module)
    owner_bound = set(owner_bound)
    if not locked and not owner_bound:
        return
    callbacks = _callback_names(module)
    for fn in module.functions:
        is_async = isinstance(fn.node, ast.AsyncFunctionDef)
        is_callback = fn.name in callbacks \
            and isinstance(fn.node, ast.FunctionDef)
        if not (is_async or is_callback):
            continue
        ctx_name = "async handler" if is_async else \
            "loop callback (registered via call_soon*)"
        own = _own_nodes(fn.node)
        parents = {}
        for parent in own:
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        seen = set()
        for node in own:
            if not isinstance(node, ast.Attribute) \
                    or not _engine_receiver(node.value):
                continue
            parent = parents.get(node)
            is_direct_call = isinstance(parent, ast.Call) \
                and parent.func is node
            key = (node.lineno, node.attr)
            if key in seen:
                continue
            if node.attr in owner_bound:
                seen.add(key)
                yield Finding(
                    "TL009", module.path, node.lineno, node.col_offset,
                    f"owner-bound '{dotted_name(node) or node.attr}' in "
                    f"{ctx_name} '{fn.name}' — only the scheduler-owner "
                    f"thread may drive step()/drain()/preempt(); an "
                    f"executor detour still raises at runtime.  Signal "
                    f"the scheduler thread instead (srv.wake / a flag "
                    f"the owner polls)")
            elif node.attr in locked and is_direct_call:
                seen.add(key)
                yield Finding(
                    "TL009", module.path, node.lineno, node.col_offset,
                    f"direct call to lock-taking "
                    f"'{dotted_name(node) or node.attr}()' in {ctx_name} "
                    f"'{fn.name}' blocks the event loop for up to a full "
                    f"scheduler step — route it through "
                    f"`await loop.run_in_executor(None, "
                    f"{dotted_name(node) or node.attr}, ...)`")
