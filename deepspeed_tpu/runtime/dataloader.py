"""Data loading — parity with reference ``runtime/dataloader.py``
(``DeepSpeedDataLoader:41``, ``RepeatingLoader:17``).

On TPU the DistributedSampler disappears: batches are *global* — every JAX
process feeds its local shard of a globally-sharded batch, and the engine
places them with the DP/SP data sharding.  This loader handles host-side
batching/collation from an indexable dataset (numpy arrays, dict-of-arrays,
torch Datasets, or any sequence)."""

import numpy as np

from deepspeed_tpu.utils.logging import logger


class RepeatingLoader:
    """Wrap an iterator to restart on StopIteration (reference ``:17``)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __len__(self):
        return len(self.loader)

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


def default_collate(samples):
    """Stack a list of samples into a batch pytree."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(s[k]) for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack([np.asarray(s[i]) for s in samples])
                           for i in range(len(first)))
    return np.stack([np.asarray(s) for s in samples])


class DeepSpeedDataLoader:

    def __init__(self, dataset, batch_size, collate_fn=None, num_workers=0,
                 engine=None, drop_last=True, shuffle=False, seed=0,
                 data_sampler=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate
        self.drop_last = drop_last
        self.shuffle = shuffle
        self.engine = engine
        self.data_sampler = data_sampler
        self.epoch = 0
        self._seed = seed
        self.len = len(dataset) // batch_size if drop_last else \
            (len(dataset) + batch_size - 1) // batch_size

    def __len__(self):
        return self.len

    def set_epoch(self, epoch):
        self.epoch = epoch
        if self.data_sampler is not None and hasattr(self.data_sampler, "set_epoch"):
            self.data_sampler.set_epoch(epoch)

    def __iter__(self):
        n = len(self.dataset)
        if self.data_sampler is not None:
            order = list(iter(self.data_sampler))
        elif self.shuffle:
            rng = np.random.default_rng(self._seed + self.epoch)
            order = rng.permutation(n).tolist()
        else:
            order = list(range(n))
        for start in range(0, n - (self.batch_size - 1 if self.drop_last else 0),
                           self.batch_size):
            idx = order[start:start + self.batch_size]
            if not idx:
                return
            samples = [self.dataset[i] for i in idx]
            batch = self.collate_fn(samples)
            if self.engine is not None:
                batch = self.engine.put_batch(batch)
            yield batch
