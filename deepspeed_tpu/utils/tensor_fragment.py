"""Access to full / fragmented optimizer-state views — TPU-native re-design of
reference ``deepspeed/utils/tensor_fragment.py`` (``safe_get_full_fp32_param``
etc., used for debugging and universal checkpointing).

The reference maintains explicit fragment maps because ZeRO flattens and
slices tensors by hand.  Under GSPMD the "fragments" are just the shards of a
sharded ``jax.Array``, so the full view is ``jax.device_get`` (an all-gather)
and a fragment is ``array.addressable_shards`` — these helpers keep the
reference's API names so user diagnostics port 1:1.
"""

import numpy as np

import jax
import jax.numpy as jnp


def _lookup(tree, path):
    node = tree
    for part in path.split("/"):
        if part:
            node = node[part]
    return node


def safe_get_full_fp32_param(engine, param_path):
    """Full fp32 master weight of one parameter (reference
    ``safe_get_full_fp32_param``).  ``param_path``: '/'-joined tree path."""
    if engine.params is None:
        return None
    return np.asarray(jax.device_get(_lookup(engine.params, param_path)))


def safe_set_full_fp32_param(engine, param_path, value):
    """Overwrite one master weight, preserving its sharding (reference
    ``safe_set_full_fp32_param``)."""
    cur = _lookup(engine._params, param_path)
    new = jax.device_put(jnp.asarray(value, cur.dtype), cur.sharding)

    def replace(tree, parts):
        key = parts[0]
        if len(parts) == 1:
            return {**tree, key: new}
        return {**tree, key: replace(tree[key], parts[1:])}

    engine._params = replace(engine._params, [p for p in param_path.split("/") if p])


def safe_get_full_optimizer_state(engine, param_path, optim_state_key):
    """Full view of one optimizer-state slot, e.g. 'exp_avg' (reference
    ``safe_get_full_optimizer_state``)."""
    if engine._opt_state is None:
        return None
    field = getattr(engine._opt_state, optim_state_key, None)
    if field is None and hasattr(engine._opt_state, "_asdict"):
        field = engine._opt_state._asdict().get(optim_state_key)
    if field is None:
        return None
    return np.asarray(jax.device_get(_lookup(field, param_path)))


def safe_get_full_grad(engine, param_path):
    """Most recent full gradient for a param (reference
    ``safe_get_full_grad``); engine retains grads only between backward and
    step in the 3-call API."""
    grads = getattr(engine, "_grad_acc", None)
    if grads is None:
        pending = getattr(engine, "_pending", None)
        grads = pending[0] if pending else None
    if grads is None:
        return None
    leaf = _lookup(grads, param_path)
    # staged grads are of (loss × scale / gas) — unscale so the caller sees
    # the true gradient the optimizer will consume after its own unscale
    scaler = getattr(engine, "_scaler_state", None)
    if scaler is not None:
        leaf = leaf / scaler.scale
    return np.asarray(jax.device_get(leaf))


def get_local_fragment(array):
    """This process's shards of a sharded array — the analog of the
    reference's mapped flat fragment."""
    return [(s.index, np.asarray(s.data)) for s in array.addressable_shards]
