"""Speculative multi-token decoding tests (``inference/serving/``,
``docs/serving.md`` "Speculative decoding").

The acceptance contract: with ``serving.speculative`` on, a draft model
proposes ``spec_k`` tokens per live slot, the target verifies the whole
window in ONE batched forward, and greedy outputs stay BITWISE-identical
to non-speculative serving / solo ``generate()`` — through slot churn,
mid-window EOS, paged mode, preempt→restore (committed tokens only ever
reach snapshots and streams), with exactly one draft-propose and one
verify-and-commit executable per server lifetime."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.inference.serving.slo import RequestStatus
from deepspeed_tpu.models.transformer import Transformer, TransformerConfig


def tiny_cfg(**over):
    base = dict(vocab_size=97, hidden_size=64, num_layers=2, num_heads=4,
                max_seq_len=64, use_flash_attention=False, dtype="float32")
    base.update(over)
    return TransformerConfig(**base)


SERVING = {"enabled": True, "num_slots": 3, "max_cache_len": 64,
           "prefill_chunk": 8, "prefill_token_budget": 16,
           "decode_block": 2}


@pytest.fixture
def served_engine():
    model = Transformer(tiny_cfg())
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 97, (2, 12)),
                      jnp.int32)
    params = model.init(jax.random.key(0), {"input_ids": ids})
    # prefill_chunk_size=8: the solo generate() reference replays the
    # SAME split-prefill chunk program the serving admission path runs
    eng = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "prefill_chunk_size": 8,
                       "serving": SERVING})
    eng.set_params(params)
    return eng


@pytest.fixture
def draft_pair():
    """A distinct, smaller random draft model sharing the target vocab —
    the low-accept-rate end (correctness must not depend on the draft
    being any good)."""
    dcfg = tiny_cfg(hidden_size=32, num_layers=1)
    draft = Transformer(dcfg)
    ids = jnp.asarray(np.random.default_rng(1).integers(0, 97, (1, 8)),
                      jnp.int32)
    return draft, draft.init(jax.random.key(1), {"input_ids": ids})


def _mixed_workload(rng, n=7):
    lens = rng.integers(9, 21, (n,))
    news = rng.integers(3, 13, (n,))
    prompts = [rng.integers(1, 97, (int(p),)).astype(np.int32)
               for p in lens]
    return prompts, [int(x) for x in news]


def _mid_stream_eos(eng, prompts, news, every=2):
    """Per-request eos ids that actually fire mid-stream for every
    ``every``-th request (probed from the greedy continuation)."""
    eos_ids = []
    for i, (p, n) in enumerate(zip(prompts, news)):
        if i % every == 0:
            probe = np.asarray(eng.generate(p[None], max_new_tokens=n))[0]
            eos_ids.append(int(probe[len(p) + n // 2]))
        else:
            eos_ids.append(-1)
    return eos_ids


# --------------------------------------------------------------------- #
# The bitwise-greedy acceptance contract
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("k", [1, 3])
def test_spec_matches_solo_generate(served_engine, k):
    """num_slots(3) < num_requests(7), mid-stream EOS on half the
    requests, slot churn — greedy speculative outputs bitwise-equal to
    solo generate(), for window sizes k=1 and k=3."""
    eng = served_engine
    rng = np.random.default_rng(3)
    prompts, news = _mixed_workload(rng)
    eos_ids = _mid_stream_eos(eng, prompts, news)

    srv = eng.serve(speculative=True, spec_k=k, spec_draft_model="self")
    rids = [srv.submit(p, max_new_tokens=n, eos_token_id=e)
            for p, n, e in zip(prompts, news, eos_ids)]
    outs = srv.drain()
    assert sorted(outs) == sorted(rids)
    for rid, p, n, e in zip(rids, prompts, news, eos_ids):
        want = np.asarray(eng.generate(p[None], max_new_tokens=n,
                                       eos_token_id=e))[0]
        np.testing.assert_array_equal(
            outs[rid], want,
            err_msg=f"request {rid} (P={len(p)}, new={n}, eos={e}, "
                    f"k={k}) diverges from its solo generate() run")
    # slot churn really happened (EOS frees slots mid-flight)
    occ = [o for _, o in srv.occupancy_trace]
    assert any(occ[i] < occ[i - 1] for i in range(1, len(occ))), occ
    assert srv.stats["completed"] == len(rids)
    # self-draft greedy: the accept machinery actually accepted drafts
    assert srv.stats["spec_committed_tokens"] > srv.stats["spec_windows"]


def test_spec_matches_nonspec_serving(served_engine):
    """Speculative serving outputs == NON-speculative serving outputs,
    bitwise, on the same workload (the tentpole claim verbatim)."""
    eng = served_engine
    rng = np.random.default_rng(11)
    prompts, news = _mixed_workload(rng, n=5)
    eos_ids = _mid_stream_eos(eng, prompts, news)

    base = eng.serve()
    b_rids = [base.submit(p, max_new_tokens=n, eos_token_id=e)
              for p, n, e in zip(prompts, news, eos_ids)]
    b_outs = base.drain()
    base.close()
    spec = eng.serve(speculative=True, spec_k=4, spec_draft_model="self")
    s_rids = [spec.submit(p, max_new_tokens=n, eos_token_id=e)
              for p, n, e in zip(prompts, news, eos_ids)]
    s_outs = spec.drain()
    for br, sr in zip(b_rids, s_rids):
        np.testing.assert_array_equal(b_outs[br], s_outs[sr])
    # and speculation needed FEWER target dispatches than non-spec
    # decode rounds would commit: each spec round commits up to k+1
    # per slot vs decode_block(=2) for the baseline config
    assert spec.stats["spec_tokens_per_dispatch"] > 1.0


def test_spec_random_draft_still_bitwise(served_engine, draft_pair):
    """A terrible (random) draft model must only cost THROUGHPUT, never
    correctness: accept rate ~0, outputs still bitwise-equal to solo."""
    eng = served_engine
    draft, dparams = draft_pair
    rng = np.random.default_rng(13)
    prompts, news = _mixed_workload(rng, n=4)
    srv = eng.serve(speculative=True, spec_k=2, draft_module=draft,
                    draft_params=dparams)
    rids = [srv.submit(p, max_new_tokens=n)
            for p, n in zip(prompts, news)]
    outs = srv.drain()
    for rid, p, n in zip(rids, prompts, news):
        want = np.asarray(eng.generate(p[None], max_new_tokens=n))[0]
        np.testing.assert_array_equal(outs[rid], want)
    assert srv.stats["spec_accept_rate"] < 0.5


def test_spec_paged_matches_solo(served_engine):
    """Paged pool + speculation: the verify window's per-row multi-token
    writes route through the page tables; outputs bitwise vs solo with
    slot churn and mid-stream EOS.  (Prefix sharing is disabled under
    speculation — the draft cache prefills from position 0.)"""
    eng = served_engine
    rng = np.random.default_rng(17)
    prompts, news = _mixed_workload(rng, n=6)
    eos_ids = _mid_stream_eos(eng, prompts, news)
    srv = eng.serve(speculative=True, spec_k=2, spec_draft_model="self",
                    paged=True, page_size=16)
    assert srv.stats["prefix_lookups"] == 0
    rids = [srv.submit(p, max_new_tokens=n, eos_token_id=e)
            for p, n, e in zip(prompts, news, eos_ids)]
    outs = srv.drain()
    for rid, p, n, e in zip(rids, prompts, news, eos_ids):
        want = np.asarray(eng.generate(p[None], max_new_tokens=n,
                                       eos_token_id=e))[0]
        np.testing.assert_array_equal(outs[rid], want)
    assert srv.stats["prefix_lookups"] == 0      # disabled under spec


# --------------------------------------------------------------------- #
# TokenStream: a dispatch committing m tokens emits m ORDERED events
# --------------------------------------------------------------------- #
def test_spec_stream_emits_per_token_events(served_engine):
    """Multi-token commits must stream as individual ordered per-token
    events (monotonic indices, lossless replay) — including a request
    whose EOS lands mid-speculation-window, whose stream must end at
    exactly the terminal token."""
    eng = served_engine
    rng = np.random.default_rng(19)
    prompts, news = _mixed_workload(rng, n=3)
    news = [max(n, 8) for n in news]
    eos_ids = _mid_stream_eos(eng, prompts, news, every=1)
    eos_ids[1] = -1                       # one request without EOS
    srv = eng.serve(speculative=True, spec_k=3, spec_draft_model="self")
    rids = [srv.submit(p, max_new_tokens=n, eos_token_id=e)
            for p, n, e in zip(prompts, news, eos_ids)]
    streams = {rid: srv.token_events(rid) for rid in rids}
    outs = srv.drain()
    for rid, p, n, e in zip(rids, prompts, news, eos_ids):
        toks, end = streams[rid].tokens(timeout=5)
        res = srv.result(rid)
        # stream == the generated region of the final result, bitwise
        gen = [int(t) for t in outs[rid][len(p):len(p) + len(toks)]]
        assert toks == gen, (rid, toks, gen)
        assert end["status"] == RequestStatus.COMPLETED
        # per-token: more events than dispatches for this rid, indices
        # contiguous from 0 (TokenStream replays + live pushes agree)
        assert len(toks) >= 1
        if e >= 0:
            # EOS mid-window: the stream ends AT the eos token — nothing
            # past it was ever surfaced
            assert toks[-1] == e
            assert e not in toks[:-1]
    # late subscription replays losslessly after completion
    replay, end = srv.token_events(rids[0]).tokens(timeout=1)
    first, _ = streams[rids[0]].rid, None
    want = [int(t) for t in
            outs[rids[0]][len(prompts[0]):len(prompts[0]) + len(replay)]]
    assert replay == want and end["status"] == RequestStatus.COMPLETED


def test_spec_stream_event_indices_monotonic(served_engine):
    """The raw event dicts carry strictly increasing ``index`` values
    starting at 0 — one event per committed token, never a blob per
    dispatch."""
    eng = served_engine
    rng = np.random.default_rng(23)
    p = rng.integers(1, 97, (10,)).astype(np.int32)
    srv = eng.serve(speculative=True, spec_k=4, spec_draft_model="self")
    rid = srv.submit(p, max_new_tokens=12)
    stream = srv.token_events(rid)
    srv.drain()
    events = list(stream.events(timeout=5))
    tok_events = [ev for ev in events if ev["event"] == "token"]
    assert [ev["index"] for ev in tok_events] == \
        list(range(len(tok_events)))
    assert events[-1]["event"] == "end"
    # at least one dispatch committed more than one token (self-draft
    # greedy accepts) — the per-token contract did real work here
    assert srv.stats["spec_tokens_per_dispatch"] > 1.0


# --------------------------------------------------------------------- #
# Preempt / restore: committed tokens only, bitwise resume
# --------------------------------------------------------------------- #
def test_spec_preempt_restore_bitwise(served_engine, tmp_path):
    """preempt() mid-speculation snapshots COMMITTED tokens only (every
    snapshotted token list is a prefix of the final output; uncommitted
    draft tokens are never surfaced) and a restarted speculative server
    resumes bitwise.  Draft state is re-derived through the ordinary
    re-prefill path — nothing draft-side is snapshotted."""
    eng = served_engine
    rng = np.random.default_rng(29)
    prompts, _ = _mixed_workload(rng, n=3)
    srv = eng.serve(speculative=True, spec_k=3, spec_draft_model="self")
    rids = [srv.submit(p, max_new_tokens=14) for p in prompts]
    for _ in range(4):
        srv.step()
    tag, snapped, finished = srv.preempt(str(tmp_path), drain_budget_s=0.0)
    assert snapped, "nothing was mid-flight — the test lost its point"
    state = json.loads(
        (tmp_path / tag / "serving_state.json").read_text())
    assert not any("draft" in k for k in state), \
        "draft state must be re-derived on restore, never snapshotted"

    srv2 = eng.serve(speculative=True, spec_k=3, spec_draft_model="self")
    restored = srv2.restore(str(tmp_path))
    assert sorted(restored) == sorted(snapped)
    outs = dict(finished)
    outs.update(srv2.drain())
    for rid, p in zip(rids, prompts):
        want = np.asarray(eng.generate(p[None], max_new_tokens=14))[0]
        np.testing.assert_array_equal(outs[rid], want)
    # committed-only: each snapshotted token list is a PREFIX of the
    # final generated region
    by_rid = {int(r["rid"]): r for r in state["requests"]}
    for rid, p in zip(rids, prompts):
        if rid not in by_rid:
            continue
        snap_toks = [int(t) for t in by_rid[rid]["tokens"]]
        gen = [int(t) for t in outs[rid][len(p):]]
        assert snap_toks == gen[:len(snap_toks)], (snap_toks, gen)


# --------------------------------------------------------------------- #
# One draft + one verify executable per server lifetime
# --------------------------------------------------------------------- #
def test_spec_zero_new_executables_across_churn_and_resume(tmp_path):
    """Overload + shed + cancel + preempt + restarted-server resume mint
    exactly ONE draft-propose and ONE verify-and-commit executable per
    server lifetime, with zero executable-store traffic (the serving
    programs bypass the persistent caches)."""
    from deepspeed_tpu.runtime import compile_cache as cc

    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        model = Transformer(tiny_cfg())
        ids = jnp.asarray(
            np.random.default_rng(0).integers(0, 97, (1, 12)), jnp.int32)
        params = model.init(jax.random.key(0), {"input_ids": ids})
        config = {"dtype": "float32", "prefill_chunk_size": 8,
                  "serving": {**SERVING, "speculative": True, "spec_k": 2,
                              "spec_draft_model": "self"},
                  "compile_cache": {"enabled": True,
                                    "cache_dir": str(tmp_path / "cache"),
                                    "min_compile_time_secs": 0.0}}
        snap = str(tmp_path / "snap")
        rng = np.random.default_rng(57)
        prompts, news = _mixed_workload(rng, n=7)

        def fresh_server():
            eng = deepspeed_tpu.init_inference(model, config=config)
            eng.set_params(params)
            srv = eng.serve()
            return eng, srv, srv.warmup()

        eng1, srv1, report1 = fresh_server()
        assert any(k.startswith("serving_spec_verify") for k in report1)
        assert any(k.startswith("serving_spec_propose") for k in report1)
        rids = [srv1.submit(p, max_new_tokens=n)
                for p, n in zip(prompts[:5], news[:5])]
        r_shed = srv1.submit(prompts[5], max_new_tokens=4, deadline_s=0.0)
        r_cancel = srv1.submit(prompts[6], max_new_tokens=4)
        srv1.cancel(r_cancel)
        early = {}
        for _ in range(4):
            early.update(srv1.step())
        s1 = cc.stats().snapshot()
        tag, snapped, finished = srv1.preempt(snap, drain_budget_s=0.0)
        finished = {**early, **finished}
        assert srv1.result(r_shed).status == RequestStatus.SHED_DEADLINE

        eng2, srv2, report2 = fresh_server()
        s2 = cc.stats().snapshot()
        assert s2["executable_saves"] == s1["executable_saves"]
        assert s2["executable_hits"] == s1["executable_hits"]
        restored = srv2.restore(snap)
        assert sorted(restored) == sorted(snapped)
        outs = dict(finished)
        outs.update(srv2.drain())
        s3 = cc.stats().snapshot()
        assert s3["executable_saves"] == s1["executable_saves"], \
            "the spec overload+resume cycle persisted a new executable"
        for srv, eng in ((srv1, eng1), (srv2, eng2)):
            for fn, what in ((srv._propose_fn, "draft-propose"),
                             (srv._verify_fn, "verify-and-commit")):
                n_sig = sum(1 for sig in eng._aot
                            if sig and sig[0] == id(fn))
                assert n_sig == 1, (what, n_sig)
        for rid, p, n in zip(rids, prompts[:5], news[:5]):
            want = np.asarray(
                eng2.generate(p[None], max_new_tokens=n))[0]
            np.testing.assert_array_equal(outs[rid], want)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          prev_min)
        cc._configured_dir = prev_dir


# --------------------------------------------------------------------- #
# Validation, capacity reserve, observability, registry
# --------------------------------------------------------------------- #
def test_spec_validation(served_engine, draft_pair):
    eng = served_engine
    draft, dparams = draft_pair
    with pytest.raises(ValueError, match="greedy"):
        eng.serve(speculative=True, spec_draft_model="self",
                  do_sample=True)
    with pytest.raises(ValueError, match="draft model"):
        eng.serve(speculative=True)
    with pytest.raises(ValueError, match="draft_params"):
        eng.serve(speculative=True, draft_module=draft)
    with pytest.raises(ValueError, match="spec_k"):
        eng.serve(speculative=True, spec_draft_model="self", spec_k=0)
    bad = Transformer(tiny_cfg(vocab_size=96, hidden_size=32))
    bad_params = bad.init(jax.random.key(2),
                          {"input_ids": jnp.zeros((1, 8), jnp.int32)})
    with pytest.raises(ValueError, match="vocab"):
        eng.serve(speculative=True, draft_module=bad,
                  draft_params=bad_params)


def test_spec_window_capacity_reserve(served_engine):
    """Each lane reserves spec_k-1 tail positions for the verify
    window's writes: a request that exactly fills the lane in non-spec
    mode must be REJECTED under speculation with a clear reason."""
    eng = served_engine
    p = np.ones((40,), np.int32)
    base = eng.serve()
    base.submit(p, max_new_tokens=24)           # 40+24 = 64: fits
    base.close()
    srv = eng.serve(speculative=True, spec_k=4, spec_draft_model="self")
    with pytest.raises(ValueError, match="speculative window reserve"):
        srv.submit(p, max_new_tokens=24)        # 40+24+3 > 64
    rid = srv.submit(p, max_new_tokens=21)      # 40+21+3 = 64: fits
    out = srv.drain()[rid]
    want = np.asarray(eng.generate(p[None], max_new_tokens=21))[0]
    np.testing.assert_array_equal(out, want)


def test_spec_observability_and_registry(served_engine):
    """Monitor events, stats keys and the concurrency registry cover the
    speculative path: Serving/spec_* events emitted, spec_* stats keys
    live (→ dstpu_serving_spec_* gauges via the /metrics stats sweep),
    and the draft-mirror fields declared in GUARDED_FIELDS exist on a
    speculative engine."""
    from deepspeed_tpu.inference.serving.concurrency import GUARDED_FIELDS

    class FakeMonitor:
        enabled = True

        def __init__(self):
            self.events = []

        def write_events(self, evs):
            self.events.extend(evs)

    eng = served_engine
    mon = FakeMonitor()
    srv = eng.serve(monitor=mon, speculative=True, spec_k=2,
                    spec_draft_model="self")
    for field in ("_draft_cache", "_draft_lanes"):
        assert field in GUARDED_FIELDS["ServingEngine"]
        assert hasattr(srv, field), field
    rng = np.random.default_rng(31)
    prompts, news = _mixed_workload(rng, n=4)
    for p, n in zip(prompts, news):
        srv.submit(p, max_new_tokens=n)
    srv.drain()
    names = {n for n, _, _ in mon.events}
    for want in ("Serving/spec_accept_rate",
                 "Serving/spec_tokens_per_dispatch",
                 "Serving/spec_draft_fraction"):
        assert want in names, names
    for key in ("spec_rounds", "spec_windows", "spec_committed_tokens",
                "spec_accept_rate", "spec_tokens_per_dispatch",
                "spec_draft_secs", "spec_verify_secs",
                "spec_draft_fraction"):
        assert key in srv.stats, key
    assert srv.stats["spec_rounds"] > 0
    assert 0.0 <= srv.stats["spec_accept_rate"] <= 1.0
    assert srv.stats["spec_draft_secs"] > 0.0
    rates = [v for n, v, _ in mon.events
             if n == "Serving/spec_accept_rate"]
    assert rates and all(0.0 <= v <= 1.0 for v in rates)
