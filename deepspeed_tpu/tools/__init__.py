"""Developer tooling (tpu-lint and friends).

The linter itself is dev-only, but ``tools.lint.hotpath`` IS a runtime
dependency: the engines import its (identity) ``@hot_path`` decorator to
mark their hot paths for static analysis.
"""
