"""SPMD pipeline parallelism over the ``pp`` mesh axis.

TPU-native re-design of the reference pipeline engine
(``runtime/pipe/engine.py:42``, ``schedule.py:135,189``, ``p2p.py:50,71``).
The reference interprets an instruction schedule per-rank and exchanges
activations with NCCL point-to-point sends.  Under single-controller SPMD the
whole schedule becomes ONE differentiable program:

* stages are shards of the ``pp`` axis inside ``shard_map`` (manual over
  ``pp`` only — dp/tp/sp/ep stay GSPMD-automatic);
* the schedule is a ``lax.scan`` over ticks; stage *s* works on microbatch
  ``m = t - s`` (the classic pipeline wavefront);
* activation transfer is one ``lax.ppermute`` per tick riding ICI neighbors
  (both halves of the reference's send/recv pair);
* the backward pipeline is **not hand-written**: differentiating the scan
  yields the reverse wavefront with reversed ppermutes automatically, with
  the per-tick stage inputs as residuals (= the reference's activation
  stash).  ``jax.checkpoint`` on the stage body gives the same memory
  behavior as its activation-checkpointed stages.

Schedule menu (``pipeline.schedule`` + ``max_in_flight_microbatches``):

* ``spmd_pipeline`` (fill_drain, default) — all M microbatches flow
  forward, then backward via autodiff.  Bubble ``(P-1)/(M+P-1)`` (the
  1F1B number — throughput-optimal), but the activation stash grows with
  M where the reference's ``TrainSchedule`` (1F1B, ``schedule.py:189``)
  bounds in-flight microbatches to ~P.
* ``spmd_pipeline_1f1b`` (schedule="1f1b") — hand-rolled interleaved
  one-forward-one-backward ticks with an O(P) input ring and in-region
  boundary layers, staged as three scans (P-1 forward-only warmup ticks,
  M combined fwd+bwd steady ticks, P-1 backward-only cooldown ticks) so
  the fill/drain ticks cost only their live half.  Bubble
  ``(P-1)/(M+P-1)`` — the reference ``TrainSchedule`` number (see
  ``one_f_one_b_phase_ticks``).  The memory-bounded mode of choice.
* chunked accumulation (``max_in_flight_microbatches=C``) — fill-drain
  over chunks of C; O(C) stash at a per-chunk bubble ``(P-1)/(C+P-1)``.
  Kept for when C must be tuned independently of P.

Activations may be arbitrary pytrees (e.g. ``(hidden, aux_loss)`` for MoE
trunks); every per-tick primitive is tree-mapped.
"""

import jax
import jax.numpy as jnp
from jax import lax

from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import PP_AXIS
from deepspeed_tpu.utils.jax_compat import shard_map as _shard_map


def spmd_pipeline(stage_fn, stacked_params, x0, num_micro, mesh,
                  pp_axis=PP_AXIS, remat_stage=True):
    """Run the pipelined forward: returns last-stage outputs ``[M, ...]``.

    ``stage_fn(stage_params, x) -> y`` maps one stage over one microbatch
    activation (a pytree; same structure/shapes in and out).
    ``stacked_params`` leaves have leading dim P (one slice per stage).
    ``x0``: pytree of ``[M, ...]`` microbatch activations entering stage 0.
    Fully differentiable.
    """
    n_stages = mesh.shape[pp_axis]
    if remat_stage:
        stage_fn = jax.checkpoint(stage_fn)

    # XLA's CPU backend (the simulated test mesh) crashes promoting bf16
    # all-reduces, which the region's backward emits for the replicated x0
    # cotangent.  Run the region in f32 on CPU; TPU stays bf16.
    cast_back = None
    if jax.default_backend() == "cpu" and any(
            l.dtype == jnp.bfloat16 for l in jax.tree.leaves(x0)):
        orig_dtypes = jax.tree.map(lambda l: l.dtype, x0)
        cast_back = orig_dtypes
        up = lambda t: jax.tree.map(
            lambda l: l.astype(jnp.float32)
            if l.dtype == jnp.bfloat16 else l, t)
        down = lambda t: jax.tree.map(
            lambda l, d: l.astype(d), t, orig_dtypes)
        inner_stage_fn = stage_fn
        stage_fn = lambda p, x: up(inner_stage_fn(p, down(x)))
        x0 = up(x0)

    def region(params, x0):
        sid = lax.axis_index(pp_axis)
        M = num_micro
        T = M + n_stages - 1
        params_local = jax.tree.map(lambda a: jnp.squeeze(a, 0), params)
        state0 = jax.tree.map(lambda l: jnp.zeros_like(l[0]), x0)

        fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(state, t):
            # receive previous stage's activation (stage 0 receives zeros)
            recv = jax.tree.map(
                lambda l: lax.ppermute(l, pp_axis, fwd_perm),
                state) if n_stages > 1 else state
            x_t = jax.tree.map(
                lambda l: lax.dynamic_index_in_dim(
                    l, jnp.minimum(t, M - 1), 0, keepdims=False), x0)
            inp = jax.tree.map(lambda a, b: jnp.where(sid == 0, a, b),
                               x_t, recv)
            m = t - sid
            active = jnp.logical_and(m >= 0, m < M)
            y = stage_fn(params_local, inp)
            y = jax.tree.map(
                lambda l: jnp.where(active, l, jnp.zeros_like(l)), y)
            # emit only the last stage's finished microbatches
            emit = jnp.logical_and(active, sid == n_stages - 1)
            out = jax.tree.map(
                lambda l: jnp.where(emit, l, jnp.zeros_like(l)), y)
            return y, out

        _, outs = lax.scan(tick, state0, jnp.arange(T))
        # outs[t] holds microbatch m = t-(P-1) on the last stage, zeros
        # elsewhere; psum over pp broadcasts last-stage values to all shards.
        outs = jax.tree.map(lambda l: l[n_stages - 1:], outs)
        if n_stages > 1:
            outs = lax.psum(outs, pp_axis)
        return outs

    in_specs = (jax.tree.map(lambda _: P(pp_axis), stacked_params), P())  # tpu-lint: disable=TL010 -- every stage needs the full microbatch stream: the region slices its own microbatch per tick in-program; batch sharding over edp runs manually inside (jax_compat axis_names fallback)
    out = _shard_map(
        region, mesh=mesh, in_specs=in_specs, out_specs=P(),
        axis_names=frozenset({pp_axis}), check_vma=False,
    )(stacked_params, x0)
    if cast_back is not None:
        out = jax.tree.map(lambda l, d: l.astype(d), out, cast_back)
    return out  # structure matches x0 (stage in == stage out)


def pipeline_bubble_fraction(num_micro, num_stages):
    return (num_stages - 1) / (num_micro + num_stages - 1)


def one_f_one_b_ticks(num_micro, num_stages):
    """Total scan-tick count of the interleaved 1F1B schedule: M + 2(P-1).

    See ``one_f_one_b_phase_ticks`` — the first P-1 ticks are
    forward-only and the last P-1 backward-only, so only the M steady
    ticks pay a full fwd+bwd slot and the wall-clock bubble is the
    reference ``TrainSchedule``'s (P-1)/(M+P-1)."""
    return num_micro + 2 * (num_stages - 1)


def one_f_one_b_phase_ticks(num_micro, num_stages):
    """Per-phase tick counts ``(warmup, steady, cooldown)`` of the
    interleaved 1F1B schedule: ``(P-1, M, P-1)``.

    The schedule's global tick grid is M + 2(P-1) ticks — stage *s*
    forwards microbatch ``t - s`` and backwards ``t - 2(P-1) + s`` — but
    no stage has live backward work before tick P-1 and none has live
    forward (or loss) work from tick M+P-1 on.  Staging the scan as three
    bodies (fwd-only / fwd+bwd / bwd-only) therefore drops only dead
    compute: warmup ticks cost one forward, cooldown ticks one backward,
    for a wall-clock of ``(P-1)·tf + M·(tf+tb) + (P-1)·tb =
    (M+P-1)·(tf+tb)`` — a bubble fraction of ``(P-1)/(M+P-1)``, exactly
    the reference's asynchronous 1F1B (``runtime/pipe/schedule.py:189``).
    It keeps 1F1B's O(P) activation stash and strictly beats chunked
    fill-drain at the same memory bound (M/C chunks × (C+P-1) full ticks;
    e.g. P=4, M=16, C=4: 28 chunked full ticks vs 19 equivalent here)."""
    return num_stages - 1, num_micro, num_stages - 1



def spmd_pipeline_1f1b(stage_fn, stacked_params, first_fn, first_params,
                       last_fn, last_params, inputs, labels, num_micro, mesh,
                       cotangent_seed=1.0, pp_axis=PP_AXIS):
    """Interleaved 1F1B pipeline with hand-rolled per-tick backward.

    TPU-native rendering of the reference ``TrainSchedule``
    (``runtime/pipe/schedule.py:189``): three ``lax.scan`` phases over one
    global grid of ``one_f_one_b_ticks(M, P)`` ticks inside ``shard_map``
    over ``pp`` — P-1 forward-only warmup ticks, M combined fwd+bwd steady
    ticks, P-1 backward-only cooldown ticks (``one_f_one_b_phase_ticks``)
    — matching the reference's (P-1)/(M+P-1) bubble.
    Like the reference's stage placement, the boundary layers live INSIDE
    the schedule — ``first_fn`` (embedding/pre chain) runs on stage 0 and
    ``last_fn`` (post chain + per-microbatch loss) on the last stage — so
    the only M-sized buffers in the program are the raw ``inputs``/
    ``labels`` (token ids), exactly as in the reference.  Per tick,
    stage *s*:

    * forward of microbatch ``m_f = t - s`` (stage 0 embeds
      ``inputs[m_f]`` via ``first_fn``; other stages receive via the
      forward ``ppermute``), stashing its input activation in a ring of
      depth ``2P-1`` — the O(P) bound that replaces autodiff's O(M)
      residual stash (stage 0 also rings the raw input for its pre-chain
      backward);
    * on the LAST stage, ``last_fn`` runs for ``m_l = t-(P-1)`` and its
      vjp seeds the backward wavefront THE SAME TICK (``cotangent_seed``
      is the loss-scale/mean factor);
    * backward of microbatch ``m_b = t - 2(P-1) + s``: the stage input is
      re-read from the ring and the stage re-linearized (``jax.vjp``) —
      rematerialized backward, exactly like the fill-drain mode's
      ``jax.checkpoint``-ed stages; the input-cotangent rides the reverse
      ``ppermute`` to stage s-1, where stage 0 instead backpropagates it
      through ``first_fn``.

    Returns ``(loss_sum, body_grads_stacked, first_grads, last_grads)``:
    ``loss_sum`` is the RAW sum of per-microbatch losses (unscaled); the
    gradient sums are scaled by ``cotangent_seed`` (seed with ``scale/M``
    to get gradients of ``mean(loss)*scale``).
    """
    n_stages = mesh.shape[pp_axis]
    M = num_micro
    R = 2 * n_stages - 1
    T = one_f_one_b_ticks(M, n_stages)

    def region(params, first_p, last_p, inputs, labels, seed):
        sid = lax.axis_index(pp_axis)
        last_sid = n_stages - 1
        params_local = jax.tree.map(lambda a: jnp.squeeze(a, 0), params)

        in0 = jax.tree.map(lambda l: l[0], inputs)
        act0 = jax.eval_shape(lambda p, i: first_fn(p, i), first_p, in0)
        act0 = jax.tree.map(lambda a: jnp.zeros(a.shape, a.dtype), act0)
        ring_act0 = jax.tree.map(
            lambda l: jnp.zeros((R, *l.shape), l.dtype), act0)
        ring_in0 = jax.tree.map(
            lambda l: jnp.zeros((R, *l.shape[1:]), l.dtype), inputs)
        zeros_f32 = lambda t: jax.tree.map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.float32), t)
        gbody0, gfirst0, glast0 = (zeros_f32(params_local),
                                   zeros_f32(first_p), zeros_f32(last_p))

        fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]
        bwd_perm = [(i + 1, i) for i in range(n_stages - 1)]

        def at(tree, idx):
            return jax.tree.map(
                lambda l: lax.dynamic_index_in_dim(l, idx, 0, keepdims=False),
                tree)

        def put(tree, val, idx):
            return jax.tree.map(
                lambda l, v: lax.dynamic_update_index_in_dim(
                    l, v.astype(l.dtype), idx, 0), tree, val)

        def mask(tree, cond):
            return jax.tree.map(
                lambda l: jnp.where(cond, l, jnp.zeros_like(l)), tree)

        # NOTE control-flow discipline: every lax.cond predicate below
        # depends on the tick counter t ONLY (globally uniform), never
        # on the stage id — a sid-dependent branch containing the
        # tp-sharded head/embedding diverged the pp groups' collective
        # sequences and deadlocked the mesh.  sid-dependence is
        # expressed with jnp.where masks on uniformly-executed compute.

        def fwd_unit(y_state, ring_act, ring_in, t):
            recv = jax.tree.map(
                lambda l: lax.ppermute(l, pp_axis, fwd_perm),
                y_state) if n_stages > 1 else y_state
            m_f = t - sid
            f_active = jnp.logical_and(m_f >= 0, m_f < M)
            in_m = at(inputs, jnp.clip(m_f, 0, M - 1))
            x_first = lax.cond(t < M,
                               lambda: first_fn(first_p, in_m),
                               lambda: jax.tree.map(jnp.zeros_like, recv))
            x_in = jax.tree.map(
                lambda a, b: jnp.where(sid == 0, a, b), x_first, recv)
            y = mask(stage_fn(params_local, x_in), f_active)
            ring_act = put(ring_act, x_in, t % R)
            ring_in = put(ring_in, in_m, t % R)
            return y, ring_act, ring_in

        def seed_unit(t, y):
            # loss + backward seed on the last stage; steady ticks only
            # (t in [P-1, M+P-2] ⇒ m_l in [0, M-1], always in-window)
            m_l = t - last_sid
            l_active = jnp.logical_and(m_l >= 0, m_l < M)
            lab = at(labels, jnp.clip(m_l, 0, M - 1))
            loss_m, lvjp = jax.vjp(
                lambda lp, yy: last_fn(lp, yy, lab), last_p, y)
            dlast, dy = lvjp(seed.astype(loss_m.dtype))
            on_last = jnp.logical_and(sid == last_sid, l_active)
            return jnp.where(on_last, loss_m.astype(jnp.float32), 0.0), \
                mask(jax.tree.map(lambda g: g.astype(jnp.float32),
                                  dlast), on_last), \
                mask(dy, on_last)

        def bwd_unit(dx_state, ring_act, ring_in, gbody, gfirst,
                     dy_seed, y_ref, t):
            brecv = jax.tree.map(
                lambda l: lax.ppermute(l, pp_axis, bwd_perm),
                dx_state) if n_stages > 1 else dx_state
            m_b = t - 2 * (n_stages - 1) + sid
            b_active = jnp.logical_and(m_b >= 0, m_b < M)
            dy_in = jax.tree.map(
                lambda a, b: jnp.where(sid == last_sid, a, b),
                dy_seed, brecv)
            # the stashed input of this stage's forward of m_b (tick
            # t_f = t - 2(P-1) + 2s); re-linearize = rematerialized backward
            t_f = t - 2 * (n_stages - 1) + 2 * sid
            slot = jnp.clip(t_f, 0, T - 1) % R
            x_b = at(ring_act, slot)
            _, svjp = jax.vjp(stage_fn, params_local, x_b)
            dp, dx = svjp(jax.tree.map(
                lambda l, yl: l.astype(yl.dtype), dy_in, y_ref))
            gbody = jax.tree.map(
                lambda g, d: g + jnp.where(b_active,
                                           d.astype(jnp.float32), 0.0),
                gbody, dp)
            dx = mask(dx, b_active)

            # stage 0 backpropagates its input-cotangent through first_fn
            # (uniform-predicate window; sid-dependence via masks, as above)
            b0_window = jnp.logical_and(t >= 2 * (n_stages - 1),
                                        t < 2 * (n_stages - 1) + M)

            def first_b_branch():
                in_b = at(ring_in, slot)
                _, fvjp = jax.vjp(lambda fp: first_fn(fp, in_b), first_p)
                (dfp,) = fvjp(jax.tree.map(
                    lambda l, xl: l.astype(xl.dtype), dx, x_b))
                return mask(jax.tree.map(
                    lambda g: g.astype(jnp.float32), dfp),
                    jnp.logical_and(sid == 0, b_active))

            dfirst_m = lax.cond(b0_window, first_b_branch,
                                lambda: zeros_f32(first_p))
            gfirst = jax.tree.map(jnp.add, gfirst, dfirst_m)
            return dx, gbody, gfirst

        # Three scan phases over one global tick grid (see
        # one_f_one_b_phase_ticks): ticks [0, P-1) have no live backward
        # anywhere and ticks [M+P-1, T) no live forward/loss anywhere, so
        # the warmup body is fwd-only (costs tf) and the cooldown body
        # bwd-only (costs tb) — the wall-clock bubble is (P-1)/(M+P-1),
        # the reference TrainSchedule's.
        def warmup_tick(carry, t):
            (y_state, dx_state, ring_act, ring_in, gbody, gfirst, glast,
             loss_acc) = carry
            y, ring_act, ring_in = fwd_unit(y_state, ring_act, ring_in, t)
            return (y, dx_state, ring_act, ring_in, gbody, gfirst, glast,
                    loss_acc), None

        def steady_tick(carry, t):
            (y_state, dx_state, ring_act, ring_in, gbody, gfirst, glast,
             loss_acc) = carry
            y, ring_act, ring_in = fwd_unit(y_state, ring_act, ring_in, t)
            loss_m, dlast_m, dy_seed = seed_unit(t, y)
            loss_acc = loss_acc + loss_m
            glast = jax.tree.map(jnp.add, glast, dlast_m)
            dx, gbody, gfirst = bwd_unit(dx_state, ring_act, ring_in,
                                         gbody, gfirst, dy_seed, y, t)
            return (y, dx, ring_act, ring_in, gbody, gfirst, glast,
                    loss_acc), None

        def cooldown_tick(carry, t):
            (y_state, dx_state, ring_act, ring_in, gbody, gfirst, glast,
             loss_acc) = carry
            dy_zero = jax.tree.map(jnp.zeros_like, y_state)
            dx, gbody, gfirst = bwd_unit(dx_state, ring_act, ring_in,
                                         gbody, gfirst, dy_zero, y_state, t)
            return (y_state, dx, ring_act, ring_in, gbody, gfirst, glast,
                    loss_acc), None

        carry = (act0, jax.tree.map(jnp.zeros_like, act0), ring_act0,
                 ring_in0, gbody0, gfirst0, glast0,
                 jnp.zeros((), jnp.float32))
        warm, steady, cool = one_f_one_b_phase_ticks(M, n_stages)
        carry, _ = lax.scan(warmup_tick, carry, jnp.arange(warm))
        carry, _ = lax.scan(steady_tick, carry,
                            jnp.arange(warm, warm + steady))
        carry, _ = lax.scan(cooldown_tick, carry,
                            jnp.arange(warm + steady, T))
        (_, _, _, _, gbody, gfirst, glast, loss_acc) = carry
        # loss/last-grads live on the last stage, first-grads on stage 0;
        # psum broadcasts each to every pp shard
        if n_stages > 1:
            loss_acc = lax.psum(loss_acc, pp_axis)
            glast = lax.psum(glast, pp_axis)
            gfirst = lax.psum(gfirst, pp_axis)
        gbody = jax.tree.map(lambda g: g[None], gbody)
        return loss_acc, gbody, gfirst, glast

    in_specs = (jax.tree.map(lambda _: P(pp_axis), stacked_params),
                P(), P(), P(), P(), P())  # tpu-lint: disable=TL010 -- the 1F1B region consumes the full [M, ...] microbatch stream and slices per tick in-program (stages see different microbatches at different ticks); edp batch sharding runs manually inside the region
    out_specs = (P(), jax.tree.map(lambda _: P(pp_axis), stacked_params),
                 P(), P())
    return _shard_map(
        region, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        axis_names=frozenset({pp_axis}), check_vma=False,
    )(stacked_params, first_params, last_params, inputs, labels,
      jnp.asarray(cotangent_seed, jnp.float32))


def stack_stage_params(per_layer_params, num_stages):
    """Group L per-layer param trees (identical structure) into
    ``[P, L/P, ...]`` stacked pytrees for the SPMD pipeline."""
    L = len(per_layer_params)
    if L % num_stages != 0:
        raise ValueError(f"{L} body layers not divisible by {num_stages} stages")
    per_stage = L // num_stages
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *per_layer_params)
    return jax.tree.map(
        lambda a: a.reshape(num_stages, per_stage, *a.shape[1:]), stacked)
