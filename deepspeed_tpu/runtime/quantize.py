"""Training-time mixed-precision quantization (MoQ).

Capability parity with reference ``deepspeed/runtime/quantize.py:14``
(``Quantizer``): progressively quantize weights during training on a
period/eigenvalue-driven schedule, shrinking target bit-width from
``q_start_bits`` to ``q_target_bits``; supports symmetric/asymmetric and a
mixed-fp16 ratio ramp.  Operates functionally on param pytrees (returns new
params) rather than mutating module tensors.
"""

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.quantizer.kernels import (
    quantize as q_kernel, dequantize as dq_kernel, quantize_ternary,
    quantize_binary)
from deepspeed_tpu.utils.logging import logger

TWO_D_PARAMS = 6


class Quantizer:

    def __init__(self, q_groups=1, q_mixed_fp16=False, q_change_ratio=0.001,
                 q_type=0, q_rounding=0, q_verbose=False, q_eigenvalue=False,
                 use_quantizer_kernel=True, layer_num=0,
                 q_start_bits=16, q_target_bits=8, q_period=1000):
        self.q_groups = q_groups
        self.q_mixed_fp16 = q_mixed_fp16
        self.q_change_ratio = q_change_ratio
        self.q_type = q_type           # 0 = symmetric, 1 = asymmetric
        self.q_rounding = q_rounding   # 0 = nearest (stochastic folds to nearest on TPU)
        self.q_verbose = q_verbose
        self.q_eigenvalue = q_eigenvalue
        self.use_quantizer_kernel = use_quantizer_kernel
        self.layer_num = layer_num
        self.q_start_bits = q_start_bits
        self.q_target_bits = q_target_bits
        self.q_period = q_period
        self.qsteps = 0
        self.quantize_real_ratio = 1.0
        # per-layer current bit-width state
        self.current_bits = {}

    def any_precision_switch(self):
        """True if any layer still has bits to shed (reference ``:39``)."""
        if not self.current_bits:
            return self.q_start_bits > self.q_target_bits
        return any(b > self.q_target_bits for b in self.current_bits.values())

    def step(self):
        self.qsteps += 1

    def _bits_for(self, index, factor=1):
        start = self.current_bits.get(index, self.q_start_bits)
        # shed one bit every q_period steps (eigenvalue factor can accelerate)
        if start > self.q_target_bits and \
                self.qsteps >= self.q_period * factor * max(1, start - self.q_target_bits):
            start -= 1
            if self.q_verbose:
                logger.info(f"[MoQ] layer {index} -> {start} bits at step {self.qsteps}")
        self.current_bits[index] = start
        return start

    def compute_quantization(self, x, index=0, factor=1):
        """Quantize-dequantize one tensor at its current scheduled bit-width
        (reference ``:129``)."""
        bits = self._bits_for(index, factor)
        if bits >= 16:
            return x
        groups = min(self.q_groups, max(1, x.size))
        while x.size % groups != 0:
            groups -= 1
        if bits == 2:
            q = quantize_ternary(x, groups).reshape(x.shape).astype(x.dtype)
        elif bits == 1:
            q = quantize_binary(x, groups).reshape(x.shape).astype(x.dtype)
        else:
            qv, scale, zero = q_kernel(x, groups, bits,
                                       symmetric=(self.q_type == 0))
            q = dq_kernel(qv, scale, zero, bits,
                          symmetric=(self.q_type == 0),
                          shape=x.shape).astype(x.dtype)
        if self.q_mixed_fp16 and self.quantize_real_ratio > 0.0:
            q = self.quantize_real_ratio * x + (1 - self.quantize_real_ratio) * q
        return q

    def update_fp16_ratio(self):
        if self.q_mixed_fp16:
            self.quantize_real_ratio = max(
                0.0, self.quantize_real_ratio - self.q_change_ratio)

    def quantize(self, params, overflow=False, eigenvalue_enabled=False,
                 block_eigenvalue=None):
        """Quantize a parameter pytree in place of the reference's
        parameter_group loop (``:51``).  Skips on overflow steps (unstable
        scales).  2-D matmul weights only — biases/norms stay high precision
        (reference quantizes `dim>1` params only)."""
        if overflow and not eigenvalue_enabled:
            return params
        self.step()
        block_eigenvalue = block_eigenvalue or {}
        leaves, treedef = jax.tree.flatten(params)
        out = []
        idx = 0
        for leaf in leaves:
            if leaf.ndim > 1 and leaf.size >= TWO_D_PARAMS:
                ev = block_eigenvalue.get(idx)
                factor = 1 if ev is None else max(1, int(1.0 / max(ev, 1e-6)))
                out.append(self.compute_quantization(leaf, idx, factor))
                idx += 1
            else:
                out.append(leaf)
        self.update_fp16_ratio()
        return jax.tree.unflatten(treedef, out)


class Eigenvalue:
    """Power-iteration estimate of per-block loss-curvature eigenvalues,
    driving the MoQ schedule (reference ``runtime/eigenvalue.py:12``).

    The reference autograd-hooks a torch module; here ``compute_eigenvalue``
    takes a loss function over params and a param pytree, and runs
    Hessian-vector-product power iteration with ``jax.jvp`` over
    ``jax.grad`` — fully jittable.
    """

    def __init__(self, verbose=False, max_iter=100, tol=1e-2, stability=1e-6,
                 gas_boundary_resolution=1, layer_name="", layer_num=0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num

    def normalize(self, v):
        norm = jnp.sqrt(sum(jnp.vdot(x, x) for x in jax.tree.leaves(v)).real)
        norm = jnp.maximum(norm, self.stability)
        return jax.tree.map(lambda x: x / norm, v), norm

    def compute_eigenvalue(self, loss_fn, params, seed=0):
        """Dominant Hessian eigenvalue of ``loss_fn(params)`` via power
        iteration on HVPs.  Returns a float."""
        grad_fn = jax.grad(loss_fn)

        def hvp(v):
            return jax.jvp(grad_fn, (params,), (v,))[1]

        key = jax.random.key(seed)
        keys = jax.random.split(key, len(jax.tree.leaves(params)))
        leaves, treedef = jax.tree.flatten(params)
        v = jax.tree.unflatten(treedef, [
            jax.random.normal(k, l.shape, jnp.float32)
            for k, l in zip(keys, leaves)])
        v, _ = self.normalize(v)
        eig = 0.0
        for _ in range(self.max_iter):
            hv = hvp(v)
            v, norm = self.normalize(hv)
            new_eig = float(norm)
            if eig > 0 and abs(new_eig - eig) / eig < self.tol:
                eig = new_eig
                break
            eig = new_eig
        return eig

    def post_process(self, value_list):
        """Replace zeros/NaN with the max eigenvalue, normalize to max=1
        (reference ``:147``)."""
        import math
        vals = [0.0 if (v is None or math.isnan(v)) else v for v in value_list]
        mx = max(vals) if vals else 1.0
        if mx <= 0:
            return [1.0 for _ in vals]
        return [(v if v > 0 else mx) / mx for v in vals]
