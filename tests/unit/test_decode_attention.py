"""Pallas decode-attention kernel vs the XLA cached_attention reference.

Caches are S-major with flattened heads — [B, S_max, KVH*D] (layer-stacked:
[L, B, S_max, KVH*D]) — the decode kernel's full-lane-width DMA layout.
Helpers below build them from head-major [B, KVH, S, D] test data.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.transformer import cached_attention
from deepspeed_tpu.ops.transformer.decode_attention import decode_attention


def to_smajor(head_major):
    """[.., KVH, S, D] → [.., S, KVH*D]"""
    *lead, KVH, S, D = head_major.shape
    x = jnp.moveaxis(head_major, -3, -2)                 # [.., S, KVH, D]
    return x.reshape(*lead, S, KVH * D)


def xla_cached_attention(*args, **kwargs):
    """cached_attention forced down the einsum path — WITHOUT this guard the
    S==1 dispatch would route both sides of every comparison through the
    kernel under test."""
    os.environ["DSTPU_DISABLE_FLASH"] = "1"
    try:
        return cached_attention(*args, **kwargs)
    finally:
        del os.environ["DSTPU_DISABLE_FLASH"]


@pytest.mark.parametrize("kvh", [8, 2])   # MHA + GQA
@pytest.mark.parametrize("length", [1, 17, 64])
def test_decode_matches_cached_attention(kvh, length):
    B, H, D, S_max = 2, 8, 16, 64
    rng = np.random.default_rng(length * 10 + kvh)
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)
    k = jnp.zeros((B, kvh, S_max, D), jnp.float32)
    v = jnp.zeros((B, kvh, S_max, D), jnp.float32)
    k = k.at[:, :, :length].set(rng.standard_normal((B, kvh, length, D)))
    v = v.at[:, :, :length].set(rng.standard_normal((B, kvh, length, D)))
    k, v = to_smajor(k), to_smajor(v)
    pos = jnp.full((B, 1), length - 1, jnp.int32)
    want = np.asarray(xla_cached_attention(q, k, v, pos))          # [B,1,H,D]
    got = np.asarray(decode_attention(
        q[:, 0], k, v, jnp.full((B,), length, jnp.int32)))     # [B,H,D]
    np.testing.assert_allclose(got, want[:, 0], rtol=2e-5, atol=2e-5)


def test_decode_per_batch_lengths():
    """Each batch row masks by its own cache length."""
    B, H, D, S_max = 3, 4, 8, 32
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S_max, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S_max, D)), jnp.float32)
    ks, vs = to_smajor(k), to_smajor(v)
    lengths = jnp.asarray([1, 16, 32], jnp.int32)
    got = np.asarray(decode_attention(q, ks, vs, lengths))
    for b, L in enumerate([1, 16, 32]):
        pos = jnp.asarray([[L - 1]], jnp.int32)
        want = np.asarray(xla_cached_attention(
            q[b:b + 1, None], ks[b:b + 1], vs[b:b + 1], pos))[0, 0]
        np.testing.assert_allclose(got[b], want, rtol=2e-5, atol=2e-5)


def test_decode_blocked_cache():
    """Cache longer than one KV block exercises the online accumulation."""
    B, H, D, S_max = 1, 8, 16, 2048
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S_max, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S_max, D)), jnp.float32)
    ks, vs = to_smajor(k), to_smajor(v)
    L = 1500
    got = np.asarray(decode_attention(q, ks, vs,
                                      jnp.asarray([L], jnp.int32),
                                      block_k=512))
    want = np.asarray(xla_cached_attention(
        q[:, None], ks, vs, jnp.asarray([[L - 1]], jnp.int32)))[:, 0]
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_decode_stacked_layer_indexing():
    """The layer-stacked cache path (kernel DMAs the layer's blocks via a
    scalar-prefetch index map — no per-layer slice materializes) is
    bit-identical to slicing the layer out first."""
    rng = np.random.default_rng(0)
    L, B, KVH, S, D, H = 3, 2, 4, 64, 32, 8
    k = jnp.asarray(rng.standard_normal((L, B, KVH, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((L, B, KVH, S, D)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    ks, vs = to_smajor(k), to_smajor(v)
    lengths = jnp.asarray([30, 50], jnp.int32)
    for li in range(L):
        stacked = decode_attention(q, ks, vs, lengths, layer=jnp.asarray(li))
        sliced = decode_attention(q, ks[li], vs[li], lengths)
        np.testing.assert_array_equal(np.asarray(stacked), np.asarray(sliced))
    # stacked caches demand a layer index
    with pytest.raises(ValueError):
        decode_attention(q, ks, vs, lengths)


def quantize_smajor(cache_smajor, kvh):
    """[.., S, KVH*D] float → (int8 payload, [.., S, KVH] scales)."""
    *lead, S, KVHD = cache_smajor.shape
    d = KVHD // kvh
    r = np.asarray(cache_smajor).reshape(*lead, S, kvh, d)
    s = np.max(np.abs(r), axis=-1) / 127.0
    safe = np.where(s == 0.0, 1.0, s)
    pay = np.clip(np.round(r / safe[..., None]), -127, 127)
    return (jnp.asarray(pay.reshape(*lead, S, KVHD), jnp.int8),
            jnp.asarray(s, jnp.float32))


@pytest.mark.parametrize("kvh", [8, 2])   # MHA + GQA
def test_decode_int8_kv_matches_dequantized_reference(kvh):
    """int8-KV decode: the kernel's in-tile dequant (k-scale on the score
    tile, v-scale on the probability tile) must match attention computed
    on the explicitly dequantized payload — same ints in, same math."""
    B, H, D, S_max, L = 2, 8, 16, 96, 70
    rng = np.random.default_rng(kvh)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k = rng.standard_normal((B, kvh, S_max, D)) * 3.0
    v = rng.standard_normal((B, kvh, S_max, D))
    ks, vs = to_smajor(jnp.asarray(k, jnp.float32)), \
        to_smajor(jnp.asarray(v, jnp.float32))
    kq, ksc = quantize_smajor(ks, kvh)
    vq, vsc = quantize_smajor(vs, kvh)
    lengths = jnp.asarray([L, 31], jnp.int32)
    got = np.asarray(decode_attention(q, kq, vq, lengths, block_k=32,
                                      k_scale=ksc, v_scale=vsc))
    # reference on the dequantized payload through the dense path
    kdq = (np.asarray(kq, np.float32).reshape(B, S_max, kvh, D)
           * np.asarray(ksc)[..., None]).reshape(B, S_max, kvh * D)
    vdq = (np.asarray(vq, np.float32).reshape(B, S_max, kvh, D)
           * np.asarray(vsc)[..., None]).reshape(B, S_max, kvh * D)
    for b, Lb in enumerate([L, 31]):
        pos = jnp.asarray([[Lb - 1]], jnp.int32)
        want = np.asarray(xla_cached_attention(
            q[b:b + 1, None], jnp.asarray(kdq[b:b + 1]),
            jnp.asarray(vdq[b:b + 1]), pos))[0, 0]
        np.testing.assert_allclose(got[b], want, rtol=2e-5, atol=2e-5)


def test_decode_int8_kv_stacked_layer():
    """Layer-stacked int8 cache: scale blocks index the layer the same way
    the payload blocks do."""
    rng = np.random.default_rng(3)
    Lyr, B, KVH, S, D, H = 3, 2, 4, 64, 32, 8
    k = jnp.asarray(rng.standard_normal((Lyr, B, KVH, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((Lyr, B, KVH, S, D)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    ks, vs = to_smajor(k), to_smajor(v)
    kq, ksc = quantize_smajor(ks, KVH)
    vq, vsc = quantize_smajor(vs, KVH)
    lengths = jnp.asarray([30, 50], jnp.int32)
    for li in range(Lyr):
        stacked = decode_attention(q, kq, vq, lengths,
                                   layer=jnp.asarray(li),
                                   k_scale=ksc, v_scale=vsc)
        sliced = decode_attention(q, kq[li], vq[li], lengths,
                                  k_scale=ksc[li], v_scale=vsc[li])
        np.testing.assert_array_equal(np.asarray(stacked),
                                      np.asarray(sliced))


def test_int8_kv_generation_end_to_end():
    """kv_cache_quant through the full model decode: logits after several
    cached decode steps stay close to the bf16-cache logits (int8
    per-(position, head) scales keep the attention error ~1%)."""
    from deepspeed_tpu.models.transformer import Transformer, TransformerConfig
    ids = np.random.default_rng(0).integers(0, 64, (2, 12)).astype(np.int32)

    def run(quant):
        cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=2,
                                num_heads=4, max_seq_len=32, dtype="float32",
                                use_flash_attention=False, scan_layers=False,
                                kv_cache_quant=quant)
        model = Transformer(cfg)
        params = model.init(jax.random.key(0), {"input_ids": ids})
        cache = model.init_cache(2, 32)
        if quant:
            assert cache["k"].dtype == jnp.int8 and "k_scale" in cache
        logits, cache = model.apply(params, jnp.asarray(ids), cache, 0,
                                    method=Transformer.decode)
        outs = [np.asarray(logits[:, -1])]
        tok = jnp.argmax(logits[:, -1], -1)[:, None].astype(jnp.int32)
        for step in range(3):
            logits, cache = model.apply(params, tok, cache, 12 + step,
                                        method=Transformer.decode)
            outs.append(np.asarray(logits[:, -1]))
            tok = jnp.argmax(logits[:, -1], -1)[:, None].astype(jnp.int32)
        return np.stack(outs)

    ref = run(False)
    got = run(True)
    err = np.abs(got - ref).mean()
    assert err < 0.02 * np.abs(ref).mean() + 1e-3, err


@pytest.mark.parametrize("kvh", [8, 2])
def test_decode_int8_mxu_matmuls_accuracy(kvh):
    """Full-int8 MXU decode (int8_matmuls): q and the probability rows are
    additionally quantized so the score and PV matmuls run int8×int8 —
    the output must stay within ~1% of the exact dequantized-reference
    attention."""
    B, H, D, S_max, L = 2, 8, 16, 96, 70
    rng = np.random.default_rng(kvh + 100)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k = rng.standard_normal((B, kvh, S_max, D)) * 2.0
    v = rng.standard_normal((B, kvh, S_max, D))
    ks, vs = to_smajor(jnp.asarray(k, jnp.float32)), \
        to_smajor(jnp.asarray(v, jnp.float32))
    kq, ksc = quantize_smajor(ks, kvh)
    vq, vsc = quantize_smajor(vs, kvh)
    lengths = jnp.asarray([L, 31], jnp.int32)
    exact = np.asarray(decode_attention(q, kq, vq, lengths, block_k=32,
                                        k_scale=ksc, v_scale=vsc))
    fast = np.asarray(decode_attention(q, kq, vq, lengths, block_k=32,
                                       k_scale=ksc, v_scale=vsc,
                                       int8_matmuls=True))
    err = np.abs(fast - exact).mean() / (np.abs(exact).mean() + 1e-9)
    assert err < 0.015, err
    # int8_matmuls without quantized caches is rejected
    with pytest.raises(ValueError, match="int8_matmuls"):
        decode_attention(q, ks, vs, lengths, int8_matmuls=True)


@pytest.mark.parametrize("window", [8, 40, 200])
def test_decode_sliding_window(window):
    """Sliding-window decode (mistral-style) in-kernel: only the last
    `window` positions before the query are live — matches the dense
    cached_attention window path per batch row, including per-batch
    lengths and windows larger than the live cache."""
    B, H, D, S_max = 3, 4, 16, 128
    rng = np.random.default_rng(window)
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S_max, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S_max, D)), jnp.float32)
    ks, vs = to_smajor(k), to_smajor(v)
    lens = [5, 64, 128]
    got = np.asarray(decode_attention(q[:, 0], ks, vs,
                                      jnp.asarray(lens, jnp.int32),
                                      block_k=32, window=window))
    for b, L in enumerate(lens):
        pos = jnp.asarray([[L - 1]], jnp.int32)
        want = np.asarray(xla_cached_attention(
            q[b:b + 1], ks[b:b + 1], vs[b:b + 1], pos,
            window=window))[0, 0]
        np.testing.assert_allclose(got[b], want, rtol=2e-5, atol=2e-5)


def test_decode_short_lengths_exact():
    """Dead-region DMA pinning (indices past `lengths` pin to the last live
    block so Mosaic skips their copies) must not change results, including
    degenerate lengths and block-boundary lengths."""
    rng = np.random.default_rng(0)
    B, KVH, S, D, H = 4, 4, 256, 32, 4
    k = jnp.asarray(rng.standard_normal((B, KVH, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, KVH, S, D)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    ks, vs = to_smajor(k), to_smajor(v)
    for lens in ([1, 5, 64, 65], [256, 128, 127, 2]):
        lengths = jnp.asarray(lens, jnp.int32)
        got = np.asarray(decode_attention(q, ks, vs, lengths, block_k=64))
        for b in range(B):
            for h in range(KVH):
                s = (np.asarray(q[b, h]) @ np.asarray(k[b, h]).T) / np.sqrt(D)
                s[lens[b]:] = -np.inf
                p = np.exp(s - s.max())
                p /= p.sum()
                ref = p @ np.asarray(v[b, h])
                np.testing.assert_allclose(got[b, h], ref, rtol=2e-5,
                                           atol=2e-5)


# --------------------------------------------------------------------- #
# chunk_prefill_attention — the chunked-prefill kernel
# --------------------------------------------------------------------- #

from deepspeed_tpu.ops.transformer.decode_attention import \
    chunk_prefill_attention


@pytest.mark.parametrize("kvh", [8, 2])   # MHA + GQA
@pytest.mark.parametrize("start", [0, 24])
def test_chunk_prefill_matches_cached_attention(kvh, start):
    """A C-token chunk at offset ``start`` must match the dense cached
    path (causal within the chunk + full attention to the prefix)."""
    B, H, D, S_max, C = 2, 8, 16, 64, 16
    rng = np.random.default_rng(start * 10 + kvh)
    q = jnp.asarray(rng.standard_normal((B, C, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, kvh, S_max, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, kvh, S_max, D)), jnp.float32)
    ks, vs = to_smajor(k), to_smajor(v)
    pos = start + jnp.broadcast_to(jnp.arange(C), (B, C))
    want = np.asarray(xla_cached_attention(q, ks, vs, pos.astype(jnp.int32)))
    got = np.asarray(chunk_prefill_attention(
        q, ks, vs, jnp.full((B,), start, jnp.int32)))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_chunk_prefill_blocked_and_per_row_starts():
    """Multi-block cache + per-row starts: each row's chunk begins at its
    own offset (padded-prompt chunked prefill)."""
    B, H, D, S_max, C = 2, 4, 8, 256, 32
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.standard_normal((B, C, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S_max, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S_max, D)), jnp.float32)
    ks, vs = to_smajor(k), to_smajor(v)
    starts = jnp.asarray([64, 128], jnp.int32)
    got = np.asarray(chunk_prefill_attention(q, ks, vs, starts, block_k=64))
    for b in range(B):
        pos = (int(starts[b]) + jnp.arange(C))[None].astype(jnp.int32)
        want = np.asarray(xla_cached_attention(
            q[b:b + 1], ks[b:b + 1], vs[b:b + 1], pos))[0]
        np.testing.assert_allclose(got[b], want, rtol=2e-4, atol=2e-4)


def test_chunk_prefill_stacked_int8():
    """Layer-stacked int8 cache through the chunk kernel == dense math on
    the dequantized payload."""
    rng = np.random.default_rng(3)
    L, B, KVH, S_max, D, H, C = 2, 2, 4, 96, 16, 8, 16
    k = rng.standard_normal((L, B, KVH, S_max, D)) * 3.0
    v = rng.standard_normal((L, B, KVH, S_max, D))
    ks = to_smajor(jnp.asarray(k, jnp.float32))
    vs = to_smajor(jnp.asarray(v, jnp.float32))
    kq, ksc = quantize_smajor(ks, KVH)
    vq, vsc = quantize_smajor(vs, KVH)
    q = jnp.asarray(rng.standard_normal((B, C, H, D)), jnp.float32)
    starts = jnp.asarray([32, 5], jnp.int32)
    for li in range(L):
        got = np.asarray(chunk_prefill_attention(
            q, kq, vq, starts, block_k=32, layer=jnp.asarray(li),
            k_scale=ksc, v_scale=vsc))
        kdq = (np.asarray(kq[li], np.float32).reshape(B, S_max, KVH, D)
               * np.asarray(ksc[li])[..., None]).reshape(B, S_max, KVH * D)
        vdq = (np.asarray(vq[li], np.float32).reshape(B, S_max, KVH, D)
               * np.asarray(vsc[li])[..., None]).reshape(B, S_max, KVH * D)
        for b in range(B):
            pos = (int(starts[b]) + jnp.arange(C))[None].astype(jnp.int32)
            want = np.asarray(xla_cached_attention(
                q[b:b + 1], jnp.asarray(kdq[b:b + 1]),
                jnp.asarray(vdq[b:b + 1]), pos))[0]
            np.testing.assert_allclose(got[b], want, rtol=2e-4, atol=2e-4)


# --------------------------------------------------------------------- #
# fused in-kernel cache write (new_k/new_v)
# --------------------------------------------------------------------- #

def _write_rows_ref(cache, rows, lengths):
    """Reference: write rows [B, KVH*D] at per-row positions lengths-1."""
    out = np.asarray(cache).copy()
    for b in range(out.shape[0]):
        out[b, int(lengths[b]) - 1] = rows[b]
    return jnp.asarray(out)


@pytest.mark.parametrize("kvh", [4, 2])
def test_fused_write_matches_prewrite(kvh):
    """decode_attention(new_k=, new_v=) must equal pre-writing the row
    then attending — same outputs AND same cache contents afterward."""
    B, H, D, S_max = 3, 4, 16, 128
    rng = np.random.default_rng(kvh)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, kvh, S_max, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, kvh, S_max, D)), jnp.float32)
    ks, vs = to_smajor(k), to_smajor(v)
    lengths = jnp.asarray([5, 64, 128], jnp.int32)   # incl. a block edge
    kn = rng.standard_normal((B, kvh, D)).astype(np.float32)
    vn = rng.standard_normal((B, kvh, D)).astype(np.float32)
    # reference: write first, then plain kernel
    ks_w = _write_rows_ref(ks, kn.reshape(B, kvh * D), lengths)
    vs_w = _write_rows_ref(vs, vn.reshape(B, kvh * D), lengths)
    want = np.asarray(decode_attention(q, ks_w, vs_w, lengths, block_k=32))
    got, ko, vo = decode_attention(q, ks, vs, lengths, block_k=32,
                                   new_k=jnp.asarray(kn),
                                   new_v=jnp.asarray(vn))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(ko), np.asarray(ks_w),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(vo), np.asarray(vs_w),
                               rtol=1e-6, atol=1e-6)


def test_fused_write_int8_stacked():
    """Quantized + layer-stacked fused write: payload/scale rows written
    by the kernel must match the model's quantization, and the attention
    must match the unfused write-then-read path."""
    rng = np.random.default_rng(0)
    L, B, KVH, S_max, D, H = 2, 2, 4, 96, 16, 8
    k = rng.standard_normal((L, B, KVH, S_max, D)) * 3.0
    v = rng.standard_normal((L, B, KVH, S_max, D))
    ksm = to_smajor(jnp.asarray(k, jnp.float32))
    vsm = to_smajor(jnp.asarray(v, jnp.float32))
    kq, ksc = quantize_smajor(ksm, KVH)
    vq, vsc = quantize_smajor(vsm, KVH)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    lengths = jnp.asarray([33, 80], jnp.int32)
    kn = jnp.asarray(rng.standard_normal((B, KVH, D)) * 3.0, jnp.float32)
    vn = jnp.asarray(rng.standard_normal((B, KVH, D)), jnp.float32)
    for li in range(L):
        got, ko, vo, kso, vso = decode_attention(
            q, kq, vq, lengths, block_k=32, layer=jnp.asarray(li),
            k_scale=ksc, v_scale=vsc, new_k=kn, new_v=vn)
        # reference: quantize the rows the model's way, write, then attend
        def quant_rows(new):
            r = np.asarray(new, np.float32)
            s = np.max(np.abs(r), axis=-1) / 127.0
            safe = np.where(s == 0.0, 1.0, s)
            pay = np.clip(np.round(r / safe[..., None]), -127, 127)
            return pay, s
        kpay, ksn = quant_rows(kn)
        vpay, vsn = quant_rows(vn)
        kq_w = np.asarray(kq).copy()
        vq_w = np.asarray(vq).copy()
        ksc_w = np.asarray(ksc).copy()
        vsc_w = np.asarray(vsc).copy()
        for b in range(B):
            p = int(lengths[b]) - 1
            kq_w[li, b, p] = kpay[b].reshape(-1)
            vq_w[li, b, p] = vpay[b].reshape(-1)
            ksc_w[li, b, p] = ksn[b]
            vsc_w[li, b, p] = vsn[b]
        want = np.asarray(decode_attention(
            q, jnp.asarray(kq_w), jnp.asarray(vq_w), lengths, block_k=32,
            layer=jnp.asarray(li), k_scale=jnp.asarray(ksc_w),
            v_scale=jnp.asarray(vsc_w)))
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_array_equal(np.asarray(ko)[li, [0, 1],
                                                     lengths - 1],
                                      kq_w[li, [0, 1], lengths - 1])
        np.testing.assert_array_equal(np.asarray(vo)[li, [0, 1],
                                                     lengths - 1],
                                      vq_w[li, [0, 1], lengths - 1])
        np.testing.assert_allclose(
            np.asarray(kso)[li, [0, 1], lengths - 1],
            ksc_w[li, [0, 1], lengths - 1], rtol=1e-6, atol=1e-6)
        # untouched rows preserved through the aliased outputs
        np.testing.assert_array_equal(np.asarray(ko)[li, 0, :32],
                                      np.asarray(kq)[li, 0, :32])


def test_fused_write_sliding_window():
    """Fused write + sliding-window decode: the fresh row's score
    substitution and the window's live mask interact at the write block —
    must match pre-writing the row then windowed attention."""
    B, H, D, S_max, W = 2, 4, 16, 128, 48
    rng = np.random.default_rng(9)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S_max, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S_max, D)), jnp.float32)
    ks, vs = to_smajor(k), to_smajor(v)
    # lengths straddling block edges AND the window boundary
    lengths = jnp.asarray([40, 104], jnp.int32)
    kn = rng.standard_normal((B, H, D)).astype(np.float32)
    vn = rng.standard_normal((B, H, D)).astype(np.float32)
    ks_w = _write_rows_ref(ks, kn.reshape(B, H * D), lengths)
    vs_w = _write_rows_ref(vs, vn.reshape(B, H * D), lengths)
    want = np.asarray(decode_attention(q, ks_w, vs_w, lengths, block_k=32,
                                       window=W))
    got, ko, vo = decode_attention(q, ks, vs, lengths, block_k=32,
                                   window=W, new_k=jnp.asarray(kn),
                                   new_v=jnp.asarray(vn))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(ko), np.asarray(ks_w),
                               rtol=1e-6, atol=1e-6)


def test_fused_write_rejects_bad_blocks():
    B, H, D, S_max = 1, 4, 16, 96
    q = jnp.zeros((B, H, D), jnp.float32)
    c = jnp.zeros((B, S_max, H * D), jnp.float32)
    n = jnp.zeros((B, H, D), jnp.float32)
    with pytest.raises(ValueError, match="block_k % 8"):
        decode_attention(q, c, c, jnp.asarray([5], jnp.int32), block_k=20,
                         new_k=n, new_v=n)
    odd = jnp.zeros((B, 92, H * D), jnp.float32)
    with pytest.raises(ValueError, match="S_max % 8"):
        decode_attention(q, odd, odd, jnp.asarray([5], jnp.int32),
                         new_k=n, new_v=n)


def test_fused_write_zero_length_row_clamped():
    """A zero-length row (invalid input — lengths include the fresh token,
    so the minimum is 1) must NOT corrupt cache rows 0-7: unclamped, its
    in-kernel write row computes (-1) % block_k = block_k-1 and the far
    stripe's stale rows get merged over the cache head (ADVICE round 5).
    Clamped, it degenerates to the benign length=1 write at row 0 and
    every other row of the stripe survives byte-for-byte."""
    B, H, D, S_max = 3, 4, 16, 64
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S_max, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S_max, D)), jnp.float32)
    ks, vs = to_smajor(k), to_smajor(v)
    kn = rng.standard_normal((B, H, D)).astype(np.float32)
    vn = rng.standard_normal((B, H, D)).astype(np.float32)
    lengths = jnp.asarray([5, 0, 33], jnp.int32)      # row 1: zero-length
    _, ko, vo = decode_attention(q, ks, vs, lengths, block_k=32,
                                 new_k=jnp.asarray(kn),
                                 new_v=jnp.asarray(vn))
    ko, vo = np.asarray(ko), np.asarray(vo)
    # the zero-length row's write clamps to position 0; positions 1-7 (the
    # rest of its 8-row write stripe) and everything beyond stay intact
    np.testing.assert_array_equal(ko[1, 1:], np.asarray(ks)[1, 1:])
    np.testing.assert_array_equal(vo[1, 1:], np.asarray(vs)[1, 1:])
    np.testing.assert_allclose(ko[1, 0], kn[1].reshape(-1), rtol=1e-6)
    # the VALID rows still write at lengths-1 exactly
    for b, pos in ((0, 4), (2, 32)):
        np.testing.assert_allclose(ko[b, pos], kn[b].reshape(-1), rtol=1e-6)
        other = np.delete(np.arange(S_max), pos)
        np.testing.assert_array_equal(ko[b, other], np.asarray(ks)[b, other])


# --------------------------------------------------------------------- #
# cached_attention chunk-branch contract (models/transformer.py)
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("starts", [(0, 0), (3, 11)])
def test_cached_attention_chunk_branch_matches_dense(starts):
    """The ``1 < S <= 512`` Pallas chunk branch of cached_attention derives
    row positions as ``q_positions[:, 0] + iota`` — for its documented
    contract (per-row CONTIGUOUS ascending positions, possibly different
    per row) it must agree with the dense einsum fallback, which masks per
    position."""
    B, S, H, D, S_max = 2, 8, 4, 16, 64
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S_max, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S_max, D)), jnp.float32)
    ks, vs = to_smajor(k), to_smajor(v)
    q_pos = jnp.asarray([[s + i for i in range(S)] for s in starts],
                        jnp.int32)
    got = cached_attention(q, ks, vs, q_pos)          # chunk kernel branch
    want = xla_cached_attention(q, ks, vs, q_pos)     # dense fallback
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
