"""Per-architecture injection policies.

Counterpart of reference ``module_inject/containers/{opt,gpt2,gptneox,gptj,
bloom,llama,...}.py`` — one policy class per HF decoder family, each encoding
(a) the architecture knobs (``build_config``) and (b) the checkpoint layout
(``layer_params``/``top_params``).
"""

import numpy as np

from deepspeed_tpu.models.transformer import TransformerConfig
from deepspeed_tpu.module_inject.policy import (
    ACT_MAP, HFPolicy, _np, linear_kernel, o_kernel, qkv_bias, qkv_kernel,
    split_fused_qkv_columns, split_fused_qkv_headwise)


class OPTPolicy(HFPolicy):
    """facebook/opt-* (reference ``containers/opt.py``)."""

    model_types = ("opt",)

    def build_config(self, hf, **over):
        proj = getattr(hf, "word_embed_proj_dim", hf.hidden_size)
        base = dict(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            num_layers=hf.num_hidden_layers,
            num_heads=hf.num_attention_heads,
            ffn_hidden_size=hf.ffn_dim,
            max_seq_len=hf.max_position_embeddings,
            activation=ACT_MAP[hf.activation_function],
            position_embedding="learned",
            tie_word_embeddings=hf.tie_word_embeddings,
            # opt-350m: embeddings in a 512-dim space with project_in/out,
            # post-LN blocks, no final norm
            embed_proj_dim=proj if proj != hf.hidden_size else None,
            pre_layer_norm=getattr(hf, "do_layer_norm_before", True),
        )
        base.update(over)
        return TransformerConfig(**base)

    def top_params(self, sd, cfg):
        out = {"embed_tokens/embedding": _np(sd["model.decoder.embed_tokens.weight"]),
               # OPTLearnedPositionalEmbedding carries a +2 offset; drop the
               # two offset rows so plain arange positions index correctly.
               "embed_positions/embedding":
                   _np(sd["model.decoder.embed_positions.weight"])[2:]}
        if cfg.pre_layer_norm:
            out.update(self.norm(sd, "model.decoder.final_layer_norm",
                                 "final_norm"))
        if cfg.embed_proj_dim is not None:
            out["project_in/kernel"] = linear_kernel(
                sd["model.decoder.project_in.weight"])
            out["project_out/kernel"] = linear_kernel(
                sd["model.decoder.project_out.weight"])
        if not cfg.tie_word_embeddings:
            out["lm_head/kernel"] = linear_kernel(sd["lm_head.weight"])
        return out

    def layer_params(self, sd, i, cfg):
        p = f"model.decoder.layers.{i}"
        out = self.attn_separate(sd, f"{p}.self_attn", cfg)
        out.update(self.norm(sd, f"{p}.self_attn_layer_norm", "input_norm"))
        # OPT's per-layer "final_layer_norm" is the pre-MLP norm
        out.update(self.norm(sd, f"{p}.final_layer_norm", "post_attn_norm"))
        out["mlp/up_proj/kernel"] = linear_kernel(sd[f"{p}.fc1.weight"])
        out["mlp/up_proj/bias"] = _np(sd[f"{p}.fc1.bias"])
        out["mlp/down_proj/kernel"] = linear_kernel(sd[f"{p}.fc2.weight"])
        out["mlp/down_proj/bias"] = _np(sd[f"{p}.fc2.bias"])
        return out

    def export_convert(self, flat, cfg):
        """Inverse of convert: flax flat params → HF OPT state dict (the
        key table ``layer_params``/``top_params`` read from, inverted —
        reference ``engine.py:3297`` save_16bit_model emits HF-loadable
        names)."""
        from deepspeed_tpu.module_inject.policy import (
            inv_linear_kernel, inv_o_kernel, inv_qkv_bias, inv_qkv_kernel)
        sd = {"model.decoder.embed_tokens.weight":
              np.asarray(flat["embed_tokens/embedding"])}
        pos = np.asarray(flat["embed_positions/embedding"])
        # restore OPTLearnedPositionalEmbedding's +2 offset rows (HF indexes
        # past them via the offset; their values are never read)
        sd["model.decoder.embed_positions.weight"] = np.concatenate(
            [np.zeros((2, pos.shape[1]), pos.dtype), pos])
        if cfg.pre_layer_norm:
            sd["model.decoder.final_layer_norm.weight"] = \
                np.asarray(flat["final_norm/scale"])
            sd["model.decoder.final_layer_norm.bias"] = \
                np.asarray(flat["final_norm/bias"])
        if cfg.embed_proj_dim is not None:
            sd["model.decoder.project_in.weight"] = \
                inv_linear_kernel(flat["project_in/kernel"])
            sd["model.decoder.project_out.weight"] = \
                inv_linear_kernel(flat["project_out/kernel"])
        if not cfg.tie_word_embeddings and "lm_head/kernel" in flat:
            sd["lm_head.weight"] = inv_linear_kernel(flat["lm_head/kernel"])

        def src(i, key):
            if getattr(cfg, "scan_layers", True):
                return np.asarray(flat[f"layers/{key}"])[i]
            return np.asarray(flat[f"layers_{i}/{key}"])

        def has(i, key):
            return (f"layers/{key}" in flat) if getattr(cfg, "scan_layers",
                                                        True) \
                else (f"layers_{i}/{key}" in flat)

        for i in range(cfg.num_layers):
            p = f"model.decoder.layers.{i}"
            for std in ("q_proj", "k_proj", "v_proj"):
                sd[f"{p}.self_attn.{std}.weight"] = \
                    inv_qkv_kernel(src(i, f"attn/{std}/kernel"))
                if has(i, f"attn/{std}/bias"):
                    sd[f"{p}.self_attn.{std}.bias"] = \
                        inv_qkv_bias(src(i, f"attn/{std}/bias"))
            sd[f"{p}.self_attn.out_proj.weight"] = \
                inv_o_kernel(src(i, "attn/o_proj/kernel"))
            if has(i, "attn/o_proj/bias"):
                sd[f"{p}.self_attn.out_proj.bias"] = src(i, "attn/o_proj/bias")
            sd[f"{p}.self_attn_layer_norm.weight"] = src(i, "input_norm/scale")
            sd[f"{p}.self_attn_layer_norm.bias"] = src(i, "input_norm/bias")
            sd[f"{p}.final_layer_norm.weight"] = src(i, "post_attn_norm/scale")
            sd[f"{p}.final_layer_norm.bias"] = src(i, "post_attn_norm/bias")
            sd[f"{p}.fc1.weight"] = inv_linear_kernel(src(i, "mlp/up_proj/kernel"))
            sd[f"{p}.fc1.bias"] = src(i, "mlp/up_proj/bias")
            sd[f"{p}.fc2.weight"] = inv_linear_kernel(src(i, "mlp/down_proj/kernel"))
            sd[f"{p}.fc2.bias"] = src(i, "mlp/down_proj/bias")
        return sd


class GPT2Policy(HFPolicy):
    """gpt2* (reference ``containers/gpt2.py`` / megatron containers).
    GPT2 uses Conv1D ([in, out]) weights — no transpose needed."""

    model_types = ("gpt2",)

    def build_config(self, hf, **over):
        base = dict(
            vocab_size=hf.vocab_size,
            hidden_size=hf.n_embd,
            num_layers=hf.n_layer,
            num_heads=hf.n_head,
            ffn_hidden_size=(hf.n_inner or 4 * hf.n_embd),
            max_seq_len=hf.n_positions,
            activation=ACT_MAP[hf.activation_function],
            position_embedding="learned",
            tie_word_embeddings=True,
        )
        base.update(over)
        return TransformerConfig(**base)

    def top_params(self, sd, cfg):
        out = {"embed_tokens/embedding": _np(sd["transformer.wte.weight"]),
               "embed_positions/embedding": _np(sd["transformer.wpe.weight"])}
        out.update(self.norm(sd, "transformer.ln_f", "final_norm"))
        return out

    def layer_params(self, sd, i, cfg):
        p = f"transformer.h.{i}"
        H, D = cfg.num_heads, cfg.head_dim
        out = split_fused_qkv_columns(_np(sd[f"{p}.attn.c_attn.weight"]), H, D,
                                      bias=_np(sd[f"{p}.attn.c_attn.bias"]))
        # c_proj is Conv1D [in=H*D, out=h]: already [in, out]
        out["attn/o_proj/kernel"] = np.ascontiguousarray(
            _np(sd[f"{p}.attn.c_proj.weight"]).reshape(H, D, -1))
        out["attn/o_proj/bias"] = _np(sd[f"{p}.attn.c_proj.bias"])
        out.update(self.norm(sd, f"{p}.ln_1", "input_norm"))
        out.update(self.norm(sd, f"{p}.ln_2", "post_attn_norm"))
        out["mlp/up_proj/kernel"] = _np(sd[f"{p}.mlp.c_fc.weight"])
        out["mlp/up_proj/bias"] = _np(sd[f"{p}.mlp.c_fc.bias"])
        out["mlp/down_proj/kernel"] = _np(sd[f"{p}.mlp.c_proj.weight"])
        out["mlp/down_proj/bias"] = _np(sd[f"{p}.mlp.c_proj.bias"])
        return out


class LlamaPolicy(HFPolicy):
    """llama/mistral family (reference ``containers/llama.py``)."""

    model_types = ("llama", "mistral")

    def build_config(self, hf, **over):
        base = dict(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            num_layers=hf.num_hidden_layers,
            num_heads=hf.num_attention_heads,
            num_kv_heads=getattr(hf, "num_key_value_heads",
                                 hf.num_attention_heads),
            ffn_hidden_size=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            activation=ACT_MAP[hf.hidden_act],
            gated_mlp=True,
            position_embedding="rope",
            rope_theta=getattr(hf, "rope_theta", 10000.0),
            rms_norm=True,
            layernorm_epsilon=hf.rms_norm_eps,
            tie_word_embeddings=getattr(hf, "tie_word_embeddings", False),
        )
        base.update(over)
        return TransformerConfig(**base)

    def top_params(self, sd, cfg):
        out = {"embed_tokens/embedding": _np(sd["model.embed_tokens.weight"])}
        out.update(self.norm(sd, "model.norm", "final_norm", rms=True))
        if not cfg.tie_word_embeddings:
            out["lm_head/kernel"] = linear_kernel(sd["lm_head.weight"])
        return out

    def layer_params(self, sd, i, cfg):
        p = f"model.layers.{i}"
        out = self.attn_separate(sd, f"{p}.self_attn", cfg, out_name="o_proj")
        out.update(self.norm(sd, f"{p}.input_layernorm", "input_norm", rms=True))
        out.update(self.norm(sd, f"{p}.post_attention_layernorm",
                             "post_attn_norm", rms=True))
        for name in ("gate_proj", "up_proj", "down_proj"):
            out[f"mlp/{name}/kernel"] = linear_kernel(sd[f"{p}.mlp.{name}.weight"])
        return out


class BloomPolicy(HFPolicy):
    """bigscience/bloom* (reference ``containers/bloom.py``): ALiBi
    positions, embedding layernorm, head-interleaved fused QKV."""

    model_types = ("bloom",)

    def build_config(self, hf, **over):
        base = dict(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            num_layers=hf.n_layer,
            num_heads=hf.n_head,
            ffn_hidden_size=4 * hf.hidden_size,
            max_seq_len=2048,
            activation="gelu",           # BloomGelu is the tanh approximation
            position_embedding="alibi",
            embedding_norm=True,
            layernorm_epsilon=hf.layer_norm_epsilon,
            tie_word_embeddings=True,
        )
        base.update(over)
        return TransformerConfig(**base)

    def top_params(self, sd, cfg):
        out = {"embed_tokens/embedding": _np(sd["transformer.word_embeddings.weight"])}
        out.update(self.norm(sd, "transformer.word_embeddings_layernorm",
                             "embed_norm"))
        out.update(self.norm(sd, "transformer.ln_f", "final_norm"))
        return out

    def layer_params(self, sd, i, cfg):
        p = f"transformer.h.{i}"
        H, D = cfg.num_heads, cfg.head_dim
        out = split_fused_qkv_headwise(
            sd[f"{p}.self_attention.query_key_value.weight"], H, D,
            bias=sd[f"{p}.self_attention.query_key_value.bias"])
        out["attn/o_proj/kernel"] = o_kernel(
            sd[f"{p}.self_attention.dense.weight"], H, D)
        out["attn/o_proj/bias"] = _np(sd[f"{p}.self_attention.dense.bias"])
        out.update(self.norm(sd, f"{p}.input_layernorm", "input_norm"))
        out.update(self.norm(sd, f"{p}.post_attention_layernorm",
                             "post_attn_norm"))
        out["mlp/up_proj/kernel"] = linear_kernel(sd[f"{p}.mlp.dense_h_to_4h.weight"])
        out["mlp/up_proj/bias"] = _np(sd[f"{p}.mlp.dense_h_to_4h.bias"])
        out["mlp/down_proj/kernel"] = linear_kernel(sd[f"{p}.mlp.dense_4h_to_h.weight"])
        out["mlp/down_proj/bias"] = _np(sd[f"{p}.mlp.dense_4h_to_h.bias"])
        return out


class GPTNeoXPolicy(HFPolicy):
    """EleutherAI/pythia + gpt-neox (reference ``containers/gptneox.py``):
    parallel residual, partial rotary, head-interleaved fused QKV."""

    model_types = ("gpt_neox",)

    def build_config(self, hf, **over):
        head_dim = hf.hidden_size // hf.num_attention_heads
        base = dict(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            num_layers=hf.num_hidden_layers,
            num_heads=hf.num_attention_heads,
            ffn_hidden_size=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            activation=ACT_MAP[hf.hidden_act],
            position_embedding="rope",
            rope_dim=int(head_dim * hf.rotary_pct),
            rope_theta=getattr(hf, "rotary_emb_base",
                               getattr(hf, "rope_theta", 10000.0)),
            parallel_residual=hf.use_parallel_residual,
            layernorm_epsilon=hf.layer_norm_eps,
            tie_word_embeddings=getattr(hf, "tie_word_embeddings", False),
        )
        base.update(over)
        return TransformerConfig(**base)

    def top_params(self, sd, cfg):
        out = {"embed_tokens/embedding": _np(sd["gpt_neox.embed_in.weight"])}
        out.update(self.norm(sd, "gpt_neox.final_layer_norm", "final_norm"))
        if not cfg.tie_word_embeddings:
            out["lm_head/kernel"] = linear_kernel(sd["embed_out.weight"])
        return out

    def layer_params(self, sd, i, cfg):
        p = f"gpt_neox.layers.{i}"
        H, D = cfg.num_heads, cfg.head_dim
        out = split_fused_qkv_headwise(
            sd[f"{p}.attention.query_key_value.weight"], H, D,
            bias=sd[f"{p}.attention.query_key_value.bias"])
        out["attn/o_proj/kernel"] = o_kernel(sd[f"{p}.attention.dense.weight"],
                                             H, D)
        out["attn/o_proj/bias"] = _np(sd[f"{p}.attention.dense.bias"])
        out.update(self.norm(sd, f"{p}.input_layernorm", "input_norm"))
        out.update(self.norm(sd, f"{p}.post_attention_layernorm",
                             "post_attn_norm"))
        out["mlp/up_proj/kernel"] = linear_kernel(
            sd[f"{p}.mlp.dense_h_to_4h.weight"])
        out["mlp/up_proj/bias"] = _np(sd[f"{p}.mlp.dense_h_to_4h.bias"])
        out["mlp/down_proj/kernel"] = linear_kernel(
            sd[f"{p}.mlp.dense_4h_to_h.weight"])
        out["mlp/down_proj/bias"] = _np(sd[f"{p}.mlp.dense_4h_to_h.bias"])
        return out


class GPTJPolicy(HFPolicy):
    """gpt-j (reference ``containers/gptj.py``): parallel residual with a
    single shared layernorm, interleaved partial rotary, biasless attention,
    biased lm_head."""

    model_types = ("gptj",)

    def build_config(self, hf, **over):
        base = dict(
            vocab_size=hf.vocab_size,
            hidden_size=hf.n_embd,
            num_layers=hf.n_layer,
            num_heads=hf.n_head,
            ffn_hidden_size=(hf.n_inner or 4 * hf.n_embd),
            max_seq_len=hf.n_positions,
            activation=ACT_MAP[hf.activation_function],
            position_embedding="rope",
            rope_dim=hf.rotary_dim,
            rope_interleaved=True,
            parallel_residual=True,
            shared_attn_mlp_norm=True,
            attention_bias=False,
            mlp_bias=True,
            lm_head_bias=True,
            layernorm_epsilon=hf.layer_norm_epsilon,
            tie_word_embeddings=getattr(hf, "tie_word_embeddings", False),
        )
        base.update(over)
        return TransformerConfig(**base)

    def top_params(self, sd, cfg):
        out = {"embed_tokens/embedding": _np(sd["transformer.wte.weight"])}
        out.update(self.norm(sd, "transformer.ln_f", "final_norm"))
        if not cfg.tie_word_embeddings:
            out["lm_head/kernel"] = linear_kernel(sd["lm_head.weight"])
            out["lm_head/bias"] = _np(sd["lm_head.bias"])
        return out

    def layer_params(self, sd, i, cfg):
        p = f"transformer.h.{i}"
        out = self.attn_separate(sd, f"{p}.attn", cfg, out_name="out_proj")
        out.update(self.norm(sd, f"{p}.ln_1", "input_norm"))
        out["mlp/up_proj/kernel"] = linear_kernel(sd[f"{p}.mlp.fc_in.weight"])
        out["mlp/up_proj/bias"] = _np(sd[f"{p}.mlp.fc_in.bias"])
        out["mlp/down_proj/kernel"] = linear_kernel(sd[f"{p}.mlp.fc_out.weight"])
        out["mlp/down_proj/bias"] = _np(sd[f"{p}.mlp.fc_out.bias"])
        return out


class GPTNeoPolicy(HFPolicy):
    """EleutherAI/gpt-neo (reference ``containers/gptneo.py``): alternating
    global/local (banded, window 256) attention layers, *unscaled* attention
    logits, biasless q/k/v with a biased out-projection, Linear (not Conv1D)
    MLP weights.  Per-layer attention patterns make the trunk heterogeneous,
    so layers are emitted unstacked (``scan_layers=False``)."""

    model_types = ("gpt_neo",)

    def build_config(self, hf, **over):
        over.pop("scan_layers", None)   # forced off: heterogeneous layers
        base = dict(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            num_layers=hf.num_layers,
            num_heads=hf.num_heads,
            ffn_hidden_size=(hf.intermediate_size or 4 * hf.hidden_size),
            max_seq_len=hf.max_position_embeddings,
            activation=ACT_MAP[hf.activation_function],
            position_embedding="learned",
            tie_word_embeddings=True,
            attention_bias=False,         # q/k/v carry no bias...
            attention_out_bias=True,      # ...but out_proj does
            attention_softmax_scale=1.0,  # gpt-neo skips 1/sqrt(D)
            attention_layers=tuple(hf.attention_layers),
            window_size=hf.window_size,
            layernorm_epsilon=hf.layer_norm_epsilon,
            scan_layers=False,
        )
        base.update(over)
        return TransformerConfig(**base)

    def top_params(self, sd, cfg):
        out = {"embed_tokens/embedding": _np(sd["transformer.wte.weight"]),
               "embed_positions/embedding": _np(sd["transformer.wpe.weight"])}
        out.update(self.norm(sd, "transformer.ln_f", "final_norm"))
        return out

    def layer_params(self, sd, i, cfg):
        p = f"transformer.h.{i}"
        out = self.attn_separate(sd, f"{p}.attn.attention", cfg)
        out.update(self.norm(sd, f"{p}.ln_1", "input_norm"))
        out.update(self.norm(sd, f"{p}.ln_2", "post_attn_norm"))
        out["mlp/up_proj/kernel"] = linear_kernel(sd[f"{p}.mlp.c_fc.weight"])
        out["mlp/up_proj/bias"] = _np(sd[f"{p}.mlp.c_fc.bias"])
        out["mlp/down_proj/kernel"] = linear_kernel(sd[f"{p}.mlp.c_proj.weight"])
        out["mlp/down_proj/bias"] = _np(sd[f"{p}.mlp.c_proj.bias"])
        return out


class BertPolicy(HFPolicy):
    """bert-* (reference ``module_inject/replace_policy.py``
    HFBertLayerPolicy — the reference's inference test-matrix workhorse).
    Encoder-family: converts HF BertForMaskedLM / BertModel weights onto
    :class:`deepspeed_tpu.models.bert.BertForMaskedLM`, whose encoder stack
    is the fused ``DeepSpeedTransformerLayer`` (post-LN)."""

    model_types = ("bert",)

    def build_config(self, hf, **over):
        from deepspeed_tpu.models.bert import BertConfig
        base = dict(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            num_layers=hf.num_hidden_layers,
            num_heads=hf.num_attention_heads,
            intermediate_size=hf.intermediate_size,
            max_position_embeddings=hf.max_position_embeddings,
            type_vocab_size=hf.type_vocab_size,
            layer_norm_eps=hf.layer_norm_eps,
        )
        # decoder-config aliases used by convert_hf_model callers
        if "max_seq_len" in over:
            over["max_position_embeddings"] = over.pop("max_seq_len")
        base.update(over)
        # unknown overrides raise (same contract as the decoder policies)
        return BertConfig(**base)

    def build_model(self, cfg):
        from deepspeed_tpu.models.bert import (BertEncoder, BertForMaskedLM)
        if getattr(self, "_has_mlm_head", True):
            return BertForMaskedLM(cfg)
        return BertEncoder(cfg, add_pooler=getattr(self, "_has_pooler", False))

    def convert(self, sd, cfg):
        H = cfg.num_heads
        D = cfg.hidden_size // H
        pfx = "bert." if any(k.startswith("bert.") for k in sd) else ""
        flat = {
            "bert/embeddings/word_embeddings/embedding":
                _np(sd[f"{pfx}embeddings.word_embeddings.weight"]),
            "bert/embeddings/position_embeddings/embedding":
                _np(sd[f"{pfx}embeddings.position_embeddings.weight"]),
            "bert/embeddings/token_type_embeddings/embedding":
                _np(sd[f"{pfx}embeddings.token_type_embeddings.weight"]),
            "bert/embeddings/layer_norm/scale":
                _np(sd[f"{pfx}embeddings.LayerNorm.weight"]),
            "bert/embeddings/layer_norm/bias":
                _np(sd[f"{pfx}embeddings.LayerNorm.bias"]),
        }
        for i in range(cfg.num_layers):
            p = f"{pfx}encoder.layer.{i}"
            o = f"bert/layers_{i}"
            for std, src in (("q_proj", "query"), ("k_proj", "key"),
                             ("v_proj", "value")):
                flat[f"{o}/{std}/kernel"] = qkv_kernel(
                    sd[f"{p}.attention.self.{src}.weight"], H, D)
                flat[f"{o}/{std}/bias"] = qkv_bias(
                    sd[f"{p}.attention.self.{src}.bias"], H, D)
            flat[f"{o}/out_proj/kernel"] = linear_kernel(
                sd[f"{p}.attention.output.dense.weight"])
            flat[f"{o}/out_proj/bias"] = _np(
                sd[f"{p}.attention.output.dense.bias"])
            flat[f"{o}/attn_ln/scale"] = _np(
                sd[f"{p}.attention.output.LayerNorm.weight"])
            flat[f"{o}/attn_ln/bias"] = _np(
                sd[f"{p}.attention.output.LayerNorm.bias"])
            flat[f"{o}/intermediate/kernel"] = linear_kernel(
                sd[f"{p}.intermediate.dense.weight"])
            flat[f"{o}/intermediate/bias"] = _np(
                sd[f"{p}.intermediate.dense.bias"])
            flat[f"{o}/output/kernel"] = linear_kernel(
                sd[f"{p}.output.dense.weight"])
            flat[f"{o}/output/bias"] = _np(sd[f"{p}.output.dense.bias"])
            flat[f"{o}/mlp_ln/scale"] = _np(
                sd[f"{p}.output.LayerNorm.weight"])
            flat[f"{o}/mlp_ln/bias"] = _np(sd[f"{p}.output.LayerNorm.bias"])
        # headless checkpoints (BertModel) convert onto BertEncoder; those
        # with a pooler keep it
        self._has_mlm_head = "cls.predictions.transform.dense.weight" in sd
        self._has_pooler = f"{pfx}pooler.dense.weight" in sd
        if self._has_pooler and not self._has_mlm_head:
            flat["bert/pooler/kernel"] = linear_kernel(
                sd[f"{pfx}pooler.dense.weight"])
            flat["bert/pooler/bias"] = _np(sd[f"{pfx}pooler.dense.bias"])
        # MLM head (present on BertForMaskedLM checkpoints)
        if "cls.predictions.transform.dense.weight" in sd:
            flat["transform_dense/kernel"] = linear_kernel(
                sd["cls.predictions.transform.dense.weight"])
            flat["transform_dense/bias"] = _np(
                sd["cls.predictions.transform.dense.bias"])
            flat["transform_ln/scale"] = _np(
                sd["cls.predictions.transform.LayerNorm.weight"])
            flat["transform_ln/bias"] = _np(
                sd["cls.predictions.transform.LayerNorm.bias"])
            flat["decoder_bias"] = _np(sd["cls.predictions.bias"])
        return flat


class ClipTextPolicy(HFPolicy):
    """CLIP text encoder (reference ``containers/clip.py`` DS_CLIPContainer /
    ``HFCLIPLayerPolicy``): causal pre-LN encoder with quick-gelu MLPs —
    structurally our decoder trunk; consumers read ``hidden_states`` (the
    vision tower rides ``model_implementations/transformers/clip_encoder``).
    Accepts a bare ``CLIPTextModel`` or a full ``CLIPModel`` (text tower)."""

    model_types = ("clip_text_model", "clip")

    def build_config(self, hf, **over):
        if hasattr(hf, "text_config"):      # full CLIPModel config
            hf = hf.text_config
        base = dict(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            num_layers=hf.num_hidden_layers,
            num_heads=hf.num_attention_heads,
            ffn_hidden_size=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            activation=ACT_MAP[hf.hidden_act],
            position_embedding="learned",
            layernorm_epsilon=hf.layer_norm_eps,
            # encoder: no LM head; tied head keeps the param tree headless
            tie_word_embeddings=True,
        )
        base.update(over)
        return TransformerConfig(**base)

    @staticmethod
    def _pfx(sd):
        return "text_model." if any(k.startswith("text_model.") for k in sd) \
            else ""

    def top_params(self, sd, cfg):
        p = self._pfx(sd)
        out = {"embed_tokens/embedding":
                   _np(sd[f"{p}embeddings.token_embedding.weight"]),
               "embed_positions/embedding":
                   _np(sd[f"{p}embeddings.position_embedding.weight"])}
        out.update(self.norm(sd, f"{p}final_layer_norm", "final_norm"))
        return out

    def layer_params(self, sd, i, cfg):
        p = f"{self._pfx(sd)}encoder.layers.{i}"
        out = self.attn_separate(sd, f"{p}.self_attn", cfg)
        out.update(self.norm(sd, f"{p}.layer_norm1", "input_norm"))
        out.update(self.norm(sd, f"{p}.layer_norm2", "post_attn_norm"))
        out["mlp/up_proj/kernel"] = linear_kernel(sd[f"{p}.mlp.fc1.weight"])
        out["mlp/up_proj/bias"] = _np(sd[f"{p}.mlp.fc1.bias"])
        out["mlp/down_proj/kernel"] = linear_kernel(sd[f"{p}.mlp.fc2.weight"])
        out["mlp/down_proj/bias"] = _np(sd[f"{p}.mlp.fc2.bias"])
        return out


class MegatronGPTPolicy(HFPolicy):
    """Megatron-LM GPT checkpoints (reference ``containers/megatron_gpt.py``
    + ``replace_policy.py`` MegatronLayerPolicy): pre-LN GPT-2 architecture
    with fused query_key_value, dense_h_to_4h/dense_4h_to_h MLP naming, and
    two fused-QKV row layouts — Megatron v2 interleaves per head ([H, 3, D]),
    v1 chunks per projection ([3, H·D]).  Consumes a *merged* state dict (use
    ``runtime/state_dict_factory.py`` MegatronSDLoader to fold TP shards
    first); see ``replace_module.load_megatron_model`` for the end-to-end
    path."""

    model_types = ("megatron-gpt",)
    PREFIXES = ("model.language_model.", "language_model.", "module.", "")

    @staticmethod
    def normalize(sd):
        """Strip megatron wrapper prefixes; unify encoder/transformer."""
        out = {}
        for k, v in sd.items():
            for p in MegatronGPTPolicy.PREFIXES:
                if p and k.startswith(p):
                    k = k[len(p):]
                    break
            k = k.replace("encoder.layers.", "transformer.layers.")
            out[k] = v
        return out

    def build_config(self, hf, **over):
        # hf here is a plain namespace/dict-like carrying megatron args
        get = lambda n, d=None: getattr(hf, n, d)
        base = dict(
            vocab_size=get("padded_vocab_size") or get("vocab_size"),
            hidden_size=get("hidden_size"),
            num_layers=get("num_layers"),
            num_heads=get("num_attention_heads") or get("num_heads"),
            ffn_hidden_size=get("ffn_hidden_size") or 4 * get("hidden_size"),
            max_seq_len=get("max_position_embeddings", 1024),
            activation="gelu",
            position_embedding="learned",
            tie_word_embeddings=True,
            layernorm_epsilon=get("layernorm_epsilon", 1e-5),
        )
        base.update(over)
        return TransformerConfig(**base)

    def top_params(self, sd, cfg):
        out = {"embed_tokens/embedding":
                   _np(sd["embedding.word_embeddings.weight"]
                       if "embedding.word_embeddings.weight" in sd
                       else sd["word_embeddings.weight"])[:cfg.vocab_size],
               "embed_positions/embedding":
                   _np(sd["embedding.position_embeddings.weight"]
                       if "embedding.position_embeddings.weight" in sd
                       else sd["position_embeddings.weight"])}
        out.update(self.norm(sd, "transformer.final_layernorm", "final_norm"))
        return out

    def _attn_and_norms(self, sd, i, cfg):
        """The attention + layernorm portion of one Megatron layer —
        shared with the MoE subclass, whose MLP mapping differs."""
        p = f"transformer.layers.{i}"
        H, D = cfg.num_heads, cfg.head_dim
        w = sd[f"{p}.attention.query_key_value.weight"]
        b = sd.get(f"{p}.attention.query_key_value.bias")
        if getattr(self, "megatron_v2", True):
            out = split_fused_qkv_headwise(w, H, D, bias=b)
        else:
            out = split_fused_qkv_columns(_np(w).T, H, D,
                                          bias=None if b is None else _np(b))
        out["attn/o_proj/kernel"] = o_kernel(
            sd[f"{p}.attention.dense.weight"], H, D)
        out["attn/o_proj/bias"] = _np(sd[f"{p}.attention.dense.bias"])
        out.update(self.norm(sd, f"{p}.input_layernorm", "input_norm"))
        out.update(self.norm(sd, f"{p}.post_attention_layernorm",
                             "post_attn_norm"))
        return out

    def layer_params(self, sd, i, cfg):
        p = f"transformer.layers.{i}"
        out = self._attn_and_norms(sd, i, cfg)
        out["mlp/up_proj/kernel"] = linear_kernel(
            sd[f"{p}.mlp.dense_h_to_4h.weight"])
        out["mlp/up_proj/bias"] = _np(sd[f"{p}.mlp.dense_h_to_4h.bias"])
        out["mlp/down_proj/kernel"] = linear_kernel(
            sd[f"{p}.mlp.dense_4h_to_h.weight"])
        out["mlp/down_proj/bias"] = _np(sd[f"{p}.mlp.dense_4h_to_h.bias"])
        return out


class MegatronGPTMoEPolicy(MegatronGPTPolicy):
    """Megatron-DeepSpeed MoE-GPT checkpoints (reference
    ``containers/megatron_gpt_moe.py`` ``MegatronMoELayerPolicy``, standard
    ``moe_type``): a Megatron GPT trunk where every ``expert_interval``-th
    layer's MLP is a DeepSpeed-MoE block — per-expert 2-layer MLPs under
    ``mlp.deepspeed_moe.experts.deepspeed_experts.{e}.*`` plus a top-k
    gate ``mlp.deepspeed_moe.gate.wg.weight``.  Maps onto the MoE trunk of
    ``models/transformer.py`` (experts stacked on a leading E dim, sharded
    over the ``ep`` mesh axis; gate kernel transposed to [M, E]).

    The reference's ``moe_type='residual'`` (expert outputs blended with a
    dense MLP through a learned coefficient) is not mapped: our residual
    MoE uses a single-Dense blend, so the checkpoint shapes differ."""

    model_types = ("megatron-gpt-moe",)

    @staticmethod
    def detect_moe(sd):
        """(num_experts, expert_interval, first_moe_layer) from a merged/
        normalized state dict; (0, 0, 0) when no MoE layers exist.  The
        interval is derived from the spacing between consecutive MoE layer
        indices, so patterns that don't start at ``interval - 1`` (e.g.
        layers 0,2,4 with interval 2) map too — only genuinely irregular
        layouts (pyramid-residual etc.) are rejected."""
        import re as _re
        moe_layers, experts, all_layers = set(), set(), set()
        for k in sd:
            lm = _re.match(r"transformer\.layers\.(\d+)\.", k)
            if lm:
                all_layers.add(int(lm.group(1)))
            m = _re.match(r"transformer\.layers\.(\d+)\.mlp\.deepspeed_moe\."
                          r"experts\.deepspeed_experts\.(\d+)\.", k)
            if m:
                moe_layers.add(int(m.group(1)))
                experts.add(int(m.group(2)))
        if not moe_layers:
            return 0, 0, 0
        # residual moe_type stores the dense blend branch as mlp.mlp.* and
        # the blend weights as mlp.coefficient.* (reference MoE layer's
        # use_residual members)
        if any(k.startswith("transformer.layers.")
               and (".mlp.coefficient." in k or ".mlp.mlp." in k)
               for k in sd):
            raise NotImplementedError(
                "megatron moe_type='residual' checkpoints are not supported "
                "(see MegatronGPTMoEPolicy docstring)")
        ordered = sorted(moe_layers)
        first = ordered[0]
        # single MoE layer: spacing is undefined — an interval past the
        # model depth makes exactly that one layer match the pattern
        interval = ordered[1] - ordered[0] if len(ordered) > 1 \
            else 1 + max(all_layers)
        # the pattern must hold over the FULL model depth, not just the
        # [first, last] MoE span — a truncated pattern (dense where the
        # interval predicts an expert) would otherwise surface later as a
        # bare KeyError deep in the per-layer weight mapping
        expect = set(range(first, 1 + max(all_layers), interval))
        if moe_layers != expect:
            raise ValueError(
                f"MoE layers {sorted(moe_layers)} are not a fixed "
                f"expert-interval pattern over all {1 + max(all_layers)} "
                f"layers (supported: evenly spaced indices through the last "
                f"layer; pyramid/residual layouts are not)")
        return len(experts), interval, first

    def build_config(self, hf, **over):
        get = lambda n, d=None: getattr(hf, n, d)
        base = dict(
            moe_num_experts=get("num_experts", 0),
            moe_every=get("expert_interval", 2),
            moe_layer_offset=get("first_moe_layer", -1),
            # megatron-deepspeed's arg name is 'topk'
            moe_top_k=get("moe_top_k", None) or get("topk", None) or 1,
            moe_expert_bias=True,
            # mixed dense/MoE blocks are heterogeneous — no layer scan
            scan_layers=False,
        )
        base.update(over)
        return super().build_config(hf, **base)

    def layer_params(self, sd, i, cfg):
        from deepspeed_tpu.models.transformer import _is_moe_layer
        if not _is_moe_layer(cfg, i):
            return super().layer_params(sd, i, cfg)
        p = f"transformer.layers.{i}.mlp.deepspeed_moe"
        E = cfg.moe_num_experts
        ex = lambda e, n: sd[f"{p}.experts.deepspeed_experts.{e}.{n}"]
        out = self._attn_and_norms(sd, i, cfg)
        # gate wg: torch [E, M] → flax [M, E]
        out["moe_mlp/gate_kernel"] = linear_kernel(sd[f"{p}.gate.wg.weight"])
        out["moe_mlp/ExpertsMLP_0/experts_wi"] = np.stack(
            [linear_kernel(ex(e, "dense_h_to_4h.weight")) for e in range(E)])
        out["moe_mlp/ExpertsMLP_0/experts_bi"] = np.stack(
            [_np(ex(e, "dense_h_to_4h.bias")) for e in range(E)])
        out["moe_mlp/ExpertsMLP_0/experts_wo"] = np.stack(
            [linear_kernel(ex(e, "dense_4h_to_h.weight")) for e in range(E)])
        out["moe_mlp/ExpertsMLP_0/experts_bo"] = np.stack(
            [_np(ex(e, "dense_4h_to_h.bias")) for e in range(E)])
        return out


class DistilBertPolicy(BertPolicy):
    """distilbert-* (reference ``containers/distil_bert.py``): BERT encoder
    minus token-type embeddings; MLM head named vocab_transform /
    vocab_layer_norm / vocab_projector (projector tied to embeddings)."""

    model_types = ("distilbert",)

    def build_config(self, hf, **over):
        from deepspeed_tpu.models.bert import BertConfig
        if hf.activation != "gelu":
            raise NotImplementedError(
                f"DistilBERT activation {hf.activation!r}: the fused encoder "
                "layer is gelu-only")
        base = dict(
            vocab_size=hf.vocab_size,
            hidden_size=hf.dim,
            num_layers=hf.n_layers,
            num_heads=hf.n_heads,
            intermediate_size=hf.hidden_dim,
            max_position_embeddings=hf.max_position_embeddings,
            type_vocab_size=1,           # none in distilbert; zero table
            layer_norm_eps=1e-12,
        )
        if "max_seq_len" in over:
            over["max_position_embeddings"] = over.pop("max_seq_len")
        base.update(over)
        return BertConfig(**base)

    def convert(self, sd, cfg):
        H = cfg.num_heads
        D = cfg.hidden_size // H
        pfx = "distilbert." if any(k.startswith("distilbert.") for k in sd) \
            else ""
        flat = {
            "bert/embeddings/word_embeddings/embedding":
                _np(sd[f"{pfx}embeddings.word_embeddings.weight"]),
            "bert/embeddings/position_embeddings/embedding":
                _np(sd[f"{pfx}embeddings.position_embeddings.weight"]),
            # distilbert has no segment embeddings: zero table, index 0
            "bert/embeddings/token_type_embeddings/embedding":
                np.zeros((1, cfg.hidden_size), np.float32),
            "bert/embeddings/layer_norm/scale":
                _np(sd[f"{pfx}embeddings.LayerNorm.weight"]),
            "bert/embeddings/layer_norm/bias":
                _np(sd[f"{pfx}embeddings.LayerNorm.bias"]),
        }
        for i in range(cfg.num_layers):
            p = f"{pfx}transformer.layer.{i}"
            o = f"bert/layers_{i}"
            for std, src in (("q_proj", "q_lin"), ("k_proj", "k_lin"),
                             ("v_proj", "v_lin")):
                flat[f"{o}/{std}/kernel"] = qkv_kernel(
                    sd[f"{p}.attention.{src}.weight"], H, D)
                flat[f"{o}/{std}/bias"] = qkv_bias(
                    sd[f"{p}.attention.{src}.bias"], H, D)
            flat[f"{o}/out_proj/kernel"] = linear_kernel(
                sd[f"{p}.attention.out_lin.weight"])
            flat[f"{o}/out_proj/bias"] = _np(sd[f"{p}.attention.out_lin.bias"])
            flat[f"{o}/attn_ln/scale"] = _np(sd[f"{p}.sa_layer_norm.weight"])
            flat[f"{o}/attn_ln/bias"] = _np(sd[f"{p}.sa_layer_norm.bias"])
            flat[f"{o}/intermediate/kernel"] = linear_kernel(
                sd[f"{p}.ffn.lin1.weight"])
            flat[f"{o}/intermediate/bias"] = _np(sd[f"{p}.ffn.lin1.bias"])
            flat[f"{o}/output/kernel"] = linear_kernel(
                sd[f"{p}.ffn.lin2.weight"])
            flat[f"{o}/output/bias"] = _np(sd[f"{p}.ffn.lin2.bias"])
            flat[f"{o}/mlp_ln/scale"] = _np(
                sd[f"{p}.output_layer_norm.weight"])
            flat[f"{o}/mlp_ln/bias"] = _np(sd[f"{p}.output_layer_norm.bias"])
        self._has_mlm_head = "vocab_transform.weight" in sd
        self._has_pooler = False
        if self._has_mlm_head:
            flat["transform_dense/kernel"] = linear_kernel(
                sd["vocab_transform.weight"])
            flat["transform_dense/bias"] = _np(sd["vocab_transform.bias"])
            flat["transform_ln/scale"] = _np(sd["vocab_layer_norm.weight"])
            flat["transform_ln/bias"] = _np(sd["vocab_layer_norm.bias"])
            flat["decoder_bias"] = _np(sd["vocab_projector.bias"])
        return flat


ALL_POLICIES = [OPTPolicy, GPT2Policy, LlamaPolicy, BloomPolicy,
                GPTNeoXPolicy, GPTJPolicy, GPTNeoPolicy, BertPolicy,
                DistilBertPolicy, MegatronGPTPolicy, ClipTextPolicy]
