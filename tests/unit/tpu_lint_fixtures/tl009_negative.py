"""TL009 negative fixture — the mechanical fixes and non-engine
receivers.  Expect ZERO findings."""
import asyncio  # noqa: F401


async def handler(loop, srv, spec):
    # the fix: a bare method REFERENCE handed to the executor
    rid = await loop.run_in_executor(None, srv.submit, spec)
    await loop.run_in_executor(None, srv.token_events, rid, print)
    return rid


async def cancel_route(loop, srv, rid):
    def _cancel():                       # executor thunk: exempt
        try:
            srv.cancel(rid)
        except KeyError:
            pass
    await loop.run_in_executor(None, _cancel)


async def close_listener(self_server):
    # receiver is not an engine by the naming convention
    self_server.close()


async def drain_writer(writer):
    await writer.drain()                 # asyncio writer, not the engine


def scheduler_loop(srv):
    # a plain sync function IS the scheduler-owner thread's body
    while srv.queue_depth:
        srv.step()


def on_event(loop, ev):
    loop.call_soon_threadsafe(print, ev)
