"""Continuous-batching serving engine tests (``inference/serving/``).

The scheduler-correctness acceptance contract: with fewer slots than
requests, every request's output is BITWISE-identical to its solo
``generate()`` run (greedy), EOS retirement frees slots mid-decode
(asserted via the slot-occupancy trace), and exactly one decode-step
executable is compiled for the whole run — plus compile-cache counters
proving a restarted server RELOADS the decode program instead of
recompiling it."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.transformer import Transformer, TransformerConfig


def tiny_cfg(**over):
    base = dict(vocab_size=97, hidden_size=64, num_layers=2, num_heads=4,
                max_seq_len=64, use_flash_attention=False, dtype="float32")
    base.update(over)
    return TransformerConfig(**base)


SERVING = {"enabled": True, "num_slots": 3, "max_cache_len": 64,
           "prefill_chunk": 8, "prefill_token_budget": 16,
           "decode_block": 2}


@pytest.fixture
def served_engine():
    model = Transformer(tiny_cfg())
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 97, (2, 12)),
                      jnp.int32)
    params = model.init(jax.random.key(0), {"input_ids": ids})
    # prefill_chunk_size=8: solo generate() reference runs the SAME
    # split-prefill chunk program the serving admission path replays
    eng = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "prefill_chunk_size": 8,
                       "serving": SERVING})
    eng.set_params(params)
    return eng


def _mixed_workload(rng, n=7):
    lens = rng.integers(9, 21, (n,))          # > chunk: solo also splits
    news = rng.integers(3, 13, (n,))
    prompts = [rng.integers(1, 97, (int(p),)).astype(np.int32)
               for p in lens]
    return prompts, [int(x) for x in news]


def test_serving_matches_solo_generate(served_engine):
    """The acceptance contract: num_slots(3) < num_requests(7); greedy
    outputs bitwise-equal to solo generate(); EOS frees slots mid-decode;
    ONE decode-step executable for the whole run."""
    eng = served_engine
    rng = np.random.default_rng(3)
    prompts, news = _mixed_workload(rng)

    # per-request eos that actually fires mid-stream for some requests:
    # probe the greedy continuation and pick the token emitted ~halfway
    eos_ids = []
    for i, (p, n) in enumerate(zip(prompts, news)):
        if i % 2 == 0:
            probe = np.asarray(eng.generate(p[None], max_new_tokens=n))[0]
            eos_ids.append(int(probe[len(p) + n // 2]))
        else:
            eos_ids.append(-1)

    srv = eng.serve()
    rids = [srv.submit(p, max_new_tokens=n, eos_token_id=e)
            for p, n, e in zip(prompts, news, eos_ids)]
    outs = srv.drain()
    assert sorted(outs) == sorted(rids)

    for rid, p, n, e in zip(rids, prompts, news, eos_ids):
        want = np.asarray(eng.generate(p[None], max_new_tokens=n,
                                       eos_token_id=e))[0]
        np.testing.assert_array_equal(
            outs[rid], want,
            err_msg=f"request {rid} (P={len(p)}, new={n}, eos={e}) "
                    f"diverges from its solo generate() run")

    # EOS retirement mid-flight: the occupancy trace must show slots
    # FREEING while later requests still got admitted afterwards (churn:
    # occupancy dips and recovers)
    occ = [o for _, o in srv.occupancy_trace]
    assert any(occ[i] < occ[i - 1] for i in range(1, len(occ))), occ
    assert any(occ[i] > occ[i - 1] for i in range(1, len(occ))), occ
    assert srv.stats["completed"] == len(rids)
    assert srv.stats["admitted"] == len(rids)

    # exactly ONE decode-step executable for the whole run: slot
    # occupancy/EOS/admission all ride traced arguments
    n_decode_sigs = sum(1 for sig in eng._aot
                        if sig and sig[0] == id(srv._decode_fn))
    assert n_decode_sigs == 1, n_decode_sigs


def test_serving_slot_lane_reuse_no_stale_rows(served_engine):
    """A slot lane reused across requests must not leak the previous
    occupant's KV rows: run a LONG request through a slot, then a SHORT
    one (strictly inside the old live region) with single-slot serving —
    its output must equal the solo run on a fresh cache."""
    eng = served_engine
    rng = np.random.default_rng(11)
    long_p = rng.integers(1, 97, (20,)).astype(np.int32)
    short_p = rng.integers(1, 97, (9,)).astype(np.int32)
    srv = eng.serve(num_slots=1)
    r1 = srv.submit(long_p, max_new_tokens=12)
    r2 = srv.submit(short_p, max_new_tokens=4)
    outs = srv.drain()
    want1 = np.asarray(eng.generate(long_p[None], max_new_tokens=12))[0]
    want2 = np.asarray(eng.generate(short_p[None], max_new_tokens=4))[0]
    np.testing.assert_array_equal(outs[r1], want1)
    np.testing.assert_array_equal(outs[r2], want2)


def test_serving_decode_block_invariance(served_engine):
    """Tokens are independent of the decode block size (the block only
    changes how many steps run per host round trip)."""
    eng = served_engine
    rng = np.random.default_rng(5)
    prompts, news = _mixed_workload(rng, n=5)
    ref = None
    for block in (1, 3):
        srv = eng.serve(decode_block=block)
        rids = [srv.submit(p, max_new_tokens=n)
                for p, n in zip(prompts, news)]
        outs = srv.drain()
        got = [outs[r] for r in rids]
        if ref is None:
            ref = got
        else:
            for a, b in zip(ref, got):
                np.testing.assert_array_equal(a, b)


def test_serving_submit_while_running(served_engine):
    """Requests submitted mid-flight join freed slots (in-flight batching,
    not batch boundaries)."""
    eng = served_engine
    rng = np.random.default_rng(7)
    p1 = rng.integers(1, 97, (10,)).astype(np.int32)
    p2 = rng.integers(1, 97, (13,)).astype(np.int32)
    srv = eng.serve()
    r1 = srv.submit(p1, max_new_tokens=8)
    outs = {}
    outs.update(srv.step())
    outs.update(srv.step())
    r2 = srv.submit(p2, max_new_tokens=5)      # joins while r1 decodes
    while srv.queue_depth or srv.active_slots:
        outs.update(srv.step())
    np.testing.assert_array_equal(
        outs[r1], np.asarray(eng.generate(p1[None], max_new_tokens=8))[0])
    np.testing.assert_array_equal(
        outs[r2], np.asarray(eng.generate(p2[None], max_new_tokens=5))[0])


def test_serving_admission_policies_and_validation(served_engine):
    eng = served_engine
    rng = np.random.default_rng(9)
    srv = eng.serve(admission="shortest_first", num_slots=1,
                    prefill_token_budget=0)
    long_p = rng.integers(1, 97, (20,)).astype(np.int32)
    short_p = rng.integers(1, 97, (9,)).astype(np.int32)
    r_long = srv.submit(long_p, max_new_tokens=3)
    r_short = srv.submit(short_p, max_new_tokens=3)
    first_done = None
    while first_done is None:
        done = srv.step()
        if done:
            first_done = sorted(done)
    # shortest_first: the short prompt (submitted second) admits first
    assert first_done[0] == r_short
    srv.drain()

    with pytest.raises(ValueError, match="cache positions"):
        srv.submit(np.ones((60,), np.int32), max_new_tokens=32)
    with pytest.raises(ValueError, match="max_new_tokens"):
        srv.submit(short_p, max_new_tokens=0)
    with pytest.raises(ValueError, match="empty"):
        srv.submit(np.zeros((0,), np.int32))
    with pytest.raises(ValueError, match="admission"):
        eng.serve(admission="priority")


def test_serving_max_new_one_and_first_token_eos(served_engine):
    """Requests that finish AT admission (max_new=1, or eos on the first
    token) release their slot without ever entering decode."""
    eng = served_engine
    rng = np.random.default_rng(13)
    p = rng.integers(1, 97, (9,)).astype(np.int32)
    want1 = np.asarray(eng.generate(p[None], max_new_tokens=1))[0]
    first_tok = int(want1[-1])
    srv = eng.serve()
    r1 = srv.submit(p, max_new_tokens=1)
    r2 = srv.submit(p, max_new_tokens=6, eos_token_id=first_tok)
    outs = srv.drain()
    np.testing.assert_array_equal(outs[r1], want1)
    want2 = np.asarray(eng.generate(p[None], max_new_tokens=6,
                                    eos_token_id=first_tok))[0]
    np.testing.assert_array_equal(outs[r2], want2)
    assert srv.stats["decode_tokens"] == 0       # nothing ever decoded


def test_serving_sampled_generation_runs(served_engine):
    eng = served_engine
    rng = np.random.default_rng(15)
    prompts, news = _mixed_workload(rng, n=4)
    srv = eng.serve(do_sample=True, temperature=0.8, top_k=10, top_p=0.9)
    rids = [srv.submit(p, max_new_tokens=n) for p, n in zip(prompts, news)]
    outs = srv.drain()
    for rid, p, n in zip(rids, prompts, news):
        assert outs[rid].shape == (len(p) + n,)
        assert (outs[rid] >= 0).all() and (outs[rid] < 97).all()


def test_serving_row_step_efficiency(served_engine):
    """The perf mechanism, deterministically (no wall clocks): on a
    mixed-completion workload the serving engine spends fewer decode
    row-steps (iterations x slots) than lockstep whole-batch generate()
    spends (batch x the batch's max max_new) — the waste continuous
    batching exists to recover."""
    eng = served_engine
    rng = np.random.default_rng(17)
    prompts = [rng.integers(1, 97, (int(p),)).astype(np.int32)
               for p in rng.integers(9, 16, (8,))]
    news = [2, 30, 2, 30, 2, 30, 2, 30]
    srv = eng.serve(num_slots=2, max_cache_len=64, decode_block=2)
    rids = [srv.submit(p, max_new_tokens=n) for p, n in zip(prompts, news)]
    srv.drain()
    serving_row_steps = srv.stats["decode_calls"] * srv.block * 2
    # lockstep: 4 sequential batches of 2, each decoding to ITS max (30)
    lockstep_row_steps = 4 * 2 * 30
    assert serving_row_steps < lockstep_row_steps, \
        (serving_row_steps, lockstep_row_steps)


def test_serving_monitor_events(served_engine):
    """Per-iteration Serving/* monitor events (queue depth, occupancy,
    decode tokens/s, prefill/decode ratio) + Compile/ events from warmup."""
    eng = served_engine

    class FakeMonitor:
        enabled = True

        def __init__(self):
            self.events = []

        def write_events(self, evs):
            self.events.extend(evs)

    mon = FakeMonitor()
    srv = eng.serve(monitor=mon)
    srv.warmup()
    rng = np.random.default_rng(19)
    prompts, news = _mixed_workload(rng, n=4)
    for p, n in zip(prompts, news):
        srv.submit(p, max_new_tokens=n)
    srv.drain()
    names = {n for n, _, _ in mon.events}
    for want in ("Serving/queue_depth", "Serving/slot_occupancy",
                 "Serving/decode_tok_s", "Serving/prefill_decode_ratio",
                 "Serving/completed"):
        assert want in names, names
    assert any(n.startswith("Compile/serving_decode") for n in names), names
    occ = [v for n, v, _ in mon.events if n == "Serving/slot_occupancy"]
    assert occ and max(occ) <= 1.0 and min(occ) >= 0.0


def test_serving_programs_bypass_persistent_cache_across_restarts(tmp_path):
    """The serving slot programs must NOT round-trip either persistent
    cache layer: cross-process reloaded serving executables corrupt the
    donated slot workspace (wrong tokens / cross-lane mixing / segfaults
    — bisected with the kill-harness driver, see
    ServingEngine.__init__).  A restarted server recompiles its three
    serving programs — zero store saves/hits — and serves outputs
    bitwise-identical to the first server's."""
    from deepspeed_tpu.runtime import compile_cache as cc

    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        model = Transformer(tiny_cfg())
        ids = jnp.asarray(np.random.default_rng(0).integers(0, 97, (1, 12)),
                          jnp.int32)
        params = model.init(jax.random.key(0), {"input_ids": ids})
        config = {"dtype": "float32", "prefill_chunk_size": 8,
                  "serving": SERVING,
                  "compile_cache": {"enabled": True,
                                    "cache_dir": str(tmp_path),
                                    "min_compile_time_secs": 0.0}}

        def run_server():
            eng = deepspeed_tpu.init_inference(model, config=config)
            eng.set_params(params)
            srv = eng.serve()
            report = srv.warmup()
            rng = np.random.default_rng(3)
            p = rng.integers(1, 97, (11,)).astype(np.int32)
            rid = srv.submit(p, max_new_tokens=5)
            out = srv.drain()[rid]
            return report, out

        s0 = cc.stats().snapshot()
        report1, out1 = run_server()
        s1 = cc.stats().snapshot()
        # the decode program really compiled — and NOTHING serving was
        # persisted to the executable store
        assert any(k.startswith("serving_decode") for k in report1)
        assert s1["executable_saves"] == s0["executable_saves"]

        report2, out2 = run_server()
        s2 = cc.stats().snapshot()
        # restarted server: compiles again (a fresh report, no store
        # traffic), outputs bitwise-identical
        assert any(k.startswith("serving_decode") for k in report2)
        assert s2["executable_saves"] == s1["executable_saves"]
        assert s2["executable_hits"] == s1["executable_hits"]
        np.testing.assert_array_equal(out1, out2)
        # within one server lifetime nothing recompiles: warmup again is
        # a no-op (0.0 = already compiled in this process)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          prev_min)
        cc._configured_dir = prev_dir


def test_serving_decode_failure_recovers(served_engine):
    """A failed decode dispatch (donated cache/state dead) aborts the
    in-flight requests but must leave the scheduler CONSISTENT: every
    slot returns to the free list, stale events are dropped, and queued
    requests complete correctly on a fresh workspace afterwards
    (regression: the slots leaked and drain() spun forever; a stale
    admit event replayed against the fresh state emitted -1 garbage)."""
    eng = served_engine
    rng = np.random.default_rng(23)
    prompts, news = _mixed_workload(rng, n=6)
    srv = eng.serve(num_slots=2)
    for p, n in zip(prompts[:4], news[:4]):
        srv.submit(p, max_new_tokens=n)
    srv.step()
    srv.step()                                   # slots busy, events live

    real_run = eng._run_guarded
    blown = []

    def blow_decode(fn, args):
        if fn is srv._decode_fn and not blown:
            blown.append(True)
            for leaf in jax.tree.leaves((args[1], args[2])):
                if hasattr(leaf, "delete"):
                    leaf.delete()            # simulate post-donation death
            raise RuntimeError("injected decode failure")
        return real_run(fn, args)

    eng._run_guarded = blow_decode
    with pytest.raises(RuntimeError, match="injected decode failure"):
        srv.drain()
    eng._run_guarded = real_run
    # consistent after the failure: all slots free, nothing in flight
    assert len(srv._free) == 2 and not srv._events
    assert srv.active_slots == 0
    assert srv.stats.get("aborted", 0) >= 1

    # queued + fresh requests complete bitwise-correct on a new workspace
    tail = [srv.submit(p, max_new_tokens=n)
            for p, n in zip(prompts[4:], news[4:])]
    outs = srv.drain()
    for rid, p, n in zip(tail, prompts[4:], news[4:]):
        want = np.asarray(eng.generate(p[None], max_new_tokens=n))[0]
        np.testing.assert_array_equal(outs[rid], want)


def test_serving_close_releases_and_recovers(served_engine):
    """close() retires the server: workspaces released, undrained request
    ids reported (idempotently), submit() afterwards raises — a fresh
    serve() on the same engine reproduces the outputs bitwise."""
    eng = served_engine
    rng = np.random.default_rng(21)
    p = rng.integers(1, 97, (10,)).astype(np.int32)
    srv = eng.serve()
    r1 = srv.submit(p, max_new_tokens=4)
    out1 = srv.drain()[r1]
    q = srv.submit(p, max_new_tokens=4)        # left undrained on purpose
    undrained = srv.close()
    assert srv._cache is None
    assert undrained == [q]
    assert srv.result(q).status == "ABORTED"
    # idempotent: a second close() is a no-op reporting the same ids
    assert srv.close() == [q]
    with pytest.raises(RuntimeError, match="closed ServingEngine"):
        srv.submit(p, max_new_tokens=4)
    # a fresh server on the same engine serves identically
    srv2 = eng.serve()
    r2 = srv2.submit(p, max_new_tokens=4)
    out2 = srv2.drain()[r2]
    np.testing.assert_array_equal(out1, out2)
