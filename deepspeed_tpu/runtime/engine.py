"""DeepSpeedEngine — the training engine façade over jitted XLA programs.

TPU-native re-design of reference ``runtime/engine.py:181`` (DeepSpeedEngine).
The imperative 3-call API is preserved::

    loss = engine(batch)        # forward
    engine.backward(loss)       # gradient production + accumulation
    engine.step()               # optimizer update at the GAS boundary

but the implementation is functional: params / optimizer state / gradient
accumulators are sharded ``jax.Array`` pytrees placed by the ZeRO sharding
plan (see ``runtime/zero/partition.py``), and each phase is ONE compiled XLA
program:

* ``forward``+``backward`` together run a jitted ``value_and_grad`` with
  gradient out-shardings = the ZeRO-2 scattered layout, so XLA lowers the
  grad reduction to overlapped reduce-scatters (what the reference builds by
  hand with IPG buckets + comm streams, ``stage_1_and_2.py:833,900``).
* ``step`` runs a jitted, donated update: unscale → global-norm clip →
  fused optimizer → loss-scale update, skipped branch-free on overflow
  (reference ``stage_1_and_2.py:1642,1791,1808``).
* ``train_batch`` additionally offers the fully-fused whole-step program
  (forward+backward over all accumulation micro-batches via ``lax.scan`` +
  update) — the maximum-overlap hot path used by benchmarks, with the same
  semantics as the 3-call sequence.

Model protocol: a flax ``nn.Module`` (``.init``/``.apply``) or a plain
``apply_fn(params, batch, rng) -> loss``.  Parameters are *born sharded* —
initialization is jitted with the plan's out-shardings, the analog of
``zero.Init`` (reference ``partition_parameters.py:603``) without the
monkey-patching.
"""

import os
import inspect
from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu import comm as dist
from deepspeed_tpu.accelerator import get_accelerator
from deepspeed_tpu.monitor.monitor import MonitorMaster
from deepspeed_tpu.parallel import topology as topo_mod
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import OrbaxCheckpointEngine
from deepspeed_tpu.runtime.fp16.loss_scaler import create_loss_scaler
from deepspeed_tpu.runtime.lr_schedules import build_lr_scheduler
from deepspeed_tpu.runtime.optimizers import build_optimizer
from deepspeed_tpu.runtime.zero.partition import build_sharding_plan
from deepspeed_tpu.tools.lint.hotpath import hot_path
from deepspeed_tpu.utils.logging import logger, log_dist
from deepspeed_tpu.utils.timer import (SynchronizedWallClockTimer, ThroughputTimer,
                                       FORWARD_GLOBAL_TIMER, BACKWARD_GLOBAL_TIMER,
                                       STEP_GLOBAL_TIMER)

MEMORY_OPT_ALLREDUCE_SIZE = 500_000_000

# _pending marker: this micro's gradients were already added into the
# running accumulator by the fused forward program (see forward())
_ACCUMULATED = object()


def _finish_grads(grads, acc_dt):
    """Shared epilogue of every backward variant: cast to the accumulation
    dtype and derive the overflow flag (one place — the grouped and
    one-pass paths must never diverge here)."""
    grads = jax.tree.map(lambda g: g.astype(acc_dt), grads)
    leaves = jax.tree.leaves(grads)
    found_inf = jnp.logical_not(jnp.all(jnp.stack(
        [jnp.all(jnp.isfinite(g)) for g in leaves])))
    return grads, found_inf


def _unscale_and_clip(grads, scale, clip):
    """Unscale by the loss scale, compute the global grad norm, clip
    (reference ``stage_1_and_2.py:1791`` unscale_and_clip_grads)."""
    inv = 1.0 / scale
    grads = jax.tree.map(lambda g: g * inv, grads)
    gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                         for g in jax.tree.leaves(grads)))
    if clip > 0.0:
        factor = jnp.minimum(1.0, clip / (gnorm + 1e-6))
        grads = jax.tree.map(lambda g: g * factor, grads)
    return grads, gnorm


def _is_flax_module(model):
    try:
        import flax.linen as nn
        return isinstance(model, nn.Module)
    except ImportError:
        return False


class DeepSpeedEngine:
    """Training engine (reference ``engine.py:181``)."""

    def __init__(self,
                 model=None,
                 optimizer=None,
                 model_parameters=None,
                 training_data=None,
                 lr_scheduler=None,
                 collate_fn=None,
                 config=None,
                 config_class: Optional[DeepSpeedConfig] = None,
                 topology: Optional[topo_mod.ParallelTopology] = None,
                 loss_fn=None,
                 dont_change_device=False):
        self.module = model
        self.client_optimizer = optimizer
        self.client_lr_scheduler = lr_scheduler
        self.training_dataloader = None
        self.loss_fn = loss_fn

        dist.init_distributed()

        # ---- config + topology -------------------------------------- #
        raw = config if isinstance(config, dict) else {}
        if isinstance(config, str):
            import json
            with open(config) as f:
                raw = json.load(f)
        tp = raw.get("tensor_parallel", {}).get("tp_size", 1)
        pp = raw.get("pipeline", {}).get("stages", 1) if isinstance(raw.get("pipeline"), dict) else 1
        sp = raw.get("sequence_parallel", {}).get("sp_size", 1)
        ep = raw.get("moe", {}).get("ep_size", 1)
        mics = raw.get("zero_optimization", {}).get("mics_shard_size", 0)
        if topology is not None:
            self.topology = topo_mod.set_topology(topology)
        else:
            self.topology = topo_mod.initialize_topology(tp=tp, pp=pp, sp=sp,
                                                         ep=ep, mics=mics)
        self.mesh = self.topology.mesh

        if config_class is not None:
            self._config = config_class
        else:
            self._config = DeepSpeedConfig(raw if raw else config,
                                           mesh_world_size=self.topology.dp)
        dist.configure(self._config)

        # ---- engine state -------------------------------------------- #
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self._skipped_steps = 0
        self._pending_inf_flags = []   # device overflow flags, drained lazily
        self.training = True
        self._params = None            # master (fp32) param pytree, sharded
        self._opt_state = None
        self._grad_acc = None          # accumulated grads (fp32, ZeRO-sharded)
        self._found_inf_acc = None
        self._plan = None
        self._compiled = {}
        self._last_loss = None
        self.warn_unscaled_loss = True
        # persistent compile/executable cache (runtime/compile_cache.py):
        # None = disabled, the plain jit path below is untouched
        from deepspeed_tpu.runtime.compile_cache import ProgramCache
        self._program_cache = ProgramCache.from_config(
            getattr(self._config, "compile_cache", None))
        self._train_aot = {}     # abstract signature -> AOT executable

        # ZeRO-Offload (reference stage_1_and_2.py:1037 CPU-offload path /
        # stage3.py:1637 NVMe): host-resident fp32 masters + moments stepped
        # by the native C++ Adam; device keeps bf16 working params only.
        off = self._config.zero_config.offload_optimizer
        self._offload_cfg = off if (off is not None and off.device != "none") else None
        self._host_opt = None

        self.optimizer = self.client_optimizer or build_optimizer(self._config.optimizer)
        if self._offload_cfg is not None and self.optimizer is not None and \
                "adam" not in type(self.optimizer).__name__.lower():
            # the host kernel implements Adam/AdamW only — replacing a
            # non-Adam optimizer silently would change the training
            # trajectory (reference validates the offload optimizer)
            raise ValueError(
                "zero_optimization.offload_optimizer requires an Adam-family "
                f"optimizer, got {type(self.optimizer).__name__}")
        self.lr_scheduler = self.client_lr_scheduler or build_lr_scheduler(
            self._config.scheduler, self.optimizer)
        self.loss_scaler = create_loss_scaler(self._config.fp16)
        self._scaler_state = self._replicate(self.loss_scaler.init())

        # precision
        if self._config.fp16.enabled:
            self.compute_dtype = jnp.float16
        elif self._config.bf16.enabled:
            self.compute_dtype = jnp.bfloat16
        else:
            self.compute_dtype = jnp.float32
        # persistent master-param storage dtype (fp32 unless the memory-lean
        # bf16 master option is on; optimizer math stays fp32 either way)
        self._master_dtype = jnp.bfloat16 \
            if (self._config.bf16.enabled
                and self._config.bf16.master_weights_in_bf16) else jnp.float32
        if self._config.bf16.master_weights_in_bf16 \
                and not self._config.bf16.enabled:
            logger.warning(
                "bf16.master_weights_in_bf16 is set but bf16.enabled is "
                "false — masters stay fp32; the memory-lean mode requires "
                "bf16 compute")

        accel = get_accelerator()
        accel.manual_seed(self._config.seed)
        self._rng = jax.random.key(self._config.seed)

        self.monitor = MonitorMaster(self._config.monitor_config)
        self.timers = SynchronizedWallClockTimer()

        # Curriculum learning (reference engine.py:1700-1708 curriculum_seqlen
        # kwarg injection): here the engine slices the batch's sequence axis
        # to the scheduler's current difficulty before the jitted step — each
        # quantised seqlen is its own cached XLA program.
        self.curriculum_scheduler = None
        if self._config.curriculum_learning_legacy.enabled:
            from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import \
                CurriculumScheduler
            cl = self._config.curriculum_learning_legacy
            self.curriculum_scheduler = CurriculumScheduler({
                "min_difficulty": cl.min_difficulty,
                "max_difficulty": cl.max_difficulty,
                "schedule_type": cl.schedule_type,
                "schedule_config": cl.schedule_config,
            })
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_batch_size(),
            steps_per_output=self.steps_per_print())

        # model adapter
        self._setup_model_fns(model, model_parameters)

        if training_data is not None:
            self.training_dataloader = self.deepspeed_io(training_data, collate_fn=collate_fn)

        # reference engine.py:858 _configure_checkpointing: nebula block
        # selects the async tiered engine
        if getattr(self._config, "nebula_config", None) is not None \
                and self._config.nebula_config.enabled:
            from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine \
                import NebulaCheckpointEngine
            self.checkpoint_engine = NebulaCheckpointEngine(
                self._config.nebula_config)
        else:
            self.checkpoint_engine = OrbaxCheckpointEngine()
        self.flops_profiler = None
        if self._config.flops_profiler.enabled:
            from deepspeed_tpu.profiling.flops_profiler.profiler import FlopsProfiler
            self.flops_profiler = FlopsProfiler(self)

        log_dist(f"DeepSpeedEngine configured: zero_stage={self.zero_optimization_stage()} "
                 f"mesh={dict(self.mesh.shape)} dtype={self.compute_dtype.__name__} "
                 f"micro_bs={self.train_micro_batch_size_per_gpu()} "
                 f"gas={self.gradient_accumulation_steps()}", ranks=[0])

    # ------------------------------------------------------------------ #
    # Config property accessors (reference engine.py:456-825)
    # ------------------------------------------------------------------ #
    def train_batch_size(self):
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self._config.gradient_accumulation_steps

    def zero_optimization_stage(self):
        return self._config.zero_config.stage

    def zero_optimization(self):
        return self._config.zero_enabled

    def gradient_clipping(self):
        return self._config.gradient_clipping

    def steps_per_print(self):
        return self._config.steps_per_print

    def fp16_enabled(self):
        return self._config.fp16.enabled

    def bfloat16_enabled(self):
        return self._config.bf16.enabled

    def wall_clock_breakdown(self):
        return self._config.wall_clock_breakdown

    def get_global_grad_norm(self):
        return getattr(self, "_last_global_grad_norm", None)

    def get_lr(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler.get_last_lr()
        lr = getattr(self.optimizer, "lr", 0.0)
        return [lr]

    def learning_rate(self):
        return self.get_lr()[0]

    @property
    def communication_data_type(self):
        return self._config.communication_data_type

    def train(self, mode=True):
        self.training = mode
        return self

    def eval(self):
        return self.train(False)

    # ------------------------------------------------------------------ #
    # Model adapter + lazy sharded init (zero.Init analog)
    # ------------------------------------------------------------------ #
    def _setup_model_fns(self, model, model_parameters):
        self._is_flax = _is_flax_module(model)
        if self._is_flax:
            self._raw_apply = model.apply
            self._init_fn = model.init
        elif callable(model):
            self._raw_apply = model
            self._init_fn = getattr(model, "init", None)
        elif model is None and model_parameters is not None and self.loss_fn is not None:
            self._raw_apply = self.loss_fn
            self._init_fn = None
        else:
            raise ValueError("model must be a flax Module or callable apply_fn")

        if model_parameters is not None and not _is_generator(model_parameters):
            self._init_params_from(model_parameters)

    def _apply_model(self, params, args, kwargs, rng, train):
        """Call the model with compute-dtype params (mixed precision: master
        fp32 params cast at use — the bf16/fp16 cast the reference does once
        at wrap time, ``engine.py:1020``)."""
        cast = jax.tree.map(
            lambda p: p.astype(self.compute_dtype)
            if (hasattr(p, "dtype") and jnp.issubdtype(p.dtype, jnp.floating)) else p,
            params)
        if self._is_flax:
            kw = dict(kwargs)
            if train:
                kw.setdefault("rngs", {"dropout": rng})
            try:
                out = self._raw_apply(cast, *args, **kw)
            except TypeError:
                kw.pop("rngs", None)
                out = self._raw_apply(cast, *args, **kw)
        else:
            out = self._raw_apply(cast, *args, **kwargs)
        return out

    def _extract_loss(self, out):
        if isinstance(out, tuple):
            return out[0], out[1:]
        return out, ()

    def _init_params_from(self, params, materialize_opt=True):
        """Place user-provided params: cast to fp32 master, shard per plan.
        ``materialize_opt=False`` computes optimizer shardings only (the
        caller will install loaded state) — no fresh m/v allocation."""
        abstract = jax.eval_shape(lambda t: jax.tree.map(
            lambda p: p.astype(self._master_dtype)
            if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating) else jnp.asarray(p),
            t), params)
        self._build_plan(abstract)
        put = jax.jit(
            lambda t: jax.tree.map(
                lambda p: p.astype(self._master_dtype)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, t),
            out_shardings=self._plan.param_shardings)
        self._params = put(params)
        self._init_opt_state(materialize=materialize_opt)

    def _build_plan(self, abstract_params):
        self._plan = build_sharding_plan(abstract_params, self.topology,
                                         self._config.zero_config)
        self._abstract_params = abstract_params

    def _init_opt_state(self, materialize=True):
        if self._offload_cfg is not None:
            from deepspeed_tpu.runtime.zero.offload import HostOffloadedAdam
            opt = self.optimizer
            self._host_opt = HostOffloadedAdam(
                self._abstract_params, self._offload_cfg,
                lr=getattr(opt, "lr", 1e-3),
                betas=(getattr(opt, "beta1", 0.9), getattr(opt, "beta2", 0.999)),
                eps=getattr(opt, "eps", 1e-8),
                weight_decay=getattr(opt, "weight_decay", 0.0),
                adamw_mode=getattr(opt, "adam_w_mode", True),
                bias_correction=getattr(opt, "bias_correction", True))
            self._host_opt.init_from_params(self._params)
            # downcast device params to the compute dtype: the HBM saving
            # that is the point of offload (masters now live on host)
            cast = jax.jit(
                lambda t: jax.tree.map(
                    lambda p: p.astype(self.compute_dtype)
                    if jnp.issubdtype(p.dtype, jnp.floating) else p, t),
                out_shardings=self._plan.param_shardings,
                donate_argnums=(0,))
            self._params = cast(self._params)
            self._opt_state = None
            self._opt_shardings = None
            return
        abstract_opt = jax.eval_shape(self.optimizer.init, self._abstract_params)
        self._opt_shardings = _opt_state_shardings(
            abstract_opt, self._abstract_params, self._plan.opt_specs, self.mesh)
        if not materialize:        # caller installs loaded state itself
            self._abstract_opt = abstract_opt
            return
        init_jit = jax.jit(self.optimizer.init, out_shardings=self._opt_shardings)
        self._opt_state = init_jit(self._params)

    def _lazy_init(self, args, kwargs):
        """First-forward param init, jitted with sharded out_shardings so
        full weights never materialize on one device (zero.Init analog,
        reference ``partition_parameters.py:603``)."""
        if self._params is not None:
            return
        if self._init_fn is None:
            raise RuntimeError("no parameters: pass model_parameters or use a flax module")
        self._rng, init_rng = jax.random.split(self._rng)
        abstract = jax.eval_shape(lambda r: self._init_fn(r, *args, **kwargs), init_rng)
        abstract = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(
                s.shape, self._master_dtype
                if jnp.issubdtype(s.dtype, jnp.floating) else s.dtype),
            abstract)
        self._build_plan(abstract)
        init_jit = jax.jit(
            lambda r, a, kw: jax.tree.map(
                lambda p: p.astype(self._master_dtype)
                if jnp.issubdtype(p.dtype, jnp.floating) else p,
                self._init_fn(r, *a, **kw)),
            out_shardings=self._plan.param_shardings)
        self._params = init_jit(init_rng, args, kwargs)
        n_params = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(self._params))
        log_dist(f"initialized {n_params/1e6:.2f}M parameters (sharded at birth)", ranks=[0])
        self._init_opt_state()

    # ------------------------------------------------------------------ #
    # Data placement
    # ------------------------------------------------------------------ #
    def _replicate(self, tree):
        sh = NamedSharding(self.mesh, P())
        return jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), sh), tree)

    def _data_sharding(self, ndim):
        parts = [topo_mod.DP_AXES]
        if self.topology.sp > 1 and ndim >= 2:
            parts.append(topo_mod.SP_AXIS)
        return NamedSharding(self.mesh, P(*parts))

    def put_batch(self, batch):
        """Shard a host batch across the DP (and sp) mesh axes."""
        def put(x):
            x = jnp.asarray(x) if not isinstance(x, jax.Array) else x
            if x.ndim == 0:  # tpu-lint: disable=TL006 -- rank probe for scalar placement; a workload's batch ranks are fixed, not per-step drift
                return jax.device_put(x, NamedSharding(self.mesh, P()))  # tpu-lint: disable=TL010,TL011 -- rank-0 host scalars replicate by definition, and this put is the batch's host->device ADMISSION, not a reshard
            return jax.device_put(x, self._data_sharding(x.ndim))  # tpu-lint: disable=TL011 -- host->device batch admission: the input starts on the host and this is its one placement into the DP/sp layout
        return jax.tree.map(put, batch)

    def deepspeed_io(self, dataset, batch_size=None, collate_fn=None, num_workers=0):
        """Build the sharded training loader (reference ``engine.py:1571``)."""
        from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader
        return DeepSpeedDataLoader(
            dataset,
            batch_size=batch_size or self.train_micro_batch_size_per_gpu() * self.topology.dp,
            collate_fn=collate_fn,
            num_workers=num_workers,
            engine=self)

    # ------------------------------------------------------------------ #
    # forward / backward / step
    # ------------------------------------------------------------------ #
    @hot_path("runtime.fwd_bwd")
    def _fwd_bwd_core(self, params, scale, rng, *args, **kwargs):
        """Traced body shared by ``_get_fwd_bwd`` (fresh grads) and
        ``_get_fwd_bwd_acc`` (fused accumulate)."""
        gas = self.gradient_accumulation_steps()

        def loss_of(p):
            out = self._apply_model(p, args, kwargs, rng, train=True)
            loss, aux = self._extract_loss(out)
            # reference engine.py:1821: scale loss by 1/GAS
            scaled = loss.astype(jnp.float32) * scale / gas
            return scaled, (loss, aux)

        grads, (loss, aux) = jax.grad(loss_of, has_aux=True)(params)
        # grad accumulation dtype: fp32 by default even when working
        # params are 16-bit (offload path; reference stage_1_and_2.py
        # fp32 accum); ``data_types.grad_accum_dtype: "bf16"`` halves the
        # accumulator — the enabler for 2.7B-class offload on a 16 GB
        # chip, at the documented cost of bf16 addition noise across the
        # accumulation window (reference data_types knob)
        grads, found_inf = _finish_grads(grads, self._accum_dtype())
        return grads, loss, found_inf

    def _get_fwd_bwd(self):
        key = "fwd_bwd"
        if key not in self._compiled:
            self._compiled[key] = jax.jit(  # tpu-lint: disable=TL002 -- params must stay live: the same buffers feed every micro-step and the optimizer step
                self._fwd_bwd_core,
                out_shardings=(self._plan.grad_shardings,
                               NamedSharding(self.mesh, P()),
                               NamedSharding(self.mesh, P())))
        return self._compiled[key]

    def _accum_dtype(self):
        table = {"bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
                 "fp16": jnp.float16, "float16": jnp.float16,
                 "fp32": jnp.float32, "float32": jnp.float32}
        want = self._config.gradient_accumulation_dtype or "fp32"
        if want not in table:
            raise ValueError(
                f"data_types.grad_accum_dtype={want!r}: expected "
                f"one of {sorted(table)} (or null = fp32)")
        return table[want]

    def _group_bounds(self, n_groups):
        """Contiguous leaf-index ranges of ~equal parameter bytes for the
        partitioned backward (zero_optimization.grad_partition_groups)."""
        sizes = [int(np.prod(l.shape)) * l.dtype.itemsize
                 for l in jax.tree.leaves(self._params)]
        total = sum(sizes)
        bounds, lo, acc = [], 0, 0
        for i, s in enumerate(sizes):
            acc += s
            if acc >= total * (len(bounds) + 1) / n_groups \
                    and len(bounds) < n_groups - 1:
                bounds.append((lo, i + 1))
                lo = i + 1
        bounds.append((lo, len(sizes)))
        return [b for b in bounds if b[0] < b[1]]

    def _get_fwd_bwd_group(self, lo, hi):
        """Partitioned backward: gradients for leaves [lo:hi) only — the
        other parameters enter the loss as constants, so this program's
        gradient temporaries are ~1/N of the tree.  Each group re-runs
        the forward+backward sweep (FLOPs for memory — the trade that
        fits 2.7B's boundary on one 16 GB chip, where the step is
        host-link-bound anyway)."""
        key = ("fwd_bwd_group", lo, hi)
        if key not in self._compiled:
            gas = self.gradient_accumulation_steps()
            acc_dt = self._accum_dtype()

            def fwd_bwd_g(params, acc_slice, scale, rng, *args, **kwargs):
                flat, treedef = jax.tree_util.tree_flatten(params)

                def loss_of(group):
                    flat2 = list(flat)
                    flat2[lo:hi] = group
                    p = jax.tree_util.tree_unflatten(treedef, flat2)
                    out = self._apply_model(p, args, kwargs, rng,
                                            train=True)
                    loss, aux = self._extract_loss(out)
                    return loss.astype(jnp.float32) * scale / gas, loss

                grads, loss = jax.grad(loss_of, has_aux=True)(flat[lo:hi])
                grads, found_inf = _finish_grads(grads, acc_dt)
                acc_slice = [a + g for a, g in zip(acc_slice, grads)]
                return acc_slice, loss, found_inf

            gshard = jax.tree.leaves(self._plan.grad_shardings)[lo:hi]
            self._compiled[key] = jax.jit(
                fwd_bwd_g,
                donate_argnums=(1,),
                out_shardings=(gshard,
                               NamedSharding(self.mesh, P()),
                               NamedSharding(self.mesh, P())))
        return self._compiled[key]

    def _forward_grouped(self, n_groups, step_rng, args, kwargs):
        """One micro-step through the partitioned backward (see
        ``_get_fwd_bwd_group``): every group pass adds its gradient slice
        into the running accumulator in place."""
        if self._grad_acc is None:
            if "acc_zeros" not in self._compiled:
                acc_dt = self._accum_dtype()
                # close over SHAPES only — capturing the live param arrays
                # would pin this window's params forever (they are
                # replaced every optimizer step)
                shapes = jax.tree.map(lambda l: l.shape, self._params)
                self._compiled["acc_zeros"] = jax.jit(
                    lambda: jax.tree.map(
                        lambda s: jnp.zeros(s, acc_dt), shapes,
                        is_leaf=lambda x: isinstance(x, tuple)),
                    out_shardings=self._plan.grad_shardings)
            self._grad_acc = self._compiled["acc_zeros"]()
            self._found_inf_acc = jnp.asarray(False)
        flat_acc, acc_def = jax.tree_util.tree_flatten(self._grad_acc)
        self._grad_acc = None              # detach before donating calls
        loss = found = None
        try:
            for lo, hi in self._group_bounds(n_groups):
                new_slice, loss, fi = self._get_fwd_bwd_group(lo, hi)(
                    self._params, flat_acc[lo:hi], self._scaler_state.scale,
                    step_rng, *args, **kwargs)
                flat_acc[lo:hi] = list(new_slice)
                found = fi if found is None else jnp.logical_or(found, fi)
        except BaseException:
            # a failed pass leaves donated (dead) slices behind — keep the
            # accumulator detached (None) so the next micro-step restarts
            # the window instead of feeding deleted buffers back in
            self._grad_acc = None
            raise
        self._grad_acc = jax.tree_util.tree_unflatten(acc_def, flat_acc)
        return loss, found

    def _get_fwd_bwd_acc(self):
        """Fused gradient-compute + accumulate: like ``_get_fwd_bwd`` but
        the running accumulator rides in as a DONATED argument and the
        program returns ``acc + grads`` — the fresh gradient tree never
        coexists with params AND the accumulator as a third full-size
        tree (see forward())."""
        key = "fwd_bwd_acc"
        if key not in self._compiled:
            fwd_bwd_core = self._fwd_bwd_core

            @hot_path("runtime.fwd_bwd_acc")
            def fwd_bwd_acc(params, acc, scale, rng, *args, **kwargs):
                grads, loss, found_inf = fwd_bwd_core(params, scale, rng,
                                                      *args, **kwargs)
                acc = jax.tree.map(jnp.add, acc, grads)
                return acc, loss, found_inf

            self._compiled[key] = jax.jit(
                fwd_bwd_acc,
                donate_argnums=(1,),
                out_shardings=(self._plan.grad_shardings,
                               NamedSharding(self.mesh, P()),
                               NamedSharding(self.mesh, P())))
        return self._compiled[key]

    def _get_fwd_only(self):
        key = "fwd_only"
        if key not in self._compiled:
            def fwd(params, rng, *args, **kwargs):
                return self._apply_model(params, args, kwargs, rng, train=False)
            self._compiled[key] = jax.jit(fwd)  # tpu-lint: disable=TL002 -- eval forward: params are read-only and stay live for the next step
        return self._compiled[key]

    def _get_accum(self):
        key = "accum"
        if key not in self._compiled:
            self._compiled[key] = jax.jit(
                lambda acc, g: jax.tree.map(jnp.add, acc, g),
                donate_argnums=(0,))
        return self._compiled[key]

    def _curriculum_slice(self, batch, lead_dims):
        """Slice the sequence axis of every leaf to the scheduler's current
        difficulty (reference engine.py:1700-1708 injects curriculum_seqlen;
        here the engine slices directly).  Only axes beyond the leading
        ``lead_dims`` batch axes whose length equals the reference sequence
        length (taken from ``input_ids``) are sliced — square attention
        masks get both seq axes sliced, hidden dims are untouched.
        Init must happen on the full-length batch *before* this runs."""
        if (self.curriculum_scheduler is None or not self.training
                or self._config.curriculum_learning_legacy.curriculum_type != "seqlen"):
            return batch
        seqlen = self.curriculum_scheduler.update_difficulty(self.global_steps + 1)
        ref_seq = None
        if isinstance(batch, dict) and "input_ids" in batch:
            ref_seq = batch["input_ids"].shape[-1]
        if ref_seq is None or seqlen >= ref_seq:
            return batch

        def slc(x):
            if getattr(x, "ndim", 0) <= lead_dims:
                return x
            idx = tuple(
                slice(0, seqlen) if d >= lead_dims and x.shape[d] == ref_seq
                else slice(None) for d in range(x.ndim))
            return x[idx]

        return jax.tree.map(slc, batch)

    def _maybe_start_profiler(self, batch):
        """Start the flops profiler at the configured step (reference
        ``engine.py:1692``); training steps only."""
        if self.flops_profiler is not None \
                and not self.flops_profiler.started and self.training \
                and self.global_steps + 1 == \
                self._config.flops_profiler.profile_step:
            self.flops_profiler.start_profile()
            self._profile_batch = batch

    def _maybe_finish_profiler(self):
        """Stop + print when the profiled step completes (reference: the
        profile step's report prints at the end of its step)."""
        if self.flops_profiler is not None and self.flops_profiler.started:
            pcfg = self._config.flops_profiler
            self.flops_profiler.stop_profile()
            self.flops_profiler.print_model_profile(
                profile_step=pcfg.profile_step,
                module_depth=pcfg.module_depth,
                top_modules=pcfg.top_modules,
                detailed=pcfg.detailed,
                output_file=pcfg.output_file,
                batch=getattr(self, "_profile_batch", None))

    @hot_path("runtime.forward")
    def forward(self, *args, **kwargs):
        self._lazy_init(args, kwargs)
        args = tuple(self._curriculum_slice(a, 1) if _is_batch_like(a) else a
                     for a in args)
        kwargs = {k: self._curriculum_slice(v, 1) if _is_batch_like(v) else v
                  for k, v in kwargs.items()}
        # capture the batch AFTER curriculum slicing so the profiled program
        # has the shapes the step actually runs; the batch may arrive as a
        # positional OR a keyword argument
        self._maybe_start_profiler(
            next((a for a in (*args, *kwargs.values())
                  if _is_batch_like(a)), None))
        args = tuple(self.put_batch(a) if _is_batch_like(a) else a for a in args)
        kwargs = {k: self.put_batch(v) if _is_batch_like(v) else v
                  for k, v in kwargs.items()}
        if self.wall_clock_breakdown():
            self.timers(FORWARD_GLOBAL_TIMER).start()
        self._rng, step_rng = jax.random.split(self._rng)
        if not self.training:
            out = self._get_fwd_only()(self._params, step_rng, *args, **kwargs)
            if self.wall_clock_breakdown():
                self.timers(FORWARD_GLOBAL_TIMER).stop()
            return out
        self.tput_timer.start()
        if getattr(self, "_pending", None) is not None \
                and self._grad_acc is not None:
            # gradients from the un-backward()ed forward are already IN
            # the running accumulator (fused/grouped paths) or would be
            # silently dropped mid-window — either way the window would
            # train on the wrong gradient sum.  (A fresh forward with NO
            # window in flight stays allowed: loss-only forwards are a
            # legitimate pattern and their pending grads are discarded.)
            raise RuntimeError(
                "forward() called twice without backward() inside an "
                "accumulation window — call backward(loss) after each "
                "forward")
        n_groups = int(getattr(self._config.zero_config,
                               "grad_partition_groups", 1) or 1)
        if n_groups > 1:
            if getattr(self, "_pending", None) is not None:
                # grouped mode accumulates on the FIRST micro too — a
                # pending forward's grads are already in the buffer
                raise RuntimeError(
                    "forward() called twice without backward() (grouped "
                    "accumulation adds into the running buffer)")
            loss, found_inf = self._forward_grouped(n_groups, step_rng,
                                                    args, kwargs)
            self._pending = (_ACCUMULATED, found_inf)
            self._last_loss = loss
            if self.wall_clock_breakdown():
                self.timers(FORWARD_GLOBAL_TIMER).stop()
            return loss
        if self._grad_acc is None:
            grads, loss, found_inf = self._get_fwd_bwd()(
                self._params, self._scaler_state.scale, step_rng,
                *args, **kwargs)
            self._pending = (grads, found_inf)
        else:
            # micro-steps after the first ADD into the donated running
            # accumulator inside the SAME program that computes the
            # gradients: a separate grad tree + accumulate would hold
            # THREE param-sized trees at the boundary (params + acc +
            # fresh grads = 15.9 GB at 2.7B bf16 — the OOM that killed
            # the first single-chip 2.7B run); fused, XLA folds each
            # layer's add into its grad computation and the fresh tree
            # never fully materializes
            # detach the accumulator BEFORE the donating call: a failure
            # mid-program would otherwise leave self._grad_acc bound to
            # the donated (deleted) buffer and poison the next micro-step
            acc, self._grad_acc = self._grad_acc, None
            self._grad_acc, loss, found_inf = self._get_fwd_bwd_acc()(
                self._params, acc, self._scaler_state.scale,
                step_rng, *args, **kwargs)
            self._pending = (_ACCUMULATED, found_inf)
        self._last_loss = loss
        if self.wall_clock_breakdown():
            self.timers(FORWARD_GLOBAL_TIMER).stop()
        return loss

    __call__ = forward

    def backward(self, loss, retain_graph=False):
        """Accumulate the gradients produced by forward (reference
        ``engine.py:1804``; in JAX fwd+bwd are one fused program, so backward
        is the accumulation phase)."""
        if not self.training:
            raise RuntimeError("backward called in eval mode")
        if getattr(self, "_pending", None) is None:
            raise RuntimeError("backward called without a prior forward")
        if self.wall_clock_breakdown():
            self.timers(BACKWARD_GLOBAL_TIMER).start()
        grads, found_inf = self._pending
        self._pending = None
        if grads is _ACCUMULATED:
            # forward already added this micro's grads into the running
            # accumulator (fused program — see forward)
            self._found_inf_acc = jnp.logical_or(self._found_inf_acc,
                                                 found_inf)
        elif self._grad_acc is None:
            self._grad_acc = grads
            self._found_inf_acc = found_inf
        else:
            self._grad_acc = self._get_accum()(self._grad_acc, grads)
            self._found_inf_acc = jnp.logical_or(self._found_inf_acc, found_inf)
        self.micro_steps += 1
        if self.wall_clock_breakdown():
            self.timers(BACKWARD_GLOBAL_TIMER).stop()
        return loss

    @property
    def skipped_steps(self):
        """Overflow-skipped step count; reading drains any pending device
        flags in one batched transfer (the per-step flag is never synced on
        the hot path — see step())."""
        self._drain_skipped_steps()
        return self._skipped_steps

    @skipped_steps.setter
    def skipped_steps(self, value):
        self._pending_inf_flags = []
        self._skipped_steps = int(value)

    def _drain_skipped_steps(self):  # tpu-lint: disable=TL001 -- this IS the amortized sync point: one batched read for all queued flags
        if self._pending_inf_flags:
            flags, self._pending_inf_flags = self._pending_inf_flags, []
            # device_get batches the list itself — a jnp.stack would compile
            # a fresh N-scalar program per distinct queue length
            self._skipped_steps += int(np.sum(jax.device_get(flags)))

    def is_gradient_accumulation_boundary(self):
        return self.micro_steps % self.gradient_accumulation_steps() == 0

    def zero_grad(self):
        self._grad_acc = None
        self._found_inf_acc = None

    def _get_apply(self):
        key = "apply"
        if key not in self._compiled:
            clip = float(self.gradient_clipping() or 0.0)
            scaler = self.loss_scaler

            @hot_path("runtime.apply_update")
            def apply_update(params, opt_state, scaler_state, grads, found_inf, lr, step):
                grads, gnorm = _unscale_and_clip(grads, scaler_state.scale, clip)
                new_params, new_opt = self.optimizer.update(grads, opt_state, params,
                                                            lr=lr, step=step)
                # branch-free overflow skip (reference stage_1_and_2.py:1808)
                keep = lambda new, old: jax.tree.map(
                    lambda n, o: jnp.where(found_inf, o, n), new, old)
                new_params = keep(new_params, params)
                new_opt = keep(new_opt, opt_state)
                new_scaler = scaler.update(scaler_state, found_inf)
                return new_params, new_opt, new_scaler, gnorm

            self._compiled[key] = jax.jit(
                apply_update,
                donate_argnums=(0, 1, 2, 3),
                out_shardings=(self._plan.param_shardings, self._opt_shardings,
                               None, None))
        return self._compiled[key]

    @hot_path("runtime.step")
    def step(self, lr_kwargs=None):
        """Optimizer step at the accumulation boundary (reference
        ``engine.py:2000`` / ``_take_model_step:1935``)."""
        if not self.is_gradient_accumulation_boundary():
            return
        if self._grad_acc is None:
            raise RuntimeError("step called with no accumulated gradients")
        if self.wall_clock_breakdown():
            self.timers(STEP_GLOBAL_TIMER).start()
        if self._host_opt is not None:
            self._offload_step(lr_kwargs)
            if self.wall_clock_breakdown():
                self.timers(STEP_GLOBAL_TIMER).stop()
            return
        lr = jnp.asarray(self.get_lr()[0], jnp.float32)
        step_no = jnp.asarray(self.global_steps + 1, jnp.int32)
        found_inf_acc = self._found_inf_acc
        (self._params, self._opt_state, self._scaler_state, gnorm) = self._get_apply()(
            self._params, self._opt_state, self._scaler_state,
            self._grad_acc, found_inf_acc, lr, step_no)
        self._last_global_grad_norm = gnorm
        self.zero_grad()
        self.global_steps += 1
        self.global_samples += self.train_batch_size()
        if self.lr_scheduler is not None:
            self.lr_scheduler.step(**(lr_kwargs or {}))
        if self.fp16_enabled() and found_inf_acc is not None:
            # surface skipped steps for parity with reference loss-scale
            # logs — but do NOT read the flag here: that host sync would
            # serialize every fp16 step.  Flags queue on device and drain
            # in one batched read at the logging boundary (or whenever
            # skipped_steps is read, e.g. checkpoint save).
            self._pending_inf_flags.append(found_inf_acc)
            if self.global_steps % self.steps_per_print() == 0:
                before = self._skipped_steps
                self._drain_skipped_steps()
                if self._skipped_steps > before:
                    log_dist(
                        f"overflow: skipped {self._skipped_steps - before} "
                        f"recent step(s), new loss scale "
                        f"{float(jax.device_get(self._scaler_state.scale))}",  # tpu-lint: disable=TL001 -- print-gated, amortized over steps_per_print
                        ranks=[0])
        self.tput_timer.stop(global_step=True)
        self._maybe_finish_profiler()
        if self.monitor.enabled and self.global_steps % self.steps_per_print() == 0:
            events = [("Train/Samples/lr", self.get_lr()[0], self.global_samples)]
            if self._last_loss is not None:
                events.append(("Train/Samples/train_loss",
                               float(jax.device_get(self._last_loss)), self.global_samples))  # tpu-lint: disable=TL001 -- monitor read, gated on steps_per_print
            self.monitor.write_events(events + self._hbm_events())
        if self.wall_clock_breakdown():
            self.timers(STEP_GLOBAL_TIMER).stop()
            if self.global_steps % self.steps_per_print() == 0:
                self.timers.log([FORWARD_GLOBAL_TIMER, BACKWARD_GLOBAL_TIMER,
                                 STEP_GLOBAL_TIMER])

    def _offload_step(self, lr_kwargs=None):  # tpu-lint: disable=TL001 -- ZeRO-Offload: grads cross to the host BY DESIGN (see docstring)
        """Host optimizer step (ZeRO-Offload): host-side unscale/clip ->
        host C++ Adam -> upload (reference stage_1_and_2.py:1630 CPU Adam
        step + :1750 updated-param gather).  The unscale + global-norm
        clip run ON HOST (numpy, fp32): a device prep program at the
        boundary held grad-sized temps next to params + accumulator —
        the last straw for 2.7B on a 16 GB chip — and the grads are
        crossing to the host anyway."""
        flat_acc = list(jax.tree.leaves(self._grad_acc))
        self._grad_acc = None
        found_inf = bool(jax.device_get(self._found_inf_acc)) \
            if self._found_inf_acc is not None else False
        if not found_inf:
            host_grads = []
            for i in range(len(flat_acc)):
                host_grads.append(np.asarray(jax.device_get(flat_acc[i]),
                                             dtype=np.float32))
                flat_acc[i] = None         # free each device leaf as it
                # lands — never hold the full acc on BOTH sides
            inv = 1.0 / float(jax.device_get(self._scaler_state.scale))
            sq = sum(float(np.dot(g.ravel(), g.ravel()))
                     for g in host_grads)
            gnorm = float(np.sqrt(sq)) * inv
            clip = float(self.gradient_clipping() or 0.0)
            factor = inv * (min(1.0, clip / (gnorm + 1e-6)) if clip > 0.0
                            else 1.0)
            if factor != 1.0:
                for g in host_grads:
                    np.multiply(g, np.float32(factor), out=g)
            self._last_global_grad_norm = gnorm
            # fp32 compute must upload the fp32 masters directly — rounding
            # working params through bf16 every step would silently degrade
            # full-precision training
            want_fp32 = self.compute_dtype != jnp.bfloat16
            leaves = self._host_opt.step(host_grads, lr=self.get_lr()[0],
                                         fp32_out=want_fp32)
            new_tree = self._host_opt.leaves_to_tree(leaves)
            dtypes = jax.tree.map(lambda p: p.dtype, self._params)
            self._params = None            # free old params before upload
            new_tree = jax.tree.map(
                lambda a, dt: a if a.dtype == dt else a.astype(dt),
                new_tree, dtypes)
            # one host->device transfer straight into the sharded layout —
            # an eager asarray + re-placement jit would hold two device
            # copies of the new params
            self._params = jax.device_put(  # tpu-lint: disable=TL011 -- offload path: the host optimizer's new params start on the host; this is their one upload into the sharded layout, not a reshard
                new_tree, self._plan.param_shardings)
        else:
            self.skipped_steps += 1
            # the skipped step's norm is the honest value for telemetry —
            # leaving the previous step's number would make overflow steps
            # invisible in grad-norm logs
            self._last_global_grad_norm = float("inf")
        self._scaler_state = self.loss_scaler.update(
            self._scaler_state, jnp.asarray(found_inf))
        self.zero_grad()
        self.global_steps += 1
        self.global_samples += self.train_batch_size()
        if self.lr_scheduler is not None:
            self.lr_scheduler.step(**(lr_kwargs or {}))
        self.tput_timer.stop(global_step=True)

    # ------------------------------------------------------------------ #
    # Fully-fused train step (scan over GAS) — the benchmark hot path
    # ------------------------------------------------------------------ #
    def _get_fused_step(self):
        key = "fused_step"
        if key not in self._compiled:
            gas = self.gradient_accumulation_steps()
            clip = float(self.gradient_clipping() or 0.0)
            scaler = self.loss_scaler
            # bf16/fp32 run a static UNIT scale: the overflow check (a full
            # pass over every gradient), the where-select rollback, and the
            # scaler update are dead weight — compile them out.  An explicit
            # fp16 static loss_scale != 1 still needs unscaling AND the
            # overflow skip, so only scale==1.0 takes the fast path.
            from deepspeed_tpu.runtime.fp16.loss_scaler import StaticLossScaler
            static_scale = isinstance(scaler, StaticLossScaler) and \
                float(scaler.scale_value) == 1.0  # tpu-lint: disable=TL001 -- python attribute of the host-side scaler, runs once per compile

            @hot_path("runtime.train_step")
            def train_step(params, opt_state, scaler_state, lr, step, rng, batches):
                # derive this step's stream on-device: the caller passes the
                # same base key every step (no per-step host-side split op)
                rng = jax.random.fold_in(rng, step)

                def micro(carry, mb):
                    acc, inf_acc, r = carry
                    r, sub = jax.random.split(r)

                    def loss_of(p):
                        out = self._apply_model(p, (mb,), {}, sub, train=True)
                        loss, _ = self._extract_loss(out)
                        return loss.astype(jnp.float32) * scaler_state.scale / gas, loss

                    grads, loss = jax.grad(loss_of, has_aux=True)(params)
                    if not static_scale:
                        flat = jax.tree.leaves(grads)
                        inf = jnp.logical_not(jnp.all(jnp.stack(
                            [jnp.all(jnp.isfinite(g)) for g in flat])))
                        inf_acc = jnp.logical_or(inf_acc, inf)
                    acc = jax.tree.map(jnp.add, acc, grads) if acc is not None \
                        else grads
                    return (acc, inf_acc, r), loss

                if gas == 1:
                    # no accumulation buffer: saves a full-size zero init +
                    # read-modify-write over the gradients
                    mb = jax.tree.map(lambda x: x[0], batches)
                    (acc, found_inf, _), loss0 = micro(
                        (None, jnp.asarray(False), rng), mb)
                    losses = loss0[None]
                else:
                    zero_acc = jax.tree.map(
                        lambda p: jnp.zeros(p.shape, jnp.float32), params)
                    (acc, found_inf, _), losses = jax.lax.scan(
                        micro, (zero_acc, jnp.asarray(False), rng), batches)
                grads, gnorm = _unscale_and_clip(
                    acc, 1.0 if static_scale else scaler_state.scale, clip)
                new_params, new_opt = self.optimizer.update(grads, opt_state, params,
                                                            lr=lr, step=step)
                if not static_scale:
                    keep = lambda new, old: jax.tree.map(
                        lambda n, o: jnp.where(found_inf, o, n), new, old)
                    new_params = keep(new_params, params)
                    new_opt = keep(new_opt, opt_state)
                new_scaler = scaler.update(scaler_state, found_inf)
                return new_params, new_opt, new_scaler, jnp.mean(losses), gnorm

            self._compiled[key] = jax.jit(
                train_step,
                donate_argnums=(0, 1, 2),
                out_shardings=(self._plan.param_shardings, self._opt_shardings,
                               None, None, None))
        return self._compiled[key]

    def _run_fused_step(self, args):
        """Execute the fused train step — through an AOT executable when
        one exists (warmup() or the compile_cache executable store),
        through the plain jit call otherwise (exactly the seed behavior
        when the compile_cache block is off)."""
        fused = self._get_fused_step()
        if self._program_cache is None and not self._train_aot:
            return fused(*args)
        from deepspeed_tpu.runtime import compile_cache as cc
        sig = cc.abstract_signature(args)
        exe = self._train_aot.get(sig)
        if exe is None:
            exe, _, _ = self._train_exe_for(fused, args, sig)
        return exe(*args)

    def _train_key_parts(self, sig):
        """Executable-store key context for the train step: everything that
        changes the compiled program besides the arg shapes."""
        import json as _json
        cfg = _json.dumps(self._config._param_dict, sort_keys=True,
                          default=repr)
        return (sig, cfg,
                repr(getattr(self.module, "config",
                             type(self.module).__name__)),
                tuple(sorted(dict(self.mesh.shape).items())),
                type(self.optimizer).__name__,
                type(self.loss_scaler).__name__)

    def _train_exe_for(self, fused, args, sig):
        """AOT-compile the fused step (consulting the executable store when
        enabled); falls back to the jit callable itself on any failure.
        Returns ``(exe, compile_seconds, store_hit)``."""
        from deepspeed_tpu.runtime.compile_cache import aot_compile_with_store
        exe, dt, hit = aot_compile_with_store(
            self._program_cache, "train_step", self._train_key_parts(sig),
            fused, args)
        if exe is None:            # AOT failed (warned): plain jit call —
            exe = fused            # no fake 0.0s compile event
        else:
            self._report_compile("train_step", dt, hit)
        self._train_aot[sig] = exe
        return exe, dt, hit

    def _report_compile(self, name, seconds, cache_hit):
        log_dist(f"compile[{name}]: "
                 + ("executable-cache hit" if cache_hit
                    else f"{seconds:.1f}s"), ranks=[0])
        if self.monitor.enabled:
            self.monitor.write_events(
                [(f"Compile/{name}_secs", seconds, self.global_steps)])

    def warmup(self, batch=None, data_iter=None):
        """Pre-compile the fused whole-step train program for this batch's
        shapes, reporting the compile time through the monitor — so the
        multi-minute large-model compile is paid at a chosen moment (and,
        with the ``compile_cache`` block enabled, once per machine) instead
        of silently inside the first ``train_batch``.  Nothing executes and
        no engine state advances; the batch (same ``[gas, micro, ...]``
        stacked contract as ``train_batch``) is only used for shapes +
        lazy param init.

        Returns ``{"train_step": seconds}`` (0.0 = executable-store hit),
        or ``{}`` on the offload / grouped-backward paths (those run the
        3-call sequence whose programs compile per micro-step).

        NOTE: ``data_iter`` is CONSUMED exactly like ``train_batch`` would
        consume it (``gas`` micro-batches) — pass a throwaway/example
        ``batch`` instead when every sample must reach training."""
        gas = self.gradient_accumulation_steps()
        n_groups = int(getattr(self._config.zero_config,
                               "grad_partition_groups", 1) or 1)
        if self._offload_cfg is not None or n_groups > 1:
            # before touching data_iter: an engine this cannot warm must
            # not eat a global batch of real training data on the way out
            logger.warning("warmup(): offload/grouped engines run the "
                           "3-call path — no fused step to precompile")
            return {}
        if batch is None:
            mbs = [next(data_iter) for _ in range(gas)]
            batch = jax.tree.map(lambda *xs: jnp.stack(xs), *mbs)
        self._lazy_init((jax.tree.map(lambda x: x[0], batch),), {})
        # same curriculum slice train_batch applies — without it the
        # warmed signature would never match the sliced batch's and the
        # first real step would recompile anyway
        batch = self._curriculum_slice(batch, 2)
        batch = jax.tree.map(
            lambda x: jax.device_put(
                jnp.asarray(x),
                NamedSharding(self.mesh,
                              P(None, *(self._data_sharding(x.ndim - 1)
                                        .spec)))),
            batch)
        lr = jnp.asarray(self.get_lr()[0], jnp.float32)
        step_no = jnp.asarray(self.global_steps + 1, jnp.int32)
        args = (self._params, self._opt_state, self._scaler_state,
                lr, step_no, self._rng, batch)
        from deepspeed_tpu.runtime import compile_cache as cc
        sig = cc.abstract_signature(args)
        if sig in self._train_aot:
            return {"train_step": 0.0}
        _, dt, hit = self._train_exe_for(self._get_fused_step(), args, sig)
        return {"train_step": 0.0 if hit else dt}

    precompile = warmup

    @hot_path("runtime.train_batch")
    def train_batch(self, data_iter=None, batch=None):
        """One full global-batch step as a single XLA program (analog of
        ``PipelineEngine.train_batch``, reference ``pipe/engine.py:286``, for
        the non-pipelined engine)."""
        gas = self.gradient_accumulation_steps()
        if batch is None:
            mbs = [next(data_iter) for _ in range(gas)]
            batch = jax.tree.map(lambda *xs: jnp.stack(xs), *mbs)
        else:
            # batch already stacked [gas, micro_batch, ...]
            pass
        n_groups = int(getattr(self._config.zero_config,
                               "grad_partition_groups", 1) or 1)
        if self._offload_cfg is not None or n_groups > 1:
            # offload path: the optimizer lives on host, so the step cannot
            # fuse into one XLA program — run the 3-call sequence per micro.
            # Same for the partitioned backward (grad_partition_groups):
            # the memory lever lives in forward()'s grouped passes
            micro_losses = []
            for i in range(gas):
                mb = jax.tree.map(lambda x: x[i], batch)
                loss = self.forward(mb)
                self.backward(loss)
                micro_losses.append(loss)
            # mean over the global batch, assigned BEFORE step() so the
            # monitor event written inside step() logs THIS iteration's loss
            self._last_loss = jnp.mean(jnp.stack(micro_losses))
            self.step()
            return self._last_loss
        self._lazy_init((jax.tree.map(lambda x: x[0], batch),), {})
        batch = self._curriculum_slice(batch, 2)
        self._maybe_start_profiler(jax.tree.map(lambda x: x[0], batch))
        batch = jax.tree.map(
            lambda x: jax.device_put(  # tpu-lint: disable=TL011 -- host->device batch admission for the fused step: one placement of the host batch into [gas, dp, ...] layout per train_batch, by design
                jnp.asarray(x),
                NamedSharding(self.mesh, P(None, *(self._data_sharding(x.ndim - 1).spec)))),
            batch)
        self.tput_timer.start()
        lr = jnp.asarray(self.get_lr()[0], jnp.float32)
        step_no = jnp.asarray(self.global_steps + 1, jnp.int32)
        args = (self._params, self._opt_state, self._scaler_state,
                lr, step_no, self._rng, batch)
        (self._params, self._opt_state, self._scaler_state, loss, gnorm) = \
            self._run_fused_step(args)
        self._last_global_grad_norm = gnorm
        self._last_loss = loss
        self.global_steps += 1
        self.micro_steps += gas
        self.global_samples += self.train_batch_size()
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        self.tput_timer.stop(global_step=True)
        self._maybe_finish_profiler()
        if self.monitor.enabled and self.global_steps % self.steps_per_print() == 0:
            # same Train/Samples series the 3-call path emits — fetching the
            # loss here syncs, but only every steps_per_print steps
            self.monitor.write_events(
                [("Train/Samples/lr", self.get_lr()[0], self.global_samples),
                 ("Train/Samples/train_loss", float(jax.device_get(loss)),  # tpu-lint: disable=TL001 -- monitor read, gated on steps_per_print
                  self.global_samples)] + self._hbm_events())
        return loss

    def _hbm_events(self):
        """Peak-HBM watermark monitor events, print-gated like the loss
        fetch (one PJRT ``memory_stats()`` host call per device through
        the accelerator's canonical reader; empty on backends with no
        live stats — the CPU test backend stays event-identical to the
        pre-telemetry engine)."""
        try:
            wm = self.hbm_watermark()
        except Exception:
            return []
        if not wm.get("peak_bytes_in_use"):
            return []
        return [("Train/Samples/hbm_bytes_in_use",
                 wm["bytes_in_use"], self.global_samples),
                ("Train/Samples/hbm_peak_bytes",
                 wm["peak_bytes_in_use"], self.global_samples)]

    def hbm_watermark(self):
        """Per-run peak-HBM watermark: the accelerator's canonical
        per-device memory record (process-lifetime peak — one training
        run owns its process in every bench phase), for callers stamping
        records (``bench.py`` train phases read this at run end)."""
        from deepspeed_tpu.monitor.memwatch import device_memory_record
        return device_memory_record()

    def eval_batch(self, batch):
        prev = self.training
        self.eval()
        out = self.forward(batch)
        self.train(prev)
        return out

    # ------------------------------------------------------------------ #
    # Checkpointing (reference engine.py:2841 save_checkpoint /
    # :2536 load_checkpoint)
    # ------------------------------------------------------------------ #
    def _fault_config(self):
        fcfg = getattr(self._config, "fault", None)
        return fcfg if (fcfg is not None and fcfg.enabled) else None

    def _checkpoint_arrays(self):
        return {
            "module": self._params,
            "optimizer": self._opt_state,
            "loss_scaler": self._scaler_state,
        }

    def _checkpoint_meta(self, client_state):
        meta = {
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "micro_steps": self.micro_steps,
            "skipped_steps": self.skipped_steps,
            "lr_scheduler": self.lr_scheduler.state_dict() if self.lr_scheduler else None,
            "ds_config": self._config._param_dict,
            "client_state": client_state or {},
        }
        # the engine RNG key: restoring it is what makes a resumed 3-call
        # trajectory bitwise-identical to an uninterrupted one (the fused
        # path folds the step counter in on-device and is already
        # deterministic given global_steps)
        try:
            meta["rng_key_data"] = np.asarray(
                jax.device_get(jax.random.key_data(self._rng)))
        except Exception:
            pass
        return meta

    def save_checkpoint(self, save_dir, tag=None, client_state=None, save_latest=True,
                        exclude_frozen_parameters=False):
        if self._params is None:
            # nothing trained yet (params are lazily initialized by the
            # first forward) — writing a weightless tag would poison
            # resume walk-back with an unloadable checkpoint
            logger.warning("save_checkpoint called before parameters "
                           "exist; nothing saved")
            return False
        if tag is None:
            tag = f"global_step{self.global_steps}"
        fcfg = self._fault_config()
        if fcfg is not None:
            return self._save_checkpoint_atomic(save_dir, str(tag),
                                                client_state, save_latest,
                                                fcfg)
        ckpt_dir = os.path.join(save_dir, str(tag))
        os.makedirs(ckpt_dir, exist_ok=True)
        self.checkpoint_engine.create(tag)
        if self._host_opt is not None:
            # streamed per-leaf .npy files — never one giant pickle
            self._host_opt.save(os.path.join(ckpt_dir, "host_optimizer"))
        self.checkpoint_engine.save(self._checkpoint_arrays(),
                                    self._checkpoint_meta(client_state),
                                    os.path.join(ckpt_dir, "state"))
        # commit (async engines: wait for durability) BEFORE advancing the
        # 'latest' pointer — a crash mid-save must leave 'latest' on the
        # previous complete checkpoint, never a partial one
        self.checkpoint_engine.commit(tag)
        if save_latest and jax.process_index() == 0:
            # temp-file + os.replace: an in-place truncate-then-write
            # bricked resume when the process died between the two
            from deepspeed_tpu.runtime.fault.atomic import atomic_write_text
            atomic_write_text(os.path.join(save_dir, "latest"), str(tag))
        log_dist(f"saved checkpoint {tag} to {save_dir}", ranks=[0])
        return True

    def _save_checkpoint_atomic(self, save_dir, tag, client_state,
                                save_latest, fcfg):
        """Crash-atomic checkpoint protocol (``fault.enabled``): stage into
        ``<tag>.tmp/``, emit ``MANIFEST.json`` (sizes + checksums +
        fingerprint + step metadata), fsync, atomically rename to
        ``<tag>/``, atomically swap ``latest``, then GC per retention
        policy.  A kill at ANY instruction leaves either the previous
        consistent state or the new one — never a loadable partial.
        Transient I/O during the write stage retries with backoff."""
        import shutil
        import time as _time
        from deepspeed_tpu.runtime.fault import inject
        from deepspeed_tpu.runtime.fault.atomic import (atomic_publish_dir,
                                                        atomic_write_text)
        from deepspeed_tpu.runtime.fault.manifest import (
            build_manifest, gc_checkpoints, is_reserved_tag, write_manifest)
        from deepspeed_tpu.runtime.fault.retry import (
            retry_call, retry_policy_from_config)
        if is_reserved_tag(tag):
            # '<x>.tmp' / '<x>.old.<pid>' are the protocol's staging
            # namespace — a committed dir with such a name would be
            # destroyed (or relocated) by the next GC pass
            raise ValueError(
                f"checkpoint tag {tag!r} collides with the crash-atomic "
                "staging namespace ('*.tmp' / '*.old.<pid>'); pick "
                "another tag")
        os.makedirs(save_dir, exist_ok=True)
        final_dir = os.path.join(save_dir, tag)
        tmp_dir = final_dir + ".tmp"
        # host-side staging surgery (rmtree, manifest, rename, GC) is
        # process-0's job on a shared filesystem — every process still
        # participates in the array save/commit (Orbax coordinates the
        # sharded write + its own cross-process barrier internally)
        lead = jax.process_index() == 0

        def write_stage():
            if lead:
                if os.path.isdir(tmp_dir):  # stale orphan / failed attempt
                    shutil.rmtree(tmp_dir)
                os.makedirs(tmp_dir)
            inject.fire("ckpt.save_io", path=tmp_dir)
            self.checkpoint_engine.create(tag)
            if self._host_opt is not None:
                self._host_opt.save(os.path.join(tmp_dir, "host_optimizer"))
            self.checkpoint_engine.save(self._checkpoint_arrays(),
                                        self._checkpoint_meta(client_state),
                                        os.path.join(tmp_dir, "state"))
            # durability barrier for async engines: array shards AND
            # deferred metadata must be on disk before the manifest walks
            # the staging dir
            self.checkpoint_engine.commit(tag)

        retry_call(write_stage, label=f"checkpoint write ({tag})",
                   **retry_policy_from_config(fcfg))
        if not lead:
            return True
        inject.fire("ckpt.before_manifest", path=tmp_dir)
        t0 = _time.monotonic()
        manifest = build_manifest(
            tmp_dir, tag,
            step_meta={"global_steps": self.global_steps,
                       "global_samples": self.global_samples,
                       "micro_steps": self.micro_steps},
            checksum=fcfg.checksum, mesh_shape=self.mesh.shape,
            advance_latest=bool(save_latest))
        write_manifest(tmp_dir, manifest)
        verify_secs = _time.monotonic() - t0
        inject.fire("ckpt.corrupt_shard", path=os.path.join(tmp_dir, "state"))
        inject.fire("ckpt.before_commit_rename", path=tmp_dir)
        atomic_publish_dir(tmp_dir, final_dir)
        inject.fire("ckpt.before_latest_swap", path=save_dir)
        if save_latest:
            atomic_write_text(os.path.join(save_dir, "latest"), tag)
        # retention never deletes this tag NOR whatever 'latest' points to
        # (they differ under save_latest=False)
        protect = {tag}
        latest_path = os.path.join(save_dir, "latest")
        if os.path.exists(latest_path):
            with open(latest_path) as f:
                protect.add(f.read().strip())
        gc_checkpoints(save_dir, fcfg.keep_last_n, protect=tuple(protect))
        if self.monitor.enabled:
            self.monitor.write_events(
                [("Fault/ckpt_verify_secs", verify_secs, self.global_steps)])
        log_dist(f"saved checkpoint {tag} to {save_dir} "
                 f"(manifest {len(manifest['files'])} files, "
                 f"checksum {verify_secs:.2f}s)", ranks=[0])
        return True

    def _metadata_restore_targets(self, md):
        """Restore targets for a FRESH engine from checkpoint metadata:
        build this engine's sharding plan from the saved module shapes,
        then aim every congruent subtree (params, Adam moments in their
        restored plain-tree form) straight at plan shardings — each device
        reads only its shard, no replicated materialization."""
        from deepspeed_tpu.runtime.zero.partition import spec_or_replicated
        # Orbax ArrayMetadata leaves carry shape/dtype but no ndim — map to
        # ShapeDtypeStructs up front so downstream spec decisions (which
        # rank-check leaves) see real abstract arrays
        abstract = jax.tree.map(
            lambda m: jax.ShapeDtypeStruct(tuple(m.shape), m.dtype), md)
        mod_abs = abstract["module"]
        self._build_plan(mod_abs)
        params_def = jax.tree.structure(mod_abs)
        rep = NamedSharding(self.mesh, P())

        def congruent_shardings(sub):
            try:
                if jax.tree.structure(sub) == params_def:
                    return jax.tree.map(
                        lambda s, leaf: spec_or_replicated(self.mesh, s,
                                                           leaf),
                        self._plan.opt_specs, sub,
                        is_leaf=lambda x: isinstance(x, P))
            except Exception:
                pass
            if isinstance(sub, dict):
                return {k: congruent_shardings(v) for k, v in sub.items()}
            if isinstance(sub, (list, tuple)):
                return type(sub)(congruent_shardings(v) for v in sub)
            return rep

        def with_sh(abs_tree, sh_tree):
            return jax.tree.map(
                lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                                  sharding=s),
                abs_tree, sh_tree)

        targets = {"module": with_sh(mod_abs, self._plan.param_shardings)}
        for key in abstract:
            if key == "module":
                continue
            if abstract[key] is None:     # e.g. offload engines save no
                targets[key] = None       # device optimizer state
                continue
            sh = congruent_shardings(abstract[key]) if key == "optimizer" \
                else jax.tree.map(lambda _: rep, abstract[key])
            targets[key] = with_sh(abstract[key], sh)
        return targets

    def load_checkpoint(self, load_dir, tag=None, load_module_strict=True,
                        load_optimizer_states=True, load_lr_scheduler_states=True,
                        load_module_only=False):
        fcfg = self._fault_config()
        requested = tag
        if tag is None:
            latest = os.path.join(load_dir, "latest")
            if os.path.exists(latest):
                with open(latest) as f:
                    tag = f.read().strip()
            elif fcfg is None:
                logger.warning(f"no 'latest' file at {load_dir}; nothing loaded")
                return None, {}
        if fcfg is None:
            return self._load_checkpoint_tag(
                load_dir, tag, load_module_strict, load_optimizer_states,
                load_lr_scheduler_states, load_module_only)
        # fault-tolerant load: verify the candidate tag's manifest; on a
        # missing / partial / corrupt tag walk back to the newest valid
        # one instead of crashing (CheckFreq's verified-restore property)
        import time as _time
        from deepspeed_tpu.runtime.fault.manifest import (
            newest_valid_tag, read_manifest, verify_manifest)
        if requested is None:
            # the 'latest' pointer legitimately lags one tag when a crash
            # lands between the atomic tag rename and the pointer swap —
            # manifest step ordering is authoritative for resume-eligible
            # tags (those saved with save_latest=True; side checkpoints
            # record advance_latest=false and never hijack auto-resume)
            tag = None
        tried = []
        t0 = _time.monotonic()
        pre_verified = False
        while True:
            if tag is None:
                tag = newest_valid_tag(load_dir,
                                       checksum_verify=fcfg.verify_on_load,
                                       skip=tried, for_resume=True)
                # newest_valid_tag already deep-checksummed this tag —
                # re-verifying would double the restore's I/O + hashing
                pre_verified = fcfg.verify_on_load
            if tag is None:
                from deepspeed_tpu.runtime.fault.manifest import list_tags
                remaining = [t for t in list_tags(load_dir)
                             if t not in tried]
                # tags that SHOULD have been resume candidates but were
                # rejected (invalid) — distinct from side checkpoints
                # (advance_latest=false), which are not failures
                eligible = [t for t in remaining
                            if (read_manifest(os.path.join(load_dir, t))
                                or {}).get("advance_latest") is not False]
                if tried or eligible:
                    raise RuntimeError(
                        f"no valid checkpoint in {load_dir}: every "
                        "resume-eligible tag failed verification or load "
                        f"(tried={tried or eligible})")
                if remaining:
                    logger.warning(
                        f"{load_dir} holds only side checkpoints "
                        f"(save_latest=False: {remaining}); nothing "
                        "loaded — starting fresh")
                else:
                    logger.warning(f"no checkpoint found at {load_dir}; "
                                   "nothing loaded")
                return None, {}
            ckpt_dir = os.path.join(load_dir, str(tag))
            if fcfg.verify_on_load and not pre_verified \
                    and read_manifest(ckpt_dir) is not None:
                problems = verify_manifest(ckpt_dir, deep=True)
                if problems:
                    if requested is not None:
                        # an EXPLICITLY requested tag that fails must fail
                        # loudly — silently substituting older weights
                        # would poison evals/exports; auto-resume
                        # (tag=None) is where walk-back applies
                        from deepspeed_tpu.runtime.fault.manifest import \
                            CheckpointCorrupt
                        raise CheckpointCorrupt(
                            f"requested checkpoint {tag!r} in {load_dir} "
                            f"failed verification: {problems[:5]}")
                    logger.warning(
                        f"[fault] checkpoint {tag} failed verification "
                        f"({problems[:3]}{'...' if len(problems) > 3 else ''})"
                        " — walking back to the previous valid tag")
                    tried.append(str(tag))
                    tag = None
                    continue
            try:
                # a transient I/O error (NFS EIO/ESTALE mid-restore) must
                # NOT be conflated with a corrupt tag: retry the SAME tag
                # with backoff first — walking back on a flake would
                # silently discard committed steps
                from deepspeed_tpu.runtime.fault.retry import (
                    retry_call, retry_policy_from_config)
                result = retry_call(
                    self._load_checkpoint_tag, load_dir, tag,
                    load_module_strict, load_optimizer_states,
                    load_lr_scheduler_states, load_module_only,
                    label=f"checkpoint load ({tag})",
                    **retry_policy_from_config(fcfg))
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                if requested is not None:
                    # same loud-failure contract for load errors on an
                    # explicitly requested tag
                    raise
                logger.warning(f"[fault] tag {tag} failed to load "
                               f"({type(e).__name__}: {e}); walking back")
                tried.append(str(tag))
                tag = None
                continue
            if requested is None:
                latest_hint = None
                latest_path = os.path.join(load_dir, "latest")
                if os.path.exists(latest_path):
                    with open(latest_path) as f:
                        latest_hint = f.read().strip()
                if latest_hint and latest_hint != str(tag):
                    # newest-eligible-valid wins over the pointer (the
                    # crash window leaves 'latest' lagging) — but say so
                    # loudly: an operator who HAND-EDITED 'latest' to
                    # roll back must instead load an explicit tag or GC
                    # the newer tags (docs/fault_tolerance.md)
                    logger.warning(
                        f"[fault] resuming from {tag} although 'latest' "
                        f"points at {latest_hint} (newest valid "
                        "resume-eligible tag wins; for a manual rollback "
                        "load an explicit tag or remove the newer tags)")
            if self.monitor.enabled:
                self.monitor.write_events(
                    [("Fault/ckpt_verify_secs", _time.monotonic() - t0,
                      self.global_steps)])
            return result

    def _load_checkpoint_tag(self, load_dir, tag, load_module_strict=True,
                             load_optimizer_states=True,
                             load_lr_scheduler_states=True,
                             load_module_only=False):
        path = os.path.join(load_dir, str(tag), "state")
        abstract = None
        if self._params is not None:
            abstract = {
                "module": _abstract_like(self._params),
                "optimizer": _abstract_like(self._opt_state),
                "loss_scaler": _abstract_like(self._scaler_state),
            }
        else:
            # fresh engine: the checkpoint may come from a DIFFERENT
            # process/device topology (cross-world-size resume) — build
            # device-agnostic restore targets from the checkpoint's own
            # metadata, SHARDED under this engine's plan (built from the
            # checkpoint's shapes) so a ZeRO-sized model never
            # materializes replicated during the restore
            md = getattr(self.checkpoint_engine, "metadata",
                         lambda p: None)(path)
            if md is not None:
                abstract = self._metadata_restore_targets(md)
        fresh_engine = self._params is None
        arrays, meta = self.checkpoint_engine.load(path, abstract_arrays=abstract)
        if arrays is None or not isinstance(arrays, dict) \
                or arrays.get("module") is None:
            # missing/partial 'arrays' dir: the seed indexed
            # arrays["module"] with arrays=None and died on a TypeError —
            # surface what actually happened (fault-enabled loads catch
            # this and walk back to the previous tag).  Deliberately NOT
            # an OSError: the retry policy treats those as transient, and
            # this condition is permanent
            from deepspeed_tpu.runtime.fault.manifest import \
                CheckpointCorrupt
            raise CheckpointCorrupt(
                f"checkpoint {tag!r} at {path} has no loadable 'arrays' "
                "payload (partial or corrupt save?) — cannot restore "
                "module weights")
        self._params = arrays["module"]
        if load_module_only:
            if fresh_engine and self._host_opt is None:
                # fresh engine: build the plan and re-place the loaded
                # weights (fresh optimizer state — module only; the
                # metadata path may have pre-built self._plan, so key on
                # fresh_engine, not plan presence)
                self._init_params_from(self._params)
            elif self._host_opt is not None:
                # fresh masters from the loaded weights — stale fp32 masters
                # would overwrite them on the next offload step
                self._host_opt.init_from_params(self._params)
            return path, meta.get("client_state", {})
        host_opt_dir = os.path.join(load_dir, str(tag), "host_optimizer")
        if self._host_opt is not None:
            if load_optimizer_states and os.path.isdir(host_opt_dir):
                self._host_opt.load(host_opt_dir)
            else:
                # no host states loaded: re-seed fp32 masters from the loaded
                # params, else the next step() would run Adam on stale masters
                # and silently overwrite the checkpoint's weights
                self._host_opt.init_from_params(self._params)
        if load_optimizer_states and arrays.get("optimizer") is not None:
            from deepspeed_tpu.runtime.utils import rehydrate_opt_state
            self._opt_state = rehydrate_opt_state(self._opt_state,
                                                  arrays["optimizer"])
        if arrays.get("loss_scaler") is not None:
            sc = arrays["loss_scaler"]
            if isinstance(sc, dict):
                from deepspeed_tpu.runtime.fp16.loss_scaler import LossScalerState
                sc = LossScalerState(**sc)
            self._scaler_state = self._replicate(sc)
        self.global_steps = meta.get("global_steps", 0)
        self.global_samples = meta.get("global_samples", 0)
        self.micro_steps = meta.get("micro_steps", 0)
        self.skipped_steps = meta.get("skipped_steps", 0)
        if meta.get("rng_key_data") is not None:
            # restore the engine RNG stream: resumed runs draw the same
            # dropout/init keys an uninterrupted run would have drawn
            try:
                self._rng = jax.random.wrap_key_data(
                    jnp.asarray(meta["rng_key_data"]))
            except Exception as e:
                logger.warning(f"could not restore engine RNG key: {e}")
        if load_lr_scheduler_states and self.lr_scheduler and meta.get("lr_scheduler"):
            self.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        if fresh_engine and self._host_opt is None:
            # checkpoint loaded into a fresh engine, possibly on a DIFFERENT
            # topology than it was saved from (the reference's universal-
            # checkpoint resize): build this engine's sharding plan from the
            # loaded shapes and re-place params + optimizer state under it.
            loaded_opt = self._opt_state
            have_loaded_opt = load_optimizer_states and loaded_opt is not None
            self._opt_state = None
            # when loaded state exists, compute shardings only — allocating
            # a fresh m/v just to overwrite it would spike HBM
            self._init_params_from(self._params,
                                   materialize_opt=not have_loaded_opt)
            if self._host_opt is not None:
                # offload engine born from this load: prefer the saved host
                # optimizer states over the fresh init_from_params seed
                if load_optimizer_states and os.path.isdir(host_opt_dir):
                    self._host_opt.load(host_opt_dir)
            elif have_loaded_opt and self._opt_shardings is not None:
                from deepspeed_tpu.runtime.utils import rehydrate_opt_state
                loaded_opt = rehydrate_opt_state(
                    getattr(self, "_abstract_opt", None), loaded_opt)
                self._opt_state = jax.jit(
                    lambda t: t,
                    out_shardings=self._opt_shardings)(loaded_opt)
        state = meta
        log_dist(f"loaded checkpoint {tag} from {load_dir}", ranks=[0])
        return path, state.get("client_state", {})

    def save_16bit_model(self, save_dir, save_filename="pytorch_model.bin",
                         hf_policy=None):
        """Gathered 16-bit weights for serving (reference engine.py:3297:
        emits a consumer-loadable state dict, not an internal format).

        * ``save_filename`` ending in ``.safetensors`` → safetensors file;
          anything else → a REAL ``torch.save`` state dict (bf16 tensors
          round-trip via a uint16 view since numpy has no native bf16).
        * ``hf_policy``: an injection policy instance (or HF ``model_type``
          string, e.g. ``"opt"``) whose ``export_convert`` renames the flax
          params to that family's HF checkpoint keys — the inverse of the
          ``module_inject`` load mapping.  Default: flax dotted paths.
        """
        os.makedirs(save_dir, exist_ok=True)
        dtype = self.compute_dtype if self.compute_dtype != jnp.float32 \
            else jnp.bfloat16
        gathered = jax.device_get(jax.tree.map(
            lambda p: p.astype(dtype)
            if jnp.issubdtype(p.dtype, jnp.floating) else p, self._params))
        from deepspeed_tpu.checkpoint.deepspeed_checkpoint import (
            _flatten_with_paths)
        flat = {k: np.asarray(v)
                for k, v in _flatten_with_paths(gathered).items()}
        # keys relative to the 'params' collection (policy key space)
        flat = {(k[len("params/"):] if k.startswith("params/") else k): v
                for k, v in flat.items()}
        if hf_policy is not None:
            if isinstance(hf_policy, str):
                from deepspeed_tpu.module_inject.containers import ALL_POLICIES
                matches = [p for p in ALL_POLICIES
                           if hf_policy in p.model_types]
                if not matches:
                    raise ValueError(f"no injection policy for model_type="
                                     f"{hf_policy!r}")
                hf_policy = matches[0]()
            cfg = getattr(self.module, "config", None)
            if cfg is None:
                raise ValueError(
                    "hf_policy export requires the module to expose a "
                    ".config (TransformerConfig); wrap or pass the flax "
                    "model family the policy maps")
            flat = hf_policy.export_convert(flat, cfg)
        path = os.path.join(save_dir, save_filename)
        if save_filename.endswith(".safetensors"):
            from safetensors.numpy import save_file
            save_file({k: np.ascontiguousarray(v) for k, v in flat.items()},
                      path)
        else:
            import torch

            def to_torch(a):
                # copy: jax-owned buffers are read-only, torch wants writable
                a = np.ascontiguousarray(a).copy()
                if a.dtype == jnp.bfloat16:
                    return torch.from_numpy(
                        a.view(np.uint16)).view(torch.bfloat16)
                return torch.from_numpy(a)

            torch.save({k: to_torch(v) for k, v in flat.items()}, path)
        log_dist(f"saved 16-bit model ({len(flat)} tensors, "
                 f"{jnp.dtype(dtype).name}) to {path}", ranks=[0])
        return True

    # ------------------------------------------------------------------ #
    @property
    def params(self):
        return self._params

    def load_params(self, tree):
        """Replace the live master params (same structure/shapes), re-placed
        with the plan's shardings — the write-back half of
        ``zero.GatheredParameters`` surgery."""
        if self._params is None or self._plan is None:
            raise RuntimeError("engine params not initialized yet")
        import chex
        chex.assert_trees_all_equal_shapes(tree, self._params)
        put = jax.jit(
            lambda t: jax.tree.map(
                lambda p, old: p.astype(old.dtype), t, self._params),
            out_shardings=self._plan.param_shardings)
        self._params = put(tree)
        if self._host_opt is not None:
            # ZeRO-Offload: the host fp32 masters are authoritative — the
            # next _offload_step overwrites device params from them, so the
            # surgery must be re-seeded there too (values only: Adam
            # moments and step count survive, unlike init_from_params)
            self._host_opt.reseed_masters(self._params)
        # hybrid engine caches a bf16 inference view keyed on global_steps;
        # surgery changes weights without a step, so drop it explicitly
        if getattr(self, "_infer_params", None) is not None:
            self._infer_params = None

    def module_state_dict(self):
        return self._params

    def get_model(self):
        return self.module

    def destroy(self):
        self._compiled.clear()


# --------------------------------------------------------------------- #
def _is_generator(x):
    return inspect.isgenerator(x)


def _is_batch_like(a):
    if hasattr(a, "shape") and getattr(a, "ndim", 0) >= 1:
        return True
    if isinstance(a, dict):
        return all(hasattr(v, "shape") for v in a.values())
    if isinstance(a, (tuple, list)):
        return all(hasattr(v, "shape") for v in a)
    return False


def _abstract_like(tree):
    return jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=l.sharding)
        if isinstance(l, jax.Array) else l, tree)


def _opt_state_shardings(abstract_opt, abstract_params, opt_specs, mesh):
    """Build shardings for optimizer state: any field congruent to the param
    tree gets the ZeRO opt-state specs; scalars replicate."""
    params_def = jax.tree.structure(abstract_params)

    def field_shardings(field):
        from deepspeed_tpu.runtime.zero.partition import spec_or_replicated
        try:
            if jax.tree.structure(field) == params_def:
                return jax.tree.map(
                    lambda s, leaf: spec_or_replicated(mesh, s, leaf),
                    opt_specs, field, is_leaf=lambda x: isinstance(x, P))
        except Exception:
            pass
        return jax.tree.map(lambda _: NamedSharding(mesh, P()), field)

    if hasattr(abstract_opt, "_fields"):
        return type(abstract_opt)(*[field_shardings(getattr(abstract_opt, f))
                                    for f in abstract_opt._fields])
    return field_shardings(abstract_opt)
