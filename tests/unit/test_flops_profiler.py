"""Per-module flops/latency tree (reference ``flops_profiler/profiler.py:239``
``print_model_profile`` / ``:375`` aggregated profile)."""

import numpy as np

import jax

from deepspeed_tpu.models.transformer import Transformer, TransformerConfig
from deepspeed_tpu.profiling.flops_profiler.profiler import (
    ModuleProfile, _scope_to_path, aggregate_by_depth, format_profile_tree,
    model_profile_tree)


def tiny_model():
    cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=2,
                            num_heads=4, max_seq_len=32, dtype="float32",
                            use_flash_attention=False, remat=False,
                            scan_layers=False)
    return Transformer(cfg)


def tiny_batch():
    rng = np.random.default_rng(0)
    return {"input_ids": rng.integers(0, 64, (2, 16)).astype(np.int32)}


def test_scope_to_path_strips_transform_and_method_frames():
    assert _scope_to_path(
        "jit(f)/Transformer/Transformer.hidden_states/layers_0/attn/"
        "dot_general") == ("layers_0", "attn", "dot_general")
    assert _scope_to_path(
        "jit(f)/Transformer/layers_1/attn/bhst,bthd->bshd/transpose") == \
        ("layers_1", "attn", "transpose")
    assert _scope_to_path("reduce_sum") == ()


def test_model_profile_tree_structure_params_flops():
    model = tiny_model()
    root, _ = model_profile_tree(model, jax.random.key(0), tiny_batch())
    # module tree mirrors the flax structure
    assert set(root.children) >= {"embed_tokens", "layers_0", "layers_1",
                                  "final_norm", "lm_head"}
    blk = root.children["layers_0"]
    assert set(blk.children) >= {"input_norm", "attn", "mlp"}
    # subtree-aggregated params: root = model total, block > its norms
    total = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(
        model.init(jax.random.key(0), tiny_batch())))
    assert root.params == total
    assert blk.params > blk.children["input_norm"].params
    # flops: attention + mlp dominate the block (CPU path uses flax's
    # per-module cost analysis)
    assert root.flops > 0
    assert blk.flops >= blk.children["attn"].flops > 0
    assert blk.children["mlp"].flops > 0


def test_format_and_aggregate_render():
    model = tiny_model()
    root, total_ps = model_profile_tree(model, jax.random.key(0),
                                        tiny_batch())
    txt = format_profile_tree(root, total_ps, depth=2)
    assert "Transformer(" in txt and "(layers_0): Block(" in txt
    assert "% Params" in txt and "MACs" in txt
    agg = aggregate_by_depth(root, max_depth=1)
    assert "depth 0:" in agg and "depth 1:" in agg


def test_engine_prints_profile_tree(tmp_path):
    import deepspeed_tpu
    report_file = tmp_path / "profile.txt"
    engine, *_ = deepspeed_tpu.initialize(
        model=tiny_model(),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "flops_profiler": {"enabled": True, "profile_step": 1,
                                   "output_file": str(report_file)}})
    rng = np.random.default_rng(0)
    b = {"input_ids": rng.integers(0, 64, (8, 16)).astype(np.int32)}
    for _ in range(2):
        loss = engine(b)
        engine.backward(loss)
        engine.step()
    out = report_file.read_text()
    assert "DeepSpeed Flops Profiler" in out
    assert "(layers_0): Block(" in out
    assert "Detailed Profile per GPU" in out


def test_module_profile_walk_depths():
    root = ModuleProfile("", "M")
    root.child("a").child("b")
    depths = {node.name: d for d, node in root.walk()}
    assert depths == {"": 0, "a": 1, "b": 2}


# --------------------------------------------------------------------- #
# The unified device-memory reader + shared cost model (PR: device-memory
# & roofline observatory)
# --------------------------------------------------------------------- #
class _FakeAccel:
    """Accelerator stub with a controllable memory_snapshot."""

    def __init__(self, limit, source):
        self._limit, self._source = limit, source

    def memory_snapshot(self, device_index=None):
        return {"device": "fake:0", "platform": "fake",
                "bytes_in_use": 123, "peak_bytes_in_use": 456,
                "bytes_limit": self._limit, "limit_source": self._source}


def test_device_hbm_bytes_prefers_backend_limit(monkeypatch):
    from deepspeed_tpu.accelerator import real_accelerator
    from deepspeed_tpu.profiling.flops_profiler import profiler
    monkeypatch.setattr(real_accelerator, "_accelerator",
                        _FakeAccel(7 * 2**30, "runtime"))
    assert profiler.device_hbm_bytes() == 7 * 2**30


def test_device_hbm_bytes_missing_limit_falls_back(monkeypatch):
    """The previously untested bytes_limit-missing path: a backend
    reporting no limit answers through the accelerator's datasheet
    fallback; fully unknown answers 0 and callers must skip budget
    checks."""
    from deepspeed_tpu.accelerator import real_accelerator
    from deepspeed_tpu.profiling.flops_profiler import profiler
    monkeypatch.setattr(real_accelerator, "_accelerator",
                        _FakeAccel(0, "unknown"))
    assert profiler.device_hbm_bytes() == 0
    # the datasheet path itself: a TPU-kind device with empty live stats
    from deepspeed_tpu.accelerator.tpu_accelerator import \
        datasheet_hbm_bytes

    class _Dev:
        device_kind = "TPU v5 lite"
        platform = "tpu"
    assert datasheet_hbm_bytes(_Dev()) == int(16.0e9)

    class _Unknown:
        device_kind = "mystery"
        platform = "mystery"
    assert datasheet_hbm_bytes(_Unknown()) == 0


def test_memory_snapshot_datasheet_source(monkeypatch):
    """TPU_Accelerator.memory_snapshot: live bytes_limit wins; absent
    live stats fall back to the datasheet capacity with the source
    labeled — the one reader every consumer shares."""
    from deepspeed_tpu.accelerator.tpu_accelerator import TPU_Accelerator

    class _Dev:
        id = 0
        device_kind = "TPU v4"
        platform = "tpu"

        def memory_stats(self):
            return {}                    # tunneled PJRT: empty stats
    accel = TPU_Accelerator()
    monkeypatch.setattr(accel, "devices", lambda: [_Dev()])
    snap = accel.memory_snapshot()
    assert snap["bytes_limit"] == int(32.0e9)
    assert snap["limit_source"] == "datasheet"
    assert snap["bytes_in_use"] == 0


def test_cost_analysis_of_routes_through_shared_model():
    """profile-side cost extraction == the contract/roofline cost model
    (autotuning.cost_model.xla_cost_analysis) on the same program."""
    import jax.numpy as jnp
    from deepspeed_tpu.autotuning.cost_model import (compiled_costs,
                                                     xla_cost_analysis)
    from deepspeed_tpu.profiling.flops_profiler.profiler import \
        cost_analysis_of

    def f(x):
        return (x @ x).sum()
    x = jnp.ones((32, 32))
    via_profiler = cost_analysis_of(f, x)
    compiled = jax.jit(f).lower(x).compile()
    assert via_profiler == xla_cost_analysis(compiled)
    costs = compiled_costs(compiled)
    assert costs["flops"] == float(via_profiler.get("flops", 0.0)) > 0
    assert costs["bytes_accessed"] > 0
