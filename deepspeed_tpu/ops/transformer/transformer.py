"""DeepSpeedTransformerLayer — the standalone fused training layer op.

Reference parity: ``deepspeed/ops/transformer/transformer.py:296``
(``DeepSpeedTransformerLayer``) + ``DeepSpeedTransformerConfig`` (``:18``),
the API behind the reference's ~8k LoC of fused CUDA training kernels
(``csrc/transformer/``: QKV gemm, softmax, dropout, layernorm, gelu, with a
"stochastic" fast-math variant).

TPU redesign: the fusion IS the compiler — one flax module whose attention
runs the Pallas flash kernel and whose gemm/bias/gelu/layernorm chain XLA
fuses; ``stochastic_mode`` maps to enabling non-deterministic fast paths
(here: nothing to do — TPU matmuls are deterministic at equal cost, so it is
accepted for parity and ignored).
"""

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import flax.linen as nn


@dataclass
class DeepSpeedTransformerConfig:
    """Reference ``DeepSpeedTransformerConfig``: BERT-style encoder layer
    hyperparameters."""
    batch_size: int = -1
    hidden_size: int = 768
    intermediate_size: Optional[int] = None
    heads: int = 12
    attn_dropout_ratio: float = 0.1
    hidden_dropout_ratio: float = 0.1
    num_hidden_layers: int = -1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    local_rank: int = -1
    seed: int = -1
    fp16: bool = False
    pre_layer_norm: bool = True
    normalize_invertible: bool = False   # memory trick — jax.checkpoint covers it
    gelu_checkpoint: bool = False        # ditto
    adjust_init_range: bool = True
    attn_dropout_checkpoint: bool = False
    stochastic_mode: bool = False
    return_tuple: bool = False
    training: bool = True
    # explicit compute dtype; None keeps the reference's fp16-flag semantics
    compute_dtype: Optional[object] = None

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @property
    def dtype(self):
        if self.compute_dtype is not None:
            return self.compute_dtype
        return jnp.float16 if self.fp16 else jnp.float32


class DeepSpeedTransformerLayer(nn.Module):
    """Fused BERT-style encoder layer (bidirectional attention + GELU MLP),
    pre- or post-LN per config.  ``__call__(hidden_states, attention_mask)``
    matches the reference layer's forward contract."""

    config: DeepSpeedTransformerConfig

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None,
                 deterministic=True):
        cfg = self.config
        h = cfg.hidden_size
        heads = cfg.heads
        head_dim = h // heads
        dt = cfg.dtype
        x = hidden_states.astype(dt)
        ln = lambda name: nn.LayerNorm(epsilon=cfg.layer_norm_eps, name=name,
                                       param_dtype=jnp.float32)
        dense = lambda feat, name: nn.DenseGeneral(
            feat, name=name, dtype=dt, param_dtype=jnp.float32,
            kernel_init=nn.initializers.normal(cfg.initializer_range))

        def attention(y):
            B, S, _ = y.shape
            q = dense((heads, head_dim), "q_proj")(y)
            k = dense((heads, head_dim), "k_proj")(y)
            v = dense((heads, head_dim), "v_proj")(y)
            if attention_mask is None:
                from deepspeed_tpu.ops.transformer.flash_attention import (
                    flash_attention, pallas_supported)
                if pallas_supported():
                    out = flash_attention(q, k, v, causal=False)
                else:
                    logits = jnp.einsum("bshd,bthd->bhst", q, k) / \
                        jnp.sqrt(float(head_dim))
                    out = jnp.einsum(
                        "bhst,bthd->bshd",
                        jax.nn.softmax(logits.astype(jnp.float32), -1).astype(dt), v)
            else:
                logits = jnp.einsum("bshd,bthd->bhst", q, k) / \
                    jnp.sqrt(float(head_dim))
                mask = attention_mask.astype(bool)
                while mask.ndim < 4:
                    mask = mask[:, None]
                logits = jnp.where(mask, logits.astype(jnp.float32), -1e30)
                out = jnp.einsum("bhst,bthd->bshd",
                                 jax.nn.softmax(logits, -1).astype(dt), v)
            out = dense(h, "out_proj")(out.reshape(B, S, heads * head_dim))
            if cfg.attn_dropout_ratio > 0 and not deterministic:
                out = nn.Dropout(cfg.attn_dropout_ratio)(
                    out, deterministic=deterministic)
            return out

        def mlp(y):
            z = dense(cfg.intermediate_size, "intermediate")(y)
            z = nn.gelu(z, approximate=False)  # BERT-exact erf gelu
            z = dense(h, "output")(z)
            if cfg.hidden_dropout_ratio > 0 and not deterministic:
                z = nn.Dropout(cfg.hidden_dropout_ratio)(
                    z, deterministic=deterministic)
            return z

        if cfg.pre_layer_norm:
            x = x + attention(ln("attn_ln")(x).astype(dt))
            x = x + mlp(ln("mlp_ln")(x).astype(dt))
        else:
            x = ln("attn_ln")(x + attention(x)).astype(dt)
            x = ln("mlp_ln")(x + mlp(x)).astype(dt)
        return (x,) if cfg.return_tuple else x


# reference exposes a stochastic variant as a separate builder/class
DeepSpeedStochasticTransformerLayer = DeepSpeedTransformerLayer
