"""TL001 positive fixture: host syncs inside a hot path."""
import numpy as np
import jax
from deepspeed_tpu.tools.lint.hotpath import hot_path


@hot_path("fixture.train_step")
def train_step(params, batch):
    loss = compute_loss(params, batch)
    metric = loss.item()                      # TL001
    host = np.asarray(loss)                   # TL001
    pulled = jax.device_get(loss)             # TL001
    loss.block_until_ready()                  # TL001
    scale = float(params["scale"])            # TL001 (computed cast)
    return metric, host, pulled, scale


def helper_called_from_hot(x):
    return float(jax.device_get(x))           # TL001 x2 (reachable)


@hot_path("fixture.decode")
def decode(tokens):
    return helper_called_from_hot(tokens)


def compute_loss(params, batch):
    return batch
