"""Coalesced collectives — reference
``runtime/comm/coalesced_collectives.py:29`` (``reduce_scatter_coalesced``,
the batched reduce-scatter ZeRO-3 grad reduction rides on).

On TPU, XLA already coalesces collectives it can prove adjacent, but an
explicit coalesced form still helps when many small tensors reduce together
(one fused collective instead of N): flatten every tensor into one padded
buffer, reduce-scatter once over the axis, and hand each rank its shard
views.  Callable inside ``shard_map``.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


def reduce_scatter_coalesced(tensors, axis):
    """Reduce-scatter a list of tensors in ONE collective.

    Each input is this device's full copy.  Returns a list of 1-D shards:
    rank r's view of each tensor's r-th partition (tensor flattened and
    padded to the axis size), matching the reference's output contract.
    """
    W = lax.psum(1, axis)
    numels = [int(np.prod(t.shape)) for t in tensors]
    padded = [-(-n // W) * W for n in numels]
    # reduce in the widest participating dtype, hand back per-tensor dtypes
    # (the reference preserves input dtype — bf16 grads stay bf16 on the wire)
    acc_dtype = jnp.result_type(*[t.dtype for t in tensors])
    flat = jnp.concatenate(
        [jnp.pad(t.astype(acc_dtype).ravel(), (0, p - n))
         for t, n, p in zip(tensors, numels, padded)])
    # lay out as [W, total/W] so scatter dim 0 hands rank r one row of every
    # tensor: interleave per-tensor partitions
    parts = []
    offset = 0
    for p in padded:
        seg = flat[offset:offset + p].reshape(W, p // W)
        parts.append(seg)
        offset += p
    stacked = jnp.concatenate(parts, axis=1)          # [W, sum(p)/W]
    # untiled psum_scatter: [W, c] in → [c] out (rank r keeps summed row r)
    reduced = lax.psum_scatter(stacked, axis, scatter_dimension=0)
    # split back into per-tensor shards, each in its input dtype
    out, offset = [], 0
    for t, p in zip(tensors, padded):
        out.append(reduced[offset:offset + p // W].astype(t.dtype))
        offset += p // W
    return out


def all_gather_coalesced(shards, axis):
    """Inverse companion (reference pairs this with the ZeRO-3 param
    gather): one all_gather for a list of per-rank shards; returns each
    tensor's full flat (padded) buffer."""
    widths = [s.shape[0] for s in shards]
    flat = jnp.concatenate(shards)
    gathered = lax.all_gather(flat, axis, tiled=False)   # [W, sum(w)]
    out, offset = [], 0
    for w in widths:
        out.append(gathered[:, offset:offset + w].ravel())
        offset += w
    return out
