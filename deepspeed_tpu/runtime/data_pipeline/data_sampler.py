"""Curriculum-aware distributed data sampler.

Capability parity with reference
``runtime/data_pipeline/data_sampling/data_sampler.py:36``
(``DeepSpeedDataSampler``): deterministic, resumable, difficulty-filtered
sample selection sharded over the data-parallel axis.  The reference
consumes offline ``DataAnalyzer`` index files; here the per-sample difficulty
metric is supplied as a callable or array (``metric_values``) and clustering
happens in memory — same semantics, host-side numpy (this never touches the
device; batches it yields feed the jitted step).
"""

import os

import numpy as np


class DeepSpeedDataSampler:
    """Yields per-step lists of sample indices for this dp rank.

    Curriculum semantics (reference ``:165 get_new_cluster``): at each step
    the scheduler's current difficulty gates which samples are eligible
    (``metric <= difficulty``); eligible-but-unseen samples are shuffled
    deterministically per difficulty cluster.
    """

    def __init__(self, curriculum_scheduler, total_samples,
                 micro_batch_size, data_parallel_rank, data_parallel_size,
                 gradient_accumulation_steps=1, metric_values=None,
                 drop_last=True, seed=1234):
        self.curriculum_scheduler = curriculum_scheduler
        self.total_samples = int(total_samples)
        self.micro_batch_size = int(micro_batch_size)
        self.dp_rank = int(data_parallel_rank)
        self.dp_size = int(data_parallel_size)
        self.gas = int(gradient_accumulation_steps)
        self.global_batch_size = (self.micro_batch_size * self.dp_size
                                  * self.gas)
        self.metric_values = (np.asarray(metric_values)
                              if metric_values is not None else None)
        self.drop_last = drop_last
        self.seed = seed
        self.consumed_samples = 0
        self.np_rng = np.random.default_rng(seed)
        self._order = None
        self._order_difficulty = None

    def __len__(self):
        return self.total_samples

    def state_dict(self):
        return {
            "consumed_samples": self.consumed_samples,
            "curriculum": (self.curriculum_scheduler.get_state()
                           if self.curriculum_scheduler else None),
        }

    def load_state_dict(self, state):
        self.consumed_samples = state["consumed_samples"]
        if self.curriculum_scheduler and state.get("curriculum"):
            self.curriculum_scheduler.set_state(state["curriculum"])

    def _eligible_order(self, difficulty):
        """Deterministic shuffled ordering of samples eligible at this
        difficulty (cluster analog of reference ``:226``)."""
        if (self._order is not None
                and self._order_difficulty == difficulty):
            return self._order
        if self.metric_values is None or difficulty is None:
            idx = np.arange(self.total_samples)
        else:
            idx = np.nonzero(self.metric_values <= difficulty)[0]
        rng = np.random.default_rng(self.seed + (difficulty or 0))
        self._order = rng.permutation(idx)
        self._order_difficulty = difficulty
        return self._order

    def get_start_end_idx(self, batch):
        """Split a global batch among dp ranks (reference ``:122``)."""
        per_rank = len(batch) // self.dp_size
        start = self.dp_rank * per_rank
        return start, start + per_rank

    def __iter__(self):
        while True:
            step = self.consumed_samples // self.global_batch_size
            difficulty = None
            if self.curriculum_scheduler is not None:
                difficulty = self.curriculum_scheduler.update_difficulty(step + 1)
            order = self._eligible_order(difficulty)
            if len(order) < self.global_batch_size:
                raise RuntimeError(
                    f"not enough eligible samples ({len(order)}) for a global "
                    f"batch ({self.global_batch_size}) at difficulty {difficulty}")
            offset = self.consumed_samples % len(order)
            if offset + self.global_batch_size > len(order):
                offset = 0  # epoch wrap within the cluster
            batch = order[offset:offset + self.global_batch_size]
            self.consumed_samples += self.global_batch_size
            start, end = self.get_start_end_idx(batch)
            yield batch[start:end].tolist()


class DataAnalyzer:
    """Offline per-sample metric analysis — reference
    ``data_sampling/data_analyzer.py`` (``DataAnalyzer``): map one or more
    metric functions over a dataset in shardable worker passes, write
    per-worker results, then merge into the two artifacts the curriculum
    sampler consumes: ``<metric>_sample_to_metric`` (sample idx → value) and
    ``<metric>_metric_to_sample`` (value → sample indices)."""

    def __init__(self, dataset, metric_names=None, metric_functions=None,
                 save_path=None, num_workers=1, worker_id=0, metric_fn=None):
        self.dataset = dataset
        if metric_fn is not None:  # single-metric convenience form
            metric_names = metric_names or ["metric"]
            metric_functions = [metric_fn]
        self.metric_names = metric_names or []
        self.metric_functions = metric_functions or []
        if save_path is None:
            # convenience uses must not litter the cwd with shard files
            import tempfile
            save_path = tempfile.mkdtemp(prefix="dstpu_data_analyzer_")
        self.save_path = save_path
        self.num_workers = num_workers
        self.worker_id = worker_id

    # -------------------------------------------------------------- #
    def _worker_indices(self, worker_id=None):
        w = self.worker_id if worker_id is None else worker_id
        n = len(self.dataset)
        per = -(-n // self.num_workers)
        return range(w * per, min((w + 1) * per, n))

    def run_map(self, worker_id=None):
        """One worker's pass (reference ``run_map``): computes every metric
        on this worker's shard and writes ``worker_<w>_<metric>.npy``."""
        idxs = list(self._worker_indices(worker_id))
        w = self.worker_id if worker_id is None else worker_id
        os.makedirs(self.save_path, exist_ok=True)
        out = {}
        for name, fn in zip(self.metric_names, self.metric_functions):
            vals = np.asarray([fn(self.dataset[i]) for i in idxs])
            np.save(os.path.join(self.save_path, f"worker_{w}_{name}.npy"), vals)
            out[name] = vals
        return out

    def run_reduce(self):
        """Merge all workers' shards (reference ``run_reduce``): writes
        ``<metric>_sample_to_metric.npy`` and ``<metric>_metric_to_sample.npz``."""
        merged = {}
        for name in self.metric_names:
            parts = []
            for w in range(self.num_workers):
                parts.append(np.load(os.path.join(self.save_path,
                                                  f"worker_{w}_{name}.npy")))
            s2m = np.concatenate(parts)
            np.save(os.path.join(self.save_path,
                                 f"{name}_sample_to_metric.npy"), s2m)
            m2s = {}
            for i, v in enumerate(s2m):
                m2s.setdefault(v.item(), []).append(i)
            np.savez(os.path.join(self.save_path, f"{name}_metric_to_sample.npz"),
                     **{str(k): np.asarray(v) for k, v in m2s.items()})
            merged[name] = s2m
        return merged

    def run(self):
        """Single-process map+reduce over all workers."""
        for w in range(self.num_workers):
            self.run_map(worker_id=w)
        merged = self.run_reduce()
        return merged[self.metric_names[0]] if len(merged) == 1 else merged

    def run_and_save(self, path=None):
        vals = self.run()
        if path is not None:
            np.save(path, vals)
        return vals

    @staticmethod
    def load(path):
        return np.load(path)

    @staticmethod
    def load_metric(save_path, metric_name):
        """The curriculum sampler's read side."""
        s2m = np.load(os.path.join(save_path,
                                   f"{metric_name}_sample_to_metric.npy"))
        with np.load(os.path.join(save_path,
                                  f"{metric_name}_metric_to_sample.npz")) as z:
            m2s = {k: z[k].copy() for k in z.files}
        return s2m, m2s
