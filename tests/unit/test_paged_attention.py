"""Pallas paged-attention kernel tests (``ops/transformer/paged_attention.py``)
and the attention-kernel registry (``ops/transformer/registry.py``).

The kernel contract: paged decode/chunk-prefill over the page pool is
BITWISE equal to the ``take_along_axis`` gather reference — the gathered
virtual view fed to the monolithic kernel at ``block_k = page_size``,
which walks the identical online-softmax block sequence — across page
sizes {16, 64, 128}, fp32 and int8-KV pools, dead lanes and unaligned
lengths.  (Serving-level mid-stream EOS / slot-churn / greedy-bitwise
coverage rides ``test_serving_paged.py``, which now exercises these
kernels end to end.)  The registry contract: one static dispatch table,
probed identically by the traced programs and the host-side attribution,
reference fallback warns instead of silently re-creating the BENCH_r04
cliff, and the traced paged decode step stays host-callback-free with
its fused write aliased in the jaxpr.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.transformer.decode_attention import (
    chunk_prefill_attention, decode_attention)
from deepspeed_tpu.ops.transformer.paged_attention import (
    paged_chunk_prefill_attention, paged_decode_attention)
from deepspeed_tpu.ops.transformer.registry import (
    MAX_CHUNK_S, kernel_modes, select_kernel)

L, B, H, KVH, D = 2, 3, 4, 2, 8
KVHD = KVH * D
LAYER = 1


def _pool_fixture(page, *, int8=False, seed=0):
    """A small pool + block tables with a dead lane (length 0, table all
    trash page 0) and unaligned live lengths."""
    rng = np.random.RandomState(seed)
    nvirt = 4
    P = 3 * nvirt + 1                       # worst case + trash page 0
    shape = (L, P, page, KVHD)
    if int8:
        k = jnp.asarray(rng.randint(-127, 128, shape), jnp.int8)
        v = jnp.asarray(rng.randint(-127, 128, shape), jnp.int8)
        ks = jnp.asarray(rng.rand(L, P, page, KVH) * 0.1 + 0.01, jnp.float32)
        vs = jnp.asarray(rng.rand(L, P, page, KVH) * 0.1 + 0.01, jnp.float32)
    else:
        k = jnp.asarray(rng.randn(*shape), jnp.float32)
        v = jnp.asarray(rng.randn(*shape), jnp.float32)
        ks = vs = None
    # non-contiguous, non-monotone physical pages; row 2 is a dead lane
    pages = jnp.asarray([[3, 5, 2, 7], [1, 4, 6, 8], [0, 0, 0, 0]],
                        jnp.int32)
    lengths = jnp.asarray([2 * page + 5, 4 * page, 0], jnp.int32)
    q = jnp.asarray(rng.randn(B, H, D), jnp.float32)
    return q, k, v, ks, vs, pages, lengths, nvirt


def _gather(buf, pages, nvirt):
    """The take_along_axis reference view: [B, nvirt*page, last-dim]."""
    return buf[LAYER, pages].reshape(B, nvirt * buf.shape[2], buf.shape[-1])


@pytest.mark.parametrize("page", [16, 64, 128])
@pytest.mark.parametrize("int8", [False, True], ids=["fp32", "int8"])
def test_paged_decode_bitwise_vs_gather(page, int8):
    """Decode over the pool == decode over the gathered virtual view,
    BITWISE (live rows; the dead lane's output is garbage either way)."""
    q, k, v, ks, vs, pages, lengths, nvirt = _pool_fixture(page, int8=int8)
    ref = decode_attention(
        q, _gather(k, pages, nvirt), _gather(v, pages, nvirt), lengths,
        block_k=page,
        k_scale=None if ks is None else _gather(ks, pages, nvirt),
        v_scale=None if vs is None else _gather(vs, pages, nvirt))
    out = paged_decode_attention(q, k, v, lengths, pages, layer=LAYER,
                                 k_scale=ks, v_scale=vs)
    np.testing.assert_array_equal(np.asarray(ref[:2]), np.asarray(out[:2]))


def test_paged_decode_fused_write_pool_contents():
    """The fused aliased write: the step's K/V row lands BITWISE at the
    table-resolved (page, offset), every untouched pool page is bitwise
    untouched (the dead lane's garbage stripe goes to trash page 0), and
    the attend output matches the pre-scattered reference within the
    fused kernel's score-column tolerance (VPU row-sum vs MXU dot —
    the same bound the monolithic fused tests use)."""
    page = 16
    q, k, v, _, _, pages, lengths, nvirt = _pool_fixture(page)
    rng = np.random.RandomState(7)
    new_k = jnp.asarray(rng.randn(B, KVH, D), jnp.float32)
    new_v = jnp.asarray(rng.randn(B, KVH, D), jnp.float32)
    # reference: pre-scatter the row through the table, then attend
    pos = jnp.maximum(lengths - 1, 0)
    phys = pages[jnp.arange(B), pos // page]
    off = pos % page
    kw = k.at[LAYER, phys, off].set(new_k.reshape(B, KVHD))
    vw = v.at[LAYER, phys, off].set(new_v.reshape(B, KVHD))
    ref = decode_attention(q, _gather(kw, pages, nvirt),
                           _gather(vw, pages, nvirt), lengths, block_k=page)
    out, ko, vo = paged_decode_attention(q, k, v, lengths, pages,
                                         layer=LAYER, new_k=new_k,
                                         new_v=new_v)
    np.testing.assert_allclose(np.asarray(ref[:2]), np.asarray(out[:2]),
                               rtol=2e-5, atol=2e-5)
    live = np.arange(2)                     # rows 0, 1 are live
    np.testing.assert_array_equal(np.asarray(kw[LAYER, phys[live], off[live]]),
                                  np.asarray(ko[LAYER, phys[live], off[live]]))
    np.testing.assert_array_equal(np.asarray(vw[LAYER, phys[live], off[live]]),
                                  np.asarray(vo[LAYER, phys[live], off[live]]))
    untouched = np.setdiff1d(np.arange(k.shape[1]), np.asarray(phys))
    np.testing.assert_array_equal(np.asarray(k[:, untouched]),
                                  np.asarray(ko[:, untouched]))
    np.testing.assert_array_equal(np.asarray(v[:, untouched]),
                                  np.asarray(vo[:, untouched]))


def test_paged_decode_fused_write_int8_quantizes_like_cache():
    """Fused write on an int8 pool: the kernel's in-kernel quantization
    of the fresh row (per-kv-head symmetric, max/127) writes the SAME
    payload bytes and scales the out-of-kernel quantize-then-scatter
    path would."""
    page = 16
    q, k, v, ks, vs, pages, lengths, _ = _pool_fixture(page, int8=True)
    rng = np.random.RandomState(11)
    new_k = jnp.asarray(rng.randn(B, KVH, D), jnp.float32)
    new_v = jnp.asarray(rng.randn(B, KVH, D), jnp.float32)
    out, ko, vo, kso, vso = paged_decode_attention(
        q, k, v, lengths, pages, layer=LAYER, k_scale=ks, v_scale=vs,
        new_k=new_k, new_v=new_v)
    assert bool(jnp.all(jnp.isfinite(out[:2])))
    pos = jnp.maximum(lengths - 1, 0)
    phys = np.asarray(pages[jnp.arange(B), pos // page])
    off = np.asarray(pos % page)
    for b in range(2):                      # live rows only
        row = np.asarray(new_k[b], np.float32)          # [KVH, D]
        s = np.abs(row).max(axis=1, keepdims=True) / 127.0
        s = np.where(s == 0.0, 1.0, s)
        qrow = np.clip(np.round(row / s), -127, 127).astype(np.int8)
        np.testing.assert_array_equal(
            np.asarray(ko[LAYER, phys[b], off[b]]).reshape(KVH, D), qrow)
        np.testing.assert_allclose(
            np.asarray(kso[LAYER, phys[b], off[b]]), s[:, 0], rtol=1e-6)


@pytest.mark.parametrize("int8", [False, True], ids=["fp32", "int8"])
def test_paged_chunk_prefill_bitwise_vs_gather(int8):
    """Chunked prefill over the pool == the monolithic chunk kernel over
    the gathered view, bitwise — per-row starts including 0 and an
    unaligned mid-page start."""
    page = 16
    q0, k, v, ks, vs, pages, _, nvirt = _pool_fixture(page, int8=int8)
    del q0
    C = 24
    rng = np.random.RandomState(3)
    qc = jnp.asarray(rng.randn(B, C, H, D), jnp.float32)
    starts = jnp.asarray([13, 0, 0], jnp.int32)
    ref = chunk_prefill_attention(
        qc, _gather(k, pages, nvirt), _gather(v, pages, nvirt), starts,
        block_k=page,
        k_scale=None if ks is None else _gather(ks, pages, nvirt),
        v_scale=None if vs is None else _gather(vs, pages, nvirt))
    out = paged_chunk_prefill_attention(qc, k, v, starts, pages,
                                        layer=LAYER, k_scale=ks, v_scale=vs)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))


def test_paged_chunk_prefill_4k_prompt_matches_dense_one_pass():
    """A 4k-prompt tail chunk through the paged kernel == the dense
    one-pass softmax over the full 4096-token history (the path 4k+
    prompts used to OOM through): same numbers, never a [S, S] score
    tensor.  Tolerance is fp32 online-softmax vs dense re-association."""
    page, nvirt = 64, 64                    # 4096 virtual positions
    S_virt, C = page * nvirt, 128
    start = S_virt - C                      # the last prefill chunk
    Hq, KVHq, Dq = 2, 1, 8
    rng = np.random.RandomState(5)
    k = jnp.asarray(rng.randn(1, nvirt + 1, page, KVHq * Dq), jnp.float32)
    v = jnp.asarray(rng.randn(1, nvirt + 1, page, KVHq * Dq), jnp.float32)
    pages = jnp.asarray(rng.permutation(nvirt) + 1, jnp.int32)[None]
    qc = jnp.asarray(rng.randn(1, C, Hq, Dq), jnp.float32)
    out = paged_chunk_prefill_attention(
        qc, k, v, jnp.asarray([start], jnp.int32), pages, layer=0)
    # dense one-pass reference over the gathered history, in float64 —
    # plain loops keep it obviously correct
    kv_g = k[0, pages[0]].reshape(S_virt, KVHq, Dq)
    vv_g = v[0, pages[0]].reshape(S_virt, KVHq, Dq)
    q_np = np.asarray(qc[0], np.float64)                 # [C, Hq, Dq]
    k_np = np.asarray(kv_g, np.float64)                  # [S, KVHq, Dq]
    v_np = np.asarray(vv_g, np.float64)
    ref = np.zeros((C, Hq, Dq))
    for h in range(Hq):
        kh = k_np[:, h // (Hq // KVHq)]                  # GQA group share
        vh = v_np[:, h // (Hq // KVHq)]
        s = (q_np[:, h] / np.sqrt(Dq)) @ kh.T            # [C, S]
        mask = np.arange(S_virt)[None, :] > (start + np.arange(C))[:, None]
        s[mask] = -np.inf
        p = np.exp(s - s.max(axis=1, keepdims=True))
        ref[:, h] = (p / p.sum(axis=1, keepdims=True)) @ vh
    np.testing.assert_allclose(np.asarray(out[0]), ref, rtol=2e-5,
                               atol=2e-5)


# --------------------------------------------------------------------- #
# The registry: one static dispatch table, host attribution included
# --------------------------------------------------------------------- #

def test_registry_dispatch_table():
    """The capability probes, in table order: paged decode only without
    bias/window/opt-out; monolithic decode masks windows in-kernel; the
    chunk kernel covers 1 < S <= MAX_CHUNK_S; everything else is the
    reference fallback."""
    assert select_kernel(s=1, paged=True) == "pallas_paged_decode"
    assert select_kernel(s=1, paged=False) == "pallas_decode"
    assert select_kernel(s=1, paged=False,
                         has_window=True) == "pallas_decode"
    assert select_kernel(s=1, paged=True,
                         has_window=True) == "reference_fallback"
    assert select_kernel(s=1, paged=True,
                         disabled=True) == "reference_fallback"
    assert select_kernel(s=1, paged=True,
                         has_bias=True) == "reference_fallback"
    for s in (2, 8, MAX_CHUNK_S):
        assert select_kernel(s=s, paged=True) == "pallas_chunked_prefill"
        assert select_kernel(s=s, paged=False) == "pallas_chunked_prefill"
    assert select_kernel(s=MAX_CHUNK_S + 1,
                         paged=True) == "reference_fallback"
    # host-side attribution probes the SAME table
    assert kernel_modes(paged=True) == {
        "decode": "pallas_paged_decode",
        "prefill_chunk": "pallas_chunked_prefill"}
    assert kernel_modes(paged=True, disabled=True) == {
        "decode": "reference_fallback",
        "prefill_chunk": "reference_fallback"}
    assert kernel_modes(paged=False) == {
        "decode": "pallas_decode",
        "prefill_chunk": "pallas_chunked_prefill"}


def test_registry_backend_gate(monkeypatch):
    """DSTPU_DISABLE_FLASH=1 drops every mode to the reference fallback —
    the probe consults live backend capability, not a cached answer."""
    monkeypatch.setenv("DSTPU_DISABLE_FLASH", "1")
    assert select_kernel(s=1, paged=True) == "reference_fallback"
    assert select_kernel(s=8, paged=False) == "reference_fallback"
    monkeypatch.delenv("DSTPU_DISABLE_FLASH")
    assert select_kernel(s=1, paged=True) == "pallas_paged_decode"


def test_prefill_plan_reasons_name_kernel_modes():
    """prefill_plan() reasons carry the registry attribution so bench
    records say which kernel path actually ran."""
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.models.transformer import (Transformer,
                                                  TransformerConfig)
    cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=1,
                            num_heads=2, max_seq_len=4096)
    eng = InferenceEngine(Transformer(cfg),
                          DeepSpeedInferenceConfig(prefill_chunk_size="auto"))
    mode, chunk, why = eng.prefill_plan(16, 4096)
    assert mode == "chunked"
    assert "prefill=pallas_chunked_prefill" in why
    assert "decode=pallas_decode" in why
    _, _, why_paged = eng.prefill_plan(16, 4096, paged=True)
    assert "decode=pallas_paged_decode" in why_paged


def test_paged_decode_jaxpr_callback_free_and_aliased():
    """The traced paged decode step: no host callbacks anywhere in the
    jaxpr, and the fused kernel's pool write is declared as
    input_output_aliases on the pallas_call — the in-place pool update
    the whole paged design rests on.  (The full entry-point donation
    proof lives in the PROGRAMS.lock harness.)"""
    page = 16
    q, k, v, _, _, pages, lengths, _ = _pool_fixture(page)
    rng = np.random.RandomState(13)
    new_k = jnp.asarray(rng.randn(B, KVH, D), jnp.float32)
    new_v = jnp.asarray(rng.randn(B, KVH, D), jnp.float32)
    jaxpr = jax.make_jaxpr(
        lambda *a: paged_decode_attention(*a, layer=LAYER, new_k=new_k,
                                          new_v=new_v))(
        q, k, v, lengths, pages)
    text = str(jaxpr)
    assert "callback" not in text
    eqns = [e for e in jaxpr.jaxpr.eqns if e.primitive.name == "pallas_call"]
    assert eqns, "paged decode did not lower to a pallas_call"
    aliases = eqns[0].params.get("input_output_aliases")
    assert aliases, "fused paged write lost its input/output aliasing"
