"""MoE layer facade — parity with reference ``deepspeed/moe/layer.py:16``
(``MoE``) and ``moe/experts.py:10`` (``Experts``), as a flax module.

Expert parameters carry a leading expert dim E; the sharding plan places it
on the ``ep`` mesh axis (see ``EXPERT_PARAM_PATTERN`` in
``runtime/zero/partition.py``), so the dispatch/combine einsums in
``sharded_moe.py`` lower to all-to-alls over ICI and expert-parameter
gradients reduce only over the expert-data-parallel group — the semantics
``utils/groups.py:108`` builds with explicit process groups.
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from deepspeed_tpu.moe.sharded_moe import TopKGate, moe_dispatch_combine


class ExpertsMLP(nn.Module):
    """Default expert: the standard 2-layer MLP, vectorized over experts
    (reference wraps arbitrary expert modules; ``Experts`` replicates them —
    here one einsum-batched module computes all local experts on the MXU)."""
    num_experts: int
    hidden_size: int
    ffn_hidden_size: int
    activation: Callable = nn.gelu
    dtype: Any = jnp.bfloat16
    use_bias: bool = False

    @nn.compact
    def __call__(self, x):
        # x: [E, C, M]
        E, M, F = self.num_experts, self.hidden_size, self.ffn_hidden_size
        wi = self.param("experts_wi", nn.initializers.lecun_normal(),
                        (E, M, F), jnp.float32)
        wo = self.param("experts_wo", nn.initializers.lecun_normal(),
                        (E, F, M), jnp.float32)
        h = jnp.einsum("ecm,emf->ecf", x, wi.astype(x.dtype))
        if self.use_bias:
            # Megatron-style experts carry per-expert biases
            bi = self.param("experts_bi", nn.initializers.zeros, (E, F),
                            jnp.float32)
            h = h + bi[:, None, :].astype(x.dtype)
        h = self.activation(h)
        y = jnp.einsum("ecf,efm->ecm", h, wo.astype(x.dtype))
        if self.use_bias:
            bo = self.param("experts_bo", nn.initializers.zeros, (E, M),
                            jnp.float32)
            y = y + bo[:, None, :].astype(x.dtype)
        return y


class MoE(nn.Module):
    """Mixture-of-experts block (reference ``layer.py:16``).

    ``__call__(x)`` with x [..., M] returns (y, aux_loss, exp_counts) —
    the reference's output triple.
    """
    hidden_size: int
    num_experts: int = 1
    ep_size: int = 1
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_residual: bool = False
    ffn_hidden_size: Optional[int] = None
    expert: Optional[nn.Module] = None
    dtype: Any = jnp.bfloat16
    expert_bias: bool = False

    @nn.compact
    def __call__(self, x, train=True):
        M = self.hidden_size
        orig_shape = x.shape
        tokens = x.reshape(-1, M)

        gate_w = self.param("gate_kernel", nn.initializers.lecun_normal(),
                            (M, self.num_experts), jnp.float32)
        logits = tokens.astype(jnp.float32) @ gate_w
        gate = TopKGate(M, self.num_experts, self.k, self.capacity_factor,
                        self.eval_capacity_factor, self.min_capacity,
                        self.noisy_gate_policy, self.drop_tokens)
        rng = self.make_rng("gating") if (train and self.noisy_gate_policy
                                          and self.has_rng("gating")) else None
        aux_loss, combine, dispatch, exp_counts = gate(logits, train, rng)

        experts = self.expert or ExpertsMLP(
            self.num_experts, M, self.ffn_hidden_size or 4 * M,
            dtype=self.dtype, use_bias=self.expert_bias)
        y = moe_dispatch_combine(tokens, combine, dispatch, experts)

        if self.use_residual:
            # residual MoE (reference layer.py use_residual): blend with a
            # dense MLP through a learned coefficient
            mlp_out = nn.Dense(M, dtype=x.dtype, name="residual_mlp")(tokens)
            coef = nn.Dense(2, dtype=x.dtype, name="coefficient")(tokens)
            coef = jax.nn.softmax(coef, axis=-1)
            y = y * coef[..., 0:1] + mlp_out * coef[..., 1:2]

        return y.reshape(orig_shape).astype(x.dtype), aux_loss, exp_counts
