"""Lazy JIT build of the native (C++) host libraries.

Analog of the reference's ``OpBuilder.jit_load`` path
(``op_builder/builder.py:442,455``): compile on first use into a per-user
cache directory keyed by a source hash, then ``ctypes.CDLL`` the result.
The reference builds torch extensions with pybind11; here the libraries
expose a plain C ABI and are bound with ctypes (pybind11 is not in this
image), which also keeps them usable from non-Python tooling.
"""

import ctypes
import hashlib
import os
import subprocess
import sysconfig
import threading

from deepspeed_tpu.utils.logging import logger

_PKG = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# repo layout (and editable installs): csrc/ sits NEXT TO the package;
# a built wheel may instead carry it inside the package as package data
_CSRC_CANDIDATES = (os.path.join(os.path.dirname(_PKG), "csrc"),
                    os.path.join(_PKG, "csrc"))
_lock = threading.Lock()
_loaded = {}


def csrc_path(*parts):
    for root in _CSRC_CANDIDATES:
        p = os.path.join(root, *parts)
        if os.path.exists(p):
            return p
    return os.path.join(_CSRC_CANDIDATES[0], *parts)


def _cache_dir():
    base = os.environ.get("DSTPU_BUILD_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "deepspeed_tpu", "build")
    os.makedirs(base, exist_ok=True)
    return base


def _hash_sources(sources, flags):
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(" ".join(flags).encode())
    return h.hexdigest()[:16]


def _try_compile(out, sources, flags):
    cmd = ["g++", "-shared", "-fPIC", "-std=c++17", "-O3", *flags,
           *sources, "-o", out]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        return proc.stderr
    return None


def jit_build(name, sources, extra_flags=(), want_openmp=True):
    """Compile ``sources`` into a cached shared library; return its path.

    Tries the fastest flag set first (-march=native -fopenmp) and degrades
    gracefully — the reference probes CPU arch flags the same way
    (``op_builder/builder.py`` cpu_arch/simd_width detection).
    """
    flag_sets = []
    base = list(extra_flags)
    if want_openmp:
        flag_sets.append(base + ["-march=native", "-fopenmp"])
        flag_sets.append(base + ["-fopenmp"])
    flag_sets.append(base + ["-march=native"])
    flag_sets.append(base)

    tag = _hash_sources(sources, base)
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    out = os.path.join(_cache_dir(), f"{name}-{tag}{suffix}")
    if os.path.exists(out):
        return out
    with _lock:
        if os.path.exists(out):
            return out
        # per-process temp name: concurrent ranks may race to build the same
        # op; each compiles privately and os.replace publishes atomically
        tmp = out + f".tmp.{os.getpid()}"
        last_err = None
        try:
            for flags in flag_sets:
                last_err = _try_compile(tmp, sources, flags)
                if last_err is None:
                    os.replace(tmp, out)
                    logger.info(f"built native op {name} ({' '.join(flags)})")
                    return out
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        raise RuntimeError(f"failed to build native op {name}:\n{last_err}")


def load_library(name, sources, extra_flags=(), want_openmp=True):
    """jit_build + CDLL with caching; raises on toolchain failure."""
    key = (name, tuple(sources))
    if key in _loaded:
        return _loaded[key]
    path = jit_build(name, sources, extra_flags, want_openmp)
    lib = ctypes.CDLL(path)
    _loaded[key] = lib
    return lib
