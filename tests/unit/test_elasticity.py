"""Elasticity tests — analog of reference ``tests/unit/elasticity/``."""

import pytest

from deepspeed_tpu.elasticity import (compute_elastic_config,
                                      ElasticityConfigError,
                                      ElasticityIncompatibleWorldSize)

BASE = {
    "elasticity": {
        "enabled": True,
        "max_train_batch_size": 10000,
        "micro_batch_sizes": [8, 12, 16, 17],
        "min_gpus": 32,
        "max_gpus": 1500,
        "min_time": 20,
        "version": 0.1,
    }
}


def test_basic_10k():
    batch, valid = compute_elastic_config(BASE)
    assert batch <= 10000
    assert len(valid) > 1
    for w in valid:
        assert any(batch % (mb * w) == 0
                   for mb in BASE["elasticity"]["micro_batch_sizes"])


def test_global_batch_invariant_across_worlds():
    cfg = dict(BASE)
    b1, valid = compute_elastic_config(cfg)
    for w in valid[:5]:
        b2, _, mb = compute_elastic_config(cfg, world_size=w, return_microbatch=True)
        assert b2 == b1
        gas = b1 // (mb * w)
        assert mb * gas * w == b1


def test_disabled_raises():
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config({"elasticity": {"enabled": False}})


def test_incompatible_world_raises():
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 4,
                          "micro_batch_sizes": [4], "min_gpus": 1,
                          "max_gpus": 4, "version": 0.1}}
    with pytest.raises(ElasticityIncompatibleWorldSize):
        compute_elastic_config(cfg, world_size=3)


def test_v02_node_granularity():
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 1024,
                          "micro_batch_sizes": [4, 8], "min_gpus": 4,
                          "max_gpus": 64, "version": 0.2,
                          "num_gpus_per_node": 4}}
    batch, valid = compute_elastic_config(cfg)
    assert all(w % 4 == 0 for w in valid)


def test_sigterm_emergency_checkpoint_and_cross_world_resume(tmp_path):
    """DSElasticAgent end-to-end: SIGTERM mid-run -> emergency checkpoint
    at the step boundary -> resume into a DIFFERENT world size via
    ``elastic_config_for``, preserving the global batch (the reference's
    v0.1/v0.2 schedulers' invariant)."""
    import os
    import signal

    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    from deepspeed_tpu.parallel.topology import reset_topology
    from simple_model import SimpleModel, random_batch

    elastic = {"enabled": True, "max_train_batch_size": 64,
               "micro_batch_sizes": [2, 4], "min_gpus": 1, "max_gpus": 64,
               "version": 0.1}
    base = {"elasticity": elastic,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 1},
            "fault": {"enabled": True, "checksum": "crc32"}}

    agent = DSElasticAgent(base, checkpoint_dir=str(tmp_path), world_size=8)
    cfg8 = agent.elastic_config_for(8)
    gbs = cfg8["train_batch_size"]
    assert cfg8["train_micro_batch_size_per_gpu"] * \
        cfg8["gradient_accumulation_steps"] * 8 == gbs

    engine, *_ = deepspeed_tpu.initialize(model=SimpleModel(hidden_dim=16),
                                          config=cfg8)
    assert engine.train_batch_size() == gbs
    step_count = [0]

    def step_fn():
        for _ in range(engine.gradient_accumulation_steps()):
            loss = engine(random_batch(batch_size=32,
                                       seed=engine.global_steps))
            engine.backward(loss)
        engine.step()
        step_count[0] += 1
        if step_count[0] == 2:        # preemption arrives mid-run
            os.kill(os.getpid(), signal.SIGTERM)

    status, steps = agent.run(step_fn, engine, max_steps=10)
    assert status == "preempted" and steps == 2
    assert engine.global_steps == 2
    from deepspeed_tpu.runtime.fault.manifest import (list_tags,
                                                      verify_manifest)
    tags = list_tags(str(tmp_path))
    assert any(t.startswith("preempt_") for t in tags), tags
    assert verify_manifest(str(tmp_path / tags[0])) == []
    w_ref = np.asarray(jax.tree.leaves(engine.params)[0], np.float32)

    # resume on a HALVED slice: tp=2 over the same 8 devices -> dp world 4
    cfg4 = agent.elastic_config_for(4)
    assert cfg4["train_batch_size"] == gbs, \
        "elastic resume must preserve the global batch"
    assert cfg4["train_micro_batch_size_per_gpu"] * \
        cfg4["gradient_accumulation_steps"] * 4 == gbs
    cfg4["tensor_parallel"] = {"tp_size": 2}
    reset_topology()
    engine2, *_ = deepspeed_tpu.initialize(model=SimpleModel(hidden_dim=16),
                                           config=cfg4)
    engine2.load_checkpoint(str(tmp_path))
    assert engine2.global_steps == 2
    assert engine2.train_batch_size() == gbs
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(engine2.params)[0], np.float32), w_ref)
    # training continues at the new world size
    for _ in range(engine2.gradient_accumulation_steps()):
        loss = engine2(random_batch(
            batch_size=cfg4["train_micro_batch_size_per_gpu"] * 4,
            seed=engine2.global_steps))
        engine2.backward(loss)
    engine2.step()
    assert engine2.global_steps == 3
    assert np.isfinite(float(jax.device_get(loss)))
