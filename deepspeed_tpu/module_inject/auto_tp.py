"""AutoTP — structural tensor-parallel rule discovery.

Reference parity: ``module_inject/auto_tp.py:13`` — for models with no
hand-written policy, the reference walks the torch module tree, finds the
linears, and infers which must be row-parallel (followed by the all-reduce)
vs column-parallel.  Here the output is a list of ``(regex, kind)`` sharding
rules consumable by ``runtime/zero/partition.py tp_spec_for`` — TP stays a
GSPMD annotation.

Heuristic (same spirit as the reference's ``tp_parser``): within each
repeated transformer block, a linear whose *output* is hidden-size and which
terminates a branch (attention output / MLP down projection) is row-parallel;
linears producing non-hidden (heads, ffn, fused qkv) outputs are
column-parallel; embeddings shard on the vocab dim; 1-D params replicate.
"""

import re
from collections import Counter

from deepspeed_tpu.utils.logging import logger


def _torch_linears(model):
    """[(qualified_name, in_features, out_features)] for Linear/Conv1D."""
    import torch.nn as torch_nn
    out = []
    for name, mod in model.named_modules():
        if isinstance(mod, torch_nn.Linear):
            out.append((name, mod.in_features, mod.out_features))
        elif type(mod).__name__ == "Conv1D":          # GPT2 style [in, out]
            w = mod.weight
            out.append((name, w.shape[0], w.shape[1]))
    return out


def _leaf(name):
    return name.split(".")[-1]


def _strip_layer_index(name):
    return re.sub(r"\.\d+\.", ".N.", name)


# HF leaf name → converted (flax Transformer) parameter names.  Conversion
# normalizes every architecture onto q/k/v/o_proj + gate/up/down_proj, so TP
# rules must target those names, not the HF ones.  Fused projections expand
# to all three; context-dependent names (c_proj, dense) disambiguate by the
# qualified module path.
def _converted_names(qualified_name):
    leaf = _leaf(qualified_name)
    in_attn = re.search(r"(attn|attention)", qualified_name) is not None
    table = {
        "q_proj": ["q_proj"], "k_proj": ["k_proj"], "v_proj": ["v_proj"],
        "query": ["q_proj"], "key": ["k_proj"], "value": ["v_proj"],
        "c_attn": ["q_proj", "k_proj", "v_proj"],
        "query_key_value": ["q_proj", "k_proj", "v_proj"],
        "qkv_proj": ["q_proj", "k_proj", "v_proj"],
        "o_proj": ["o_proj"], "out_proj": ["o_proj"],
        "gate_proj": ["gate_proj"],
        "fc1": ["up_proj"], "c_fc": ["up_proj"], "fc_in": ["up_proj"],
        "dense_h_to_4h": ["up_proj"], "wi": ["up_proj"], "up_proj": ["up_proj"],
        "fc2": ["down_proj"], "fc_out": ["down_proj"],
        "dense_4h_to_h": ["down_proj"], "wo": ["down_proj"],
        "down_proj": ["down_proj"],
    }
    if leaf == "c_proj":
        return ["o_proj"] if in_attn else ["down_proj"]
    if leaf == "dense":
        return ["o_proj"] if in_attn else ["down_proj"]
    return table.get(leaf, [leaf])


class AutoTP:
    """Derive TP rules from an HF torch model's structure."""

    def __init__(self, model):
        self.model = model
        self.hidden = getattr(model.config, "hidden_size",
                              getattr(model.config, "n_embd", None))

    def in_module_list(self):
        """Distinct per-layer linear signatures (debug aid, reference
        ``auto_tp.py`` module list)."""
        return sorted({_strip_layer_index(n)
                       for n, _, _ in _torch_linears(self.model)})

    def tp_rules(self):
        """[(regex-over-framework-param-paths, 'col'|'row'|'vocab'|'replicate')]

        Regexes target the *converted* (flax) parameter names, so the rules
        drop straight into ``build_sharding_plan(tp_rules=...)``."""
        linears = _torch_linears(self.model)
        if not linears or self.hidden is None:
            logger.warning("AutoTP: no linears or unknown hidden size; "
                           "falling back to name-based DEFAULT_TP_RULES")
            from deepspeed_tpu.runtime.zero.partition import DEFAULT_TP_RULES
            return list(DEFAULT_TP_RULES)

        # Count how often each (stripped) linear name appears: repeated names
        # form the transformer trunk; singletons are embeddings/head.
        sig_count = Counter(_strip_layer_index(n) for n, _, _ in linears)
        rules = []
        emitted = set()
        seen = set()
        for name, fin, fout in linears:
            sig = _strip_layer_index(name)
            if sig in seen:
                continue
            seen.add(sig)
            if sig_count[sig] <= 1:
                # head-level linear: vocab-producing → column over vocab
                kind = "col" if fout != self.hidden else "replicate"
            elif fout == self.hidden and fin != self.hidden:
                kind = "row"        # ffn/heads → hidden: terminates a branch
            elif fout == self.hidden and fin == self.hidden:
                # square projection: attention out-proj (row) vs separate
                # q/k/v projection (col) — distinguish by role name.
                kind = "col" if re.search(r"(q|k|v|query|key|value)",
                                          _leaf(name)) else "row"
            else:
                kind = "col"
            for conv in _converted_names(name):
                if (conv, kind) not in emitted:
                    emitted.add((conv, kind))
                    rules.append((rf"{re.escape(conv)}.*(kernel|weight)$",
                                  kind))
        rules.append((r"(embed|wte|word_embeddings|embed_tokens).*"
                      r"(embedding|kernel|weight)$", "vocab"))
        rules.append((r".*(norm|ln_|layernorm|layer_norm|bias|scale).*",
                      "replicate"))
        logger.info(f"AutoTP derived {len(rules)} rules: {rules}")
        return rules


def get_tp_rules(model):
    return AutoTP(model).tp_rules()
