"""Smoke tests for the op micro-benchmark CLI (analog of reference
``tests/perf/adam_test.py`` — correctness of the harness, not speed)."""

from deepspeed_tpu.benchmarks import op_bench


def test_bench_adam_smoke():
    r = op_bench.bench_adam(numel=2048, iters=1)
    assert r["op"] == "fused_adamw" and r["ms"] > 0


def test_bench_flash_smoke():
    r = op_bench.bench_flash_attention(b=1, s=256, h=2, d=64, iters=1)
    assert r["ms"] > 0 and "TFLOP/s" in r   # rate rounds to 0 on slow CPU
    r = op_bench.bench_flash_attention(b=1, s=256, h=2, d=64, iters=1,
                                       bwd=True)
    assert r["op"].endswith("bwd")


def test_bench_quant_smoke():
    r = op_bench.bench_quantizer(numel=64 * 2048, iters=1)
    assert r["ms"] > 0


def test_long_context_bench_smoke():
    from deepspeed_tpu.benchmarks.long_context_bench import bench_sp_attention
    from deepspeed_tpu.parallel.topology import (initialize_topology,
                                                 reset_topology)
    reset_topology()
    initialize_topology(sp=8)
    try:
        r = bench_sp_attention("ring", 512, heads=4, head_dim=16, iters=1)
        assert r["sp"] == 8 and r["ms"] > 0
    finally:
        reset_topology()
