"""Loss scaling — parity with reference ``runtime/fp16/loss_scaler.py:66,90``
(``LossScaler``/``DynamicLossScaler``).

On TPU bf16 needs no scaling (the default); fp16 mode keeps the reference
semantics: dynamic scale doubles every ``scale_window`` good steps, halves on
overflow, never below ``min_scale``.  The scaler state lives as traced scalars
inside the jitted step so overflow handling is branch-free (``lax.cond``)."""

from typing import NamedTuple

import jax.numpy as jnp


class LossScalerState(NamedTuple):
    scale: jnp.ndarray          # f32 scalar
    good_steps: jnp.ndarray     # i32 scalar
    hysteresis: jnp.ndarray     # i32 scalar


class DynamicLossScaler:

    def __init__(self, init_scale=2**16, scale_factor=2.0, scale_window=1000,
                 min_scale=1.0, delayed_shift=1, consecutive_hysteresis=False,
                 raise_error_at_min_scale=False):
        self.init_scale = float(init_scale)
        self.scale_factor = float(scale_factor)
        self.scale_window = int(scale_window)
        self.min_scale = float(min_scale)
        self.delayed_shift = int(delayed_shift)
        self.consecutive_hysteresis = consecutive_hysteresis

    def init(self):
        return LossScalerState(
            scale=jnp.asarray(self.init_scale, jnp.float32),
            good_steps=jnp.asarray(0, jnp.int32),
            hysteresis=jnp.asarray(self.delayed_shift, jnp.int32))

    def update(self, state: LossScalerState, found_inf) -> LossScalerState:
        """Branch-free dynamic-scale update given the overflow flag.

        Reference semantics (``loss_scaler.py update_scale``): every overflow
        decrements hysteresis; the scale halves only once hysteresis is
        exhausted, then hysteresis resets.  With ``consecutive_hysteresis``
        a good step restores hysteresis; without it, good steps leave it
        depleted so repeated (even non-consecutive) overflows drop the scale.
        """
        found_inf = found_inf.astype(jnp.bool_)
        hysteresis = jnp.where(found_inf, jnp.maximum(state.hysteresis - 1, 0),
                               state.hysteresis)
        drop = found_inf & (hysteresis <= 0)
        new_scale = jnp.where(
            drop,
            jnp.maximum(state.scale / self.scale_factor, self.min_scale),
            state.scale)
        window_hit = (state.good_steps + 1) >= self.scale_window
        grow = (~found_inf) & window_hit
        new_scale = jnp.where(grow, new_scale * self.scale_factor, new_scale)
        new_good = jnp.where(found_inf | grow, 0, state.good_steps + 1)
        restore = drop | ((~found_inf) & jnp.asarray(self.consecutive_hysteresis))
        new_hyst = jnp.where(restore, jnp.asarray(self.delayed_shift, jnp.int32),
                             hysteresis)
        return LossScalerState(new_scale, new_good.astype(jnp.int32), new_hyst)


class StaticLossScaler:

    def __init__(self, scale=1.0):
        self.scale_value = float(scale)

    def init(self):
        return LossScalerState(
            scale=jnp.asarray(self.scale_value, jnp.float32),
            good_steps=jnp.asarray(0, jnp.int32),
            hysteresis=jnp.asarray(1, jnp.int32))

    def update(self, state, found_inf):
        return state


def create_loss_scaler(fp16_config):
    """Reference ``fp16/loss_scaler.py CreateLossScaler`` semantics:
    loss_scale==0 → dynamic, else static."""
    if not fp16_config.enabled:
        return StaticLossScaler(1.0)
    if fp16_config.loss_scale == 0:
        return DynamicLossScaler(
            init_scale=2.0 ** fp16_config.initial_scale_power,
            scale_window=fp16_config.loss_scale_window,
            min_scale=fp16_config.min_loss_scale,
            delayed_shift=fp16_config.hysteresis)
    return StaticLossScaler(fp16_config.loss_scale)
