"""Roofline attribution: achieved FLOP/s and GB/s, arithmetic
intensity, and a memory-bound/compute-bound classification for a
measured program (``docs/observability.md``, "Device memory &
roofline").

The roofline model explains exactly the regression class the bench
trail kept restating without attribution (BENCH_r04: decode collapsing
8,673 → 1,193 tok/s/chip with HBM util at 0.075): a program whose
arithmetic intensity (flops / HBM bytes) sits left of the machine's
ridge point (peak FLOP/s ÷ peak GB/s) is **memory-bound** — its
ceiling is the bandwidth roof, and an HBM-traffic regression cuts
throughput linearly no matter how idle the MXU is.  Numerators come
from the shared compiled cost model (``autotuning.cost_model``, the
same numbers ``PROGRAMS.lock`` format 3 locks); denominators are the
accelerator-reported peaks (the bench calibration phase's *measured*
peaks when plausible, datasheet otherwise — the caller chooses and the
block records which)."""


def device_peaks(measured_tflops=None, measured_gbps=None):
    """(peak_tflops, peak_gbps, source): the caller's measured peaks
    when both are present, else the datasheet constants for the local
    device kind."""
    if measured_tflops and measured_gbps:
        return float(measured_tflops), float(measured_gbps), "measured"
    from deepspeed_tpu.profiling.flops_profiler.profiler import (
        device_peak_hbm_gbps, device_peak_tflops)
    return device_peak_tflops(), device_peak_hbm_gbps(), "datasheet"


def classify(intensity, peak_tflops, peak_gbps):
    """``"memory_bound"`` when ``intensity`` (flops/byte) sits left of
    the ridge point ``peak_flops / peak_bandwidth``, else
    ``"compute_bound"``; ``None`` when the inputs can't say."""
    if not intensity or not peak_tflops or not peak_gbps:
        return None
    ridge = (peak_tflops * 1e12) / (peak_gbps * 1e9)
    return "memory_bound" if intensity < ridge else "compute_bound"


def roofline_block(flops, hbm_bytes, wall_s, peak_tflops=None,
                   peak_gbps=None, peak_source=None):
    """One roofline record for a program measured at ``wall_s`` seconds
    per execution: ``{flops, hbm_bytes, wall_s, achieved_tflops,
    achieved_gbps, intensity, ridge, bound, flops_fraction_of_peak,
    hbm_fraction_of_peak, peak_source}``.  ``flops``/``hbm_bytes`` are
    per-execution totals (the locked ``cost`` budget for contract
    programs; an analytic estimate for model-level phases — the caller
    owns the numerator's provenance)."""
    flops = float(flops or 0)
    hbm_bytes = float(hbm_bytes or 0)
    wall_s = float(wall_s or 0)
    block = {
        "flops": int(flops),
        "hbm_bytes": int(hbm_bytes),
        "wall_s": round(wall_s, 6),
        "intensity": round(flops / hbm_bytes, 3) if hbm_bytes else None,
        "achieved_tflops": round(flops / wall_s / 1e12, 4)
        if wall_s else None,
        "achieved_gbps": round(hbm_bytes / wall_s / 1e9, 3)
        if wall_s else None,
    }
    if peak_tflops and peak_gbps:
        ridge = (peak_tflops * 1e12) / (peak_gbps * 1e9)
        block["ridge"] = round(ridge, 3)
        block["bound"] = classify(block["intensity"], peak_tflops,
                                  peak_gbps)
        if block["achieved_tflops"] is not None:
            block["flops_fraction_of_peak"] = round(
                block["achieved_tflops"] / peak_tflops, 4)
        if block["achieved_gbps"] is not None:
            block["hbm_fraction_of_peak"] = round(
                block["achieved_gbps"] / peak_gbps, 4)
        if peak_source:
            block["peak_source"] = peak_source
    return block


__all__ = ["roofline_block", "classify", "device_peaks"]
