"""Checkpoint manifest: the integrity contract of a committed tag.

A committed checkpoint directory ``<save_dir>/<tag>/`` carries a
``MANIFEST.json`` listing every file with its size and checksum, plus the
jax/topology fingerprint and step metadata of the run that wrote it.  A tag
without a verifiable manifest is treated as absent: load walks back to the
newest valid tag instead of crashing on a partial or bit-rotted save
(CheckFreq's "verified checkpoint" property).

The manifest is written LAST inside the staging directory, so its presence
implies every listed file was fully written before the atomic rename
published the tag.
"""

import binascii
import hashlib
import json
import os
import re
import shutil
import time

from deepspeed_tpu.runtime.fault.atomic import atomic_write_text, fsync_dir
from deepspeed_tpu.utils.logging import logger

MANIFEST_NAME = "MANIFEST.json"
MANIFEST_VERSION = 1
_CHUNK = 4 * 1024 * 1024


class CheckpointCorrupt(RuntimeError):
    """A tag failed manifest verification (missing/truncated/corrupt
    files) — callers walk back to the previous valid tag."""


def _checksum_file(path, algorithm="sha256"):
    if algorithm == "crc32":
        crc = 0
        with open(path, "rb") as f:
            while chunk := f.read(_CHUNK):
                crc = binascii.crc32(chunk, crc)
        return f"{crc & 0xFFFFFFFF:08x}"
    if algorithm != "sha256":
        raise ValueError(f"unknown checksum algorithm {algorithm!r} "
                         "(expected 'sha256' or 'crc32')")
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while chunk := f.read(_CHUNK):
            h.update(chunk)
    return h.hexdigest()


def runtime_fingerprint(mesh_shape=None):
    """What must match (or at least be visible) when a checkpoint is
    resumed: recorded informationally — load does NOT refuse on mismatch
    (cross-topology resume is a supported path), it logs the delta."""
    import jax
    fp = {
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "device_count": jax.device_count(),
        "process_count": jax.process_count(),
    }
    if mesh_shape:
        fp["mesh"] = dict(mesh_shape)
    return fp


def build_manifest(ckpt_dir, tag, step_meta=None, checksum="sha256",
                   mesh_shape=None, advance_latest=True):
    """Walk ``ckpt_dir`` and record every regular file (path relative to
    the tag dir, size, checksum).  Called on the fully-written staging
    directory, before the manifest itself is added."""
    files = {}
    root = os.path.abspath(ckpt_dir)
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in sorted(filenames):
            if name == MANIFEST_NAME:
                continue
            p = os.path.join(dirpath, name)
            if not os.path.isfile(p) or os.path.islink(p):
                continue
            rel = os.path.relpath(p, root)
            files[rel] = {
                "size": os.path.getsize(p),
                checksum: _checksum_file(p, checksum),
            }
    return {
        "version": MANIFEST_VERSION,
        "tag": str(tag),
        "checksum_algorithm": checksum,
        "files": files,
        "fingerprint": runtime_fingerprint(mesh_shape),
        "step": dict(step_meta or {}),
        # False = this save deliberately did NOT advance 'latest'
        # (side checkpoints, debug dumps) — auto-resume skips it
        "advance_latest": bool(advance_latest),
        "created_unix": time.time(),
    }


def write_manifest(ckpt_dir, manifest):
    atomic_write_text(os.path.join(ckpt_dir, MANIFEST_NAME),
                      json.dumps(manifest, indent=2, sort_keys=True))


def read_manifest(ckpt_dir):
    """Parsed manifest dict, or None when absent/unreadable."""
    path = os.path.join(ckpt_dir, MANIFEST_NAME)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def verify_manifest(ckpt_dir, deep=True):
    """Check every manifest entry against the tag directory.  Returns the
    list of problems (empty = valid).  ``deep=False`` checks existence and
    sizes only — the cheap scan ``ds_ckpt list`` uses; ``deep=True`` also
    re-checksums every file."""
    manifest = read_manifest(ckpt_dir)
    if manifest is None:
        return [f"{MANIFEST_NAME} missing or unparseable"]
    algo = manifest.get("checksum_algorithm", "sha256")
    problems = []
    for rel, want in manifest.get("files", {}).items():
        p = os.path.join(ckpt_dir, rel)
        if not os.path.isfile(p):
            problems.append(f"{rel}: missing")
            continue
        size = os.path.getsize(p)
        if size != want.get("size"):
            problems.append(f"{rel}: size {size} != {want.get('size')}")
            continue
        if deep and algo in want:
            got = _checksum_file(p, algo)
            if got != want[algo]:
                problems.append(f"{rel}: {algo} {got} != {want[algo]}")
    return problems


# --------------------------------------------------------------------- #
# Tag discovery / walk-back / retention
# --------------------------------------------------------------------- #
_OLD_BACKUP_RE = re.compile(r"^(?P<tag>.+)\.old\.\d+$")
_TMP_FILE_RE = re.compile(r"\.tmp\.\d+$")


def _is_staging(name):
    """Exactly the names the publish protocol generates — ``<tag>.tmp``
    (atomic-save staging) and ``<tag>.old.<pid>`` (re-publish backup).
    Substring matching would swallow user tags that merely CONTAIN
    '.tmp' or '.old.'."""
    return name.endswith(".tmp") or _OLD_BACKUP_RE.match(name) is not None


def is_reserved_tag(name):
    """Tag names the protocol reserves for its staging machinery —
    ``save_checkpoint`` refuses them up front, because GC would later
    classify the committed directory as an orphan and destroy it."""
    return _is_staging(str(name))


def _sort_entries(entries):
    # manifest-less dirs (seed-era checkpoints) sort by mtime among
    # themselves but below any manifested tag of the same mtime era
    entries.sort(key=lambda e: (e[1] is not None, e[1] or 0, e[2]),
                 reverse=True)
    return entries


def _tag_entries(save_dir):
    """(name, step, mtime, path) for every committed tag dir, newest
    first by (manifest step, mtime)."""
    if not os.path.isdir(save_dir):
        return []
    entries = []
    for name in os.listdir(save_dir):
        p = os.path.join(save_dir, name)
        if not os.path.isdir(p) or _is_staging(name):
            continue
        manifest = read_manifest(p)
        step = (manifest or {}).get("step", {}).get("global_steps")
        entries.append((name, step, os.path.getmtime(p), p))
    return _sort_entries(entries)


def list_tags(save_dir):
    """Committed tag names under ``save_dir`` (staging/backup dirs
    excluded), newest first by (manifest step, mtime)."""
    return [name for name, _s, _m, _p in _tag_entries(save_dir)]


def newest_valid_tag(save_dir, checksum_verify=True, skip=(),
                     for_resume=False):
    """The newest tag that passes manifest verification; tags in ``skip``
    and invalid tags are walked past.  Manifest-less tags count as valid
    only when NO tag in the directory has a manifest (pre-protocol
    checkpoints stay loadable).  ``for_resume=True`` additionally skips
    tags whose manifest records ``advance_latest: false`` — side
    checkpoints saved with ``save_latest=False`` must not hijack
    auto-resume."""
    tags = [t for t in list_tags(save_dir) if t not in skip]
    manifests = {t: read_manifest(os.path.join(save_dir, t)) for t in tags}
    any_manifest = any(m is not None for m in manifests.values())
    for tag in tags:
        p = os.path.join(save_dir, tag)
        m = manifests[tag]
        if m is None:
            if any_manifest:
                logger.warning(f"[fault] tag {tag}: no {MANIFEST_NAME}; "
                               "skipping (newer tags are manifested)")
                continue
            return tag
        if for_resume and m.get("advance_latest") is False:
            logger.info(f"[fault] tag {tag}: saved with save_latest=False "
                        "— not an auto-resume candidate")
            continue
        problems = verify_manifest(p, deep=checksum_verify)
        if problems:
            logger.warning(f"[fault] tag {tag} failed verification "
                           f"({len(problems)} problem(s): {problems[:3]}) "
                           "— walking back")
            continue
        return tag
    return None


def gc_checkpoints(save_dir, keep_last_n, protect=(), dry_run=False):
    """Retention: delete committed tags beyond the newest ``keep_last_n``,
    plus every orphaned staging (``*.tmp`` / ``*.old.*``) directory.

    Safety properties:

    * an ``<tag>.old.*`` backup whose tag directory is MISSING and whose
      manifest verifies is RESTORED (renamed back), not deleted — the
      crash window of a same-tag re-publish must never destroy the only
      copy of a valid checkpoint;
    * the newest ``keep_last_n`` *valid* tags survive even when newer
      invalid (bit-rotted / partial) tags exist above them — retention
      must never leave the directory without a loadable checkpoint;
    * tags named in ``protect`` (e.g. the one ``latest`` points to)
      always survive.

    ``dry_run=True`` computes the same plan (``ds_ckpt gc --dry-run``)
    without touching disk — ONE implementation, with pending restores
    folded into the retention candidates, so the preview cannot diverge
    from the real run.

    Returns the action list: tag/staging names that were (or would be)
    removed, plus ``restore:<name>`` entries for orphaned backups that
    were (or would be) renamed back to their tag."""
    actions = []
    if not os.path.isdir(save_dir):
        return actions
    restored = []          # (tag, step, mtime, path) pending in dry-run
    for name in sorted(os.listdir(save_dir)):
        p = os.path.join(save_dir, name)
        if os.path.isfile(p) and _TMP_FILE_RE.search(name):
            # a crashed atomic_write_bytes leaves '<file>.tmp.<pid>'
            if not dry_run:
                os.remove(p)
            actions.append(name)
            continue
        if not os.path.isdir(p) or not _is_staging(name):
            continue
        if name in protect:
            continue
        m = _OLD_BACKUP_RE.match(name)
        if m and not os.path.isdir(os.path.join(save_dir, m.group("tag"))) \
                and read_manifest(p) is not None \
                and not verify_manifest(p, deep=False):
            # a re-publish died between moving the old tag aside and
            # promoting the new one — put the valid backup back
            tag = m.group("tag")
            manifest = read_manifest(p)
            if dry_run:
                restored.append((tag, manifest.get("step", {})
                                 .get("global_steps"),
                                 os.path.getmtime(p), p))
            else:
                os.rename(p, os.path.join(save_dir, tag))
                logger.warning(f"[fault] restored {tag} from orphaned "
                               f"backup {name}")
            actions.append(f"restore:{name}")
            continue
        if not dry_run:
            shutil.rmtree(p, ignore_errors=True)
        actions.append(name)
    if keep_last_n and keep_last_n > 0:
        # dry-run folds pending restores in at their sorted position, so
        # the retention plan matches what the real run (restore first,
        # then retain) would do
        entries = _sort_entries(_tag_entries(save_dir) + restored)
        tags = [name for name, _s, _m, _p in entries]
        paths = {name: p for name, _s, _m, p in entries}
        # keep the newest N tags AND the newest N tags that actually
        # verify (shallow: existence + sizes) — deleting a valid older
        # tag because corrupt newer ones outrank it would be data loss
        keep = set(tags[:keep_last_n])
        valid = [t for t in tags
                 if read_manifest(paths[t]) is None
                 or not verify_manifest(paths[t], deep=False)]
        keep.update(valid[:keep_last_n])
        for tag in tags:
            if tag in keep or tag in protect:
                continue
            if not dry_run:
                shutil.rmtree(os.path.join(save_dir, tag),
                              ignore_errors=True)
            actions.append(tag)
    if actions and not dry_run:
        fsync_dir(save_dir)
        logger.info(f"[fault] checkpoint GC: {sorted(actions)}")
    return actions
