"""TL007 positive fixture: reads after donation."""
import functools

import jax
import jax.numpy as jnp


def _step(params, cache, tok):
    return tok, cache


step = jax.jit(_step, donate_argnums=(1,))


def read_after_donation(params, cache, tok):
    out, new_cache = step(params, cache, tok)
    return out, cache.shape          # TL007: `cache` is dead after the call


def double_donation(params, cache, tok):
    out1, _ = step(params, cache, tok)
    out2, _ = step(params, cache, tok)   # TL007: second donation of `cache`
    return out1, out2


def donate_in_loop(params, cache, toks):
    outs = []
    for tok in toks:
        out, _ = step(params, cache, tok)   # TL007: loop never rebinds
        outs.append(out)
    return outs


@functools.partial(jax.jit, donate_argnames=("state",))
def advance(state, x):
    return {"v": state["v"] + x}


def kwarg_donation(state, x):
    new = advance(state=state, x=x)
    return new, state["v"]           # TL007: `state` read after donation
