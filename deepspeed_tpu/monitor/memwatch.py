"""Live device-memory telemetry (``docs/observability.md``, "Device
memory & roofline").

The device side of the PR 13 observability layer: where spans and
histograms explain *time*, this module explains *HBM* — the resource
that actually produced the BENCH_r04 decode cliff (bs128's HBM
utilization collapsing to 0.075 with no memory attribution on record).

:class:`DeviceMemorySampler` is a host-side, default-off sampler that
reads per-device ``bytes_in_use`` / ``peak_bytes_in_use`` /
``bytes_limit`` through the accelerator's canonical
``memory_snapshot()`` reader (the same number ``see_memory_usage``,
the flops profiler and the autotuner report) and reconciles the
serving engine's KNOWN owners — page pool, KV/draft workspaces,
params — against the device total into an **unattributed bytes**
figure: the gap is exactly where a leak, a retained donation copy or a
forgotten staging buffer hides.

Contracts (the PR 13 discipline):

* **Host-side only.**  ``memory_stats()`` is a PJRT host call; no
  jitted program is minted, sampling on/off leaves serving outputs
  bitwise-identical (proven in ``tests/unit/test_memwatch.py``).
* **Own cadence, cheap when idle.**  ``maybe_sample(now)`` is a clock
  compare until ``interval_s`` elapses; the engine calls it at an
  existing scheduler seam.
* **Injectable reader.**  The tier-1 CPU backend reports no live
  memory stats, so the reader is a constructor argument — tests (and
  exotic platforms) inject their own; production uses the
  accelerator.
* **Flight-recorder integration.**  When a recorder is attached,
  every sample also lands in the ring as a ``memory_sample`` event —
  a crash dump then shows the HBM trajectory INTO the distress, not
  just the scheduler's decisions.
"""

import time

# The /metrics families the HTTP front end renders from a sampler
# snapshot (``frontend/transport.py``) — a PURE literal: the
# ``ds_lint --stats-docs`` gate parses this tuple (like
# ``HISTOGRAM_SERIES`` in trace.py) and asserts every family is
# documented in docs/observability.md.
MEMORY_SERIES = (
    "dstpu_device_memory_bytes_in_use",
    "dstpu_device_memory_peak_bytes",
    "dstpu_device_memory_limit_bytes",
    "dstpu_device_memory_owned_bytes",
    "dstpu_device_memory_unattributed_bytes",
)


def accelerator_reader():
    """The production reader: the accelerator's canonical per-device
    ``memory_snapshots()``."""
    from deepspeed_tpu.accelerator.real_accelerator import get_accelerator
    return get_accelerator().memory_snapshots()


def tree_device_bytes(tree):
    """Total device bytes of a pytree of arrays (``nbytes`` of every
    leaf; 0 for leaves that carry none) — how owner figures are
    computed without touching device data."""
    import jax
    total = 0
    for leaf in jax.tree.leaves(tree):
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


class DeviceMemorySampler:
    """Periodic device-memory sampler with owner reconciliation.

    ``owners_fn`` returns ``{owner_name: bytes}`` for every buffer
    class the caller can account for; ``read_fn`` returns the
    accelerator-normalized per-device snapshot list.  ``flightrec``
    (optional) receives a ``memory_sample`` ring event per sample.
    Not self-locked: the serving engine calls it lock-held at a
    scheduler seam, matching the ``stats`` discipline."""

    def __init__(self, interval_s=10.0, read_fn=None, owners_fn=None,
                 flightrec=None, clock=time.monotonic):
        self.interval_s = float(interval_s)
        self._read = read_fn or accelerator_reader
        self._owners = owners_fn or (lambda: {})
        self._flightrec = flightrec
        self._clock = clock
        self._last_t = None
        self.samples = 0
        self.last = None                 # newest sample dict

    def sample(self):
        """Take one sample now: per-device snapshots + owner
        reconciliation.  Returns the sample dict (also kept as
        ``self.last``)."""
        devices = list(self._read() or [])
        owners = {k: int(v) for k, v in (self._owners() or {}).items()}
        in_use = sum(d.get("bytes_in_use", 0) for d in devices)
        peak = sum(d.get("peak_bytes_in_use", 0) for d in devices)
        limit = sum(d.get("bytes_limit", 0) for d in devices)
        owned = sum(owners.values())
        # Unattributed = what the device holds beyond what the engine
        # can name.  Clamped at zero: a backend that reports no live
        # stats (the tier-1 CPU backend) yields in_use=0 and must not
        # produce a negative gap.
        unattributed = max(0, in_use - owned)
        sample = {
            "t_mono": round(self._clock(), 6),
            "devices": devices,
            "bytes_in_use": in_use,
            "peak_bytes_in_use": peak,
            "bytes_limit": limit,
            "owners": owners,
            "owned_bytes": owned,
            "unattributed_bytes": unattributed,
        }
        self.samples += 1
        self.last = sample
        if self._flightrec is not None:
            self._flightrec.record(
                "memory_sample", bytes_in_use=in_use,
                peak_bytes_in_use=peak, owned_bytes=owned,
                unattributed_bytes=unattributed,
                owners={k: v for k, v in sorted(owners.items())})
        return sample

    def maybe_sample(self, now=None):
        """Sample when ``interval_s`` has elapsed since the last one
        (a clock compare otherwise); returns the new sample or
        ``None``."""
        now = self._clock() if now is None else now
        if self._last_t is not None \
                and now - self._last_t < self.interval_s:
            return None
        self._last_t = now
        return self.sample()

def device_memory_record():
    """One-shot normalized device-memory record for bench phases and
    training runs (no sampler needed): per-device snapshots + the
    summed in-use/peak/limit — the per-phase peak-HBM watermark."""
    devices = accelerator_reader()
    return {
        "devices": devices,
        "bytes_in_use": sum(d.get("bytes_in_use", 0) for d in devices),
        "peak_bytes_in_use": sum(d.get("peak_bytes_in_use", 0)
                                 for d in devices),
        "bytes_limit": sum(d.get("bytes_limit", 0) for d in devices),
    }


__all__ = ["DeviceMemorySampler", "MEMORY_SERIES", "accelerator_reader",
           "tree_device_bytes", "device_memory_record"]
