"""TL003 negative fixture: traced debugging and effects outside jit."""
import jax


@jax.jit
def step(x):
    jax.debug.print("stepping {}", x)    # traced — allowed
    return x * 2


def driver(x):
    out = step(x)
    print("done", out)                   # outside jit — fine
    return out
