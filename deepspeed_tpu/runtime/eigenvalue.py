"""Eigenvalue estimation (reference ``runtime/eigenvalue.py:12``).

The implementation lives beside its only consumer, the MoQ quantizer
(``runtime/quantize.py`` — reference wires both at ``engine.py:1528``);
this module preserves the reference's import path."""

from deepspeed_tpu.runtime.quantize import Eigenvalue  # noqa: F401
