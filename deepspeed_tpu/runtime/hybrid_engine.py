"""DeepSpeedHybridEngine — one weight set, two compiled programs.

Reference parity: ``runtime/hybrid_engine.py:32`` (``DeepSpeedHybridEngine``)
— the RLHF workhorse that flips a ZeRO-3 training model into injected-kernel
inference for rollout ``generate`` (``:178``), fusing LoRA adapters before
and unfusing after (``:130-165``).

TPU-native design: the training engine owns the fp32 master params under the
ZeRO sharding plan; ``generate`` runs the same jitted prefill+scan decode
loop as ``InferenceEngine`` against a bf16 *view* of those params produced by
one jitted cast-and-reshard program (all-gather of the ZeRO shards happens
once per rollout batch inside that program — the analog of the reference's
inference-container population ``:84-130``).  The view is cached and
invalidated on every optimizer step, so back-to-back rollouts pay the gather
once.  Train step and decode loop are two cached XLA executables over the
same buffers — no weight copying between "modes".
"""

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.tools.lint.hotpath import hot_path
from deepspeed_tpu.utils.logging import log_dist, logger


class DeepSpeedHybridEngine(DeepSpeedEngine):

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._infer_params = None
        self._infer_params_step = -1
        self._gen_compiled = {}
        self._gen_aot = {}       # (id(fn),) + abstract sig -> AOT executable
        self._cast_fn = None
        self._lora_spec = None
        self._lora_fused = False
        self._gen_rng = jax.random.key(0)
        # rollout/train latency bookkeeping (reference hybrid_engine fields)
        self._generate_latency = 0.0
        self._training_latency = 0.0
        # opt-in quantized rollouts (beyond the reference: decode is
        # HBM-bound, so an int8 inference view nearly halves rollout time;
        # training always sees the exact masters)
        he = self._config._param_dict.get("hybrid_engine", {}) \
            if isinstance(getattr(self._config, "_param_dict", None), dict) \
            else {}
        self._rollout_quantizer = None
        if he.get("quantize_rollouts", False):
            self.set_rollout_quantization(
                bits=int(he.get("rollout_quant_bits", 8)))
        # rollout decode-loop form (mirrors the inference config's
        # decode_early_exit): True (default) = bounded while_loop that
        # stops once every row hit EOS; False = the fixed-length scan —
        # the escape hatch if the while form regresses donation or
        # rollout throughput
        self._rollout_early_exit = bool(he.get("decode_early_exit", True))

    def set_rollout_quantization(self, bits=8):
        """Quantize the inference view per rollout (per-channel, fusable
        dequant inside the decode program).  ``bits=0`` disables.  The
        quantization is re-derived from the CURRENT masters after every
        optimizer step — rollouts always track training, just at reduced
        weight precision (an opt-in approximation; the reference's view is
        16-bit)."""
        if not bits:
            self._rollout_quantizer = None
        else:
            from deepspeed_tpu.runtime.weight_quantizer import (
                WeightQuantization)
            # per-channel scales are symmetric-int8-only; int4 falls back
            # to the grouped-scale path
            self._rollout_quantizer = WeightQuantization(
                bits=bits, per_channel=bits == 8)
            if self.topology.tp > 1:
                logger.warning("quantize_rollouts with tp>1: quantized "
                               "payloads are replicated, not TP-sharded")
        self._infer_params = None
        self._infer_params_step = -1
        self._quant_cast_fn = None
        self._gen_compiled = {}
        self._gen_aot = {}

    def _rollout_deq(self, params):
        """In-trace dequantization hook for the rollout program (identity
        when rollout quantization is off)."""
        if self._rollout_quantizer is None:
            return params
        return self._rollout_quantizer.dequantize_tree(
            params, self.compute_dtype)

    def _drop_quantized_view(self):
        # unlike the bf16 view (which ALIASES the master buffers, costing
        # nothing to keep), a quantized view is its own HBM allocation —
        # release it before training so the train step's activations can
        # use that space; back-to-back rollouts still share one view
        if self._rollout_quantizer is not None and \
                self._infer_params is not None:
            self._infer_params = None
            self._infer_params_step = -1
        # the rollout KV-cache workspace is likewise its own HBM
        # allocation (GBs at serving batch sizes) — release it before the
        # train step's activation peak; the next rollout re-zeros it once
        if getattr(self, "_gen_workspace", None) is not None:
            self._gen_workspace.release()

    def train_batch(self, *args, **kwargs):
        self._drop_quantized_view()
        return super().train_batch(*args, **kwargs)

    def forward(self, *args, **kwargs):
        # the fused fwd+bwd program runs inside forward() on the 3-call
        # path — the view must be gone before ITS peak, not backward()'s
        self._drop_quantized_view()
        return super().forward(*args, **kwargs)

    __call__ = forward

    # ------------------------------------------------------------------ #
    # Inference view of the training params
    # ------------------------------------------------------------------ #
    def _inference_view(self):
        """bf16 (compute-dtype), TP-sharded / ZeRO-gathered view of the
        current master params; rebuilt only after an optimizer step.

        NOTE lifetime: when the masters are already compute-dtype and
        inference-placed, the view ALIASES the live master buffers
        (zero-copy) — the next optimizer step donates those buffers, so a
        view held across ``train_batch``/``step`` is dead afterwards.
        Always re-fetch per rollout (``generate`` does)."""
        if self._infer_params is not None and \
                self._infer_params_step == self.global_steps:
            return self._infer_params
        if self._params is None:
            # RLHF generates before the first train step — init params now
            # (sharded at birth), same as the first forward would.
            seq = min(8, self.module.config.max_seq_len) \
                if hasattr(self.module, "config") else 8
            dummy = {"input_ids": jnp.zeros((1, seq), jnp.int32)}
            self._lazy_init((dummy,), {})
        if self._cast_fn is None:
            cast = self.compute_dtype
            self._cast_fn = jax.jit(
                lambda t: jax.tree.map(
                    lambda p: p.astype(cast)
                    if jnp.issubdtype(p.dtype, jnp.floating) else p, t),
                out_shardings=self._infer_shardings())
        params = self._params
        if self._lora_spec is not None and not self._lora_fused:
            params = _fuse_lora(params, self._lora_spec)
        if self._rollout_quantizer is not None:
            # int8/int4-at-rest rollout view: payload+scales, replicated
            # (mirrors InferenceEngine.set_params' quantized placement)
            if getattr(self, "_quant_cast_fn", None) is None:
                from deepspeed_tpu.runtime.weight_quantizer import _is_qw
                cast = self.compute_dtype
                rep = NamedSharding(self.mesh, P())
                q = self._rollout_quantizer

                @hot_path("hybrid.rollout_cast")
                def quantize_and_cast(t):
                    t = q.quantize_tree(t)
                    return jax.tree.map(
                        lambda p: p if _is_qw(p) else (
                            p.astype(cast)
                            if jnp.issubdtype(p.dtype, jnp.floating) else p),
                        t, is_leaf=_is_qw)
                self._quant_cast_fn = jax.jit(quantize_and_cast,
                                              out_shardings=rep)
            self._infer_params = self._quant_cast_fn(params)
            self._infer_params_step = self.global_steps
            return self._infer_params
        if params is self._params and self._view_is_identity():
            # memory-lean masters are already compute-dtype and, on a
            # mesh without live ZeRO scattering, already placed as the
            # inference program wants them: the "view" IS the master
            # buffers — zero-copy weight sharing (what the reference's
            # shared-container design approximates with pointer swaps)
            self._infer_params = params
        else:
            self._infer_params = self._cast_fn(params)
        self._infer_params_step = self.global_steps
        return self._infer_params

    def _infer_shardings(self):
        """Inference placement: keep TP sharding, drop ZeRO scattering
        (replicate over dp) so each decode step is gather-free."""
        from deepspeed_tpu.runtime.zero.partition import (
            is_expert_stacked, path_to_str, tp_spec_for)

        def spec_of(path, leaf):
            ps = path_to_str(path)
            return NamedSharding(
                self.mesh,
                tp_spec_for(ps, leaf.shape, self.mesh,
                            expert_stacked=is_expert_stacked(
                                ps, len(leaf.shape))))
        abstract = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), self._params)
        return jax.tree_util.tree_map_with_path(spec_of, abstract)

    def _view_is_identity(self):
        """True when cast+reshard would be a no-op copy: every float leaf is
        already compute-dtype and every leaf is already placed exactly as
        the inference sharding plan asks.  Computed once — the donating
        update preserves dtypes and out-shardings, so the verdict cannot
        change between steps."""
        if getattr(self, "_view_identity", None) is not None:
            return self._view_identity
        cast = self.compute_dtype
        shardings = jax.tree.leaves(self._infer_shardings())
        leaves = jax.tree.leaves(self._params)
        verdict = True
        for leaf, want in zip(leaves, shardings):
            if jnp.issubdtype(leaf.dtype, jnp.floating) and leaf.dtype != cast:
                verdict = False
                break
            sh = getattr(leaf, "sharding", None)
            if sh is None or not sh.is_equivalent_to(want, leaf.ndim):  # tpu-lint: disable=TL006 -- one-time placement verdict, memoized in _view_identity (donating updates preserve dtype/sharding)
                verdict = False
                break
        self._view_identity = verdict
        return verdict

    # ------------------------------------------------------------------ #
    # LoRA (reference hybrid_engine fuse_lora_weight/unfuse_lora_weight)
    # ------------------------------------------------------------------ #
    def set_lora(self, lora_spec):
        """Register LoRA adapters: {param-path: (A [in,r], B [r,out],
        scaling)} — fused into the inference view (and optionally the master
        weights) like the reference's ``_fuse_lora`` (:130)."""
        self._lora_spec = lora_spec
        self._infer_params = None

    def fuse_lora_weight(self):
        """Fuse LoRA deltas into the master weights in-place."""
        if self._lora_spec is None or self._lora_fused:
            return
        if self._params is None:
            raise RuntimeError("fuse_lora_weight() before parameters exist; "
                               "run a forward or generate first")
        self._params = _fuse_lora(self._params, self._lora_spec)
        self._lora_fused = True
        self._infer_params = None

    def unfuse_lora_weight(self):
        if self._lora_spec is None or not self._lora_fused:
            return
        self._params = _fuse_lora(self._params, self._lora_spec, sign=-1.0)
        self._lora_fused = False
        self._infer_params = None

    # ------------------------------------------------------------------ #
    # Rollout generation (reference hybrid_engine.generate :178)
    # ------------------------------------------------------------------ #
    @hot_path("hybrid.rollout_generate")
    def generate(self, input_ids, max_new_tokens=32, do_sample=False,
                 temperature=1.0, top_k=0, top_p=1.0, eos_token_id=-1,
                 seed=None, attention_mask=None):
        """Rollout generation over the shared weights.  ``attention_mask``
        supports RIGHT-padded prompt batches — the usual RLHF rollout input
        (see ``InferenceEngine.generate`` for the layout contract)."""
        from deepspeed_tpu.inference.engine import (KVCacheWorkspace,
                                                    make_generate_fn,
                                                    require_right_padded,
                                                    required_cache_len)
        import time
        t0 = time.time()
        input_ids = jnp.asarray(input_ids)
        if attention_mask is not None:
            require_right_padded(attention_mask)
        if seed is not None:
            self._gen_rng = jax.random.key(seed)
        self._gen_rng, rng = jax.random.split(self._gen_rng)
        # rollouts keep the ONE-PASS prefill: the in-program chunked scan
        # carries an un-aliased partial cache copy (the form the inference
        # engine's split-prefill path exists to avoid), and rollout
        # prompts are short — route long-prompt/big-batch generation
        # through InferenceEngine (the weights are a shared view) to get
        # the split path's memory bounds
        chunk = None
        # the loop form rides the key — it is part of the program's
        # identity and the executable-store key derives from this tuple
        key = (input_ids.shape[1], int(max_new_tokens), bool(do_sample),
               float(temperature), int(top_k), float(top_p),
               attention_mask is not None, chunk,
               self._rollout_early_exit)
        self._get_rollout_fn(key)
        params = self._inference_view()
        if getattr(self, "_gen_workspace", None) is None:
            # donated KV-cache workspace, shared across rollouts (see
            # KVCacheWorkspace: in-place decode, no double-buffered carry)
            self._gen_workspace = KVCacheWorkspace(self.module)
        cache = self._gen_workspace.take(
            input_ids.shape[0],
            required_cache_len(input_ids.shape[1], int(max_new_tokens),
                               chunk),
            self.compute_dtype)
        args = (params, cache, input_ids, rng, jnp.asarray(eos_token_id))
        if attention_mask is not None:
            args += (jnp.asarray(attention_mask),)
        out, cache = self._run_rollout(self._gen_compiled[key], args, key)
        self._gen_workspace.give_back(cache)
        out.block_until_ready()  # tpu-lint: disable=TL001 -- rollout latency metric needs the full program, once per rollout not per token
        self._generate_latency += time.time() - t0
        return out

    def _get_rollout_fn(self, key):
        """Build (or fetch) the rollout generation program for ``key`` =
        (prompt_len, max_new, do_sample, temperature, top_k, top_p,
        with_mask, chunk, early_exit)."""
        if key not in self._gen_compiled:
            from deepspeed_tpu.inference.engine import make_generate_fn
            (P, new, do_sample, temperature, top_k, top_p, with_mask,
             chunk, _early_exit) = key
            # carry the rollout view through the decode scan only when its
            # dequant materializes full weights (see WeightQuantization
            # .materializing_dequant); the plain bf16 view stays an
            # argument buffer (no loop-temp copy)
            self._gen_compiled[key] = make_generate_fn(
                self.module, self.compute_dtype, P, new, do_sample,
                temperature, top_k, top_p,
                param_transform=self._rollout_deq,
                with_mask=with_mask,
                carry_params=self._rollout_quantizer is not None
                and self._rollout_quantizer.materializing_dequant,
                prefill_chunk=chunk,
                early_exit=self._rollout_early_exit)
        return self._gen_compiled[key]

    def _run_rollout(self, fn, args, key):
        """Execute a rollout program — through an AOT executable when one
        exists (``warmup_rollout`` or the compile_cache executable store);
        the plain jit call otherwise (seed behavior)."""
        if self._program_cache is None and not self._gen_aot:
            return fn(*args)
        from deepspeed_tpu.runtime import compile_cache as cc
        sig = (id(fn),) + cc.abstract_signature(args)
        exe = self._gen_aot.get(sig)
        if exe is None:
            exe, _, _ = self._rollout_aot_compile(fn, args, key, sig)
        return exe(*args)

    def _rollout_aot_compile(self, fn, args, key, sig):
        """Returns ``(exe, compile_seconds, store_hit)``."""
        import json as _json
        from deepspeed_tpu.runtime.compile_cache import aot_compile_with_store
        q = self._rollout_quantizer
        # same context discipline as _train_key_parts: mesh layout and the
        # full engine config are part of the program's identity (the
        # runtime fingerprint only sees device kind/count — two different
        # shardings on the same host must not share an executable)
        key_parts = (key, sig[1:],
                     repr(getattr(self.module, "config",
                                  type(self.module).__name__)),
                     self.compute_dtype.__name__,
                     None if q is None else q.bits,
                     tuple(sorted(dict(self.mesh.shape).items())),
                     _json.dumps(self._config._param_dict, sort_keys=True,
                                 default=repr))
        exe, dt, hit = aot_compile_with_store(
            self._program_cache, "rollout", key_parts, fn, args)
        if exe is None:            # AOT failed (warned): plain jit call —
            exe = fn               # no fake 0.0s compile event
        else:
            self._report_compile("rollout", dt, hit)
        self._gen_aot[sig] = exe
        return exe, dt, hit

    def warmup_rollout(self, batch_sizes, prompt_len, max_new_tokens,
                       do_sample=False, temperature=1.0, top_k=0,
                       top_p=1.0, with_mask=False):
        """AOT-compile the rollout ``generate`` program for every batch-
        size bucket (RLHF rollout sweeps run several), reporting per-
        program compile time through the monitor.  Combine with
        ``warmup()`` (the train step) to pay the whole hybrid loop's
        compile cost up front — and, with the ``compile_cache`` block
        enabled, once per machine.  ``with_mask=True`` warms the
        right-padded-prompt variant (int32 masks — the usual RLHF rollout
        input; masked and unmasked are DIFFERENT programs).  Returns
        ``{program: seconds}`` (0.0 = store hit / already warm)."""
        from deepspeed_tpu.inference.engine import required_cache_len
        from deepspeed_tpu.runtime import compile_cache as cc
        params = self._inference_view()
        P, new = int(prompt_len), int(max_new_tokens)
        key = (P, new, bool(do_sample), float(temperature), int(top_k),
               float(top_p), bool(with_mask), None,
               self._rollout_early_exit)
        fn = self._get_rollout_fn(key)
        report = {}
        for B in batch_sizes:
            B = int(B)
            cache = jax.eval_shape(
                lambda: self.module.init_cache(
                    B, required_cache_len(P, new, None),
                    dtype=self.compute_dtype))
            args = (params, cache,
                    jax.ShapeDtypeStruct((B, P), jnp.int32),
                    jax.eval_shape(lambda: jax.random.key(0)),
                    jnp.asarray(-1))
            if with_mask:
                args += (jax.ShapeDtypeStruct((B, P), jnp.int32),)
            sig = (id(fn),) + cc.abstract_signature(args)
            name = f"rollout:b{B}p{P}n{new}"
            if sig in self._gen_aot:
                report[name] = 0.0
                continue
            _, dt, hit = self._rollout_aot_compile(fn, args, key, sig)
            report[name] = 0.0 if hit else dt
        return report


@partial(jax.jit, static_argnames=("sign",))  # tpu-lint: disable=TL002 -- input is the live master tree; donating it would kill the training copy
def _fuse_lora_jit(params, lora_spec, sign):
    from deepspeed_tpu.runtime.zero.partition import path_to_str

    def one(path, w):
        entry = lora_spec.get(path_to_str(path))
        if entry is None:
            return w
        a, b, scale = entry
        delta = (a.reshape(a.shape[0], -1) @ b.reshape(b.shape[0], -1))
        return w + (sign * scale * delta.reshape(w.shape)).astype(w.dtype)

    return jax.tree_util.tree_map_with_path(one, params)


def _fuse_lora(params, lora_spec, sign=1.0):
    """W ← W + sign·scale·(A@B) for every (path, (A, B, scale)) entry.
    Module-level jit so repeated fuses (one per train-step/rollout cycle)
    hit the executable cache."""
    return _fuse_lora_jit(params, lora_spec, sign=float(sign))
