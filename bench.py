"""Benchmark suite: the reference's headline workloads on the local chip(s).

Mirrors DeepSpeed-Chat's numbers (``BASELINE.json`` / ``BASELINE.md``):

1. **North star** — step-1 SFT of OPT-1.3B with ZeRO-3, target >=35% MFU.
   A single v5e chip (16 GB) cannot hold fp32 master+moments for 1.3B
   params (12 bytes/param = 15.8 GB), and this environment's tunneled
   device makes host offload throughput-meaningless, so the 1.3B run uses
   the documented memory-lean mode (bf16 master weights + bf16 Adam
   moments, fp32 optimizer arithmetic — ``bf16.master_weights_in_bf16`` +
   optimizer ``state_dtype``).  Headline metric.
2. **Regression guard** — OPT-350M SFT with full fp32 master/moments
   (reference-exact semantics), the round-1 38%-MFU config.
3. **Generation** — the DS-Chat generation phase (prompt 256 + gen 256,
   ``blogs/deepspeed-chat/README.md:57``) through ``InferenceEngine``'s
   jitted prefill+decode program, at bf16 / int8 / int8+int8-KV and at
   throughput (bs64/bs128) and long-cache (4k) serving points.
4. **Hybrid RLHF** — DS-Chat step-3 loop (train steps + shared-weight
   rollouts) with a full-pytree weight-identity check.
5. **Long context** — seq-8k SFT through the Pallas flash path.
Plus a **calibration** phase that measures the chip's achievable HBM
bandwidth and MXU flops so every roofline/MFU claim is anchored to an
in-run measurement, not just a datasheet constant.

Crash containment (the round-3 lesson: one late-phase OOM erased the whole
record; the round-5 lesson: one 40-min cold compile starved everything
behind it): each phase runs in its OWN subprocess, like the reference runs
each workload under its launcher (``launcher/runner.py:377``).  The parent
never imports jax, so a dead phase cannot pin device memory anywhere.
Phases run CHEAP-FIRST under per-phase wall-clock budgets
(``BENCH_PHASE_TIMEOUT`` × ``PHASE_TIMEOUT_SCALE``); an overrun is
skipped-and-recorded (no fallback retry — a safe config fixes an OOM, not
slowness; ``BENCH_RETRY_ON_TIMEOUT=1`` re-enables it), and an optional
``BENCH_SUITE_BUDGET`` skips whatever the total budget can no longer
afford.  Under a suite budget, phase ORDER rotates round-robin across
rounds by staleness (``_phase_order``, reading the ``BENCH_r*.json``
trail): whatever starved last round runs first this round, so every
phase is measured every few rounds instead of the same leading k forever
(the round-5 blackout: 3/10 phases, five rounds running).  A crashed phase is retried ONCE with a safe config (remat on /
smaller batch, recorded as ``"fallback": true``) and a double failure
records an ``error`` field instead of killing the run.  Results accumulate
TWO ways as phases complete: the raw phase map in ``.bench_partial.json``
and the full driver-contract record in ``BENCH_partial.json`` (env
``BENCH_RESULTS_JSON``), so an interrupt / kill / crash after phase k
still leaves a complete record of all k finished phases — Ctrl-C and
SIGTERM additionally flush that record to stdout and exit 0.  Engines run
with the persistent compile/executable cache
(``runtime/compile_cache.py``, dir ``.jax_bench_cache``), so every
program — including sft_2.7b's — is cold exactly once per machine; each
phase's record carries a ``compile_cache`` block showing what it compiled
vs reloaded.  The final line on stdout is ONE JSON object and the exit
code is 0 whenever the harness itself survived — missing numbers are
visible as ``error`` fields, never as a stack trace in place of the
record.

``BENCH_MODEL``/``BENCH_*`` env vars run a single custom training bench
in-process instead (old behavior).
"""

import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

import numpy as np


def _cache_dir():
    return os.environ.get("DSTPU_COMPILE_CACHE_DIR") \
        or os.path.join(REPO, ".jax_bench_cache")


def _setup_compile_cache():
    """Persistent compile/executable cache (runtime/compile_cache.py): the
    suite is compile-dominated (sft_2.7b's four 2.7B backward programs
    alone approach 40 min cold — the rc=124 that erased the round-5
    record); the framework cache makes every program cold exactly once per
    machine.  Shared by all phase subprocesses."""
    from deepspeed_tpu.runtime.compile_cache import configure_persistent_cache
    configure_persistent_cache(_cache_dir(), min_compile_time_secs=2.0)


def _cc_block():
    """``compile_cache`` config block handed to every engine a phase
    builds: persistent XLA cache + serialized AOT executables, shared
    across phase subprocesses and across runs."""
    return {"enabled": True, "cache_dir": _cache_dir(),
            "min_compile_time_secs": 2.0}


def _cache_report(before):
    """Delta of the compile-cache counters across one phase body — makes
    compile cost (and the warm-run savings) visible in the record."""
    from deepspeed_tpu.runtime.compile_cache import stats
    now = stats().snapshot()
    rep = {k: now[k] - before.get(k, 0)
           for k in ("persistent_requests", "persistent_hits",
                     "executable_hits", "executable_misses",
                     "executable_saves")}
    rep["compile_seconds"] = {
        k: round(v, 1) for k, v in now["compile_seconds"].items()
        if k not in before.get("compile_seconds", {})}
    return rep


def _sync_scalar(x):
    """Dependent-sync fence (see deepspeed_tpu.utils.sync)."""
    from deepspeed_tpu.utils.sync import dependent_sync_scalar
    return dependent_sync_scalar(x)


def _measured_peaks():
    """(tflops, gbps) from the calibration phase, handed to later phases
    via env; (None, None) when calibration hasn't run."""
    t = os.environ.get("BENCH_MEASURED_TFLOPS")
    g = os.environ.get("BENCH_MEASURED_GBPS")
    return (float(t) if t else None, float(g) if g else None)


# --------------------------------------------------------------------- #
# Phase bodies (run inside a phase subprocess)
# --------------------------------------------------------------------- #

def calibrate_bench():
    """Measure what this chip actually achieves, next to the datasheet
    constants the profiler uses — anchors every ``mfu`` /
    ``hbm_utilization`` in the suite (a wrong peak constant would silently
    inflate them all).

    - HBM bandwidth: time ``y = x * 1.0001`` over a 1 GiB bf16 array
      (reads + writes 2 GiB; pure streaming, no reuse).
    - MXU flops: time a 8192^3 bf16 matmul (2*M*N*K flops, fully
      MXU-resident).
    """
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.profiling.flops_profiler.profiler import (
        device_peak_tflops, device_peak_hbm_gbps)

    on_cpu = jax.devices()[0].platform == "cpu"

    # Measurement hygiene, both learned the hard way on the tunneled
    # device: (1) every rep must live INSIDE one compiled program — each
    # separate execution pays ~30-140 ms of tunnel dispatch overhead, so
    # chained jit calls measure the tunnel, not the chip; (2) timing two
    # rep counts and differencing cancels the remaining per-execution
    # overhead (same trick the decode bench uses for prefill); (3) the
    # loop body must not be constant-foldable — a scale below 1 + 2^-7
    # rounds to bf16 1.0 and compiles to identity, and multiplying by the
    # SAME scalar every iteration folds to one multiply, so the scalar
    # rides the loop carry and changes per step; (4) completion via the
    # dependent-sync fence (block_until_ready under-waits here).
    def timed_loop(build, warm_arg, reps):
        fn = jax.jit(build, static_argnums=(1,))
        _sync_scalar(fn(warm_arg, reps))           # compile + warm
        _sync_scalar(fn(warm_arg, 2 * reps))
        # one differenced pair only cancels the MEAN dispatch overhead;
        # the tunnel's jitter spans tens of ms.  MEDIAN of several pairs:
        # min-of-diffs is biased FAST (a contended t1 shrinks the diff and
        # inflates the rate — an early round recorded 3.8x the datasheet
        # bandwidth that way), while the median rejects both tails.
        # sample until 5 positive pairs land (cap 12 attempts): on a
        # loaded 1-core CI box a burst of scheduler noise can flip several
        # consecutive diffs negative, and giving up after 5 straight
        # attempts made the whole phase flaky — the estimator is unchanged
        # (median of positive diffs), only the patience grew
        diffs = []
        for _ in range(12):
            t0 = time.perf_counter()
            _sync_scalar(fn(warm_arg, reps))
            t1 = time.perf_counter()
            _sync_scalar(fn(warm_arg, 2 * reps))
            t2 = time.perf_counter()
            d = (t2 - t1) - (t1 - t0)
            if d > 0:
                diffs.append(d)
            if len(diffs) >= 5:
                break
        if not diffs:
            raise RuntimeError(
                "calibration: dispatch jitter swamped the measurement "
                "(all differenced pairs were non-positive)")
        return float(np.median(diffs)) / reps      # per-rep, overhead-free

    # --- streaming bandwidth: v = v * s with a per-iteration scalar ---
    n = ((1 << 26) if on_cpu else (1 << 30)) // 2   # 1 GiB bf16 (64 MiB cpu)
    x = jnp.ones((n,), jnp.bfloat16)
    assert float(jnp.bfloat16(1.0078125)) != 1.0    # really a multiply

    def bw(v, reps):
        def body(_, carry):
            v, s = carry
            return v * s, s + jnp.bfloat16(0.0078125)
        out, _ = jax.lax.fori_loop(0, reps, body,
                                   (v, jnp.bfloat16(1.0078125)))
        return out[0]

    dt = timed_loop(bw, x, 16)
    measured_gbps = 2 * x.nbytes / dt / 1e9  # read + write per element

    # --- MXU matmul: out = out @ a, data-dependent, unfoldable ---
    m = 1024 if on_cpu else 8192
    a = jnp.full((m, m), 1.0 / m, jnp.bfloat16)   # fixed point of p @ a

    def mm(p, reps):
        return jax.lax.fori_loop(0, reps, lambda _, o: o @ p, p)[0, 0]

    dt = timed_loop(mm, a, 4 if on_cpu else 8)
    measured_tflops = 2 * m ** 3 / dt / 1e12

    # --- host<->device link (the offload tier's speed limit) ---
    h = np.ones((1 << 27,), np.uint8)              # 128 MB
    x = jax.device_put(h); x.block_until_ready()   # warm path + alloc
    up, down = [], []
    for _ in range(3):
        t0 = time.perf_counter()
        x = jax.device_put(h); x.block_until_ready()
        up.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        _ = np.asarray(jax.device_get(x))
        down.append(time.perf_counter() - t0)
    link_up = h.nbytes / min(up) / 1e9
    link_down = h.nbytes / min(down) / 1e9

    const_tflops, const_gbps = device_peak_tflops(), device_peak_hbm_gbps()
    return {
        "platform": jax.devices()[0].platform,
        "n_devices": jax.device_count(),
        # host link: what ZeRO-Offload's per-boundary grad-down/param-up
        # round trip can at best achieve on THIS host path (tunneled
        # devices are far below PCIe — the honest denominator for the
        # offload phase's overhead)
        "host_to_device_gbps": round(link_up, 2),
        "device_to_host_gbps": round(link_down, 2),
        "measured_hbm_gbps": round(measured_gbps, 1),
        "measured_mxu_tflops": round(measured_tflops, 1),
        "datasheet_hbm_gbps": const_gbps,
        "datasheet_mxu_tflops": const_tflops,
        # >1.0 would mean the datasheet constant understates the chip and
        # every "percent of roofline" in this suite is conservative
        "hbm_fraction_of_datasheet": round(measured_gbps / const_gbps, 3),
        "mxu_fraction_of_datasheet": round(measured_tflops / const_tflops, 3),
    }


def memory_snapshot_bench(fallback=False):
    """Per-program memory & roofline micro-phase (the r05-blackout
    lesson applied to the MEMORY record: cheap, pinned right behind
    calibration, so per-program HBM numbers commit even in rounds whose
    budget dies before the heavy phases).

    For every contract-locked hot-path program (the tier-1 entry-point
    builders — toy shapes, exact compiler budgets): compile, extract
    ``compiled.memory_analysis()`` + ``cost_analysis()`` through the
    same shared cost model ``PROGRAMS.lock`` format 3 locks, time a few
    executions, and derive the roofline block — achieved FLOP/s,
    achieved GB/s, arithmetic intensity, memory-bound/compute-bound —
    against the calibration phase's measured peaks (datasheet when
    calibration hasn't run or was implausible).  Wall times at toy
    shapes include host dispatch, so the achieved fractions are floors;
    the intensity and bound classification are timing-independent."""
    import jax
    from deepspeed_tpu.parallel.topology import reset_topology
    from deepspeed_tpu.profiling.roofline import (device_peaks,
                                                  roofline_block)
    from deepspeed_tpu.tools.lint import mem_contract

    meas_t, meas_g = _measured_peaks()
    peak_t, peak_g, peak_src = device_peaks(meas_t, meas_g)

    def _copy(x):
        try:
            return x.copy()
        except Exception:
            return x

    want = os.environ.get("BENCH_MEMSNAP_PROGRAMS")
    want = {w.strip() for w in want.split(",") if w.strip()} if want \
        else None
    fallback_keep = {"inference_decode", "serving_decode_step",
                     "serving_admit"}
    programs, errors = {}, {}
    matched = set()
    # the name filter + builder->program map discipline is shared with
    # ds_lint --mem (mem_contract.filtered_builders): subset runs skip
    # the engine builds of filtered-out programs, and the map is
    # cross-checked against what each builder actually constructs
    for build, mapped in mem_contract.filtered_builders(want):
        if fallback and build.__name__ not in fallback_keep:
            # safe-config retry: the three cheapest engine builds
            # still commit a usable memory record
            continue
        reset_topology()
        try:
            ep = build()
            drift = mem_contract.map_drift_problem(build.__name__,
                                                   mapped, ep.name)
            if drift:
                errors[build.__name__] = drift
            if want and ep.name not in want:
                continue
            # matched BEFORE compiling: a matched program whose compile
            # fails is a program_errors entry, not a "misspelled name"
            matched.add(ep.name)
            # cache-bypassed: a persistent-cache reload (bench runs with
            # the compile cache on) reports degenerate alias bytes
            with mem_contract.fresh_compile_env():
                compiled = ep.fn.lower(*ep.args).compile()
            rec = mem_contract.memory_cost_of(compiled)
            # timed execution: donated buffers die per call, so every
            # rep runs on fresh copies; median rejects dispatch jitter
            times = []
            for _ in range(3):
                args = jax.tree.map(_copy, ep.args)
                t0 = time.perf_counter()
                jax.block_until_ready(compiled(*args))
                times.append(time.perf_counter() - t0)
            wall = float(np.median(times))
            programs[ep.name] = {
                "memory": rec["memory"],
                "cost": rec["cost"],
                "roofline": roofline_block(
                    rec["cost"]["flops"], rec["cost"]["bytes_accessed"],
                    wall, peak_t, peak_g, peak_src),
            }
        except Exception as e:               # one sick program must not
            errors[build.__name__] = f"{type(e).__name__}: {e}"[:300]
        finally:                             # erase the others' numbers
            reset_topology()
    result = {
        "programs": programs,
        "n_programs": len(programs),
        "peaks": {"tflops": peak_t, "gbps": peak_g, "source": peak_src},
        "shapes": "tier-1 contract entry points (toy): budgets exact, "
                  "wall times include host dispatch",
        # the per-phase hbm_watermark is stamped centrally by run_phase
        # (device_memory_record) like every other phase
    }
    if want:
        # a misspelled subset name must fail LOUDLY, not thin the
        # record silently (ds_lint --mem enforces the same rule)
        unmatched = want - matched
        if unmatched:
            errors["unmatched_names"] = (
                f"BENCH_MEMSNAP_PROGRAMS name(s) {sorted(unmatched)} "
                f"matched no program — nothing was recorded for them")
    if errors:
        result["program_errors"] = errors
    if not programs:
        result["error"] = f"no program produced a memory record: {errors}"
    return result


def train_bench(model_name, *, micro_bs, zero_stage, steps, seq=2048,
                lean=False, remat=False, remat_policy="dots_and_attn_saveable",
                scan_layers=False, fused_qkv=False, loss_chunks=8,
                gas=1, offload=None, grad_accum_dtype=None, grad_groups=1):
    """``offload``: None (in-HBM optimizer) | "cpu" (ZeRO-Offload: bf16
    working params on device, fp32 masters+moments in host RAM, the C++
    SIMD Adam steps them) | "nvme" (moments/masters in swap files through
    ``csrc/aio``, pipelined reads).  ``gas`` amortizes the per-optimizer-
    step host round-trip over gradient-accumulation micro-steps —
    large-model single-chip training exactly as the reference stages it
    (stage_1_and_2.py:1037 offload path; blogs/deepspeed-chat README
    OPT-13B-on-one-A100 story)."""
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.opt import opt_config
    from deepspeed_tpu.models.transformer import Transformer
    from deepspeed_tpu.profiling.flops_profiler.profiler import device_peak_tflops

    cfg = opt_config(model_name, max_seq_len=seq, dtype="bfloat16",
                     remat=remat, remat_policy=remat_policy,
                     scan_layers=scan_layers, fused_qkv=fused_qkv,
                     loss_seq_chunks=loss_chunks)
    model = Transformer(cfg)
    opt_params = {"lr": 9.65e-6, "weight_decay": 0.0}
    if lean:
        opt_params["state_dtype"] = "bfloat16"
    config = {
        "train_micro_batch_size_per_gpu": micro_bs,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": opt_params},
        "bf16": {"enabled": True, "master_weights_in_bf16": bool(lean)},
        "zero_optimization": {"stage": zero_stage},
        "gradient_clipping": 1.0,
        "compile_cache": _cc_block(),
    }
    if offload:
        config["zero_optimization"]["offload_optimizer"] = {
            "device": offload, "pipeline_read": offload == "nvme",
            **({"nvme_path": "/tmp/dstpu_bench_nvme"}
               if offload == "nvme" else {})}
    if grad_groups > 1:
        config["zero_optimization"]["grad_partition_groups"] = grad_groups
    if grad_accum_dtype:
        config["data_types"] = {"grad_accum_dtype": grad_accum_dtype}
    engine, *_ = deepspeed_tpu.initialize(model=model, config=config)

    rng = np.random.default_rng(0)
    n_dev = jax.device_count()
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size,
        (gas, micro_bs * engine.topology.dp, seq)).astype(np.int32)}

    loss = engine.train_batch(batch=batch)
    loss = engine.train_batch(batch=batch)
    _sync_scalar(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch=batch)
    final_loss = _sync_scalar(loss)
    dt = (time.perf_counter() - t0) / steps

    tokens_per_step = micro_bs * engine.topology.dp * seq * gas
    n_params = cfg.num_params()
    peak = device_peak_tflops() * 1e12 * n_dev
    mfu = 6.0 * n_params * tokens_per_step / dt / peak if peak else 0.0
    result = {
        "model": model_name,
        "tokens_per_sec_chip": round(tokens_per_step / dt / n_dev, 1),
        "mfu": round(mfu, 4),
        "step_time_s": round(dt, 4),
        "loss": round(final_loss, 4),
        "seq": seq,
        "micro_bs": micro_bs,
        "zero_stage": zero_stage,
        "lean_optimizer_states": bool(lean),
        "remat": bool(remat),
        "platform": jax.devices()[0].platform,
    }
    if gas != 1:
        result["gradient_accumulation_steps"] = gas
    if offload:
        result["offload_optimizer"] = offload
    if grad_accum_dtype:
        result["grad_accum_dtype"] = grad_accum_dtype
    meas_tflops, _ = _measured_peaks()
    if meas_tflops:
        result["mfu_vs_measured_mxu"] = round(
            6.0 * n_params * tokens_per_step / dt
            / (meas_tflops * 1e12 * n_dev), 4)
    return result


def decode_bench(model_name="opt-1.3b", *, batch_size=16, prompt=256,
                 gen=256, int8=False, kv_int8=False, mxu_int8=False):
    """DS-Chat generation-phase workload (prompt 256 + gen 256) through the
    jitted prefill+decode program (reference Hybrid Engine `generate`,
    ``blogs/deepspeed-chat/README.md:265``).  ``int8=True`` runs the
    per-channel INT8-at-rest weight path (reference
    ``runtime/weight_quantizer.py``); layers are unrolled
    (``scan_layers=False``) — scanning the trunk dynamic-slices a relayout
    copy of each layer's qkv weights per token.

    ``hbm_utilization`` is estimated traffic / peak bandwidth: weight bytes
    once per decode step plus the KV blocks the Pallas decode kernel
    actually DMAs (live blocks only, at its block_k granularity)."""
    import jax
    from deepspeed_tpu.models.opt import opt_config
    from deepspeed_tpu.models.transformer import Transformer
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.ops.transformer.decode_attention import \
        DEFAULT_BLOCK_K_DECODE
    from deepspeed_tpu.profiling.flops_profiler.profiler import \
        device_peak_hbm_gbps

    cfg = opt_config(model_name, max_seq_len=prompt + gen, dtype="bfloat16",
                     scan_layers=False, kv_cache_quant=kv_int8,
                     decode_int8_matmuls=mxu_int8)
    model = Transformer(cfg)
    quant = {"enabled": True, "bits": 8, "per_channel": True} if int8 else {}
    # Long prompts must run the REAL chunked-prefill pipeline.  The r04
    # 4k phase's "fallback": true was the "auto" chunk policy silently
    # declining chunking (the Pallas chunk kernel is gated off on some
    # backends), which dropped the 3968-token prompt onto the one-pass
    # path — its dense-attention fallback materializes [B, H, S, S] fp32
    # scores (~32 GB at bs16 x 4k) and OOMs, and only the bs8 retry fit.
    # Pinning the chunk size forces the split per-chunk pipeline (dense
    # per-chunk transient is only [B, H, C, S]); prefill_plan records
    # which pipeline ran and why, either way.
    chunk_cfg = 512 if prompt >= 1024 else "auto"
    eng = InferenceEngine(model, DeepSpeedInferenceConfig(
        dtype="bfloat16", quant=quant, compile_cache=_cc_block(),
        prefill_chunk_size=chunk_cfg))
    eng.init_params()
    plan_mode, plan_chunk, plan_why = eng.prefill_plan(batch_size, prompt)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch_size, prompt)).astype(np.int32)

    def timed(n_new):
        out = eng.generate(ids, max_new_tokens=n_new)   # compile + warm
        _sync_scalar(out[:, -1])
        t0 = time.perf_counter()
        out = eng.generate(ids, max_new_tokens=n_new)
        _sync_scalar(out[:, -1])
        return time.perf_counter() - t0

    # two run lengths isolate the pure-decode rate from the shared prefill
    dt_full, dt_half = timed(gen), timed(gen // 2)
    if dt_full <= dt_half:
        # timing inversion (a scheduling hiccup on the tunneled device) —
        # re-measure once before declaring the run invalid
        dt_full, dt_half = timed(gen), timed(gen // 2)
    error = None
    if dt_full > dt_half:
        decode_rate = round(batch_size * (gen - gen // 2)
                            / (dt_full - dt_half) / jax.device_count(), 1)
        # estimated HBM traffic per decode step: all params once + the live
        # KV blocks (the kernel skips blocks past the cache's live region)
        param_bytes = sum(int(np.prod(l.shape)) * l.dtype.itemsize
                          for l in jax.tree.leaves(eng.params))
        bk = min(DEFAULT_BLOCK_K_DECODE, prompt + gen)
        steps = np.arange(gen // 2, gen)        # the measured decode steps
        live_blocks = np.ceil((prompt + steps + 1) / bk)
        # bytes per cached position: bf16 payload, or int8 + f32 scale/head
        kv_row = cfg.kv_heads * cfg.head_dim * (1 if kv_int8 else 2) \
            + (cfg.kv_heads * 4 if kv_int8 else 0)
        cache_bytes = 2 * cfg.num_layers * batch_size * kv_row * bk \
            * float(np.mean(live_blocks))
        step_t = (dt_full - dt_half) / (gen - gen // 2)
        # per-chip traffic: params are replicated at tp=1, so EVERY chip
        # streams the full param_bytes per step; only the batch's KV cache
        # spreads across chips (dp-sharded)
        traffic = param_bytes + cache_bytes / jax.device_count()
        hbm_util = traffic / step_t / (device_peak_hbm_gbps() * 1e9)
        _, meas_gbps = _measured_peaks()
        hbm_util_meas = traffic / step_t / (meas_gbps * 1e9) \
            if meas_gbps else None
        # roofline attribution (docs/observability.md "Device memory &
        # roofline"): per-chip decode-step flops ~ 2 x params x the
        # chip's batch shard (matmul-dominated), bytes = the same
        # traffic estimate hbm_utilization uses — the classification
        # says WHY a cliff happened (a decode step left of the ridge is
        # bandwidth-ceilinged: HBM traffic regressions cut throughput
        # linearly no matter how idle the MXU is)
        from deepspeed_tpu.profiling.roofline import (device_peaks,
                                                      roofline_block)
        param_count = sum(int(np.prod(l.shape))
                          for l in jax.tree.leaves(eng.params))
        flops_step = 2.0 * param_count * batch_size / jax.device_count()
        peak_t, peak_g, peak_src = device_peaks(*_measured_peaks())
        roofline = roofline_block(flops_step, traffic, step_t,
                                  peak_t, peak_g, peak_src)
    else:
        decode_rate, hbm_util, hbm_util_meas, roofline = (None,) * 4
        error = (f"timing inversion persisted across re-measure "
                 f"(gen={gen}: {dt_full:.3f}s <= gen={gen // 2}: "
                 f"{dt_half:.3f}s) — decode rate not measurable")
    result = {
        "model": model_name,
        "weights": "int8-per-channel" if int8 else "bf16",
        "kv_cache": "int8" if kv_int8 else "bf16",
        "decode_tokens_per_sec_chip": decode_rate,
        "e2e_tokens_per_sec_chip": round(batch_size * gen / dt_full
                                         / jax.device_count(), 1),
        "hbm_utilization": round(hbm_util, 3) if hbm_util else None,
        "batch_size": batch_size,
        "prompt_len": prompt,
        "gen_len": gen,
        "e2e_time_s": round(dt_full, 3),
        # which prefill pipeline generate() took and why — the condition
        # behind the old 4k "fallback": true is visible in every record
        "prefill_plan": {"mode": plan_mode, "chunk": plan_chunk,
                         "reason": plan_why},
    }
    if hbm_util_meas:
        result["hbm_utilization_vs_measured"] = round(hbm_util_meas, 3)
    if roofline:
        result["roofline"] = roofline
    if error:
        result["error"] = error
    return result


def serving_bench(model_name="opt-1.3b", *, num_slots=8, n_requests=24,
                  decode_block=8, prefill_chunk=128,
                  prefill_token_budget=256):
    """Continuous-batching serving (``inference/serving/``,
    ``docs/serving.md``) on a MIXED-LENGTH workload — varied prompt and
    completion lengths, more requests than slots — against the sequential
    bucketed ``generate()`` baseline a naive server runs: requests grouped
    into arrival-order batches of ``num_slots``, prompts right-padded to
    the batch max, every row decoding to the batch's max completion
    length.  Continuous batching recovers exactly that padding +
    lockstep waste: slots retire on completion and the queue backfills
    them mid-decode through ONE reusable decode-step program.

    ``speedup_vs_sequential`` is aggregate useful tokens/s over the same
    requests — the headline serving metric."""
    import jax
    from deepspeed_tpu.models.opt import opt_config
    from deepspeed_tpu.models.transformer import Transformer
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig

    cache_len = 384                         # prompts <= 256, new <= 128
    cfg = opt_config(model_name, max_seq_len=cache_len, dtype="bfloat16",
                     scan_layers=False)
    model = Transformer(cfg)
    eng = InferenceEngine(model, DeepSpeedInferenceConfig(
        dtype="bfloat16", compile_cache=_cc_block(),
        serving={"enabled": True, "num_slots": num_slots,
                 "max_cache_len": cache_len,
                 "prefill_chunk": prefill_chunk,
                 "prefill_token_budget": prefill_token_budget,
                 "decode_block": decode_block}))
    eng.init_params()
    rng = np.random.default_rng(0)
    prompt_lens = rng.choice([64, 96, 128, 192, 256], n_requests)
    new_lens = rng.choice([16, 32, 64, 128], n_requests)
    prompts = [rng.integers(0, cfg.vocab_size, (int(p),)).astype(np.int32)
               for p in prompt_lens]
    useful_tokens = int(np.sum(new_lens))

    def run_sequential():
        t0 = time.perf_counter()
        for i in range(0, n_requests, num_slots):
            bp = prompts[i:i + num_slots]
            bn = new_lens[i:i + num_slots]
            P = max(len(p) for p in bp)
            ids = np.zeros((len(bp), P), np.int32)
            mask = np.zeros((len(bp), P), np.int32)
            for j, p in enumerate(bp):
                ids[j, :len(p)] = p
                mask[j, :len(p)] = 1
            out = eng.generate(ids, max_new_tokens=int(max(bn)),
                               attention_mask=mask)
            _sync_scalar(out[:, -1])
        return time.perf_counter() - t0

    srv = eng.serve()
    srv.warmup()

    def run_serving():
        t0 = time.perf_counter()
        for p, n in zip(prompts, new_lens):
            srv.submit(p, max_new_tokens=int(n))
        srv.drain()
        return time.perf_counter() - t0

    run_sequential()                        # compile + warm both paths
    run_serving()
    t_seq = run_sequential()
    occ0 = len(srv.occupancy_trace)
    t_srv = run_serving()
    occ = [o for _, o in srv.occupancy_trace[occ0:]]
    return {
        "model": model_name,
        "num_slots": num_slots,
        "n_requests": n_requests,
        "decode_block": decode_block,
        "prefill_chunk": prefill_chunk,
        "prefill_token_budget": prefill_token_budget,
        "prompt_lens": sorted(int(p) for p in prompt_lens),
        "new_lens": sorted(int(n) for n in new_lens),
        "serving_tokens_per_sec": round(useful_tokens / t_srv, 1),
        "sequential_tokens_per_sec": round(useful_tokens / t_seq, 1),
        "speedup_vs_sequential": round(t_seq / t_srv, 3),
        "serving_time_s": round(t_srv, 3),
        "sequential_time_s": round(t_seq, 3),
        "mean_slot_occupancy": round(float(np.mean(occ)) / num_slots, 3)
        if occ else None,
        "decode_calls": srv.stats["decode_calls"],
        "decode_tokens": srv.stats["decode_tokens"],
        "prefill_tokens": srv.stats["prefill_tokens"],
        "platform": jax.devices()[0].platform,
    }


def serving_overload_bench(model_name="opt-1.3b", *, num_slots=8,
                           burst_factor=4, decode_block=8,
                           prefill_chunk=128):
    """Serving SLO micro-phase (``docs/serving.md`` "Robustness & SLOs"):
    a burst of ``burst_factor``x slot capacity submits with mixed
    deadlines — a quarter of the burst arrives already expired and must
    SHED before occupying a slot — then a graceful preemption mid-burst
    (drain in-flight slots, crash-atomic snapshot) and a second server
    resuming the snapshot to finish the backlog.  Records the shed rate,
    p50/p99 time-to-first-token of the completed requests, the
    preemption drain+snapshot latency, and the per-server decode-
    executable count (the one-decode-executable invariant under
    overload + drain + resume)."""
    import shutil
    import tempfile
    import jax
    from deepspeed_tpu.models.opt import opt_config
    from deepspeed_tpu.models.transformer import Transformer
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig

    cache_len = 384                         # prompts <= 256, new <= 128
    n_requests = num_slots * burst_factor
    cfg = opt_config(model_name, max_seq_len=cache_len, dtype="bfloat16",
                     scan_layers=False)
    model = Transformer(cfg)
    eng = InferenceEngine(model, DeepSpeedInferenceConfig(
        dtype="bfloat16", compile_cache=_cc_block(),
        serving={"enabled": True, "num_slots": num_slots,
                 "max_cache_len": cache_len,
                 "prefill_chunk": prefill_chunk,
                 "prefill_token_budget": 256,
                 "decode_block": decode_block,
                 "drain_budget_s": 60.0}))
    eng.init_params()
    rng = np.random.default_rng(0)
    prompt_lens = rng.choice([64, 96, 128, 192, 256], n_requests)
    new_lens = rng.choice([16, 32, 64, 128], n_requests)
    prompts = [rng.integers(0, cfg.vocab_size, (int(p),)).astype(np.int32)
               for p in prompt_lens]
    # mixed deadlines: every 4th request arrives already expired — the
    # deterministic shed-rate floor; the rest are deadline-free
    deadlines = [0.0 if i % 4 == 3 else None for i in range(n_requests)]

    srv = eng.serve()
    srv.warmup()
    t0 = time.perf_counter()
    rids = [srv.submit(p, max_new_tokens=int(n), deadline_s=dl)
            for p, n, dl in zip(prompts, new_lens, deadlines)]
    live = [r for r, dl in zip(rids, deadlines) if dl is None]
    done = {}
    # run the burst until half the live requests completed, then preempt
    # mid-flight (in-flight slots drain under the budget, the queued
    # backlog snapshots)
    it = 0
    while sum(1 for r in live if r in done) < len(live) // 2:
        done.update(srv.step())
        it += 1
        if it > 100000:                     # parent timeout is the real
            break                           # guard; this bounds the loop
    snap_dir = tempfile.mkdtemp(prefix="bench_serving_snap_")
    try:
        t_pre = time.perf_counter()
        tag, snapped, fin = srv.preempt(snap_dir)
        drain_latency = time.perf_counter() - t_pre
        done.update(fin)
        srv2 = eng.serve()
        restored = srv2.restore(snap_dir)
        done.update(srv2.drain())
        t_total = time.perf_counter() - t0
    finally:
        shutil.rmtree(snap_dir, ignore_errors=True)
    results = {**srv._results, **srv2._results}
    ttfts = sorted(r.ttft_s for r in results.values()
                   if r.status == "COMPLETED" and r.ttft_s is not None)
    shed = srv.stats["shed"] + srv2.stats["shed"]
    completed = srv.stats["completed"] + srv2.stats["completed"]
    useful = sum(int(n) for r, n in zip(rids, new_lens)
                 if results[r].status == "COMPLETED")
    return {
        "model": model_name,
        "num_slots": num_slots,
        "burst_requests": n_requests,
        "burst_factor": burst_factor,
        "shed": shed,
        "shed_rate": round(shed / n_requests, 3),
        "completed": completed,
        "ttft_p50_s": round(float(np.percentile(ttfts, 50)), 3)
        if ttfts else None,
        "ttft_p99_s": round(float(np.percentile(ttfts, 99)), 3)
        if ttfts else None,
        "drain_snapshot_latency_s": round(drain_latency, 3),
        "snapshotted_requests": len(snapped),
        "resumed_requests": len(restored),
        "useful_tokens_per_sec": round(useful / t_total, 1),
        "total_time_s": round(t_total, 3),
        # the one-decode-executable invariant under overload+drain+resume
        "decode_executables_per_server": [
            sum(1 for sig in eng._aot if sig and sig[0] == id(s._decode_fn))
            for s in (srv, srv2)],
        "platform": jax.devices()[0].platform,
    }


def serving_http_bench(model_name="opt-1.3b", *, num_slots=8,
                       n_requests=24, decode_block=8, prefill_chunk=128):
    """Network front end micro-phase (``docs/serving.md`` "Network front
    end"): the SAME mixed workload served twice — direct ``submit()`` /
    ``drain()`` vs concurrent HTTP clients (2 tenants x 2 priorities,
    half streaming, half blocking) — recording the transport overhead:
    req/s and p50/p99 TTFT for both paths, p50/p99 time-between-tokens
    on the streamed responses, and the decode-executable count proving
    the HTTP path minted nothing new."""
    import http.client
    import json
    import threading
    import jax
    from deepspeed_tpu.models.opt import opt_config
    from deepspeed_tpu.models.transformer import Transformer
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.serving.frontend import \
        ServingHTTPFrontend

    cache_len = 384                         # prompts <= 256, new <= 64
    cfg = opt_config(model_name, max_seq_len=cache_len, dtype="bfloat16",
                     scan_layers=False)
    model = Transformer(cfg)
    eng = InferenceEngine(model, DeepSpeedInferenceConfig(
        dtype="bfloat16", compile_cache=_cc_block(),
        serving={"enabled": True, "num_slots": num_slots,
                 "max_cache_len": cache_len,
                 "prefill_chunk": prefill_chunk,
                 "prefill_token_budget": 256,
                 "decode_block": decode_block,
                 "priority_lanes": 2}))
    eng.init_params()
    rng = np.random.default_rng(0)
    prompt_lens = rng.choice([64, 96, 128, 192, 256], n_requests)
    new_lens = rng.choice([16, 32, 64], n_requests)
    prompts = [rng.integers(0, cfg.vocab_size, (int(p),)).astype(np.int32)
               for p in prompt_lens]

    def pct(xs, q):
        return round(float(np.percentile(xs, q)), 4) if len(xs) else None

    # ---- direct path: submit() + drain() on the scheduler thread ----
    srv = eng.serve()
    srv.warmup()
    t0 = time.perf_counter()
    rids = [srv.submit(p, max_new_tokens=int(n),
                       client_id=f"tenant-{i % 2}", priority=(i // 2) % 2)
            for i, (p, n) in enumerate(zip(prompts, new_lens))]
    srv.drain()
    t_direct = time.perf_counter() - t0
    direct_ttfts = sorted(srv._results[r].ttft_s for r in rids
                          if srv._results[r].ttft_s is not None)
    # record the decode-executable count, then retire the direct-path
    # server BEFORE the HTTP server exists — two live servers would
    # double the phase's KV-workspace footprint for nothing
    decode_execs = [
        sum(1 for sig in eng._aot if sig and sig[0] == id(srv._decode_fn))]
    srv.close()

    # ---- HTTP path: same workload through concurrent clients ----
    # wire TTFT (streaming clients: submit -> first token ON THE WIRE,
    # includes transport + queueing) and engine TTFT (blocking clients:
    # the engine's internal admission->first-token clock) are DIFFERENT
    # quantities — recorded separately, never mixed in one percentile
    srv2 = eng.serve()
    wire_ttfts, engine_ttfts, tbt_gaps, errors = [], [], [], []

    def client(k, port):
        try:
            stream = bool(k % 2)
            t_sub = time.perf_counter()
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=600)
            conn.request("POST", "/v1/generate", json.dumps(
                {"input_ids": [int(t) for t in prompts[k]],
                 "max_new_tokens": int(new_lens[k]),
                 "client_id": f"tenant-{k % 2}",
                 "priority": (k // 2) % 2, "stream": stream}))
            resp = conn.getresponse()
            if resp.status != 200:
                raise RuntimeError(f"HTTP {resp.status}: {resp.read()!r}")
            if stream:
                arrivals = []
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    ev = json.loads(line)
                    if ev["event"] == "token":
                        arrivals.append(time.perf_counter())
                    else:
                        break
                if arrivals:
                    wire_ttfts.append(arrivals[0] - t_sub)
                    tbt_gaps.extend(np.diff(arrivals).tolist())
            else:
                body = json.loads(resp.read())
                if body.get("ttft_s") is not None:
                    engine_ttfts.append(body["ttft_s"])
            conn.close()
        except Exception as e:              # recorded, fails the phase
            errors.append(f"client {k}: {type(e).__name__}: {e}")

    t1 = time.perf_counter()
    with ServingHTTPFrontend(srv2) as fe:
        threads = [threading.Thread(target=client, args=(k, fe.port))
                   for k in range(n_requests)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    t_http = time.perf_counter() - t1
    decode_execs.append(
        sum(1 for sig in eng._aot if sig and sig[0] == id(srv2._decode_fn)))
    # engine-lock contention under concurrent HTTP handlers: per-acquire
    # wait percentiles from the InstrumentedRLock sample window — the
    # baseline future threading changes regress against (the PR 7
    # threshold machinery classifies *_s as lower-is-better)
    lock_waits = {cls: sorted(srv2._lock.samples[cls])
                  for cls in ("scheduler", "handler")}
    lock_wait_total = dict(srv2._lock.wait_s)
    srv2.close()
    if errors:
        raise RuntimeError("serving_http bench clients failed: "
                           + "; ".join(errors[:5]))
    wire_ttfts.sort()
    engine_ttfts.sort()
    return {
        "model": model_name,
        "num_slots": num_slots,
        "n_requests": n_requests,
        "tenants": 2,
        "priorities": 2,
        "direct_reqs_per_sec": round(n_requests / t_direct, 2),
        "direct_ttft_p50_s": pct(direct_ttfts, 50),
        "direct_ttft_p99_s": pct(direct_ttfts, 99),
        "http_reqs_per_sec": round(n_requests / t_http, 2),
        # engine TTFT is directly comparable to direct_ttft_* (same
        # clock); wire TTFT additionally includes the transport
        "http_engine_ttft_p50_s": pct(engine_ttfts, 50),
        "http_engine_ttft_p99_s": pct(engine_ttfts, 99),
        "http_wire_ttft_p50_s": pct(wire_ttfts, 50),
        "http_wire_ttft_p99_s": pct(wire_ttfts, 99),
        "http_time_between_tokens_p50_s": pct(tbt_gaps, 50),
        "http_time_between_tokens_p99_s": pct(tbt_gaps, 99),
        "lock_wait_scheduler_p50_s": pct(lock_waits["scheduler"], 50),
        "lock_wait_scheduler_p99_s": pct(lock_waits["scheduler"], 99),
        "lock_wait_handler_p50_s": pct(lock_waits["handler"], 50),
        "lock_wait_handler_p99_s": pct(lock_waits["handler"], 99),
        "lock_wait_scheduler_total_s": round(
            lock_wait_total["scheduler"], 4),
        "lock_wait_handler_total_s": round(
            lock_wait_total["handler"], 4),
        # < 1.0 = the transport costs throughput; the decode_block
        # flush cadence bounds per-token latency, not aggregate rate
        "http_vs_direct_reqs_ratio": round(
            (n_requests / t_http) / (n_requests / t_direct), 3),
        # the one-decode-executable invariant through the HTTP path
        "decode_executables_per_server": decode_execs,
        "platform": jax.devices()[0].platform,
    }


def serving_paged_bench(model_name="opt-1.3b", *, slots_list=(96, 128, 192),
                        page_size=64, pool_fraction=0.75, decode_block=8,
                        prefill_chunk=128, prefix_requests=24,
                        prefix_len=512):
    """Paged-KV serving (``inference/serving/paging.py``, ``docs/serving.md``
    "Paged KV cache") at the throughput serving points where the
    monolithic per-slot lanes collapsed (r04: int8-KV decode fell 8,673 →
    1,193 tok/s/chip between bs96 and bs128 as ``num_slots × cache_len``
    HBM crossed the chip).  Per concurrency level: ``num_slots`` paged
    int8-KV slots over a pool sized at ``pool_fraction`` of worst case
    (pages back ACTUAL request lengths; pressure degrades into admission
    stalls, never an allocation cliff), recording useful tok/s/chip,
    page-pool utilization, and admission stalls.  Plus a shared-prefix
    workload: ``prefix_requests`` prompts behind one ``prefix_len``-token
    system prompt — the prefix prefills ONCE (copy-on-write page sharing),
    every later admission hits the prefix index."""
    import jax
    from deepspeed_tpu.models.opt import opt_config
    from deepspeed_tpu.models.transformer import Transformer
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig

    cache_len = 384                         # prompts <= 256, new <= 128
    cfg = opt_config(model_name, max_seq_len=max(cache_len, prefix_len + 256),
                     dtype="bfloat16", scan_layers=False, kv_cache_quant=True)
    model = Transformer(cfg)
    quant = {"enabled": True, "bits": 8, "per_channel": True}
    eng = InferenceEngine(model, DeepSpeedInferenceConfig(
        dtype="bfloat16", quant=quant, compile_cache=_cc_block(),
        serving={"enabled": True, "paged": True, "page_size": page_size,
                 "max_cache_len": cache_len, "prefill_chunk": prefill_chunk,
                 "prefill_token_budget": 256, "decode_block": decode_block}))
    eng.init_params()
    rng = np.random.default_rng(0)
    n_dev = jax.device_count()
    # roofline numerators (constant across concurrency levels): int8
    # weights stream once per decode step; KV bytes come from the live
    # page-pool occupancy sampled at the decode window (the paged kernel
    # pins dead-tail page indices to the last live page, so repeated-index
    # DMAs are elided and only live pages cost traffic)
    param_bytes = sum(int(np.prod(l.shape)) * l.dtype.itemsize
                      for l in jax.tree.leaves(eng.params))
    param_count = sum(int(np.prod(l.shape))
                      for l in jax.tree.leaves(eng.params))
    # bytes per cached position, k + v: int8 payload + f32 per-head scale
    kv_row = 2 * (cfg.kv_heads * cfg.head_dim + cfg.kv_heads * 4)
    plan_mode, plan_chunk, plan_why = eng.prefill_plan(
        max(slots_list), 256, paged=True)
    per_bs = {}
    for bs in slots_list:
        n_requests = 2 * bs                 # slots churn at least once
        prompt_lens = rng.choice([64, 96, 128, 192, 256], n_requests)
        new_lens = rng.choice([16, 32, 64, 128], n_requests)
        prompts = [rng.integers(0, cfg.vocab_size, (int(p),))
                   .astype(np.int32) for p in prompt_lens]
        worst = bs * (-(-cache_len // page_size))
        num_pages = max(2, int(pool_fraction * worst)) + 1
        srv = eng.serve(num_slots=bs, num_pages=num_pages)
        srv.warmup()
        srv_modes = srv.kernel_modes
        util_peak = 0.0

        def run(srv):
            nonlocal util_peak
            t0 = time.perf_counter()
            for p, n in zip(prompts, new_lens):
                srv.submit(p, max_new_tokens=int(n))
            while srv.queue_depth or srv.in_flight or srv.active_slots:
                srv.step()
                util_peak = max(util_peak, srv.page_pool_utilization)
            return time.perf_counter() - t0

        run(srv)                            # compile + warm
        stalls0 = srv.stats["admission_stalls"]
        fb0 = srv.stats["paged_attention_fallback"]
        util_peak = 0.0
        dt = run(srv)
        useful = int(np.sum(new_lens))
        # decode-only roofline window (docs/observability.md "Device
        # memory & roofline"): park one short request per slot in steady
        # decode, then time pure decode dispatches — no admissions or
        # prefill chunks interleaved — so the step time attributes the
        # paged decode kernel itself, not the mixed scheduler loop
        for _ in range(bs):
            srv.submit(rng.integers(0, cfg.vocab_size, (64,))
                       .astype(np.int32), max_new_tokens=160)
        pf = -1
        while srv.queue_depth or srv.stats["prefill_tokens"] != pf:
            pf = srv.stats["prefill_tokens"]
            srv.step()
        live_pos = srv.page_pool_utilization * (num_pages - 1) * page_size
        n0, t0 = srv.stats["decode_calls"], time.perf_counter()
        while srv.stats["decode_calls"] - n0 < 8:
            srv.step()
        dt_win = time.perf_counter() - t0
        steps_win = (srv.stats["decode_calls"] - n0) * decode_block
        step_t = dt_win / max(steps_win, 1)
        from deepspeed_tpu.profiling.roofline import (device_peaks,
                                                      roofline_block)
        # per-chip traffic per decode step: replicated int8 params once,
        # live KV pages dp-sharded across chips
        traffic = param_bytes + cfg.num_layers * live_pos * kv_row / n_dev
        flops_step = 2.0 * param_count * bs / n_dev
        peak_t, peak_g, peak_src = device_peaks(*_measured_peaks())
        per_bs[str(bs)] = {
            "num_slots": bs,
            "n_requests": n_requests,
            "num_pages": num_pages,
            "pool_fraction_of_worst_case": pool_fraction,
            "tokens_per_sec_chip": round(useful / dt / n_dev, 1),
            "page_pool_util_peak": round(util_peak, 3),
            "admission_stalls": srv.stats["admission_stalls"] - stalls0,
            "paged_attention_fallback":
                srv.stats["paged_attention_fallback"] - fb0,
            "decode_step_ms": round(step_t * 1e3, 3),
            "roofline": roofline_block(flops_step, traffic, step_t,
                                       peak_t, peak_g, peak_src),
            "time_s": round(dt, 3),
        }
        srv.drain()
        srv.close()

    # shared-prefix workload: one system prompt, divergent user tails —
    # the prefix prefills exactly once; hit rate counts the rest
    pre = rng.integers(0, cfg.vocab_size, (prefix_len,)).astype(np.int32)
    tails = [rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
             for _ in range(prefix_requests)]
    # lanes must hold the CHUNK-PADDED prompt (submit's capacity check):
    # ceil(528 / 128) * 128 = 640 positions
    pc_len = prefix_len + 2 * prefill_chunk
    srv = eng.serve(num_slots=8, max_cache_len=pc_len)
    t0 = time.perf_counter()
    for t in tails:
        srv.submit(np.concatenate([pre, t]), max_new_tokens=32)
    srv.drain()
    dt_prefix = time.perf_counter() - t0
    prefix = {
        "requests": prefix_requests,
        "prefix_len": prefix_len,
        "prefix_hits": srv.stats["prefix_hits"],
        "prefix_hit_rate": round(srv.prefix_hit_rate, 3),
        "prefix_tokens_reused": srv.stats["prefix_tokens_reused"],
        "prefill_tokens": srv.stats["prefill_tokens"],
        # what the same workload costs with no sharing: every request
        # prefills its full chunk-padded prompt
        "prefill_tokens_without_sharing":
            prefix_requests * (-(-(prefix_len + 16) // prefill_chunk))
            * prefill_chunk,
        "time_s": round(dt_prefix, 3),
    }
    srv.close()
    r128 = per_bs.get("128", {})
    return {
        "model": model_name,
        "weights": "int8-per-channel",
        "kv_cache": "int8",
        "page_size": page_size,
        "decode_block": decode_block,
        # which attention-registry kernels the serving programs dispatch
        # through (ops/transformer/registry.py) — pallas_paged_decode /
        # pallas_chunked_prefill on kernel-capable backends,
        # reference_fallback otherwise (then per_bs
        # paged_attention_fallback counts every slow-path decode)
        "kernel_modes": dict(srv_modes),
        "prefill_plan": {"mode": plan_mode, "chunk": plan_chunk,
                         "reason": plan_why},
        "per_bs": per_bs,
        "prefix_sharing": prefix,
        # the acceptance anchor: r04's bs128 monolithic int8-KV decode
        # collapsed to 1,193 tok/s/chip (HBM util 0.58 -> 0.075)
        "vs_r04_bs128_decode": round(
            r128["tokens_per_sec_chip"] / 1193.0, 2)
        if r128.get("tokens_per_sec_chip") else None,
        "platform": jax.devices()[0].platform,
    }


def serving_spec_bench(model_name="opt-1.3b", *, slots_list=(4, 8, 16),
                       k_list=(2, 4, 8), decode_block=8,
                       prefill_chunk=128):
    """Speculative multi-token serving (``docs/serving.md`` "Speculative
    decoding") at the latency-sensitive bs<=16 points where BENCH_r02/r04
    show decode stuck near ~1.2k tok/s/chip: per (num_slots, spec_k)
    point, a SELF-draft speculative server (the target model drafts for
    itself — accept rate ~1.0 under greedy, so the measurement isolates
    the dispatch-amortization/batched-verify ceiling; a trained small
    draft trades accept rate against draft cost) against the
    non-speculative serving baseline at the same concurrency.  Records
    the accept rate, committed tokens per dispatch, decode tok/s/chip
    and speedup vs non-spec, time-between-tokens p50/p99 from the
    per-token event streams, and the executables-per-server proof
    (exactly one draft-propose + one verify-and-commit signature)."""
    import jax
    from deepspeed_tpu.models.opt import opt_config
    from deepspeed_tpu.models.transformer import Transformer
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig

    cache_len = 384                         # prompts <= 256, new <= 128
    cfg = opt_config(model_name, max_seq_len=cache_len, dtype="bfloat16",
                     scan_layers=False)
    model = Transformer(cfg)
    eng = InferenceEngine(model, DeepSpeedInferenceConfig(
        dtype="bfloat16", compile_cache=_cc_block(),
        serving={"enabled": True, "max_cache_len": cache_len,
                 "prefill_chunk": prefill_chunk,
                 "prefill_token_budget": 256,
                 "decode_block": decode_block}))
    eng.init_params()
    rng = np.random.default_rng(0)
    n_dev = jax.device_count()
    max_k = max(k_list)

    def workload(bs):
        n_requests = max(2 * bs, 12)        # slots churn at least once
        prompt_lens = rng.choice([64, 96, 128, 192], n_requests)
        new_lens = rng.choice([64, 96, 128], n_requests)
        prompts = [rng.integers(0, cfg.vocab_size, (int(p),))
                   .astype(np.int32)
                   # leave room for the spec window reserve at every k
                   if p + 128 + max_k - 1 <= cache_len else
                   rng.integers(0, cfg.vocab_size, (64,)).astype(np.int32)
                   for p in prompt_lens]
        return prompts, [int(n) for n in new_lens]

    def run(srv, prompts, new_lens):
        """Drain the workload; returns (dt, tbt_ms list) — time between
        consecutive token events per request, wall clock at the
        host-mirror drain point (the stream's tick)."""
        stamps = {}

        def on_event_for(rid):
            def on_event(ev, _rid=rid):
                if ev.get("event") == "token":
                    stamps.setdefault(_rid, []).append(time.perf_counter())
            return on_event

        t0 = time.perf_counter()
        rids = [srv.submit(p, max_new_tokens=n)
                for p, n in zip(prompts, new_lens)]
        for rid in rids:
            srv.token_events(rid, on_event=on_event_for(rid))
        srv.drain()
        dt = time.perf_counter() - t0
        tbt = []
        for ts in stamps.values():
            tbt.extend((b - a) * 1e3 for a, b in zip(ts, ts[1:]))
        return dt, tbt

    def pct(xs, q):
        return round(float(np.percentile(xs, q)), 2) if xs else None

    points, baselines = [], []
    for bs in slots_list:
        prompts, new_lens = workload(bs)
        useful = int(np.sum(new_lens))
        base = eng.serve(num_slots=bs)
        base.warmup()
        run(base, prompts, new_lens)        # compile + warm
        dt_base, tbt_base = run(base, prompts, new_lens)
        base.close()
        base_tps = useful / dt_base / n_dev
        baselines.append({
            "num_slots": bs, "n_requests": len(prompts),
            "tokens_per_sec_chip": round(base_tps, 1),
            "time_between_tokens_p50_ms": pct(tbt_base, 50),
            "time_between_tokens_p99_ms": pct(tbt_base, 99),
            "time_s": round(dt_base, 3),
        })
        for k in k_list:
            srv = eng.serve(num_slots=bs, speculative=True, spec_k=k,
                            spec_draft_model="self")
            srv.warmup()
            run(srv, prompts, new_lens)     # compile + warm
            dt, tbt = run(srv, prompts, new_lens)
            tps = useful / dt / n_dev
            points.append({
                "num_slots": bs, "spec_k": k,
                "accept_rate": round(srv.stats["spec_accept_rate"], 3),
                "tokens_per_dispatch":
                    round(srv.stats["spec_tokens_per_dispatch"], 2),
                "draft_time_fraction":
                    round(srv.stats["spec_draft_fraction"], 3),
                "tokens_per_sec_chip": round(tps, 1),
                "speedup_vs_nonspec": round(tps / base_tps, 3),
                "time_between_tokens_p50_ms": pct(tbt, 50),
                "time_between_tokens_p99_ms": pct(tbt, 99),
                "time_s": round(dt, 3),
                # the one-executable-per-program proof, per server
                "propose_executables": sum(
                    1 for sig in eng._aot
                    if sig and sig[0] == id(srv._propose_fn)),
                "verify_executables": sum(
                    1 for sig in eng._aot
                    if sig and sig[0] == id(srv._verify_fn)),
            })
            srv.close()
    best = max(points, key=lambda p: p.get("speedup_vs_nonspec") or 0.0) \
        if points else None
    return {
        "model": model_name,
        "draft": "self (accept-rate ceiling; trained small drafts trade "
                 "accept rate against draft cost)",
        "decode_block_baseline": decode_block,
        "points": points,
        "baselines": baselines,
        "best_speedup_vs_nonspec":
            best["speedup_vs_nonspec"] if best else None,
        "best_point": {"num_slots": best["num_slots"],
                       "spec_k": best["spec_k"]} if best else None,
        "platform": jax.devices()[0].platform,
    }


def long_context_bench(model_name="opt-1.3b", *, seq=8192, micro_bs=1,
                       steps=4):
    """Long-context SFT through the Pallas flash-attention path (the
    reference's long-sequence story rides its sparse/flash attention kernels,
    ``csrc/sparse_attention`` + ``ops/sparse_attention/``, SURVEY §5) — at
    the flagship OPT-1.3B scale.  ``flash_only_saveable`` remat keeps only
    the O(S) attention residuals (r3 sweep: 29.7% MFU vs 25.9% full
    recompute; dots-saveable OOMs at this length).  Reports tokens/s and an
    attention-aware MFU: at seq 8k the causal attention FLOPs (~6·L·S·H per
    token) rival the 6·N·tokens parameter FLOPs that the standard MFU
    formula counts."""
    from deepspeed_tpu.models.opt import opt_config
    from deepspeed_tpu.profiling.flops_profiler.profiler import \
        device_peak_tflops
    r = train_bench(model_name, micro_bs=micro_bs, zero_stage=3, steps=steps,
                    seq=seq, lean=True, remat=True,
                    remat_policy="flash_only_saveable", loss_chunks=32)
    cfg = opt_config(model_name, max_seq_len=seq)
    attn_flops_per_tok = 6.0 * cfg.num_layers * seq * cfg.hidden_size
    total_per_tok = 6.0 * cfg.num_params() + attn_flops_per_tok
    peak = device_peak_tflops() * 1e12
    r["mfu_attn_aware"] = round(
        r["tokens_per_sec_chip"] * total_per_tok / peak, 4)
    return r


def hybrid_bench(model_name="opt-1.3b", *, train_bs=2, rollout_bs=(8, 32, 64),
                 prompt=256, gen=128, seq=2048, cycles=2, train_steps=4,
                 remat=True, quantize_rollouts=True):
    """DS-Chat step-3 RLHF loop at OPT-1.3B scale through the Hybrid Engine
    (reference ``runtime/hybrid_engine.py:32``; headline rows in
    ``blogs/deepspeed-chat/README.md:38,52``): N ZeRO-3 train steps → rollout
    ``generate`` through the shared-weight inference view → training resumes
    on the same engine.  Reports rollout throughput, train step time before
    and after a rollout (the engine-flip cost the reference's blog headlines)
    and TWO weight checks:

    - full-pytree identity between the masters and the inference view
      (every leaf; the view must BE the cast masters — the Hybrid Engine's
      whole premise, reference ``runtime/hybrid_engine.py:84-130``);
    - the int8 quantized-rollout path's round-trip error on the LARGEST
      matmul weight (the per-channel quantizer used by
      ``hybrid_engine.quantize_rollouts``).
    """
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.opt import opt_config
    from deepspeed_tpu.models.transformer import Transformer

    # remat ON by default here: the int8 rollout view + its KV cache are
    # resident during training's activation peak at the larger rollout
    # batches (the no-remat + int8-view combination OOMs at 1.3B —
    # r3 probe); the fallback drops to the bf16 view at bs8
    cfg = opt_config(model_name, max_seq_len=seq, dtype="bfloat16",
                     remat=remat, scan_layers=False, loss_seq_chunks=8,
                     kv_cache_quant=quantize_rollouts)
    model = Transformer(cfg)
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": train_bs,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 9.65e-6, "weight_decay": 0.0,
                                     "state_dtype": "bfloat16"}},
            "bf16": {"enabled": True, "master_weights_in_bf16": True},
            "zero_optimization": {"stage": 3},
            "gradient_clipping": 1.0,
            # int8-at-rest rollout view + int8 KV cache: rollouts are the
            # Hybrid Engine's whole point (reference blog: "up to 9x vs
            # HF") and decode is HBM-bound — serve them like the
            # inference engine serves (reference runtime/hybrid_engine.py
            # :178 generate; quantized view is this framework's extension)
            "hybrid_engine": {"enabled": True,
                              "quantize_rollouts": bool(quantize_rollouts)},
            "compile_cache": _cc_block(),
        })
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size,
        (1, train_bs * engine.topology.dp, seq)).astype(np.int32)}
    if isinstance(rollout_bs, int):
        rollout_bs = (rollout_bs,)
    prompt_sets = {bs: rng.integers(0, cfg.vocab_size,
                                    (bs, prompt)).astype(np.int32)
                   for bs in rollout_bs}

    # warm both compiled programs (train step + rollout decode)
    _sync_scalar(engine.train_batch(batch=batch))
    for bs in rollout_bs:
        out = engine.generate(prompt_sets[bs], max_new_tokens=gen)
        _sync_scalar(out[:, -1])

    def timed_train(n):
        t0 = time.perf_counter()
        for _ in range(n):
            loss = engine.train_batch(batch=batch)
        _sync_scalar(loss)
        return (time.perf_counter() - t0) / n

    train_before = timed_train(train_steps)
    rollout_times = {bs: [] for bs in rollout_bs}
    train_after = None
    for _ in range(cycles):
        for bs in rollout_bs:
            t0 = time.perf_counter()
            out = engine.generate(prompt_sets[bs], max_new_tokens=gen,
                                  do_sample=True, temperature=1.0, top_p=0.9)
            _sync_scalar(out[:, -1])
            rollout_times[bs].append(time.perf_counter() - t0)
        train_after = timed_train(train_steps)

    # weight identity over the FULL pytree, reduced on device to one
    # scalar: each view leaf must equal the master cast to the view dtype
    # (the view is exactly a cast/reshard — any wrong transform on any
    # tensor fails this).  Per-leaf equality avoids fp32 upcast
    # temporaries with HBM near-full.
    import jax.numpy as jnp

    def _tree_identical(masters, views):
        checks = [jnp.all(m.astype(v.dtype) == v)
                  for m, v in zip(jax.tree.leaves(masters),
                                  jax.tree.leaves(views))]
        return jnp.all(jnp.stack(checks))

    masters = engine._params
    # the identity contract is about the UNQUANTIZED shared-weight view
    # (the reference Hybrid Engine premise); flip quantization off for the
    # check, back on after
    if quantize_rollouts:
        engine.set_rollout_quantization(bits=0)
    views = engine._inference_view()
    n_leaves = len(jax.tree.leaves(masters))
    assert n_leaves == len(jax.tree.leaves(views))
    identical = bool(jax.device_get(
        jax.jit(_tree_identical)(masters, views)))
    if quantize_rollouts:
        engine.set_rollout_quantization(bits=8)

    # int8 rollout-view spot check: round-trip the LARGEST matmul weight
    # through the same per-channel quantizer quantize_rollouts uses
    from deepspeed_tpu.runtime.weight_quantizer import WeightQuantization
    leaves = [l for l in jax.tree.leaves(masters) if l.ndim >= 2]
    big = leaves[int(np.argmax([int(np.prod(l.shape)) for l in leaves]))]
    q = WeightQuantization(bits=8, per_channel=True)
    deq = q.dequantize_tree(q.quantize_tree({"w": big}),
                            jnp.bfloat16)["w"]
    scale = float(jax.device_get(jnp.max(jnp.abs(big)).astype(jnp.float32)))
    err = float(jax.device_get(
        jnp.max(jnp.abs(deq.astype(jnp.float32)
                        - big.astype(jnp.float32)))))
    # symmetric per-channel int8: error bound is one quant step of the
    # channel max; channel maxes <= global max, so global-max/127 bounds it
    int8_roundtrip_ok = err <= scale / 127.0 + 1e-6

    per_bs = {bs: min(ts) for bs, ts in rollout_times.items()}
    best_bs = max(per_bs, key=lambda bs: bs * gen / per_bs[bs])
    result = {
        "model": model_name,
        "zero_stage": 3,
        "train_step_s_before_rollout": round(train_before, 4),
        "train_step_s_after_rollout": round(train_after, 4),
        "rollout_quant": "int8+int8kv" if quantize_rollouts else "bf16",
        "rollout_tokens_per_sec_chip": round(
            best_bs * gen / per_bs[best_bs] / jax.device_count(), 1),
        "rollout_bs": best_bs,
        "rollout_sweep_tokens_per_sec_chip": {
            str(bs): round(bs * gen / t / jax.device_count(), 1)
            for bs, t in per_bs.items()},
        "prompt_len": prompt,
        "gen_len": gen,
        "rollout_time_s": round(per_bs[best_bs], 3),
        "weights_shared_identical": identical,
        "weights_checked_leaves": n_leaves,
        "int8_view_roundtrip_ok": bool(int8_roundtrip_ok),
        "int8_view_max_abs_err": round(err, 6),
        "remat": bool(remat),
        "cycles": cycles,
    }
    return result


def offload_bench(model_name="opt-350m", *, micro_bs=4, steps=3, gas=4):
    """Measured ZeRO-Offload tier (reference ``stage_1_and_2.py:1037``
    CPU-offload + ``swap_tensor/`` NVMe, perf harness
    ``csrc/aio/py_test/``): the SAME workload in-HBM, host-offloaded
    (C++ SIMD Adam over host-resident fp32 masters/moments), and
    NVMe-swapped (pipelined ``csrc/aio`` reads behind the Adam compute).
    Reports step times and the offload overhead factor — honest even when
    ugly: through a tunneled host link the round trip dominates, which is
    exactly what the calibration phase's link numbers predict."""
    base = train_bench(model_name, micro_bs=micro_bs, zero_stage=2,
                       steps=steps, gas=gas)
    cpu = train_bench(model_name, micro_bs=micro_bs, zero_stage=2,
                      steps=steps, gas=gas, offload="cpu")
    nvme = train_bench(model_name, micro_bs=micro_bs, zero_stage=2,
                       steps=steps, gas=gas, offload="nvme")
    return {
        "model": model_name,
        "gradient_accumulation_steps": gas,
        "in_hbm_step_s": base["step_time_s"],
        "cpu_offload_step_s": cpu["step_time_s"],
        "nvme_offload_step_s": nvme["step_time_s"],
        "cpu_offload_overhead_x": round(
            cpu["step_time_s"] / base["step_time_s"], 2),
        "nvme_offload_overhead_x": round(
            nvme["step_time_s"] / base["step_time_s"], 2),
        # the NVMe leg's own cost on top of host offload = the swap
        # read/write not hidden behind the pipelined Adam
        "nvme_vs_cpu_x": round(
            nvme["step_time_s"] / cpu["step_time_s"], 2),
        "in_hbm_tokens_per_sec_chip": base["tokens_per_sec_chip"],
        "cpu_offload_tokens_per_sec_chip": cpu["tokens_per_sec_chip"],
        "nvme_offload_tokens_per_sec_chip": nvme["tokens_per_sec_chip"],
        "loss_in_hbm": base["loss"],
        "loss_cpu_offload": cpu["loss"],
    }


def custom_single_bench():
    """Env-driven single training bench (BENCH_MODEL etc.) — the round-1
    interface, kept for sweeps."""
    result = train_bench(
        os.environ.get("BENCH_MODEL", "opt-350m"),
        micro_bs=int(os.environ.get("BENCH_BS", "4")),
        zero_stage=int(os.environ.get("BENCH_ZERO", "1")),
        steps=int(os.environ.get("BENCH_STEPS", "10")),
        seq=int(os.environ.get("BENCH_SEQ", "2048")),
        lean=os.environ.get("BENCH_LEAN", "0") == "1",
        remat=os.environ.get("BENCH_REMAT", "0") == "1",
        remat_policy=os.environ.get("BENCH_REMAT_POLICY",
                                    "dots_and_attn_saveable"),
        scan_layers=os.environ.get("BENCH_SCAN", "0") == "1",
        fused_qkv=os.environ.get("BENCH_FQ", "0") == "1",
        loss_chunks=int(os.environ.get("BENCH_LOSS_CHUNKS", "8")))
    import jax
    print(json.dumps({
        "metric": f"{result['model']}-sft-tokens/sec/chip"
                  f"(seq{result['seq']},bs{result['micro_bs']},"
                  f"zero{result['zero_stage']},{jax.devices()[0].platform})",
        "value": result["tokens_per_sec_chip"],
        "unit": "tokens/s/chip",
        "vs_baseline": round(result["mfu"] / 0.35, 4),
        **result,
    }))


# --------------------------------------------------------------------- #
# Phase registry: name -> (primary kwargs, fallback kwargs)
# The fallback is the memory-safe variant recorded with "fallback": true.
# --------------------------------------------------------------------- #

def _north(fallback):
    steps = int(os.environ.get("BENCH_STEPS", "8"))
    # remat OFF for ~2 MFU points (r3 sweep: 48.8% vs 46.9% with remat);
    # the fallback flips it back on, which is the config that always fits
    return train_bench("opt-1.3b", micro_bs=2, zero_stage=3, steps=steps,
                       lean=True, remat=bool(fallback))


def _guard(fallback):
    steps = int(os.environ.get("BENCH_STEPS", "8"))
    return train_bench("opt-350m", micro_bs=4, zero_stage=1, steps=steps,
                       remat=bool(fallback))


def _sft27(fallback):
    """OPT-2.7B on ONE 16 GB chip: bf16 working params + bf16 grad
    accumulation on device (~10.8 GB), fp32 masters + Adam moments in
    host RAM stepped by the C++ SIMD Adam, with gradient accumulation
    amortizing the per-boundary host round trip — the reference's
    single-GPU large-model recipe (blogs/deepspeed-chat README:64-66,
    OPT-13B on one A100-80G via offload)."""
    # flash_only remat + 4-way partitioned backward: bf16 params + bf16
    # accumulator are 10.6 GB, and a one-pass backward's gradient
    # temporaries (~4 GB measured by memory_analysis) push the boundary
    # over this chip's budget — grad_partition_groups trades (N-1) extra
    # backward sweeps (free: the step is host-link-bound) for 1/N grad
    # temps
    r = train_bench("opt-2.7b", micro_bs=1, zero_stage=2,
                    steps=2,
                    gas=4 if fallback else 8,
                    remat=True, remat_policy="flash_only_saveable",
                    offload="cpu", grad_accum_dtype="bf16",
                    grad_groups=4, loss_chunks=8)
    r["bottleneck"] = (
        "host link: the tunneled device moves ~0.07 GB/s (calibration "
        "host_to_device_gbps) vs 16-32 GB/s PCIe, so the per-boundary "
        "grad-down/param-up round trip (~11 GB at 2.7B) dominates the "
        "step; on real hardware the same config amortizes it behind "
        "gradient accumulation")
    return r


PHASES = [
    # (key in result, phase name, runner(fallback) -> dict).  Ordered
    # cheap-first (the round-5 lesson: the most expensive phase ran 4th
    # and its 40-min cold compile starved the ten phases behind it): a
    # budget overrun late in the suite can only cost the phases BEHIND
    # it, and the record already holds everything cheap.  sft_2.7b — the
    # compile-dominated single-chip 2.7B story — runs dead last, and with
    # the persistent compile cache its cold compile happens exactly once
    # per machine.
    ("calibration", "calibrate", lambda fb: calibrate_bench()),
    # per-program memory & roofline record — pinned cheap-first right
    # behind calibration (whose measured peaks anchor its rooflines):
    # the memory record commits even in rounds that die before the
    # heavy phases (the r05-blackout lesson on the memory axis)
    ("memory_snapshot", "memory_snapshot",
     lambda fb: memory_snapshot_bench(fallback=fb)),
    ("sft_350m_guard", "guard", _guard),
    ("__headline__", "north", _north),
    # the offload/NVMe tier, measured against the same in-HBM workload
    ("optimizer_offload", "offload",
     lambda fb: offload_bench(gas=2 if fb else 4,
                              steps=2 if fb else 3)),
    ("generation", "decode",
     lambda fb: decode_bench("opt-1.3b", batch_size=8 if fb else 16)),
    # continuous-batching serving vs sequential bucketed generate() on a
    # mixed-length workload — cheap-first: one extra decode-step program
    # and a lane-width prefill chunk on top of the generation phase's cost
    ("serving_continuous_batching", "serving",
     lambda fb: serving_bench("opt-1.3b", num_slots=4 if fb else 8,
                              n_requests=12 if fb else 24)),
    # serving SLO micro-phase: 4x-capacity burst with mixed deadlines →
    # shed rate, p50/p99 TTFT, graceful-preemption drain latency and the
    # one-decode-executable invariant — cheap-first, right behind the
    # serving phase whose programs it shares
    ("serving_overload", "serving_overload",
     lambda fb: serving_overload_bench("opt-1.3b",
                                       num_slots=4 if fb else 8,
                                       burst_factor=3 if fb else 4)),
    # network-front-end micro-phase: the same mixed workload via direct
    # submit() vs concurrent HTTP clients (2 tenants x 2 priorities,
    # half streaming) — transport overhead on req/s, p50/p99 TTFT and
    # time-between-tokens; cheap-first, it shares the serving phases'
    # program shapes
    ("serving_http", "serving_http",
     lambda fb: serving_http_bench("opt-1.3b",
                                   num_slots=4 if fb else 8,
                                   n_requests=12 if fb else 24)),
    # paged-KV serving at the bs96/128/192 points where the monolithic
    # lanes collapsed (r04), plus the shared-prefix prefill-once story —
    # after the cheap serving phases (it compiles one paged decode
    # program per concurrency level; see PHASE_TIMEOUT_SCALE)
    ("serving_paged", "serving_paged",
     lambda fb: serving_paged_bench("opt-1.3b",
                                    slots_list=(48, 64) if fb
                                    else (96, 128, 192),
                                    prefix_requests=12 if fb else 24)),
    # speculative decoding at the latency-sensitive bs<=16 end (ROADMAP
    # item 3): self-draft accept-rate ceiling per (bs, k) point vs the
    # non-spec serving baseline — accept rate, tok/s/chip, TBT p50/p99,
    # and the one-propose/one-verify executables-per-server proof.
    # After serving_paged: each (bs, k) point compiles a fresh
    # propose+verify pair (serving programs bypass the persistent
    # caches), so the grid is the compile cost (see PHASE_TIMEOUT_SCALE)
    ("serving_speculative", "serving_spec",
     lambda fb: serving_spec_bench("opt-1.3b",
                                   slots_list=(4,) if fb else (4, 8, 16),
                                   k_list=(2, 4) if fb else (2, 4, 8))),
    ("generation_int8", "decode_int8",
     lambda fb: decode_bench("opt-1.3b", int8=True,
                             batch_size=8 if fb else 16)),
    ("generation_int8_kv", "decode_int8_kv",
     lambda fb: decode_bench("opt-1.3b", int8=True, kv_int8=True,
                             batch_size=8 if fb else 16)),
    # throughput serving points: at bs>=64 the KV stream dominates decode
    # traffic — where the int8 cache and the S-major kernel's dead-block
    # DMA skip pay off (reference generation-phase scaling story,
    # blogs/deepspeed-chat/README.md:265)
    ("generation_int8_kv_bs64", "decode_int8_kv_bs64",
     lambda fb: decode_bench("opt-1.3b", int8=True, kv_int8=True,
                             batch_size=32 if fb else 64, gen=128)),
    ("generation_int8_kv_bs96", "decode_int8_kv_bs96",
     lambda fb: decode_bench("opt-1.3b", int8=True, kv_int8=True,
                             batch_size=48 if fb else 96, gen=128)),
    # bs128 collapsed 8x in rounds <=4 (the decode loop's out-of-kernel
    # cache writes made XLA copy the cache per step); the fused in-kernel
    # write (decode_attention new_k/new_v) runs it at full speed
    ("generation_int8_kv_bs128", "decode_int8_kv_bs128",
     lambda fb: decode_bench("opt-1.3b", int8=True, kv_int8=True,
                             batch_size=64 if fb else 128, gen=128)),
    # long-cache point: 4k-position KV cache (prompt 3968 + gen 128).
    # r04 only completed as "fallback": true (bs8) because the "auto"
    # chunk policy dropped the 4k prompt onto the one-pass dense path
    # (~32 GB of fp32 scores at bs16); decode_bench now pins the chunk
    # size for prompts >= 1024 so the primary bs16 attempt runs the real
    # chunked-prefill pipeline, and records prefill_plan either way
    ("generation_int8_kv_4k", "decode_int8_kv_4k",
     lambda fb: decode_bench("opt-1.3b", int8=True, kv_int8=True,
                             batch_size=8 if fb else 16,
                             prompt=3968, gen=128)),
    ("hybrid_rlhf", "hybrid",
     lambda fb: hybrid_bench("opt-1.3b",
                             rollout_bs=(8,) if fb else (8, 32, 64),
                             quantize_rollouts=not fb)),
    ("long_context", "long_context",
     lambda fb: long_context_bench("opt-1.3b", seq=4096 if fb else 8192)),
    # single-chip large-model story: 2.7B via ZeRO-Offload (see _sft27) —
    # LAST: the most compile- and wall-clock-expensive phase must never
    # again starve the record (round-5 rc=124)
    ("sft_2.7b", "sft_2.7b", _sft27),
]

# per-phase wall-clock budget, as a multiple of BENCH_PHASE_TIMEOUT: the
# compile-heavy tails get more rope without inflating every phase's
# budget.  Rebalanced after the round-5 rc=124 (three phases recorded,
# everything behind the 4th starved): the BASE timeout dropped 3000→900 s
# — r5 showed the cheap phases finishing in 62-73 s each, so 900 bounds
# a wedged cheap phase at ~1/3 the old damage — while the slow tier
# (offload's three training runs, hybrid's train+rollout cycles,
# long-context's 8k compiles, and above all sft_2.7b's four 2.7B
# backward compiles, ~40 min cold) keeps its old headroom via scale.
PHASE_TIMEOUT_SCALE = {
    "sft_2.7b": 4.0,
    "long_context": 2.0,
    "hybrid": 2.0,
    # three paged decode programs (one per concurrency level) + the
    # prefix server's — all opted out of the persistent caches (the PR 5
    # reload-corruption class), so every run compiles them cold
    "serving_paged": 2.0,
    # one propose + one verify program per (bs, k) grid point, all
    # persistent-cache-opted-out like every serving program: the 3x3
    # grid compiles 18 programs cold plus 3 non-spec baselines
    "serving_spec": 3.0,
    "offload": 1.5,
}


# --------------------------------------------------------------------- #
# Round-robin phase fairness across bench ROUNDS (the r05 blackout:
# under BENCH_SUITE_BUDGET a FIXED cheap-first order measured the same
# leading phases every round and starved the other 7 forever — rc=124
# with 3/10 phases, five rounds running).
# --------------------------------------------------------------------- #

def _normalize_record(rec):
    """A usable final-format record from whatever shape a ``BENCH_r*.json``
    arrived in, or None.

    The driver may publish either the final record itself or a wrapper
    ``{n, cmd, rc, tail, parsed}`` around the run — in the wrapper the
    record is ``parsed`` (when the driver decoded it) or the LAST stdout
    line captured in ``tail`` (``main()`` prints the final record as one
    JSON line).  A tail truncated mid-record is unrecoverable: return
    None and let callers walk to an older round."""
    if not isinstance(rec, dict):
        return None
    if not ("rc" in rec and ("tail" in rec or "cmd" in rec)):
        return rec                               # already final-format
    parsed = rec.get("parsed")
    if isinstance(parsed, dict):
        return parsed
    tail = rec.get("tail") or ""
    for line in reversed(tail.rstrip().splitlines()):
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                return None                      # clipped mid-record
    return None


def _round_trail():
    """Previous rounds' final records (``BENCH_r*.json`` next to this
    file / in ``BENCH_OUT_DIR``), oldest first — the driver publishes one
    per round.  Unreadable/unrecoverable files are skipped (a partial
    record must never wedge scheduling)."""
    import glob
    recs = []
    for p in sorted(glob.glob(os.path.join(_out_dir(), "BENCH_r*.json"))):
        try:
            with open(p) as f:
                rec = _normalize_record(json.load(f))
        except (OSError, ValueError):
            continue
        if rec is not None:
            recs.append(rec)
    return recs


def _REC_KEY(key):
    """Phase key -> final-record key (the headline phase is published
    under ``north_star``)."""
    return "north_star" if key == "__headline__" else key


def _phase_measured(rec, key):
    """True when ``rec`` holds a COMPLETED measurement for the phase —
    skipped / timed-out / errored entries don't count (that phase is
    still starving)."""
    ph = rec.get(_REC_KEY(key))
    return isinstance(ph, dict) and ph \
        and not any(t in ph for t in ("skipped", "timeout", "error"))


def _phase_order(phases):
    """Order phases by STALENESS — how many rounds ago the BENCH_r* trail
    last holds a completed measurement (never measured = older than the
    whole trail) — most starved first, ties in registry (cheap-first)
    order.  With a suite budget that fits k of the n phases, every phase
    is measured at least every ceil(n/k) rounds instead of the same k
    forever, and because the incremental record is rewritten after every
    phase, each round's partial record stays a valid final-format record
    of whatever its budget afforded.  Calibration is pinned first (later
    phases anchor their roofline math to its measured peaks), the
    memory_snapshot micro-phase right behind it (the per-program memory
    record must commit before any heavy phase can starve it), and
    serving_paged third: it carries the paged-attention-kernel acceptance
    story (bs128 decode vs the r04 cliff, per-bs rooflines) and must land
    in the NEXT record (BENCH_r06) rather than wait out a starvation
    rotation."""
    trail = _round_trail()

    def staleness(key):
        for age, rec in enumerate(reversed(trail), 1):
            if _phase_measured(rec, key):
                return age
        return len(trail) + 1

    pinned = ("calibrate", "memory_snapshot", "serving_paged")
    index = {p[0]: i for i, p in enumerate(phases)}
    rest = sorted((p for p in phases if p[1] not in pinned),
                  key=lambda p: (-staleness(p[0]), index[p[0]]))
    head = sorted((p for p in phases if p[1] in pinned),
                  key=lambda p: pinned.index(p[1]))
    return head + rest


# --------------------------------------------------------------------- #
# Per-phase regression thresholds against the previous round's record
# (warn-and-annotate — ROADMAP item 5: the perf trajectory must flag its
# own cliffs, not wait for a human to diff BENCH_r* files by eye)
# --------------------------------------------------------------------- #

def _regression_direction(key):
    """+1 = higher is better, -1 = lower is better, 0 = not a perf metric."""
    if "tokens_per_sec" in key or "tok_s" in key or key == "mfu" \
            or key.startswith("speedup") or key.endswith("_efficiency") \
            or "accept_rate" in key or key == "tokens_per_dispatch" \
            or key in ("achieved_gbps", "achieved_tflops") \
            or key.startswith("hbm_utilization") \
            or key.endswith("_fraction_of_peak"):
        return 1
    if key in ("step_time_s", "e2e_time_s") or "ttft_" in key \
            or "time_between_tokens" in key or key.startswith("lock_wait_") \
            or key in ("temp_size_in_bytes", "total_bytes",
                       "hbm_unattributed_bytes"):
        # roofline regressions: a program's achieved bandwidth/compute
        # falling, or its temp/live HBM budget growing, is exactly the
        # bs128-cliff class the memory record exists to flag
        return -1
    return 0


def _walk_metrics(d, path=""):
    for k, v in d.items():
        p = f"{path}.{k}" if path else k
        if isinstance(v, dict):
            yield from _walk_metrics(v, p)
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            yield p, k, v


def _annotate_regressions(key, phase, trail=None, threshold=None):
    """Compare this phase's perf metrics against the newest previous
    ``BENCH_r*`` record that measured it; annotate drops beyond the
    threshold in the phase record (``phase["regressions"]``) and warn.
    Never fails the run — the record is the alarm, the bench keeps
    measuring (a regressed phase is exactly the one worth re-measuring
    next round)."""
    if not isinstance(phase, dict) or \
            any(t in phase for t in ("skipped", "timeout", "error")):
        return
    if threshold is None:
        threshold = float(os.environ.get("BENCH_REGRESSION_THRESHOLD",
                                         "0.15"))
    if threshold <= 0:
        return
    trail = _round_trail() if trail is None else trail
    prev = next((rec[_REC_KEY(key)] for rec in reversed(trail)
                 if _phase_measured(rec, key)), None)
    if not isinstance(prev, dict):
        return
    prev_flat = {p: v for p, _, v in _walk_metrics(prev)}
    regs = []
    for path, leaf, now in _walk_metrics(phase):
        d = _regression_direction(leaf)
        old = prev_flat.get(path)
        if not d or not isinstance(old, (int, float)) or old <= 0 or now <= 0:
            continue
        ratio = now / old if d > 0 else old / now
        if ratio < 1.0 - threshold:
            regs.append({"metric": path, "prev": old, "now": now,
                         "drop_pct": round((1.0 - ratio) * 100, 1)})
    if regs:
        regs.sort(key=lambda r: -r["drop_pct"])
        phase["regressions"] = regs
        worst = regs[0]
        print(f"bench: REGRESSION in phase {key}: {len(regs)} metric(s) "
              f"beyond the {threshold:.0%} threshold vs the previous "
              f"record (worst: {worst['metric']} {worst['prev']} -> "
              f"{worst['now']}, -{worst['drop_pct']}%)", file=sys.stderr)


def run_phase(name, fallback, out_path):
    """Entry point inside a phase subprocess: run one phase, write its JSON
    to ``out_path``."""
    if os.environ.get("DSTPU_ACCELERATOR") == "cpu":
        # a sitecustomize may pin a hardware platform; the live config must
        # be updated before first device use (env alone is too late)
        import jax
        jax.config.update("jax_platforms", "cpu")
    # crash-containment test knobs (tests/unit/test_bench_harness.py): die
    # on the primary attempt (the fallback retry must recover), die on
    # every attempt (the parent must record the error and keep going), or
    # hang (the parent's per-phase budget must skip-and-record)
    if os.environ.get("BENCH_TEST_FAIL_PRIMARY") == name and not fallback:
        raise RuntimeError("injected primary-attempt failure")
    if os.environ.get("BENCH_TEST_FAIL_ALWAYS") == name:
        raise RuntimeError("injected unconditional failure")
    if os.environ.get("BENCH_TEST_HANG") == name:
        time.sleep(10 ** 6)
    _setup_compile_cache()
    runner = next((r for _, n, r in PHASES if n == name), None)
    if runner is None:
        raise SystemExit(f"unknown phase {name!r}; valid: "
                         f"{', '.join(n for _, n, _ in PHASES)}")
    from deepspeed_tpu.runtime.compile_cache import stats
    before = stats().snapshot()
    result = runner(fallback)
    if fallback:
        result["fallback"] = True
    # compile cost observability: how much this phase compiled vs reloaded
    result["compile_cache"] = _cache_report(before)
    # per-phase peak-HBM watermark (docs/observability.md "Device memory
    # & roofline"): each phase owns its subprocess, so the accelerator's
    # process-lifetime peak IS the phase watermark.  Best-effort — a
    # backend with no live stats still records the (zero) shape
    try:
        from deepspeed_tpu.monitor.memwatch import device_memory_record
        result.setdefault("hbm_watermark", device_memory_record())
    except Exception as e:
        result.setdefault("hbm_watermark", {"error": str(e)[:200]})
    with open(out_path, "w") as f:
        json.dump(result, f)


# --------------------------------------------------------------------- #
# Parent orchestrator (never imports jax — a dead phase cannot pin HBM
# here, and the device is free for the next phase subprocess)
# --------------------------------------------------------------------- #

def _out_dir():
    """Scratch/record directory — overridable so concurrent runs (a test
    harness next to a live TPU suite) never clobber each other's partial
    results."""
    d = os.environ.get("BENCH_OUT_DIR", REPO)
    os.makedirs(d, exist_ok=True)
    return d


def _utc_now():
    """ISO-8601 UTC timestamp for per-phase forensics (the r05 blackout
    could not even be ORDERED from the record)."""
    return datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="seconds")


def _spawn_phase(name, fallback, timeout_s, extra_env):
    # pid-suffixed: two bench parents must not share phase scratch files
    out_path = os.path.join(_out_dir(),
                            f".bench_phase_{name}.{os.getpid()}.json")
    log_path = os.path.join(_out_dir(),
                            f".bench_phase_{name}.{os.getpid()}.log")
    if os.path.exists(out_path):
        os.unlink(out_path)
    cmd = [sys.executable, os.path.abspath(__file__),
           "--phase", name, "--out", out_path]
    if fallback:
        cmd.append("--fallback")
    env = dict(os.environ)
    env.update(extra_env)
    t0 = time.perf_counter()
    timed_out = False
    rc = None
    try:
        with open(log_path, "w") as log:
            proc = subprocess.run(cmd, stdout=log, stderr=subprocess.STDOUT,
                                  env=env, timeout=timeout_s)
        rc = proc.returncode
    except subprocess.TimeoutExpired:
        # distinct from any child returncode (a SIGHUP death is rc=-1 and
        # must not be mislabeled a timeout)
        timed_out = True
    wall = time.perf_counter() - t0
    if rc == 0 and os.path.exists(out_path):
        with open(out_path) as f:
            result = json.load(f)
        os.unlink(out_path)
        return result, None, wall
    tail = ""
    if os.path.exists(log_path):
        with open(log_path, errors="replace") as f:
            tail = f.read()[-2000:]
    reason = f"timeout after {timeout_s}s" if timed_out else f"rc={rc}"
    return None, f"{reason}; log tail: {tail}", wall


def _assemble_final(result, errors):
    """The final driver-contract record, from whatever phases are done —
    callable after EVERY phase (incremental record) and at exit."""
    result = dict(result)
    north = result.pop("__headline__", {})
    calib = result.get("calibration", {})
    platform = calib.get("platform", "unknown")
    final = {
        "metric": "opt-1.3b-sft-tokens/sec/chip(seq2048,bs2,zero3,"
                  "bf16-lean-opt-states," + platform + ")",
        "value": north.get("tokens_per_sec_chip"),
        "unit": "tokens/s/chip",
        # north star: >=35% MFU on the OPT-1.3B ZeRO-3 SFT workload
        "vs_baseline": round(north["mfu"] / 0.35, 4)
        if north.get("mfu") else None,
        "mfu": north.get("mfu"),
        "step_time_s": north.get("step_time_s"),
        "loss": north.get("loss"),
        "n_devices": calib.get("n_devices"),
        # honesty: on one chip the zero/dp mesh axes are size-1, so the
        # zero3 label shards nothing here — real ZeRO-3 collectives are
        # exercised on the virtual multi-device mesh (tests + driver dryrun)
        "sharding_note": ("single-chip: zero/dp axes size-1 (nominal); "
                          "multi-device sharding covered by dryrun_multichip"
                          if calib.get("n_devices") == 1 else None),
        "north_star": north,
        **result,
    }
    if errors:
        final["phase_errors"] = errors
    return final


def _write_record(path, record):
    """Atomic write: a reader (or a crash) never sees a half-written
    record."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
    os.replace(tmp, path)


def main():
    if os.environ.get("BENCH_MODEL"):
        _setup_compile_cache()
        custom_single_bench()
        return

    # 900s base (was 3000: the round-5 rebalance — see PHASE_TIMEOUT_SCALE):
    # cheap phases measured 62-73s each, so 900 bounds a wedged one, while
    # the compile-heavy tail (sft_2.7b's four 2.7B backward programs, ~40
    # min cold) keeps its headroom through its 4.0x scale; the persistent
    # cache (.jax_bench_cache) makes warm reruns fit easily
    timeout_s = int(os.environ.get("BENCH_PHASE_TIMEOUT", "900"))
    # total-suite budget (seconds; 0 = off): once exhausted, remaining
    # phases are recorded as skipped instead of starving whatever driver
    # is wrapping this run in ITS OWN timeout (the round-5 rc=124)
    suite_budget = float(os.environ.get("BENCH_SUITE_BUDGET", "0"))
    partial_path = os.path.join(_out_dir(), ".bench_partial.json")
    # final-format record, rewritten after EVERY phase: an interrupt, a
    # crash, or an external kill after phase k still leaves a complete
    # record of all k finished phases on disk
    results_path = os.environ.get("BENCH_RESULTS_JSON") \
        or os.path.join(_out_dir(), "BENCH_partial.json")
    result = {}
    errors = {}
    extra_env = {}
    suite_t0 = time.perf_counter()
    # previous rounds' records, read once: the per-phase regression
    # thresholds (warn-and-annotate) compare against the newest record
    # that measured each phase
    trail = _round_trail()

    phases = PHASES
    if suite_budget:
        # a bounded round cannot fit every phase — rotate by staleness so
        # whatever starved last round runs first this round (the r05
        # blackout fix; without a budget the registry's cheap-first order
        # is strictly better crash containment)
        phases = _phase_order(phases)
    if os.environ.get("BENCH_PHASES"):      # subset, for debugging/tests
        want = set(os.environ["BENCH_PHASES"].split(","))
        phases = [p for p in phases if p[1] in want]

    # SIGTERM (a wrapping driver's kill) lands like Ctrl-C: emit the
    # partial record instead of dying with whatever was buffered
    import signal

    def _sigterm(signum, frame):
        raise KeyboardInterrupt
    try:
        signal.signal(signal.SIGTERM, _sigterm)
    except ValueError:
        pass                               # non-main thread (tests)

    interrupted = None
    name = "startup"
    try:
        for key, name, _ in phases:
            budget = uncapped = int(timeout_s
                                    * PHASE_TIMEOUT_SCALE.get(name, 1.0))
            if suite_budget:
                # the round-5 lesson, part two: the budget was only
                # checked BETWEEN phases, so one phase could blow straight
                # through it and starve the wrapping driver into rc=124 —
                # cap every phase's timeout at what the suite can still
                # afford (30s reserved for record flushing), and skip
                # outright when the remainder is not worth a phase
                remaining = suite_budget - (time.perf_counter() - suite_t0)
                if remaining - 30 < 60:
                    # r05-blackout forensics: the record must say WHY a
                    # phase is missing (budget math at the decision
                    # point), not just that it is
                    result[key] = {
                        "skipped": f"suite budget "
                                   f"({suite_budget:.0f}s) exhausted",
                        "skipped_reason":
                            f"suite budget {suite_budget:.0f}s exhausted "
                            f"with {remaining:.0f}s remaining (< 90s "
                            f"floor incl. the 30s record-flush reserve)",
                        "started_at": _utc_now(),
                        "elapsed_s": 0.0,
                        "timeout_budget_s": 0,
                    }
                    print(f"bench: suite budget exhausted — skipping {name}",
                          file=sys.stderr)
                    _write_record(partial_path, result)
                    _write_record(results_path,
                                  _assemble_final(result, errors))
                    continue
                budget = min(budget, int(remaining - 30))
            started_at = _utc_now()
            phase, err, wall = _spawn_phase(name, False, budget, extra_env)
            timed_out = phase is None and err and err.startswith("timeout")
            if phase is None and timed_out \
                    and os.environ.get("BENCH_RETRY_ON_TIMEOUT") != "1":
                # budget overrun: SKIP AND RECORD — a fallback retry after
                # a timeout doubles the damage to every phase behind it
                # (crashes still get the fallback retry below: a safe
                # config fixes an OOM, it does not fix slowness)
                errors[name] = err
                phase = {"error": err, "timeout": True,
                         "skipped_reason": f"timed out after {budget}s "
                                           f"(BENCH_PHASE_TIMEOUT "
                                           f"x {PHASE_TIMEOUT_SCALE.get(name, 1.0)}"
                                           f"{', capped by suite budget' if budget < uncapped else ''})"}
                print(f"bench: phase {name} exceeded its {budget}s budget — "
                      f"recording the overrun and continuing",
                      file=sys.stderr)
            elif phase is None:
                print(f"bench: phase {name} failed "
                      f"({err.splitlines()[0] if err else '?'}); "
                      f"retrying with safe config", file=sys.stderr)
                phase, err2, wall = _spawn_phase(name, True, budget,
                                                 extra_env)
                # both attempts' errors matter: the fallback can fail for a
                # DIFFERENT reason than the primary (config bug, timeout)
                err = None if phase is not None else \
                    f"primary attempt: {err}\nfallback attempt: {err2}"
                if phase is None:
                    errors[name] = err
                    phase = {"error": err}
                    print(f"bench: phase {name} failed twice — recording "
                          f"the error and continuing", file=sys.stderr)
            # per-phase forensics in EVERY record (the r05 lesson: a
            # missing phase with no started_at/budget context is
            # undiagnosable from the record alone)
            phase["phase_wall_s"] = round(wall, 1)
            phase["started_at"] = started_at
            phase["elapsed_s"] = round(wall, 1)
            phase["timeout_budget_s"] = budget
            _annotate_regressions(key, phase, trail=trail)
            if key == "calibration" and "measured_mxu_tflops" in phase:
                # anchor later phases' roofline math to the measured peaks —
                # but ONLY when they are physically plausible: tunnel jitter
                # can corrupt the differenced timing (a >datasheet "measured
                # peak" would silently deflate every *_vs_measured below it)
                plausible = (0.3 <= phase.get("mxu_fraction_of_datasheet", 0)
                             <= 1.1
                             and 0.3 <= phase.get("hbm_fraction_of_datasheet",
                                                  0) <= 1.1)
                if plausible:
                    extra_env["BENCH_MEASURED_TFLOPS"] = \
                        str(phase["measured_mxu_tflops"])
                    extra_env["BENCH_MEASURED_GBPS"] = \
                        str(phase["measured_hbm_gbps"])
                else:
                    phase["calibration_unreliable"] = True
                    print("bench: calibration outside plausible range — "
                          "later phases use datasheet peaks only",
                          file=sys.stderr)
            result[key] = phase
            _write_record(partial_path, result)       # raw phase map
            _write_record(results_path,
                          _assemble_final(result, errors))
            print(f"bench: phase {name} done in {wall:.0f}s", file=sys.stderr)
    except KeyboardInterrupt:
        interrupted = name
        errors["__interrupted__"] = f"interrupted during phase {name}"
        print(f"bench: interrupted during {name} — emitting the record of "
              f"all completed phases", file=sys.stderr)

    final = _assemble_final(result, errors)
    if interrupted is not None:
        final["interrupted_during"] = interrupted
    _write_record(results_path, final)
    print(json.dumps(final))


if __name__ == "__main__":
    if "--phase" in sys.argv:
        i = sys.argv.index("--phase")
        name = sys.argv[i + 1]
        out = sys.argv[sys.argv.index("--out") + 1]
        run_phase(name, "--fallback" in sys.argv, out)
    else:
        main()
