"""Memory/FLOP program contracts: HBM footprints and compute budgets in
``PROGRAMS.lock`` (format 3) + the ``ds_lint --mem`` gate.

PR 7 locked what every hot-path program *is* (primitive multisets,
donations, collective schedules); PR 14 locked what it *moves*
(byte-level comm budgets).  This module locks what it *costs* the
device: for every hot-path program and sharding plan,

* ``compiled.memory_analysis()`` — argument / output / temp / alias /
  generated-code bytes, plus the derived ``total_bytes`` = arg + out +
  temp − alias (the live working set).  Exact on TPU, stable on the
  tier-1 CPU backend the contracts are defined under; and
* ``compiled.cost_analysis()`` — flops and bytes-accessed, the roofline
  numerators (``autotuning.cost_model`` is the shared extraction — the
  flops profiler and the bench roofline blocks read the same code).

The regression story the comm layer taught, applied to the resource
that actually produced the BENCH_r04 cliff (decode collapsing 8,673 →
1,193 tok/s/chip with HBM util falling to 0.075): a memory regression
must fail as a readable byte story — ``decode_step temp HBM: 96.0MB ->
612.0MB`` — at lock-diff time, not as an OOM or a bandwidth collapse
three rounds later.  A dropped donation is the canonical break: the
alias bytes vanish and the live total jumps by the whole donated
buffer (the synthetic-break proof in
``tests/unit/test_program_contracts.py``).

**Growth gate**: ``ds_lint --contracts --update`` REFUSES to rewrite a
program's memory contract when any byte field grew beyond
``MEM_TOLERANCE`` over the committed lock, unless the program is
declared in :data:`DECLARED_GROWTH` with a reviewable reason (the
declaration is stamped into the lock as ``memory_growth_declared``, so
the artifact diff carries the why).  Memory bloat cannot land
silently: either the program shrinks back, or the growth is declared
in a committed source file a reviewer reads.

Costs are exact compiler outputs under the tier-1 harness (CPU, 8
virtual devices) — deterministic and diffable; the tolerance band only
absorbs jax/jaxlib patch-level layout jitter.  Compiles are the
expensive half: the fast tier-1 gate diffs program contracts WITHOUT
memory (no compile — the comm probe discipline), plan contracts carry
memory for free (their schedule compile already exists), and the full
per-program memory regen-and-diff runs as the ``slow``-marked half of
``test_program_contracts.py`` and as ``ds_lint --mem`` from the CLI.
"""

import os
from contextlib import contextmanager

# ``compiled.memory_analysis()`` fields locked per program, in story
# order (the host_* twins are all zero on the device backends we lock).
MEM_FIELDS = ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes", "alias_size_in_bytes",
              "generated_code_size_in_bytes")
COST_FIELDS = ("flops", "bytes_accessed")

# display names for the byte stories
_STORY = {
    "argument_size_in_bytes": "argument HBM",
    "output_size_in_bytes": "output HBM",
    "temp_size_in_bytes": "temp HBM",
    "alias_size_in_bytes": "donated-alias HBM",
    "generated_code_size_in_bytes": "generated code",
    "total_bytes": "live HBM total",
    "flops": "flops",
    "bytes_accessed": "bytes accessed",
}

# Relative drift below this is compiler noise (padding, fusion-boundary
# layout churn across jax patch releases), not a regression; the
# absolute floor keeps the tiniest programs (the locked entry points
# run at toy shapes — some footprints are a few hundred bytes) from
# tripping on sub-KB scratch shifts.
MEM_TOLERANCE = 0.02
MEM_ABS_FLOOR = 1024

# Programs whose memory is ALLOWED to grow beyond tolerance at the next
# ``--contracts --update``, each with a reviewable reason.  An entry
# here is the only way memory growth lands: the update gate refuses to
# rewrite an undeclared grower.  Entries are meant to be TRANSIENT —
# once the grown contract is locked (the declaration is stamped into
# the lock as ``memory_growth_declared``), the next PR removes the
# entry and the ratchet re-arms.
DECLARED_GROWTH = {
    # The paged serving programs now run the Pallas paged-attention /
    # chunked-prefill kernels instead of the per-layer take_along_axis
    # gather.  On the CPU contract harness pallas_call runs in
    # interpret mode, which materialises each page block as a real HBM
    # temp and keeps the fused pool write as an extra output copy; on
    # TPU those are VMEM scratch and a true input_output_alias.  The
    # growth is tens of KB at the toy contract shapes and trades away a
    # full gathered-pool copy per layer per step at real shapes.
    "serving.decode_step_paged":
        "Pallas paged-decode kernel: interpret-mode page-block temps + "
        "fused pool-write aliasing replace the take_along_axis gather",
    "serving.prefill_chunk_paged":
        "Pallas chunked-prefill kernel: interpret-mode page-block temps "
        "replace the take_along_axis gather",
    "serving.spec_verify_paged":
        "Pallas chunked-prefill kernel (spec verify path): "
        "interpret-mode page-block temps replace the gather",
}


# ------------------------------------------------------------------ #
# Extraction
# ------------------------------------------------------------------ #
@contextmanager
def fresh_compile_env():
    """Force a REAL compile: an executable reloaded from jax's
    persistent compilation cache reports a DEGENERATE
    ``memory_analysis()`` (the donated-alias bytes read 0 and the live
    total inflates by the whole aliased buffer — the serialized
    artifact drops the alias table), so a memory contract extracted
    from a warm cache hit would read every donation as dropped.  Every
    memory-bearing compile (contract extraction, the bench
    memory_snapshot phase) runs under this guard; the test harness and
    bench both enable the persistent cache globally.  (The same
    serialization boundary is the prime suspect in the PR 5
    reloaded-executable corruption — ROADMAP item 4.)"""
    import jax

    def _reset():
        # jax memoizes "is the cache used" per process at first compile
        # (compilation_cache._cache_checked), so flipping the config
        # flag alone is a no-op once anything compiled — reset_cache()
        # drops the memo (and the in-memory handle; the disk cache
        # itself is untouched and re-attaches on next use)
        try:
            from jax._src import compilation_cache
            compilation_cache.reset_cache()
        except Exception:
            pass

    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    _reset()
    try:
        yield
    finally:
        jax.config.update("jax_enable_compilation_cache", old)
        _reset()


def memory_cost_of(compiled):
    """``{"memory": {...}, "cost": {...}}`` of one compiled program —
    exact compiler-reported bytes and flops (``autotuning.cost_model``
    is the shared extraction).  Raises when the backend exposes no
    memory analysis: a contract locked from a backend that cannot
    answer would silently lock zeros."""
    from deepspeed_tpu.autotuning import cost_model
    mem = cost_model.xla_memory_analysis(compiled)
    if mem is None:
        raise RuntimeError(
            "compiled.memory_analysis() unavailable on this backend — "
            "memory contracts are defined under the tier-1 harness "
            "(CPU backend) or on a real TPU")
    costs = cost_model.compiled_costs(compiled)
    if costs["flops"] <= 0 and costs["bytes_accessed"] <= 0:
        # compiled_costs is deliberately lenient for the profiler; a
        # CONTRACT locked from a backend whose cost_analysis answers
        # nothing would silently lock zeros and hide every later
        # flops/bytes regression — fail like the memory branch does
        raise RuntimeError(
            "compiled.cost_analysis() reported no flops and no bytes "
            "accessed — cost contracts need a backend with a working "
            "cost analysis (the tier-1 CPU harness or a real TPU)")
    return {
        "memory": {k: int(mem.get(k, 0)) for k in
                   MEM_FIELDS + ("total_bytes",)},
        "cost": {"flops": int(costs["flops"]),
                 "bytes_accessed": int(costs["bytes_accessed"])},
    }


def filtered_builders(names=None):
    """The registered entry-point builders surviving a program-name
    filter, as ``[(builder, mapped_program_name)]`` — the
    skip-BEFORE-build rule both the ``--mem`` gate and the bench
    ``memory_snapshot`` phase share (a filtered single-program sweep
    must not pay 15 discarded engine builds).  A builder missing from
    the static map is never skipped: better one redundant build than a
    silently unchecked program.  Callers MUST cross-check the built
    ``ep.name`` with :func:`map_drift_problem`."""
    from deepspeed_tpu.tools.lint import entry_points
    out = []
    for build in entry_points.BUILDERS:
        mapped = entry_points.BUILDER_PROGRAMS.get(build.__name__)
        if names and mapped is not None and mapped not in names:
            continue
        out.append((build, mapped))
    return out


def map_drift_problem(builder_name, mapped, actual):
    """The shared cross-check keeping ``BUILDER_PROGRAMS`` honest:
    a message when the map disagrees with what the builder actually
    constructed, else ``None``."""
    if mapped == actual:
        return None
    return (f"entry_points.BUILDER_PROGRAMS[{builder_name!r}] = "
            f"{mapped!r} but the builder constructs {actual!r} — fix "
            f"the map (name-filtered sweeps would skip the wrong "
            f"program)")


def memory_contract_of_entry_point(ep):
    """Memory/FLOP contract of one ``entry_points.EntryPoint`` — pays
    one REAL compile (the expensive half; the fast contract gate skips
    it, the slow gate and ``ds_lint --mem`` pay it)."""
    with fresh_compile_env():
        return memory_cost_of(ep.fn.lower(*ep.args).compile())


def attach_memory_contract(contract, name, compiled):
    """Stamp the memory/cost blocks (and any declared-growth reason)
    onto a program/plan contract dict, in place."""
    contract.update(memory_cost_of(compiled))
    reason = DECLARED_GROWTH.get(name)
    if reason:
        contract["memory_growth_declared"] = str(reason)
    return contract


# ------------------------------------------------------------------ #
# Tolerance-banded diff + byte stories
# ------------------------------------------------------------------ #
def _beyond_tolerance(old, new):
    if old == new:
        return False
    return abs(new - old) > max(MEM_ABS_FLOOR,
                                MEM_TOLERANCE * max(abs(old), 1))


def _fmt(field, n):
    from deepspeed_tpu.tools.lint.comm_contract import fmt_bytes
    if field == "flops":
        return f"{n:,}"
    return fmt_bytes(n)


def _pct(old, new):
    if not old:
        return ""
    return f" ({'+' if new >= old else ''}{100.0 * (new - old) / old:.0f}%)"


def diff_memory(name, locked, fresh):
    """Readable memory/cost diff lines for one program (``name`` is
    prepended by the caller's contract diff).  Empty = within
    tolerance.  Each beyond-tolerance field renders as a byte story —
    ``temp HBM: 96.0MB -> 612.0MB (+537%)`` — with growth flagged as
    the regression it is; a vanished donated-alias is called out as
    the dropped-donation signature."""
    out = []
    for section, fields in (("memory", MEM_FIELDS + ("total_bytes",)),
                            ("cost", COST_FIELDS)):
        lo = locked.get(section) or {}
        fr = fresh.get(section) or {}
        if not lo and not fr:
            continue
        for field in fields:
            a, b = int(lo.get(field, 0)), int(fr.get(field, 0))
            if not _beyond_tolerance(a, b):
                continue
            story = _STORY.get(field, field)
            line = f"  {story}: {_fmt(field, a)} -> {_fmt(field, b)}" \
                   f"{_pct(a, b)}"
            if field == "alias_size_in_bytes" and b < a:
                line += (" (donation lost or shrunk: bytes that aliased "
                         "in place now live twice)")
            elif field in ("temp_size_in_bytes", "total_bytes") and b > a:
                line += " (MEMORY GROWTH beyond tolerance)"
            out.append(line)
    lo_decl = locked.get("memory_growth_declared")
    fr_decl = fresh.get("memory_growth_declared")
    if fr_decl is not None and lo_decl != fr_decl:
        # one-directional on purpose: a NEW or CHANGED declaration must
        # lock (it documents a growth the reviewer should see), but
        # REMOVING a DECLARED_GROWTH entry after its grown contract
        # landed — the documented ratchet re-arm — must not turn the
        # gate red with zero byte change; the stale stamp simply drops
        # out of the lock at the next regen
        out.append(f"  memory_growth_declared: {lo_decl!r} -> "
                   f"{fr_decl!r}")
    return out


def growth_problems(name, locked, fresh, declared=None):
    """The update-time ratchet: byte fields that GREW beyond tolerance
    over the committed contract, for an undeclared program.  Returns
    problem strings (empty = clean or declared)."""
    declared = DECLARED_GROWTH if declared is None else declared
    lo = (locked or {}).get("memory") or {}
    fr = (fresh or {}).get("memory") or {}
    if not lo or not fr:
        return []                 # no committed baseline to ratchet on
    problems = []
    # alias growth is excluded: MORE aliased bytes is the donation WIN
    # (an alias drop shows up as total_bytes growth anyway)
    for field in ("argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes", "generated_code_size_in_bytes",
                  "total_bytes"):
        a, b = int(lo.get(field, 0)), int(fr.get(field, 0))
        if b > a and _beyond_tolerance(a, b):
            story = _STORY.get(field, field)
            if name in declared:
                continue
            problems.append(
                f"{name}: {story} GROWS {_fmt(field, a)} -> "
                f"{_fmt(field, b)}{_pct(a, b)} beyond the "
                f"{MEM_TOLERANCE:.0%} tolerance — memory bloat cannot "
                f"land silently; shrink the program or declare the "
                f"growth in mem_contract.DECLARED_GROWTH with a reason")
    return problems


def validate_memory_contract(name, contract):
    """Invariants of one LOCKED memory contract, checked with no
    compile: blocks present, totals consistent, a declared-donating
    program actually aliases bytes."""
    problems = []
    mem = contract.get("memory")
    cost = contract.get("cost")
    if not mem or not cost:
        return [f"{name}: no memory/cost contract locked — run "
                f"ds_lint --contracts --update"]
    total = (mem.get("argument_size_in_bytes", 0)
             + mem.get("output_size_in_bytes", 0)
             + mem.get("temp_size_in_bytes", 0)
             - mem.get("alias_size_in_bytes", 0))
    if mem.get("total_bytes") != total:
        problems.append(
            f"{name}: total_bytes {mem.get('total_bytes')} != "
            f"arg + out + temp - alias = {total}")
    if mem.get("alias_size_in_bytes", 0) \
            > mem.get("argument_size_in_bytes", 0):
        problems.append(
            f"{name}: donated-alias bytes exceed argument bytes "
            f"({mem.get('alias_size_in_bytes')} > "
            f"{mem.get('argument_size_in_bytes')})")
    don = contract.get("donation", {})
    if don.get("declared") and don.get("aliased", 0) \
            and not mem.get("alias_size_in_bytes", 0):
        problems.append(
            f"{name}: donation aliases {don.get('aliased')} buffer(s) "
            f"but the memory contract aliases 0 bytes — the donation "
            f"is declared yet buys no memory")
    if cost.get("flops", 0) <= 0 or cost.get("bytes_accessed", 0) <= 0:
        # a zero-flop hot-path program is a cost analysis that answered
        # nothing, not a real budget — it would hide every regression
        problems.append(f"{name}: degenerate cost budget {cost}")
    return problems


# ------------------------------------------------------------------ #
# The ``ds_lint --mem`` gate
# ------------------------------------------------------------------ #
def check_memory_against_lockfile(names=None, progress=None,
                                  lock_path=None):
    """(ok, lines).  Recompile the hot-path programs (``names`` limits
    the sweep — the CLI accepts program names so a single-program proof
    doesn't pay 16 engine builds) and the sharding plans, extract fresh
    memory/cost contracts, and diff them against the committed lock's
    format-3 sections with the tolerance band.  Every line is a byte
    story."""
    from deepspeed_tpu.tools.lint import contract as contract_mod
    try:
        locked = contract_mod.load_lockfile(lock_path)
    except FileNotFoundError:
        return False, [f"{contract_mod.LOCKFILE_NAME} missing — generate "
                       f"with ds_lint --contracts --update"]
    ok, lines = True, []
    meta = locked.get("_meta", {})
    if int(meta.get("format", 0)) < 3:
        return False, [
            f"{contract_mod.LOCKFILE_NAME} is format "
            f"{meta.get('format')} (< 3): no memory contracts locked — "
            f"regenerate with ds_lint --contracts --update"]

    def _check(name, fresh):
        nonlocal ok
        sec = "programs" if name in locked.get("programs", {}) \
            else "collective_schedules"
        lock_c = locked.get(sec, {}).get(name)
        if lock_c is None:
            ok = False
            lines.append(f"{name}: not in {contract_mod.LOCKFILE_NAME} — "
                         f"run ds_lint --contracts --update")
            return
        diff = diff_memory(name, lock_c, fresh)
        if diff:
            ok = False
            lines.append(f"{name}:")
            lines.extend(diff)
        for p in growth_problems(name, lock_c, fresh):
            ok = False
            lines.append(p)

    from deepspeed_tpu.parallel import plans
    from deepspeed_tpu.parallel.topology import reset_topology
    from deepspeed_tpu.tools.lint import entry_points
    matched = set()
    for build, mapped in filtered_builders(names):
        reset_topology()
        try:
            ep = build()
        finally:
            reset_topology()
        drift = map_drift_problem(build.__name__, mapped, ep.name)
        if drift:
            ok = False
            lines.append(drift)
        if names and ep.name not in names:
            continue
        matched.add(ep.name)
        if progress:
            progress(f"compiling {ep.name}")
        fresh = memory_contract_of_entry_point(ep)
        reason = DECLARED_GROWTH.get(ep.name)
        if reason:
            fresh["memory_growth_declared"] = str(reason)
        _check(ep.name, fresh)
    for build in plans.PLAN_BUILDERS:
        # plans are named "parallel.<builder>" by convention (the
        # contract tests key on it); cross-checked after the build
        guess = f"parallel.{build.__name__}"
        if names and guess not in names:
            continue
        if progress:
            progress(f"compiling plan {build.__name__}")
        pname, c = contract_mod.build_plan_contract(build.__name__)
        if pname != guess:
            ok = False
            lines.append(
                f"plan {build.__name__} constructs {pname!r}, not the "
                f"conventional {guess!r} — name-filtered sweeps would "
                f"miss it")
        matched.add(pname)
        matched.add(guess)
        _check(pname, c)
    if names:
        unknown = set(names) - matched
        if unknown:
            # a misspelled name must NEVER exit 0 having checked nothing
            ok = False
            known = sorted(entry_points.BUILDER_PROGRAMS.values()) + \
                sorted(locked.get("collective_schedules", {}))
            lines.append(
                f"unknown program name(s) {sorted(unknown)} — nothing "
                f"was checked for them; known: {known}")
    # locked-artifact invariants ride along for free
    for sec in ("programs", "collective_schedules"):
        for name, c in sorted(locked.get(sec, {}).items()):
            if names and name not in names:
                continue
            for p in validate_memory_contract(name, c):
                ok = False
                lines.append(p)
    return ok, lines


def main(names=None):
    """``ds_lint --mem [program ...]``: regenerate the memory/FLOP
    contracts under the forced tier-1 env and diff against
    ``PROGRAMS.lock``.  Exit 1 on any beyond-tolerance drift,
    undeclared growth, or missing/invalid contract."""
    lock_path = os.environ.get("DSTPU_MEM_LOCKFILE") or None
    progress = lambda msg: print(f"[mem] {msg}", flush=True)
    ok, lines = check_memory_against_lockfile(
        names=set(names) if names else None, progress=progress,
        lock_path=lock_path)
    if ok:
        print("[mem] OK — every memory/FLOP contract holds (HBM "
              "footprints and cost budgets within tolerance)")
        return 0
    print("[mem] MEMORY-CONTRACT BREAK:")
    for line in lines:
        print(f"  {line}")
    print("[mem] intentional? declare growth in "
          "mem_contract.DECLARED_GROWTH, regenerate with ds_lint "
          "--contracts --update, and review the byte stories like any "
          "lockfile bump")
    return 1


if __name__ == "__main__":
    import sys
    from deepspeed_tpu.tools.lint import contract as _c
    _c.ensure_harness_env()
    sys.exit(main(sys.argv[1:] or None))
