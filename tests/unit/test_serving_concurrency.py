"""Concurrency-contract tests for the serving host path
(``docs/tpu_lint.md`` "Concurrency contracts", ``docs/serving.md``
"Network front end").

The acceptance contract: the interleaving stress harness drives
concurrent submit/cancel/status/token_events/metrics traffic against a
stepping scheduler with randomized injected yields at the named lock
seams under ``DSTPU_CONCURRENCY_CHECKS=1`` and proves bitwise-identical
serving outputs, exactly one terminal status per request and ZERO
guarded-field assertion trips; a cancel racing the mirror drain's
retirement of the same rid resolves to exactly one terminal record; the
runtime checker actually trips on an unlocked guarded access; and the
engine-lock wait meter feeds ``stats`` and ``/metrics``."""

import threading
import time
from collections import defaultdict

import numpy as np
import pytest

from deepspeed_tpu.inference.serving.concurrency import (
    ConcurrencyViolation, GUARDED_FIELDS, InstrumentedRLock)
from deepspeed_tpu.runtime.fault import inject
from deepspeed_tpu.tools.lint.interleave_check import (
    _tiny_served_engine, run_interleave_check)


@pytest.fixture(scope="module")
def shared_engine():
    return _tiny_served_engine()


# ------------------------------------------------------------------ #
# The tentpole prover: rule + harness pairing (tier-1)
# ------------------------------------------------------------------ #
def test_interleaving_stress_harness():
    """Randomized-seed yields at every lock seam; bitwise outputs,
    single terminal statuses, zero assertion trips (the harness runs
    its engines under DSTPU_CONCURRENCY_CHECKS=1)."""
    result = run_interleave_check(seeds=(0, 1))
    assert result["ok"], "\n".join(result["problems"])
    for seed, rep in result["per_seed"].items():
        assert rep["completed"] == 6, (seed, rep)
        # the harness generates real contention — the meter must see it
        assert rep["lock_acquires"]["handler"] > 0, rep


def test_runtime_checks_trip_on_unlocked_access(shared_engine,
                                                monkeypatch):
    """The dynamic half of TL008: with checks armed, touching a guarded
    field without the lock raises at the access; the same touch under
    the lock (and the whole public surface) works."""
    monkeypatch.setenv("DSTPU_CONCURRENCY_CHECKS", "1")
    srv = shared_engine.serve()
    assert type(srv).__name__.endswith("+concurrency_checks")
    with pytest.raises(ConcurrencyViolation, match="_queue"):
        srv._queue
    with pytest.raises(ConcurrencyViolation, match="stats"):
        srv.stats["completed"] = 999
    with srv._lock:
        assert len(srv._queue) == 0
    rid = srv.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=2)
    out = srv.drain()
    assert rid in out and srv.result(rid).status == "COMPLETED"
    assert sorted(srv.close()) == []


def test_checks_off_by_default(shared_engine, monkeypatch):
    monkeypatch.delenv("DSTPU_CONCURRENCY_CHECKS", raising=False)
    srv = shared_engine.serve()
    assert not type(srv).__name__.endswith("+concurrency_checks")
    srv._queue                           # plain engine: no assertion
    srv.close()


def test_registry_matches_engine_fields(shared_engine):
    """Every registry field must exist on a live engine — a renamed
    field with a stale registry entry would silently uncheck it."""
    paged_only = {"_slot_pages", "_page_table", "_pool", "_prefix"}
    spec_only = {"_draft_cache", "_draft_lanes"}
    srv = shared_engine.serve()
    with srv._lock:
        for field in GUARDED_FIELDS["ServingEngine"]:
            if field in paged_only and not srv.paged:
                continue
            if field in spec_only and not srv.speculative:
                continue
            assert hasattr(srv, field), \
                f"registry field {field!r} missing on ServingEngine"
    srv.close()


# ------------------------------------------------------------------ #
# Satellite: cancel-vs-retire race (exactly one terminal status)
# ------------------------------------------------------------------ #
def test_cancel_vs_retire_race_single_terminal(shared_engine,
                                               monkeypatch):
    """cancel(rid) from a non-owner thread in the same window the
    scheduler's mirror drain retires that rid: exactly one terminal
    transition (no double _record_terminal, no KeyError), status
    COMPLETED xor CANCELLED — under DSTPU_CONCURRENCY_CHECKS=1 with a
    yield stretching the retirement window."""
    monkeypatch.setenv("DSTPU_CONCURRENCY_CHECKS", "1")
    srv = shared_engine.serve()
    terminal_counts = defaultdict(int)
    orig_rt, orig_fin = srv._record_terminal, srv._finalize

    def counting_rt(req, status, detail):
        terminal_counts[req.rid] += 1
        return orig_rt(req, status, detail)

    def counting_fin(req):
        terminal_counts[req.rid] += 1
        return orig_fin(req)

    srv._record_terminal = counting_rt
    srv._finalize = counting_fin
    inject.reset_injection()
    inject.configure_injection([{"point": "serving.mirror_drain",
                                 "action": "yield", "at": 1, "times": 0,
                                 "seconds": 0.002, "seed": 42}])
    rng = np.random.default_rng(0)
    errors = []
    try:
        for trial in range(25):
            prompt = rng.integers(1, 97, (8,)).astype(np.int32)
            rid = srv.submit(prompt, max_new_tokens=3)
            delay = float(rng.random()) * 0.02

            def cancel_late(rid=rid, delay=delay):
                try:
                    time.sleep(delay)
                    srv.cancel(rid)      # False when retire won the race
                except Exception as e:   # noqa: BLE001 — KeyError = bug
                    errors.append(f"trial {trial}: {type(e).__name__}: "
                                  f"{e}")

            t = threading.Thread(target=cancel_late)
            t.start()
            deadline = time.monotonic() + 60
            while srv.status(rid) not in ("COMPLETED", "CANCELLED") \
                    and time.monotonic() < deadline:
                srv.step()
            t.join(timeout=30)
            status = srv.status(rid)
            assert status in ("COMPLETED", "CANCELLED"), status
            assert terminal_counts[rid] == 1, \
                f"trial {trial}: rid {rid} recorded " \
                f"{terminal_counts[rid]} terminal transitions ({status})"
            assert srv.result(rid) is not None
        assert not errors, errors
    finally:
        inject.reset_injection()
        srv.close()


# ------------------------------------------------------------------ #
# Satellite: lock-contention observability
# ------------------------------------------------------------------ #
def test_lock_wait_observability(shared_engine):
    """Wall time a handler thread spends blocked on the engine lock
    lands in the meter, in stats after the next step, and as labeled
    ``dstpu_serving_lock_wait_seconds`` lines in /metrics."""
    srv = shared_engine.serve()
    rid = srv.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=2)
    held = threading.Event()

    def contender():
        held.wait(timeout=10)
        srv.status(rid)                  # blocks while we hold the lock

    t = threading.Thread(target=contender)
    t.start()
    with srv._lock:
        held.set()
        time.sleep(0.05)                 # the contender waits this out
    t.join(timeout=10)
    assert srv._lock.wait_s["handler"] >= 0.04
    srv.drain()                          # a step refreshes the stats copy
    assert srv.stats["lock_wait_handler_s"] >= 0.04
    assert srv.stats["lock_wait_scheduler_s"] >= 0.0

    from deepspeed_tpu.inference.serving.frontend.transport import \
        ServingHTTPFrontend
    body = ServingHTTPFrontend(srv)._metrics_body().decode()
    assert 'dstpu_serving_lock_wait_seconds{thread_class="handler"}' \
        in body
    assert 'dstpu_serving_lock_wait_seconds{thread_class="scheduler"}' \
        in body
    assert "dstpu_serving_lock_wait_handler_s" in body  # stats export
    srv.close()


def test_instrumented_rlock_condition_compat():
    """The meter composes with threading.Condition (the blocked-submit
    condvar): wait/notify round-trips and the re-acquire after wait()
    counts as lock wait."""
    lock = InstrumentedRLock()
    cond = threading.Condition(lock)
    hits = []

    def waiter():
        with lock:
            cond.wait(timeout=5)
            hits.append(True)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with lock:
        cond.notify_all()
    t.join(timeout=10)
    assert hits == [True]
    assert not lock._is_owned()
    assert sum(lock.acquires.values()) >= 3


# ------------------------------------------------------------------ #
# Satellite: TokenStream bridge drops are counted and logged
# ------------------------------------------------------------------ #
def test_stream_bridge_drop_counted_in_stats(shared_engine):
    srv = shared_engine.serve()
    rid = srv.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=4)

    def dead_bridge(ev):
        raise RuntimeError("Event loop is closed")

    stream = srv.token_events(rid, on_event=dead_bridge)
    srv.drain()
    assert srv.stats["stream_bridge_drops"] == 1, \
        "dropped bridge must be counted exactly once"
    toks, end = stream.tokens(timeout=10)
    assert end["status"] == "COMPLETED" and len(toks) == 4
    srv.close()


# ------------------------------------------------------------------ #
# health_snapshot: the locked /healthz view
# ------------------------------------------------------------------ #
def test_health_snapshot_locked_view(shared_engine):
    srv = shared_engine.serve()
    snap = srv.health_snapshot()
    assert snap["closed"] is False and snap["queue_depth"] == 0
    assert snap["num_slots"] == srv.num_slots
    assert snap["breaker"]["open"] is False
    srv.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=2)
    assert srv.health_snapshot()["queue_depth"] == 1
    srv.drain()
    srv.close()
    assert srv.health_snapshot()["closed"] is True
