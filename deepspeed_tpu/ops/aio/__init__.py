"""Async tensor I/O — Python binding for the native NVMe/disk tier.

TPU-native equivalent of reference ``deepspeed/ops/aio`` + ``csrc/aio/py_lib``
(AsyncIOBuilder, ``op_builder/async_io.py:12``): an ``AsyncIOHandle`` owning a
C++ I/O thread pool (``csrc/aio/aio.cpp``) with async/sync pread/pwrite of
numpy buffers, used by ``runtime/swap_tensor`` for optimizer-state and
parameter offload to NVMe.
"""

import ctypes

import numpy as np

_lib = None
_lib_err = None

AIO_DEFAULT_BLOCK_SIZE = 1 << 20
AIO_DEFAULT_THREADS = 8


def _load():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    try:
        from deepspeed_tpu.ops.native_build import load_library, csrc_path
        lib = load_library("ds_aio", [csrc_path("aio", "aio.cpp")],
                           want_openmp=False)
        lib.aio_handle_create.restype = ctypes.c_void_p
        lib.aio_handle_create.argtypes = [ctypes.c_int, ctypes.c_int64, ctypes.c_int]
        lib.aio_handle_destroy.argtypes = [ctypes.c_void_p]
        lib.aio_handle_num_threads.restype = ctypes.c_int
        lib.aio_handle_num_threads.argtypes = [ctypes.c_void_p]
        lib.aio_handle_block_size.restype = ctypes.c_int64
        lib.aio_handle_block_size.argtypes = [ctypes.c_void_p]
        for fn in ("aio_async_pwrite", "aio_sync_pwrite"):
            f = getattr(lib, fn)
            f.restype = ctypes.c_int
            f.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                          ctypes.c_int64]
        for fn in ("aio_async_pread", "aio_sync_pread"):
            f = getattr(lib, fn)
            f.restype = ctypes.c_int
            f.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                          ctypes.c_int64]
        lib.aio_wait.restype = ctypes.c_int
        lib.aio_wait.argtypes = [ctypes.c_void_p]
        _lib = lib
    except Exception as e:
        _lib_err = e
        _lib = None
    return _lib


def is_available():
    return _load() is not None


def build_error():
    _load()
    return _lib_err


def _buf(a):
    assert a.flags["C_CONTIGUOUS"], "aio buffers must be contiguous"
    return a.ctypes.data_as(ctypes.c_void_p)


class AsyncIOHandle:
    """Reference ``deepspeed_py_aio_handle.cpp`` aio_handle: async/sync
    read/write with a worker pool; ``wait()`` drains all pending requests.

    In-flight buffers must stay alive until ``wait()``; the handle keeps
    references to enforce that.
    """

    def __init__(self, block_size=AIO_DEFAULT_BLOCK_SIZE,
                 queue_depth=None, thread_count=AIO_DEFAULT_THREADS,
                 single_submit=False, overlap_events=True, o_direct=False):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"aio native library unavailable: {_lib_err}")
        self._lib = lib
        self._h = lib.aio_handle_create(int(thread_count), int(block_size),
                                        1 if o_direct else 0)
        self._inflight = []

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.aio_handle_destroy(h)
            self._h = None

    @property
    def num_threads(self):
        return self._lib.aio_handle_num_threads(self._h)

    @property
    def block_size(self):
        return self._lib.aio_handle_block_size(self._h)

    def async_pwrite(self, array: np.ndarray, path: str):
        rc = self._lib.aio_async_pwrite(self._h, path.encode(), _buf(array),
                                        array.nbytes)
        if rc != 0:
            raise IOError(f"aio submit write {path} failed ({rc})")
        self._inflight.append(array)

    def async_pread(self, array: np.ndarray, path: str):
        rc = self._lib.aio_async_pread(self._h, path.encode(), _buf(array),
                                       array.nbytes)
        if rc != 0:
            raise IOError(f"aio submit read {path} failed ({rc})")
        self._inflight.append(array)

    def wait(self):
        rc = self._lib.aio_wait(self._h)
        self._inflight.clear()
        if rc != 0:
            raise IOError(f"aio completed with {-rc} failed requests")
        return rc

    def sync_pwrite(self, array: np.ndarray, path: str):
        rc = self._lib.aio_sync_pwrite(self._h, path.encode(), _buf(array),
                                       array.nbytes)
        if rc != 0:
            raise IOError(f"aio write {path} failed ({rc})")

    def sync_pread(self, array: np.ndarray, path: str):
        rc = self._lib.aio_sync_pread(self._h, path.encode(), _buf(array),
                                      array.nbytes)
        if rc != 0:
            raise IOError(f"aio read {path} failed ({rc})")
