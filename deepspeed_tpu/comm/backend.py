"""Communication backend ABC + XLA backend.

Analog of the reference's ``deepspeed/comm/backend.py:25`` (``Backend`` ABC)
and ``deepspeed/comm/torch.py:39`` (``TorchBackend``).  The only production
backend here is ``XlaBackend``: collective verbs lower to ``jax.lax``
collectives over mesh axes (ICI/DCN), with process bootstrap via
``jax.distributed.initialize``.
"""

import os

from deepspeed_tpu.utils.logging import logger


class Backend:

    def __init__(self, name="backend", rank=0, size=1):
        self.name = name
        self.initialized = False

    def is_initialized(self):
        return self.initialized

    def init_process_group(self):
        self.initialized = True

    def destroy_process_group(self):
        self.initialized = False


class XlaBackend(Backend):
    """Multi-host bootstrap + rank discovery over the JAX runtime.

    The reference's ``TorchBackend.init_process_group`` (``comm/torch.py:84``)
    rendezvouses via MASTER_ADDR/PORT; the JAX runtime does the same through
    ``jax.distributed.initialize`` using the coordinator address.  On a single
    process (or under a CPU-simulated mesh) no bootstrap is needed.
    """

    def __init__(self, timeout=None, init_method=None):
        super().__init__(name="xla")
        self.timeout = timeout
        self.init_method = init_method

    def init_process_group(self):
        import jax
        if self.initialized:
            return
        coordinator = os.environ.get("DSTPU_COORDINATOR_ADDRESS")
        num_processes = os.environ.get("DSTPU_NUM_PROCESSES")
        process_id = os.environ.get("DSTPU_PROCESS_ID")
        if coordinator is not None:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=int(num_processes) if num_processes else None,
                process_id=int(process_id) if process_id else None,
            )
            logger.info(
                f"jax.distributed initialized: process {jax.process_index()}"
                f"/{jax.process_count()} via {coordinator}")
        elif os.environ.get("COORDINATOR_ADDRESS") or int(os.environ.get("DSTPU_AUTO_DIST", "0")):
            # TPU pod slices auto-discover through the TPU runtime metadata.
            jax.distributed.initialize()
        self.initialized = True

    def get_rank(self):
        import jax
        return jax.process_index()

    def get_world_size(self):
        import jax
        return jax.process_count()
