"""Pallas flash attention (fwd + bwd) — the centerpiece training kernel.

TPU-native equivalent of the reference's fused transformer attention kernels
(``csrc/transformer/*.cu`` softmax/dropout/gemm stack behind
``DeepSpeedTransformerLayer``, and the inference ``softmax_context`` op,
``csrc/transformer/inference/csrc/pt_binding.cpp:1934-``).  Instead of
separate gemm+softmax kernels stitched by a C++ scheduler, this is one
online-softmax kernel: O(S) memory, no S×S materialization, MXU-tiled.

Layout: inputs [B, S, H, D] (model-native); kernel operates in [B, H, S, D].
GQA is handled in the BlockSpec index maps (kv head = h * KVH // H) — no
jnp.repeat materialization.

Causal masking skips fully-masked KV blocks via ``pl.when`` predication.
The backward pass uses the saved LSE (log-sum-exp) rows, with two kernels:
one accumulating dq over kv blocks, one accumulating (dk, dv) over q blocks —
the standard flash-attention-2 decomposition.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30
# LSE/delta row vectors are stored with a broadcast 128-lane trailing dim so
# every Pallas block is (sublane, lane)-tileable on real TPU Mosaic (same
# layout trick as jax's reference TPU flash kernel's l/m tensors).
LSE_LANES = 128


def _interpret():
    return jax.default_backend() == "cpu"


def pallas_supported():
    """True when Pallas kernels can run here.

    CPU runs the interpreter; native TPU compiles Mosaic.  Tunneled/relay
    platforms (e.g. 'axon') hang in remote kernel compilation — route those
    to the XLA fallback unless DSTPU_FORCE_FLASH=1.
    """
    import os
    if os.environ.get("DSTPU_FORCE_FLASH") == "1":
        return True
    if os.environ.get("DSTPU_DISABLE_FLASH") == "1":
        return False
    return jax.default_backend() in ("cpu", "tpu")


# --------------------------------------------------------------------- #
# Forward
# --------------------------------------------------------------------- #
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, block_q, block_k, causal, nk, kv_len):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # skip kv blocks strictly above the causal diagonal
    run = (not causal) or (ik * block_k <= iq * block_q + block_q - 1)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)          # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)          # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)          # [bk, d]
        # zero padded tail rows: OOB block reads are undefined, and
        # garbage * 0-probability still poisons the matmul with NaN
        kv_rows = ik * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                          (block_k, 1), 0)
        valid_kv = kv_rows < kv_len
        k = jnp.where(valid_kv, k, 0.0)
        v = jnp.where(valid_kv, v, 0.0)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        cols = ik * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                       (block_q, block_k), 1)
        mask = cols < kv_len           # tail-block padding
        if causal:
            rows = iq * block_q + jax.lax.broadcasted_iota(jnp.int32,
                                                           (block_q, block_k), 0)
            mask = mask & (rows >= cols)
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:, 0:1]                        # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                        # [bq, bk]
        corr = jnp.exp(m_prev - m_new)                # [bq, 1]
        l_new = l_scr[:, 0:1] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_scr[:, 0:1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        # LSE rides a 128-lane trailing dim: Mosaic requires output block
        # shapes tiled (8, 128) on the last two dims, so a [block_q]-shaped
        # row per (b, h) cannot be written directly
        lse_ref[0, 0] = jnp.broadcast_to(m_scr[:, 0:1] + jnp.log(safe_l),
                                         lse_ref.shape[2:])


def _fwd(q, k, v, scale, causal, block_q, block_k):
    B, H, S, D = q.shape
    KVH, Sk = k.shape[1], k.shape[2]
    block_q = min(block_q, S)
    block_k = min(block_k, Sk)
    nq = pl.cdiv(S, block_q)
    nk = pl.cdiv(Sk, block_k)
    grid = (B * H, nq, nk)

    def q_map(bh, iq, ik):
        return (bh // H, bh % H, iq, 0)

    def kv_map(bh, iq, ik):
        return (bh // H, (bh % H) * KVH // H, ik, 0)

    kernel = functools.partial(_fwd_kernel, scale=scale, block_q=block_q,
                               block_k=block_k, causal=causal, nk=nk, kv_len=Sk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), q_map),
            pl.BlockSpec((1, 1, block_k, D), kv_map),
            pl.BlockSpec((1, 1, block_k, D), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), q_map),
            pl.BlockSpec((1, 1, block_q, LSE_LANES),
                         lambda bh, iq, ik: (bh // H, bh % H, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, LSE_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# --------------------------------------------------------------------- #
# Backward
# --------------------------------------------------------------------- #
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, scale, block_q, block_k, causal, nk, kv_len):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = (not causal) or (ik * block_k <= iq * block_q + block_q - 1)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, 0:1]                  # [bq, 1]
        delta = delta_ref[0, 0][:, 0:1]              # [bq, 1]
        kv_rows = ik * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                          (block_k, 1), 0)
        valid_kv = kv_rows < kv_len
        k = jnp.where(valid_kv, k, 0.0)
        v = jnp.where(valid_kv, v, 0.0)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        cols = ik * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                       (block_q, block_k), 1)
        mask = cols < kv_len
        if causal:
            rows = iq * block_q + jax.lax.broadcasted_iota(jnp.int32,
                                                           (block_q, block_k), 0)
            mask = mask & (rows >= cols)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)    # [bq, bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, scale, block_q, block_k, causal, nq, q_len):
    ik = pl.program_id(1)
    iq = pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = (not causal) or (iq * block_q + block_q - 1 >= ik * block_k)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, 0:1]
        delta = delta_ref[0, 0][:, 0:1]
        q_rows = iq * block_q + jax.lax.broadcasted_iota(jnp.int32,
                                                         (block_q, 1), 0)
        valid_q = q_rows < q_len
        q = jnp.where(valid_q, q, 0.0)
        do = jnp.where(valid_q, do, 0.0)
        # delta/lse of padded rows are OOB reads; 0*(garbage) must stay finite
        delta = jnp.where(valid_q, delta, 0.0)
        lse = jnp.where(valid_q, lse, 0.0)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = iq * block_q + jax.lax.broadcasted_iota(jnp.int32,
                                                       (block_q, block_k), 0)
        mask = rows < q_len
        if causal:
            cols = ik * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                           (block_q, block_k), 1)
            mask = mask & (rows >= cols)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)    # [bq, bk]
        dv_scr[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale                 # [bq, bk]
        dk_scr[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, res, do):
    q, k, v, out, lse = res
    B, H, S, D = q.shape
    KVH, Sk = k.shape[1], k.shape[2]
    block_q = min(block_q, S)
    block_k = min(block_k, Sk)
    nq = pl.cdiv(S, block_q)
    nk = pl.cdiv(Sk, block_k)

    delta = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                axis=-1)[..., None],
        lse.shape)

    def q_map(bh, iq, ik):
        return (bh // H, bh % H, iq, 0)

    def kv_map(bh, iq, ik):
        return (bh // H, (bh % H) * KVH // H, ik, 0)

    def lse_map(bh, iq, ik):
        return (bh // H, bh % H, iq, 0)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal, nk=nk, kv_len=Sk),
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), q_map),
            pl.BlockSpec((1, 1, block_k, D), kv_map),
            pl.BlockSpec((1, 1, block_k, D), kv_map),
            pl.BlockSpec((1, 1, block_q, D), q_map),
            pl.BlockSpec((1, 1, block_q, LSE_LANES), lse_map),
            pl.BlockSpec((1, 1, block_q, LSE_LANES), lse_map),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D), q_map),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    # dk/dv computed per (b, h) then reduced over the query-head group for GQA
    def kv_out_map(bh, ik, iq):
        return (bh // H, bh % H, ik, 0)

    def q_map2(bh, ik, iq):
        return (bh // H, bh % H, iq, 0)

    def kv_map2(bh, ik, iq):
        return (bh // H, (bh % H) * KVH // H, ik, 0)

    def lse_map2(bh, ik, iq):
        return (bh // H, bh % H, iq, 0)

    dk_full, dv_full = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal, nq=nq, q_len=S),
        grid=(B * H, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), q_map2),
            pl.BlockSpec((1, 1, block_k, D), kv_map2),
            pl.BlockSpec((1, 1, block_k, D), kv_map2),
            pl.BlockSpec((1, 1, block_q, D), q_map2),
            pl.BlockSpec((1, 1, block_q, LSE_LANES), lse_map2),
            pl.BlockSpec((1, 1, block_q, LSE_LANES), lse_map2),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, D), kv_out_map),
            pl.BlockSpec((1, 1, block_k, D), kv_out_map),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sk, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Sk, D), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    if KVH != H:
        rep = H // KVH
        dk = dk_full.reshape(B, KVH, rep, Sk, D).sum(axis=2)
        dv = dv_full.reshape(B, KVH, rep, Sk, D).sum(axis=2)
    else:
        dk, dv = dk_full, dv_full
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# --------------------------------------------------------------------- #
# Public API
# --------------------------------------------------------------------- #
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_bhsd(q, k, v, scale, causal, block_q, block_k):
    out, _ = _fwd(q, k, v, scale, causal, block_q, block_k)
    return out


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k):
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


_flash_bhsd.defvjp(_flash_fwd_rule, _bwd)


def flash_attention(q, k, v, causal=True, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Flash attention on [B, S, H, D] tensors (model-native layout).

    ``k``/``v`` may have fewer heads (GQA).  Returns [B, S, H, D].
    """
    B, S, H, D = q.shape
    if scale is None:
        scale = 1.0 / float(np.sqrt(D))
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _flash_bhsd(qt, kt, vt, float(scale), bool(causal),
                      int(block_q), int(block_k))
    return out.transpose(0, 2, 1, 3)


# parity alias for the reference inference op name
softmax_context = flash_attention
