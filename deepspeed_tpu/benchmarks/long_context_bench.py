"""Long-context benchmark: sequence-parallel attention scaling.

The reference's long-context story is block-sparse attention (no SP/CP in
v0.9.3); this framework additionally ships Ulysses-style all-to-all and ring
attention over an ``sp`` mesh axis (``parallel/sequence.py``).  This CLI
sweeps sequence lengths through ring/ulysses attention on the live mesh and
prints one JSON line per point: per-chip attention time + effective TFLOP/s.

On a laptop/CI run it uses the 8-device virtual CPU mesh; on a pod slice the
same code rides ICI.
"""

import argparse
import json

import numpy as np


def bench_sp_attention(impl, seq, heads=16, head_dim=64, batch=1, iters=5):
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.parallel.topology import get_topology
    from deepspeed_tpu.parallel.sequence import shard_map_attention

    topo = get_topology()
    sp = topo.get_sequence_parallel_world_size()
    fn = jax.jit(shard_map_attention(topo.mesh, impl=impl, axis="sp",
                                     causal=True))
    rng = np.random.default_rng(0)
    # bf16 is MXU-native on TPU but *emulated* (slow) on CPU meshes
    dtype = jnp.bfloat16 if jax.devices()[0].platform == "tpu" \
        else jnp.float32
    q, k, v = (jnp.asarray(rng.standard_normal((batch, seq, heads, head_dim)),
                           dtype) for _ in range(3))
    from deepspeed_tpu.benchmarks.op_bench import _timeit
    dt = _timeit(lambda *a: fn(*a), (q, k, v), iters)
    flops = 2 * 2 * batch * heads * seq * seq * head_dim / 2   # causal
    return {"impl": impl, "seq": seq, "sp": sp,
            "ms": round(dt * 1e3, 2),
            "TFLOP/s/chip": round(flops / dt / 1e12 / max(sp, 1), 3)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--impls", default="ring,ulysses")
    ap.add_argument("--seqs", default="8192,16384,32768")
    ap.add_argument("--sp", type=int, default=None,
                    help="sp axis size (default: all devices)")
    ap.add_argument("--iters", type=int, default=5)
    args = ap.parse_args()

    import jax
    from deepspeed_tpu.parallel.topology import (get_topology,
                                                 initialize_topology)
    # a default dp-only topology may already be live from import — the
    # sweep needs the sp axis.  An explicit --sp always wins; otherwise
    # re-initialize only when no sp axis is live yet.
    if args.sp:
        initialize_topology(sp=args.sp)
    elif get_topology().get_sequence_parallel_world_size() <= 1:
        initialize_topology(sp=jax.device_count())

    for impl in args.impls.split(","):
        for seq in (int(s) for s in args.seqs.split(",")):
            try:
                print(json.dumps(bench_sp_attention(impl.strip(), seq,
                                                    iters=args.iters)))
            except Exception as e:
                print(json.dumps({"impl": impl, "seq": seq,
                                  "error": str(e)[:200]}))


if __name__ == "__main__":
    main()
