"""Random layerwise token dropping (random-LTD).

Capability parity with reference ``runtime/data_pipeline/data_routing/``:
``RandomLayerTokenDrop`` (``basic_layer.py:14``), ``RandomLTDScheduler``
(``scheduler.py:38``), and the CUDA gather/scatter kernels
(``csrc/random_ltd/``).  TPU-first design:

* The reference needs custom ``token_sort``/``gather_scatter`` CUDA kernels;
  on TPU the same dataflow is ``jax.random.permutation`` + ``jnp.take`` /
  scatter (``.at[].set``) — XLA lowers these to efficient dynamic-gather on
  the VPU, no custom kernel warranted (SURVEY §2.2 random-LTD row).
* Everything is traceable: the kept-token count is *static* per compiled
  program (the scheduler quantises seqlen, so a handful of shapes compile).

``random_ltd_fwd``/``random_ltd_restore`` are the functional core; the
scheduler reproduces the reference's linear seqlen ramp
(``scheduler.py:85 update_seq``).
"""

import jax
import jax.numpy as jnp


def sample_kept_indices(rng, seq_len, keep_len):
    """Uniformly sample ``keep_len`` of ``seq_len`` token positions, sorted
    ascending (the reference sorts kept tokens to preserve order —
    ``csrc/random_ltd/token_sort.cu``)."""
    perm = jax.random.permutation(rng, seq_len)
    return jnp.sort(perm[:keep_len])


def gather_tokens(hidden, idx, batch_first=True):
    """Gather kept tokens: [B,S,H] → [B,K,H] (reference gather_scatter.cu)."""
    if batch_first:
        return jnp.take(hidden, idx, axis=1)
    return jnp.take(hidden, idx, axis=0)


def scatter_tokens(full, dropped_out, idx, batch_first=True):
    """Scatter layer output for kept tokens back into the full-length
    residual stream (dropped tokens keep their input values)."""
    if batch_first:
        return full.at[:, idx, :].set(dropped_out)
    return full.at[idx, :, :].set(dropped_out)


def random_ltd_layer(layer_fn, hidden, rng, keep_len, mask=None,
                     batch_first=True):
    """Run ``layer_fn`` on a random subset of tokens, scattering results back.

    The functional analog of ``RandomLayerTokenDrop.forward``
    (``basic_layer.py:66``): sample indices, gather tokens (and slice the
    attention mask — ``slice_attn_masks.cu``), apply the layer, scatter.
    """
    seq_axis = 1 if batch_first else 0
    seq_len = hidden.shape[seq_axis]
    if keep_len >= seq_len:
        out = layer_fn(hidden, mask) if mask is not None else layer_fn(hidden)
        return out
    idx = sample_kept_indices(rng, seq_len, keep_len)
    sub = gather_tokens(hidden, idx, batch_first)
    if mask is not None:
        sub_mask = jnp.take(jnp.take(mask, idx, axis=-1), idx, axis=-2)
        sub_out = layer_fn(sub, sub_mask)
    else:
        sub_out = layer_fn(sub)
    return scatter_tokens(hidden, sub_out, idx, batch_first)


class BaseScheduler:
    """Reference ``scheduler.py:15``: value schedules shared with curriculum."""

    def __init__(self):
        self.state = {}

    def _fixed_root_get_value(self, global_steps, root_degree=None):
        s = self.state
        if root_degree is None:
            root_degree = s["schedule_config"]["root_degree"]
        next_seq = (min(1.0, global_steps / s["schedule_config"]["total_layer_tokens_steps"])
                    ** (1.0 / root_degree))
        next_seq = int(next_seq * (s["max_value"] - s["min_value"]) + s["min_value"])
        next_seq -= next_seq % s["schedule_config"]["seq_step"]
        return min(next_seq, s["max_value"])

    def get_value(self, global_steps):
        stype = self.state["schedule_type"]
        if stype == "fixed_linear":
            return self._fixed_root_get_value(global_steps, 1)
        if stype == "fixed_root":
            return self._fixed_root_get_value(global_steps)
        raise RuntimeError(f"unsupported schedule type {stype}")


class RandomLTDScheduler(BaseScheduler):
    """Reference ``scheduler.py:38``: ramps the kept-token count from
    ``start_value`` to the full seqlen over ``total_steps``."""

    def __init__(self, config):
        super().__init__()
        self.model_layer_num = config["random_ltd"]["total_layer_num"]
        self.random_ltd_layer_num = config["random_ltd"]["random_ltd_layer_num"]
        self.config_schedule = config["random_ltd"]["random_ltd_schedule"]
        self.max_value = self.config_schedule["max_value"]
        self.min_value = self.config_schedule["min_value"]
        self.current_seq = self.min_value
        self.state = {
            "schedule_type": self.config_schedule["schedule_type"],
            "schedule_config": self.config_schedule["schedule_config"],
            "max_value": self.max_value,
            "min_value": self.min_value,
            "current_seq": self.min_value,
            "global_steps": 0,
        }
        self.reset_to_init()

    def get_total_layer_tokens(self, train_iters):
        total = 0
        for step in range(train_iters):
            self.update_seq(step)
            full_layers = self.model_layer_num - self.random_ltd_layer_num
            total += (full_layers * self.max_value
                      + self.random_ltd_layer_num * self.current_seq)
        return total

    def reset_to_init(self):
        self.current_seq = self.min_value
        self.state["current_seq"] = self.min_value
        self.state["global_steps"] = 0

    def get_current_seq(self):
        return self.current_seq

    def set_current_seq(self, seq_length):
        self.current_seq = seq_length
        self.state["current_seq"] = seq_length

    def get_random_ltd_layer_num(self):
        return self.random_ltd_layer_num

    def get_state(self):
        return self.state

    def set_state(self, state):
        self.state = state
        self.current_seq = state["current_seq"]

    def update_seq(self, global_steps):
        if self.current_seq < self.max_value:
            self.set_current_seq(self.get_value(global_steps))
        self.state["global_steps"] = global_steps
        return self.current_seq

    def state_dict(self):
        return dict(self.state)

    def load_state_dict(self, state_dict):
        self.set_state(dict(state_dict))
