"""Config keys and defaults — the analog of reference
``runtime/constants.py`` (417 LoC of centralized constants).  Only the
constants with behavioral meaning on TPU are kept; every JSON key name matches
the reference schema (``docs/_pages/config-json.md``) so user configs port
unchanged."""

# Batch size triple
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

# Optimizer / scheduler
OPTIMIZER = "optimizer"
SCHEDULER = "scheduler"
OPTIMIZER_TYPE_DEFAULT = None
MAX_GRAD_NORM = "max_grad_norm"

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
FUSED_ADAM_OPTIMIZER = "fusedadam"
CPU_ADAM_OPTIMIZER = "cpuadam"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
SGD_OPTIMIZER = "sgd"
ADAGRAD_OPTIMIZER = "adagrad"
LION_OPTIMIZER = "lion"

DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM_OPTIMIZER, CPU_ADAM_OPTIMIZER,
    LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER,
    ONEBIT_LAMB_OPTIMIZER, SGD_OPTIMIZER, ADAGRAD_OPTIMIZER, LION_OPTIMIZER,
]

# Precision
FP16 = "fp16"
BF16 = "bf16"
FP32 = "fp32"

# Gradients
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0
PRESCALE_GRADIENTS = "prescale_gradients"
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
SPARSE_GRADIENTS = "sparse_gradients"

# ZeRO
ZERO_OPTIMIZATION = "zero_optimization"

# Logging
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
DUMP_STATE = "dump_state"

# Subsystems
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
FLOPS_PROFILER = "flops_profiler"
COMMS_LOGGER = "comms_logger"
MONITOR_TENSORBOARD = "tensorboard"
MONITOR_WANDB = "wandb"
MONITOR_CSV = "csv_monitor"
ELASTICITY = "elasticity"
AUTOTUNING = "autotuning"
COMPRESSION_TRAINING = "compression_training"
DATA_EFFICIENCY = "data_efficiency"
CURRICULUM_LEARNING_LEGACY = "curriculum_learning"
AIO = "aio"

# Parallelism (TPU-native additions keep the same config spine)
TENSOR_PARALLEL = "tensor_parallel"
PIPELINE_PARALLEL = "pipeline"
SEQUENCE_PARALLEL = "sequence_parallel"

PIPE_REPLICATED = "ds_pipe_replicated"

ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"
