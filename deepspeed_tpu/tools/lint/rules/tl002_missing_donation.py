"""TL002 — jit over large buffers without donation.

A ``jax.jit``/``pjit`` whose wrapped function takes a known large-buffer
parameter (params / opt_state / kv_cache / cache / grads / acc) but declares
no ``donate_argnums``/``donate_argnames`` holds BOTH the input and output
copy of that buffer live across the call — at 2.7B params that is the
difference between fitting and OOM (the round-5 split-prefill fix in git
history was exactly a missing cache donation).

The rule resolves the wrapped callable when it can: a lambda inline, a local
``def`` by name, or a method of a class in the same module.
"""

import ast

from deepspeed_tpu.tools.lint.core import Finding, dotted_name, rule

JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.experimental.pjit.pjit"}
LARGE_BUFFER_PARAMS = {"params", "opt_state", "opt_states", "kv_cache",
                       "cache", "grads", "grad_acc", "acc",
                       "master_params"}
_DONATE_KEYS = {"donate_argnums", "donate_argnames"}


def _jit_callee(call):
    """(wrapped_expr, kwargs) if ``call`` is a jit/pjit application."""
    name = dotted_name(call.func)
    if name in JIT_NAMES and call.args:
        return call.args[0], call.keywords
    # functools.partial(jax.jit, ...) has no positional fn — decorator form
    return None, None


def is_jit_call(call):
    return dotted_name(call.func) in JIT_NAMES


def jit_decorator_kwargs(node):
    """kwargs of a @jax.jit / @partial(jax.jit, ...) decorator, else None."""
    for dec in getattr(node, "decorator_list", []):
        if dotted_name(dec) in JIT_NAMES:
            return []
        if isinstance(dec, ast.Call):
            name = dotted_name(dec.func)
            if name in JIT_NAMES:
                return dec.keywords
            if name in ("functools.partial", "partial") and dec.args and \
                    dotted_name(dec.args[0]) in JIT_NAMES:
                return dec.keywords
    return None


def _params_of(expr, module):
    """Parameter names of the callable expression, or None if unresolvable."""
    if isinstance(expr, ast.Lambda):
        a = expr.args
        return [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]
    name = None
    if isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Attribute):
        name = expr.attr
    if name is None:
        return None
    for fn in module.functions:
        if fn.name == name:
            return fn.params
    return None


def _large(params):
    return sorted(set(p.lower() for p in params) & LARGE_BUFFER_PARAMS)


@rule("TL002", "jit over large buffers without donation")
def check(module):
    # call form: jax.jit(f, ...)
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        wrapped, keywords = _jit_callee(node)
        if wrapped is None:
            continue
        if any(kw.arg in _DONATE_KEYS for kw in keywords):
            continue
        params = _params_of(wrapped, module)
        if params is None:
            continue
        big = _large(params)
        if big:
            yield Finding(
                "TL002", module.path, node.lineno, node.col_offset,
                f"jit of function with large-buffer parameter(s) "
                f"{', '.join(big)} but no donate_argnums — input and output "
                f"copies stay live together; donate or annotate why not")
    # decorator form: @jax.jit / @partial(jax.jit, ...)
    for fn in module.functions:
        keywords = jit_decorator_kwargs(fn.node)
        if keywords is None:
            continue
        if any(kw.arg in _DONATE_KEYS for kw in keywords):
            continue
        big = _large(fn.params)
        if big:
            yield Finding(
                "TL002", module.path, fn.node.lineno, fn.node.col_offset,
                f"@jit on '{fn.name}' with large-buffer parameter(s) "
                f"{', '.join(big)} but no donate_argnums — donate or "
                f"annotate why not")
