"""Run a command on every host in the hostfile (reference ``bin/ds_ssh``).

Package-level entry point so the installed console script works without
repo-root ``sys.path`` tricks; ``bin/ds_ssh`` delegates here.
"""

import argparse
import subprocess
import sys

from deepspeed_tpu.launcher.runner import fetch_hostfile


def main():
    p = argparse.ArgumentParser(description="run a command on all hosts")
    p.add_argument("-H", "--hostfile", default="/job/hostfile")
    p.add_argument("command", nargs=argparse.REMAINDER)
    args = p.parse_args()
    hosts = fetch_hostfile(args.hostfile) or {"localhost": 1}
    cmd = " ".join(args.command) or "hostname"
    rc = 0
    for h in hosts:
        print(f"=== {h} ===", flush=True)
        r = subprocess.run(["ssh", "-o", "StrictHostKeyChecking=no", h, cmd])
        rc = rc or r.returncode
    sys.exit(rc)


if __name__ == "__main__":
    main()
