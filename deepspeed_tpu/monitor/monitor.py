"""Experiment monitoring — parity with reference ``deepspeed/monitor/``:
``Monitor`` ABC (``monitor.py:13``), ``MonitorMaster`` fan-out
(``monitor.py:29``) over TensorBoard / WandB / CSV backends.

Events are ``(name, value, global_step)`` tuples via ``write_events``,
exactly the reference protocol, so engine-side call sites port 1:1."""

import os
import csv as _csv
from abc import ABC, abstractmethod

from deepspeed_tpu.utils.logging import logger


class Monitor(ABC):

    def __init__(self, monitor_config):
        self.monitor_config = monitor_config

    @abstractmethod
    def write_events(self, event_list):
        ...


class TensorBoardMonitor(Monitor):

    def __init__(self, tensorboard_config):
        super().__init__(tensorboard_config)
        self.enabled = tensorboard_config.enabled
        self.summary_writer = None
        if self.enabled:
            try:
                from torch.utils.tensorboard import SummaryWriter
                log_dir = os.path.join(tensorboard_config.output_path or "./runs",
                                       tensorboard_config.job_name)
                self.summary_writer = SummaryWriter(log_dir=log_dir)
            except ImportError:
                logger.warning("tensorboard not available; TensorBoardMonitor disabled")
                self.enabled = False

    def write_events(self, event_list, flush=True):
        if not self.enabled or self.summary_writer is None:
            return
        for event in event_list:
            self.summary_writer.add_scalar(*event)
        if flush:
            self.summary_writer.flush()


class WandbMonitor(Monitor):

    def __init__(self, wandb_config):
        super().__init__(wandb_config)
        self.enabled = wandb_config.enabled
        if self.enabled:
            try:
                import wandb
                self.wandb = wandb
                wandb.init(project=wandb_config.project, group=wandb_config.group,
                           entity=wandb_config.team)
            except ImportError:
                logger.warning("wandb not available; WandbMonitor disabled")
                self.enabled = False

    def write_events(self, event_list):
        if not self.enabled:
            return
        for name, value, step in event_list:
            self.wandb.log({name: value}, step=int(step))


class csvMonitor(Monitor):

    def __init__(self, csv_config):
        super().__init__(csv_config)
        self.enabled = csv_config.enabled
        self.output_path = csv_config.output_path or "./csv_monitor"
        self.job_name = csv_config.job_name
        self.filehandles = {}
        if self.enabled:
            os.makedirs(os.path.join(self.output_path, self.job_name), exist_ok=True)

    def write_events(self, event_list):
        if not self.enabled:
            return
        for name, value, step in event_list:
            safe = name.replace("/", "_")
            path = os.path.join(self.output_path, self.job_name, f"{safe}.csv")
            new = not os.path.exists(path)
            with open(path, "a", newline="") as f:
                w = _csv.writer(f)
                if new:
                    w.writerow(["step", safe])
                w.writerow([int(step), float(value)])


class MonitorMaster(Monitor):
    """Fan events out to all enabled backends; only JAX process 0 writes
    (reference gates on rank 0, ``monitor.py:29``)."""

    def __init__(self, monitor_config):
        super().__init__(monitor_config)
        import jax
        self.enabled = monitor_config.enabled
        self.backends = []
        if jax.process_index() == 0:
            if monitor_config.tensorboard.enabled:
                self.backends.append(TensorBoardMonitor(monitor_config.tensorboard))
            if monitor_config.wandb.enabled:
                self.backends.append(WandbMonitor(monitor_config.wandb))
            if monitor_config.csv_monitor.enabled:
                self.backends.append(csvMonitor(monitor_config.csv_monitor))

    def write_events(self, event_list):
        for backend in self.backends:
            backend.write_events(event_list)
