"""SparseSelfAttention module.

Parity with reference
``deepspeed/ops/sparse_attention/sparse_self_attention.py:12``
(``SparseSelfAttention(Module)``) and the drop-in helpers in
``sparse_attention_utils.py``: applies block-sparse attention under a
``SparsityConfig``.  Functional core + a thin flax wrapper so it slots into
model definitions the way the reference slots into BERT self-attention.
"""

import flax.linen as nn
import jax.numpy as jnp

from deepspeed_tpu.ops.sparse_attention.block_sparse import (
    block_sparse_attention)
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    SparsityConfig, FixedSparsityConfig)


class SparseAttentionFn:
    """Callable holding a config + cached layouts per seq_len (the reference
    caches master_layout/ops per seq_len too)."""

    def __init__(self, sparsity_config=None, key_padding_mask_mode="add",
                 attn_mask_mode="mul"):
        self.sparsity_config = sparsity_config or FixedSparsityConfig(num_heads=4)
        assert isinstance(self.sparsity_config, SparsityConfig)
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self._layouts = {}

    def get_layout(self, seq_len):
        from deepspeed_tpu.ops.sparse_attention.block_sparse import cached_layout
        return cached_layout(self.sparsity_config, seq_len)

    def __call__(self, query, key, value, key_padding_mask=None,
                 attn_mask=None):
        """query/key/value: [B, S, H, D].  ``key_padding_mask`` [B, S]
        (1 = attend) is folded into the kernel via a k-bias feature — see
        ``block_sparse_attention``."""
        B, S, H, D = query.shape
        layout = self.get_layout(S)
        causal = getattr(self.sparsity_config, "attention",
                         "bidirectional") == "unidirectional"
        return block_sparse_attention(query, key, value, layout,
                                      self.sparsity_config.block,
                                      causal=causal,
                                      key_padding_mask=key_padding_mask)


class SparseSelfAttention(nn.Module):
    """Flax module: projects hidden → q,k,v, applies block-sparse attention,
    projects back (the reference module takes pre-projected q,k,v; this
    wrapper covers the full BertSparseSelfAttention use too)."""

    hidden_size: int
    num_heads: int
    sparsity_config: SparsityConfig = None
    dtype: str = "float32"

    @nn.compact
    def __call__(self, hidden, key_padding_mask=None):
        B, S, _ = hidden.shape
        H = self.num_heads
        D = self.hidden_size // H
        dt = jnp.dtype(self.dtype)
        qkv = nn.Dense(3 * self.hidden_size, dtype=dt, name="qkv")(hidden)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        fn = SparseAttentionFn(self.sparsity_config
                               or FixedSparsityConfig(num_heads=H))
        out = fn(q.reshape(B, S, H, D), k.reshape(B, S, H, D),
                 v.reshape(B, S, H, D), key_padding_mask=key_padding_mask)
        out = out.reshape(B, S, self.hidden_size)
        return nn.Dense(self.hidden_size, dtype=dt, name="out")(out)
