"""Topology tests — analog of reference ``tests/unit/runtime/pipe/test_topology.py``.

The PartitionSpec-helper tests at the bottom validate every helper's spec
against a LIVE 8-device mesh placement (``jax.device_put`` +
``addressable_shards``), not just spec equality — a helper that names the
wrong axis produces the wrong shard shapes here instead of a silent
replication three layers up."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.topology import (
    ParallelTopology, initialize_topology, get_topology, AXIS_ORDER, DP_AXES)


def test_default_topology_all_dp():
    topo = initialize_topology()
    assert topo.world_size == 8
    assert topo.dp == 8
    assert topo.tp == topo.pp == topo.sp == topo.ep == 1
    assert topo.mesh.axis_names == AXIS_ORDER


def test_2d_topology():
    topo = initialize_topology(tp=2)
    assert topo.dp == 4
    assert topo.get_model_parallel_world_size() == 2
    assert topo.get_data_parallel_world_size() == 4


def test_3d_topology():
    topo = initialize_topology(tp=2, pp=2)
    assert topo.dp == 2
    assert topo.world_size == 8


def test_expert_topology():
    topo = initialize_topology(ep=4)
    assert topo.dp == 8
    assert topo.edp == 2
    assert topo.axis_size("ep") == 4


def test_sequence_topology():
    topo = initialize_topology(sp=2, tp=2)
    assert topo.sp == 2
    assert topo.dp == 2


def test_invalid_topology_raises():
    with pytest.raises(ValueError):
        ParallelTopology(dp=16, tp=2, devices=jax.devices())


def test_ep_must_divide_dp():
    with pytest.raises(ValueError):
        ParallelTopology(dp=4, ep=3, devices=jax.devices())


def test_batch_spec():
    topo = initialize_topology()
    assert topo.data_spec() == P(DP_AXES)


# --------------------------------------------------------------------- #
# PartitionSpec helpers vs LIVE mesh placement (8 virtual devices)
# --------------------------------------------------------------------- #
def _place(topo, spec, shape, dtype=jnp.float32):
    """device_put under the helper's spec; returns the placed array."""
    arr = jnp.zeros(shape, dtype)
    return jax.device_put(arr, NamedSharding(topo.mesh, spec))


def _live_shard_shapes(placed):
    return {s.data.shape for s in placed.addressable_shards}


@pytest.mark.parametrize("kw,global_shape,want_shard", [
    # pure dp=8: batch dim splits 8 ways
    (dict(), (16, 32), (2, 32)),
    # tp=2 -> dp=4: batch splits over the compound (mdp, edp, ep) = 4
    (dict(tp=2), (16, 32), (4, 32)),
    # ep=2 carves expert groups out of dp: batch still splits over all 8
    (dict(ep=2), (16, 32), (2, 32)),
    # MiCS mdp=2 replica groups: batch is STILL fully dp-sharded (grads
    # reduce across groups; only param sharding is group-local)
    (dict(mics=4), (16, 32), (2, 32)),
])
def test_data_spec_places_batch_sharded(kw, global_shape, want_shard):
    topo = initialize_topology(**kw)
    placed = _place(topo, topo.data_spec(), global_shape)
    assert topo.shard_shape(topo.data_spec(), global_shape) == want_shard
    assert _live_shard_shapes(placed) == {want_shard}


def test_data_spec_seq_dim_over_sp():
    """sp=2: dim0 carries the dp product (2 here with tp=2), dim1 the
    sequence — both verified on the live mesh."""
    topo = initialize_topology(sp=2, tp=2)
    spec = topo.data_spec(seq_dim=1)
    want = (8, 32, 16)
    placed = _place(topo, spec, (16, 64, 16))
    assert topo.shard_shape(spec, (16, 64, 16)) == want
    assert _live_shard_shapes(placed) == {want}
    # without an sp axis the seq dim stays whole
    topo = initialize_topology(tp=2)
    spec = topo.data_spec(seq_dim=1)
    assert topo.shard_shape(spec, (16, 64, 16)) == (4, 64, 16)


def test_batch_spec_sp_routes_batch_and_seq():
    """batch_spec under sp>1: dim0 over (mdp, edp, ep), dim1 over sp —
    the Ulysses layout the sequence-parallel plans assume."""
    topo = initialize_topology(sp=2)
    spec = topo.batch_spec()
    placed = _place(topo, spec, (8, 64))
    assert topo.shard_shape(spec, (8, 64)) == (2, 32)
    assert _live_shard_shapes(placed) == {(2, 32)}
    # dense topology: one batch axis over the full dense grad group
    topo = initialize_topology()
    assert topo.shard_shape(topo.batch_spec(), (8, 64)) == (1, 64)
    # extra_dims pad with None (replicated feature dims)
    topo2 = initialize_topology(tp=2)
    spec2 = topo2.batch_spec(extra_dims=2)
    assert topo2.shard_shape(spec2, (8, 4, 4)) == (2, 4, 4)


def test_replicated_spec_is_fully_replicated_everywhere():
    """replicated_spec() must mean ONE full copy per device on every
    topology — and shards_per_device exposes exactly the TL010 smell
    (1.0 = full replication) the sharding lint flags statically."""
    for kw in (dict(), dict(tp=2), dict(sp=2), dict(ep=2), dict(mics=4)):
        topo = initialize_topology(**kw)
        placed = _place(topo, topo.replicated_spec(), (4, 8))
        assert _live_shard_shapes(placed) == {(4, 8)}
        assert len(placed.addressable_shards) == 8
        assert topo.shards_per_device(topo.replicated_spec(),
                                      (4, 8)) == 1.0
    # a sharded batch spec holds 1/dp of the array per device
    topo = initialize_topology()
    assert topo.shards_per_device(topo.data_spec(), (16, 32)) == \
        pytest.approx(1 / 8)


def test_axis_sizes_reports_live_mesh():
    topo = initialize_topology(tp=2, sp=2)
    assert topo.axis_sizes() == {"pp": 1, "mdp": 1, "edp": 2, "ep": 1,
                                 "sp": 2, "tp": 2}
