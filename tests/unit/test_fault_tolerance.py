"""Fault-tolerance tests — crash-atomic checkpoints, fault injection,
auto-resume (docs/fault_tolerance.md).

The centerpiece is the kill-and-resume proof: a subprocess driver
(``fault_driver.py``) is killed via ``os._exit`` at every registered
checkpoint injection seam, relaunched, and its merged loss trajectory must
be bitwise-identical to an uninterrupted run — the property that makes
preemptible TPU capacity usable for training at all.
"""

import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.runtime.fault import inject
from deepspeed_tpu.runtime.fault.manifest import (
    MANIFEST_NAME, build_manifest, gc_checkpoints, list_tags,
    newest_valid_tag, read_manifest, verify_manifest, write_manifest)
from deepspeed_tpu.runtime.fault.retry import backoff_delay, retry_call
from deepspeed_tpu.runtime.fault.supervisor import (run_resilient,
                                                    elastic_resume_config)
from simple_model import SimpleModel, random_batch

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
DRIVER = os.path.join(REPO, "tests", "unit", "fault_driver.py")


@pytest.fixture(autouse=True)
def _disarm_injection():
    inject.reset_injection()
    yield
    inject.reset_injection()


def fault_config(**over):
    fault = {"enabled": True, "checksum": "crc32",
             "backoff_base_secs": 0.01, "backoff_max_secs": 0.05}
    fault.update(over.pop("fault", {}))
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2},
        "seed": 7,
        "fault": fault,
    }
    cfg.update(over)
    return cfg


def make_engine(**over):
    engine, *_ = deepspeed_tpu.initialize(model=SimpleModel(hidden_dim=16),
                                          config=fault_config(**over))
    return engine


def train_steps(engine, n):
    for _ in range(n):
        loss = engine(random_batch(batch_size=16, seed=engine.global_steps))
        engine.backward(loss)
        engine.step()
    return loss


def fresh_engine(**over):
    from deepspeed_tpu.parallel.topology import reset_topology
    reset_topology()
    return make_engine(**over)


# --------------------------------------------------------------------- #
# Manifest + atomic primitives
# --------------------------------------------------------------------- #
def test_manifest_build_verify_corrupt(tmp_path):
    d = tmp_path / "tag1"
    (d / "sub").mkdir(parents=True)
    (d / "a.bin").write_bytes(b"x" * 1000)
    (d / "sub" / "b.bin").write_bytes(b"y" * 500)
    m = build_manifest(str(d), "tag1", step_meta={"global_steps": 3})
    write_manifest(str(d), m)
    assert set(m["files"]) == {"a.bin", os.path.join("sub", "b.bin")}
    assert read_manifest(str(d))["step"]["global_steps"] == 3
    assert verify_manifest(str(d)) == []
    # same-size corruption: only the checksum notices
    with open(d / "sub" / "b.bin", "r+b") as f:
        f.seek(100)
        f.write(b"Z")
    assert verify_manifest(str(d), deep=False) == []
    problems = verify_manifest(str(d), deep=True)
    assert len(problems) == 1 and "b.bin" in problems[0]
    # truncation: the shallow size scan catches it
    with open(d / "a.bin", "r+b") as f:
        f.truncate(10)
    assert any("a.bin" in p for p in verify_manifest(str(d), deep=False))
    # a missing manifest is its own problem
    os.remove(d / MANIFEST_NAME)
    assert verify_manifest(str(d)) == [f"{MANIFEST_NAME} missing or "
                                       "unparseable"]


def test_newest_valid_tag_walkback(tmp_path):
    for i, tag in enumerate(["global_step2", "global_step4"]):
        d = tmp_path / tag
        d.mkdir()
        (d / "data.bin").write_bytes(bytes(100 + i))
        write_manifest(str(d), build_manifest(
            str(d), tag, step_meta={"global_steps": 2 * (i + 1)}))
    assert newest_valid_tag(str(tmp_path)) == "global_step4"
    # corrupt the newest -> walk back
    with open(tmp_path / "global_step4" / "data.bin", "r+b") as f:
        f.seek(0)
        f.write(b"\xff")
    assert newest_valid_tag(str(tmp_path)) == "global_step2"
    # staging orphans are never candidates
    (tmp_path / "global_step9.tmp").mkdir()
    assert newest_valid_tag(str(tmp_path)) == "global_step2"


def test_backoff_delay_capped_and_jittered():
    assert backoff_delay(1, base=1.0, jitter=0.0) == 1.0
    assert backoff_delay(4, base=1.0, max_delay=5.0, jitter=0.0) == 5.0
    d = backoff_delay(2, base=1.0, jitter=0.5)
    assert 2.0 <= d <= 3.0
    # deterministic for a fixed (attempt, pid)
    assert d == backoff_delay(2, base=1.0, jitter=0.5)


def test_permanent_os_errors_not_retried():
    """A typo'd path or permissions problem does not heal with backoff:
    retry_call re-raises permanent errno classes immediately."""
    calls = []

    def missing():
        calls.append(1)
        raise FileNotFoundError("no such file")

    with pytest.raises(FileNotFoundError):
        retry_call(missing, retries=3, base=0.0, jitter=0.0)
    assert len(calls) == 1, "permanent errors must not be retried"


def test_supervisor_surfaces_permanent_step_errors(tmp_path):
    """A deterministic FileNotFoundError inside step_fn is a BUG — the
    supervisor must surface it, not mask it behind resume churn."""
    engine = make_engine()
    train_steps(engine, 1)

    def broken_step(engine):
        raise FileNotFoundError("/nonexistent/data.bin")

    with pytest.raises(FileNotFoundError):
        run_resilient(engine, broken_step, str(tmp_path), max_steps=3)


def test_side_tags_only_dir_is_fresh_start(tmp_path):
    """A directory holding ONLY save_latest=False side checkpoints is a
    fresh start for auto-resume (warn + nothing loaded), not a 'no valid
    checkpoint' crash."""
    engine = make_engine()
    train_steps(engine, 2)
    engine.save_checkpoint(str(tmp_path), tag="debug_only",
                           save_latest=False)
    e2 = fresh_engine()
    path, state = e2.load_checkpoint(str(tmp_path))
    assert path is None and state == {}
    # run_resilient on the same dir trains from scratch instead of dying
    status, info = run_resilient(e2, _step_fn, str(tmp_path), max_steps=2)
    assert status == "done" and e2.global_steps == 2


def test_retry_call_bounded():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise IOError("transient")
        return "ok"

    assert retry_call(flaky, retries=3, base=0.0, jitter=0.0) == "ok"
    assert len(calls) == 3
    calls.clear()
    with pytest.raises(IOError):
        retry_call(flaky, retries=1, base=0.0, jitter=0.0)
    assert len(calls) == 2  # 1 call + 1 retry, then give up


# --------------------------------------------------------------------- #
# Engine checkpoint protocol
# --------------------------------------------------------------------- #
def test_atomic_save_layout_and_latest(tmp_path):
    engine = make_engine()
    train_steps(engine, 2)
    engine.save_checkpoint(str(tmp_path))
    names = sorted(os.listdir(tmp_path))
    assert names == ["global_step2", "latest"]
    assert (tmp_path / "latest").read_text() == "global_step2"
    # no staging or temp droppings anywhere
    for dirpath, dirnames, filenames in os.walk(tmp_path):
        for n in dirnames + filenames:
            assert ".tmp" not in n and ".old." not in n, n
    assert verify_manifest(str(tmp_path / "global_step2")) == []
    fp = read_manifest(str(tmp_path / "global_step2"))["fingerprint"]
    assert fp["device_count"] == jax.device_count()


def test_load_missing_arrays_is_clear_error_not_typeerror(tmp_path):
    """Satellite: the seed indexed arrays["module"] with arrays=None and
    died on a TypeError when the 'arrays' dir was missing.  The error is
    CheckpointCorrupt (NOT an OSError): the retry policy treats OSErrors
    as transient, and this condition is permanent."""
    import shutil
    from deepspeed_tpu.runtime.fault.manifest import CheckpointCorrupt
    engine = make_engine(fault={"enabled": False})
    train_steps(engine, 1)
    engine.save_checkpoint(str(tmp_path))
    shutil.rmtree(tmp_path / "global_step1" / "state" / "arrays")
    e2 = fresh_engine(fault={"enabled": False})
    with pytest.raises(CheckpointCorrupt, match="arrays"):
        e2.load_checkpoint(str(tmp_path))


def test_reserved_tag_names_rejected(tmp_path):
    """Tags colliding with the staging namespace would be destroyed by
    the next GC pass — save refuses them up front."""
    engine = make_engine()
    train_steps(engine, 1)
    with pytest.raises(ValueError, match="staging namespace"):
        engine.save_checkpoint(str(tmp_path), tag="run1.tmp")
    with pytest.raises(ValueError, match="staging namespace"):
        engine.save_checkpoint(str(tmp_path), tag="v1.old.2")


def test_save_latest_false_tags_do_not_hijack_resume(tmp_path):
    """A side checkpoint saved with save_latest=False (debug dump) must
    not be picked by auto-resume even though it is newer."""
    engine = make_engine()
    train_steps(engine, 2)
    engine.save_checkpoint(str(tmp_path))                      # step 2
    train_steps(engine, 2)
    engine.save_checkpoint(str(tmp_path), tag="debug_dump",
                           save_latest=False)                  # step 4
    e2 = fresh_engine()
    e2.load_checkpoint(str(tmp_path))
    assert e2.global_steps == 2, \
        "auto-resume must skip advance_latest=false tags"
    # the side tag stays explicitly loadable
    e3 = fresh_engine()
    e3.load_checkpoint(str(tmp_path), tag="debug_dump")
    assert e3.global_steps == 4


def test_corrupt_and_partial_tags_walk_back_on_load(tmp_path):
    """Acceptance: a corrupted-shard checkpoint is detected by manifest
    verification and load falls back to the previous valid tag; a
    data-partial tag (missing arrays) walks back the same way."""
    import shutil
    engine = make_engine()
    train_steps(engine, 2)
    engine.save_checkpoint(str(tmp_path))
    w2 = np.asarray(jax.tree.leaves(engine.params)[0], np.float32)
    train_steps(engine, 2)
    engine.save_checkpoint(str(tmp_path))

    # corrupt one array shard of the newest tag (size-preserving)
    newest = tmp_path / "global_step4"
    target, size = None, -1
    for dirpath, _d, filenames in os.walk(newest / "state" / "arrays"):
        for n in filenames:
            p = os.path.join(dirpath, n)
            if os.path.getsize(p) > size:
                target, size = p, os.path.getsize(p)
    with open(target, "r+b") as f:
        f.seek(size // 2)
        f.write(b"\xde\xad\xbe\xef")

    e2 = fresh_engine()
    e2.load_checkpoint(str(tmp_path))
    assert e2.global_steps == 2, "load must walk back to global_step2"
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(e2.params)[0], np.float32), w2)

    # now ALSO gut the older tag's arrays -> no valid tag at all
    shutil.rmtree(tmp_path / "global_step2" / "state" / "arrays")
    with open(target, "r+b") as f:   # keep newest corrupt
        f.seek(0)
        f.write(b"\xff")
    e3 = fresh_engine()
    with pytest.raises(RuntimeError, match="no valid checkpoint"):
        e3.load_checkpoint(str(tmp_path))


def test_transient_save_ioerror_retries(tmp_path):
    engine = make_engine()
    train_steps(engine, 1)
    specs = inject.configure_injection(
        {"point": "ckpt.save_io", "action": "raise", "times": 2})
    assert engine.save_checkpoint(str(tmp_path)) is True
    assert specs[0].fired == 2, "save must have retried through 2 faults"
    assert verify_manifest(str(tmp_path / "global_step1")) == []


def test_keep_last_n_retention_and_orphan_gc(tmp_path):
    engine = make_engine(fault={"keep_last_n": 2})
    (tmp_path / "global_step99.tmp").mkdir(parents=True)  # stale orphan
    for _ in range(4):
        train_steps(engine, 1)
        engine.save_checkpoint(str(tmp_path))
    tags = list_tags(str(tmp_path))
    assert tags == ["global_step4", "global_step3"]
    assert not (tmp_path / "global_step99.tmp").exists()
    assert (tmp_path / "latest").read_text() == "global_step4"


def test_explicit_tag_failure_raises_not_walks_back(tmp_path):
    """An explicitly requested tag that fails verification must raise —
    silently substituting an older tag's weights would poison evals;
    walk-back is the auto-resume (tag=None) contract only."""
    from deepspeed_tpu.runtime.fault.manifest import CheckpointCorrupt
    engine = make_engine()
    train_steps(engine, 2)
    engine.save_checkpoint(str(tmp_path))
    train_steps(engine, 2)
    engine.save_checkpoint(str(tmp_path))
    target, size = None, -1
    for dirpath, _d, filenames in os.walk(
            tmp_path / "global_step4" / "state" / "arrays"):
        for n in filenames:
            p = os.path.join(dirpath, n)
            if os.path.getsize(p) > size:
                target, size = p, os.path.getsize(p)
    with open(target, "r+b") as f:
        f.seek(size // 2)
        f.write(b"\xbe\xef")
    e2 = fresh_engine()
    with pytest.raises(CheckpointCorrupt, match="global_step4"):
        e2.load_checkpoint(str(tmp_path), tag="global_step4")
    # auto-resume still walks back fine
    e2.load_checkpoint(str(tmp_path))
    assert e2.global_steps == 2


def test_gc_never_deletes_last_valid_tag(tmp_path):
    """Retention must not leave the directory without a loadable
    checkpoint: when corrupt newer tags outrank a valid older one, the
    newest valid tags survive too."""
    for step in (2, 4):
        d = tmp_path / f"global_step{step}"
        d.mkdir()
        (d / "data.bin").write_bytes(b"x" * 64)
        write_manifest(str(d), build_manifest(
            str(d), d.name, step_meta={"global_steps": step}))
    # newest tag truncated -> invalid (shallow-detectable)
    with open(tmp_path / "global_step4" / "data.bin", "r+b") as f:
        f.truncate(3)
    removed = gc_checkpoints(str(tmp_path), keep_last_n=1)
    assert "global_step2" not in removed
    assert newest_valid_tag(str(tmp_path)) == "global_step2"


def test_gc_restores_orphaned_backup(tmp_path):
    """A same-tag re-publish that dies between moving the old tag aside
    and promoting the new one leaves only <tag>.old.<pid> — GC must
    restore the valid backup, never delete the only copy; the dry-run
    plan must match."""
    d = tmp_path / "global_step2.old.1234"
    d.mkdir()
    (d / "data.bin").write_bytes(b"y" * 32)
    write_manifest(str(d), build_manifest(
        str(d), "global_step2", step_meta={"global_steps": 2}))
    plan = gc_checkpoints(str(tmp_path), keep_last_n=0, dry_run=True)
    assert plan == ["restore:global_step2.old.1234"]
    assert list_tags(str(tmp_path)) == []          # dry run touched nothing
    actions = gc_checkpoints(str(tmp_path), keep_last_n=0)
    assert actions == plan, "dry-run plan must match the real run"
    assert list_tags(str(tmp_path)) == ["global_step2"]
    assert verify_manifest(str(tmp_path / "global_step2")) == []


def test_gc_collects_stray_tmp_files(tmp_path):
    """A crashed atomic_write_bytes leaves '<file>.tmp.<pid>' — the
    orphan pass collects files too, not just staging dirs."""
    (tmp_path / "latest.tmp.4242").write_text("global_step9")
    (tmp_path / "latest").write_text("global_step1")
    d = tmp_path / "global_step1"
    d.mkdir()
    (d / "f").write_bytes(b"z")
    write_manifest(str(d), build_manifest(
        str(d), "global_step1", step_meta={"global_steps": 1}))
    actions = gc_checkpoints(str(tmp_path), keep_last_n=0)
    assert actions == ["latest.tmp.4242"]
    assert not (tmp_path / "latest.tmp.4242").exists()
    assert (tmp_path / "latest").read_text() == "global_step1"


def test_gc_checkpoints_protects(tmp_path):
    for step in (1, 2, 3):
        d = tmp_path / f"global_step{step}"
        d.mkdir()
        (d / "f").write_bytes(b"z")
        write_manifest(str(d), build_manifest(
            str(d), d.name, step_meta={"global_steps": step}))
    removed = gc_checkpoints(str(tmp_path), keep_last_n=1,
                             protect=("global_step1",))
    assert sorted(removed) == ["global_step2"]
    assert sorted(list_tags(str(tmp_path))) == ["global_step1",
                                                "global_step3"]


# --------------------------------------------------------------------- #
# Checkpoint engine ordering (satellites)
# --------------------------------------------------------------------- #
def test_orbax_meta_write_is_atomic(tmp_path):
    from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import \
        OrbaxCheckpointEngine
    eng = OrbaxCheckpointEngine()
    eng.save(None, {"k": 1}, str(tmp_path / "state"))
    files = os.listdir(tmp_path / "state")
    assert "meta.pkl" in files
    assert not any(".tmp" in f for f in files)


def test_nebula_async_meta_lands_only_at_commit(tmp_path):
    """Satellite: async save must not leave a metadata-complete but
    data-incomplete checkpoint — meta.pkl durability is established at
    commit(), after the array shards'."""
    import jax.numpy as jnp
    from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import \
        NebulaCheckpointEngine
    eng = NebulaCheckpointEngine()
    arrays = {"module": {"w": jnp.arange(8, dtype=jnp.float32)}}
    path = str(tmp_path / "state")
    eng.save(arrays, {"global_steps": 5}, path)
    assert not os.path.exists(os.path.join(path, "meta.pkl")), \
        "meta.pkl must not exist before commit() in async mode"
    eng.commit("tag")
    assert os.path.exists(os.path.join(path, "meta.pkl"))
    loaded, meta = eng.load(path)
    assert meta["global_steps"] == 5
    np.testing.assert_array_equal(np.asarray(loaded["module"]["w"]),
                                  np.arange(8, dtype=np.float32))


# --------------------------------------------------------------------- #
# Supervisor: preemption, hang watchdog, resume
# --------------------------------------------------------------------- #
def _step_fn(engine):
    loss = engine(random_batch(batch_size=16, seed=engine.global_steps))
    engine.backward(loss)
    engine.step()
    return float(jax.device_get(loss))


def _reference_losses(n):
    engine = fresh_engine()
    return [_step_fn(engine) for _ in range(n)]


def test_run_resilient_plain_completion_and_resume(tmp_path):
    engine = make_engine()
    status, info = run_resilient(engine, _step_fn, str(tmp_path),
                                 max_steps=3, save_interval=2)
    assert status == "done" and info["steps"] == 3
    assert newest_valid_tag(str(tmp_path)) == "global_step3"
    # a restarted process resumes from the final checkpoint and runs the
    # remaining steps only
    e2 = fresh_engine()
    status, info = run_resilient(e2, _step_fn, str(tmp_path), max_steps=5)
    assert status == "done" and e2.global_steps == 5


def test_run_resilient_sigterm_preempt_then_resume_bitwise(tmp_path):
    losses = {}

    def recording_step(engine):
        step = engine.global_steps + 1
        losses[step] = _step_fn(engine)

    engine = make_engine()
    inject.configure_injection(
        {"point": "train.step_begin", "action": "sigterm", "at": 3})
    status, info = run_resilient(engine, recording_step, str(tmp_path),
                                 max_steps=6, save_interval=10)
    assert status == "preempted"
    assert engine.global_steps == 3
    tags = list_tags(str(tmp_path))
    assert any(t.startswith("preempt_") for t in tags), tags
    inject.reset_injection()

    # resume in a fresh engine (simulated restart) and finish
    e2 = fresh_engine()
    status, info = run_resilient(e2, recording_step, str(tmp_path),
                                 max_steps=6, save_interval=10)
    assert status == "done" and e2.global_steps == 6
    ref = _reference_losses(6)
    assert [losses[s] for s in range(1, 7)] == ref, \
        "resumed trajectory must be bitwise-identical to uninterrupted"


def test_run_resilient_hang_watchdog_recovers(tmp_path):
    losses = {}

    def recording_step(engine):
        step = engine.global_steps + 1
        losses[step] = _step_fn(engine)

    engine = make_engine(fault={"heartbeat_timeout_secs": 1.0})
    # step 1 runs OUTSIDE the supervisor: it pays the XLA compile, which
    # would otherwise trip a 1s heartbeat (production: warm up first or
    # size heartbeat_timeout_secs to cover the worst compile)
    recording_step(engine)
    inject.configure_injection(
        {"point": "train.step_begin", "action": "hang", "at": 2,
         "times": 1, "seconds": 30})
    status, info = run_resilient(engine, recording_step, str(tmp_path),
                                 max_steps=4, save_interval=1)
    assert status == "done", info
    assert info["hangs"] == 1 and info["resumes"] >= 1
    assert any(t.startswith("hang_step") for t in list_tags(str(tmp_path)))
    assert [losses[s] for s in range(1, 5)] == _reference_losses(4)


def test_run_resilient_transient_step_fault_reloads(tmp_path):
    engine = make_engine()
    inject.configure_injection(
        {"point": "train.step_begin", "action": "raise", "at": 3,
         "times": 1})
    status, info = run_resilient(engine, _step_fn, str(tmp_path),
                                 max_steps=4, save_interval=2)
    assert status == "done" and info["resumes"] == 1
    assert engine.global_steps == 4


def test_run_resilient_gives_up_after_max_resumes(tmp_path):
    engine = make_engine(fault={"max_resumes": 2})
    inject.configure_injection(
        {"point": "train.step_begin", "action": "raise", "at": 2,
         "times": 0})                      # every step from 2 on faults
    status, info = run_resilient(engine, _step_fn, str(tmp_path),
                                 max_steps=10, save_interval=1)
    assert status == "failed"
    assert info["resumes"] == 2


def test_elastic_resume_config_preserves_global_batch():
    cfg = {
        "elasticity": {"enabled": True, "max_train_batch_size": 64,
                       "micro_batch_sizes": [2, 4], "min_gpus": 1,
                       "max_gpus": 64, "version": 0.1},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    }
    c8 = elastic_resume_config(cfg, world_size=8)
    c4 = elastic_resume_config(cfg, world_size=4)
    assert c8["train_batch_size"] == c4["train_batch_size"]
    for c, w in ((c8, 8), (c4, 4)):
        assert c["train_micro_batch_size_per_gpu"] * \
            c["gradient_accumulation_steps"] * w == c["train_batch_size"]
    # no elasticity block -> unchanged
    assert elastic_resume_config({"train_batch_size": 16}) == \
        {"train_batch_size": 16}


# --------------------------------------------------------------------- #
# The kill-and-resume proof (subprocess: os._exit at every seam)
# --------------------------------------------------------------------- #
KILL_POINTS = (
    "ckpt.arrays_write",        # mid-save: data written, metadata absent
    "ckpt.before_manifest",     # staging complete, manifest absent
    "ckpt.before_commit_rename",  # manifest durable, tag not promoted
    "ckpt.before_latest_swap",  # tag promoted, pointer still on previous
)


def _run_driver(ckpt_dir, losses_path, inject_spec=None, max_steps=6,
                save_interval=2):
    env = dict(os.environ)
    env["DSTPU_REPO_ROOT"] = REPO
    # drivers get their own compile cache (shared across the launches of
    # one scenario, isolated from the suite's): an os._exit mid-cache-
    # write would otherwise poison tests/.jax_compile_cache for every
    # later process (native abort loading the truncated executable)
    env["DSTPU_DRIVER_CACHE"] = os.path.join(
        os.path.dirname(str(ckpt_dir)), ".jax_driver_cache")
    env.pop("DSTPU_FAULT_INJECT", None)
    env.pop("BENCH_MODEL", None)
    if inject_spec:
        env["DSTPU_FAULT_INJECT"] = inject_spec
    return subprocess.run(
        [sys.executable, DRIVER, "--ckpt-dir", str(ckpt_dir),
         "--max-steps", str(max_steps), "--save-interval",
         str(save_interval), "--losses", str(losses_path)],
        env=env, capture_output=True, text=True, timeout=240)


def _merged_losses(path):
    """step -> last recorded loss repr (a resumed run re-records the steps
    it replays; last write wins and must equal the first bitwise)."""
    out = {}
    with open(path) as f:
        for line in f:
            step, _, loss = line.strip().partition(",")
            out[int(step)] = loss
    return out


def test_kill_at_every_seam_resumes_bitwise(tmp_path):
    """Acceptance: with fault injection killing the run at EACH registered
    checkpoint seam (including mid-arrays write and pre-latest swap),
    run_resilient restarts from the newest valid checkpoint and the
    resumed loss trajectory is bitwise-identical to an uninterrupted run
    (CPU, fixed seeds)."""
    ref_dir = tmp_path / "ref"
    ref_losses = ref_dir / "losses.txt"
    ref_dir.mkdir()
    proc = _run_driver(ref_dir / "ckpt", ref_losses)
    assert proc.returncode == 0, proc.stderr[-3000:]
    ref = _merged_losses(ref_losses)
    assert sorted(ref) == [1, 2, 3, 4, 5, 6]

    for point in KILL_POINTS:
        d = tmp_path / point.replace(".", "_")
        d.mkdir()
        losses = d / "losses.txt"
        # the SECOND save (step 4) dies: step-2 state is committed, the
        # kill lands in the middle of writing step 4's checkpoint
        proc = _run_driver(d / "ckpt", losses,
                           inject_spec=f"point={point},action=exit,at=2")
        assert proc.returncode == 17, \
            f"{point}: expected injected exit, got rc={proc.returncode}\n" \
            + proc.stderr[-3000:]
        # relaunch clean: resume from the newest valid checkpoint
        proc = _run_driver(d / "ckpt", losses)
        assert proc.returncode == 0, \
            f"{point}: resume failed\n" + proc.stderr[-3000:]
        got = _merged_losses(losses)
        assert got == ref, \
            f"{point}: resumed trajectory diverged from uninterrupted run"


# --------------------------------------------------------------------- #
# ds_ckpt CLI
# --------------------------------------------------------------------- #
def test_ds_ckpt_cli_verify_list_gc(tmp_path, capsys):
    from deepspeed_tpu.runtime.fault import ckpt_cli
    engine = make_engine()
    for _ in range(3):
        train_steps(engine, 1)
        engine.save_checkpoint(str(tmp_path))

    assert ckpt_cli.main(["list", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "global_step3" in out and "<- latest" in out

    assert ckpt_cli.main(["verify", str(tmp_path)]) == 0

    # corrupt the middle tag: verify fails loudly, exit code 1
    target = None
    for dirpath, _d, filenames in os.walk(tmp_path / "global_step2"):
        for n in filenames:
            if n != MANIFEST_NAME:
                target = os.path.join(dirpath, n)
    with open(target, "r+b") as f:
        f.write(b"\x00\x01\x02\x03")
    assert ckpt_cli.main(["verify", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "INVALID" in out

    # gc --dry-run touches nothing
    assert ckpt_cli.main(["gc", str(tmp_path), "--keep", "1",
                          "--dry-run"]) == 0
    assert len(list_tags(str(tmp_path))) == 3
    assert ckpt_cli.main(["gc", str(tmp_path), "--keep", "1"]) == 0
    assert list_tags(str(tmp_path)) == ["global_step3"]


# --------------------------------------------------------------------- #
# Config plumbing
# --------------------------------------------------------------------- #
def test_fault_config_defaults_off():
    cfg = deepspeed_tpu.DeepSpeedConfig(
        {"train_micro_batch_size_per_gpu": 2}, mesh_world_size=8)
    assert cfg.fault.enabled is False
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    assert DeepSpeedInferenceConfig().fault.enabled is False


def test_injection_env_spec_parsing(monkeypatch):
    specs = inject.configure_injection(
        "point=ckpt.save_io,action=raise,at=2,times=3")
    assert specs[0].point == "ckpt.save_io"
    assert (specs[0].at, specs[0].times) == (2, 3)
    with pytest.raises(ValueError, match="unknown injection point"):
        inject.configure_injection({"point": "nope"})
    with pytest.raises(ValueError, match="unknown injection action"):
        inject.configure_injection({"point": "ckpt.save_io",
                                    "action": "nope"})
