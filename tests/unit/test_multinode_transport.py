"""Execute the MultiNodeRunner transports for real through fake
``pdsh``/``mpirun``/``srun`` shims on PATH — each shim implements its
backend's contract (per-host fan-out, env export flags, rank variable) by
spawning the per-host command locally.  Unlike ``test_data_launcher.py``
(command-string asserts only), these tests prove the built commands
actually launch workers with correct env injection and rank assignment
end-to-end (reference ``launcher/multinode_runner.py:51-265``)."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

FAKE_PDSH = r'''#!/usr/bin/env python3
"""pdsh contract: -S (max rc), -f fanout, -w host1,host2, then the remote
command string; %n -> per-host rank, %h -> hostname (run locally here)."""
import subprocess, sys
args, hosts, cmd_parts, i = sys.argv[1:], [], [], 0
while i < len(args):
    a = args[i]
    if a == "-w":
        hosts = args[i + 1].split(","); i += 2
    elif a == "-S":
        i += 1
    elif a == "-f":
        i += 2
    else:
        cmd_parts.append(a); i += 1
remote = " ".join(cmd_parts)
procs = [subprocess.Popen(
    ["bash", "-c", remote.replace("%n", str(n)).replace("%h", h)])
    for n, h in enumerate(hosts)]
sys.exit(max([p.wait() for p in procs] + [0]))
'''

FAKE_MPIRUN = r'''#!/usr/bin/env python3
"""mpirun contract, both flavors the runners emit: OpenMPI (-n, --map-by,
--host, --mca, -x K=V exports, OMPI_COMM_WORLD_RANK) and MPICH (-n, -ppn,
-hosts, -genv K V exports, PMI_RANK)."""
import os, subprocess, sys
args, n, exports, tail, i = sys.argv[1:], 1, {}, [], 0
rank_var = "OMPI_COMM_WORLD_RANK"
while i < len(args):
    a = args[i]
    if a == "-n":
        n = int(args[i + 1]); i += 2
    elif a in ("--map-by", "--host"):
        i += 2
    elif a == "--mca":
        i += 3
    elif a == "-x":
        k, v = args[i + 1].split("=", 1); exports[k] = v; i += 2
    elif a == "-ppn":
        rank_var = "PMI_RANK"; i += 2
    elif a == "-hosts":
        rank_var = "PMI_RANK"; i += 2
    elif a == "-genv":
        rank_var = "PMI_RANK"; exports[args[i + 1]] = args[i + 2]; i += 3
    else:
        tail = args[i:]; break
procs = []
for r in range(n):
    env = dict(os.environ); env.update(exports); env[rank_var] = str(r)
    procs.append(subprocess.Popen(tail, env=env))
sys.exit(max([p.wait() for p in procs] + [0]))
'''

FAKE_SRUN = r'''#!/usr/bin/env python3
"""srun contract the runner emits: -N nodes, --ntasks-per-node=1, -w
hostlist, --export=ALL,K=V,..., SLURM_PROCID rank variable."""
import os, subprocess, sys
args, n, exports, tail, i = sys.argv[1:], 1, {}, [], 0
while i < len(args):
    a = args[i]
    if a == "-N":
        n = int(args[i + 1]); i += 2
    elif a.startswith("--ntasks-per-node"):
        i += 1
    elif a == "-w":
        i += 2
    elif a.startswith("--export="):
        for kv in a[len("--export="):].split(","):
            if "=" in kv:
                k, v = kv.split("=", 1); exports[k] = v
        i += 1
    elif a == "--comment":
        i += 2
    else:
        tail = args[i:]; break
procs = []
for r in range(n):
    env = dict(os.environ); env.update(exports); env["SLURM_PROCID"] = str(r)
    procs.append(subprocess.Popen(tail, env=env))
sys.exit(max([p.wait() for p in procs] + [0]))
'''

ECHO_WORKER = r'''import json, os, sys
out = sys.argv[1]
rank = os.environ["DSTPU_PROCESS_ID"]
info = {k: os.environ.get(k) for k in
        ("DSTPU_PROCESS_ID", "DSTPU_COORDINATOR_ADDRESS",
         "DSTPU_NUM_PROCESSES")}
info["cwd"] = os.getcwd()
with open(os.path.join(out, f"rank{rank}.json"), "w") as f:
    json.dump(info, f)
'''


def _shim_dir(tmp_path):
    d = tmp_path / "fakebin"
    d.mkdir()
    for name, body in (("pdsh", FAKE_PDSH), ("mpirun", FAKE_MPIRUN),
                       ("srun", FAKE_SRUN)):
        p = d / name
        p.write_text(body)
        p.chmod(0o755)
    return str(d)


def _run_launcher(tmp_path, launcher, worker_args, extra_env=None,
                  timeout=180):
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("nodeA slots=1\nnodeB slots=1\n")
    env = dict(os.environ)
    env["PATH"] = _shim_dir(tmp_path) + os.pathsep + env["PATH"]
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
         "-H", str(hostfile), "--launcher", launcher,
         "--master_addr", "127.0.0.1", "--master_port", "29871",
         *worker_args],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout)


@pytest.mark.parametrize("launcher", ["pdsh", "openmpi", "mpich", "slurm",
                                      "mvapich"])
def test_transport_spawns_ranked_workers(tmp_path, launcher):
    """The runner-built command, executed through its backend's CLI
    contract, spawns one worker per host with distinct ranks, the
    coordinator env injected, and the launch cwd restored."""
    worker = tmp_path / "echo_worker.py"
    worker.write_text(ECHO_WORKER)
    out = tmp_path / "out"
    out.mkdir()
    result = _run_launcher(tmp_path, launcher,
                           [str(worker), str(out)])
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    records = {}
    for f in os.listdir(out):
        with open(out / f) as fh:
            records[f] = json.load(fh)
    assert len(records) == 2, (records, result.stderr)
    ranks = sorted(int(r["DSTPU_PROCESS_ID"]) for r in records.values())
    assert ranks == [0, 1], records
    for r in records.values():
        assert r["DSTPU_COORDINATOR_ADDRESS"] == "127.0.0.1:29871"
        assert r["DSTPU_NUM_PROCESSES"] == "2"
        assert r["cwd"] == REPO              # cd-to-launch-cwd contract


@pytest.mark.slow
def test_pdsh_transport_full_rendezvous(tmp_path):
    """The pdsh transport end-to-end: two shim-spawned workers rendezvous
    through jax.distributed.initialize into one 8-device mesh and produce
    identical ZeRO-2 losses — the full multi-host path with only the ssh
    hop replaced by the shim."""
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "mp_worker.py")
    out = str(tmp_path / "losses")
    port = _free_port()
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("nodeA slots=1\nnodeB slots=1\n")
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("XLA_", "JAX_", "DSTPU_"))}
    env.update({"DSTPU_REPO_ROOT": REPO, "WORKER_OUT": out,
                "WORKER_LOCAL_DEVICES": "4"})
    env["PATH"] = _shim_dir(tmp_path) + os.pathsep + env["PATH"]
    result = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
         "-H", str(hostfile), "--launcher", "pdsh",
         "--master_addr", "127.0.0.1", "--master_port", str(port), worker],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    with open(f"{out}.rank0") as f:
        l0 = [float(x) for x in f.read().split()]
    with open(f"{out}.rank1") as f:
        l1 = [float(x) for x in f.read().split()]
    np.testing.assert_allclose(l0, l1, rtol=0, atol=0)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
