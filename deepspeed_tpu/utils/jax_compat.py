"""Version shims for JAX APIs that moved or were renamed between releases.

The runtime targets the newest JAX surface (``jax.shard_map`` with
``check_vma``/``axis_names``; ``pltpu.CompilerParams``) but must also run on
0.4.x, where the same features live at ``jax.experimental.shard_map.shard_map``
(kwargs ``check_rep``/``auto``) and ``pltpu.TPUCompilerParams``.  Import from
here instead of probing ``jax`` at each call site.
"""

import functools

import jax

try:  # jax >= 0.6: top-level export
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
    _NEW_SHARD_MAP = True
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map
    _NEW_SHARD_MAP = False

try:
    from jax.experimental.pallas import tpu as _pltpu
    # Renamed TPUCompilerParams -> CompilerParams in newer releases.
    CompilerParams = getattr(_pltpu, "CompilerParams", None) or \
        _pltpu.TPUCompilerParams
except ImportError:  # pallas absent (minimal CPU builds)
    CompilerParams = None


def axis_size(axis_name):
    """``lax.axis_size`` (new JAX) or a psum-of-ones fallback (0.4.x)."""
    from jax import lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def get_opaque_trace_state():
    """``jax.core.get_opaque_trace_state``; 0.4.x requires a ``convention``
    argument it then ignores."""
    from jax import core
    try:
        return core.get_opaque_trace_state()
    except TypeError:
        return core.get_opaque_trace_state(convention="nnx")


def process_allgather_stacked(x):
    """``multihost_utils.process_allgather`` with a guaranteed leading
    process axis — the 0.4.x single-process fast path returns the input
    unstacked, so a reduce over axis 0 would silently reduce the data."""
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    g = multihost_utils.process_allgather(x)
    if jax.process_count() == 1 and jnp.shape(g) == jnp.shape(x):
        g = jnp.asarray(g)[None]
    return g


def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=None,
              axis_names=None, **kw):
    """``jax.shard_map`` with new-style kwargs on any supported JAX.

    ``check_vma`` maps to 0.4.x ``check_rep``; ``axis_names`` (the manual
    axes) maps to its complement ``auto``.  Usable directly or as a
    ``functools.partial`` decorator target, like the real thing.
    """
    if f is None:
        return functools.partial(shard_map, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=check_vma,
                                 axis_names=axis_names, **kw)
    if _NEW_SHARD_MAP:
        if check_vma is not None:
            kw["check_vma"] = check_vma
        if axis_names is not None:
            kw["axis_names"] = frozenset(axis_names)
    else:
        if check_vma is not None:
            kw["check_rep"] = check_vma
        # axis_names is dropped: 0.4.x partial-auto (``auto=`` complement)
        # lowers to a PartitionId op XLA:CPU rejects, and fully-manual is
        # SEMANTICALLY equivalent when the body only names the manual axes.
        # It is not partitioning-equivalent: unmentioned axes replicate the
        # body's compute instead of staying auto-sharded — warn so a
        # multi-axis production mesh doesn't silently pay that.
        if axis_names is not None:
            extra = set(mesh.axis_names) - set(axis_names)
            if any(mesh.shape[a] > 1 for a in extra):
                import warnings
                warnings.warn(
                    f"jax {jax.__version__} shard_map has no axis_names: "
                    f"axes {sorted(a for a in extra if mesh.shape[a] > 1)} "
                    f"run fully-manual (body replicated over them) instead "
                    f"of auto-partitioned", stacklevel=2)
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kw)
