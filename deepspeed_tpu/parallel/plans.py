"""Canonical tiny sharding-plan builders for the static collective-schedule
gate (``tools/lint/contract.py``) and the mesh-scaling prover
(``tools/lint/comm_contract.py``).

Each builder constructs the SAME plan family the MULTICHIP dry-run exercises
(``__graft_entry__._run_dryrun_phases``: ZeRO-3 + tp + sp, MoE expert
parallelism, 1F1B pipeline x tp, MiCS hierarchical ZeRO) at toy sizes on the
8-virtual-device CPU mesh, and returns the jitted fused train step plus
concrete args — so the contract analyzer can compile it once and COUNT the
collective ops XLA actually scheduled.  Locking those counts in
``PROGRAMS.lock`` turns the dry-run's re-measured collective totals into a
static, diffable artifact: a sharding-plan change that silently adds an
all-gather (or drops the Ulysses all-to-all) fails the tier-1 gate with a
per-plan diff instead of surfacing as a multichip perf cliff.

Every builder takes ``world`` (default 8, the full tier-1 mesh) and scales
its plan DOWN through a fixed per-plan axis allocation (``MESH_POINTS`` =
{1, 2, 4, 8}) so the comm-cost analyzer can compile the same plan family at
every mesh size and lock a bytes-per-chip scaling table: a collective whose
per-chip volume GROWS with mesh size is the classic replicated-tensor smell
and fails the prover.  The ``world=8`` allocation is bit-identical to the
pre-scaling builders (no explicit topology is passed), so the locked
schedules never move.  Deliberately replicated traffic that must grow is
declared per-plan in ``allowed_growth`` with a reviewable reason.

Builders are self-contained and deterministic (fixed seeds, fixed shapes);
``world=8`` requires ``jax.device_count() >= 8`` (the tier-1 harness forces
8 virtual CPU devices; the ``ds_lint --contracts`` / ``--comm`` CLIs do the
same).
"""

import dataclasses
from typing import Any, Callable, Dict, Tuple

import numpy as np

# Mesh sizes the scaling prover compiles every plan at.  The top point is
# the canonical full-mesh plan whose schedule is locked in PROGRAMS.lock.
MESH_POINTS = (1, 2, 4, 8)


@dataclasses.dataclass
class PlanProgram:
    """One sharding plan's fused step, ready to lower/compile.

    ``expect`` names the collectives the plan MUST schedule (sanity
    invariants, checked on top of the exact locked counts): e.g. ZeRO-3
    must all-gather params, a pipeline must collective-permute at stage
    boundaries.  ``reduction`` plans additionally require at least one of
    all-reduce / reduce-scatter (XLA picks per shape).  ``world`` is the
    number of mesh devices the plan was built for; ``allowed_growth``
    maps a collective op to the REASON its per-chip byte volume may grow
    with mesh size (anything not listed fails the scaling prover when it
    grows — the replicated-tensor smell)."""
    name: str
    fn: Callable
    args: Tuple[Any, ...]
    mesh: Dict[str, int]
    expect: Tuple[str, ...] = ()
    reduction: bool = True
    world: int = 8
    allowed_growth: Dict[str, str] = dataclasses.field(default_factory=dict)


def _tiny_cfg(**over):
    from deepspeed_tpu.models.transformer import TransformerConfig
    base = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                max_seq_len=32, dtype="float32", use_flash_attention=False,
                remat=False)
    base.update(over)
    return TransformerConfig(**base)


def _fused_step_args(engine, batch):
    """(fused_step, args) for a lazily-initialized DeepSpeedEngine —
    the exact per-step program ``train_batch`` dispatches."""
    import jax
    import jax.numpy as jnp
    fused = engine._get_fused_step()
    args = (engine._params, engine._opt_state, engine._scaler_state,
            jnp.asarray(1e-3, jnp.float32), jnp.asarray(1, jnp.int32),
            engine._rng, jax.tree.map(jnp.asarray, batch))
    return fused, args


def _scaled_topology(world, **axes):
    """Explicit topology over the first ``world`` devices — only for the
    scaled-down mesh points; ``world=8`` builders pass ``topology=None``
    so the canonical locked plans keep the exact pre-scaling build path."""
    import jax
    from deepspeed_tpu.parallel.topology import ParallelTopology
    if world >= 8:
        return None
    return ParallelTopology(devices=jax.devices()[:world], **axes)


def _check_world(world):
    if world not in MESH_POINTS:
        raise ValueError(f"world={world} not a mesh point {MESH_POINTS}")


def zero3_tp_sp(world=8):
    """ZeRO-3 param sharding + Megatron tp=2 + Ulysses sp=2 over dp=2:
    param all-gathers, grad reduction, and the sp head/seq all-to-all.

    Scaling allocation (axis added per doubling, innermost first):
    1 -> dp=1; 2 -> dp=2 (pure ZeRO-3); 4 -> dp=2 x tp=2;
    8 -> dp=2 x tp=2 x sp=2 (the canonical locked plan)."""
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import Transformer
    _check_world(world)
    dp, tp, sp = {1: (1, 1, 1), 2: (2, 1, 1),
                  4: (2, 2, 1), 8: (2, 2, 2)}[world]
    rng = np.random.default_rng(0)
    engine, *_ = deepspeed_tpu.initialize(
        model=Transformer(_tiny_cfg(max_seq_len=64)),
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 3},
                "gradient_clipping": 1.0,
                "tensor_parallel": {"tp_size": tp},
                "sequence_parallel": {"sp_size": sp}},
        topology=_scaled_topology(world, dp=dp, tp=tp, sp=sp))
    batch = {"input_ids": rng.integers(0, 64, (2, dp, 64)).astype(np.int32)}
    micro = {"input_ids": batch["input_ids"][0]}
    engine._lazy_init((micro,), {})
    fn, args = _fused_step_args(engine, batch)
    return PlanProgram(
        "parallel.zero3_tp_sp", fn, args,
        mesh=dict(engine.mesh.shape),
        expect=("all-gather", "all-to-all") if world == 8 else (),
        reduction=world > 1, world=world,
        allowed_growth={
            "all-gather": "the Ulysses sp axis exists only at mesh 8: "
                          "sequence-parallel activation regathers are "
                          "added traffic from the new axis, not lost "
                          "param sharding (per-chip param gathers fall "
                          "2->4)",
            "all-to-all": "the Ulysses head<->seq exchange is batch-"
                          "proportional and the toy global batch grows "
                          "with dp",
            "collective-permute": "axis-boundary reshard permutes track "
                                  "the tp/sp axes added at meshes 4 and "
                                  "8",
        })


def moe_ep(world=8):
    """Expert parallelism: experts sharded over ep=2, GShard
    dispatch/combine einsums, expert-data-parallel gradient semantics
    (ZeRO-2).  The dispatch is the einsum formulation
    (``moe/sharded_moe.py``), so GSPMD picks the collective: at this toy
    config XLA lowers it through all-gathers rather than an explicit
    all-to-all — the locked counts pin whichever schedule it chose, which
    is exactly what the gate is for (a strategy flip on a jax/XLA bump
    shows up as a readable diff, not a multichip surprise).

    Scaling allocation: 1 -> ep=1, dp=1; 2 -> ep=2, dp=2;
    4 -> ep=2, dp=4; 8 -> ep=2, dp=8 (canonical)."""
    import jax
    import jax.numpy as jnp
    import flax.linen as nn
    import deepspeed_tpu
    from deepspeed_tpu.moe.layer import MoE
    _check_world(world)
    ep, dp = {1: (1, 1), 2: (2, 2), 4: (2, 4), 8: (2, 8)}[world]

    class MoELM(nn.Module):
        @nn.compact
        def __call__(self, batch):
            ids = batch["input_ids"]
            h = nn.Embed(64, 32, param_dtype=jnp.float32)(ids)
            y, aux, _ = MoE(hidden_size=32, num_experts=4, ep_size=ep,
                            k=1, capacity_factor=2.0, dtype=jnp.float32,
                            name="moe")(h)
            h = h + y
            logits = nn.Dense(64)(h)
            tgt = jnp.pad(ids[:, 1:], ((0, 0), (0, 1)))
            ce = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits)
                                   * jax.nn.one_hot(tgt, 64), -1))
            return ce + 0.01 * aux

    rng = np.random.default_rng(1)
    engine, *_ = deepspeed_tpu.initialize(
        model=MoELM(),
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                "moe": {"ep_size": ep},
                "zero_optimization": {"stage": 2}},
        topology=_scaled_topology(world, dp=dp, ep=ep))
    batch = {"input_ids": rng.integers(0, 64, (1, dp, 16)).astype(np.int32)}
    micro = {"input_ids": batch["input_ids"][0]}
    engine._lazy_init((micro,), {})
    fn, args = _fused_step_args(engine, batch)
    return PlanProgram(
        "parallel.moe_ep", fn, args,
        mesh=dict(engine.mesh.shape),
        reduction=world > 1, world=world,
        allowed_growth={
            "all-reduce": "the toy global batch grows with dp, so batch-"
                          "proportional activation/aux-loss reductions "
                          "grow with it; per-chip dense-grad reduction "
                          "is flat",
            "all-gather": "the GShard dispatch gathers tokens over the "
                          "edp group and the toy token count grows with "
                          "dp",
        })


def pipeline_1f1b(world=8):
    """pp=2 x tp=2 interleaved 1F1B: stage-boundary activations ride
    collective-permute; tp adds Megatron all-reduces.

    Scaling allocation: 1 -> pp=1 (degenerate single-stage pipe);
    2 -> pp=2; 4 -> pp=2 x tp=2; 8 -> pp=2 x tp=2 x dp=2 (canonical)."""
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models.pipeline_transformer import transformer_pipe
    _check_world(world)
    pp, tp, dp = {1: (1, 1, 1), 2: (2, 1, 1),
                  4: (2, 2, 1), 8: (2, 2, 2)}[world]
    rng = np.random.default_rng(2)
    pipe_module = transformer_pipe(_tiny_cfg(
        num_layers=4, scan_layers=False, pre_layer_norm=False,
        embed_proj_dim=32, tie_word_embeddings=True))
    engine, *_ = deepspeed_tpu.initialize(
        model=pipe_module,
        config={"train_micro_batch_size_per_gpu": 2,
                # M=4 > P=2 so the interleaved schedule's steady state
                # genuinely executes (same contract as the dry-run)
                "gradient_accumulation_steps": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                "tensor_parallel": {"tp_size": tp},
                "pipeline": {"stages": pp, "schedule": "1f1b"}},
        topology=_scaled_topology(world, dp=dp, tp=tp, pp=pp))
    batch = jax.tree.map(
        jnp.asarray,
        {"input_ids": rng.integers(0, 64, (4, 2, 32)).astype(np.int32)})
    engine._lazy_init_pipe(batch)
    fused = engine._get_fused_step()
    args = (engine._params, engine._opt_state, engine._scaler_state,
            jnp.asarray(1e-4, jnp.float32), jnp.asarray(1, jnp.int32),
            engine._rng, batch)
    return PlanProgram(
        "parallel.pipeline_1f1b", fused, args,
        mesh=dict(engine.mesh.shape),
        expect=("collective-permute",) if world == 8 else (),
        reduction=world > 1, world=world,
        allowed_growth={
            "all-gather": "Megatron tp=2 param/activation gathers "
                          "appear with the tp axis at mesh 4; the "
                          "per-chip trajectory is flat from there "
                          "(4 -> 8 unchanged)",
        })


def mics(world=8):
    """MiCS hierarchical ZeRO-3 + tp=2: params shard within edp=2 groups
    (ICI-local all-gather) and grads reduce across mdp x edp.

    Scaling allocation: 1 -> dp=1 (plain ZeRO-3, no groups);
    2 -> dp=2, shard group 2; 4 -> dp=4, two groups of 2;
    8 -> dp=4 x tp=2, two groups of 2 (canonical)."""
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import Transformer
    _check_world(world)
    dp, tp, mics_size = {1: (1, 1, 0), 2: (2, 1, 2),
                         4: (4, 1, 2), 8: (4, 2, 2)}[world]
    mdp = (dp // mics_size) if mics_size else 1
    rng = np.random.default_rng(3)
    zero_cfg = {"stage": 3}
    if mics_size:
        zero_cfg["mics_shard_size"] = mics_size
    engine, *_ = deepspeed_tpu.initialize(
        model=Transformer(_tiny_cfg()),
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                "bf16": {"enabled": True},
                "tensor_parallel": {"tp_size": tp},
                "zero_optimization": zero_cfg},
        topology=_scaled_topology(world, dp=dp, tp=tp, mdp=mdp))
    dp_world = engine.topology.mdp * engine.topology.edp
    batch = {"input_ids": rng.integers(0, 64, (1, dp_world, 32))
             .astype(np.int32)}
    micro = {"input_ids": batch["input_ids"][0]}
    engine._lazy_init((micro,), {})
    fn, args = _fused_step_args(engine, batch)
    return PlanProgram(
        "parallel.mics", fn, args,
        mesh=dict(engine.mesh.shape),
        expect=("all-gather",) if world == 8 else (),
        reduction=world > 1, world=world,
        allowed_growth={
            "all-reduce": "cross-group (mdp) grad reduction appears at "
                          "mesh 4 on top of the batch-proportional toy "
                          "reductions",
            "all-gather": "the mdp hierarchy at mesh 4 adds cross-group "
                          "param propagation to the ICI-local gathers",
            "collective-permute": "group-boundary reshards track the "
                                  "mdp/tp axes added at meshes 4 and 8",
            "all-to-all": "the tp axis exists only at mesh 8: XLA "
                          "lowers its boundary reshards through "
                          "all-to-alls (new-axis traffic, same ops as "
                          "zero3_tp_sp at tp introduction)",
        })


PLAN_BUILDERS = (zero3_tp_sp, moe_ep, pipeline_1f1b, mics)
