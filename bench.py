"""Benchmark: OPT SFT training throughput on the local chip(s).

Mirrors the reference's headline workload — DeepSpeed-Chat step-1 SFT of OPT
(``BASELINE.json``: tokens/sec/chip + MFU, north star ≥35% MFU with ZeRO-3).
Runs the fused engine train step on an OPT-family model sized to the chip,
measures steady-state tokens/sec, derives MFU from the analytic flop count
(6·N·T per token), and prints ONE JSON line.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def main():
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.opt import opt_model, opt_config
    from deepspeed_tpu.profiling.flops_profiler.profiler import device_peak_tflops

    model_name = os.environ.get("BENCH_MODEL", "opt-350m")
    seq = int(os.environ.get("BENCH_SEQ", "2048"))
    micro_bs = int(os.environ.get("BENCH_BS", "4"))
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    platform = jax.devices()[0].platform
    n_dev = jax.device_count()

    cfg = opt_config(
        model_name, max_seq_len=seq, dtype="bfloat16",
        # remat off is the fastest fit for 350m @ bs4 on one v5e chip
        # (38.0% vs 35.3% MFU measured); larger models re-enable via env
        remat=os.environ.get("BENCH_REMAT", "0") == "1",
        remat_policy=os.environ.get("BENCH_REMAT_POLICY",
                                    "dots_and_attn_saveable"),
        scan_layers=os.environ.get("BENCH_SCAN", "0") == "1",
        fused_qkv=os.environ.get("BENCH_FQ", "0") == "1",
        loss_seq_chunks=int(os.environ.get("BENCH_LOSS_CHUNKS", "8")))
    model = deepspeed_tpu.models.transformer.Transformer(cfg)
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": micro_bs,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 9.65e-6, "weight_decay": 0.0}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": int(os.environ.get("BENCH_ZERO", "1"))},
            "gradient_clipping": 1.0,
        })

    rng = np.random.default_rng(0)
    def make_batch():
        ids = rng.integers(0, cfg.vocab_size,
                           (1, micro_bs * engine.topology.dp, seq)).astype(np.int32)
        return {"input_ids": ids}

    # compile + warmup.  NOTE: sync must be a *dependent* device_get — through
    # the axon tunnel block_until_ready returns early, so timing keys off
    # fetching the loss value produced by the final step.
    batch = make_batch()
    loss = engine.train_batch(batch=batch)
    loss = engine.train_batch(batch=batch)
    float(jax.device_get(loss))

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch=batch)
    final_loss = float(jax.device_get(loss))
    dt = (time.perf_counter() - t0) / steps

    tokens_per_step = micro_bs * engine.topology.dp * seq
    tokens_per_sec = tokens_per_step / dt
    tokens_per_sec_chip = tokens_per_sec / n_dev
    n_params = cfg.num_params()
    # 6ND for fwd+bwd; remat recompute ignored (standard MFU convention)
    flops_per_step = 6.0 * n_params * tokens_per_step
    peak = device_peak_tflops() * 1e12 * n_dev
    mfu = flops_per_step / dt / peak if peak else 0.0

    # vs_baseline: the reference north-star target is 35% MFU (BASELINE.json)
    result = {
        "metric": f"{model_name}-sft-tokens/sec/chip(seq{seq},bs{micro_bs},"
                  f"zero{engine.zero_optimization_stage()},{platform})",
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4),
        "mfu": round(mfu, 4),
        "step_time_s": round(dt, 4),
        "loss": round(final_loss, 4),
        "n_devices": n_dev,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    # the tunneled remote-compile service occasionally drops a request on
    # the first cold compile; one retry rides the now-warm cache
    try:
        main()
    except Exception:
        import traceback
        traceback.print_exc()
        print("bench: transient failure, retrying once", file=sys.stderr)
        main()
